"""PR10 tentpole: bucket-ready overlapped allreduce + ZeRO-2/3.

Overlap correctness — bit-identical params between barrier mode (comm
pinned behind the whole backward) and ready mode (per-bucket collectives
issued as gradients become available) for sgd/adam x multi-precision
off/bf16 x K in {1, 4}; ZeRO-2/3 vs ZeRO-0 parity on the same plans;
staged-mode (host-driven 3-dispatch baseline) agreement; the 2-bit
compressed bucket path (allreduce == reduce-scatter flavor, kvstore
bucket == per-key reference semantics); the ZeRO memory report; elastic
ZeRO checkpoints across dp sizes; and the readiness-order / bucket-plan
unit contracts. Runs on the 8-device virtual CPU mesh (conftest)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import fusedstep, gluon, parallel
from mxnet_tpu.parallel import overlap as ovl
from mxnet_tpu.parallel.spmd import spmd_load_states, spmd_save_states

loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

_X = np.random.RandomState(0).rand(8, 10).astype(np.float32)
_Y = np.random.RandomState(1).randint(0, 4, (8,)).astype(np.float32)
_XS = np.stack([np.random.RandomState(10 + i).rand(8, 10).astype(np.float32)
                for i in range(4)])
_YS = np.stack([np.random.RandomState(20 + i).randint(0, 4, (8,))
                .astype(np.float32) for i in range(4)])


def _mesh(dp=4):
    return parallel.make_mesh({"dp": dp}, devices=jax.devices()[:dp])


def _net(dtype="float32"):
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu", in_units=10))
    net.add(gluon.nn.Dense(4, in_units=16))
    net.initialize(init=mx.initializer.Constant(0.0))
    rng = np.random.RandomState(3)
    for _, p in _psorted(net.collect_params().items()):
        p.set_data(mx.nd.array(
            rng.uniform(-0.3, 0.3, p.shape).astype(np.float32))
            .astype(dtype))
    return net


from conftest import natsorted_items as _psorted  # noqa: E402 — the
# natural sort lives in conftest now (shared with test_fused_step /
# test_higher_order_grad / test_amp); a plain name sort swaps layers
# when the gluon auto-name counter straddles a digit boundary


def _weights(net):
    return [np.asarray(p.data().data) for _, p in
            _psorted(net.collect_params().items())]


def _run(mode, k=1, opt="adam", stage=0, mp=False, comp=None, dp=4,
         lr=0.05, n_groups=1):
    mx.random.seed(42)
    net = _net("bfloat16" if mp else "float32")
    step = parallel.SPMDTrainStep(
        net, loss_fn, opt, {"momentum": 0.9} if opt == "sgd" else {},
        _mesh(dp), zero_stage=stage, overlap=mode, multi_precision=mp,
        compression_params=comp)
    losses = []
    for _ in range(n_groups):
        if k == 1:
            for i in range(4):
                losses.append(float(step(_XS[i], _YS[i], lr=lr)))
        else:
            out = step.run_superstep(_XS[:k], _YS[:k], lr=lr)
            losses.extend(np.asarray(out, dtype=np.float32).tolist())
    step.sync_to_block()
    return losses, _weights(net), step


# ---------------------------------------------------------------------------
# overlap correctness: ready == barrier bit-identically
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 4])
@pytest.mark.parametrize("opt,mp", [
    ("sgd", False), ("adam", False), ("sgd", True), ("adam", True),
])
def test_ready_matches_barrier_bitwise(opt, mp, k):
    """The bucket-ready schedule changes WHEN collectives run, never
    what they compute: params after barrier-mode and ready-mode runs
    are bit-identical for sgd/adam x mp off/bf16 x K in {1,4}."""
    lb, wb, _ = _run("barrier", k=k, opt=opt, mp=mp)
    lr_, wr, _ = _run("ready", k=k, opt=opt, mp=mp)
    assert lb == lr_, (lb, lr_)
    for a, b in zip(wb, wr):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)


def test_staged_with_compression_falls_to_barrier():
    """staged is the UNCOMPRESSED measurement baseline: requesting
    compression with it declines loudly to the in-graph barrier mode
    (which carries the compressed path) instead of silently dropping
    the compression."""
    comp = {"type": "2bit", "threshold": 0.05}
    _, wr, _ = _run("ready", comp=comp)
    _, ws, st = _run("staged", comp=comp)
    assert st._mode == "overlap" and st._overlap_mode == "barrier"
    for a, b in zip(wr, ws):
        np.testing.assert_array_equal(a, b)


def test_staged_matches_in_graph_modes():
    """The host-driven 3-dispatch baseline computes the same step (it
    exists to EXPOSE comm, not to change numerics)."""
    _, wb, _ = _run("barrier")
    _, ws, st = _run("staged")
    assert st._mode == "staged"
    for a, b in zip(wb, ws):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# ZeRO-2/3: same numbers, 1/dp the state
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stage", [2, 3])
@pytest.mark.parametrize("k", [1, 4])
def test_zero_stage_parity(stage, k):
    """Reduce-scattered grads + flat-sharded opt state (+ sharded-at-
    rest params at stage 3) produce bit-identical training to the
    replicated stage-0 layout, one-step and inside the K-step scan."""
    l0, w0, _ = _run("ready", k=k, stage=0)
    ls, ws, _ = _run("ready", k=k, stage=stage)
    assert l0 == ls, (l0, ls)
    for a, b in zip(w0, ws):
        np.testing.assert_array_equal(a, b)


def test_zero_stage_parity_vs_zero1():
    """ZeRO-1 (GSPMD constraint sharding, the jit path) agrees with the
    ZeRO-2 shard_map layout."""
    l1, w1, s1 = _run("ready", stage=1)
    l2, w2, s2 = _run("ready", stage=2)
    assert s1._mode == "jit" and s2._mode == "overlap"
    np.testing.assert_allclose(l1, l2, rtol=1e-6, atol=1e-7)
    for a, b in zip(w1, w2):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_zero_memory_report_reductions():
    """Stage 2 cuts per-device optimizer+gradient bytes to ~1/dp of
    replicated (scalar step counters stay replicated); stage 3 also
    cuts at-rest param bytes for the trainable set."""
    _, _, s0 = _run("ready", stage=0, n_groups=1)
    _, _, s2 = _run("ready", stage=2, n_groups=1)
    _, _, s3 = _run("ready", stage=3, n_groups=1)
    r0, r2, r3 = (s.zero_memory_report() for s in (s0, s2, s3))
    dp = r2["dp"]
    assert dp == 4
    # optimizer + gradient memory: >= (dp-1)/dp reduction modulo the
    # replicated scalar counters (adam's t: a few bytes per param)
    for rep in (r2, r3):
        repl = rep["opt_bytes_replicated"] + rep["grad_bytes_replicated"]
        dev = rep["opt_bytes_per_device"] + rep["grad_bytes_per_device"]
        assert dev <= repl / dp * 1.05, rep
    assert r0["opt_bytes_per_device"] == r0["opt_bytes_replicated"]
    assert r3["param_bytes_per_device"] < r0["param_bytes_per_device"]


# ---------------------------------------------------------------------------
# 2-bit compression on the bucket plan
# ---------------------------------------------------------------------------

def test_compressed_buckets_allreduce_matches_reduce_scatter():
    """The quantizer is elementwise, so the compressed allreduce (ZeRO
    0) and compressed reduce-scatter (ZeRO 2) flavors train
    identically — compression rides the overlapped path in both."""
    comp = {"type": "2bit", "threshold": 0.05}
    l0, w0, s0 = _run("ready", stage=0, comp=comp)
    l2, w2, s2 = _run("ready", stage=2, comp=comp)
    assert s0._residuals is not None and s2._residuals is not None
    assert l0 == l2, (l0, l2)
    for a, b in zip(w0, w2):
        np.testing.assert_array_equal(a, b)


def test_compression_error_feedback_changes_numerics_but_converges():
    """The carry is real: compressed training differs from exact
    training (quantized comm) but still reduces the loss."""
    le, _, _ = _run("ready", n_groups=2)
    lc, _, _ = _run("ready", comp={"type": "2bit", "threshold": 0.05},
                    n_groups=2)
    assert lc != le
    assert lc[-1] < lc[0], lc


def test_kvstore_compressed_bucketed_matches_per_key_reference():
    """The kvstore's compressed bucketed pushpull (one compiled
    pack+quantize+reduce+unpack) matches the reference per-key
    merge -> quantize -> residual semantics across iterations."""
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.3})
    rng = np.random.RandomState(0)
    arrs = [rng.uniform(-1, 1, (64,)).astype(np.float32)
            for _ in range(3)]
    keys, vals, outs = [], [], []
    for i, a in enumerate(arrs):
        kv.init(i, mx.nd.zeros((64,)))
        per_dev = []
        for d in jax.devices()[:2]:
            nd = mx.nd.array(a.copy())
            nd._set_data(jax.device_put(nd.data, d))
            per_dev.append(nd)
        keys.append(i)
        vals.append(per_dev)
        outs.append(mx.nd.zeros((64,)))
    thr = 0.3
    res = [np.zeros_like(a) for a in arrs]
    for it in range(3):
        kv.pushpull(keys, vals, out=outs)
        for i, a in enumerate(arrs):
            acc = 2 * a + res[i]
            q = np.where(acc >= thr, thr,
                         np.where(acc <= -thr, -thr, 0.0)).astype(
                             np.float32)
            res[i] = acc - q
            np.testing.assert_allclose(outs[i].asnumpy(), q,
                                       rtol=1e-6, atol=1e-7)
    assert len(kv._bucket_plans) == 1  # one compiled plan, reused


def test_kvstore_compression_single_device_rides_bucketed_path():
    """Quantization is in-graph work even with nothing to reduce: a
    single-device compressed pushpull must take the bucketed path (the
    old behavior fell all the way back to eager per-key dispatches)."""
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(0, mx.nd.zeros((8,)))
    out = mx.nd.zeros((8,))
    kv.pushpull([0], [[mx.nd.ones((8,))]], out=[out])
    assert len(kv._bucket_plans) == 1
    np.testing.assert_allclose(out.asnumpy(), np.full((8,), 0.5))


# ---------------------------------------------------------------------------
# elastic ZeRO checkpoints
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stage", [
    pytest.param(2, marks=pytest.mark.slow),  # stage-3 twin covers the
    3,  # same restore path plus param sharding
])
def test_zero_checkpoint_elastic_restore(tmp_path, stage):
    """A dp=4 ZeRO-sharded save (flat-padded shards, clipped to the
    LOGICAL length) restores bit-exactly onto a dp=2 step — the pad is
    layout, not state — and training continues identically."""
    net = _net()
    s4 = parallel.SPMDTrainStep(net, loss_fn, "adam", {}, _mesh(4),
                                zero_stage=stage)
    for i in range(2):
        s4(_XS[i], _YS[i], lr=0.05)
    s4.sync_to_block()
    w_before = _weights(net)
    prefix = str(tmp_path / "ck")
    spmd_save_states(s4, prefix)
    s2 = parallel.SPMDTrainStep(net, loss_fn, "adam", {}, _mesh(2),
                                zero_stage=stage)
    s2(_XS[0], _YS[0], lr=0.05)  # init + compile under the new layout
    spmd_load_states(s2, prefix)
    s2.sync_to_block()
    for a, b in zip(w_before, _weights(net)):
        np.testing.assert_array_equal(a, b)
    la = float(s4(_XS[2], _YS[2], lr=0.05))
    lb = float(s2(_XS[2], _YS[2], lr=0.05))
    assert abs(la - lb) < 1e-5, (la, lb)


@pytest.mark.parametrize("stage", [
    pytest.param(2, marks=pytest.mark.slow),  # stage-3 twin covers the
    3,  # same shrink-to-one path plus param sharding
])
def test_zero_checkpoint_restores_onto_single_device(tmp_path, stage):
    """Elastic shrink all the way down: a dp=4 flat-sharded ZeRO save
    restores bit-exactly onto a mesh-less single-device (jit-mode)
    step whose params keep their natural shapes."""
    net = _net()
    s4 = parallel.SPMDTrainStep(net, loss_fn, "adam", {}, _mesh(4),
                                zero_stage=stage)
    for i in range(2):
        s4(_XS[i], _YS[i], lr=0.05)
    s4.sync_to_block()
    w_before = _weights(net)
    prefix = str(tmp_path / "ck")
    spmd_save_states(s4, prefix)
    s1 = parallel.SPMDTrainStep(net, loss_fn, "adam", {})
    s1(_XS[0], _YS[0], lr=0.05)  # perturb; load must win
    spmd_load_states(s1, prefix)
    s1.sync_to_block()
    for a, b in zip(w_before, _weights(net)):
        np.testing.assert_array_equal(a, b)
    la = float(s4(_XS[2], _YS[2], lr=0.05))
    lb = float(s1(_XS[2], _YS[2], lr=0.05))
    assert abs(la - lb) < 1e-5, (la, lb)


def test_zero_checkpoint_stage_change_roundtrip(tmp_path):
    """Stage changes across save/restore cross the flat<->natural
    layout boundary in both directions: a stage-0 (natural) save loads
    into a stage-2 (flat-sharded) step and vice versa, bit-exactly."""
    net = _net()
    s0 = parallel.SPMDTrainStep(net, loss_fn, "adam", {}, _mesh(4),
                                zero_stage=0)
    for i in range(2):
        s0(_XS[i], _YS[i], lr=0.05)
    s0.sync_to_block()
    w_before = _weights(net)
    p0 = str(tmp_path / "ck0")
    spmd_save_states(s0, p0)
    # natural -> flat
    s2 = parallel.SPMDTrainStep(net, loss_fn, "adam", {}, _mesh(4),
                                zero_stage=2)
    s2(_XS[0], _YS[0], lr=0.05)
    spmd_load_states(s2, p0)
    s2.sync_to_block()
    for a, b in zip(w_before, _weights(net)):
        np.testing.assert_array_equal(a, b)
    p2 = str(tmp_path / "ck2")
    spmd_save_states(s2, p2)
    # flat -> natural
    s0b = parallel.SPMDTrainStep(net, loss_fn, "adam", {}, _mesh(4),
                                 zero_stage=0)
    s0b(_XS[0], _YS[0], lr=0.05)
    spmd_load_states(s0b, p2)
    s0b.sync_to_block()
    for a, b in zip(w_before, _weights(net)):
        np.testing.assert_array_equal(a, b)
    la = float(s0(_XS[2], _YS[2], lr=0.05))
    lb = float(s0b(_XS[2], _YS[2], lr=0.05))
    assert abs(la - lb) < 1e-5, (la, lb)


def test_zero_checkpoint_residuals_roundtrip(tmp_path):
    """The 2-bit error-feedback carry is state: it round-trips through
    the sharded checkpoint on an unchanged dp layout."""
    comp = {"type": "2bit", "threshold": 0.05}
    net = _net()
    s = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, _mesh(4),
                               zero_stage=2, compression_params=comp)
    for i in range(2):
        s(_XS[i], _YS[i], lr=0.05)
    prefix = str(tmp_path / "ck")
    spmd_save_states(s, prefix)
    want = [np.asarray(r) for r in s._residuals]
    assert any(np.abs(w).max() > 0 for w in want)  # carry is nonzero
    s2 = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, _mesh(4),
                                zero_stage=2, compression_params=comp)
    s2(_XS[0], _YS[0], lr=0.05)
    spmd_load_states(s2, prefix)
    for w, g in zip(want, [np.asarray(r) for r in s2._residuals]):
        np.testing.assert_array_equal(w, g)


def test_zero_checkpoint_residuals_restore_before_first_step(tmp_path):
    """The normal resume path loads the checkpoint into a step that has
    never compiled — the carry tensors don't exist yet. The saved carry
    must be stashed and applied when _init_residuals runs at the first
    step, not silently replaced with zeros."""
    comp = {"type": "2bit", "threshold": 0.05}
    net = _net()
    s = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, _mesh(4),
                               zero_stage=2, compression_params=comp)
    for i in range(2):
        s(_XS[i], _YS[i], lr=0.05)
    prefix = str(tmp_path / "ck")
    spmd_save_states(s, prefix)
    want = [np.asarray(r) for r in s._residuals]
    assert any(np.abs(w).max() > 0 for w in want)
    # never-stepped step: residuals are deferred to the first compile
    s2 = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, _mesh(4),
                                zero_stage=2, compression_params=comp)
    spmd_load_states(s2, prefix)
    assert s2._residuals is None and s2._pending_residual_chunks
    # post-compile restore (the roundtrip above) is the oracle: both
    # steps must carry identical residual state into the next update
    s3 = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, _mesh(4),
                                zero_stage=2, compression_params=comp)
    s3(_XS[0], _YS[0], lr=0.05)
    spmd_load_states(s3, prefix)
    l2 = float(s2(_XS[2], _YS[2], lr=0.05))
    l3 = float(s3(_XS[2], _YS[2], lr=0.05))
    assert s2._pending_residual_chunks is None
    assert l2 == l3, (l2, l3)
    for a, b in zip(s2._residuals, s3._residuals):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip([np.asarray(p) for p in s2._state[0]],
                    [np.asarray(p) for p in s3._state[0]]):
        np.testing.assert_array_equal(a, b)


def test_zero_checkpoint_residuals_dp_shrink_restarts_carry(tmp_path, caplog):
    """The carry's element layout is dp-interleaved, so a dp=4 save
    must NOT restore onto a dp=2 step (the chunks that would reveal
    the mismatch are span-filtered away — the guard compares the saved
    GLOBAL extent instead): the carry keeps its current value and one
    warning fires."""
    import logging

    comp = {"type": "2bit", "threshold": 0.05}
    net = _net()
    s = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, _mesh(4),
                               zero_stage=2, compression_params=comp)
    for i in range(2):
        s(_XS[i], _YS[i], lr=0.05)
    prefix = str(tmp_path / "ck")
    spmd_save_states(s, prefix)
    s2 = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, _mesh(2),
                                zero_stage=2, compression_params=comp)
    s2(_XS[0], _YS[0], lr=0.05)
    before = [np.asarray(r) for r in s2._residuals]
    with caplog.at_level(logging.WARNING, "mxnet_tpu.parallel.spmd"):
        spmd_load_states(s2, prefix)
    assert any("error-feedback carry" in m for m in caplog.messages)
    for w, g in zip(before, [np.asarray(r) for r in s2._residuals]):
        np.testing.assert_array_equal(w, g)


def test_grad_dtype_reduced_precision_wire():
    """grad_dtype casts each bucket to the wire dtype for the
    collective: fp32 (the native dtype) is a bitwise no-op, bf16
    changes the summed gradients slightly but trains equivalently."""
    def _run_wire(wire):
        mx.random.seed(42)
        net = _net()
        step = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, _mesh(4),
                                      overlap="ready", grad_dtype=wire)
        losses = [float(step(_XS[i], _YS[i], lr=0.05)) for i in range(4)]
        step.sync_to_block()
        return losses, _weights(net)

    l32, w32 = _run_wire(None)
    lsame, wsame = _run_wire(np.float32)
    assert l32 == lsame
    for a, b in zip(w32, wsame):
        np.testing.assert_array_equal(a, b)
    lbf, wbf = _run_wire(jnp.bfloat16)
    assert any(not np.array_equal(a, b) for a, b in zip(w32, wbf)), \
        "bf16 wire dtype changed nothing — grad_dtype is a no-op"
    for a, b in zip(w32, wbf):
        np.testing.assert_allclose(a, b, rtol=0.05, atol=0.02)


# ---------------------------------------------------------------------------
# plan/readiness unit contracts
# ---------------------------------------------------------------------------

def test_first_use_order_reflects_forward_order():
    """Reverse-mode AD yields the LAST-used parameter's gradient first:
    the readiness order must put later-used params earlier."""
    def f(params, x):
        h = x @ params[0]
        h = h @ params[1]
        return jnp.sum(h @ params[2])

    avals = [jax.ShapeDtypeStruct((4, 4), jnp.float32)] * 3
    order = ovl.first_use_order(
        f, (avals, jax.ShapeDtypeStruct((2, 4), jnp.float32)), 3)
    assert order == [2, 1, 0], order


def test_bucket_plan_padding_and_homogeneity():
    shapes = [(7,), (5,), (3, 3), (4,)]
    dtypes = ["float32", "float16", "float32", "float32"]
    plan = ovl.build_bucket_plan(shapes, dtypes, bucket_bytes=1 << 20,
                                 dp=4)
    for idxs in plan.buckets:
        assert len({dtypes[i] for i in idxs}) == 1
    # default order: reversed (the DDP heuristic)
    assert plan.order == (3, 2, 1, 0)
    for s, p in zip(plan.sizes, plan.pad_sizes):
        assert p % 4 == 0 and p >= s


def test_bucket_plan_splits_at_target_bytes():
    shapes = [(1024,)] * 6
    dtypes = ["float32"] * 6
    plan = ovl.build_bucket_plan(shapes, dtypes, bucket_bytes=8192)
    assert len(plan.buckets) == 3
    assert all(len(b) == 2 for b in plan.buckets)


def test_overlap_mode_env_knob(monkeypatch):
    monkeypatch.setenv("MXTPU_OVERLAP", "barrier")
    assert fusedstep.overlap_mode() == "barrier"
    monkeypatch.setenv("MXTPU_OVERLAP", "1")
    assert fusedstep.overlap_mode() == "ready"
    monkeypatch.setenv("MXTPU_OVERLAP", "bogus")
    assert fusedstep.overlap_mode() == "ready"  # warn-once fallback
    monkeypatch.setenv("MXTPU_ZERO_STAGE", "2")
    assert fusedstep.zero_stage() == 2
    monkeypatch.setenv("MXTPU_ZERO_STAGE", "7")
    assert fusedstep.zero_stage() == 0


def test_measure_overlap_probe_publishes_metrics():
    from mxnet_tpu import observability as obs

    prev = obs.set_enabled(True)
    try:
        out = parallel.measure_overlap(
            _net, loss_fn, "sgd", {}, _mesh(2), _X, _Y, lr=0.05,
            steps=2, warmup=1, modes=("nocomm", "ready", "staged"))
        assert set(out["exposed_comm_seconds"]) == {"ready", "staged"}
        assert out["hidden_fraction"] is None or \
            0.0 <= out["hidden_fraction"] <= 1.0
    finally:
        obs.set_enabled(prev)
        obs.reset()
