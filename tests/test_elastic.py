"""Live elasticity (ISSUE 11 tentpole): runtime grow/shrink without a
process restart — chaos-driven 4->2->4 resize with bit-exact state at
the boundary and zero committed steps lost, straggler detection via the
barrier-latency policy (chaos-stalled rank evicted BEFORE the watchdog
timeout would fire, pinned by a subprocess test), preemption-notice
pause points, in-memory snapshot descriptors + the --from-json
verifier, prefetcher cursor re-partition, and the extended chaos fault
sites (bucket collectives, resize) with the zero-dispatch-when-off
contract re-pinned."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest
from conftest import natsorted_items

import jax
import numpy as onp
from jax.sharding import Mesh

import mxnet_tpu as mx
from mxnet_tpu import gluon, observability as obs, parallel, resilience
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import chaos, elastic

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEVS = jax.devices()
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()


@pytest.fixture(autouse=True)
def _clean_chaos_and_monitor():
    yield
    chaos.reset()
    if elastic.monitor() is not None:
        elastic.monitor().detach()


def _build(width=16, classes=4):
    net = nn.HybridSequential()
    net.add(nn.Dense(width, activation="relu", in_units=8))
    net.add(nn.Dense(classes, in_units=width))
    net.initialize(init=mx.initializer.Constant(0.0))
    r = np.random.RandomState(7)
    for _, p in natsorted_items(net.collect_params().items()):
        p.set_data(mx.nd.array(
            r.uniform(-0.2, 0.2, p.shape).astype(np.float32)))
    net.hybridize()
    return net


def _batch(n=12):
    r = np.random.RandomState(0)
    return (r.rand(n, 8).astype(np.float32),
            r.randint(0, 4, (n,)).astype(np.float32))


def _canon(chunks):
    """Auto-name-independent view: natural-sorted positional order of
    keys, chunk spans + payload bytes."""
    out = []
    for key in sorted(chunks, key=lambda k: [
            int(t) if t.isdigit() else t
            for t in __import__("re").split(r"(\d+)", k)]):
        out.append(sorted(
            (tuple((sl.start, sl.stop) for sl in idx), d.tobytes())
            for idx, d in chunks[key]))
    return out


# ---------------------------------------------------------------------------
# the tentpole: chaos-driven 4->2->4 with bit-exact boundary state
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt,stage", [
    pytest.param("adam", 2, id="adam_zero2",  # middle zero stage; the
                 marks=pytest.mark.slow),     # 0/3 extremes stay tier-1
    pytest.param("sgd", 3, id="sgd_zero3"),
    pytest.param("adam", 0, id="adam_zero0"),
])
def test_chaos_resize_4_2_4_bitexact_zero_lost(opt, stage):
    """A mid-run 4->2->4 resize: zero committed steps lost, the
    in-memory snapshot at the shrink boundary is BIT-EXACT with an
    uninterrupted dp=4 reference (ZeRO-2/3 state crossing two pad
    layouts through the logical-span machinery), the first-phase losses
    match bit-exactly, and re-growing to dp=4 re-enters WARM (the
    cached executable, no recompile)."""
    x, y = _batch()
    hyper = {"momentum": 0.9} if opt == "sgd" else {}

    mesh4 = Mesh(onp.array(DEVS[:4]), ("dp",))
    net_ref = _build()
    mx.random.seed(42)
    ref = parallel.SPMDTrainStep(net_ref, loss_fn, opt, dict(hyper),
                                 mesh=mesh4, zero_stage=stage)
    ref_losses = [ref(x, y, lr=0.05) for _ in range(5)]
    ref_chunks = _canon(parallel.spmd_state_snapshot(ref)[0])

    chaos.configure("resize:6:2,resize:9:4")
    snap = {}
    net_el = _build()
    mx.random.seed(42)
    et = elastic.ElasticTrainer(
        net_el, loss_fn, opt, dict(hyper), devices=list(DEVS[:4]),
        device_pool=list(DEVS[:4]), zero_stage=stage,
        on_resize=lambda ev, ch: snap.setdefault("chunks", ch))
    losses = [et.step(x, y, lr=0.05) for _ in range(11)]
    chaos.reset()

    assert [e["to"] for e in et.resize_events] == [2, 4]
    assert et.resize_events[0]["step"] == 5  # boundary: 5 committed
    assert et.committed_steps == 11 and len(losses) == 11  # zero lost
    assert losses[:5] == ref_losses
    assert _canon(snap["chunks"]) == ref_chunks  # bit-exact handoff
    assert et.resize_events[1]["warm"] is True  # 2->4 reuses the step
    assert resilience.verify_descriptor(et.last_descriptor) == []
    et.close()


def test_multi_eviction_one_drain_removes_the_right_devices():
    """Two ranks flagged in the SAME drain evict the right devices:
    rank indices refer to the enqueue-time device list, so they are
    applied as a set against it (a sequential pop would shift indices
    and evict a healthy peer). Grow-after-evict in one drain must not
    re-add a just-evicted device."""
    x, y = _batch()
    et = elastic.ElasticTrainer(_build(), loss_fn, "sgd", {},
                                devices=list(DEVS[:4]),
                                device_pool=list(DEVS[:6]),
                                min_devices=1)
    et.step(x, y, lr=0.05)
    et.monitor._enqueue({"kind": "dead_peer", "reason": "dead_peer",
                         "target": None, "rank": 1, "detail": ""})
    et.monitor._enqueue({"kind": "straggler", "reason": "straggler",
                         "target": None, "rank": 2, "detail": ""})
    et.step(x, y, lr=0.05)
    assert et.devices == [DEVS[0], DEVS[3]], et.devices  # 1 AND 2 out
    # evicted devices never return via a same-drain grow
    et.monitor._enqueue({"kind": "straggler", "reason": "straggler",
                         "target": None, "rank": 1, "detail": ""})
    et.monitor.request_resize(3, reason="grow")
    et.step(x, y, lr=0.05)
    assert DEVS[3] not in et.devices and len(et.devices) == 3, \
        et.devices
    et.close()


def test_resize_drops_old_topology_state():
    """Warm re-entry keeps only the COMPILED executable per topology:
    the old step's full param/opt-state copy is dropped at resize (one
    model's worth of device memory per topology otherwise), and a
    later re-entry re-initializes + restores over it."""
    x, y = _batch()
    chaos.configure("resize:3:2,resize:5:4")
    et = elastic.ElasticTrainer(_build(), loss_fn, "adam", {},
                                devices=list(DEVS[:4]), zero_stage=2)
    l1 = [et.step(x, y, lr=0.05) for _ in range(2)]
    old = et.spmd_step
    et.step(x, y, lr=0.05)  # shrink fires here
    assert et.spmd_step is not old and old._state is None
    et.step(x, y, lr=0.05)
    et.step(x, y, lr=0.05)  # grow back: re-enters the dropped step
    chaos.reset()
    assert et.spmd_step is old and old._state is not None
    assert et.resize_events[1]["warm"] is True
    et.step(x, y, lr=0.05)  # and it still trains
    et.close()


def test_grow_and_clip_contracts():
    """Grow extends from the pool (spot add), a target beyond the pool
    clips to it, and a shrink below min_devices clips up to it."""
    x, y = _batch()
    et = elastic.ElasticTrainer(_build(), loss_fn, "sgd", {},
                                devices=list(DEVS[:2]),
                                device_pool=list(DEVS[:4]),
                                min_devices=2)
    et.step(x, y, lr=0.05)
    et.monitor.request_resize(8, reason="grow")  # pool only has 4
    et.step(x, y, lr=0.05)
    assert len(et.devices) == 4
    et.monitor.request_resize(1, reason="shrink")  # min_devices=2
    et.step(x, y, lr=0.05)
    assert len(et.devices) == 2
    et.close()


# ---------------------------------------------------------------------------
# straggler: barrier-latency policy evicts a chaos-stalled rank
# ---------------------------------------------------------------------------

def test_straggler_policy_math():
    mon = elastic.MembershipMonitor(straggler_factor=3.0,
                                    min_samples=3, min_latency_s=0.01)
    for i in range(3):
        for r in range(4):
            mon.observe_latency(r, 0.05 if r == 2 else 0.001)
    assert mon.straggler_ranks() == [2]
    sigs = mon.drain()
    assert [s["kind"] for s in sigs] == ["straggler"]  # flagged ONCE
    assert sigs[0]["rank"] == 2
    # below the absolute floor nothing is flagged, however skewed
    mon2 = elastic.MembershipMonitor(straggler_factor=3.0,
                                     min_samples=3, min_latency_s=0.01)
    for i in range(3):
        for r in range(4):
            mon2.observe_latency(r, 0.005 if r == 1 else 0.0001)
    assert mon2.straggler_ranks() == []
    # too few samples: no verdict
    mon3 = elastic.MembershipMonitor(straggler_factor=3.0, min_samples=5)
    for r in range(4):
        mon3.observe_latency(r, 0.5 if r == 0 else 0.001)
    assert mon3.straggler_ranks() == []


def test_straggler_evicted_in_process():
    x, y = _batch()
    chaos.configure("stall@rank2:p1:0.05")
    mon = elastic.MembershipMonitor(straggler_factor=3.0,
                                    min_latency_s=0.02)
    et = elastic.ElasticTrainer(_build(), loss_fn, "sgd",
                                {"momentum": 0.9},
                                devices=list(DEVS[:4]), monitor=mon,
                                zero_stage=2)
    for _ in range(8):
        et.step(x, y, lr=0.05)
        if et.resize_events:
            break
    chaos.reset()
    assert et.resize_events and \
        et.resize_events[0]["reason"] == "straggler"
    assert len(et.devices) == 3 and DEVS[2] not in et.devices
    # training continues on the shrunk mesh (24 % 3 == 0)
    et.step(x, y, lr=0.05)
    et.close()


def test_straggler_evicted_before_watchdog_subprocess(tmp_path):
    """The acceptance pin: in a fresh process with the barrier watchdog
    armed (MXTPU_BARRIER_TIMEOUT_S), a chaos-stalled peer is detected
    via the latency histogram and resized out with the job still
    running — in far less wall time than the watchdog timeout that
    would otherwise have been the first sign of trouble."""
    timeout_s = 60.0
    child = f"""
import json, time, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {ROOT!r})
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.resilience import chaos, elastic
from mxnet_tpu.gluon import nn
devs = jax.devices()
assert len(devs) >= 4, devs
net = nn.HybridSequential()
net.add(nn.Dense(16, activation="relu", in_units=8))
net.add(nn.Dense(4, in_units=16))
net.initialize(init=mx.initializer.Xavier()); net.hybridize()
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
r = np.random.RandomState(0)
x = r.rand(12, 8).astype(np.float32)
y = r.randint(0, 4, (12,)).astype(np.float32)
mon = elastic.MembershipMonitor(min_latency_s=0.02)
et = elastic.ElasticTrainer(net, loss_fn, "sgd", {{"momentum": 0.9}},
                            devices=list(devs[:4]), monitor=mon,
                            zero_stage=2)
t0 = time.monotonic()
for i in range(12):
    et.step(x, y, lr=0.05)
    if et.resize_events:
        break
wall = time.monotonic() - t0
et.step(x, y, lr=0.05)   # the job is ALIVE after the eviction
print("RESULT " + json.dumps({{
    "events": et.resize_events, "wall": wall,
    "devices": len(et.devices)}}))
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f) + \
        " --xla_force_host_platform_device_count=4"
    env["MXTPU_CHAOS"] = "stall@rank1:p1:0.05"
    env["MXTPU_STRAGGLER_FACTOR"] = "3.0"
    env["MXTPU_BARRIER_TIMEOUT_S"] = str(timeout_s)
    res = subprocess.run([sys.executable, "-c", child], env=env,
                         capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stderr[-2000:]
    line = [ln for ln in res.stdout.splitlines()
            if ln.startswith("RESULT ")][-1]
    data = json.loads(line[len("RESULT "):])
    assert data["events"], data
    assert data["events"][0]["reason"] == "straggler", data
    assert data["devices"] == 3, data
    # resized out well before the watchdog would have fired
    assert data["wall"] < timeout_s / 2, data


# ---------------------------------------------------------------------------
# preemption notice: resize + the Trainer pause point
# ---------------------------------------------------------------------------

def test_preempt_notice_shrink_then_grow(tmp_path):
    x, y = _batch()
    notice = tmp_path / "notice"
    mon = elastic.MembershipMonitor(notice_path=str(notice))
    et = elastic.ElasticTrainer(_build(), loss_fn, "adam", {},
                                devices=list(DEVS[:4]), monitor=mon,
                                zero_stage=2)
    et.step(x, y, lr=0.05)
    notice.write_text("shrink:2")
    et.step(x, y, lr=0.05)
    assert len(et.devices) == 2
    assert et.resize_events[0]["reason"] == "notice"
    time.sleep(0.01)  # distinct mtime
    notice.write_text("grow:4")
    et.step(x, y, lr=0.05)
    assert len(et.devices) == 4
    assert et.resize_events[1]["warm"] is True
    et.close()


def test_preempt_notice_proactive_checkpoint_at_pause_point(tmp_path):
    """The Gluon path: a preemption notice turns into a PROACTIVE async
    checkpoint at the next Trainer.step boundary (the pause point) —
    no mesh to rebuild, but the recovery point is fresh before the
    SIGTERM even lands."""
    notice = tmp_path / "notice"
    net = _build()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    mgr = resilience.CheckpointManager(
        str(tmp_path / "ck"), every_n_steps=10 ** 6, net=net,
        trainer=tr).attach(tr)
    mon = elastic.MembershipMonitor(notice_path=str(notice)).attach()
    x, y = _batch(8)
    X, Y = mx.nd.array(x), mx.nd.array(y)
    try:
        from mxnet_tpu import autograd

        def one():
            with autograd.record():
                l = loss_fn(net(X), Y)
            l.backward()
            tr.step(8)

        one(), one()
        assert mgr.commits == 0  # interval never fires
        notice.write_text("")    # plain preemption notice
        one()
        assert mgr.flush(timeout=60)
        assert mgr.commits == 1
        man = json.load(open(os.path.join(mgr.last_saved,
                                          "MANIFEST.json")))
        assert man["reason"] == "preempt_notice"
        # one notice = one checkpoint (consumed, not re-fired)
        one()
        mgr.flush(timeout=60)
        assert mgr.commits == 1
    finally:
        mon.detach()
        mgr.close()


# ---------------------------------------------------------------------------
# descriptors: verify_descriptor + the --from-json CLI
# ---------------------------------------------------------------------------

def test_descriptor_verify_and_cli(tmp_path):
    x, y = _batch()
    et = elastic.ElasticTrainer(_build(), loss_fn, "adam", {},
                                devices=list(DEVS[:4]), zero_stage=2)
    et.step(x, y, lr=0.05)
    desc = et.snapshot(reason="manual")
    assert resilience.verify_descriptor(desc) == []
    p = et.dump_descriptor(tmp_path / "desc.json")
    tool = os.path.join(ROOT, "tools", "verify_checkpoint.py")
    res = subprocess.run([sys.executable, tool, "--from-json", p],
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    assert res.stdout.startswith("OK"), res.stdout

    # corruption is CAUGHT: nbytes mismatch, missing opt leaf, bad fmt
    bad = json.loads(open(p).read())
    k = next(iter(bad["tensors"]))
    bad["tensors"][k]["nbytes"] += 4
    name = next(iter(bad["extras"]["opt_leaves"]))
    bad["extras"]["opt_leaves"][name] += 1
    bad_p = tmp_path / "bad.json"
    bad_p.write_text(json.dumps(bad))
    probs = resilience.verify_descriptor(bad)
    assert any("nbytes" in q for q in probs), probs
    assert any("opt state leaf" in q for q in probs), probs
    res = subprocess.run([sys.executable, tool, "--from-json",
                          str(bad_p)],
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 1, res.stdout
    assert resilience.verify_descriptor({"format": "nope"}) \
        == ["unknown snapshot format 'nope'"]
    et.close()


# ---------------------------------------------------------------------------
# input pipeline: cursor-preserving repartition
# ---------------------------------------------------------------------------

def test_prefetcher_repartition_preserves_cursor_and_data():
    from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher

    mesh4 = Mesh(onp.array(DEVS[:4]), ("dp",))
    mesh2 = Mesh(onp.array(DEVS[:2]), ("dp",))
    batches = [np.full((12, 4), i, np.float32) for i in range(6)]
    pf = DevicePrefetcher(batches, mesh=mesh4, depth=4)
    it = iter(pf)
    got = [next(it) for _ in range(2)]
    assert pf.cursor == 2
    pf.repartition(mesh=mesh2)  # mid-epoch, staged batches in flight
    got += list(it)
    assert pf.cursor == 6
    # every batch delivered exactly once, in order, values intact
    vals = [float(np.asarray(b.data)[0, 0]) for b in got]
    assert vals == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
    # everything delivered after the repartition lives on the 2-mesh
    for b in got[2:]:
        assert set(b.data.sharding.device_set) <= set(DEVS[:2]), \
            b.data.sharding
    pf.close()


def test_superstep_ring_repartition_delegates():
    from mxnet_tpu.gluon.data.prefetcher import SuperstepRing

    mesh4 = Mesh(onp.array(DEVS[:4]), ("dp",))
    mesh2 = Mesh(onp.array(DEVS[:2]), ("dp",))
    batches = [(np.full((8, 4), i, np.float32),
                np.zeros((8,), np.float32)) for i in range(4)]
    ring = SuperstepRing(batches, k=2, mesh=mesh4)
    it = iter(ring)
    g1, k1 = next(it)
    assert k1 == 2 and ring.cursor == 2
    ring.repartition(mesh=mesh2)
    g2, k2 = next(it)
    assert k2 == 2 and ring.cursor == 4
    assert set(g2[0].data.sharding.device_set) <= set(DEVS[:2])
    ring.close()


# ---------------------------------------------------------------------------
# chaos: new fault sites + the zero-dispatch-when-off contract
# ---------------------------------------------------------------------------

def test_chaos_bucket_collective_faults_surface_loudly():
    x, y = _batch()
    mesh4 = Mesh(onp.array(DEVS[:4]), ("dp",))
    chaos.configure("collective@bucket_psum:1")
    st = parallel.SPMDTrainStep(_build(), loss_fn, "sgd", {},
                                mesh=mesh4, zero_stage=0)
    with pytest.raises(chaos.ChaosInjectedError):
        st(x, y, lr=0.05)
    chaos.reset()
    chaos.configure("collective@bucket_psum_scatter:1")
    st2 = parallel.SPMDTrainStep(_build(), loss_fn, "adam", {},
                                 mesh=mesh4, zero_stage=2)
    with pytest.raises(chaos.ChaosInjectedError):
        st2(x, y, lr=0.05)
    chaos.reset()
    chaos.configure("collective@bucket_allgather:1")
    st3 = parallel.SPMDTrainStep(_build(), loss_fn, "sgd", {},
                                 mesh=mesh4, zero_stage=3)
    with pytest.raises(chaos.ChaosInjectedError):
        st3(x, y, lr=0.05)
    chaos.reset()


def test_chaos_resize_spec_parsing():
    faults = chaos.configure("resize:8:2,resize@elastic:16:4")
    assert faults[0]["kind"] == "resize" and faults[0]["arg"] == "2"
    assert faults[1]["site"] == "elastic"
    chaos.reset()
    with pytest.raises(mx.MXNetError):
        chaos.configure("resize:8")  # target count is mandatory
    chaos.reset()
    # per-rank sites parse (digit-bearing site names)
    faults = chaos.configure("stall@rank12:p0.5:0.1,seed=3")
    assert faults[0]["site"] == "rank12"
    chaos.reset()


def test_chaos_off_adds_zero_dispatches_elastic_loop():
    """The new fault sites keep the zero-cost contract: the per-step
    dispatch count of the elastic SPMD loop (bucket collectives inside,
    resize poll at the boundary) is IDENTICAL with chaos off and with
    chaos armed-but-never-firing."""
    x, y = _batch()
    prev = obs.set_enabled(True)
    try:
        def measure(spec):
            if spec:
                chaos.configure(spec)
            et = elastic.ElasticTrainer(
                _build(), loss_fn, "sgd", {}, devices=list(DEVS[:4]),
                zero_stage=2)
            et.step(x, y, lr=0.05)  # warm: compile
            c0 = obs.XLA_DISPATCH_TOTAL.total()
            for _ in range(4):
                et.step(x, y, lr=0.05)
            out = (obs.XLA_DISPATCH_TOTAL.total() - c0) / 4
            et.close()
            chaos.reset()
            return out

        base = measure(None)
        armed = measure("resize:999999:2,collective@bucket_psum:999999")
        assert base == armed, (base, armed)
    finally:
        obs.set_enabled(prev)


# ---------------------------------------------------------------------------
# kvstore hook
# ---------------------------------------------------------------------------

def test_kvstore_reset_world_clears_reduce_cache():
    from mxnet_tpu.kvstore import dist as kvd

    kvd._REDUCE["mesh"] = "stale"
    kvd._REDUCE["fn"] = "stale"
    kvd.reset_world()
    assert kvd._REDUCE["mesh"] is None and kvd._REDUCE["fn"] is None


# ---------------------------------------------------------------------------
# cross-topology elastic restore (PR19): the snapshot is the state,
# the layout is the executor's business
# ---------------------------------------------------------------------------

def test_composed4d_snapshot_crosses_topology_bitexact():
    """(dp=4, pp=1, zero=0) -> (dp=2, pp=2, zero=2): restoring the
    chunk snapshot into a DIFFERENT mesh shape and ZeRO stage, then
    re-snapshotting, reproduces every tensor BIT-EXACTLY — and the two
    trainers continue with identical losses."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P  # noqa: F401

    from mxnet_tpu.parallel.composed import Composed4DStep
    from mxnet_tpu.parallel.mesh import composed_mesh

    L, D, B, M = 4, 8, 16, 4
    rng = np.random.RandomState(0)
    W0 = (rng.randn(L, D, D) * 0.3).astype(np.float32)
    b0 = (rng.randn(L, D) * 0.1).astype(np.float32)
    x = rng.randn(B, D).astype(np.float32)
    y = rng.randn(B, D).astype(np.float32)

    def stage_fn(p, h):
        W, b = p
        return jnp.tanh(h @ W + b)

    def loss_fn(o, yy):
        return jnp.mean((o - yy) ** 2)

    def build(mesh, zero):
        return Composed4DStep(stage_fn,
                              (jnp.asarray(W0), jnp.asarray(b0)),
                              mesh, loss_fn, optimizer="adam",
                              num_microbatches=M, zero_stage=zero)

    mesh_a = composed_mesh(dp=4, devices=list(jax.devices()[:4]))
    mesh_b = composed_mesh(dp=2, pp=2, devices=list(jax.devices()[:4]))
    step_a = build(mesh_a, 0)
    for _ in range(3):  # adam state becomes nontrivial
        step_a(x, y, lr=0.02)
    chunks_a, extents = step_a.state_snapshot()

    step_b = build(mesh_b, 2)
    step_b.restore_chunks(chunks_a)
    chunks_b, _ = step_b.state_snapshot()
    assert set(chunks_a) == set(chunks_b), \
        set(chunks_a) ^ set(chunks_b)
    for key in natsorted_items(chunks_a):
        (idx_a, arr_a), = chunks_a[key]
        (idx_b, arr_b), = chunks_b[key]
        assert idx_a == idx_b, key
        np.testing.assert_array_equal(arr_a, arr_b, err_msg=key)

    # and BACK across the crossing: restore A's successor from B
    step_a2 = build(composed_mesh(dp=4, devices=list(jax.devices()[:4])),
                    0)
    step_a2.restore_chunks(chunks_b)
    chunks_a2, _ = step_a2.state_snapshot()
    for key in natsorted_items(chunks_a):
        np.testing.assert_array_equal(chunks_a[key][0][1],
                                      chunks_a2[key][0][1], err_msg=key)

    la = [float(step_a(x, y, lr=0.02)) for _ in range(3)]
    lb = [float(step_b(x, y, lr=0.02)) for _ in range(3)]
    np.testing.assert_allclose(lb, la, atol=2e-5)
