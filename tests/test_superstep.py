"""K-step on-device superstep (PR6 tentpole): whole-program capture of
fwd+bwd+update into one lax.scan dispatch — parity vs the single-step
fused path (params, optimizer state, loss trajectory) for sgd/adam x
AMP off/bf16/fp16 at K in {1, 2, 4}, the dispatch-count amortization
regression, per-iteration in-scan fp16 overflow skip, state migration
between paths, the staging ring contract, and the scan-compatible
bucketed psum."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import (amp, autograd, fusedstep, gluon,
                       observability as obs, parallel)
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data.prefetcher import (DevicePrefetcher,
                                             SuperstepRing, stack_batches)


@pytest.fixture(autouse=True)
def _fused_on():
    prev = fusedstep.set_enabled(True)
    yield
    fusedstep.set_enabled(prev)


loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()


def _batch(i, n=16, width=8, classes=3, dtype=None, poison=False):
    rs = np.random.RandomState(100 + i)
    x = rs.randn(n, width).astype(np.float32)
    if poison:
        x[0, 0] = np.inf
    y = rs.randint(0, classes, (n,)).astype(np.float32)
    if dtype:
        x = x.astype(dtype)
    return mx.nd.array(x, dtype=str(x.dtype)), mx.nd.array(y)


def _build(opt="sgd", amp_dtype=None, bn=False, deferred=False,
           scale_window=2000):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu",
                     **({} if deferred else {"in_units": 8})))
    if bn:
        net.add(nn.BatchNorm())
    net.add(nn.Dense(3, **({} if deferred else {"in_units": 16})))
    net.initialize(init=mx.initializer.Xavier())
    if amp_dtype:
        amp.convert_model(net)
    net.hybridize()
    params = {"learning_rate": 0.05, "multi_precision": bool(amp_dtype)}
    if opt == "sgd":
        params["momentum"] = 0.9
    tr = gluon.Trainer(net.collect_params(), opt, params, kvstore=None)
    if amp_dtype == "float16":
        tr._amp_loss_scaler = amp.LossScaler(
            init_scale=1024.0, scale_factor=2.0, scale_window=scale_window)
    return net, tr


def _weights(net):
    return [p.data().asnumpy().astype(np.float32) for _, p in
            sorted(net.collect_params().items(),
                   key=lambda kv: kv[0].split("_", 1)[-1])]


def _opt_states(net, tr):
    # ordered by the block's layer-REGISTRATION order: param names carry
    # run-dependent global counters, so any name-based ordering flips at
    # digit boundaries (dense10 < dense9) between the two compared runs
    out = []
    for _, p in net.collect_params().items():
        st = tr._fused_states.get(p.name)
        if st is not None:
            out.append(tuple(np.asarray(leaf, dtype=np.float32)
                             for leaf in st))
    return out


def _run_single(steps, opt="sgd", amp_dtype=None, poison=None, bn=False,
                scale_window=2000):
    net, tr = _build(opt, amp_dtype, bn=bn, scale_window=scale_window)
    losses = []
    for i in range(steps):
        x, y = _batch(i, dtype=amp_dtype, poison=(i == poison))
        with autograd.record():
            l = loss_fn(net(x), y)
            if amp_dtype == "float16":
                with amp.scale_loss(l, tr) as sl:
                    sl.backward()
        if amp_dtype != "float16":
            l.backward()
        tr.step(16)
        losses.append(float(jnp.mean(l.data.astype(jnp.float32))))
    return net, tr, losses


def _run_super(steps, k, opt="sgd", amp_dtype=None, poison=None, bn=False,
               scale_window=2000):
    net, tr = _build(opt, amp_dtype, bn=bn, scale_window=scale_window)
    ss = gluon.Superstep(net, loss_fn, tr, k=k)
    losses = []
    for g in range(steps // k):
        xs = stack_batches([_batch(g * k + i, dtype=amp_dtype,
                                   poison=(g * k + i == poison))[0]
                            for i in range(k)])
        ys = stack_batches([_batch(g * k + i)[1] for i in range(k)])
        l = ss.step(xs, ys, 16)
        losses.extend(np.asarray(l.data, dtype=np.float32).tolist())
    assert isinstance(ss._plan, dict), \
        f"superstep declined for {opt}/{amp_dtype}: {ss._plan}"
    return net, tr, losses


# ---------------------------------------------------------------------------
# parity: K-step superstep == single-step fused path
# (params, optimizer state, loss trajectory)
# ---------------------------------------------------------------------------

# Full matrix is opt x dtype x k (18 cells). Tier-1 keeps every
# opt/dtype combo at the real superstep depth (k=4) plus the k-axis
# itself on one combo; the remaining cells only re-cross axes that are
# each already covered and run under -m slow.
@pytest.mark.parametrize("opt,amp_dtype,tol,k", [
    pytest.param("sgd", None, 1e-5, 1),
    pytest.param("sgd", None, 1e-5, 2, marks=pytest.mark.slow),
    pytest.param("sgd", None, 1e-5, 4),
    pytest.param("adam", None, 1e-5, 1, marks=pytest.mark.slow),
    pytest.param("adam", None, 1e-5, 2, marks=pytest.mark.slow),
    pytest.param("adam", None, 1e-5, 4, marks=pytest.mark.slow),
    pytest.param("sgd", "bfloat16", 2e-2, 1, marks=pytest.mark.slow),
    pytest.param("sgd", "bfloat16", 2e-2, 2, marks=pytest.mark.slow),
    pytest.param("sgd", "bfloat16", 2e-2, 4),
    pytest.param("adam", "bfloat16", 2e-2, 1, marks=pytest.mark.slow),
    pytest.param("adam", "bfloat16", 2e-2, 2, marks=pytest.mark.slow),
    pytest.param("adam", "bfloat16", 2e-2, 4, marks=pytest.mark.slow),
    pytest.param("sgd", "float16", 2e-3, 1, marks=pytest.mark.slow),
    pytest.param("sgd", "float16", 2e-3, 2, marks=pytest.mark.slow),
    pytest.param("sgd", "float16", 2e-3, 4, marks=pytest.mark.slow),
    pytest.param("adam", "float16", 2e-3, 1, marks=pytest.mark.slow),
    pytest.param("adam", "float16", 2e-3, 2, marks=pytest.mark.slow),
    pytest.param("adam", "float16", 2e-3, 4),
])
def test_superstep_parity(k, opt, amp_dtype, tol):
    if amp_dtype:
        amp.init(amp_dtype)
    try:
        steps = 2 * k if k > 1 else 4
        n1, t1, l1 = _run_single(steps, opt, amp_dtype)
        n2, t2, l2 = _run_super(steps, k, opt, amp_dtype)
    finally:
        if amp_dtype:
            amp.disable()
    np.testing.assert_allclose(l1, l2, rtol=tol, atol=tol)
    for a, b in zip(_weights(n1), _weights(n2)):
        np.testing.assert_allclose(a, b, rtol=tol, atol=tol)
    # optimizer state parity: the single-step run's fused states vs the
    # superstep carry (both live in trainer._fused_states)
    s1, s2 = _opt_states(n1, t1), _opt_states(n2, t2)
    assert len(s1) == len(s2) and s1
    for st1, st2 in zip(s1, s2):
        assert len(st1) == len(st2)
        for a, b in zip(st1, st2):
            np.testing.assert_allclose(a, b, rtol=tol, atol=tol)
    if amp_dtype == "float16":
        assert t1._amp_loss_scaler.loss_scale == \
            t2._amp_loss_scaler.loss_scale


def test_superstep_parity_batchnorm_aux_carry():
    """BN running stats (non-diff aux params) ride the scan carry and
    match the single-step trajectory."""
    n1, _, _ = _run_single(8, bn=True)
    n2, _, _ = _run_super(8, 4, bn=True)
    for a, b in zip(_weights(n1), _weights(n2)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_superstep_deferred_init_probe():
    """Uninitialized (deferred) params resolve via the slot-0 predict
    probe without consuming an update."""
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(3))
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=None)
    ss = gluon.Superstep(net, loss_fn, tr, k=4)
    xs = stack_batches([_batch(i)[0] for i in range(4)])
    ys = stack_batches([_batch(i)[1] for i in range(4)])
    ss.step(xs, ys, 16)
    ss.step(stack_batches([_batch(4 + i)[0] for i in range(4)]),
            stack_batches([_batch(4 + i)[1] for i in range(4)]), 16)
    assert isinstance(ss._plan, dict)
    n1, _, _ = _run_single(8)
    for a, b in zip(_weights(n1), _weights(net)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# in-scan fp16 overflow skip: iteration i overflows, i+1 still applies
# ---------------------------------------------------------------------------

def test_superstep_fp16_overflow_skip_mid_scan():
    amp.init("float16")
    try:
        # poison iteration 1 of 8 (inside the first K=4 superstep)
        n1, t1, _ = _run_single(8, amp_dtype="float16", poison=1)
        n2, t2, _ = _run_super(8, 4, amp_dtype="float16", poison=1)
    finally:
        amp.disable()
    # exactly one overflow: scale backed off 1024 -> 512 once, and the
    # weights kept training (iterations 2..7 applied) with parity
    assert t2._amp_loss_scaler.loss_scale == 512.0
    assert t2._amp_loss_scaler.overflow_total == 1
    assert t1._amp_loss_scaler.loss_scale == 512.0
    for a, b in zip(_weights(n1), _weights(n2)):
        assert np.isfinite(a).all() and np.isfinite(b).all()
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-3)


def test_superstep_fp16_scale_growth_in_scan():
    """The growth branch also runs per-iteration in-graph: a small
    scale_window grows the scale inside one superstep."""
    amp.init("float16")
    try:
        _, tr, _ = _run_super(4, 4, amp_dtype="float16", scale_window=2)
    finally:
        amp.disable()
    # 4 clean iterations, window 2 -> two growth events: 1024 -> 4096
    assert tr._amp_loss_scaler.loss_scale == 4096.0


# ---------------------------------------------------------------------------
# dispatch-count amortization regression
# ---------------------------------------------------------------------------

def _dispatch_total():
    return obs.XLA_DISPATCH_TOTAL.total()


def test_superstep_dispatch_amortization():
    prev = obs.set_enabled(True)
    obs.reset()
    try:
        k = 4
        # single-step fused loop (today's behavior), warmed
        net, tr = _build()
        for i in range(2):
            x, y = _batch(i)
            with autograd.record():
                l = loss_fn(net(x), y)
            l.backward()
            tr.step(16)
        c0 = _dispatch_total()
        for i in range(k):
            x, y = _batch(i)
            with autograd.record():
                l = loss_fn(net(x), y)
            l.backward()
            tr.step(16)
        per_step_k1 = (_dispatch_total() - c0) / k

        net2, tr2 = _build()
        ss = gluon.Superstep(net2, loss_fn, tr2, k=k)
        xs = stack_batches([_batch(i)[0] for i in range(k)])
        ys = stack_batches([_batch(i)[1] for i in range(k)])
        ss.step(xs, ys, 16)  # warm: capture + compile
        c0 = _dispatch_total()
        ss.step(xs, ys, 16)
        per_step_kk = (_dispatch_total() - c0) / k
        # ONE dispatch per K steps: amortization >= K (vs >= 3 executables
        # per step on the one-step fused path)
        assert per_step_kk <= 1.0 / k + 1e-9, per_step_kk
        assert per_step_k1 / per_step_kk >= k, (per_step_k1, per_step_kk)
        # telemetry: superstep counters advanced, gauges have K-cadence
        assert obs.SUPERSTEP_ITERATIONS_TOTAL.total() == 2 * k
        assert obs.SUPERSTEP_TOTAL.total() == 2
    finally:
        obs.set_enabled(prev)
        obs.reset()


def test_superstep_amortization_report_line():
    """tools/telemetry_report.py prints the dispatches-per-step line
    from trainer.superstep trace events."""
    import sys
    sys.path.insert(0, mx.__path__[0].rsplit("/", 1)[0])
    from tools.telemetry_report import render_superstep

    events = [{"name": "trainer.superstep", "cat": "trainer",
               "dur": 4000.0, "args": {"k": 8, "step": 8}},
              {"name": "trainer.superstep", "cat": "trainer",
               "dur": 3900.0, "args": {"k": 8, "step": 16}}]
    out = render_superstep(events)
    assert "2 dispatches covering 16 training steps" in out
    assert "0.125 dispatches/step" in out
    assert render_superstep([]) == ""
    # malformed args must not crash (crash-proof contract)
    assert "1 dispatches" in render_superstep(
        [{"name": "trainer.superstep", "args": None}])


# ---------------------------------------------------------------------------
# migration to/from the single-step plan
# ---------------------------------------------------------------------------

def test_superstep_migration_keeps_momentum():
    """step -> superstep -> step interleaving matches an all-single-step
    run exactly (optimizer state migrates both ways, never resets)."""
    n1, _, _ = _run_single(8)
    net, tr = _build()
    ss = gluon.Superstep(net, loss_fn, tr, k=4)
    for i in range(2):
        x, y = _batch(i)
        with autograd.record():
            l = loss_fn(net(x), y)
        l.backward()
        tr.step(16)
    ss.step(stack_batches([_batch(2 + i)[0] for i in range(4)]),
            stack_batches([_batch(2 + i)[1] for i in range(4)]), 16)
    for i in range(6, 8):
        x, y = _batch(i)
        with autograd.record():
            l = loss_fn(net(x), y)
        l.backward()
        tr.step(16)
    for a, b in zip(_weights(n1), _weights(net)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_superstep_does_not_rebuild_one_step_plan():
    """Interleaving superstep and trainer.step must NOT drop the
    one-step fused plan (a rebuild retraces its executable): the plan
    object survives and only its state copies re-migrate by identity."""
    net, tr = _build()
    ss = gluon.Superstep(net, loss_fn, tr, k=2)
    x, y = _batch(0)
    with autograd.record():
        l = loss_fn(net(x), y)
    l.backward()
    tr.step(16)
    plan_before = tr._fused
    assert isinstance(plan_before, dict)
    ss.step(stack_batches([_batch(1 + i)[0] for i in range(2)]),
            stack_batches([_batch(1 + i)[1] for i in range(2)]), 16)
    assert tr._fused is plan_before  # not invalidated by the superstep
    x, y = _batch(3)
    with autograd.record():
        l = loss_fn(net(x), y)
    l.backward()
    tr.step(16)
    assert tr._fused is plan_before  # same compiled plan, states refreshed
    n1, _, _ = _run_single(4)
    for a, b in zip(_weights(n1), _weights(net)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_superstep_adam_update_counts_advance_by_k():
    """Bias-correction t advances per scan iteration and the host-side
    update counts advance by K per dispatch (scheduler cadence)."""
    net, tr = _build(opt="adam")
    ss = gluon.Superstep(net, loss_fn, tr, k=4)
    ss.step(stack_batches([_batch(i)[0] for i in range(4)]),
            stack_batches([_batch(i)[1] for i in range(4)]), 16)
    assert tr._optimizer.num_update == 4
    ts = [int(st[-1]) for st in tr._fused_states.values()]
    assert all(t == 4 for t in ts), ts


class _StepDownSched(mx.lr_scheduler.LRScheduler):
    """Probe schedule: records every sampled count, steps 0.1 -> 0.01
    after update 2 — INSIDE the first K=4 superstep, so per-iteration
    sampling is observable in the weights, not just the counts."""

    def __init__(self, seen):
        super().__init__()
        self.seen = seen

    def __call__(self, num_update):
        self.seen.append(num_update)
        return 0.1 if num_update <= 2 else 0.01


def _build_sched(seen):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Dense(3, in_units=8)
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1,
                        "lr_scheduler": _StepDownSched(seen)},
                       kvstore=None)
    return net, tr


def test_superstep_lr_scheduler_per_iteration():
    """ROADMAP item 5 remainder: the scheduler is sampled PER SCAN
    ITERATION (counts first_update .. first_update+K-1 ride the scan as
    a [K] lr vector), so a schedule boundary inside a superstep applies
    at the right iteration — no more K-step lr granularity."""
    seen = []
    net, tr = _build_sched(seen)
    ss = gluon.Superstep(net, loss_fn, tr, k=2)
    for g in range(2):
        ss.step(stack_batches([_batch(g * 2 + i)[0] for i in range(2)]),
                stack_batches([_batch(g * 2 + i)[1] for i in range(2)]),
                16)
    # sampled once per iteration, at exactly the single-step counts
    assert seen == [1, 2, 3, 4], seen


def test_superstep_lr_schedule_parity_vs_single_step():
    """A schedule stepping down mid-superstep produces bit-comparable
    weights to the single-step loop over the same batches (the parity
    pin for the per-iteration lr vector)."""
    net_s, tr_s = _build_sched([])
    for i in range(4):
        x, y = _batch(i)
        with autograd.record():
            l = loss_fn(net_s(x), y)
        l.backward()
        tr_s.step(16)
    net_k, tr_k = _build_sched([])
    ss = gluon.Superstep(net_k, loss_fn, tr_k, k=4)
    ss.step(stack_batches([_batch(i)[0] for i in range(4)]),
            stack_batches([_batch(i)[1] for i in range(4)]), 16)
    for a, b in zip(_weights(net_s), _weights(net_k)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# fallback contract
# ---------------------------------------------------------------------------

def test_superstep_unfusable_optimizer_falls_back_and_logs(caplog):
    fusedstep.reset_fallback_log()
    mx.random.seed(0)
    net = nn.Dense(3, in_units=8)
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adagrad",
                       {"learning_rate": 0.05}, kvstore=None)
    ss = gluon.Superstep(net, loss_fn, tr, k=4)
    xs = stack_batches([_batch(i)[0] for i in range(4)])
    ys = stack_batches([_batch(i)[1] for i in range(4)])
    import logging
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.fusedstep"):
        l = ss.step(xs, ys, 16)
    assert ss._plan is False
    assert l.shape == (4,)
    assert np.isfinite(np.asarray(l.data)).all()
    assert any("superstep" in r.message for r in caplog.records)
    # the fallback actually trained (4 single steps)
    assert tr._optimizer.num_update == 4


def test_superstep_disabled_flag_uses_single_steps():
    prev = fusedstep.set_enabled(False)
    try:
        net, tr = _build()
        ss = gluon.Superstep(net, loss_fn, tr, k=4)
        l = ss.step(stack_batches([_batch(i)[0] for i in range(4)]),
                    stack_batches([_batch(i)[1] for i in range(4)]), 16)
        assert l.shape == (4,)
        assert ss._plan is None  # never decided, flag short-circuits
    finally:
        fusedstep.set_enabled(prev)
    n1, _, _ = _run_single(4)
    for a, b in zip(_weights(n1), _weights(net)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_superstep_k_env_default():
    prev = fusedstep.set_superstep_k(6)
    try:
        net, tr = _build()
        ss = gluon.Superstep(net, loss_fn, tr)
        assert ss.k == 6
    finally:
        fusedstep.set_superstep_k(prev)


# ---------------------------------------------------------------------------
# staging ring + run() epoch driver
# ---------------------------------------------------------------------------

def test_superstep_ring_groups_and_tail():
    ring = SuperstepRing(((_batch(i)) for i in range(10)), 4,
                         device=mx.cpu())
    groups = list(ring)
    assert [n for _, n in groups] == [4, 4, 2]
    stacked, n = groups[0]
    assert n == 4 and stacked[0].shape == (4, 16, 8)
    tail, n = groups[2]
    assert isinstance(tail, list) and len(tail) == 2
    ring.close()


def test_superstep_ring_error_contract():
    def bad():
        yield _batch(0)
        yield _batch(1)
        yield _batch(2)
        raise RuntimeError("producer exploded")

    ring = SuperstepRing(bad(), 2, device=mx.cpu())
    _, n = next(ring)
    assert n == 2
    tail, n = next(ring)  # staged batch delivered before the error
    assert n == 1
    with pytest.raises(RuntimeError, match="producer exploded"):
        next(ring)
    ring.close()  # idempotent
    ring.close()


def test_superstep_ring_wraps_existing_prefetcher():
    pf = DevicePrefetcher((_batch(i) for i in range(6)), device=mx.cpu())
    ring = SuperstepRing(pf, 2)
    assert ring._pf is pf and ring._own is False
    _, n = next(iter(ring))
    assert n == 2
    ring.close()  # must NOT close a prefetcher it doesn't own
    x, _ = next(pf)  # still serving staged batches after ring.close()
    assert x.shape == (16, 8)
    pf.close()


def test_stack_batches_structure_and_mismatch():
    b0 = {"x": mx.nd.ones((2, 3)), "y": [mx.nd.zeros((2,)), 7]}
    b1 = {"x": mx.nd.ones((2, 3)), "y": [mx.nd.zeros((2,)), 7]}
    out = stack_batches([b0, b1])
    assert out["x"].shape == (2, 2, 3)
    assert out["y"][0].shape == (2, 2) and out["y"][1] == 7
    with pytest.raises(ValueError, match="shape/structure"):
        stack_batches([b0, {"x": mx.nd.ones((3, 3)),
                            "y": [mx.nd.zeros((2,)), 7]}])


def test_superstep_run_with_dataloader_list_batches():
    """run() over a real DataLoader: the default batchify yields LIST
    batches, whose stacked full groups must still route to the one-
    dispatch path (regression: a list-typed stacked group was once
    mistaken for a short tail)."""
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    rs = np.random.RandomState(7)
    ds = ArrayDataset(rs.randn(64, 8).astype(np.float32),
                      rs.randint(0, 3, (64,)).astype(np.float32))
    net, tr = _build()
    ss = gluon.Superstep(net, loss_fn, tr, k=2)
    losses = ss.run(DataLoader(ds, batch_size=16), 16, device=mx.cpu())
    assert len(losses) == 4
    assert np.isfinite(losses).all()
    assert isinstance(ss._plan, dict), ss._plan  # superstep path engaged
    assert tr._optimizer.num_update == 4


def test_superstep_run_with_mismatched_ring_k():
    """A caller-supplied ring whose k differs from the Superstep's:
    full groups of RING.k run stacked, and a short tail of exactly
    superstep-k batches must still single-step (regression: it was once
    mistaken for a stacked block, training with batch-1 as labels)."""
    net, tr = _build()
    ss = gluon.Superstep(net, loss_fn, tr, k=2)
    # 6 batches through a k=4 ring: one full group of 4, tail of 2 == ss.k
    ring = SuperstepRing((_batch(i) for i in range(6)), 4, device=mx.cpu())
    losses = ss.run(ring, 16)
    assert len(losses) == 6
    n1, _, ref = _run_single(6)
    np.testing.assert_allclose(losses, ref, rtol=1e-5, atol=1e-6)
    for a, b in zip(_weights(n1), _weights(net)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_superstep_ring_does_not_defer_keyboard_interrupt():
    """Ctrl-C must surface immediately, not after a tail group trains."""
    def src():
        yield _batch(0)
        raise KeyboardInterrupt

    ring = SuperstepRing(src(), 4, device=mx.cpu())
    with pytest.raises(KeyboardInterrupt):
        next(ring)
    ring.close()


def test_superstep_run_epoch_with_tail_parity():
    net, tr = _build()
    ss = gluon.Superstep(net, loss_fn, tr, k=4)
    losses = ss.run((_batch(i) for i in range(10)), 16, device=mx.cpu())
    assert len(losses) == 10
    n1, _, ref = _run_single(10)
    np.testing.assert_allclose(losses, ref, rtol=1e-5, atol=1e-6)
    for a, b in zip(_weights(n1), _weights(net)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# scan-compatible bucketed allreduce + SPMD superstep
# ---------------------------------------------------------------------------

def test_bucketed_psum_in_scan_parity():
    from mxnet_tpu.parallel.compat import get_shard_map
    from jax.sharding import PartitionSpec as P

    shard_map = get_shard_map()
    mesh = parallel.make_mesh({"dp": 8})
    rs = np.random.RandomState(0)
    grads = [jnp.asarray(rs.randn(*s).astype(dt)) for s, dt in
             [((33, 7), np.float32), ((5,), np.float32),
              ((4, 4), np.float16), ((129,), np.float32),
              ((2, 3, 5), np.float16)]]

    def inner(gs):
        def body(c, _):
            return c, parallel.bucketed_psum(gs, "dp", bucket_bytes=256)

        _, outs = jax.lax.scan(body, 0, jnp.arange(2))
        return [o[1] for o in outs]  # second scan iteration's results

    outs = shard_map(inner, mesh=mesh, in_specs=(P(),),
                     out_specs=P())(grads)
    for g, o in zip(grads, outs):
        np.testing.assert_allclose(np.asarray(o, np.float32),
                                   8 * np.asarray(g, np.float32),
                                   rtol=6e-3)


def test_bucketed_psum_single_tensor_and_split():
    """Odd sizes, one-tensor buckets, and the bucket-bytes split all
    reduce correctly (dtype-homogeneous buckets only)."""
    from mxnet_tpu.parallel.compat import get_shard_map
    from jax.sharding import PartitionSpec as P

    shard_map = get_shard_map()
    mesh = parallel.make_mesh({"dp": 8})
    grads = [jnp.ones((1000,), jnp.float32),  # 4000 B: splits at 1024
             jnp.ones((3,), jnp.float32),
             jnp.ones((7,), jnp.float16)]

    f = shard_map(lambda gs: parallel.bucketed_psum(gs, "dp",
                                                    bucket_bytes=1024),
                  mesh=mesh, in_specs=(P(),), out_specs=P())
    outs = f(grads)
    for g, o in zip(grads, outs):
        np.testing.assert_allclose(np.asarray(o, np.float32),
                                   8 * np.asarray(g, np.float32),
                                   rtol=1e-3)


def test_spmd_run_superstep_parity():
    def build():
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(3, in_units=16))
        net.initialize(init=mx.initializer.Xavier())
        return net

    mesh = parallel.make_mesh({"dp": 8})
    for use_mesh in (None, mesh):
        net1 = build()
        s1 = parallel.SPMDTrainStep(net1, loss_fn, "sgd",
                                    {"momentum": 0.9}, mesh=use_mesh)
        seq = [s1(*_batch(i), lr=0.1) for i in range(4)]
        net2 = build()
        s2 = parallel.SPMDTrainStep(net2, loss_fn, "sgd",
                                    {"momentum": 0.9}, mesh=use_mesh)
        xs = stack_batches([_batch(i)[0] for i in range(4)])
        ys = stack_batches([_batch(i)[1] for i in range(4)])
        losses = s2.run_superstep(xs, ys, lr=0.1)
        np.testing.assert_allclose(np.asarray(losses, np.float32), seq,
                                   rtol=1e-4, atol=1e-5)
        s1.sync_to_block()
        s2.sync_to_block()
        for a, b in zip(_weights(net1), _weights(net2)):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# in-scan device metrics (PR7): per-iteration loss/grad-norm/overflow
# series at K=8 with zero added dispatches — K-step capture no longer
# reduces metric cadence to K
# ---------------------------------------------------------------------------

def test_superstep_per_iteration_series_zero_added_dispatches():
    prev = obs.set_enabled(True)
    obs.reset()
    try:
        net, tr = _build("sgd")
        ss = gluon.Superstep(net, loss_fn, tr, k=8)
        xs = stack_batches([_batch(i)[0] for i in range(8)])
        ys = stack_batches([_batch(i)[1] for i in range(8)])
        ss.step(xs, ys, 16)  # warm: capture + compile
        assert isinstance(ss._plan, dict)
        before = obs.XLA_DISPATCH_TOTAL.total()
        ss.step(xs, ys, 16)
        # the K=8 superstep is STILL one dispatch — publishing the
        # per-iteration series stores the scan's stacked outputs whole
        # (lazy), never slicing or syncing on the hot path
        assert obs.XLA_DISPATCH_TOTAL.total() - before == 1
        series = obs.superstep_series()
        assert len(series["loss"]) == 8
        assert len(series["grad_norm"]) == 8
        assert len(series["overflow"]) == 8
        assert all(np.isfinite(series["loss"]))
        assert all(g > 0 for g in series["grad_norm"])
        assert series["overflow"] == [0.0] * 8
        # per-slot exposition for scrapers
        expo = obs.dump_prometheus()
        assert 'mxtpu_superstep_iter_loss{slot="7"}' in expo
        assert 'mxtpu_superstep_iter_grad_norm{slot="0"}' in expo
    finally:
        obs.set_enabled(prev)
        obs.reset()


def test_superstep_overflow_series_marks_poisoned_iteration():
    """fp16 in-scan AMP: the overflow series points at the exact
    iteration that skipped its update (slot 1 of 4), not just a per-K
    total."""
    amp.init("float16")
    prev = obs.set_enabled(True)
    obs.reset()
    try:
        net, tr = _build("sgd", amp_dtype="float16")
        ss = gluon.Superstep(net, loss_fn, tr, k=4)
        xs = stack_batches([_batch(i, dtype="float16",
                                   poison=(i == 1))[0] for i in range(4)])
        ys = stack_batches([_batch(i)[1] for i in range(4)])
        ss.step(xs, ys, 16)
        assert isinstance(ss._plan, dict)
        series = obs.superstep_series()
        assert series["overflow"] == [0.0, 1.0, 0.0, 0.0]
        assert tr._amp_loss_scaler.overflow_total == 1
    finally:
        amp.disable()
        obs.set_enabled(prev)
        obs.reset()
