"""Module API tests (reference model: test_module.py + train/test_mlp.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp_symbol(num_hidden=16, classes=3):
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, sym.var("fc1_weight"), sym.var("fc1_bias"),
                             num_hidden=num_hidden, name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, sym.var("fc2_weight"), sym.var("fc2_bias"),
                             num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(fc2, sym.var("softmax_label"), name="softmax")


def _toy_data(n=240, dim=10, classes=3, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype(np.float32)
    W = rng.randn(dim, classes).astype(np.float32)
    Y = (X @ W).argmax(1).astype(np.float32)
    return X, Y


# fit-loop mechanics stay tier-1 via forward_backward_update /
# predict / checkpoint; the convergence soak rides -m slow
@pytest.mark.slow
def test_module_fit_convergence():
    """End-to-end Module.fit (the reference's train/test_mlp.py pattern)."""
    X, Y = _toy_data()
    train_iter = mx.io.NDArrayIter(X, Y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.fit(train_iter, num_epoch=25,
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.initializer.Xavier())
    score = mod.score(mx.io.NDArrayIter(X, Y, batch_size=40), "acc")
    acc = dict(score)["accuracy"]
    assert acc > 0.9, f"Module.fit failed to converge: {acc}"


def test_module_forward_backward_update():
    X, Y = _toy_data(n=40)
    it = mx.io.NDArrayIter(X, Y, batch_size=20)
    mod = mx.mod.Module(_mlp_symbol())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))
    batch = next(iter(it))
    mod.forward(batch, is_train=True)
    out = mod.get_outputs()[0]
    assert out.shape == (20, 3)
    w_before = mod.get_params()[0]["fc1_weight"].asnumpy().copy()
    mod.backward()
    mod.update()
    w_after = mod.get_params()[0]["fc1_weight"].asnumpy()
    assert not np.allclose(w_before, w_after)


def test_module_predict():
    X, Y = _toy_data(n=60)
    it = mx.io.NDArrayIter(X, Y, batch_size=30)
    mod = mx.mod.Module(_mlp_symbol())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.init_params(initializer=mx.initializer.Xavier())
    preds = mod.predict(it)
    assert preds.shape == (60, 3)


def test_module_checkpoint(tmp_path):
    X, Y = _toy_data(n=40)
    it = mx.io.NDArrayIter(X, Y, batch_size=20)
    mod = mx.mod.Module(_mlp_symbol())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 3)
    symbol, arg_params, aux_params = mx.module.load_checkpoint(prefix, 3)
    assert "fc1_weight" in arg_params
    mod2 = mx.mod.Module(symbol)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.set_params(arg_params, aux_params)
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    assert_almost_equal(mod.get_outputs()[0],
                        mod2.get_outputs()[0].asnumpy())


def test_bucketing_module():
    def sym_gen(seq_len):
        data = sym.var("data")
        fc = sym.FullyConnected(data, sym.var("w"), sym.var("b"),
                                num_hidden=4)
        out = sym.SoftmaxOutput(fc, sym.var("softmax_label"))
        return out, ["data"], ["softmax_label"]

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    X = np.random.rand(16, 8).astype(np.float32)
    Y = np.zeros(16, np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer()
    batch = next(iter(it))
    batch.bucket_key = 8
    batch.provide_data = it.provide_data
    batch.provide_label = it.provide_label
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    assert mod.get_outputs()[0].shape == (8, 4)
