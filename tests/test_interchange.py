"""Interchange formats: NDARRAY_V2 binary .params + nnvm symbol JSON.

VERDICT r2 Missing #1: these are the declared compatibility boundary
(docs/design_decisions.md), so they must hold byte-for-byte. The fixtures
here are built BY HAND with raw struct packing / literal JSON against the
reference formats (src/ndarray/ndarray.cc NDArray::Save magic NDARRAY_V2;
nnvm SaveJSON schema), independent of the library's own writers.
"""

import json
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.symbol import symbol as sym_mod


# ---------------------------------------------------------------------------
# NDARRAY_V2 binary container
# ---------------------------------------------------------------------------


def _hand_build_params(path, arrays, names):
    """Reference-format writer, independent of serialization.py."""
    TYPE_FLAGS = {np.dtype(np.float32): 0, np.dtype(np.float64): 1,
                  np.dtype(np.int32): 4, np.dtype(np.uint8): 3,
                  np.dtype(np.int64): 6}
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", 0x112))       # kMXAPINDArrayListMagic
        f.write(struct.pack("<Q", 0))           # reserved
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            f.write(struct.pack("<I", 0xF993FAC9))      # NDARRAY_V2_MAGIC
            f.write(struct.pack("<i", 0))               # kDefaultStorage
            f.write(struct.pack("<I", a.ndim))
            f.write(struct.pack(f"<{a.ndim}I", *a.shape))
            f.write(struct.pack("<ii", 1, 0))           # Context cpu(0)
            f.write(struct.pack("<i", TYPE_FLAGS[a.dtype]))
            f.write(np.ascontiguousarray(a).tobytes())
        f.write(struct.pack("<Q", len(names)))
        for n in names:
            nb = n.encode()
            f.write(struct.pack("<Q", len(nb)) + nb)


def test_load_hand_built_ndarray_v2(tmp_path):
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = np.array([1, 2, 3], dtype=np.int32)
    path = str(tmp_path / "ref.params")
    _hand_build_params(path, [w, b], ["arg:weight", "arg:bias"])
    loaded = mx.nd.load(path)
    assert set(loaded) == {"arg:weight", "arg:bias"}
    np.testing.assert_array_equal(loaded["arg:weight"].asnumpy(), w)
    np.testing.assert_array_equal(loaded["arg:bias"].asnumpy(), b)
    assert loaded["arg:bias"].dtype == np.int32


def test_save_produces_reference_layout(tmp_path):
    """Parse our writer's output with an independent hand reader."""
    path = str(tmp_path / "ours.params")
    x = np.random.rand(2, 5).astype(np.float32)
    mx.nd.save(path, {"w": mx.nd.array(x)})
    with open(path, "rb") as f:
        magic, reserved = struct.unpack("<QQ", f.read(16))
        assert magic == 0x112 and reserved == 0
        (count,) = struct.unpack("<Q", f.read(8))
        assert count == 1
        (blob_magic,) = struct.unpack("<I", f.read(4))
        assert blob_magic == 0xF993FAC9
        (stype,) = struct.unpack("<i", f.read(4))
        assert stype == 0
        (ndim,) = struct.unpack("<I", f.read(4))
        shape = struct.unpack(f"<{ndim}I", f.read(4 * ndim))
        assert shape == (2, 5)
        f.read(8)  # context
        (flag,) = struct.unpack("<i", f.read(4))
        assert flag == 0  # float32
        data = np.frombuffer(f.read(4 * 10), np.float32).reshape(2, 5)
        np.testing.assert_array_equal(data, x)
        (ncount,) = struct.unpack("<Q", f.read(8))
        assert ncount == 1
        (ln,) = struct.unpack("<Q", f.read(8))
        assert f.read(ln).decode() == "w"


def test_roundtrip_list_and_dtypes(tmp_path):
    path = str(tmp_path / "list.params")
    arrs = [mx.nd.array(np.random.rand(3, 3).astype(np.float32)),
            mx.nd.array(np.arange(4).astype(np.int64)),
            mx.nd.array(np.random.rand(2, 2).astype(np.float32))
            .astype("bfloat16")]
    mx.nd.save(path, arrs)
    back = mx.nd.load(path)
    assert isinstance(back, list) and len(back) == 3
    np.testing.assert_allclose(back[0].asnumpy(), arrs[0].asnumpy())
    np.testing.assert_array_equal(back[1].asnumpy(), arrs[1].asnumpy())
    assert str(back[2].dtype) in ("bfloat16",)
    np.testing.assert_allclose(np.asarray(back[2].asnumpy(), np.float32),
                               np.asarray(arrs[2].asnumpy(), np.float32))


def test_legacy_npz_still_loads(tmp_path):
    path = str(tmp_path / "legacy.params")
    x = np.random.rand(4).astype(np.float32)
    with open(path, "wb") as f:
        np.savez(f, **{"k": x})
    loaded = mx.nd.load(path)
    np.testing.assert_array_equal(loaded["k"].asnumpy(), x)


def test_unsupported_dtype_falls_back_to_npz(tmp_path):
    """bool masks have no NDARRAY_V2 type flag -> npz fallback, no
    truncated binary left behind."""
    path = str(tmp_path / "mask.params")
    data = {"mask": mx.nd.array(np.zeros((2, 2), np.float32)).astype("bool")}
    assert data["mask"].dtype == np.bool_
    mx.nd.save(path, data)
    from mxnet_tpu.ndarray import serialization

    assert serialization.sniff_format(path) == "npz"
    back = mx.nd.load(path)
    assert back["mask"].dtype == np.bool_
    assert not back["mask"].asnumpy().any()


def test_var_dtype_emitted_as_flag():
    """Reference loaders int()-parse __dtype__; we must write '0' not
    'float32'."""
    v = sym_mod.var("data", shape=(2, 3), dtype="float32")
    blob = json.loads(v.tojson())
    (node,) = [n for n in blob["nodes"] if n["name"] == "data"]
    assert node["attrs"]["__dtype__"] == "0"
    # and it round-trips back to a name through our loader
    v2 = sym_mod.load_json(v.tojson())
    assert v2._attrs.get("__dtype__") == "float32"


def test_bad_magic_raises(tmp_path):
    path = str(tmp_path / "junk.params")
    with open(path, "wb") as f:
        f.write(b"\x01\x23\x45\x67\x89\xab\xcd\xef" * 4)
    with pytest.raises(Exception):
        mx.nd.load(path)


# ---------------------------------------------------------------------------
# nnvm symbol JSON
# ---------------------------------------------------------------------------


_HAND_JSON = {
    # MXNet-style: every attr value a STRING; arg_nodes; node_row_ptr
    "nodes": [
        {"op": "null", "name": "data", "inputs": []},
        {"op": "null", "name": "fc1_weight", "inputs": []},
        {"op": "null", "name": "fc1_bias", "inputs": []},
        {"op": "FullyConnected", "name": "fc1",
         "attrs": {"num_hidden": "8", "flatten": "True"},
         "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
        {"op": "Activation", "name": "relu1",
         "attrs": {"act_type": "relu"}, "inputs": [[3, 0, 0]]},
        {"op": "null", "name": "fc2_weight", "inputs": []},
        {"op": "null", "name": "fc2_bias", "inputs": []},
        {"op": "FullyConnected", "name": "fc2",
         "attrs": {"num_hidden": "3", "flatten": "True"},
         "inputs": [[4, 0, 0], [5, 0, 0], [6, 0, 0]]},
    ],
    "arg_nodes": [0, 1, 2, 5, 6],
    "node_row_ptr": [0, 1, 2, 3, 4, 5, 6, 7, 8],
    "heads": [[7, 0, 0]],
    "attrs": {"mxnet_version": ["int", 10700]},
}


def _mlp_params(rng):
    return {
        "fc1_weight": rng.randn(8, 5).astype(np.float32),
        "fc1_bias": rng.randn(8).astype(np.float32),
        "fc2_weight": rng.randn(3, 8).astype(np.float32),
        "fc2_bias": rng.randn(3).astype(np.float32),
    }


def _mlp_numpy(params, x):
    h = np.maximum(x @ params["fc1_weight"].T + params["fc1_bias"], 0)
    return h @ params["fc2_weight"].T + params["fc2_bias"]


def test_load_hand_built_nnvm_json():
    sym = sym_mod.load_json(json.dumps(_HAND_JSON))
    assert set(sym.list_arguments()) == {"data", "fc1_weight", "fc1_bias",
                                         "fc2_weight", "fc2_bias"}
    rng = np.random.RandomState(0)
    params = _mlp_params(rng)
    x = rng.randn(4, 5).astype(np.float32)
    from mxnet_tpu.symbol.executor import eval_symbol

    args = {k: mx.nd.array(v) for k, v in params.items()}
    args["data"] = mx.nd.array(x)
    (out,) = eval_symbol(sym, args)
    np.testing.assert_allclose(out.asnumpy(), _mlp_numpy(params, x),
                               rtol=1e-5, atol=1e-5)


def test_tojson_emits_nnvm_schema():
    sym = sym_mod.load_json(json.dumps(_HAND_JSON))
    blob = json.loads(sym.tojson())
    assert set(blob) >= {"nodes", "arg_nodes", "node_row_ptr", "heads"}
    assert blob["arg_nodes"] == [i for i, n in enumerate(blob["nodes"])
                                 if n["op"] == "null"]
    assert blob["node_row_ptr"][0] == 0
    assert len(blob["node_row_ptr"]) == len(blob["nodes"]) + 1
    fc = next(n for n in blob["nodes"] if n["name"] == "fc1")
    assert fc["attrs"]["num_hidden"] == "8"      # stringified, MXNet-style
    assert fc["attrs"]["flatten"] == "True"


def test_json_roundtrip_forward_equal():
    sym = sym_mod.load_json(json.dumps(_HAND_JSON))
    sym2 = sym_mod.load_json(sym.tojson())
    rng = np.random.RandomState(1)
    params = _mlp_params(rng)
    x = rng.randn(2, 5).astype(np.float32)
    from mxnet_tpu.symbol.executor import eval_symbol

    args = {k: mx.nd.array(v) for k, v in params.items()}
    args["data"] = mx.nd.array(x)
    (o1,) = eval_symbol(sym, args)
    (o2,) = eval_symbol(sym2, args)
    np.testing.assert_allclose(o1.asnumpy(), o2.asnumpy(), rtol=1e-6)


def test_pre16_attr_key_variant():
    """Old reference files use "attr" (or "param") instead of "attrs"."""
    blob = json.loads(json.dumps(_HAND_JSON))
    for n in blob["nodes"]:
        if "attrs" in n:
            n["attr"] = n.pop("attrs")
    sym = sym_mod.load_json(json.dumps(blob))
    assert "fc2_weight" in sym.list_arguments()


def test_variable_dtype_flag_parsed():
    blob = json.loads(json.dumps(_HAND_JSON))
    blob["nodes"][0]["attrs"] = {"__shape__": "(4, 5)", "__dtype__": "0"}
    sym = sym_mod.load_json(json.dumps(blob))
    shapes, _, _ = sym.infer_shape()
    assert shapes is not None


# ---------------------------------------------------------------------------
# end-to-end: export -> hand-check -> SymbolBlock.imports
# ---------------------------------------------------------------------------


def test_export_imports_with_binary_params(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.rand(2, 5).astype(np.float32))
    want = net(x).asnumpy()
    path = str(tmp_path / "model")
    net.export(path)
    # the exported params file must be the reference binary container
    from mxnet_tpu.ndarray import serialization

    assert serialization.sniff_format(f"{path}-0000.params") == "ndarray_v2"
    blob = json.loads(open(f"{path}-symbol.json").read())
    assert "arg_nodes" in blob and "node_row_ptr" in blob
    net2 = gluon.SymbolBlock.imports(f"{path}-symbol.json", ["data"],
                                     f"{path}-0000.params")
    got = net2(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_reference_scalar_op_json_imports():
    """A reference-exported graph containing _mul_scalar/_plus_scalar
    nodes (the names MXNet's Python operator lowering emits) loads and
    evaluates (round-4 scalar-family registration)."""
    import json

    g = {
        "nodes": [
            {"op": "null", "name": "a", "inputs": []},
            {"op": "_mul_scalar", "name": "mul0",
             "attrs": {"scalar": "3.0"}, "inputs": [[0, 0, 0]]},
            {"op": "_plus_scalar", "name": "plus0",
             "attrs": {"scalar": "1.5"}, "inputs": [[1, 0, 0]]},
            {"op": "Activation", "name": "relu0",
             "attrs": {"act_type": "relu"}, "inputs": [[2, 0, 0]]},
        ],
        "arg_nodes": [0],
        "node_row_ptr": [0, 1, 2, 3, 4],
        "heads": [[3, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10700]},
    }
    sym = mx.sym.load_json(json.dumps(g))
    ex = sym.simple_bind(a=(2, 3))
    x = np.array([[-1.0, 0.5, 2.0], [0.1, -0.2, 0.3]], np.float32)
    out = ex.forward(a=mx.nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(out, np.maximum(x * 3 + 1.5, 0), rtol=1e-6)
