"""Optimizer tests (reference model: test_optimizer.py update-rule checks)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.optimizer import (
    SGD, NAG, Adam, AdamW, AdaGrad, AdaDelta, RMSProp, Ftrl, FTML, LAMB,
    LARS, Signum, DCASGD, create, get_updater,
)
from mxnet_tpu.test_utils import assert_almost_equal


def _run_steps(opt, steps=3, shape=(4, 3), seed=0):
    rng = np.random.RandomState(seed)
    w = mx.nd.array(rng.randn(*shape).astype(np.float32))
    state = opt.create_state_multi_precision(0, w)
    ws = [w.asnumpy().copy()]
    for _ in range(steps):
        g = mx.nd.array(rng.randn(*shape).astype(np.float32))
        opt.update_multi_precision(0, w, g, state)
        ws.append(w.asnumpy().copy())
    return ws


def test_sgd_momentum_formula():
    opt = SGD(learning_rate=0.1, momentum=0.9, wd=0.0, rescale_grad=1.0)
    w = mx.nd.array([1.0])
    state = opt.create_state(0, w)
    g = mx.nd.array([0.5])
    opt.update(0, w, g, state)
    # mom = -0.1*0.5 = -0.05; w = 0.95
    assert_almost_equal(w, np.array([0.95], np.float32))
    opt.update(0, w, g, state)
    # mom = 0.9*-0.05 - 0.05 = -0.095; w = 0.855
    assert_almost_equal(w, np.array([0.855], np.float32))


def test_sgd_wd():
    opt = SGD(learning_rate=0.1, wd=0.1, rescale_grad=1.0)
    w = mx.nd.array([1.0])
    opt.update(0, w, mx.nd.array([0.0]), None)
    assert_almost_equal(w, np.array([0.99], np.float32))  # 1 - 0.1*0.1*1


def test_adam_first_step():
    opt = Adam(learning_rate=0.001, rescale_grad=1.0)
    w = mx.nd.array([1.0])
    state = opt.create_state(0, w)
    opt.update(0, w, mx.nd.array([1.0]), state)
    # first adam step moves by ~lr regardless of grad magnitude
    assert abs(float(w.asscalar()) - (1.0 - 0.001)) < 1e-5


def test_all_optimizers_decrease_quadratic():
    for cls, kwargs in [
        (SGD, {"learning_rate": 0.1}),
        (SGD, {"learning_rate": 0.1, "momentum": 0.9}),
        (NAG, {"learning_rate": 0.1, "momentum": 0.9}),
        (Adam, {"learning_rate": 0.1}),
        (AdamW, {"learning_rate": 0.1, "wd": 0.01}),
        (AdaGrad, {"learning_rate": 0.5}),
        (AdaDelta, {}),
        (RMSProp, {"learning_rate": 0.05}),
        (RMSProp, {"learning_rate": 0.05, "centered": True}),
        (Ftrl, {"learning_rate": 0.5}),
        (FTML, {"learning_rate": 0.1}),
        (LAMB, {"learning_rate": 0.05}),
        (LARS, {"learning_rate": 0.5}),
        (Signum, {"learning_rate": 0.01}),
        (DCASGD, {"learning_rate": 0.1}),
    ]:
        opt = cls(rescale_grad=1.0, **kwargs)
        w = mx.nd.array([3.0])
        state = opt.create_state_multi_precision(0, w)
        # minimize f(w) = w^2 / 2; grad = w — every rule must descend
        # (fixed-step rules like Signum/LARS descend slowly by design)
        for _ in range(50):
            g = mx.nd.array([float(w.asscalar())])
            opt.update_multi_precision(0, w, g, state)
        final = abs(float(w.asscalar()))
        assert final < 2.95, f"{cls.__name__} did not descend: {final}"


def test_multi_precision_fp16():
    opt = SGD(learning_rate=0.1, momentum=0.9, multi_precision=True,
              rescale_grad=1.0)
    w = mx.nd.array([1.0]).astype("float16")
    state = opt.create_state_multi_precision(0, w)
    master, _ = state
    assert master.dtype == np.float32
    opt.update_multi_precision(0, w, mx.nd.array([0.5]).astype("float16"),
                               state)
    assert w.dtype == np.float16
    assert abs(float(w.asscalar()) - 0.95) < 1e-3


def test_clip_gradient():
    opt = SGD(learning_rate=1.0, clip_gradient=0.1, rescale_grad=1.0)
    w = mx.nd.array([0.0])
    opt.update(0, w, mx.nd.array([100.0]), None)
    assert_almost_equal(w, np.array([-0.1], np.float32))


def test_lr_scheduler_in_optimizer():
    sched = mx.lr_scheduler.MultiFactorScheduler(step=[2, 4], factor=0.1)
    opt = SGD(learning_rate=1.0, lr_scheduler=sched, rescale_grad=1.0)
    w = mx.nd.array([0.0])
    for i in range(6):
        opt.update(0, w, mx.nd.array([0.0]), None)
    assert opt.learning_rate < 1.0


def test_create_registry():
    assert isinstance(create("sgd"), SGD)
    assert isinstance(create("adam", learning_rate=0.1), Adam)
    with pytest.raises(mx.MXNetError):
        create("definitely_not_an_optimizer")


def test_updater():
    upd = get_updater(SGD(learning_rate=0.1, rescale_grad=1.0))
    w = mx.nd.array([1.0])
    upd(0, mx.nd.array([1.0]), w)
    assert_almost_equal(w, np.array([0.9], np.float32))


def test_lr_wd_mult():
    opt = SGD(learning_rate=1.0, rescale_grad=1.0)
    opt.set_lr_mult({0: 0.1})
    assert opt._get_lr(0) == pytest.approx(0.1)
    assert opt._get_lr(1) == pytest.approx(1.0)


def test_group_adagrad_row_wise_history():
    """GroupAdaGrad (reference: contrib GroupAdaGrad over
    _contrib_group_adagrad_update): ONE accumulator per row, so every
    element of a row shares its effective lr."""
    from mxnet_tpu import optimizer as opt

    o = opt.create("groupadagrad", learning_rate=0.1)
    with pytest.raises(mx.base.MXNetError):
        bad = opt.create("groupadagrad", learning_rate=0.1, wd=1e-4)
        bad.update(9, mx.nd.ones((2, 2)), mx.nd.ones((2, 2)),
                   bad.create_state(9, mx.nd.ones((2, 2))))
    w = mx.nd.ones((3, 4))
    g = mx.nd.array(np.array([[1, 1, 1, 1],
                              [2, 2, 2, 2],
                              [0, 0, 0, 0]], np.float32))
    state = o.create_state(0, w)
    assert state.shape == (3,)
    o.update(0, w, g, state)
    wn = w.asnumpy()
    # within a row, updates are identical; zero-grad row unchanged
    for r in range(3):
        assert np.allclose(wn[r], wn[r][0])
    assert np.allclose(wn[2], 1.0)
    assert wn[0][0] != wn[1][0]


def test_lbsgd_warmup_and_trust_ratio():
    from mxnet_tpu import optimizer as opt

    o = opt.create("lbsgd", learning_rate=1.0, momentum=0.0,
                   warmup_strategy="linear", warmup_epochs=1,
                   updates_per_epoch=10)
    w = mx.nd.ones((4,))
    g = mx.nd.full((4,), 0.5)
    w0 = w.asnumpy().copy()
    o.update(0, w, g, o.create_state(0, w))
    d1 = np.abs(w.asnumpy() - w0).max()
    # early-warmup step is scaled down hard
    assert 0 < d1 < 0.5
    # batch_scale ramps the post-warmup lr multiplier
    ob = opt.create("lbsgd", learning_rate=0.01, warmup_epochs=0,
                    batch_scale=8)
    wb = mx.nd.ones((4,))
    ob.update(2, wb, mx.nd.full((4,), 0.5), ob.create_state(2, wb))
    small = opt.create("lbsgd", learning_rate=0.01, warmup_epochs=0,
                       batch_scale=1)
    ws = mx.nd.ones((4,))
    small.update(3, ws, mx.nd.full((4,), 0.5), small.create_state(3, ws))
    assert np.abs(wb.asnumpy() - 1).max() > np.abs(ws.asnumpy() - 1).max()
    # fp16 weights keep their dtype through the update
    wh = mx.nd.ones((4,)).astype("float16")
    o.update(4, wh, mx.nd.full((4,), 0.5).astype("float16"),
             o.create_state(4, wh))
    assert wh.dtype == np.float16
    # trust ratio caps at 2: with tiny grads the step never explodes
    w2 = mx.nd.ones((4,))
    o2 = opt.create("lbsgd", learning_rate=1.0, warmup_epochs=0)
    o2.update(1, w2, mx.nd.full((4,), 1e-8), o2.create_state(1, w2))
    assert np.abs(w2.asnumpy() - 1.0).max() < 1.0


def test_new_optimizers_converge():
    from mxnet_tpu import autograd, gluon

    for name in ("groupadagrad", "lbsgd"):
        net = gluon.nn.Dense(4, in_units=6)
        net.initialize()
        kwargs = {"learning_rate": 0.1}
        if name == "lbsgd":
            kwargs["momentum"] = 0.9
        tr = gluon.Trainer(net.collect_params(), name, kwargs)
        loss_fn = gluon.loss.L2Loss()
        x = mx.nd.random.uniform(shape=(8, 6))
        y = mx.nd.ones((8, 4))
        losses = []
        for _ in range(15):
            with autograd.record():
                loss = loss_fn(net(x), y).mean()
            loss.backward()
            tr.step(1)
            losses.append(float(loss.asnumpy()))
        assert losses[-1] < losses[0], (name, losses)
