"""Optimizer tests (reference model: test_optimizer.py update-rule checks)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.optimizer import (
    SGD, NAG, Adam, AdamW, AdaGrad, AdaDelta, RMSProp, Ftrl, FTML, LAMB,
    LARS, Signum, DCASGD, create, get_updater,
)
from mxnet_tpu.test_utils import assert_almost_equal


def _run_steps(opt, steps=3, shape=(4, 3), seed=0):
    rng = np.random.RandomState(seed)
    w = mx.nd.array(rng.randn(*shape).astype(np.float32))
    state = opt.create_state_multi_precision(0, w)
    ws = [w.asnumpy().copy()]
    for _ in range(steps):
        g = mx.nd.array(rng.randn(*shape).astype(np.float32))
        opt.update_multi_precision(0, w, g, state)
        ws.append(w.asnumpy().copy())
    return ws


def test_sgd_momentum_formula():
    opt = SGD(learning_rate=0.1, momentum=0.9, wd=0.0, rescale_grad=1.0)
    w = mx.nd.array([1.0])
    state = opt.create_state(0, w)
    g = mx.nd.array([0.5])
    opt.update(0, w, g, state)
    # mom = -0.1*0.5 = -0.05; w = 0.95
    assert_almost_equal(w, np.array([0.95], np.float32))
    opt.update(0, w, g, state)
    # mom = 0.9*-0.05 - 0.05 = -0.095; w = 0.855
    assert_almost_equal(w, np.array([0.855], np.float32))


def test_sgd_wd():
    opt = SGD(learning_rate=0.1, wd=0.1, rescale_grad=1.0)
    w = mx.nd.array([1.0])
    opt.update(0, w, mx.nd.array([0.0]), None)
    assert_almost_equal(w, np.array([0.99], np.float32))  # 1 - 0.1*0.1*1


def test_adam_first_step():
    opt = Adam(learning_rate=0.001, rescale_grad=1.0)
    w = mx.nd.array([1.0])
    state = opt.create_state(0, w)
    opt.update(0, w, mx.nd.array([1.0]), state)
    # first adam step moves by ~lr regardless of grad magnitude
    assert abs(float(w.asscalar()) - (1.0 - 0.001)) < 1e-5


def test_all_optimizers_decrease_quadratic():
    for cls, kwargs in [
        (SGD, {"learning_rate": 0.1}),
        (SGD, {"learning_rate": 0.1, "momentum": 0.9}),
        (NAG, {"learning_rate": 0.1, "momentum": 0.9}),
        (Adam, {"learning_rate": 0.1}),
        (AdamW, {"learning_rate": 0.1, "wd": 0.01}),
        (AdaGrad, {"learning_rate": 0.5}),
        (AdaDelta, {}),
        (RMSProp, {"learning_rate": 0.05}),
        (RMSProp, {"learning_rate": 0.05, "centered": True}),
        (Ftrl, {"learning_rate": 0.5}),
        (FTML, {"learning_rate": 0.1}),
        (LAMB, {"learning_rate": 0.05}),
        (LARS, {"learning_rate": 0.5}),
        (Signum, {"learning_rate": 0.01}),
        (DCASGD, {"learning_rate": 0.1}),
    ]:
        opt = cls(rescale_grad=1.0, **kwargs)
        w = mx.nd.array([3.0])
        state = opt.create_state_multi_precision(0, w)
        # minimize f(w) = w^2 / 2; grad = w — every rule must descend
        # (fixed-step rules like Signum/LARS descend slowly by design)
        for _ in range(50):
            g = mx.nd.array([float(w.asscalar())])
            opt.update_multi_precision(0, w, g, state)
        final = abs(float(w.asscalar()))
        assert final < 2.95, f"{cls.__name__} did not descend: {final}"


def test_multi_precision_fp16():
    opt = SGD(learning_rate=0.1, momentum=0.9, multi_precision=True,
              rescale_grad=1.0)
    w = mx.nd.array([1.0]).astype("float16")
    state = opt.create_state_multi_precision(0, w)
    master, _ = state
    assert master.dtype == np.float32
    opt.update_multi_precision(0, w, mx.nd.array([0.5]).astype("float16"),
                               state)
    assert w.dtype == np.float16
    assert abs(float(w.asscalar()) - 0.95) < 1e-3


def test_clip_gradient():
    opt = SGD(learning_rate=1.0, clip_gradient=0.1, rescale_grad=1.0)
    w = mx.nd.array([0.0])
    opt.update(0, w, mx.nd.array([100.0]), None)
    assert_almost_equal(w, np.array([-0.1], np.float32))


def test_lr_scheduler_in_optimizer():
    sched = mx.lr_scheduler.MultiFactorScheduler(step=[2, 4], factor=0.1)
    opt = SGD(learning_rate=1.0, lr_scheduler=sched, rescale_grad=1.0)
    w = mx.nd.array([0.0])
    for i in range(6):
        opt.update(0, w, mx.nd.array([0.0]), None)
    assert opt.learning_rate < 1.0


def test_create_registry():
    assert isinstance(create("sgd"), SGD)
    assert isinstance(create("adam", learning_rate=0.1), Adam)
    with pytest.raises(mx.MXNetError):
        create("definitely_not_an_optimizer")


def test_updater():
    upd = get_updater(SGD(learning_rate=0.1, rescale_grad=1.0))
    w = mx.nd.array([1.0])
    upd(0, mx.nd.array([1.0]), w)
    assert_almost_equal(w, np.array([0.9], np.float32))


def test_lr_wd_mult():
    opt = SGD(learning_rate=1.0, rescale_grad=1.0)
    opt.set_lr_mult({0: 0.1})
    assert opt._get_lr(0) == pytest.approx(0.1)
    assert opt._get_lr(1) == pytest.approx(1.0)
