"""Round-3 op breadth: optimizer update ops, sample_*/random_pdf_*,
modulated deformable conv, misc indexing ops, sparse FComputeEx twins.

Each op checks numeric semantics against an independent NumPy
formulation (reference: the formulas in optimizer_op-inl.h / sample_op.cc
/ pdf_op.cc), not just shapes.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import op as ndop


def _rand(*s):
    return np.random.RandomState(sum(s) + 7).randn(*s).astype(np.float32)


# ---------------------------------------------------------------------------
# optimizer update ops
# ---------------------------------------------------------------------------


def test_sgd_and_mom_update():
    w, g, m = _rand(4, 3), _rand(4, 3) * 0.1, np.zeros((4, 3), np.float32)
    out = ndop.sgd_update(mx.nd.array(w), mx.nd.array(g), 0.1, wd=0.01)
    want = w - 0.1 * (g + 0.01 * w)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)

    w2, m2 = ndop.sgd_mom_update(mx.nd.array(w), mx.nd.array(g),
                                 mx.nd.array(m), 0.1, momentum=0.9, wd=0.01)
    mom = 0.9 * m - 0.1 * (g + 0.01 * w)
    np.testing.assert_allclose(w2.asnumpy(), w + mom, rtol=1e-5)
    np.testing.assert_allclose(m2.asnumpy(), mom, rtol=1e-5)


def test_clip_gradient_applies():
    w, g = np.zeros((3,), np.float32), np.array([10., -10., 0.1], np.float32)
    out = ndop.sgd_update(mx.nd.array(w), mx.nd.array(g), 1.0,
                          clip_gradient=1.0)
    np.testing.assert_allclose(out.asnumpy(), [-1.0, 1.0, -0.1], rtol=1e-6)


def test_adam_update():
    w, g = _rand(5), _rand(5) * 0.1
    m, v = np.zeros(5, np.float32), np.zeros(5, np.float32)
    w2, m2, v2 = ndop.adam_update(mx.nd.array(w), mx.nd.array(g),
                                  mx.nd.array(m), mx.nd.array(v), 0.01,
                                  beta1=0.9, beta2=0.999, epsilon=1e-8)
    me = 0.1 * g
    ve = 0.001 * g * g
    np.testing.assert_allclose(m2.asnumpy(), me, rtol=1e-5)
    np.testing.assert_allclose(v2.asnumpy(), ve, rtol=1e-4)
    np.testing.assert_allclose(w2.asnumpy(),
                               w - 0.01 * me / (np.sqrt(ve) + 1e-8),
                               rtol=1e-4)


def test_rmsprop_adagrad_adadelta_ftrl():
    w, g = _rand(6), _rand(6) * 0.2
    n = np.abs(_rand(6))
    w2, n2 = ndop.rmsprop_update(mx.nd.array(w), mx.nd.array(g),
                                 mx.nd.array(n), 0.01, gamma1=0.9)
    ne = 0.1 * g * g + 0.9 * n
    np.testing.assert_allclose(n2.asnumpy(), ne, rtol=1e-5)
    np.testing.assert_allclose(w2.asnumpy(),
                               w - 0.01 * g / np.sqrt(ne + 1e-8), rtol=1e-4)

    h = np.abs(_rand(6))
    w2, h2 = ndop.adagrad_update(mx.nd.array(w), mx.nd.array(g),
                                 mx.nd.array(h), 0.01, epsilon=1e-7)
    he = h + g * g
    np.testing.assert_allclose(h2.asnumpy(), he, rtol=1e-5)
    np.testing.assert_allclose(
        w2.asnumpy(), w - 0.01 * (g / np.sqrt(he + 1e-7)), rtol=1e-4)

    ag, ad = np.abs(_rand(6)), np.abs(_rand(6))
    w2, ag2, ad2 = ndop.adadelta_update(mx.nd.array(w), mx.nd.array(g),
                                        mx.nd.array(ag), mx.nd.array(ad),
                                        rho=0.9, epsilon=1e-5)
    age = 0.9 * ag + 0.1 * g * g
    delta = np.sqrt(ad + 1e-5) / np.sqrt(age + 1e-5) * g
    np.testing.assert_allclose(w2.asnumpy(), w - delta, rtol=1e-4)
    np.testing.assert_allclose(ad2.asnumpy(),
                               0.9 * ad + 0.1 * delta * delta, rtol=1e-4)

    z, nn = _rand(6), np.abs(_rand(6))
    w2, z2, n2 = ndop.ftrl_update(mx.nd.array(w), mx.nd.array(g),
                                  mx.nd.array(z), mx.nd.array(nn), 0.1,
                                  lamda1=0.01, beta=1.0)
    n_new = nn + g * g
    sigma = (np.sqrt(n_new) - np.sqrt(nn)) / 0.1
    z_new = z + g - sigma * w
    want = np.where(np.abs(z_new) <= 0.01, 0.0,
                    -(z_new - np.sign(z_new) * 0.01)
                    / ((1.0 + np.sqrt(n_new)) / 0.1))
    np.testing.assert_allclose(w2.asnumpy(), want, rtol=1e-4, atol=1e-6)


def test_sign_family_and_nag():
    w, g, m = _rand(4), _rand(4), _rand(4)
    out = ndop.signsgd_update(mx.nd.array(w), mx.nd.array(g), 0.1, wd=0.01)
    np.testing.assert_allclose(out.asnumpy(),
                               (1 - 0.1 * 0.01) * w - 0.1 * np.sign(g),
                               rtol=1e-5)
    w2, m2 = ndop.signum_update(mx.nd.array(w), mx.nd.array(g),
                                mx.nd.array(m), 0.1, momentum=0.9)
    me = 0.9 * m - 0.1 * g
    np.testing.assert_allclose(m2.asnumpy(), me, rtol=1e-5)
    np.testing.assert_allclose(w2.asnumpy(), w + 0.1 * np.sign(me), rtol=1e-5)

    w2, m2 = ndop.nag_mom_update(mx.nd.array(w), mx.nd.array(g),
                                 mx.nd.array(m), 0.1, momentum=0.9, wd=0.0)
    me = 0.9 * m + g
    np.testing.assert_allclose(w2.asnumpy(), w - 0.1 * (g + 0.9 * me),
                               rtol=1e-5)


def test_mp_sgd_keeps_fp32_master():
    w32 = _rand(4)
    w16 = w32.astype(np.float16)
    g16 = (_rand(4) * 0.1).astype(np.float16)
    w2, w32n = ndop.mp_sgd_update(mx.nd.array(w16, dtype="float16"),
                                  mx.nd.array(g16, dtype="float16"),
                                  mx.nd.array(w32), 0.1, wd=0.0)
    assert w2.dtype == np.float16
    assert w32n.dtype == np.float32
    np.testing.assert_allclose(w32n.asnumpy(),
                               w32 - 0.1 * g16.astype(np.float32), rtol=1e-3)


def test_lamb_phases():
    w, g = _rand(5), _rand(5) * 0.1
    m, v = np.zeros(5, np.float32), np.zeros(5, np.float32)
    d, m2, v2 = ndop.lamb_update_phase1(mx.nd.array(w), mx.nd.array(g),
                                        mx.nd.array(m), mx.nd.array(v),
                                        beta1=0.9, beta2=0.999, t=1, wd=0.01)
    mh = (0.1 * g) / (1 - 0.9)
    vh = (0.001 * g * g) / (1 - 0.999)
    np.testing.assert_allclose(
        d.asnumpy(), mh / (np.sqrt(vh) + 1e-6) + 0.01 * w, rtol=1e-3)
    r1 = np.linalg.norm(w).astype(np.float32)
    r2 = np.linalg.norm(d.asnumpy()).astype(np.float32)
    w2 = ndop.lamb_update_phase2(mx.nd.array(w), d, mx.nd.array(r1),
                                 mx.nd.array(r2), 0.01)
    np.testing.assert_allclose(w2.asnumpy(),
                               w - 0.01 * (r1 / r2) * d.asnumpy(), rtol=1e-4)


def test_multi_tensor_family():
    ws = [_rand(3), _rand(2, 2)]
    gs = [_rand(3) * 0.1, _rand(2, 2) * 0.1]
    arrays = [mx.nd.array(a) for pair in zip(ws, gs) for a in pair]
    outs = ndop.multi_sgd_update(*arrays, lrs=(0.1, 0.2), wds=(0.0, 0.0),
                                 num_weights=2)
    np.testing.assert_allclose(outs[0].asnumpy(), ws[0] - 0.1 * gs[0],
                               rtol=1e-5)
    np.testing.assert_allclose(outs[1].asnumpy(), ws[1] - 0.2 * gs[1],
                               rtol=1e-5)

    sq = ndop.multi_sum_sq(mx.nd.array(ws[0]), mx.nd.array(ws[1]),
                           num_arrays=2)
    np.testing.assert_allclose(sq.asnumpy(),
                               [np.sum(ws[0] ** 2), np.sum(ws[1] ** 2)],
                               rtol=1e-5)

    lrs = np.array([0.1, 0.1], np.float32)
    wsq = sq.asnumpy()
    gsq = np.array([np.sum(gs[0] ** 2), np.sum(gs[1] ** 2)], np.float32)
    wds = np.array([0.0, 0.0], np.float32)
    new_lrs = ndop.multi_lars(mx.nd.array(lrs), sq,
                              mx.nd.array(gsq), mx.nd.array(wds), eta=0.01)
    want = lrs * 0.01 * np.sqrt(wsq) / (np.sqrt(gsq) + 1e-8)
    np.testing.assert_allclose(new_lrs.asnumpy(), want, rtol=1e-4)

    # preloaded variant: lrs/wds as trailing arrays
    outs = ndop.preloaded_multi_sgd_update(
        *arrays, mx.nd.array(lrs), mx.nd.array(wds), num_weights=2)
    np.testing.assert_allclose(outs[0].asnumpy(), ws[0] - 0.1 * gs[0],
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# sample_* / random_pdf_*
# ---------------------------------------------------------------------------


def test_sample_ops_shapes_and_moments():
    mx.random.seed(7)
    low = mx.nd.array(np.array([0.0, 10.0], np.float32))
    high = mx.nd.array(np.array([1.0, 20.0], np.float32))
    s = ndop.sample_uniform(low, high, shape=(4000,))
    assert s.shape == (2, 4000)
    m = s.asnumpy().mean(axis=1)
    np.testing.assert_allclose(m, [0.5, 15.0], atol=0.3)

    mu = mx.nd.array(np.array([-2.0, 3.0], np.float32))
    sig = mx.nd.array(np.array([1.0, 2.0], np.float32))
    s = ndop.sample_normal(mu, sig, shape=(4000,))
    np.testing.assert_allclose(s.asnumpy().mean(axis=1), [-2, 3], atol=0.2)
    np.testing.assert_allclose(s.asnumpy().std(axis=1), [1, 2], atol=0.2)

    lam = mx.nd.array(np.array([1.0, 5.0], np.float32))
    s = ndop.sample_poisson(lam, shape=(4000,))
    np.testing.assert_allclose(s.asnumpy().mean(axis=1), [1, 5], atol=0.3)

    s = ndop.sample_exponential(lam, shape=(4000,))
    np.testing.assert_allclose(s.asnumpy().mean(axis=1), [1.0, 0.2],
                               atol=0.15)

    a = mx.nd.array(np.array([2.0], np.float32))
    b = mx.nd.array(np.array([3.0], np.float32))
    s = ndop.sample_gamma(a, b, shape=(6000,))
    np.testing.assert_allclose(s.asnumpy().mean(axis=1), [6.0], atol=0.5)

    k = mx.nd.array(np.array([4.0], np.float32))
    p = mx.nd.array(np.array([0.5], np.float32))
    s = ndop.sample_negative_binomial(k, p, shape=(6000,))
    np.testing.assert_allclose(s.asnumpy().mean(axis=1), [4.0], atol=0.5)

    mu = mx.nd.array(np.array([3.0], np.float32))
    alpha = mx.nd.array(np.array([0.5], np.float32))
    s = ndop.sample_generalized_negative_binomial(mu, alpha, shape=(6000,))
    np.testing.assert_allclose(s.asnumpy().mean(axis=1), [3.0], atol=0.5)


def test_sample_multinomial_distribution():
    mx.random.seed(3)
    probs = mx.nd.array(np.array([[0.8, 0.2], [0.1, 0.9]], np.float32))
    s = ndop.sample_multinomial(probs, shape=(3000,))
    freq0 = (s.asnumpy()[0] == 0).mean()
    freq1 = (s.asnumpy()[1] == 1).mean()
    assert abs(freq0 - 0.8) < 0.05
    assert abs(freq1 - 0.9) < 0.05


def test_random_pdfs_against_closed_forms():
    x = mx.nd.array(np.array([[0.3, 0.7]], np.float32))
    low = mx.nd.array(np.array([0.0], np.float32))
    high = mx.nd.array(np.array([2.0], np.float32))
    pdf = ndop.random_pdf_uniform(x, low, high)
    np.testing.assert_allclose(pdf.asnumpy(), [[0.5, 0.5]], rtol=1e-5)

    mu = mx.nd.array(np.array([0.0], np.float32))
    sig = mx.nd.array(np.array([1.0], np.float32))
    pdf = ndop.random_pdf_normal(x, mu, sig)
    want = np.exp(-np.array([[0.3, 0.7]]) ** 2 / 2) / np.sqrt(2 * np.pi)
    np.testing.assert_allclose(pdf.asnumpy(), want, rtol=1e-5)

    lam = mx.nd.array(np.array([2.0], np.float32))
    pdf = ndop.random_pdf_exponential(x, lam)
    np.testing.assert_allclose(pdf.asnumpy(),
                               2 * np.exp(-2 * np.array([[0.3, 0.7]])),
                               rtol=1e-5)

    ks = mx.nd.array(np.array([[1.0, 3.0]], np.float32))
    pmf = ndop.random_pdf_poisson(ks, lam)
    from math import factorial

    want = [[2 ** 1 * np.exp(-2) / factorial(1),
             2 ** 3 * np.exp(-2) / factorial(3)]]
    np.testing.assert_allclose(pmf.asnumpy(), want, rtol=1e-4)

    alpha = mx.nd.array(np.array([2.0], np.float32))
    beta = mx.nd.array(np.array([0.5], np.float32))
    pdf = ndop.random_pdf_gamma(x, alpha, beta)
    xs = np.array([[0.3, 0.7]])
    want = xs ** 1 * np.exp(-xs / 0.5) / (0.5 ** 2 * 1.0)  # Γ(2)=1
    np.testing.assert_allclose(pdf.asnumpy(), want, rtol=1e-4)


def test_eager_random_names_registered():
    mx.random.seed(11)
    u = ndop.uniform(low=0.0, high=1.0, shape=(100,))
    assert u.shape == (100,)
    assert 0 <= float(u.asnumpy().min()) and float(u.asnumpy().max()) <= 1
    n = ndop.normal(loc=5.0, scale=0.1, shape=(500,))
    assert abs(float(n.asnumpy().mean()) - 5.0) < 0.1
    r = ndop.randint(0, 10, shape=(50,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10
    sh = ndop.shuffle(mx.nd.array(np.arange(10, dtype=np.float32)))
    assert sorted(sh.asnumpy().tolist()) == list(range(10))


# ---------------------------------------------------------------------------
# modulated deformable conv + misc
# ---------------------------------------------------------------------------


def test_modulated_deformable_conv_vs_v1():
    """mask == 1 must reproduce DeformableConvolution exactly; mask == 0
    must zero the output."""
    n, c, h, w = 1, 4, 6, 6
    kh = kw = 3
    f = 8
    x = mx.nd.array(_rand(n, c, h, w))
    offset = mx.nd.array(_rand(n, 2 * kh * kw, h, w) * 0.3)
    weight = mx.nd.array(_rand(f, c, kh, kw) * 0.1)
    ones_mask = mx.nd.array(np.ones((n, kh * kw, h, w), np.float32))
    v1 = ndop.DeformableConvolution(x, offset, weight, kernel=(3, 3),
                                    pad=(1, 1), num_filter=f, no_bias=True)
    v2 = ndop.ModulatedDeformableConvolution(
        x, offset, ones_mask, weight, kernel=(3, 3), pad=(1, 1),
        num_filter=f, no_bias=True)
    np.testing.assert_allclose(v2.asnumpy(), v1.asnumpy(), rtol=1e-4,
                               atol=1e-5)
    zero_mask = mx.nd.array(np.zeros((n, kh * kw, h, w), np.float32))
    v0 = ndop.ModulatedDeformableConvolution(
        x, offset, zero_mask, weight, kernel=(3, 3), pad=(1, 1),
        num_filter=f, no_bias=True)
    np.testing.assert_allclose(v0.asnumpy(), 0.0, atol=1e-6)


def test_batch_take_and_friends():
    a = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = mx.nd.array(np.array([0, 2, 1, 0], np.float32))
    out = ndop.batch_take(a, idx)
    np.testing.assert_array_equal(out.asnumpy(), [0, 5, 7, 9])
    out = ndop.choose_element_0index(a, idx)
    np.testing.assert_array_equal(out.asnumpy(), [0, 5, 7, 9])
    filled = ndop.fill_element_0index(a, mx.nd.array(
        np.array([-1, -2, -3, -4], np.float32)), idx)
    got = filled.asnumpy()
    assert got[0, 0] == -1 and got[1, 2] == -2 and got[2, 1] == -3


def test_index_add_update():
    a = mx.nd.array(np.zeros((3, 3), np.float32))
    ind = mx.nd.array(np.array([[0, 2], [1, 2]], np.float32))  # coords
    val = mx.nd.array(np.array([5.0, 7.0], np.float32))
    out = ndop.index_add(a, ind, val)
    want = np.zeros((3, 3))
    want[0, 1] += 5
    want[2, 2] += 7
    np.testing.assert_array_equal(out.asnumpy(), want)
    out = ndop.index_update(out, ind, mx.nd.array(
        np.array([1.0, 2.0], np.float32)))
    want[0, 1] = 1
    want[2, 2] = 2
    np.testing.assert_array_equal(out.asnumpy(), want)


def test_interp_diagflat_addn_amp():
    x = ndop.interp(mx.nd.array(np.array([0.5, 1.5], np.float32)),
                    mx.nd.array(np.array([0.0, 1.0, 2.0], np.float32)),
                    mx.nd.array(np.array([0.0, 10.0, 20.0], np.float32)))
    np.testing.assert_allclose(x.asnumpy(), [5.0, 15.0], rtol=1e-6)

    d = ndop.diagflat(mx.nd.array(np.array([1.0, 2.0], np.float32)))
    np.testing.assert_array_equal(d.asnumpy(), [[1, 0], [0, 2]])

    s = ndop.add_n(mx.nd.ones((2, 2)), mx.nd.ones((2, 2)),
                   mx.nd.ones((2, 2)))
    np.testing.assert_allclose(s.asnumpy(), 3 * np.ones((2, 2)))

    c = ndop.amp_cast(mx.nd.ones((2,)), dtype="bfloat16")
    assert str(c.dtype) == "bfloat16"
    a16 = mx.nd.ones((2,)).astype("bfloat16")
    a32 = mx.nd.ones((2,))
    o1, o2 = ndop.amp_multicast(a16, a32, num_outputs=2)
    assert o1.dtype == np.float32 and o2.dtype == np.float32


def test_identity_attach_kl_sparse_reg_grad():
    from mxnet_tpu import autograd

    x = mx.nd.array(np.full((4, 2), 0.5, np.float32))
    x.attach_grad()
    with autograd.record():
        y = ndop.IdentityAttachKLSparseReg(x, sparseness_target=0.1,
                                           penalty=0.001)
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy())  # identity fwd
    # grad = 1 (from sum) + penalty*KL'(rho_hat=0.5)/batch
    kl = 0.001 * (-0.1 / 0.5 + 0.9 / 0.5) / 4
    np.testing.assert_allclose(x.grad.asnumpy(), 1.0 + kl, rtol=1e-5)


# ---------------------------------------------------------------------------
# sparse FComputeEx twins
# ---------------------------------------------------------------------------


def test_sparse_elemwise_storage_preserved():
    from mxnet_tpu.ndarray import sparse as sp

    a = sp.row_sparse_array((np.array([[1., 2.], [3., 4.]], np.float32),
                             np.array([0, 2])), shape=(4, 2))
    b = sp.row_sparse_array((np.array([[10., 20.], [30., 40.]], np.float32),
                             np.array([2, 3])), shape=(4, 2))
    s = sp.elemwise_add(a, b)
    assert s.stype == "row_sparse"
    assert sorted(np.asarray(s.indices.data).tolist()) == [0, 2, 3]
    np.testing.assert_allclose(s.asnumpy(), a.asnumpy() + b.asnumpy())

    d = sp.elemwise_sub(a, b)
    np.testing.assert_allclose(d.asnumpy(), a.asnumpy() - b.asnumpy())

    p = sp.elemwise_mul(a, b)
    assert p.stype == "row_sparse"
    assert np.asarray(p.indices.data).tolist() == [2]
    np.testing.assert_allclose(p.asnumpy(), a.asnumpy() * b.asnumpy())

    t = sp.add_n(a, b, a)
    np.testing.assert_allclose(t.asnumpy(),
                               2 * a.asnumpy() + b.asnumpy())
    assert t.stype == "row_sparse"


def test_sparse_value_maps_and_clip():
    from mxnet_tpu.ndarray import sparse as sp

    a = sp.row_sparse_array((np.array([[-1., 4.], [9., -16.]], np.float32),
                             np.array([1, 3])), shape=(5, 2))
    sq = sp.square(a)
    assert sq.stype == "row_sparse"
    np.testing.assert_allclose(sq.asnumpy(), a.asnumpy() ** 2)

    sg = sp.sign(a)
    np.testing.assert_allclose(sg.asnumpy(), np.sign(a.asnumpy()))

    r = sp.relu(a)
    np.testing.assert_allclose(r.asnumpy(), np.maximum(a.asnumpy(), 0))

    m = sp.scalar_mul(a, 2.0)
    assert m.stype == "row_sparse"
    np.testing.assert_allclose(m.asnumpy(), 2 * a.asnumpy())

    c = sp.clip(a, -2.0, 2.0)  # 0 inside range -> stays sparse
    assert c.stype == "row_sparse"
    np.testing.assert_allclose(c.asnumpy(), np.clip(a.asnumpy(), -2, 2))
    c2 = sp.clip(a, 1.0, 2.0)  # 0 outside range -> dense fallback
    assert not isinstance(c2, sp.BaseSparseNDArray)
    np.testing.assert_allclose(c2.asnumpy(), np.clip(a.asnumpy(), 1, 2))

    total = sp.sum(a)
    np.testing.assert_allclose(total.asnumpy(), a.asnumpy().sum())


def test_csr_value_map():
    from mxnet_tpu.ndarray import sparse as sp

    m = sp.csr_matrix(np.array([[0, 2., 0], [3., 0, 4.]], np.float32))
    sq = sp.square(m)
    assert sq.stype == "csr"
    np.testing.assert_allclose(sq.asnumpy(), m.asnumpy() ** 2)


def test_encdec_interleaved_matmul():
    """encdec qk/valatt vs a plain attention computed from the same
    interleaved tensors."""
    Tq, Tk, N, H, D = 3, 5, 2, 2, 4
    rng = np.random.RandomState(0)
    q = rng.randn(Tq, N, H * D).astype(np.float32)
    kv = rng.randn(Tk, N, 2 * H * D).astype(np.float32)
    scores = ndop.interleaved_matmul_encdec_qk(
        mx.nd.array(q), mx.nd.array(kv), heads=H)
    assert scores.shape == (N * H, Tq, Tk)
    # reference math
    qr = q.reshape(Tq, N, H, D).transpose(1, 2, 0, 3).reshape(N * H, Tq, D)
    kvr = kv.reshape(Tk, N, H, 2, D)
    kr = kvr[:, :, :, 0].transpose(1, 2, 0, 3).reshape(N * H, Tk, D)
    want = np.einsum("btd,bsd->bts", qr / np.sqrt(D), kr)
    np.testing.assert_allclose(scores.asnumpy(), want, rtol=1e-4, atol=1e-5)

    att = np.abs(rng.randn(N * H, Tq, Tk)).astype(np.float32)
    out = ndop.interleaved_matmul_encdec_valatt(
        mx.nd.array(kv), mx.nd.array(att), heads=H)
    assert out.shape == (Tq, N, H * D)
    vr = kvr[:, :, :, 1].transpose(1, 2, 0, 3).reshape(N * H, Tk, D)
    wanto = np.einsum("bts,bsd->btd", att, vr).reshape(N, H, Tq, D) \
        .transpose(2, 0, 1, 3).reshape(Tq, N, H * D)
    np.testing.assert_allclose(out.asnumpy(), wanto, rtol=1e-4, atol=1e-5)


def test_fft_roundtrip_and_quadratic():
    x = mx.nd.array(np.random.RandomState(1).randn(2, 8).astype(np.float32))
    f = ndop.fft(x)
    assert f.shape == (2, 16)
    back = ndop.ifft(f) / 8  # reference cuFFT convention: unnormalized
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), rtol=1e-4,
                               atol=1e-5)

    q = ndop.quadratic(mx.nd.array(np.array([1., 2.], np.float32)),
                       a=2.0, b=3.0, c=4.0)
    np.testing.assert_allclose(q.asnumpy(), [9., 18.])


def test_group_adagrad_update():
    w = _rand(4, 3)
    g = _rand(4, 3) * 0.1
    h = np.abs(_rand(4))
    w2, h2 = ndop.group_adagrad_update(mx.nd.array(w), mx.nd.array(g),
                                       mx.nd.array(h), 0.1)
    he = h + (g * g).mean(axis=1)
    np.testing.assert_allclose(h2.asnumpy(), he, rtol=1e-5)
    np.testing.assert_allclose(
        w2.asnumpy(), w - 0.1 * g / (np.sqrt(he)[:, None] + 1e-5),
        rtol=1e-4)


def test_masked_softmax():
    x = mx.nd.array(np.array([[1.0, 2.0, 3.0]], np.float32))
    m = mx.nd.array(np.array([[1, 1, 0]], np.float32))
    out = ndop.masked_softmax(x, m).asnumpy()
    assert out[0, 2] == 0.0
    np.testing.assert_allclose(out[0, :2],
                               np.exp([1., 2.]) / np.exp([1., 2.]).sum(),
                               rtol=1e-5)
    lout = ndop.masked_log_softmax(x, m).asnumpy()
    np.testing.assert_allclose(np.exp(lout[0, :2]), out[0, :2], rtol=1e-5)
    assert np.isneginf(lout[0, 2])


def test_dynamic_reshape_and_getnnz():
    x = mx.nd.array(np.arange(6, dtype=np.float32))
    out = ndop.dynamic_reshape(x, mx.nd.array(np.array([2, 3], np.float32)))
    assert out.shape == (2, 3)
    n = ndop.getnnz(mx.nd.array(np.array([[0, 1.], [2., 0]], np.float32)))
    assert int(n.asnumpy()) == 2


def test_sparse_value_map_dense_fallback():
    """Review regression: lambda-based twins must work on dense input."""
    from mxnet_tpu.ndarray import sparse as sp

    d = mx.nd.array(np.array([-1.0, 2.0], np.float32))
    np.testing.assert_allclose(sp.relu(d).asnumpy(), [0.0, 2.0])
    np.testing.assert_allclose(sp.scalar_mul(d, 3.0).asnumpy(), [-3.0, 6.0])
    np.testing.assert_allclose(sp.square(d).asnumpy(), [1.0, 4.0])


def test_masked_softmax_fully_masked_row():
    """Review regression: padding rows must not produce NaN."""
    x = mx.nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    m = mx.nd.array(np.array([[1, 1], [0, 0]], np.float32))
    out = ndop.masked_softmax(x, m).asnumpy()
    assert not np.isnan(out).any()
    np.testing.assert_allclose(out[1], [0.0, 0.0])
    lout = ndop.masked_log_softmax(x, m).asnumpy()
    assert not np.isnan(lout).any()
    assert np.isneginf(lout[1]).all()


def test_sldwin_mask_dilation():
    """Review regression: scalar dilation must actually dilate."""
    score = mx.nd.array(np.zeros((2, 5, 5), np.float32))
    vl = mx.nd.array(np.array([5, 5], np.float32))
    m1 = ndop.sldwin_atten_mask_like(score, vl, dilation=1, w=1).asnumpy()
    m2 = ndop.sldwin_atten_mask_like(score, vl, dilation=2, w=1).asnumpy()
    assert not np.array_equal(m1, m2)
    # dilation=2, w=1: row 2 attends cols j with |2 - 2j| <= 2 -> j in {0,1,2}
    np.testing.assert_array_equal(m2[0, 2], [1, 1, 1, 0, 0])
    # per-head dilation tuple with B*H=2, heads=2
    m3 = ndop.sldwin_atten_mask_like(score, vl, dilation=(1, 2),
                                     w=1).asnumpy()
    np.testing.assert_array_equal(m3[0], m1[0])
    np.testing.assert_array_equal(m3[1], m2[1])


# ---------------------------------------------------------------------------
# round-3: AMP finiteness / adamw / reset_arrays / legacy aliases
# ---------------------------------------------------------------------------

nd = mx.nd


def test_all_finite_family():
    assert nd.all_finite(nd.array([1.0, 2.0])).asnumpy()[0] == 1.0
    assert nd.all_finite(nd.array([1.0, np.inf])).asnumpy()[0] == 0.0
    assert nd.all_finite(nd.array([np.nan])).asnumpy()[0] == 0.0
    ok = nd.multi_all_finite(nd.ones((2,)), nd.ones((3,)))
    bad = nd.multi_all_finite(nd.ones((2,)), nd.array([np.nan]))
    assert ok.asnumpy()[0] == 1.0 and bad.asnumpy()[0] == 0.0


def test_reset_arrays():
    a, b = nd.ones((2, 2)), nd.full((3,), 7.0)
    out = nd.reset_arrays(a, b, num_arrays=2)
    # reference contract: pure side effect — inputs are zeroed in place
    assert out is None
    assert np.all(a.asnumpy() == 0) and np.all(b.asnumpy() == 0)


def test_adamw_update_decoupled_decay():
    w = nd.ones((4,))
    g = nd.zeros((4,))
    m = nd.zeros((4,))
    v = nd.zeros((4,))
    # zero grad -> pure decoupled decay: w -= eta * wd * w
    w2, m2, v2 = nd.adamw_update(w, g, m, v, nd.array(1.0), lr=0.1, wd=0.1,
                                 eta=1.0)
    np.testing.assert_allclose(w2.asnumpy(), 0.9 * np.ones(4), rtol=1e-6)
    # multi-tensor variant agrees with the single-tensor op
    outs = nd.multi_adamw_update(w, nd.full((4,), 0.5), m, v,
                                 w, nd.full((4,), 0.5), m, v,
                                 lrs=(0.01, 0.01), wds=(0.0, 0.0))
    single = nd.adamw_update(w, nd.full((4,), 0.5), m, v, nd.array(1.0),
                             lr=0.01, wd=0.0)
    np.testing.assert_allclose(outs[0].asnumpy(), single[0].asnumpy(),
                               rtol=1e-6)
    np.testing.assert_allclose(outs[3].asnumpy(), single[0].asnumpy(),
                               rtol=1e-6)
    # mp variant keeps a float32 master copy
    outs5 = nd.multi_mp_adamw_update(
        w.astype("float16"), nd.full((4,), 0.5), m, v, w,
        lrs=(0.01,), wds=(0.0,))
    assert outs5[0].dtype == np.float16 and outs5[3].dtype == np.float32


def test_legacy_v1_aliases():
    x = nd.random.uniform(shape=(1, 3, 8, 8))
    w = nd.random.uniform(shape=(4, 3, 3, 3))
    b = nd.zeros((4,))
    y1 = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    y2 = nd.Convolution_v1(x, w, b, kernel=(3, 3), num_filter=4)
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy())
    np.testing.assert_allclose(
        nd.broadcast_plus(nd.ones((2, 1)), nd.ones((1, 3))).asnumpy(),
        2 * np.ones((2, 3)))
    # scalar-attr form: shape IS the output shape (reference _random_gamma)
    g = nd.random_gamma(alpha=9.0, beta=0.5, shape=(2, 2))
    assert g.shape == (2, 2) and np.all(g.asnumpy() > 0)
    assert mx.nd.cast_storage(nd.array([[0, 1]]), "csr").stype == "csr"


def test_scalar_op_family_and_internal_namespace():
    """Round-4 op tail (VERDICT r3 item 10): the reference's
    _scalar elemwise family, exposed via nd._internal / sym._internal
    exactly like python/mxnet/ndarray/_internal.py."""
    import numpy as np

    x = mx.nd.array([1.0, 2.0, 4.0])
    cases = {
        "_plus_scalar": [3, 4, 6], "_minus_scalar": [-1, 0, 2],
        "_rminus_scalar": [1, 0, -2], "_mul_scalar": [2, 4, 8],
        "_div_scalar": [0.5, 1, 2], "_rdiv_scalar": [2, 1, 0.5],
        "_power_scalar": [1, 4, 16], "_maximum_scalar": [2, 2, 4],
        "_minimum_scalar": [1, 2, 2],
    }
    for name, expect in cases.items():
        fn = getattr(mx.nd._internal, name)
        np.testing.assert_allclose(fn(x, scalar=2.0).asnumpy(), expect,
                                   rtol=1e-6, err_msg=name)
        assert hasattr(mx.sym._internal, name)
    np.testing.assert_allclose(
        mx.nd._internal._greater_scalar(x, scalar=1.5).asnumpy(), [0, 1, 1])
    np.testing.assert_allclose(
        mx.nd.logical_xor(x, mx.nd.array([0.0, 2.0, 0.0])).asnumpy(),
        [1, 0, 1])
    np.testing.assert_allclose(mx.nd.trapz(x).asnumpy(), 4.5)
    # registry growth bar from the verdict: ~450 unique implementations
    from mxnet_tpu.ops import registry
    uniq = {id(od): od.name for od in registry.all_ops().values()}
    assert len(set(uniq.values())) >= 440, len(set(uniq.values()))


def test_spectral_norm_layer():
    """gluon.contrib.nn.SpectralNorm: effective weight has unit top
    singular value and gradients flow to the wrapped weight."""
    import numpy as np

    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.contrib.nn import SpectralNorm

    layer = SpectralNorm(gluon.nn.Dense(4, in_units=6, use_bias=False),
                         num_power_iter=8)
    layer.initialize()
    x = mx.nd.array(np.eye(6, dtype=np.float32))
    for _ in range(5):
        y = layer(x)  # converge the power iteration
    sv = np.linalg.svd(y.asnumpy().T, compute_uv=False)[0]
    assert abs(sv - 1.0) < 5e-3, sv
    layer.module.weight.data().attach_grad()
    with autograd.record():
        out = (layer(x) ** 2).sum()
    out.backward()
    g = layer.module.weight.data().grad
    assert g is not None and np.isfinite(g.asnumpy()).all()
    # analytic check (sigma detached): y = x @ (W/sigma).T, L = sum(y^2)
    # => dL/dW = (2/sigma) * y.T @ x.  The r4 advisor found the 1/sigma
    # chain factor silently dropped; this catches any regression.
    w = layer.module.weight.data().asnumpy()
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    y = x.asnumpy() @ (w / sigma).T
    expected = (2.0 / sigma) * (y.T @ x.asnumpy())
    np.testing.assert_allclose(g.asnumpy(), expected, rtol=2e-3, atol=1e-5)
    with pytest.raises(mx.base.MXNetError):
        SpectralNorm(gluon.nn.Flatten())
