"""The composed 4D-parallel trainer (PR19): one mesh contract
(dp, pp, tp, sp, ep), 1F1B-family pipeline schedules, Megatron-style
tensor parallelism, and ZeRO sharding on the dp axis — every layout
must reproduce the single-device autodiff loss trajectory, and the
(dp, pp) -> (dp', pp') snapshot crossing must be bit-exact (the
bit-exact pin itself lives in test_elastic.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mxnet_tpu import parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel.composed import Composed4DStep, tp_all_gather, tp_copy
from mxnet_tpu.parallel.mesh import composed_mesh
from mxnet_tpu.parallel.pipeline import (PipelineTrainStep,
                                         build_pipeline_schedule,
                                         stage_permutation)

L, D, B, M = 4, 8, 16, 4

_rng = np.random.RandomState(0)
W0 = (_rng.randn(L, D, D) * 0.3).astype(np.float32)
b0 = (_rng.randn(L, D) * 0.1).astype(np.float32)
X = _rng.randn(B, D).astype(np.float32)
Y = _rng.randn(B, D).astype(np.float32)


def _stage_fn(p, h):
    W, b = p
    return jnp.tanh(h @ W + b)


def _stage_fn_tp(p, h):
    # W column-sharded over tp: the Megatron f/g bracket (identity
    # fwd / psum bwd on entry, gather fwd / slice bwd on exit)
    W, b = p
    out = tp_copy(h, "tp") @ W
    return jnp.tanh(tp_all_gather(out, "tp", axis=1) + b)


def _loss_fn(o, y):
    return jnp.mean((o - y) ** 2)


def _ref_losses(steps=5, lr=0.1):
    """Single-device plain-autodiff sgd reference trajectory."""
    W, b = jnp.asarray(W0), jnp.asarray(b0)

    @jax.jit
    def one(W, b, x, y):
        def loss_of(W, b):
            h = x
            for i in range(L):
                h = _stage_fn((W[i], b[i]), h)
            return _loss_fn(h, y)

        loss, (gW, gb) = jax.value_and_grad(loss_of, (0, 1))(W, b)
        return W - lr * gW, b - lr * gb, loss

    out = []
    for _ in range(steps):
        W, b, l = one(W, b, jnp.asarray(X), jnp.asarray(Y))
        out.append(float(l))
    return out


def _composed(mesh, zero, opt="sgd", tp_specs=None, sf=_stage_fn,
              steps=5, lr=0.1, schedule=None):
    step = Composed4DStep(sf, (jnp.asarray(W0), jnp.asarray(b0)), mesh,
                          _loss_fn, optimizer=opt, num_microbatches=M,
                          zero_stage=zero, tp_specs=tp_specs,
                          schedule=schedule)
    return step, [float(step(X, Y, lr=lr)) for _ in range(steps)]


def _mesh_dp():
    return composed_mesh(dp=4, devices=jax.devices()[:4])


def _mesh_pp():
    return composed_mesh(dp=2, pp=2, devices=jax.devices()[:4])


def _mesh_3d():
    return composed_mesh(dp=2, pp=2, tp=2)


# ---------------------------------------------------------------------------
# parity against the single-device trajectory
# ---------------------------------------------------------------------------


def test_composed_dp_only_matches_ref():
    ref = _ref_losses()
    step, ls = _composed(_mesh_dp(), 0)
    assert step.schedule.name == "interleaved"  # pp=1 -> v=L chunks
    np.testing.assert_allclose(ls, ref, atol=2e-5)


@pytest.mark.parametrize("zero", [
    pytest.param(0, marks=pytest.mark.slow),  # pp base case is pinned
    pytest.param(2, marks=pytest.mark.slow),  # by the gpipe/1f1b ref
    3,  # test; zero3 keeps the deep reshard path tier-1
])
def test_composed_dp_pp_matches_ref(zero):
    ref = _ref_losses()
    _, ls = _composed(_mesh_pp(), zero)
    np.testing.assert_allclose(ls, ref, atol=2e-5)


@pytest.mark.parametrize("zero", [
    pytest.param(0, marks=pytest.mark.slow),  # plain dp+pp+tp is
    2,  # covered by the schedule/ref tests; zero2 adds the sharding
])
def test_composed_dp_pp_tp_matches_ref(zero):
    ref = _ref_losses()
    _, ls = _composed(_mesh_3d(), zero, sf=_stage_fn_tp,
                      tp_specs=(P(None, "tp"), P()))
    np.testing.assert_allclose(ls, ref, atol=2e-5)


def test_composed_gpipe_and_1f1b_match_ref():
    ref = _ref_losses()
    mesh = composed_mesh(dp=2, pp=4)
    for sched in ("gpipe", "1f1b"):
        step, ls = _composed(mesh, 0, schedule=sched)
        assert step.schedule.name == sched
        np.testing.assert_allclose(ls, ref, atol=2e-5, err_msg=sched)


@pytest.mark.slow
@pytest.mark.parametrize("opt", ["adam", "lamb"])
def test_composed_zero_stages_agree(opt):
    """ZeRO is a memory layout, not a numeric change: stage 0/2/3 give
    the SAME trajectory (lamb exercises the sharded trust-ratio norms
    — psum over pp+dp must reproduce the unsharded global norm)."""
    _, l0 = _composed(_mesh_pp(), 0, opt=opt, lr=0.02)
    _, l2 = _composed(_mesh_pp(), 2, opt=opt, lr=0.02)
    _, l3 = _composed(_mesh_pp(), 3, opt=opt, lr=0.02)
    np.testing.assert_allclose(l2, l0, atol=2e-5)
    np.testing.assert_allclose(l3, l0, atol=2e-5)


@pytest.mark.slow
def test_composed_lamb_tp_sharded_norms_agree():
    """lamb + tensor-parallel leaves: the trust-ratio norm must span
    the tp shards too (per-leaf psum axes), so zero-0 and zero-2 agree
    on a (dp, pp, tp) mesh."""
    _, l0 = _composed(_mesh_3d(), 0, opt="lamb", lr=0.02,
                      sf=_stage_fn_tp, tp_specs=(P(None, "tp"), P()))
    _, l2 = _composed(_mesh_3d(), 2, opt="lamb", lr=0.02,
                      sf=_stage_fn_tp, tp_specs=(P(None, "tp"), P()))
    np.testing.assert_allclose(l2, l0, atol=2e-5)


@pytest.mark.slow
def test_composed_superstep_matches_stepwise():
    stepA, ls = _composed(_mesh_pp(), 2, opt="adam", lr=0.02, steps=4)
    stepB, _ = _composed(_mesh_pp(), 2, opt="adam", lr=0.02, steps=0)
    xs = np.stack([X] * 4)
    ys = np.stack([Y] * 4)
    got = [float(v) for v in stepB.run_superstep(xs, ys, lr=0.02)]
    np.testing.assert_allclose(got, ls, atol=2e-5)


# ---------------------------------------------------------------------------
# memory layout + reports
# ---------------------------------------------------------------------------


def test_composed_zero2_shards_optimizer_memory():
    s0, _ = _composed(_mesh_pp(), 0, opt="adam", steps=1, lr=0.02)
    s2, _ = _composed(_mesh_pp(), 2, opt="adam", steps=1, lr=0.02)
    m0, m2 = s0.memory_report(), s2.memory_report()
    # dp=2: ZeRO-2 halves per-device optimizer state (within padding)
    assert m2["opt_bytes_per_device"] <= m0["opt_bytes_per_device"] \
        * 0.55, (m0, m2)
    assert m2["zero_stage"] == 2 and m0["zero_stage"] == 0
    for key in ("schedule", "bubble_fraction", "stash_slots",
                "param_bytes_per_device"):
        assert key in m0, m0


def test_composed_schedule_report_fields():
    step, _ = _composed(_mesh_pp(), 0, steps=0)
    rep = step.schedule_report()
    assert rep["schedule"] == "interleaved"  # L=4 over pp=2 -> v=2
    assert rep["ranks"] == 2 and rep["virtual"] == 2
    assert 0.0 <= rep["bubble_fraction"] < 1.0
    assert rep["stash_slots"] >= 1


# ---------------------------------------------------------------------------
# schedule table pins (host-side, no compile)
# ---------------------------------------------------------------------------


def test_bubble_fraction_table():
    """The honest schedule math, pinned: plain 1F1B keeps GPipe's
    fill-drain bubble (S-1)/(M+S-1) and only shrinks the activation
    stash to S in-flight microbatches; interleaving v chunks cuts the
    bubble to (S-1)/(M*v+S-1)."""
    gp = build_pipeline_schedule(4, 8, "gpipe")
    f1b = build_pipeline_schedule(4, 8, "1f1b")
    il = build_pipeline_schedule(2, 8, "interleaved", virtual=2)
    assert abs(gp.bubble_fraction - 3.0 / 11.0) < 1e-6
    assert abs(f1b.bubble_fraction - gp.bubble_fraction) < 1e-9
    assert f1b.stash_slots == 4 and gp.stash_slots == 8
    assert abs(il.bubble_fraction - 1.0 / 17.0) < 1e-6
    assert 1.0 - il.bubble_fraction >= 0.9  # the PR19 overlap gate
    gp2 = build_pipeline_schedule(2, 8, "gpipe")
    assert il.bubble_fraction < gp2.bubble_fraction


def test_stage_permutation_roundtrip():
    for S, v in [(2, 2), (4, 2), (2, 4), (3, 3)]:
        perm = stage_permutation(S, v)
        assert sorted(perm) == list(range(S * v))
        # position p = r*v + c holds global stage c*S + r
        for r in range(S):
            for c in range(v):
                assert perm[r * v + c] == c * S + r
        inv = np.argsort(np.asarray(perm))
        np.testing.assert_array_equal(
            np.asarray(perm)[inv], np.arange(S * v))


# ---------------------------------------------------------------------------
# contract errors
# ---------------------------------------------------------------------------


def test_composed_declines_sp_ep_axes():
    four = jax.devices()[:4]
    mesh = composed_mesh(dp=2, sp=2, devices=four)
    with pytest.raises(MXNetError, match="ring_attention"):
        Composed4DStep(_stage_fn, (jnp.asarray(W0), jnp.asarray(b0)),
                       mesh, _loss_fn)
    mesh = composed_mesh(dp=2, ep=2, devices=four)
    with pytest.raises(MXNetError, match="moe_apply_a2a"):
        Composed4DStep(_stage_fn, (jnp.asarray(W0), jnp.asarray(b0)),
                       mesh, _loss_fn)


def test_composed_gpipe_needs_one_stage_per_rank():
    # L=4 stages over pp=2 means v=2 virtual chunks: fill-drain and
    # plain 1F1B must decline loudly toward interleaved
    for sched in ("gpipe", "1f1b"):
        with pytest.raises(MXNetError, match="interleaved"):
            _composed(_mesh_pp(), 0, schedule=sched, steps=0)


def test_composed_batch_must_tile_dp():
    step, _ = _composed(_mesh_pp(), 0, steps=0)
    bad = np.zeros((6, D), np.float32)  # 6/M microbatch can't tile dp=2
    with pytest.raises(MXNetError, match="dp"):
        step(bad, bad[:, :D], lr=0.1)


def test_spmd_step_declines_pp_mesh():
    from mxnet_tpu.parallel.spmd import SPMDTrainStep

    net = None  # params unused: the mesh contract fails first
    with pytest.raises(MXNetError, match="Composed4DStep"):
        SPMDTrainStep(net, _loss_fn, mesh=_mesh_pp())


# ---------------------------------------------------------------------------
# pipeline schedule trajectory parity through PipelineTrainStep
# (sgd/adam x AMP off/bf16): 1F1B and interleaved are reorderings of
# the same microbatch work — the update must be identical to gpipe's
# ---------------------------------------------------------------------------


def _pp_stages(n):
    rng = np.random.RandomState(7)
    return [(jnp.asarray((np.eye(D) + rng.randn(D, D) * 0.05)
                         .astype(np.float32)),
             jnp.asarray(np.full(D, 0.05, np.float32)))
            for _ in range(n)]


def _pp_losses(schedule, stages, S, opt, amp, steps=3):
    from mxnet_tpu.parallel.pipeline import stack_stage_params

    mesh = parallel.make_mesh({"pp": S}, devices=jax.devices()[:S])
    step = PipelineTrainStep(
        _stage_fn, stack_stage_params(stages), mesh, _loss_fn,
        num_microbatches=4, schedule=schedule, optimizer=opt,
        amp_dtype=amp)
    x = np.asarray(X[:8], np.float32)
    y = np.asarray(Y[:8], np.float32)
    return [float(step(x, y, lr=0.05)) for _ in range(steps)]


# one (opt, amp) cell stays in tier-1 as the representative; the rest
# of the matrix compiles 9 extra pipeline graphs (~25 s) for the same
# schedule-equivalence property and runs with the slow tier
@pytest.mark.parametrize("opt,amp", [
    ("sgd", None),
    pytest.param("adam", None, marks=pytest.mark.slow),
    pytest.param("sgd", "bfloat16", marks=pytest.mark.slow),
    pytest.param("adam", "bfloat16", marks=pytest.mark.slow),
])
def test_pipeline_schedules_agree(opt, amp):
    stages = _pp_stages(2)
    gp = _pp_losses("gpipe", stages, 2, opt, amp)
    f1b = _pp_losses("1f1b", stages, 2, opt, amp)
    il = _pp_losses("interleaved", _pp_stages(4), 2, opt, amp)
    tol = 2e-2 if amp else 2e-5
    np.testing.assert_allclose(f1b, gp, atol=tol)
    # interleaved runs 4 stages as 2 virtual chunks per rank — a
    # different (deeper) net, so only the gpipe/1f1b pair is exact;
    # the interleaved leg must still train sanely
    assert il[-1] <= il[0] + tol, il
    if amp is None and opt == "sgd":
        # AMP off: the manual tick-table executor reproduces plain
        # autodiff exactly
        W = np.stack([np.asarray(w) for w, _ in stages])
        bb = np.stack([np.asarray(b) for _, b in stages])

        def ref():
            Wj, bj = jnp.asarray(W), jnp.asarray(bb)
            out = []
            for _ in range(3):
                def loss_of(Wj, bj):
                    h = jnp.asarray(X[:8])
                    for i in range(2):
                        h = _stage_fn((Wj[i], bj[i]), h)
                    return _loss_fn(h, jnp.asarray(Y[:8]))

                loss, (gW, gb) = jax.value_and_grad(
                    loss_of, (0, 1))(Wj, bj)
                Wj, bj = Wj - 0.05 * gW, bj - 0.05 * gb
                out.append(float(loss))
            return out

        np.testing.assert_allclose(gp, ref(), atol=2e-5)
