"""Small reference surfaces with no dedicated tests so far: mx.callback,
mx.visualization, mx.runtime, mx.name / mx.attribute scopes (reference:
python/mxnet/{callback,visualization,runtime,name,attribute}.py)."""

import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym


def _net():
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, sym.var("w1"), sym.var("b1"),
                             num_hidden=8, name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, sym.var("w2"), sym.var("b2"),
                             num_hidden=3, name="fc2")
    return sym.SoftmaxOutput(fc2, sym.var("softmax_label"), name="softmax")


def test_visualization_print_summary(capsys):
    mx.visualization.print_summary(_net(), shape={"data": (2, 5)})
    out = capsys.readouterr().out
    assert "fc1" in out and "fc2" in out
    # per-layer param counts: fc1 = 5*8+8 = 48, fc2 = 8*3+3 = 27
    assert "48" in out and "27" in out and "Total params:" in out


def test_callback_speedometer_and_do_checkpoint(tmp_path, caplog):
    from mxnet_tpu.callback import BatchEndParam, Speedometer, do_checkpoint

    metric = mx.metric.create("acc")
    metric.update([mx.nd.array([0, 1])],
                  [mx.nd.array([[0.9, 0.1], [0.1, 0.9]])])
    speed = Speedometer(batch_size=4, frequent=1)
    with caplog.at_level(logging.INFO):
        for nbatch in range(3):
            speed(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=metric))
    assert any("Speed" in r.message or "samples/sec" in r.message
               for r in caplog.records)

    prefix = str(tmp_path / "model")
    cb = do_checkpoint(prefix, period=1)
    net = _net()
    args = {"w1": mx.nd.ones((8, 5)), "b1": mx.nd.zeros((8,)),
            "w2": mx.nd.ones((3, 8)), "b2": mx.nd.zeros((3,))}
    cb(0, net, args, {})
    loaded_sym, loaded_args, _ = mx.model.load_checkpoint(prefix, 1)
    assert sorted(loaded_args) == sorted(args)
    np.testing.assert_allclose(loaded_args["w1"].asnumpy(),
                               args["w1"].asnumpy())


def test_runtime_features():
    feats = mx.runtime.Features()
    # XLA/PJIT/PALLAS are build capabilities (always on); TPU reflects
    # the LIVE backend and is False on this CPU-forced suite
    assert feats.is_enabled("XLA") and feats.is_enabled("PJIT")
    assert feats.is_enabled("PALLAS") and feats.is_enabled("BF16")
    # reference-named features that are honestly absent report False
    assert not feats.is_enabled("CUDA")
    assert not feats.is_enabled("MKLDNN")
    # liveness: TPU reflects the running backend, False under forced CPU
    assert not feats.is_enabled("TPU")


def test_name_manager_and_attr_scope():
    mx.name.reset()
    a = mx.name.next_name("conv")
    b = mx.name.next_name("conv")
    assert a != b and a.startswith("conv") and b.startswith("conv")
    mx.name.reset()
    assert mx.name.next_name("conv") == a

    from mxnet_tpu.attribute import AttrScope

    with AttrScope(ctx_group="dev1", foo="bar"):
        attrs = AttrScope.current().get()
        assert attrs["ctx_group"] == "dev1" and attrs["foo"] == "bar"
        with AttrScope(foo="baz"):
            inner = AttrScope.current().get()
            assert inner["foo"] == "baz" and inner["ctx_group"] == "dev1"
    assert "foo" not in (AttrScope.current().get() or {})
