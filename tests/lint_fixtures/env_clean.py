"""Clean twin of env_bad.py: documented names through the accessor,
and an env WRITE (launcher-style child env setup), which is allowed."""

import os

from mxnet_tpu.base import getenv


def telemetry_on():
    return bool(getenv("MXTPU_TELEMETRY", False, dtype=bool))


def child_env(rank):
    env = dict(os.environ)
    os.environ["MXTPU_PROCESS_ID"] = str(rank)   # write: allowed
    return env
