"""Clean twin of guard_bad.py: every declared mutation under its lock
(nested with-blocks count), undeclared attributes unconstrained."""

import threading


class Writer:
    _GUARDED_BY = {"_pending": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0

    def enqueue(self):
        with self._lock:
            self._pending += 1

    def drain(self, cv):
        with cv:
            with self._lock:
                self._pending -= 1
        self._hint = "drained"        # undeclared attr: unconstrained

    def submit(self, executor):
        def done_cb(fut):
            with self._lock:          # the closure takes the lock itself
                self._pending -= 1
        executor.add_done_callback(done_cb)
