"""Seeded violations for the thread-guard rule (clean twin:
guard_clean.py): _GUARDED_BY-declared state mutated off-lock."""

import threading


class Writer:
    _GUARDED_BY = {"_pending": "_lock", "_queue_depth": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0             # __init__ is exempt
        self._queue_depth = 0

    def enqueue(self):
        self._pending += 1            # violation: no lock held

    def drain(self):
        with self._lock:
            self._pending -= 1
        self._queue_depth = 0         # violation: outside the with block

    def submit(self, executor):
        with self._lock:
            def done_cb(fut):
                self._pending -= 1    # violation: the closure runs LATER,
                # when the lock held at its definition site is long gone
            executor.add_done_callback(done_cb)
