"""Seeded violations for the overlap-window-sync rule (the clean twin
is overlap_clean.py). Never imported — parsed by mxtpu-lint."""

import numpy as np

import jax
from mxnet_tpu import engine


def issue_buckets(grads, axis, log):  # mxtpu-lint: overlap-window
    flat = [g.reshape(-1) for g in grads]
    # violation: graph-level barrier pins comm behind the whole backward
    flat = jax.lax.optimization_barrier(tuple(flat))
    out = []
    for b in flat:
        red = jax.lax.psum(b, axis)
        log.append(float(red[0]))      # violation: float() host sync
        out.append(red)
    host = np.asarray(out[0])          # violation: host materialization
    return out, host


def staged_window(kv, buckets):  # mxtpu-lint: overlap-window
    reduced = []
    for b in buckets:
        kv.barrier()                   # violation: host-level barrier
        reduced.append(kv._reduce_raw(b))
    engine.wait(reduced[0])            # violation: host-level barrier
    reduced[0].block_until_ready()     # violation: host sync
    return reduced
