"""Seeded lock-order violations (tier-1 fixture; never imported).

Expected: an A->B / B->A acquisition cycle, a thread join while
holding a lock, and a non-reentrant self re-acquisition.
"""

import threading

_REG_LOCK = threading.Lock()
_IO_LOCK = threading.Lock()


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=lambda: None)

    def swap(self):
        with _REG_LOCK:
            with _IO_LOCK:  # edge _REG_LOCK -> _IO_LOCK
                return 1

    def rotate(self):
        with _IO_LOCK:
            with _REG_LOCK:  # edge _IO_LOCK -> _REG_LOCK: closes the cycle
                return 2

    def close(self):
        with self._lock:
            self._thread.join()  # blocks every thread wanting _lock

    def reenter(self):
        with self._lock:
            with self._lock:  # plain Lock: self-deadlock
                return 3
