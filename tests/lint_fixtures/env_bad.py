"""Seeded violations for the env-var-discipline rule (clean twin:
env_clean.py): direct os.environ reads of MXTPU_* names, and a name
that is nowhere in docs/env_vars.md."""

import os


def depth():
    return int(os.environ.get("MXTPU_FIXTURE_KNOB", "2"))  # violation x2
    # (direct read bypassing the accessor + undocumented name)


def rank():
    if "MXTPU_FIXTURE_RANK" in os.environ:     # violation: membership read
        return int(os.environ["MXTPU_FIXTURE_RANK"])  # violation: [] read
    return 0
