"""Seeded violations for the capture-unsafe-in-graph rule (clean twin:
capture_clean.py): trace-unsafe Python inside functions that become
jit / scan bodies."""

import os
import random
import time

import numpy as np

_CALLS = 0


def body(carry, x):
    t = time.time()                       # violation: trace-time constant
    noise = np.random.normal()            # violation: one draw at trace
    jitter = random.random()              # violation: one draw at trace
    print("tracing", carry)               # violation: prints once
    mode = os.environ.get("MXTPU_MODE")   # violation: env read at trace
    global _CALLS                         # violation: global mutation
    _CALLS += 1
    return carry + x + noise + jitter, (t, mode)


def run(xs):
    import jax

    return jax.lax.scan(body, 0.0, xs)


def fwd(params, x):
    print("fwd trace")                    # violation: decorated jit body
    return params @ x


def build():
    import jax

    return jax.jit(fwd)


def branch_true(x):
    return x + 1


def branch_false(x):
    return x * np.random.rand()           # violation: a cond BRANCH
    # (beyond arg 0) is a traced body too


def choose(pred, x):
    from jax import lax

    return lax.cond(pred, branch_true, branch_false, x)
