"""Seeded violations for the donation-after-use rule (clean twin:
donation_clean.py). `_apply_fused_update` donates args 0 and 2; the
`donates=` annotation marks an ad-hoc donating call line."""


def step(ws, gs, sts, update):
    new_ws, new_sts = _apply_fused_update(ws, gs, sts, update)  # noqa: F821
    norm = sum(w.sum() for w in ws)   # violation: ws donated above
    return new_ws, new_sts, norm


def dispatch(fn, args, introspect):
    out = fn(*args)  # mxtpu-lint: donates=args
    introspect.record(args)           # violation: args donated above
    return out
