"""Clean twin of host_sync_bad.py: a hot function that keeps values
lazy, and a COLD function where host syncs are allowed."""

import numpy as np


def hot_step(batch, metrics):  # mxtpu-lint: hot-path
    loss = batch.mean()
    metrics.set_lazy(loss)            # lazy device scalar: fine
    n = int(batch.shape[0])           # static shape metadata: fine
    return loss, n


def cold_summary(batch):
    # not marked hot: host materialization is allowed here
    return float(batch.mean()), np.asarray(batch).tolist()
