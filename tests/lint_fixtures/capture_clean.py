"""Clean twin of capture_bad.py: the same shapes with the unsafe work
hoisted OUT of the traced bodies (operands in, logging outside)."""

import time


def body(carry, slot):
    x, key = slot                     # randomness rides in as operands
    return carry + x, key


def run(xs, keys):
    import jax

    t0 = time.time()                  # host timing OUTSIDE the graph
    out = jax.lax.scan(body, 0.0, (xs, keys))
    print("scan took", time.time() - t0)
    return out


def helper(x):
    # not a graph body anywhere in this file: unsafe-for-trace calls
    # are fine in plain host code
    print("host-side", time.time())
    return x
