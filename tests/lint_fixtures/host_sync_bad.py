"""Seeded violations for the host-sync-in-hot-path rule (the clean
twin is host_sync_clean.py). Never imported — parsed by mxtpu-lint."""

import numpy as np


def hot_step(batch, metrics):  # mxtpu-lint: hot-path
    loss = batch.mean()
    metrics.append(loss.item())       # violation: .item() scalar sync
    host = np.asarray(loss)           # violation: host materialization
    return float(loss), host          # violation: float() on array
