"""Clean twin of donation_bad.py: capture-before-donate, rebinding,
and branch-isolated reads (an `else` branch must not be poisoned by a
donation in the `if` branch)."""


def step(ws, gs, sts, update, introspect):
    avals = introspect.avals_of(ws)   # captured BEFORE the donation
    new_ws, new_sts = _apply_fused_update(ws, gs, sts, update)  # noqa: F821
    ws = new_ws                       # rebound: the name is fresh again
    return ws, new_sts, avals


def dispatch(fn, args, instrumented):
    if instrumented:
        out = _dispatch_call("site", "span", fn, args)  # noqa: F821
    else:
        out = fn(*args)               # sibling branch: args not donated
    return out
