"""Clean twin of overlap_bad.py: the same shape of work with nothing
re-serializing the overlap window. Never imported — parsed by
mxtpu-lint."""

import jax
import jax.numpy as jnp


def issue_buckets(grads, axis, plan, barrier=False):  # mxtpu-lint: overlap-window
    flat = [g.reshape(-1) for g in grads]
    if barrier:
        # the sanctioned ablation site: same numerics, no early start
        flat = list(jax.lax.optimization_barrier(  # mxtpu-lint: overlap-barrier-ok
            tuple(flat)))
    out = []
    for idxs in plan:
        parts = [flat[i] for i in idxs]
        b = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        # host-side plan integers are fine: int() never touches the
        # device stream
        n = int(b.shape[0])
        out.append(jax.lax.psum(b, axis)[:n])
    return out


def after_the_window(reduced, log):
    # host syncs OUTSIDE a window function are the caller's business
    log.append(float(reduced[0][0]))
    return reduced
