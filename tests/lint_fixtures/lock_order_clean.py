"""Clean twin of lock_order_bad.py: one global acquisition order, waits
happen outside locks (or bounded), RLock re-entry, and a documented
sanctioned edge."""

import threading

_REG_LOCK = threading.Lock()
_IO_LOCK = threading.Lock()


class Worker:
    def __init__(self):
        self._lock = threading.RLock()
        self._thread = threading.Thread(target=lambda: None)

    def swap(self):
        with _REG_LOCK:
            with _IO_LOCK:  # the ONE order: _REG_LOCK before _IO_LOCK, everywhere
                return 1

    def rotate(self):
        with _REG_LOCK:
            with _IO_LOCK:
                return 2

    def close(self):
        with self._lock:
            thread = self._thread
        thread.join()  # the wait happens OUTSIDE the lock

    def bounded(self):
        with self._lock:
            self._thread.join(timeout=1.0)  # bounded wait is fine

    def reenter(self):
        with self._lock:
            with self._lock:  # RLock: re-entry is legal
                return 3

    def sanctioned(self):
        with _IO_LOCK:
            # the drain path takes _IO_LOCK alone; documented exception:
            with _REG_LOCK:  # mxtpu-lint: lock-order-ok
                return 4
