"""LibSVMIter tests (reference: ``src/io/iter_libsvm.cc`` +
``tests/python/unittest/test_io.py`` test_LibSVMIter)."""

import numpy as np
import pytest

import mxnet_tpu as mx


def _write_libsvm(path, X, y):
    with open(path, "w") as f:
        for row, lab in zip(X, y):
            feats = " ".join(f"{i}:{v:g}" for i, v in enumerate(row) if v)
            f.write(f"{lab:g} {feats}\n")


@pytest.fixture
def libsvm_file(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.rand(10, 6).astype(np.float32)
    X[X < 0.5] = 0  # sparsify
    y = rng.randint(0, 2, 10).astype(np.float32)
    path = tmp_path / "train.libsvm"
    _write_libsvm(path, X, y)
    return str(path), X, y


def test_basic_batches(libsvm_file):
    path, X, y = libsvm_file
    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(6,), batch_size=5)
    batches = list(it)
    assert len(batches) == 2
    for bi, batch in enumerate(batches):
        assert batch.data[0].stype == "csr"
        dense = batch.data[0].asnumpy()
        np.testing.assert_allclose(dense, X[bi * 5:(bi + 1) * 5], rtol=1e-6)
        np.testing.assert_allclose(batch.label[0].asnumpy(),
                                   y[bi * 5:(bi + 1) * 5])


def test_round_batch_wraps(libsvm_file):
    path, X, y = libsvm_file
    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(6,), batch_size=4,
                          round_batch=True)
    batches = list(it)
    # pad reports the wrapped-row count (reference num_batch_padd) even
    # though the rows are filled by wrapping
    assert len(batches) == 3 and batches[-1].pad == 2
    dense = batches[-1].data[0].asnumpy()
    np.testing.assert_allclose(dense[:2], X[8:10], rtol=1e-6)
    np.testing.assert_allclose(dense[2:], X[0:2], rtol=1e-6)  # wrapped
    it.reset()
    assert len(list(it)) == 3  # reset replays the epoch


def test_round_batch_shorter_than_batch(libsvm_file):
    """Dataset smaller than one batch: round_batch wraps the epoch
    repeatedly (modular rows), never zero-pads (r4 advisor finding)."""
    path, X, y = libsvm_file
    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(6,), batch_size=23,
                          round_batch=True)
    batches = list(it)
    assert len(batches) == 1 and batches[0].pad == 13
    dense = batches[0].data[0].asnumpy()
    expect = X[np.arange(23) % 10]
    np.testing.assert_allclose(dense, expect, rtol=1e-6)
    np.testing.assert_allclose(batches[0].label[0].asnumpy(),
                               y[np.arange(23) % 10])


def test_pad_mode(libsvm_file):
    path, X, y = libsvm_file
    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(6,), batch_size=4,
                          round_batch=False)
    batches = list(it)
    assert batches[-1].pad == 2
    dense = batches[-1].data[0].asnumpy()
    np.testing.assert_allclose(dense[2:], 0.0)  # padded rows empty


def test_comments_and_blank_lines(tmp_path):
    path = tmp_path / "c.libsvm"
    path.write_text("# header comment\n"
                    "1 0:1.5 3:2.0  # trailing comment\n"
                    "\n"
                    "0 1:0.5\n")
    it = mx.io.LibSVMIter(data_libsvm=str(path), data_shape=(4,),
                          batch_size=2)
    batch = next(iter(it))
    np.testing.assert_allclose(batch.data[0].asnumpy(),
                               [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    np.testing.assert_allclose(batch.label[0].asnumpy(), [1.0, 0.0])


def test_separate_label_file(tmp_path):
    dpath = tmp_path / "d.libsvm"
    lpath = tmp_path / "l.libsvm"
    dpath.write_text("0 0:1.0\n0 1:2.0\n")
    lpath.write_text("0 0:1.0 2:1.0\n0 1:1.0\n")  # multi-label rows
    it = mx.io.LibSVMIter(data_libsvm=str(dpath), data_shape=(2,),
                          label_libsvm=str(lpath), label_shape=(3,),
                          batch_size=2)
    batch = next(iter(it))
    np.testing.assert_allclose(batch.label[0].asnumpy(),
                               [[1, 0, 1], [0, 1, 0]])


def test_index_out_of_range_raises(tmp_path):
    path = tmp_path / "bad.libsvm"
    path.write_text("1 7:1.0\n")
    with pytest.raises(mx.base.MXNetError, match="out of range"):
        mx.io.LibSVMIter(data_libsvm=str(path), data_shape=(4,),
                         batch_size=1)


def test_trains_linear_model(tmp_path):
    """End-to-end: LibSVMIter feeds dot(csr, dense) training."""
    rng = np.random.RandomState(3)
    w_true = rng.randn(8).astype(np.float32)
    X = (rng.rand(64, 8) * (rng.rand(64, 8) > 0.5)).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)
    path = tmp_path / "t.libsvm"
    _write_libsvm(path, X, y)

    w = mx.nd.zeros((8, 1))
    losses = []
    for epoch in range(40):
        it = mx.io.LibSVMIter(data_libsvm=str(path), data_shape=(8,),
                              batch_size=32, round_batch=True)
        total = 0.0
        for batch in it:
            w.attach_grad()
            with mx.autograd.record():
                logits = mx.nd.dot(batch.data[0], w).reshape((-1,))
                lbl = batch.label[0]
                loss = mx.nd.mean(
                    mx.nd.log(1 + mx.nd.exp(-(2 * lbl - 1) * logits)))
            loss.backward()
            w._set_data((w - 2.0 * w.grad).data)
            total += float(loss.asnumpy())
        losses.append(total)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_num_parts_sharding(libsvm_file):
    """Distributed sharded read (reference num_parts/part_index)."""
    path, X, y = libsvm_file
    rows = []
    for part in range(3):
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(6,),
                              batch_size=10, round_batch=False,
                              num_parts=3, part_index=part)
        batch = next(iter(it))
        n = 10 - batch.pad
        rows.append(batch.data[0].asnumpy()[:n])
    got = np.concatenate(rows)
    np.testing.assert_allclose(got, X, rtol=1e-6)  # parts tile the file
    with pytest.raises(mx.base.MXNetError, match="part_index"):
        mx.io.LibSVMIter(data_libsvm=path, data_shape=(6,), batch_size=2,
                         num_parts=2, part_index=5)


def test_num_parts_with_label_file(tmp_path):
    """Sharded read shards the separate label file by the same blocks."""
    dpath = tmp_path / "d.libsvm"
    lpath = tmp_path / "l.libsvm"
    dpath.write_text("".join(f"0 0:{i}.0\n" for i in range(1, 5)))
    lpath.write_text("".join(f"0 {i % 3}:1.0\n" for i in range(4)))
    for part in range(2):
        it = mx.io.LibSVMIter(data_libsvm=str(dpath), data_shape=(1,),
                              label_libsvm=str(lpath), label_shape=(3,),
                              batch_size=2, num_parts=2, part_index=part)
        batch = next(iter(it))
        np.testing.assert_allclose(
            batch.data[0].asnumpy()[:, 0],
            [1.0 + 2 * part, 2.0 + 2 * part])
        lab = batch.label[0].asnumpy()
        assert lab.shape == (2, 3)
        assert lab[0, (2 * part) % 3] == 1.0


def test_part_index_validated_even_for_one_part(libsvm_file):
    path, _, _ = libsvm_file
    with pytest.raises(mx.base.MXNetError, match="part_index"):
        mx.io.LibSVMIter(data_libsvm=path, data_shape=(6,), batch_size=2,
                         num_parts=1, part_index=3)
    with pytest.raises(mx.base.MXNetError, match="part_index"):
        mx.io.LibSVMIter(data_libsvm=path, data_shape=(6,), batch_size=2,
                         num_parts=0)
