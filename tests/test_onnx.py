"""ONNX export/import through the checked-in proto codec.

Round-trips are numeric: export a trained net, re-import, compare
predictions. The wire format uses the upstream ONNX field numbers
(onnx_support/onnx.proto), so files interchange with standard tooling.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import onnx as onnx_mod
from mxnet_tpu.gluon import nn


def _export_net(net, tmp_path, shape):
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).rand(*shape).astype(np.float32))
    want = net(x).asnumpy()
    net.export(str(tmp_path / "m"))
    path = onnx_mod.export_model(
        str(tmp_path / "m-symbol.json"), str(tmp_path / "m-0000.params"),
        [shape], onnx_file_path=str(tmp_path / "m.onnx"))
    return path, x, want


def test_onnx_export_import_mlp(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    path, x, want = _export_net(net, tmp_path, (4, 8))

    sym, args, aux = onnx_mod.import_model(path)
    from mxnet_tpu.symbol.executor import eval_symbol

    feed = {k: v for k, v in args.items()}
    feed["data"] = x
    (got,) = eval_symbol(sym, feed)
    np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-4, atol=1e-5)


def test_onnx_export_import_cnn(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Flatten(),
            nn.Dense(5))
    path, x, want = _export_net(net, tmp_path, (2, 3, 8, 8))

    block = onnx_mod.import_to_gluon(path)
    got = block(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_onnx_metadata_and_wire_sanity(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    path, x, want = _export_net(net, tmp_path, (2, 6))
    meta = onnx_mod.get_model_metadata(path)
    assert meta["input_tensor_data"] == [("data", (2, 6))]
    assert len(meta["output_tensor_data"]) == 1

    # wire sanity: proto3 field layout — first bytes of ModelProto encode
    # ir_version (field 1, varint): tag 0x08
    raw = open(path, "rb").read()
    assert raw[0] == 0x08
    assert b"mxnet_tpu" in raw  # producer_name survives
    # initializers must carry the exact little-endian f32 weight bytes
    from mxnet_tpu.ndarray import ndarray as nd_mod

    params = nd_mod.load(str(tmp_path / "m-0000.params"))
    (wname, warr) = next((k.split(":", 1)[1], v) for k, v in params.items()
                         if k.endswith("weight"))
    assert warr.asnumpy().astype(np.float32).tobytes() in raw, \
        "raw weight bytes not found in the ONNX file"


def test_onnx_unmapped_op_raises(tmp_path):
    from mxnet_tpu.symbol import symbol as sym_mod

    data = sym_mod.var("data")
    odd = sym_mod.Symbol("arcsinh", {}, [data], name="odd")
    with pytest.raises(MXNetError):
        onnx_mod.export_model(odd, {}, [(2, 2)],
                              onnx_file_path=str(tmp_path / "x.onnx"))
