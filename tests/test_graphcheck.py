"""mxtpu-lint --graph: compiled-artifact contract checking.

Unit leg: every graph rule fires on a hand-built stub record and stays
quiet on its clean twin — jaxprs are duck-typed, so nothing here needs
jax. Integration leg: ONE subprocess ``--graph --json`` run asserts the
trace harness registers the full canonical site set and the shipped
tree is clean against the checked-in contracts (the tier-1 gate: a
reordered collective in overlap.py or a dead donation turns this red).
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.mxtpu_lint import apply_baseline, write_baseline  # noqa: E402
from tools.mxtpu_lint.__main__ import main as lint_main  # noqa: E402
from tools.mxtpu_lint.graphcheck import (  # noqa: E402
    CONTRACTS_RELPATH, SiteRecord, collective_signature, graph_rule_names,
    load_contracts, missing_canonical, run_graph, write_contracts)
from tools.mxtpu_lint.graphcheck.rules import (  # noqa: E402
    CANONICAL_SITES, SPMD_SITES, iter_eqns)

MIB = 1 << 20


# ---------------------------------------------------------------------------
# duck-typed jaxpr stubs (rules only touch .eqns/.primitive.name/.aval)
# ---------------------------------------------------------------------------

class Aval:
    def __init__(self, dtype, shape=()):
        self.dtype = dtype
        self.shape = tuple(shape)


class Var:
    def __init__(self, dtype, shape=()):
        self.aval = Aval(dtype, shape)


class Prim:
    def __init__(self, name):
        self.name = name


class Eqn:
    def __init__(self, prim, invars=(), outvars=(), params=None):
        self.primitive = Prim(prim)
        self.invars = list(invars)
        self.outvars = list(outvars)
        self.params = dict(params or {})


class Jaxpr:
    def __init__(self, eqns, consts=()):
        self.eqns = list(eqns)
        self.consts = list(consts)


class Closed:
    """ClosedJaxpr shape: eqns live one level down at .jaxpr.eqns."""

    def __init__(self, jaxpr):
        self.jaxpr = jaxpr


def psum(shape=(195,), dtype="float32", axes=("dp",)):
    return Eqn("psum", invars=[Var(dtype, shape)],
               outvars=[Var(dtype, shape)], params={"axes": axes})


def graph(records, rules=None, contracts_path=None, **kw):
    kw.setdefault("const_bytes", MIB)
    findings, gctx = run_graph(ROOT, records, rules=rules,
                               contracts_path=contracts_path, **kw)
    return findings, gctx


# ---------------------------------------------------------------------------
# jaxpr walking + signatures
# ---------------------------------------------------------------------------

def test_iter_eqns_descends_into_params_subjaxprs():
    inner = Jaxpr([psum()])
    outer = Closed(Jaxpr([
        Eqn("dot_general"),
        Eqn("shard_map", params={"jaxpr": Closed(inner)}),
    ]))
    names = [e.primitive.name for e in iter_eqns(outer)]
    assert names == ["dot_general", "shard_map", "psum"]


def test_collective_signature_format_and_order():
    j = Jaxpr([
        Eqn("dot_general"),  # non-collective: excluded
        psum(shape=(), dtype="float32"),
        Eqn("all_gather", invars=[Var("bfloat16", (4, 8))],
            params={"axis_name": "dp"}),
    ])
    assert collective_signature(j) == [
        "psum[dp] float32[()]", "all_gather[dp] bfloat16[4x8]"]


def test_missing_canonical():
    assert missing_canonical([]) != []
    full = list(CANONICAL_SITES) + [
        "cachedop_fwd[n:1]", "cachedop_bwd[n:1]", "serving[s:8]", "op[x]",
        "decode_prefill[m:8]"]
    assert missing_canonical(full) == []
    assert "spmd_step" in missing_canonical(
        [s for s in full if s != "spmd_step"])
    assert "serving[...]" in missing_canonical(
        [s for s in full if not s.startswith("serving[")])


def test_graph_rule_catalog():
    assert graph_rule_names() == [
        "amp-dtype-leak", "baked-constant", "collective-order",
        "donation-dead", "host-callback-in-graph"]


# ---------------------------------------------------------------------------
# donation-dead
# ---------------------------------------------------------------------------

def test_donation_dead_fires_on_zero_alias():
    rec = SiteRecord("trainer_fused", jaxpr=Jaxpr([]), donated=True,
                     alias_bytes=0)
    findings, _ = graph([rec], rules=["donation-dead"])
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "donation-dead" and f.file == "graph:trainer_fused"
    assert "donation is dead" in f.message


def test_donation_dead_quiet_twins():
    quiet = [
        SiteRecord("a", jaxpr=Jaxpr([]), donated=True, alias_bytes=1560),
        SiteRecord("b", jaxpr=Jaxpr([]), donated=True, alias_bytes=None),
        SiteRecord("c", jaxpr=Jaxpr([]), donated=False, alias_bytes=0),
    ]
    findings, _ = graph(quiet, rules=["donation-dead"])
    assert findings == []


# ---------------------------------------------------------------------------
# amp-dtype-leak
# ---------------------------------------------------------------------------

def _amp_rec(eqns, amp="bfloat16", site="trainer_fused"):
    return SiteRecord(site, jaxpr=Jaxpr(eqns), amp_dtype=amp)


def test_amp_leak_fires_on_f32_matmul_under_policy():
    eqn = Eqn("dot_general",
              invars=[Var("float32", (4, 8)), Var("float32", (8, 2))],
              outvars=[Var("float32", (4, 2))])
    findings, _ = graph([_amp_rec([eqn])], rules=["amp-dtype-leak"])
    assert len(findings) == 1
    assert "escaped low precision" in findings[0].message


def test_amp_leak_fires_on_low_precision_transcendental():
    eqn = Eqn("exp", invars=[Var("bfloat16", (8,))],
              outvars=[Var("bfloat16", (8,))])
    findings, _ = graph([_amp_rec([eqn])], rules=["amp-dtype-leak"])
    assert len(findings) == 1
    assert "PR-5 underflow class" in findings[0].message


def test_amp_leak_quiet_twins():
    mixed_matmul = Eqn(
        "dot_general",
        invars=[Var("bfloat16", (4, 8)), Var("bfloat16", (8, 2))],
        outvars=[Var("float32", (4, 2))])  # f32 accum output is the contract
    f32_exp = Eqn("exp", invars=[Var("float32", (8,))],
                  outvars=[Var("float32", (8,))])
    findings, _ = graph([_amp_rec([mixed_matmul, f32_exp])],
                        rules=["amp-dtype-leak"])
    assert findings == []
    # no active cast policy: everything-f32 is the NORMAL state
    f32_matmul = Eqn("dot_general",
                     invars=[Var("float32", (4, 8)), Var("float32", (8, 2))],
                     outvars=[Var("float32", (4, 2))])
    findings, _ = graph([_amp_rec([f32_matmul], amp=None)],
                        rules=["amp-dtype-leak"])
    assert findings == []


# ---------------------------------------------------------------------------
# baked-constant (+ the graph_meta sanction path)
# ---------------------------------------------------------------------------

def _const(nbytes, shape=(512, 512), dtype="float32"):
    return {"index": 0, "shape": shape, "dtype": dtype, "nbytes": nbytes}


def test_baked_constant_threshold():
    big = SiteRecord("s", jaxpr=Jaxpr([]), consts=[_const(MIB + 1)])
    small = SiteRecord("t", jaxpr=Jaxpr([]), consts=[_const(MIB)])
    findings, _ = graph([big, small], rules=["baked-constant"])
    assert [f.file for f in findings] == ["graph:s"]
    assert "float32[512x512]" in findings[0].message
    # a tighter explicit threshold catches the small one too
    findings, _ = graph([small], rules=["baked-constant"], const_bytes=8)
    assert len(findings) == 1


def test_baked_constant_site_sanction():
    """graph_meta={'disable': ...} at the registration call site (the
    QuantizedNet mechanism) suppresses by SITE, rule-scoped."""
    rec = SiteRecord("serving[int8:8]", jaxpr=Jaxpr([]),
                     consts=[_const(4 * MIB)],
                     donated=True, alias_bytes=0,
                     meta={"disable": ("baked-constant",),
                           "reason": "calibrated int8 payloads"})
    findings, _ = graph([rec], rules=["baked-constant", "donation-dead"])
    # baked-constant sanctioned off; donation-dead still fires
    assert [f.rule for f in findings] == ["donation-dead"]


def test_const_threshold_env_override(monkeypatch):
    from tools.mxtpu_lint.graphcheck.runner import const_threshold
    monkeypatch.setenv("MXTPU_GRAPHCHECK_CONST_BYTES", "4096")
    assert const_threshold() == 4096


# ---------------------------------------------------------------------------
# host-callback-in-graph
# ---------------------------------------------------------------------------

def test_host_callback_fires_once_per_prim_and_sees_subjaxprs():
    inner = Jaxpr([Eqn("io_callback")])
    j = Jaxpr([
        Eqn("pure_callback"),
        Eqn("pure_callback"),  # deduped: one finding per prim name
        Eqn("scan", params={"jaxpr": Closed(inner)}),
    ])
    findings, _ = graph([SiteRecord("s", jaxpr=j)],
                        rules=["host-callback-in-graph"])
    assert sorted(f.message.split("`")[1] for f in findings) == [
        "io_callback", "pure_callback"]


def test_host_callback_quiet_twin():
    j = Jaxpr([Eqn("dot_general"), psum()])
    findings, _ = graph([SiteRecord("s", jaxpr=j)],
                        rules=["host-callback-in-graph"])
    assert findings == []


# ---------------------------------------------------------------------------
# collective-order
# ---------------------------------------------------------------------------

def _pin(tmp_path, sites):
    p = tmp_path / "contracts.json"
    p.write_text(json.dumps({"version": 1, "sites": sites}))
    return str(p)


def test_collective_order_registration_disagreement(tmp_path):
    a = SiteRecord("spmd_step", jaxpr=Jaxpr([psum()]))
    b = SiteRecord("spmd_step", jaxpr=Jaxpr([psum(shape=(7,))]))
    path = _pin(tmp_path, {"spmd_step": ["psum[dp] float32[195]"]})
    findings, _ = graph([a, b], rules=["collective-order"],
                        contracts_path=path)
    assert any("disagree" in f.message for f in findings)


def test_collective_order_unpinned_site(tmp_path):
    rec = SiteRecord("kv_bucket", jaxpr=Jaxpr([psum()]))
    findings, _ = graph([rec], rules=["collective-order"],
                        contracts_path=_pin(tmp_path, {}))
    assert len(findings) == 1
    assert "not pinned" in findings[0].message


def test_collective_order_mismatch_diff(tmp_path):
    rec = SiteRecord("spmd_step", jaxpr=Jaxpr(
        [psum(shape=()), psum(shape=(7,))]))
    path = _pin(tmp_path, {"spmd_step": ["psum[dp] float32[()]",
                                         "psum[dp] float32[195]"]})
    findings, _ = graph([rec], rules=["collective-order"],
                        contracts_path=path)
    assert len(findings) == 1
    msg = findings[0].message
    assert "position 1" in msg
    assert "psum[dp] float32[195]" in msg and "psum[dp] float32[7]" in msg


def test_collective_order_stale_pin(tmp_path):
    rec = SiteRecord("spmd_step", jaxpr=Jaxpr([psum()]))
    path = _pin(tmp_path, {"spmd_step": ["psum[dp] float32[195]"],
                           "ghost_site": ["psum[dp] float32[1]"]})
    findings, _ = graph([rec], rules=["collective-order"],
                        contracts_path=path)
    assert [f.file for f in findings] == ["graph:ghost_site"]
    assert "stale" in findings[0].message


def test_collective_order_clean_match(tmp_path):
    recs = [SiteRecord("spmd_step", jaxpr=Jaxpr([psum()])),
            SiteRecord("spmd_step", jaxpr=Jaxpr([psum()]))]
    path = _pin(tmp_path, {"spmd_step": ["psum[dp] float32[195]"]})
    findings, gctx = graph(recs, rules=["collective-order"],
                           contracts_path=path)
    assert findings == []
    assert gctx.signatures == {"spmd_step": ["psum[dp] float32[195]"]}


# ---------------------------------------------------------------------------
# shared-engine integration: baseline identity, --rule across legs
# ---------------------------------------------------------------------------

def test_graph_finding_baseline_identity_survives_reregistration(tmp_path):
    """A graph finding freezes by (graph:<site>, rule, message) — a later
    harness run re-registering the SAME site (fresh record objects, same
    defect) stays frozen."""
    mk = lambda: SiteRecord("trainer_fused", jaxpr=Jaxpr([]),  # noqa: E731
                            donated=True, alias_bytes=0)
    findings, _ = graph([mk()], rules=["donation-dead"])
    baseline = tmp_path / "b.json"
    entries = write_baseline(str(baseline), findings)
    findings2, _ = graph([mk()], rules=["donation-dead"])
    new, frozen, stale = apply_baseline(findings2, entries)
    assert new == [] and len(frozen) == 1 and stale == []


def test_rule_filter_spans_both_legs():
    """One --rule list mixing AST and graph names: the graph runner
    ignores AST names instead of erroring, and filters to the graph
    names given."""
    rec = SiteRecord("s", jaxpr=Jaxpr([Eqn("pure_callback")]),
                     donated=True, alias_bytes=0)
    findings, _ = graph([rec],
                        rules=["thread-guard", "host-callback-in-graph"])
    assert [f.rule for f in findings] == ["host-callback-in-graph"]


# ---------------------------------------------------------------------------
# pinned contracts file: present, complete, byte-stable
# ---------------------------------------------------------------------------

def test_shipped_contracts_pin_spmd_sites_and_are_stable(tmp_path):
    path = os.path.join(ROOT, CONTRACTS_RELPATH)
    data = load_contracts(path)
    assert data is not None and data.get("version") == 1
    sites = data["sites"]
    assert {"spmd_step", "spmd_superstep", "kv_bucket"} <= set(sites)
    assert set(sites) <= set(SPMD_SITES) | {
        s for s in sites if sites[s]}  # only SPMD or non-empty sigs pinned
    # regeneration from its own payload is byte-identical
    out = tmp_path / "regen.json"
    write_contracts(str(out), sites)
    with open(path, "rb") as f:
        assert out.read_bytes() == f.read()


# ---------------------------------------------------------------------------
# CLI guards (no jax needed)
# ---------------------------------------------------------------------------

def test_cli_update_contracts_requires_graph(capsys):
    assert lint_main(["--update-contracts"]) == 2


def test_cli_graph_rejects_path_args():
    assert lint_main(["--graph", "some_file.py", "--root", ROOT]) == 2


# ---------------------------------------------------------------------------
# the integration gate: real trace harness, real contracts, rc 0
# ---------------------------------------------------------------------------

def test_graph_cli_clean_and_canonical_sites_covered():
    """The shipped tree traces clean under --graph with an EMPTY
    baseline, and the harness registered every canonical site family —
    reverting a dogfood fix or reordering a collective flips rc to 1."""
    res = subprocess.run(
        [sys.executable, "-m", "tools.mxtpu_lint", "--graph", "--json",
         "--root", ROOT],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, (
        f"--graph found NEW findings:\n{res.stdout}\n{res.stderr}")
    out = json.loads(res.stdout)
    assert out["new"] == []
    assert out["rules"] == graph_rule_names()
    missing = missing_canonical(out["sites"])
    assert missing == [], (
        f"trace harness silently skipped site(s) {missing}; "
        f"registered: {out['sites']}")


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
