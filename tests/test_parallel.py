"""SPMD mesh parallelism tests — run on the 8-device virtual CPU mesh
(the reference tested distribution with multi-process localhost ps-lite;
here XLA collectives over forced host devices — SURVEY.md §4)."""

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, parallel
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_mesh_creation():
    mesh = parallel.make_mesh({"dp": 8})
    assert mesh.devices.size == 8
    mesh2 = parallel.make_mesh({"dp": -1, "tp": 2})
    assert mesh2.shape["dp"] == 4
    assert mesh2.shape["tp"] == 2


def test_shard_batch():
    mesh = parallel.make_mesh({"dp": 8})
    x = mx.nd.random.normal(shape=(16, 4))
    sharded = parallel.shard_batch(x, mesh)
    assert sharded.shape == (16, 4)
    assert len(sharded.sharding.device_set) == 8


def test_spmd_data_parallel_step():
    mesh = parallel.make_mesh({"dp": 8})
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = parallel.SPMDTrainStep(net, loss_fn, "sgd", {"momentum": 0.9}, mesh)
    x = mx.nd.random.normal(shape=(32, 8))
    y = mx.nd.array(np.random.randint(0, 4, (32,)).astype(np.float32))
    losses = [step(x, y, lr=0.1) for _ in range(10)]
    assert losses[-1] < losses[0], f"no improvement: {losses}"


def test_spmd_matches_single_device():
    """DP over 8 devices must equal single-device training numerically."""

    def build():
        net = nn.Dense(2, in_units=4, use_bias=False)
        net.initialize(init=mx.initializer.One())
        return net

    x = mx.nd.array(np.random.RandomState(3).randn(8, 4).astype(np.float32))
    y = mx.nd.array(np.array([0, 1] * 4, np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # single-device fused step
    net_a = build()
    step_a = parallel.SPMDTrainStep(net_a, loss_fn, "sgd", {}, mesh=None)
    for _ in range(3):
        step_a(x, y, lr=0.5)
    step_a.sync_to_block()
    w_single = net_a.weight.data().asnumpy()

    # 8-device mesh
    net_b = build()
    mesh = parallel.make_mesh({"dp": 8})
    step_b = parallel.SPMDTrainStep(net_b, loss_fn, "sgd", {}, mesh=mesh)
    for _ in range(3):
        step_b(x, y, lr=0.5)
    step_b.sync_to_block()
    w_mesh = net_b.weight.data().asnumpy()

    np.testing.assert_allclose(w_single, w_mesh, rtol=1e-5, atol=1e-6)


def test_spmd_tensor_parallel_sharding():
    """P9: tensor-parallel weight sharding via PartitionSpec annotations."""
    from jax.sharding import PartitionSpec as P

    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16), nn.Dense(8, in_units=32))
    net.initialize()
    names = sorted(net.collect_params().keys())
    dense0_w = [n for n in names if n.endswith("weight")][0]
    sharding = {dense0_w: P("tp", None)}
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, mesh,
                                  param_sharding=sharding)
    x = mx.nd.random.normal(shape=(8, 16))
    y = mx.nd.array(np.random.randint(0, 8, (8,)).astype(np.float32))
    l0 = step(x, y, lr=0.1)
    l1 = step(x, y, lr=0.1)
    assert np.isfinite(l0) and np.isfinite(l1)


def test_trainer_multi_device_contexts():
    """P1 path: Parameter replicated on several devices + kvstore aggregation."""
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    ctxs = [mx.Context("cpu", 0), mx.Context("cpu", 1)]
    net = nn.Dense(2, in_units=3, use_bias=False)
    net.initialize(init=mx.initializer.One(), ctx=ctxs)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="device")
    loss_fn = gluon.loss.L2Loss()
    from mxnet_tpu.gluon.utils import split_and_load

    x = mx.nd.random.normal(shape=(4, 3))
    y = mx.nd.random.normal(shape=(4, 2))
    xs = split_and_load(x, ctxs)
    ys = split_and_load(y, ctxs)
    with autograd.record():
        losses = [loss_fn(net(xi), yi) for xi, yi in zip(xs, ys)]
    for l in losses:
        l.backward()
    trainer.step(4)
    w0 = net.weight.data(ctxs[0]).asnumpy()
    w1 = net.weight.data(ctxs[1]).asnumpy()
    np.testing.assert_allclose(w0, w1)


def test_spmd_zero1_shards_opt_states():
    """P13 ZeRO-1: shard_opt_states=True shards adam moments along dp."""
    from jax.sharding import PartitionSpec as P

    mesh = parallel.make_mesh({"dp": 8})
    net = nn.Dense(4, in_units=16, use_bias=False)
    net.initialize()
    loss_fn = gluon.loss.L2Loss()
    step = parallel.SPMDTrainStep(net, loss_fn, "adam", {}, mesh,
                                  shard_opt_states=True)
    x = mx.nd.random.normal(shape=(8, 16))
    y = mx.nd.random.normal(shape=(8, 4))
    step(x, y, lr=1e-3)
    _, opt_states = step._state
    # Dense weight is (4, 16): dim0=4 not divisible by dp=8 -> moments
    # stay replicated (the fallback branch)
    assert opt_states[0][0].sharding.is_fully_replicated
    # weight (16, 4) IS divisible by dp=8 -> sharded branch
    net3 = nn.Dense(16, in_units=4, use_bias=False)
    net3.initialize()
    step3 = parallel.SPMDTrainStep(net3, loss_fn, "adam", {}, mesh,
                                   shard_opt_states=True)
    x3 = mx.nd.random.normal(shape=(8, 4))
    y3 = mx.nd.random.normal(shape=(8, 16))
    l0 = step3(x3, y3, lr=1e-3)
    _, states3 = step3._state
    (m, v, t) = states3[0]
    # moments (16, 4) sharded 8-ways on dim 0; each device holds 2 rows
    assert len(m.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in m.addressable_shards}
    assert shard_shapes == {(2, 4)}, shard_shapes
    assert t.sharding.is_fully_replicated
    assert np.isfinite(l0)


def test_spmd_nag_matches_optimizer():
    """SPMD 'nag' rule must match optimizer.NAG, not plain momentum."""
    w0 = np.random.RandomState(0).randn(4, 3).astype(np.float32)

    def run_spmd():
        net = nn.Dense(4, in_units=3, use_bias=False)
        net.initialize()
        net.weight.set_data(mx.nd.array(w0))
        loss_fn = gluon.loss.L2Loss()
        step = parallel.SPMDTrainStep(net, loss_fn, "nag",
                                      {"momentum": 0.9}, mesh=None)
        x = mx.nd.array(np.ones((2, 3), np.float32))
        y = mx.nd.array(np.zeros((2, 4), np.float32))
        for _ in range(3):
            step(x, y, lr=0.1)
        step.sync_to_block()
        return net.weight.data().asnumpy()

    def run_ref():
        net = nn.Dense(4, in_units=3, use_bias=False)
        net.initialize()
        net.weight.set_data(mx.nd.array(w0))
        trainer = gluon.Trainer(net.collect_params(), "nag",
                                {"learning_rate": 0.1, "momentum": 0.9})
        loss_fn = gluon.loss.L2Loss()
        x = mx.nd.array(np.ones((2, 3), np.float32))
        y = mx.nd.array(np.zeros((2, 4), np.float32))
        for _ in range(3):
            with autograd.record():
                l = loss_fn(net(x), y)
            l.backward()
            trainer.step(2)  # mean loss over batch=2: grads normalized
        return net.weight.data().asnumpy()

    np.testing.assert_allclose(run_spmd(), run_ref(), rtol=1e-5, atol=1e-6)


def test_sync_exec_flag(monkeypatch):
    """MXTPU_SYNC_EXEC=1 -> every dispatch blocks (NaiveEngine analog)."""
    import mxnet_tpu.engine as engine
    import mxnet_tpu.ops.dispatch as dispatch

    calls = []
    real = engine.wait

    def spy(x):
        calls.append(1)
        return real(x)

    monkeypatch.setenv("MXTPU_SYNC_EXEC", "1")
    monkeypatch.setattr(dispatch.engine, "wait", spy)
    a = mx.nd.ones((2, 2))
    b = a + a
    assert_almost_equal(b, np.full((2, 2), 2.0, np.float32))
    assert calls, "sync-exec did not block on dispatch"
    calls.clear()
    monkeypatch.setenv("MXTPU_SYNC_EXEC", "0")
    _ = a + a
    assert not calls


def test_run_steps_matches_python_loop():
    """Bulked execution (run_steps) must produce the same parameters as
    n individual step() calls with the same data and a fixed key stream
    is NOT required — compare against an independent step with the same
    rng-free model (no dropout)."""
    from mxnet_tpu import parallel

    X = np.random.RandomState(0).randn(16, 6).astype(np.float32)
    Y = np.random.RandomState(1).randn(16, 1).astype(np.float32)
    w0 = np.random.RandomState(2).randn(1, 6).astype(np.float32)

    def make():
        net = mx.gluon.nn.Dense(1, in_units=6)
        net.initialize()
        net.weight.set_data(mx.nd.array(w0))  # same start for both paths
        net.bias.set_data(mx.nd.zeros((1,)))
        return parallel.SPMDTrainStep(net, mx.gluon.loss.L2Loss(), "sgd",
                                      {"momentum": 0.9}, mesh=None)

    a = make()
    for _ in range(6):
        la = a(mx.nd.array(X), mx.nd.array(Y), lr=0.1, sync=False)
    b = make()
    lb = b.run_steps(mx.nd.array(X), mx.nd.array(Y), 6, lr=0.1)
    np.testing.assert_allclose(np.asarray(a._state[0][0]),
                               np.asarray(b._state[0][0]), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(float(jax.device_get(la)),
                               float(jax.device_get(lb)), rtol=1e-4)
    # further run_steps calls (any n) reuse the one compiled loop
    _ = b.run_steps(mx.nd.array(X), mx.nd.array(Y), 6, lr=0.1)
    _ = b.run_steps(mx.nd.array(X), mx.nd.array(Y), 3, lr=0.1)
    assert b._run_many is not None


def test_sync_batchnorm_global_stats_across_shards():
    """SyncBatchNorm semantics under SPMD: stats are computed over the
    GLOBAL batch even when the batch is sharded over dp (reference:
    contrib SyncBatchNorm; here GSPMD inserts the cross-device reduction)."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.contrib import nn as contrib_nn

    mesh = parallel.make_mesh({"dp": 8})
    net = contrib_nn.SyncBatchNorm(in_channels=3)
    net.initialize()
    rng = np.random.RandomState(0)
    # shards with wildly different means: per-shard BN would differ from
    # global BN by construction
    host = np.concatenate(
        [rng.rand(2, 3, 4, 4).astype(np.float32) + 10 * k for k in range(8)])
    x = mx.nd.NDArray(parallel.shard_batch(host, mesh))
    for _, prm in net.collect_params().items():
        prm.set_data(mx.nd.NDArray(parallel.replicate(prm.data(), mesh)))
    with autograd.record():
        y = net(x)
    got = y.asnumpy()
    mean = host.mean(axis=(0, 2, 3), keepdims=True)
    var = host.var(axis=(0, 2, 3), keepdims=True)
    want = (host - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
    # moving stats saw the global mean too
    np.testing.assert_allclose(
        net.running_mean.data().asnumpy(),
        0.1 * mean.ravel(), rtol=1e-3)


def test_spmd_sharded_checkpoint_roundtrip(tmp_path):
    """spmd_save_states/load_states: per-process shard files, restored
    into the current sharding, bit-exact training resume (reference
    analog: Trainer.save_states, redesigned so no host materializes a
    full tensor on a pod)."""
    from jax.sharding import PartitionSpec as P

    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    net = gluon.nn.Dense(8, in_units=4)
    net.initialize()
    wname = [n for n in net.collect_params() if n.endswith("weight")][0]
    kw = dict(mesh=mesh, param_sharding={wname: P("tp", None)},
              shard_opt_states=True)
    step = parallel.SPMDTrainStep(net, gluon.loss.L2Loss(), "adam", {}, **kw)
    x = mx.nd.ones((8, 4))
    y = mx.nd.ones((8, 8))
    step(x, y, lr=0.05)
    prefix = str(tmp_path / "ck")
    fname = step.save_states(prefix)
    assert fname.endswith(".shard0.npz")
    iw = step._names.index(wname)
    w_saved = np.asarray(step._state[0][iw]).copy()
    for _ in range(3):
        step(x, y, lr=0.05)
    assert not np.allclose(np.asarray(step._state[0][iw]), w_saved)
    step.load_states(prefix)
    np.testing.assert_allclose(np.asarray(step._state[0][iw]), w_saved,
                               rtol=1e-6)
    # handles see the restored values too (copied, not aliased)
    np.testing.assert_allclose(net.weight.data().asnumpy(), w_saved,
                               rtol=1e-6)
    l1 = step(x, y, lr=0.05)
    # a FRESH step (new compile, same shardings) resumes bit-exact
    step2 = parallel.SPMDTrainStep(net, gluon.loss.L2Loss(), "adam", {},
                                   **kw)
    step2.init_state()
    step2.load_states(prefix)
    l2 = step2(x, y, lr=0.05)
    assert abs(l1 - l2) < 1e-6
    # missing-prefix errors are loud
    with pytest.raises(mx.base.MXNetError):
        step2.load_states(str(tmp_path / "nope"))
