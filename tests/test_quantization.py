"""int8 quantization: op semantics + end-to-end post-training quantization.

Reference parity target: ``src/operator/quantization/`` +
``contrib/quantization.py`` (``quantize_net`` with naive min/max
calibration, int8 symmetric).
"""

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray import op as ndop


def test_quantize_dequantize_roundtrip():
    x = np.linspace(-3.0, 3.0, 101).astype(np.float32)
    q, mn, mx_ = ndop.quantize(mx.nd.array(x), mx.nd.array(
        np.float32(-3.0)), mx.nd.array(np.float32(3.0)))
    assert q.dtype == np.int8
    back = ndop.dequantize(q, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x, atol=3.0 / 127 + 1e-6)


def test_quantize_v2_auto_range():
    x = np.array([-1.0, 0.5, 2.0], np.float32)
    q, mn, mx_ = ndop.quantize_v2(mx.nd.array(x))
    np.testing.assert_allclose(float(mx_.asnumpy()), 2.0, rtol=1e-6)
    back = ndop.dequantize(q, mn, mx_).asnumpy()
    np.testing.assert_allclose(back, x, atol=2.0 / 127 + 1e-6)


def test_quantized_fully_connected_matches_float():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype(np.float32)
    w = rng.randn(6, 8).astype(np.float32) * 0.5
    qx, mnx, mxx = ndop.quantize_v2(mx.nd.array(x))
    qw, mnw, mxw = ndop.quantize_v2(mx.nd.array(w))
    acc, mn, mx_ = ndop.quantized_fully_connected(
        qx, qw, None, mnx, mxx, mnw, mxw, no_bias=True, num_hidden=6)
    assert acc.dtype == np.int32
    sx = 127.0 / np.abs(x).max()
    sw = 127.0 / np.abs(w).max()
    got = acc.asnumpy().astype(np.float32) / (sx * sw)
    want = x @ w.T
    # int8 per-tensor: ~1% relative error expected
    assert np.abs(got - want).max() / np.abs(want).max() < 0.03


def test_quantized_conv_matches_float():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.3
    qx, mnx, mxx = ndop.quantize_v2(mx.nd.array(x))
    qw, mnw, mxw = ndop.quantize_v2(mx.nd.array(w))
    acc, _, _ = ndop.quantized_conv(qx, qw, None, mnx, mxx, mnw, mxw,
                                    kernel=(3, 3), pad=(1, 1), num_filter=4,
                                    no_bias=True)
    sx = 127.0 / np.abs(x).max()
    sw = 127.0 / np.abs(w).max()
    got = acc.asnumpy().astype(np.float32) / (sx * sw)
    want = ndop.Convolution(mx.nd.array(x), mx.nd.array(w), None,
                            no_bias=True, kernel=(3, 3), pad=(1, 1),
                            num_filter=4).asnumpy()
    assert np.abs(got - want).max() / np.abs(want).max() < 0.05


def test_requantize_to_int8():
    acc = np.array([1 << 20, -(1 << 21), 1 << 19], np.int32)
    q8, mn, mx_ = ndop.requantize(mx.nd.array(acc, dtype="int32"),
                                  mx.nd.array(np.float32(-4.0)),
                                  mx.nd.array(np.float32(4.0)))
    assert q8.dtype == np.int8
    # ratios preserved: -2x and 0.5x of the first element
    v = q8.asnumpy().astype(np.float32)
    np.testing.assert_allclose(v[1] / v[0], -2.0, rtol=0.05)
    np.testing.assert_allclose(v[2] / v[0], 0.5, rtol=0.05)


def _make_cnn():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1),       # conv -> BN -> relu: the
            nn.BatchNorm(),                   # foldable ordering
            nn.Activation("relu"),
            nn.Conv2D(16, 3, padding=1, strides=2, activation="relu"),
            nn.Flatten(),
            nn.Dense(10))
    return net


def test_quantize_net_end_to_end():
    """fp32-trained CNN -> int8: argmax agreement must be high."""
    from mxnet_tpu.contrib.quantization import quantize_net

    np.random.seed(0)
    mx.random.seed(0)
    net = _make_cnn()
    net.initialize(init=mx.initializer.Xavier())
    X = np.random.rand(64, 3, 8, 8).astype(np.float32)
    y = (X.mean(axis=(1, 2, 3)) * 10).astype(np.int64) % 10

    # brief training so BN stats + weights are meaningful
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(10):
        with autograd.record():
            out = net(mx.nd.array(X))
            l = loss_fn(out, mx.nd.array(y.astype(np.float32)))
        l.backward()
        trainer.step(64)

    fp32_out = net(mx.nd.array(X)).asnumpy()
    qnet = quantize_net(net, calib_data=[mx.nd.array(X[:32])])
    int8_out = qnet(mx.nd.array(X)).asnumpy()
    assert int8_out.shape == fp32_out.shape
    agree = (int8_out.argmax(1) == fp32_out.argmax(1)).mean()
    assert agree >= 0.9, agree
    # outputs correlate strongly
    c = np.corrcoef(int8_out.ravel(), fp32_out.ravel())[0, 1]
    assert c > 0.99, c


def test_quantize_net_rejects_fold_across_fused_act():
    """bn(relu(conv(x))) cannot fold: must refuse, not silently change."""
    from mxnet_tpu.contrib.quantization import quantize_net

    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, activation="relu"), nn.BatchNorm(),
            nn.Flatten(), nn.Dense(2))
    net.initialize()
    _ = net(mx.nd.ones((1, 3, 4, 4)))
    with pytest.raises(MXNetError):
        quantize_net(net, calib_data=[mx.nd.ones((1, 3, 4, 4))])


def test_quantize_net_exclude_layers():
    """Excluded layers stay fp32: output must match fp32 more closely on
    the excluded stage (exactly, for a single-layer net)."""
    from mxnet_tpu.contrib.quantization import quantize_net

    net = nn.HybridSequential()
    net.add(nn.Dense(6))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).rand(4, 5).astype(np.float32))
    _ = net(x)
    name = net._children and list(net._children.values())[0].name
    q_all = quantize_net(net, calib_data=[x])
    q_none = quantize_net(net, calib_data=[x], exclude_layers=(name,))
    fp32 = net(x).asnumpy()
    np.testing.assert_allclose(q_none(x).asnumpy(), fp32, rtol=1e-5,
                               atol=1e-6)  # excluded -> bit-faithful fp32
    assert np.abs(q_all(x).asnumpy() - fp32).max() > 0  # int8 really ran


def test_quantize_net_rejects_unsupported():
    from mxnet_tpu.contrib.quantization import quantize_net

    net = nn.HybridSequential()
    net.add(nn.Dense(4, activation="tanh"))
    net.initialize()
    _ = net(mx.nd.ones((1, 4)))
    with pytest.raises(MXNetError):
        quantize_net(net, calib_data=[mx.nd.ones((1, 4))])


def test_quantize_net_entropy_calibration():
    """calib_mode='entropy' (reference calibrate.cc): accuracy comparable
    to naive min/max on a conv net with outlier activations."""
    rng = np.random.RandomState(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, in_channels=3),
            nn.Activation("relu"), nn.Flatten(), nn.Dense(4))
    net.initialize()
    x = rng.rand(64, 3, 8, 8).astype(np.float32)
    x[0, 0, 0, 0] = 40.0  # outlier that wrecks a pure min/max range
    ref = net(mx.nd.array(x)).asnumpy()
    from mxnet_tpu.contrib import quantization

    qnet = quantization.quantize_net(net, calib_data=[mx.nd.array(x)],
                                     calib_mode="entropy")
    got = qnet(mx.nd.array(x)).asnumpy()
    # entropy calibration trades the outlier sample for resolution on the
    # bulk: non-outlier rows must be accurate, and tighter than naive
    err = np.abs(got[1:] - ref[1:]).max() / (np.abs(ref[1:]).max() + 1e-6)
    assert err < 0.2, err
    qnaive = quantization.quantize_net(net, calib_data=[mx.nd.array(x)],
                                       calib_mode="naive")
    gn = qnaive(mx.nd.array(x)).asnumpy()
    err_naive = np.abs(gn[1:] - ref[1:]).max() / (np.abs(ref[1:]).max() + 1e-6)
    assert err <= err_naive + 1e-6, (err, err_naive)
    with pytest.raises(mx.base.MXNetError):
        quantization.quantize_net(net, calib_data=[mx.nd.array(x)],
                                  calib_mode="bogus")


def test_intgemm_family():
    """intgemm int8 GEMM surface (reference: contrib/intgemm/*.cc): the
    prepared format on TPU is plain int8 (MXU-native), math matches fp32
    within int8 tolerance."""
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(4, 8).astype(np.float32))
    w = mx.nd.array(rng.randn(5, 8).astype(np.float32))
    sx = mx.nd.contrib.intgemm_maxabsolute(x)
    sw = mx.nd.contrib.intgemm_maxabsolute(w)
    np.testing.assert_allclose(sx.asnumpy()[0],
                               np.abs(x.asnumpy()).max(), rtol=1e-6)
    qx = mx.nd.contrib.intgemm_prepare_data(x, sx)
    qw = mx.nd.contrib.intgemm_prepare_weight(w, sw)
    assert qx.dtype == np.int8 and qw.dtype == np.int8
    scaling = float(sx.asnumpy()[0]) * float(sw.asnumpy()[0]) / (127.0 ** 2)
    out = mx.nd.contrib.intgemm_fully_connected(qx, qw, mx.nd.array(scaling),
                                                num_hidden=5)
    ref = x.asnumpy() @ w.asnumpy().T
    err = np.abs(out.asnumpy() - ref).max() / np.abs(ref).max()
    assert err < 0.03, err
    # int32 accumulator output + row selection
    acc = mx.nd.contrib.intgemm_fully_connected(qx, qw, out_type="int32")
    assert acc.dtype == np.int32
    sel = mx.nd.contrib.intgemm_take_weight(qw, mx.nd.array([0, 2]))
    np.testing.assert_array_equal(sel.asnumpy(), qw.asnumpy()[[0, 2]])
    # already-quantized weights pass through
    qw2 = mx.nd.contrib.intgemm_prepare_weight(qw, already_quantized=True)
    np.testing.assert_array_equal(qw2.asnumpy(), qw.asnumpy())


def test_quantized_act_sigmoid_tanh_softrelu():
    """Non-relu int8 activations (VERDICT r3 item 9; reference
    quantized_activation.cc ships them via float round-trip)."""
    rng = np.random.RandomState(1)
    f = rng.uniform(-3, 3, (4, 8)).astype(np.float32)
    q, qlo, qhi = mx.nd.contrib.quantize_v2(mx.nd.array(f))
    for act, ref_fn in [
            ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
            ("tanh", np.tanh),
            ("softrelu", lambda v: np.log1p(np.exp(v)))]:
        qa, amn, amx = mx.nd.contrib.quantized_act(q, qlo, qhi, act_type=act)
        assert qa.dtype == np.int8
        deq = mx.nd.contrib.dequantize(qa, amn, amx).asnumpy()
        ref = ref_fn(f)
        assert np.abs(deq - ref).max() < 0.06, act
    with pytest.raises(NotImplementedError):
        mx.nd.contrib.quantized_act(q, qlo, qhi, act_type="bogus")


def test_quantized_concat_range_unification():
    """quantized_concat rescales differing input ranges into one
    (reference quantized_concat.cc)."""
    rng = np.random.RandomState(2)
    a = rng.uniform(-1, 1, (2, 3)).astype(np.float32)
    b = rng.uniform(-6, 6, (2, 4)).astype(np.float32)
    qa, amn, amx = mx.nd.contrib.quantize_v2(mx.nd.array(a))
    qb, bmn, bmx = mx.nd.contrib.quantize_v2(mx.nd.array(b))
    out, omn, omx = mx.nd.contrib.quantized_concat(qa, qb, amn, bmn,
                                                   amx, bmx, dim=1)
    assert out.dtype == np.int8 and out.shape == (2, 7)
    deq = mx.nd.contrib.dequantize(out, omn, omx).asnumpy()
    ref = np.concatenate([a, b], axis=1)
    # resolution is set by the widest range (|b| ~ 6): ~6/127 per step
    assert np.abs(deq - ref).max() < 0.1


# end_to_end + entropy_calibration keep the quantize_net surface in
# tier-1; these variant cells ride -m slow
@pytest.mark.slow
def test_quantize_net_pooling_runs_int8(monkeypatch):
    """ResNet-style conv/relu/pool stacks keep activations in int8
    through the pooling stages (VERDICT r3 item 9 done-criterion)."""
    from mxnet_tpu.contrib import quantization
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
            nn.Activation("relu"), nn.MaxPool2D(pool_size=2, strides=2),
            nn.Conv2D(16, 3, padding=1), nn.Activation("relu"),
            nn.GlobalAvgPool2D(), nn.Flatten(), nn.Dense(10))
    net.initialize(init=mx.initializer.Xavier())
    rng = np.random.RandomState(3)
    x = rng.rand(8, 3, 16, 16).astype(np.float32)
    net(mx.nd.array(x))  # materialize params
    ref = net(mx.nd.array(x)).asnumpy()

    calls = {"pool": 0, "act": 0}
    real_pool = quantization.qops.quantized_pooling
    real_act = quantization.qops.quantized_act

    def count_pool(*a, **k):
        calls["pool"] += 1
        return real_pool(*a, **k)

    def count_act(*a, **k):
        calls["act"] += 1
        return real_act(*a, **k)

    monkeypatch.setattr(quantization.qops, "quantized_pooling", count_pool)
    monkeypatch.setattr(quantization.qops, "quantized_act", count_act)
    qnet = quantization.quantize_net(net, calib_data=[mx.nd.array(x)])
    out = qnet(mx.nd.array(x)).asnumpy()
    assert calls["pool"] == 2, calls  # both pools ran the int8 op
    assert calls["act"] == 2, calls   # both relus ran the int8 op
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.25, err


@pytest.mark.slow
def test_quantize_net_ceil_mode_and_exclude_pad():
    """int8 pooling honors pooling_convention='full' (ceil_mode) and
    count_include_pad=False like the float path (review regression)."""
    from mxnet_tpu.contrib import quantization
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1), nn.Activation("relu"),
            nn.MaxPool2D(pool_size=2, strides=2, ceil_mode=True),
            nn.AvgPool2D(pool_size=2, strides=2, padding=1,
                         count_include_pad=False))
    net.initialize(init=mx.initializer.Xavier())
    x = np.random.RandomState(5).rand(2, 3, 7, 7).astype(np.float32)
    ref = net(mx.nd.array(x)).asnumpy()
    qnet = quantization.quantize_net(net, calib_data=[mx.nd.array(x)])
    out = qnet(mx.nd.array(x)).asnumpy()
    assert out.shape == ref.shape, (out.shape, ref.shape)
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.2, err


def test_quantized_elemwise_add_op():
    """reference: src/operator/quantization/quantized_elemwise_add.cc —
    int8 add with range unification; calibrated output range tightens."""
    from mxnet_tpu.ops import quantization as qops

    rng = np.random.RandomState(0)
    a = rng.uniform(-2, 2, (4, 8)).astype(np.float32)
    b = rng.uniform(-0.5, 0.5, (4, 8)).astype(np.float32)
    qa, mna, mxa = qops.quantize(jnp.asarray(a), -2.0, 2.0)
    qb, mnb, mxb = qops.quantize(jnp.asarray(b), -0.5, 0.5)
    out, lo, hi = qops.quantized_elemwise_add(qa, qb, mna, mxa, mnb, mxb)
    assert out.dtype == jnp.int8
    deq = np.asarray(out, np.float32) * (float(hi) / 127.0)
    np.testing.assert_allclose(deq, a + b, atol=2.6 * float(hi) / 127.0)
    # calibrated range: tighter than |a|+|b| conservative bound
    s = a + b
    out2, lo2, hi2 = qops.quantized_elemwise_add(
        qa, qb, mna, mxa, mnb, mxb,
        min_calib_range=float(s.min()), max_calib_range=float(s.max()))
    assert float(hi2) < float(hi)
    deq2 = np.asarray(out2, np.float32) * (float(hi2) / 127.0)
    assert np.abs(deq2 - s).max() < np.abs(deq - s).max() + 1e-6


def test_quantized_batch_norm_op():
    """reference: src/operator/quantization/quantized_batch_norm.cc —
    running-stat affine on int8, recalibrated symmetric output range."""
    from mxnet_tpu.ops import quantization as qops

    rng = np.random.RandomState(1)
    x = rng.uniform(-3, 3, (2, 4, 5, 5)).astype(np.float32)
    g = (rng.rand(4) + 0.5).astype(np.float32)
    beta = rng.randn(4).astype(np.float32)
    mean = rng.randn(4).astype(np.float32)
    var = (rng.rand(4) + 0.5).astype(np.float32)
    q, mn, mx = qops.quantize(jnp.asarray(x), -3.0, 3.0)
    out, lo, hi = qops.quantized_batch_norm(
        q, jnp.asarray(g), jnp.asarray(beta), jnp.asarray(mean),
        jnp.asarray(var), mn, mx, eps=1e-5)
    assert out.dtype == jnp.int8
    ref = (x - mean.reshape(1, -1, 1, 1)) \
        / np.sqrt(var.reshape(1, -1, 1, 1) + 1e-5) \
        * g.reshape(1, -1, 1, 1) + beta.reshape(1, -1, 1, 1)
    deq = np.asarray(out, np.float32) * (float(hi) / 127.0)
    step = float(hi) / 127.0
    in_step = 3.0 / 127.0
    amp = float((g / np.sqrt(var + 1e-5)).max())
    assert np.abs(deq - ref).max() < amp * in_step + step


def _mini_resnet(classes=4):
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.model_zoo.vision.resnet import BasicBlockV1

    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, use_bias=False))
    net.add(nn.BatchNorm())
    net.add(nn.Activation("relu"))
    net.add(BasicBlockV1(8, 1, downsample=False, in_channels=8))
    net.add(BasicBlockV1(16, 2, downsample=True, in_channels=8))
    net.add(nn.GlobalAvgPool2D())
    net.add(nn.Flatten())
    net.add(nn.Dense(classes))
    return net


@pytest.mark.slow
def test_quantize_net_resnet_residuals_stay_int8():
    """VERDICT r4 #4: quantize_net on a ResNet topology keeps the
    skip-adds int8 end-to-end (quantized_elemwise_add), and int8
    accuracy stays within 1% of the float net on a trained model."""
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.contrib import quantization

    rng = np.random.RandomState(0)
    net = _mini_resnet()
    net.initialize(init=mx.initializer.Xavier())
    # synthetic separable task: class = quadrant of the image mean signs
    X = rng.randn(256, 3, 16, 16).astype(np.float32)
    labels = ((X[:, 0].mean((1, 2)) > 0) * 2
              + (X[:, 1].mean((1, 2)) > 0)).astype(np.float32)
    xb, yb = mx.nd.array(X), mx.nd.array(labels)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(60):
        with autograd.record():
            loss = loss_fn(net(xb), yb).mean()
        loss.backward()
        trainer.step(1)
    logits_f = net(xb).asnumpy()
    acc_f = (logits_f.argmax(1) == labels).mean()
    assert acc_f > 0.8, acc_f  # the float model actually learned

    # count int8 adds via monkeypatch-free wrapper
    from mxnet_tpu.ops import quantization as qops
    calls = {"add": 0}
    orig = qops.quantized_elemwise_add

    def counting_add(*a, **k):
        calls["add"] += 1
        return orig(*a, **k)

    quantization.qops.quantized_elemwise_add = counting_add
    try:
        qnet = quantization.quantize_net(
            net, calib_data=[mx.nd.array(X[i:i + 64])
                             for i in range(0, 256, 64)])
        logits_q = qnet(xb).asnumpy()
    finally:
        quantization.qops.quantized_elemwise_add = orig
    assert calls["add"] == 2, calls  # both residual adds ran int8
    acc_q = (logits_q.argmax(1) == labels).mean()
    assert acc_q >= acc_f - 0.01, (acc_f, acc_q)  # 1% budget


@pytest.mark.slow
def test_quantize_net_standalone_bn():
    """A BN with no conv to fold into runs as quantized_batch_norm on
    live int8 activations."""
    from mxnet_tpu.contrib import quantization
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1), nn.Activation("relu"),
            nn.MaxPool2D(pool_size=2, strides=2),
            nn.BatchNorm(),  # after pool: cannot fold
            nn.Flatten(), nn.Dense(3))
    net.initialize(init=mx.initializer.Xavier())
    x = np.random.RandomState(2).rand(2, 3, 8, 8).astype(np.float32)
    ref = net(mx.nd.array(x)).asnumpy()
    qnet = quantization.quantize_net(net, calib_data=[mx.nd.array(x)])
    out = qnet(mx.nd.array(x)).asnumpy()
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.25, err
