"""Autoregressive decode fast path (``mxnet_tpu.serving.generation``):
the paged-cache decode must match a dense full-context recompute
token-for-token, spend at most ~1 dispatch per chunk of tokens, never
retrace after warmup, and keep the continuous-batching contracts
(late join without drain, typed refusals, lifecycle).

One module-scoped engine carries most tests — the sealed executables
compile once; every test asserts on stat DELTAS so ordering never
matters."""

import time

import numpy as np
import pytest

from mxnet_tpu import observability as obs
from mxnet_tpu.serving import (
    EngineClosed,
    GenerationEngine,
    LocalReplica,
    ModelRepository,
    ReplicaDead,
    RequestCancelled,
    RequestTimeout,
    RetraceForbidden,
    ServingError,
    TransformerDecoderLM,
    sample_tokens,
)

VOCAB, MAX_SEQ, BUCKETS, SLOTS, CHUNK = 48, 64, [4, 8, 16], 4, 4


@pytest.fixture(autouse=True)
def _telemetry_state():
    obs.set_enabled(False)
    obs.reset()
    yield
    obs.set_enabled(False)
    obs.reset()


@pytest.fixture(scope="module")
def net():
    return TransformerDecoderLM(vocab_size=VOCAB, num_layers=2,
                                d_model=32, num_heads=4, kv_heads=2,
                                max_seq=MAX_SEQ, seed=0)


@pytest.fixture(scope="module")
def eng(net):
    e = GenerationEngine(net, BUCKETS, slots=SLOTS, chunk=CHUNK,
                         queue_cap=64, cache_blocks=96,
                         cache_block_size=4, name="gen-test")
    yield e
    e.close()


def _assert_matches_dense(net, prompt, toks):
    """Dense full-context recompute check: ONE causal forward over
    prompt+generated must greedy-predict every generated token from its
    own prefix (equivalent to re-running the dense net per step — the
    first mismatch fails exactly where a stepwise oracle would)."""
    fwd, params = net.forward_fn(), net.params()
    seq = np.array([int(t) for t in prompt] + [int(t) for t in toks],
                   np.int32)
    logits = np.asarray(fwd(params, seq[None]))
    want = logits[0, len(prompt) - 1:len(seq) - 1].argmax(-1)
    assert [int(t) for t in toks] == [int(t) for t in want]


def _drain(eng, timeout=10.0):
    deadline = time.perf_counter() + timeout
    while (eng.active_slots() or eng.queue_depth()) \
            and time.perf_counter() < deadline:
        time.sleep(0.002)


# ---------------------------------------------------------------------------
# correctness vs dense recompute
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prompt,n", [
    ([3, 1, 4], 10),            # bucket 4, crosses 2 chunk boundaries
    ([7, 2, 9, 11, 5, 40], 9),  # bucket 8, partial final chunk
    (list(range(2, 15)), 17),   # bucket 16, multi-block prompt
])
def test_greedy_decode_matches_dense_recompute(eng, net, prompt, n):
    toks = eng.predict(np.array(prompt, np.int32),
                       max_new_tokens=n, greedy=True, timeout=60.0)
    assert toks.dtype == np.int32
    assert len(toks) == n
    _assert_matches_dense(net, prompt, toks)


def test_batch_axis_squeeze_and_validation(eng):
    a = eng.predict(np.array([[5, 6, 7]], np.int32),
                    max_new_tokens=3, timeout=60.0)
    b = eng.predict(np.array([5, 6, 7], np.int32),
                    max_new_tokens=3, timeout=60.0)
    assert list(a) == list(b)
    with pytest.raises(ServingError):
        eng.submit(np.zeros((2, 3), np.int32))  # real batches: one each
    with pytest.raises(ServingError):
        eng.submit(np.array([], np.int32))


def test_eos_stops_early_and_is_included(eng, net):
    prompt = [3, 1, 4]
    ref = eng.predict(np.array(prompt, np.int32), max_new_tokens=12,
                      greedy=True, timeout=60.0)
    _assert_matches_dense(net, prompt, ref)  # trusted greedy reference
    eos = int(ref[5])
    want = [int(t) for t in ref[:list(ref).index(eos) + 1]]
    toks = eng.predict(np.array(prompt, np.int32), max_new_tokens=12,
                       eos=eos, timeout=60.0)
    assert [int(t) for t in toks] == want
    assert toks[-1] == eos


def test_max_new_clipped_to_max_seq(eng):
    prompt = np.arange(1, 14, dtype=np.int32)  # plen 13, bucket 16
    toks = eng.predict(prompt, max_new_tokens=10_000, timeout=120.0)
    assert len(toks) == MAX_SEQ - 13


def test_sampling_first_token_is_seed_deterministic(eng):
    """The documented reproducibility contract: the prefill token is
    drawn from the request's own seed (bit-stable run to run); later
    tokens ride the engine-level per-chunk key stream."""
    kw = dict(max_new_tokens=8, greedy=False, temperature=0.8,
              top_k=12, seed=7, timeout=60.0)
    a = eng.predict(np.array([9, 8, 7], np.int32), **kw)
    b = eng.predict(np.array([9, 8, 7], np.int32), **kw)
    assert a[0] == b[0]
    c = eng.predict(np.array([9, 8, 7], np.int32),
                    **{**kw, "seed": 1234})
    for toks in (a, b, c):
        assert np.all(toks >= 0) and np.all(toks < VOCAB)


# ---------------------------------------------------------------------------
# on-device sampler unit tests
# ---------------------------------------------------------------------------

def test_sample_tokens_policies():
    import jax

    rs = np.random.RandomState(0)
    logits = np.asarray(rs.randn(3, 16), np.float32)
    key = jax.random.PRNGKey(0)
    ones = np.ones(3, np.float32)
    zeros_i = np.zeros(3, np.int32)
    amax = logits.argmax(-1)

    def draw(temperature=ones, top_k=zeros_i, top_p=ones,
             greedy=np.zeros(3, bool), k=key):
        return np.asarray(sample_tokens(
            np.asarray(logits), k, np.asarray(temperature),
            np.asarray(top_k), np.asarray(top_p), np.asarray(greedy)))

    assert np.array_equal(draw(greedy=np.ones(3, bool)), amax)
    # top_k=1 collapses to argmax no matter the temperature
    assert np.array_equal(
        draw(temperature=ones * 5.0, top_k=np.ones(3, np.int32)), amax)
    # a tiny nucleus keeps only the argmax (it always survives)
    assert np.array_equal(draw(top_p=ones * 1e-6), amax)
    # per-row policies compose inside ONE call
    mixed = draw(greedy=np.array([True, False, False]),
                 top_k=np.array([0, 1, 0], np.int32))
    assert mixed[0] == amax[0] and mixed[1] == amax[1]
    # seeded: same key -> same draw; keys differ -> free to differ
    t = ones * 3.0
    assert np.array_equal(draw(temperature=t), draw(temperature=t))
    assert np.all(draw() >= 0) and np.all(draw() < 16)


# ---------------------------------------------------------------------------
# sealed-engine + dispatch-budget contracts
# ---------------------------------------------------------------------------

def test_over_bucket_prompt_is_typed_refusal_not_retrace(eng):
    st0 = eng.stats()
    with pytest.raises(RetraceForbidden, match="no prefill bucket"):
        eng.submit(np.arange(17, dtype=np.int32))  # > max bucket 16
    with pytest.raises(RetraceForbidden):
        eng.submit(np.zeros(MAX_SEQ, np.int32))    # prompt fills max_seq
    st1 = eng.stats()
    assert st1["refused"] - st0["refused"] == 2
    assert st1["compiles"] == st0["compiles"]


def test_single_dispatch_chunk_budget(eng):
    """One request of N tokens costs 1 prefill + ~ceil((N-1)/chunk)
    chunk dispatches — the whole point of the fast path. Checked on the
    engine's own counters AND the XLA dispatch telemetry."""
    obs.set_enabled(True)
    d0c = obs.XLA_DISPATCH_TOTAL.value(site="decode_chunk")
    d0p = obs.XLA_DISPATCH_TOTAL.value(site="decode_prefill")
    st0 = eng.stats()
    n = 9  # prefill token + 8 more = 2 full chunks of 4
    toks = eng.predict(np.array([2, 4, 6], np.int32),
                       max_new_tokens=n, greedy=True, timeout=60.0)
    assert len(toks) == n
    st1 = eng.stats()
    assert st1["prefills"] - st0["prefills"] == 1
    chunks = st1["decode_chunks"] - st0["decode_chunks"]
    assert chunks == -(-(n - 1) // CHUNK)  # exactly ceil, no slack
    assert obs.XLA_DISPATCH_TOTAL.value(site="decode_chunk") - d0c \
        == chunks
    assert obs.XLA_DISPATCH_TOTAL.value(site="decode_prefill") - d0p == 1
    assert st1["compiles"] == st0["compiles"]


def test_ragged_traffic_never_retraces_and_frees_cache(eng, net):
    """A burst of mixed prompt lengths / budgets / sampling policies:
    zero compiles after warmup, zero retraces, amortized dispatch cost
    under 1/chunk + scheduling slack, and the cache drains to empty."""
    st0 = eng.stats()
    rs = np.random.RandomState(3)
    futs, oracle_checks = [], []
    for i in range(14):
        plen = int(rs.choice([3, 4, 6, 8, 11, 16]))
        prompt = rs.randint(0, VOCAB, plen).astype(np.int32)
        n = int(rs.choice([2, 5, 8, 13]))
        if i % 3 == 0:
            futs.append(eng.submit(prompt, max_new_tokens=n, greedy=True))
            oracle_checks.append((len(futs) - 1, list(prompt), n))
        else:
            futs.append(eng.submit(prompt, max_new_tokens=n, greedy=False,
                                   temperature=0.9, top_k=10,
                                   top_p=0.95, seed=i))
    outs = [f.result(120.0) for f in futs]
    st1 = eng.stats()
    assert st1["requests_ok"] - st0["requests_ok"] == 14
    assert st1["compiles"] == st0["compiles"]  # warm: nothing compiled
    assert st1["recompiles_after_warmup"] == 0
    assert st1["retraces_after_warmup"] == 0
    for idx, prompt, n in oracle_checks:  # greedy ones stay exact
        assert len(outs[idx]) == n
        _assert_matches_dense(net, prompt, outs[idx])
    tokens = st1["tokens_generated"] - st0["tokens_generated"]
    disp = st1["dispatches"] - st0["dispatches"]
    prefills = st1["prefills"] - st0["prefills"]
    assert (disp - prefills) <= ((tokens - prefills) / CHUNK) * 1.5 + 3
    _drain(eng)
    assert eng.stats()["cache"]["blocks_used"] == 0


def test_late_join_rides_next_chunk_without_drain(eng):
    long_f = eng.submit(np.array([1, 2, 3], np.int32),
                        max_new_tokens=40, greedy=True)
    deadline = time.perf_counter() + 10.0
    while eng.active_slots() == 0 and time.perf_counter() < deadline:
        time.sleep(0.001)
    assert eng.active_slots() > 0
    short_f = eng.submit(np.array([9, 9], np.int32),
                         max_new_tokens=3, greedy=True)
    assert len(short_f.result(60.0)) == 3
    assert len(long_f.result(60.0)) == 40
    # the short request joined mid-flight and retired first — token-
    # level batching, not request-level (no drain between admissions)
    assert short_f.token_times()[1] < long_f.token_times()[1]


def test_deadline_expires_in_queue(eng):
    longs = [eng.submit(np.array([5, 3], np.int32), max_new_tokens=30,
                        greedy=True) for _ in range(SLOTS + 1)]
    f = eng.submit(np.array([1, 1], np.int32), max_new_tokens=30,
                   deadline_ms=0.1)
    with pytest.raises(RequestTimeout):
        f.result(60.0)
    for lf in longs:
        assert len(lf.result(120.0)) == 30  # bystanders unharmed


def test_cancel_only_before_admission(eng):
    longs = [eng.submit(np.array([5, 3], np.int32), max_new_tokens=25,
                        greedy=True) for _ in range(SLOTS + 2)]
    victim = eng.submit(np.array([2, 2], np.int32), max_new_tokens=4)
    assert victim.cancel() is True
    assert victim.cancelled()
    with pytest.raises(RequestCancelled):
        victim.result(10.0)
    done = longs[0]
    done.result(120.0)
    assert done.cancel() is False  # too late: already ran
    for lf in longs[1:]:
        lf.result(120.0)


# ---------------------------------------------------------------------------
# lifecycle + integration (dedicated engines: these ones die)
# ---------------------------------------------------------------------------

def _tiny_net(**kw):
    kw.setdefault("vocab_size", 32)
    kw.setdefault("num_layers", 1)
    kw.setdefault("d_model", 16)
    kw.setdefault("num_heads", 2)
    kw.setdefault("max_seq", 32)
    kw.setdefault("seed", 0)
    return TransformerDecoderLM(**kw)


_TINY_ENG = dict(slots=2, chunk=2, cache_blocks=24, cache_block_size=4)


def test_pause_resume_kill_lifecycle():
    e = GenerationEngine(_tiny_net(), [4], name="gen-life", **_TINY_ENG)
    try:
        assert len(e.predict([1, 2], max_new_tokens=2, timeout=60.0)) == 2
        e.pause()
        with pytest.raises(EngineClosed):
            e.submit(np.array([1, 2], np.int32))
        e.resume()
        assert len(e.predict([1, 2], max_new_tokens=2, timeout=60.0)) == 2
        f = e.submit(np.array([3, 1], np.int32), max_new_tokens=20)
        e.kill()  # host death: in-flight fails typed, nothing hangs
        with pytest.raises(ReplicaDead):
            f.result(30.0)
        with pytest.raises(EngineClosed):
            e.resume()
    finally:
        e.close()  # idempotent after kill


def test_close_drains_inflight():
    e = GenerationEngine(_tiny_net(), [4], name="gen-drain", **_TINY_ENG)
    f = e.submit(np.array([1, 2, 3], np.int32), max_new_tokens=10)
    e.close()
    assert len(f.result(1.0)) == 10  # drained, not aborted
    with pytest.raises(EngineClosed):
        e.submit(np.array([1, 2], np.int32))


def test_repository_dispatches_decode_capable_nets():
    """``repo.load`` sees ``decode_step_fn`` and serves the net with a
    GenerationEngine behind the same repository surface — the fleet
    stack from PR 17 needs zero changes."""
    repo = ModelRepository()
    try:
        engine = repo.load("lm", _tiny_net(), [4], version="v1",
                           **_TINY_ENG)
        assert isinstance(engine, GenerationEngine)
        st = repo.stats("lm")
        assert st["engine"] == "generation"
        toks = repo.predict("lm", np.array([1, 2, 3], np.int32),
                            max_new_tokens=4, timeout=60.0)
        assert len(toks) == 4
        assert repo.stats("lm")["requests_ok"] >= 1
    finally:
        repo.close()


def test_local_replica_serves_decoder_spec():
    """The plain-dict ``{"decoder": ...}`` spec crosses the replica
    boundary: same seed -> identical weights -> greedy output matches a
    directly-built engine."""
    net = _tiny_net()
    spec = {"net": net.spec(), "shapes": [4], "version": "v1",
            "engine": dict(_TINY_ENG)}
    rep = LocalReplica(0, spec, name="lm")
    try:
        assert rep.state == "live"
        got = rep.submit(np.array([4, 2, 1], np.int32),
                         max_new_tokens=5, greedy=True).result(60.0)
        direct = GenerationEngine(net, [4], name="lm-ref", **_TINY_ENG)
        try:
            want = direct.predict(np.array([4, 2, 1], np.int32),
                                  max_new_tokens=5, greedy=True,
                                  timeout=60.0)
        finally:
            direct.close()
        assert list(got) == list(want)
    finally:
        rep.close()
