"""Sparse NDArray tests (reference model: test_sparse_ndarray.py) +
the factorization-machine path (BASELINE config #4)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse
from mxnet_tpu.test_utils import assert_almost_equal


def test_row_sparse_roundtrip():
    dense = np.zeros((6, 3), np.float32)
    dense[1] = 1.0
    dense[4] = 2.0
    rsp = sparse.cast_storage(mx.nd.array(dense), "row_sparse")
    assert rsp.stype == "row_sparse"
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [1, 4])
    assert_almost_equal(rsp.tostype("default"), dense)
    # dense ops work directly on the sparse handle
    assert_almost_equal((rsp * 2).asnumpy(), dense * 2)


def test_csr_roundtrip():
    dense = np.array([[0, 1, 0], [2, 0, 3]], np.float32)
    csr = sparse.cast_storage(mx.nd.array(dense), "csr")
    assert csr.stype == "csr"
    assert_almost_equal(csr.tostype("default"), dense)
    np.testing.assert_array_equal(csr.indptr.asnumpy(), [0, 1, 3])
    np.testing.assert_array_equal(csr.indices.asnumpy(), [1, 0, 2])


def test_row_sparse_array_ctor():
    rsp = sparse.row_sparse_array(
        (np.ones((2, 4), np.float32), [1, 3]), shape=(5, 4))
    assert rsp.shape == (5, 4)
    d = rsp.tostype("default").asnumpy()
    assert d[1].sum() == 4 and d[3].sum() == 4 and d[0].sum() == 0


def test_csr_matrix_ctor():
    csr = sparse.csr_matrix(
        (np.array([1.0, 2.0], np.float32), [0, 2], [0, 1, 2]), shape=(2, 3))
    d = csr.tostype("default").asnumpy()
    assert d[0, 0] == 1.0 and d[1, 2] == 2.0


def test_retain():
    dense = np.arange(12, dtype=np.float32).reshape(4, 3)
    rsp = sparse.cast_storage(mx.nd.array(dense), "row_sparse")
    kept = sparse.retain(rsp, mx.nd.array([0, 2]))
    d = kept.tostype("default").asnumpy()
    np.testing.assert_array_equal(d[1], 0)
    np.testing.assert_array_equal(d[0], dense[0])
    np.testing.assert_array_equal(d[2], dense[2])


def test_sparse_dot():
    dense = np.random.rand(4, 5).astype(np.float32)
    w = np.random.rand(5, 2).astype(np.float32)
    csr = sparse.cast_storage(mx.nd.array(dense), "csr")
    out = sparse.dot(csr, mx.nd.array(w))
    assert_almost_equal(out, dense @ w, rtol=1e-5)


def test_sparse_embedding_grad_and_kvstore():
    """The FM training pattern: sparse embedding grads + row_sparse_pull."""
    from mxnet_tpu import autograd, gluon

    emb = gluon.contrib.nn.SparseEmbedding(20, 4)
    emb.initialize()
    idx = mx.nd.array([1.0, 5.0, 5.0])
    with autograd.record():
        out = emb(idx)
        loss = out.sum()
    loss.backward()
    g = emb.weight.grad().asnumpy()
    assert g[1].sum() == pytest.approx(4.0)
    assert g[5].sum() == pytest.approx(8.0)  # appears twice
    assert g[0].sum() == 0

    kv = mx.kv.create("local")
    kv.init("emb", emb.weight.data())
    out_buf = mx.nd.zeros((20, 4))
    kv.row_sparse_pull("emb", out=out_buf, row_ids=mx.nd.array([1, 5]))
    assert out_buf.asnumpy()[2].sum() == 0
    assert_almost_equal(out_buf.asnumpy()[1], emb.weight.data().asnumpy()[1])


@pytest.mark.slow
def test_factorization_machine_convergence():
    """Tiny FM on synthetic sparse data (BASELINE config #4)."""
    from mxnet_tpu import autograd, gluon

    rng = np.random.RandomState(3)
    n, num_feat, k = 200, 30, 4
    # each sample activates 3 features
    feats = rng.randint(0, num_feat, (n, 3)).astype(np.float32)
    true_w = rng.randn(num_feat).astype(np.float32)
    y = (true_w[feats.astype(int)].sum(1) > 0).astype(np.float32)

    class FM(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.w = gluon.contrib.nn.SparseEmbedding(num_feat, 1,
                                                          prefix="w_")
                self.v = gluon.contrib.nn.SparseEmbedding(num_feat, k,
                                                          prefix="v_")

        def hybrid_forward(self, F, x):
            linear = self.w(x).sum(axis=1).reshape((-1,))
            vecs = self.v(x)  # (N, 3, k)
            sum_sq = F.square(vecs.sum(axis=1)).sum(axis=1)
            sq_sum = F.square(vecs).sum(axis=2).sum(axis=1)
            return linear + 0.5 * (sum_sq - sq_sum)

    net = FM()
    net.initialize(init=mx.initializer.Normal(0.1))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SigmoidBCELoss()
    X, Y = mx.nd.array(feats), mx.nd.array(y)
    for _ in range(60):
        with autograd.record():
            l = loss_fn(net(X), Y)
        l.backward()
        trainer.step(n)
    pred = (net(X).sigmoid().asnumpy() > 0.5).astype(np.float32)
    acc = (pred == y).mean()
    assert acc > 0.85, f"FM failed to converge: {acc}"


# sparse-training mechanics stay tier-1 via the embedding-grad /
# kvstore test; both FM soaks (convergence + e2e) ride -m slow
@pytest.mark.slow
def test_factorization_machine_end_to_end():
    """FM on synthetic CTR (BASELINE config #4): dot(csr, dense) forward,
    sparse-aware grads, convergence; the multi-process kvstore variant
    lives in tests/distributed/fm_worker.py."""
    from mxnet_tpu.models import fm as fm_mod
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray, csr_matrix

    F = 80
    vals, indptr, indices, labels = fm_mod.synthetic_ctr(150, F, seed=3)
    fm = fm_mod.FactorizationMachine(F, num_factors=4, seed=1)
    X = csr_matrix((vals, indices, indptr), shape=(150, F))
    y = mx.nd.array(labels)
    losses = [fm_mod.train_step(fm, X, y, lr=0.5) for _ in range(150)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    pred = np.sign(fm.forward(X).asnumpy())
    assert (pred == labels).mean() > 0.9

    # the gradient wire format is row_sparse over touched rows only
    with mx.autograd.record():
        l = fm.loss(X, y)
    l.backward()
    g = fm.grad_rsp(fm.v)
    assert isinstance(g, RowSparseNDArray)
    assert g.indices.shape[0] <= F
    np.testing.assert_allclose(g.asnumpy(), fm.v.grad.asnumpy(), rtol=1e-5)
