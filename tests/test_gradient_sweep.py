"""Registry-wide gradient sweep (VERDICT r3 item 4).

Reference pattern: ``tests/python/unittest/test_operator.py`` numeric-checks
nearly every op's gradient with ``check_numeric_gradient``. This module does
the same systematically: EVERY unique registered op must either carry a
spec (numeric central-difference vs tape backward on sampled inputs) or a
documented exclusion with a reason. An op in neither table FAILS — adding
an op to the registry forces a gradient spec or a justified exclusion.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import registry
from mxnet_tpu.ops.dispatch import invoke
from mxnet_tpu.test_utils import check_numeric_gradient

_R = np.random.RandomState(7)


def u(shape=(2, 3), lo=-1.0, hi=1.0):
    return (_R.uniform(lo, hi, shape)).astype(np.float32)


def distinct(shape=(2, 3), step=0.3):
    """Values pairwise >= step apart (safe for max/sort/median kinks)."""
    n = int(np.prod(shape))
    vals = (np.arange(n) * step - n * step / 2).astype(np.float32)
    return _R.permutation(vals).reshape(shape)


def away0(shape=(2, 3), lo=0.2, hi=1.0):
    """Magnitudes in [lo, hi], random signs (away from kinks at 0)."""
    return (_R.uniform(lo, hi, shape) *
            _R.choice([-1.0, 1.0], shape)).astype(np.float32)


def pos(shape=(2, 3), lo=0.3, hi=1.5):
    return _R.uniform(lo, hi, shape).astype(np.float32)


def spd(n=3):
    a = _R.uniform(-1, 1, (n, n)).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


def ints(shape, hi):
    return _R.randint(0, hi, shape).astype(np.int32)


def op_fn(name, pick_out=None, **kw):
    def fn(*xs):
        r = invoke(name, *xs, **kw)
        if isinstance(r, (list, tuple)):
            r = r[0 if pick_out is None else pick_out]
        return r
    return fn


def unary(name, dom=u, shape=None, **kw):
    return lambda: (op_fn(name, **kw),
                    [dom(shape) if shape is not None else dom()])


def binary(name, dom_l=u, dom_r=u, **kw):
    return lambda: (op_fn(name, **kw), [dom_l(), dom_r()])


# --------------------------------------------------------------------------
# Exclusions: name -> reason. Every reason must say WHY no numeric gradient
# check applies (non-differentiable output, randomness, in-place update
# semantics, or dedicated coverage elsewhere).
# --------------------------------------------------------------------------
NONDIFF = "integer/boolean output; no gradient defined"
CONST = "output independent of float inputs (constant/shape/init op)"
RANDOM = "stochastic output; distribution checks in test_random.py"
OPTIMIZER = "fused optimizer update kernel; semantics tested in test_optimizer.py"
QUANT = "integer quantization path; tested in tests/test_quantization*.py"
INDEXSEL = "pure index-selection output"

EXCLUDED = {
    # int/bool outputs
    "argmax": NONDIFF, "argmin": NONDIFF, "argsort": NONDIFF,
    "argmax_channel": NONDIFF,
    "broadcast_equal": NONDIFF, "broadcast_greater": NONDIFF,
    "broadcast_greater_equal": NONDIFF, "broadcast_lesser": NONDIFF,
    "broadcast_lesser_equal": NONDIFF, "broadcast_not_equal": NONDIFF,
    "broadcast_logical_and": NONDIFF, "broadcast_logical_or": NONDIFF,
    "broadcast_logical_xor": NONDIFF, "logical_not": NONDIFF,
    "bitwise_and": NONDIFF, "bitwise_or": NONDIFF, "bitwise_xor": NONDIFF,
    "bitwise_not": NONDIFF, "left_shift": NONDIFF, "right_shift": NONDIFF,
    "allclose": NONDIFF, "all_finite": NONDIFF, "multi_all_finite": NONDIFF,
    "isfinite": NONDIFF, "isinf": NONDIFF, "isnan": NONDIFF,
    "isneginf": NONDIFF, "isposinf": NONDIFF,
    "bincount": NONDIFF, "digitize": NONDIFF, "searchsorted": NONDIFF,
    "unique": NONDIFF, "getnnz": NONDIFF, "histogram": NONDIFF,
    "unravel_index": NONDIFF, "ravel_multi_index": NONDIFF,
    "index_array": CONST, "shape_array": CONST, "size_array": CONST,
    "edge_id": NONDIFF, "dgl_adjacency": NONDIFF, "dgl_subgraph": NONDIFF,
    "dgl_csr_neighbor_non_uniform_sample": RANDOM,
    "dgl_csr_neighbor_uniform_sample": RANDOM,
    "round": NONDIFF, "rint": NONDIFF, "ceil": NONDIFF, "floor": NONDIFF,
    "trunc": NONDIFF, "fix": NONDIFF, "sign": NONDIFF,
    "fmod": "piecewise-constant w.r.t. divisor, kinks at multiples",
    "broadcast_mod": "piecewise-constant w.r.t. divisor, kinks at multiples",
    "floor_divide": NONDIFF,
    "one_hot": "indices input is integral; output constant w.r.t. it",
    "_onehot_encode": "indices input is integral; output constant w.r.t. it",
    # constants / initializers
    "zeros_like": CONST, "ones_like": CONST, "full_like": CONST,
    "arange_like": CONST, "logspace": CONST,
    "sldwin_atten_mask_like": CONST,
    # randomness
    "normal": RANDOM, "uniform": RANDOM, "randint": RANDOM,
    "exponential": RANDOM, "gamma": RANDOM, "poisson": RANDOM,
    "negative_binomial": RANDOM, "generalized_negative_binomial": RANDOM,
    "multinomial": RANDOM, "shuffle": RANDOM,
    "sample_exponential": RANDOM, "sample_gamma": RANDOM,
    "sample_generalized_negative_binomial": RANDOM,
    "sample_multinomial": RANDOM, "sample_negative_binomial": RANDOM,
    "sample_normal": RANDOM, "sample_poisson": RANDOM,
    "sample_uniform": RANDOM, "_random_gamma": RANDOM,
    "random_brightness": RANDOM, "random_color_jitter": RANDOM,
    "random_contrast": RANDOM, "random_flip_left_right": RANDOM,
    "random_flip_top_bottom": RANDOM, "random_hue": RANDOM,
    "random_lighting": RANDOM, "random_saturation": RANDOM,
    "Dropout": RANDOM,
    # optimizer update kernels
    "adadelta_update": OPTIMIZER, "adagrad_update": OPTIMIZER,
    "adam_update": OPTIMIZER, "adamw_update": OPTIMIZER,
    "dcasgd_update": OPTIMIZER, "ftml_update": OPTIMIZER,
    "ftrl_update": OPTIMIZER, "group_adagrad_update": OPTIMIZER,
    "lamb_update_phase1": OPTIMIZER, "lamb_update_phase2": OPTIMIZER,
    "mp_adamw_update": OPTIMIZER, "mp_lamb_update_phase1": OPTIMIZER,
    "mp_lamb_update_phase2": OPTIMIZER, "mp_nag_mom_update": OPTIMIZER,
    "mp_sgd_mom_update": OPTIMIZER, "mp_sgd_update": OPTIMIZER,
    "multi_adamw_update": OPTIMIZER, "multi_lamb_update": OPTIMIZER,
    "multi_lars": OPTIMIZER, "multi_mp_adamw_update": OPTIMIZER,
    "multi_mp_lamb_update": OPTIMIZER, "multi_mp_sgd_mom_update": OPTIMIZER,
    "multi_mp_sgd_update": OPTIMIZER, "multi_sgd_mom_update": OPTIMIZER,
    "multi_sgd_update": OPTIMIZER, "multi_sum_sq": OPTIMIZER,
    "nag_mom_update": OPTIMIZER,
    "preloaded_multi_mp_sgd_mom_update": OPTIMIZER,
    "preloaded_multi_mp_sgd_update": OPTIMIZER,
    "preloaded_multi_sgd_mom_update": OPTIMIZER,
    "preloaded_multi_sgd_update": OPTIMIZER,
    "reset_arrays": OPTIMIZER, "rmsprop_update": OPTIMIZER,
    "rmspropalex_update": OPTIMIZER, "sgd_mom_update": OPTIMIZER,
    "sgd_update": OPTIMIZER, "signsgd_update": OPTIMIZER,
    "signum_update": OPTIMIZER,
    # quantization / int8
    "quantize": QUANT, "quantize_v2": QUANT, "quantize_2bit": QUANT,
    "quantized_act": QUANT, "quantized_conv": QUANT,
    "quantized_flatten": QUANT, "quantized_fully_connected": QUANT,
    "quantized_pooling": QUANT, "quantized_concat": QUANT, "requantize": QUANT, "dequantize": QUANT,
    "quantized_elemwise_add": QUANT, "quantized_batch_norm": QUANT,
    "calibrate_entropy": QUANT,
    "intgemm_fully_connected": QUANT, "intgemm_maxabsolute": QUANT,
    "intgemm_prepare_data": QUANT, "intgemm_prepare_weight": QUANT,
    "intgemm_take_weight": QUANT,
    # detection / assignment (piecewise-constant box logic)
    "box_nms": "hard selection; forward tested in test_detection.py",
    "box_non_maximum_suppression":
        "hard selection; forward tested in test_detection.py",
    "box_iou": "piecewise w.r.t. box corners; forward in test_detection.py",
    "box_encode": "target-assignment transform; tested in test_detection.py",
    "box_decode": "target-assignment transform; tested in test_detection.py",
    "bipartite_matching": "discrete matching; tested in test_detection.py",
    "MultiBoxPrior": CONST,
    "MultiBoxTarget": "discrete target assignment; test_detection.py",
    "MultiBoxDetection": "hard NMS selection; test_detection.py",
    "Proposal": "hard NMS selection; test_detection.py",
    "mrcnn_mask_target": "discrete target assignment; test_detection.py",
    # specialized coverage elsewhere
    "RNN": "fused RNN gradients covered by test_gluon_rnn.py cell-vs-fused",
    "flash_attention":
        "gradients covered by tests_tpu/test_pallas_flash.py + "
        "test_attention_models.py reference-vs-kernel checks",
    "_contrib_fused_matmul_stats":
        "hand-derived custom_vjp checked against jax autodiff in "
        "test_fused_conv_bn.py (test_custom_vjp_matches_autodiff)",
    "_contrib_fused_scaled_matmul_stats":
        "hand-derived custom_vjp checked against jax autodiff in "
        "test_fused_conv_bn.py (test_custom_vjp_matches_autodiff)",
    "paged_decode_attention":
        "inference-only decode kernel (serving fast path, never under "
        "autograd); forward parity vs dense recompute in "
        "test_generation.py greedy-oracle checks",
    "sldwin_atten_score": "covered with flash_attention (banded kernels)",
    "sldwin_atten_context": "covered with flash_attention (banded kernels)",
    "_ctc_loss": "CTC gradient checked in test_contrib.py against torch",
    "fft": "complex output; roundtrip tested in test_contrib.py",
    "ifft": "complex intermediate; roundtrip tested in test_contrib.py",
    "count_sketch": "random-hash sketch; tested in test_contrib.py",
    "hawkesll": "specialized likelihood; forward tested in test_contrib.py",
    "random_pdf_dirichlet": "density defined on the probability simplex; "
                            "off-simplex central differences are invalid",
    "gradientmultiplier":
        "gradient is INTENTIONALLY scale*identity (mismatches numeric)",
    "stop_gradient": "gradient is INTENTIONALLY zero (mismatches numeric)",
    "linalg_eig": "general eigendecomposition has no stable VJP in XLA",
    "linalg_eigvals": "general eigenvalues have no stable VJP in XLA",
    "linalg_matrix_rank": NONDIFF,
    "linalg_lstsq": "returns (x, resid, rank, sv); rank is integral",
    "_contrib_moe": "gating uses hard top-k routing; tested in test_moe",
    "Correlation": "patch-comparison op; grads in test_contrib_extra.py",
    "DeformableConvolution":
        "offset-sampling grads in test_contrib_extra.py",
    "ModulatedDeformableConvolution":
        "offset-sampling grads in test_contrib_extra.py",
    "DeformablePSROIPooling": "roi sampling; test_contrib_extra.py",
    "PSROIPooling": "roi sampling; test_contrib_extra.py",
    "ROIPooling": "max-pool roi selection; test_contrib_extra.py",
    "RROIAlign": "rotated roi sampling; test_contrib_extra.py",
    "UpSampling": "nearest upsampling is piecewise-constant in scale; "
                  "bilinear path covered by BilinearResize2D spec",
    "BatchNormWithReLU": "relu kink at 0 composed with BN; BN itself and "
                         "Activation are both swept",
    "SVMOutput": "hinge loss kinks at margin; forward in test_operator.py",
    "SoftmaxOutput": "loss op: backward injects (softmax - label), an "
                     "intentional mismatch with d(forward)",
    "LinearRegressionOutput": "loss op: backward injects (data - label)",
    "LogisticRegressionOutput": "loss op: backward injects (sigmoid - label)",
    "MAERegressionOutput": "loss op: backward injects sign(data - label)",
    "IdentityAttachKLSparseReg": "identity forward with injected KL "
                                 "regularizer gradient",
    "_slice_basic": INDEXSEL,
    "dynamic_reshape": "data-dependent output shape (no jit); forward "
                       "covered in test_operator_breadth.py",
    "boolean_mask": "data-dependent output shape; forward covered in "
                    "test_operator_breadth.py",
    "topk": "returns indices by default; value-mode swept as topk_value",
    "cast": "dtype cast; identity gradient exercised via amp tests",
    "amp_cast": "dtype cast; identity gradient exercised via amp tests",
    "amp_multicast": "dtype cast; identity gradient exercised via amp tests",
    "to_tensor": "uint8 HWC -> float CHW conversion; input is integral",
    "adjust_lighting": "PCA lighting on uint8 images; input is integral",
    "image_crop": "static crop of integral image input",
    "image_resize": "integral image input; bilinear grads via "
                    "BilinearResize2D spec",
}

# --------------------------------------------------------------------------
# Specs: name -> () -> (fn, inputs)
# --------------------------------------------------------------------------
SPECS = {}

# smooth unaries on (-1, 1)
for _n in ["sin", "cos", "tanh", "sinh", "cosh", "arctan", "arcsinh",
           "exp", "expm1", "sigmoid", "erf", "softplus", "softsign",
           "gelu", "gelu_tanh", "silu", "mish", "hard_sigmoid", "square",
           "negative", "identity", "log_sigmoid", "degrees", "radians",
           "nan_to_num", "quadratic"]:
    SPECS[_n] = unary(_n)
# positive domain
for _n in ["sqrt", "rsqrt", "log", "log10", "log1p", "log2", "cbrt",
           "rcbrt", "gammaln", "digamma", "erfc", "reciprocal"]:
    SPECS[_n] = unary(_n, dom=pos)
SPECS["gamma"] = unary("gamma", dom=pos)  # overrides RANDOM exclusion? no—
EXCLUDED.pop("gamma", None)  # mx.nd.gamma is the Gamma FUNCTION here
SPECS["tan"] = unary("tan", dom=lambda: u(lo=-0.6, hi=0.6))
SPECS["arcsin"] = unary("arcsin", dom=lambda: u(lo=-0.8, hi=0.8))
SPECS["arccos"] = unary("arccos", dom=lambda: u(lo=-0.8, hi=0.8))
SPECS["arctanh"] = unary("arctanh", dom=lambda: u(lo=-0.8, hi=0.8))
SPECS["arccosh"] = unary("arccosh", dom=lambda: pos(lo=1.3, hi=2.5))
SPECS["erfinv"] = unary("erfinv", dom=lambda: u(lo=-0.7, hi=0.7))
# kink at 0 -> stay away from it
for _n in ["abs", "relu", "elu", "selu", "leaky_relu_away0"]:
    pass
SPECS["abs"] = unary("abs", dom=away0)
SPECS["relu"] = unary("relu", dom=away0)
SPECS["elu"] = unary("elu", dom=away0)
SPECS["selu"] = unary("selu", dom=away0)
SPECS["hard_swish"] = unary("hard_swish", dom=lambda: away0(lo=0.5, hi=1.2))
SPECS["smooth_l1"] = unary("smooth_l1", dom=lambda: away0(lo=0.3, hi=0.7))
SPECS["clip"] = unary("clip", dom=lambda: away0(lo=0.2, hi=0.45),
                      a_min=-0.5, a_max=0.5)

# binaries
SPECS["broadcast_add"] = binary("broadcast_add")
SPECS["broadcast_sub"] = binary("broadcast_sub")
SPECS["broadcast_mul"] = binary("broadcast_mul")
SPECS["broadcast_div"] = binary("broadcast_div", dom_r=lambda: away0())
SPECS["broadcast_power"] = binary("broadcast_power", dom_l=pos)
SPECS["broadcast_maximum"] = binary(
    "broadcast_maximum", dom_l=lambda: distinct(step=0.4),
    dom_r=lambda: distinct(step=0.4) + 0.17)
SPECS["broadcast_minimum"] = binary(
    "broadcast_minimum", dom_l=lambda: distinct(step=0.4),
    dom_r=lambda: distinct(step=0.4) + 0.17)
SPECS["broadcast_hypot"] = binary("broadcast_hypot", dom_l=lambda: away0(),
                                  dom_r=lambda: away0())
SPECS["arctan2"] = binary("arctan2", dom_l=lambda: pos(), dom_r=lambda: pos())
SPECS["copysign"] = binary("copysign", dom_l=away0, dom_r=away0)
SPECS["logaddexp"] = binary("logaddexp")
SPECS["ldexp"] = binary("ldexp")
SPECS["squared_difference"] = binary("squared_difference")
SPECS["add_n"] = lambda: (op_fn("add_n"), [u(), u(), u()])
SPECS["interp"] = lambda: (
    op_fn("interp"),
    [np.linspace(0.05, 0.95, 4).astype(np.float32),
     np.linspace(0.0, 1.0, 6).astype(np.float32), u((6,))])

# reductions (sum over output inside harness)
for _n in ["sum", "mean", "nansum", "logsumexp"]:
    SPECS[_n] = unary(_n)
SPECS["prod"] = unary("prod", dom=away0)
SPECS["nanprod"] = unary("nanprod", dom=away0)
SPECS["max"] = unary("max", dom=distinct)
SPECS["min"] = unary("min", dom=distinct)
SPECS["ptp"] = unary("ptp", dom=distinct)
SPECS["median"] = unary("median", dom=lambda: distinct((7,)))
SPECS["quantile"] = lambda: (op_fn("quantile", q=0.5),
                             [distinct((7,))])
SPECS["std"] = unary("std")
SPECS["var"] = unary("var")
SPECS["norm"] = unary("norm", dom=away0)
SPECS["average"] = unary("average")
SPECS["moments"] = lambda: (op_fn("moments", pick_out=0), [u()])
SPECS["cumsum"] = unary("cumsum", axis=0)
SPECS["cumprod"] = unary("cumprod", dom=away0, axis=0)
SPECS["cummax"] = unary("cummax", dom=distinct, axis=0)
SPECS["cummin"] = unary("cummin", dom=distinct, axis=0)
SPECS["sort"] = unary("sort", dom=distinct)
SPECS["topk_value"] = lambda: (
    op_fn("topk", k=2, ret_typ="value"), [distinct((5,))])
SPECS["softmax_cross_entropy"] = lambda: (
    op_fn("softmax_cross_entropy"), [u((2, 4)), ints((2,), 4)])

# shape / movement
SPECS["reshape"] = lambda: (op_fn("reshape", shape=(3, 2)), [u((2, 3))])
SPECS["reshape_like"] = binary("reshape_like", dom_r=lambda: u((3, 2)))
SPECS["transpose"] = unary("transpose")
SPECS["swapaxes"] = unary("swapaxes", dim1=0, dim2=1)
SPECS["moveaxis"] = unary("moveaxis", source=0, destination=1)
SPECS["flip"] = unary("flip", axis=0)
SPECS["flip_left_right"] = lambda: (op_fn("flip_left_right"), [u((4, 4, 3))])
SPECS["flip_top_bottom"] = lambda: (op_fn("flip_top_bottom"), [u((4, 4, 3))])
SPECS["tile"] = unary("tile", reps=(2, 1))
SPECS["repeat"] = unary("repeat", repeats=2)
SPECS["squeeze"] = lambda: (op_fn("squeeze"), [u((2, 1, 3))])
SPECS["expand_dims"] = unary("expand_dims", axis=1)
SPECS["slice"] = unary("slice", begin=(0, 1), end=(2, 3))
SPECS["slice_axis"] = unary("slice_axis", axis=1, begin=0, end=2)
SPECS["slice_like"] = binary("slice_like", dom_r=lambda: u((2, 2)))
SPECS["concat"] = lambda: (op_fn("concat", dim=1), [u(), u()])
SPECS["stack"] = lambda: (op_fn("stack", axis=0), [u(), u()])
SPECS["split"] = lambda: (op_fn("split", pick_out=0, num_outputs=3, axis=1),
                          [u((2, 6))])
SPECS["split_v2"] = lambda: (
    op_fn("split_v2", pick_out=1, sections=2, axis=1), [u((2, 6))])
SPECS["pad"] = lambda: (
    op_fn("pad", mode="constant",
          pad_width=(0, 0, 0, 0, 1, 1, 1, 1)), [u((1, 2, 3, 3))])
SPECS["roll"] = unary("roll", shift=1, axis=0)
SPECS["rot90"] = unary("rot90")
SPECS["flatten"] = lambda: (op_fn("flatten"), [u((2, 2, 2))])
SPECS["broadcast_to"] = lambda: (op_fn("broadcast_to", shape=(3, 4)),
                                 [u((1, 4))])
SPECS["broadcast_axis"] = lambda: (op_fn("broadcast_axis", axis=0, size=3),
                                   [u((1, 4))])
SPECS["broadcast_like"] = binary("broadcast_like", dom_l=lambda: u((1, 3)),
                                 dom_r=lambda: u((4, 3)))
SPECS["depth_to_space"] = lambda: (op_fn("depth_to_space", block_size=2),
                                   [u((1, 4, 2, 2))])
SPECS["space_to_depth"] = lambda: (op_fn("space_to_depth", block_size=2),
                                   [u((1, 1, 4, 4))])
SPECS["diag"] = unary("diag", shape=(3, 3))
SPECS["diagflat"] = lambda: (op_fn("diagflat"), [u((3,))])
SPECS["tril"] = unary("tril", shape=(3, 3))
SPECS["triu"] = unary("triu", shape=(3, 3))
SPECS["trace"] = unary("trace", shape=(3, 3))
SPECS["diff"] = unary("diff", shape=(5,))
SPECS["ediff1d"] = unary("ediff1d", shape=(5,))
SPECS["where"] = lambda: (
    op_fn("where"),
    [np.array([[1.0, 0, 1], [0, 1, 0]], np.float32), u(), u()])
SPECS["Crop"] = lambda: (op_fn("Crop", h_w=(2, 2)), [u((1, 1, 4, 4))])
SPECS["sequence_mask"] = lambda: (
    op_fn("sequence_mask", use_sequence_length=True, value=0.0),
    [u((3, 2, 2)), np.array([1, 3], np.int32)])
SPECS["SequenceLast"] = lambda: (op_fn("SequenceLast"), [u((3, 2, 4))])
SPECS["SequenceReverse"] = lambda: (op_fn("SequenceReverse"), [u((3, 2, 4))])

# indexing / gather
SPECS["take"] = lambda: (op_fn("take", axis=0), [u((4, 3)), ints((2,), 4)])
SPECS["batch_take"] = lambda: (op_fn("batch_take"),
                               [u((3, 4)), ints((3,), 4)])
SPECS["pick"] = lambda: (op_fn("pick", axis=-1), [u((3, 4)), ints((3,), 4)])
SPECS["choose_element_0index"] = lambda: (
    op_fn("choose_element_0index"), [u((3, 4)), ints((3,), 4)])
SPECS["fill_element_0index"] = lambda: (
    op_fn("fill_element_0index"),
    [u((3, 4)), u((3,)), ints((3,), 4)])
SPECS["gather_nd"] = lambda: (op_fn("gather_nd"),
                              [u((4, 3)), ints((1, 2), 3)])
SPECS["scatter_nd"] = lambda: (
    op_fn("scatter_nd", shape=(4, 3)), [u((2, 3)), ints((1, 2), 4)])
SPECS["index_add"] = lambda: (
    op_fn("index_add"), [u((4, 3)), ints((1, 2), 3), u((2, 3))])
SPECS["index_update"] = lambda: (
    op_fn("index_update"),
    [u((4, 3)), np.array([[0], [2]], np.int32).T, u((1, 3))])
SPECS["index_copy"] = lambda: (
    op_fn("index_copy"), [u((4, 3)), ints((2,), 4), u((2, 3))])
SPECS["Embedding"] = lambda: (
    op_fn("Embedding", input_dim=5, output_dim=3),
    [ints((2, 2), 5), u((5, 3))])
SPECS["one_hot_like"] = None  # placeholder never used
del SPECS["one_hot_like"]

# matmul family
SPECS["dot"] = binary("dot", dom_l=lambda: u((2, 3)), dom_r=lambda: u((3, 2)))
SPECS["batch_dot"] = binary("batch_dot", dom_l=lambda: u((2, 2, 3)),
                            dom_r=lambda: u((2, 3, 2)))
SPECS["matmul"] = binary("matmul", dom_l=lambda: u((2, 3)),
                         dom_r=lambda: u((3, 2)))
SPECS["inner"] = binary("inner", dom_l=lambda: u((2, 3)),
                        dom_r=lambda: u((4, 3)))
SPECS["outer"] = binary("outer", dom_l=lambda: u((3,)), dom_r=lambda: u((4,)))
SPECS["vdot"] = binary("vdot", dom_l=lambda: u((4,)), dom_r=lambda: u((4,)))
SPECS["kron"] = binary("kron", dom_l=lambda: u((2, 2)),
                       dom_r=lambda: u((2, 2)))
SPECS["cross"] = binary("cross", dom_l=lambda: u((2, 3)),
                        dom_r=lambda: u((2, 3)))
SPECS["tensordot"] = binary("tensordot", dom_l=lambda: u((2, 3, 4)),
                            dom_r=lambda: u((3, 4, 2)))
SPECS["identity_with_attr_like_rhs"] = binary("identity_with_attr_like_rhs")
SPECS["einsum"] = lambda: (
    op_fn("einsum", subscripts="ij,jk->ik"), [u((2, 3)), u((3, 2))])
SPECS["khatri_rao"] = lambda: (op_fn("khatri_rao"), [u((2, 3)), u((4, 3))])
SPECS["interleaved_matmul_selfatt_qk"] = lambda: (
    op_fn("interleaved_matmul_selfatt_qk", heads=2), [u((3, 2, 3 * 8))])
SPECS["interleaved_matmul_selfatt_valatt"] = lambda: (
    op_fn("interleaved_matmul_selfatt_valatt", heads=2),
    [u((3, 2, 3 * 8)), u((4, 3, 3))])
SPECS["interleaved_matmul_encdec_qk"] = lambda: (
    op_fn("interleaved_matmul_encdec_qk", heads=2),
    [u((3, 2, 8)), u((3, 2, 2 * 8))])
SPECS["interleaved_matmul_encdec_valatt"] = lambda: (
    op_fn("interleaved_matmul_encdec_valatt", heads=2),
    [u((3, 2, 2 * 8)), u((4, 3, 3))])

# nn ops
SPECS["FullyConnected"] = lambda: (
    op_fn("FullyConnected", num_hidden=4),
    [u((2, 3)), u((4, 3)), u((4,))])
SPECS["Convolution"] = lambda: (
    op_fn("Convolution", kernel=(3, 3), num_filter=3, pad=(1, 1)),
    [u((1, 2, 4, 4)), u((3, 2, 3, 3)), u((3,))])
SPECS["Deconvolution"] = lambda: (
    op_fn("Deconvolution", kernel=(3, 3), num_filter=3, no_bias=True),
    [u((1, 2, 4, 4)), u((2, 3, 3, 3))])
SPECS["Pooling_avg"] = lambda: (
    op_fn("Pooling", kernel=(2, 2), pool_type="avg", stride=(2, 2)),
    [u((1, 2, 4, 4))])
SPECS["Pooling"] = lambda: (
    op_fn("Pooling", kernel=(2, 2), pool_type="max", stride=(2, 2)),
    [distinct((1, 2, 4, 4), step=0.2)])
SPECS["BatchNorm"] = lambda: (
    op_fn("BatchNorm", pick_out=0, training=True, fix_gamma=False,
          momentum=0.9, eps=1e-3),
    [u((3, 2, 2)), pos((2,)), u((2,)), np.zeros(2, np.float32),
     np.ones(2, np.float32)])
SPECS["LayerNorm"] = lambda: (
    op_fn("LayerNorm"), [u((2, 4)), pos((4,)), u((4,))])
SPECS["GroupNorm"] = lambda: (
    op_fn("GroupNorm", num_groups=2), [u((2, 4, 3)), pos((4,)), u((4,))])
SPECS["InstanceNorm"] = lambda: (
    op_fn("InstanceNorm"), [u((2, 3, 4)), pos((3,)), u((3,))])
SPECS["L2Normalization"] = unary("L2Normalization",
                                 dom=lambda: away0((2, 4)))
SPECS["LRN"] = lambda: (op_fn("LRN", nsize=3), [u((1, 4, 2, 2))])
SPECS["Activation"] = unary("Activation", dom=away0, act_type="relu")
SPECS["LeakyReLU"] = unary("LeakyReLU", dom=away0, act_type="leaky")
SPECS["prelu"] = lambda: (op_fn("prelu"), [away0((2, 3)), pos((1,))])
SPECS["softmax"] = unary("softmax")
SPECS["log_softmax"] = unary("log_softmax")
SPECS["softmin"] = unary("softmin")
SPECS["masked_softmax"] = lambda: (
    op_fn("masked_softmax"),
    [u((2, 4)), np.array([[1, 1, 0, 1], [1, 0, 1, 1]], bool)])
def _masked_log_softmax_spec():
    m = np.array([[1, 1, 0, 1], [1, 0, 1, 1]], bool)
    mf = mx.nd.array(m.astype(np.float32))

    def fn(d, mask):
        out = invoke("masked_log_softmax", d, mask)
        # masked slots are -inf by construction; zero them so the
        # harness's sum stays finite (their gradient is 0 either way)
        return mx.nd.where(mf, out, mx.nd.zeros_like(out))
    return fn, [u((2, 4)), m]


SPECS["masked_log_softmax"] = _masked_log_softmax_spec
SPECS["im2col"] = lambda: (
    op_fn("im2col", kernel=(2, 2), stride=(1, 1)), [u((1, 2, 3, 3))])
SPECS["col2im"] = lambda: (
    op_fn("col2im", input_size=(2, 3, 3), kernel=(2, 2), stride=(1, 1)),
    [u((1, 8, 4))])
SPECS["AdaptiveAvgPooling2D"] = lambda: (
    op_fn("AdaptiveAvgPooling2D", output_size=(2, 2)), [u((1, 2, 4, 4))])
SPECS["BilinearResize2D"] = lambda: (
    op_fn("BilinearResize2D", height=5, width=5), [u((1, 2, 3, 3))])
SPECS["GridGenerator"] = lambda: (
    op_fn("GridGenerator", transform_type="affine", target_shape=(3, 3)),
    [u((1, 6))])
SPECS["BilinearSampler"] = lambda: (
    op_fn("BilinearSampler"),
    [u((1, 1, 4, 4)), (u((1, 2, 3, 3)) * 0.4)])
SPECS["SpatialTransformer"] = lambda: (
    op_fn("SpatialTransformer", transform_type="affine",
          sampler_type="bilinear", target_shape=(3, 3)),
    [u((1, 1, 4, 4)), u((1, 6)) * 0.3])
SPECS["ROIAlign"] = lambda: (
    op_fn("ROIAlign", pooled_size=(2, 2), spatial_scale=1.0),
    [u((1, 2, 6, 6)),
     np.array([[0, 0.7, 0.7, 4.2, 4.2]], np.float32)])
SPECS["UpSampling_bilinear"] = lambda: (
    op_fn("BilinearResize2D", height=6, width=6), [u((1, 1, 3, 3))])
del SPECS["UpSampling_bilinear"]
SPECS["image_normalize"] = lambda: (
    op_fn("image_normalize", mean=(0.5,), std=(0.3,)), [pos((3, 4, 4))])

# linalg
SPECS["linalg_cholesky"] = lambda: (op_fn("linalg_cholesky"), [spd()])
SPECS["linalg_potrf"] = lambda: (op_fn("linalg_potrf"), [spd()])
SPECS["linalg_potri"] = lambda: (op_fn("linalg_potri"), [spd()])
SPECS["linalg_det"] = lambda: (op_fn("linalg_det"), [spd()])
SPECS["linalg_slogdet"] = lambda: (op_fn("linalg_slogdet", pick_out=1),
                                   [spd()])
SPECS["linalg_inverse"] = lambda: (op_fn("linalg_inverse"), [spd()])
SPECS["linalg_solve"] = lambda: (op_fn("linalg_solve"), [spd(), u((3, 2))])
SPECS["linalg_sumlogdiag"] = lambda: (op_fn("linalg_sumlogdiag"), [spd()])
SPECS["linalg_extractdiag"] = lambda: (op_fn("linalg_extractdiag"),
                                       [u((3, 3))])
SPECS["linalg_makediag"] = lambda: (op_fn("linalg_makediag"), [u((3,))])
SPECS["linalg_extracttrian"] = lambda: (op_fn("linalg_extracttrian"),
                                        [u((3, 3))])
SPECS["linalg_maketrian"] = lambda: (op_fn("linalg_maketrian"), [u((6,))])
SPECS["linalg_gemm"] = lambda: (
    op_fn("linalg_gemm"), [u((2, 3)), u((3, 2)), u((2, 2))])
SPECS["linalg_gemm2"] = lambda: (
    op_fn("linalg_gemm2"), [u((2, 3)), u((3, 2))])
SPECS["linalg_syrk"] = lambda: (op_fn("linalg_syrk"), [u((2, 3))])
SPECS["linalg_trmm"] = lambda: (
    op_fn("linalg_trmm"), [np.tril(pos((3, 3)) + np.eye(3, dtype=np.float32)),
                           u((3, 2))])
SPECS["linalg_trsm"] = lambda: (
    op_fn("linalg_trsm"), [np.tril(pos((3, 3))) + 2 * np.eye(3,
                                                             dtype=np.float32),
                           u((3, 2))])
SPECS["linalg_svd"] = lambda: (op_fn("linalg_svd", pick_out=1),
                               [np.diag([3.0, 2.0, 1.0]).astype(np.float32)
                                + 0.1 * u((3, 3))])
SPECS["linalg_qr"] = lambda: (op_fn("linalg_qr", pick_out=1), [spd()])
SPECS["linalg_eigh"] = lambda: (op_fn("linalg_eigh", pick_out=0), [spd()])
SPECS["linalg_eigvalsh"] = lambda: (op_fn("linalg_eigvalsh"), [spd()])
SPECS["linalg_syevd"] = lambda: (op_fn("linalg_syevd", pick_out=1), [spd()])
SPECS["linalg_norm"] = lambda: (op_fn("linalg_norm"), [away0((3, 3))])
SPECS["linalg_pinv"] = lambda: (op_fn("linalg_pinv"), [spd()])
SPECS["linalg_gelqf"] = lambda: (op_fn("linalg_gelqf", pick_out=1),
                                 [u((2, 3))])
SPECS["linalg_multi_dot"] = lambda: (
    op_fn("linalg_multi_dot"), [u((2, 3)), u((3, 2))])
SPECS["linalg_tensorinv"] = lambda: (
    op_fn("linalg_tensorinv", ind=1), [spd(4).reshape(4, 2, 2) * 0 +
                                       np.eye(4, dtype=np.float32)
                                       .reshape(4, 2, 2) + 0.1 * u((4, 2, 2))])
SPECS["linalg_tensorsolve"] = lambda: (
    op_fn("linalg_tensorsolve"),
    [np.eye(4, dtype=np.float32).reshape(2, 2, 2, 2) + 0.1 * u((2, 2, 2, 2)),
     u((2, 2))])

# random pdfs (deterministic densities, differentiable w.r.t. params)
SPECS["random_pdf_normal"] = lambda: (
    op_fn("random_pdf_normal"), [u((2, 3)), u((2,)), pos((2,))])
SPECS["random_pdf_exponential"] = lambda: (
    op_fn("random_pdf_exponential"), [pos((2, 3)), pos((2,))])
SPECS["random_pdf_uniform"] = lambda: (
    op_fn("random_pdf_uniform"), [pos((2, 3), lo=0.3, hi=0.7),
                                  np.zeros(2, np.float32) - 0.1,
                                  np.ones(2, np.float32) + 0.2])
SPECS["random_pdf_gamma"] = lambda: (
    op_fn("random_pdf_gamma"), [pos((2, 3)), pos((2,)), pos((2,))])
SPECS["random_pdf_poisson"] = lambda: (
    op_fn("random_pdf_poisson"), [ints((2, 3), 4).astype(np.float32),
                                  pos((2,))])
SPECS["random_pdf_negative_binomial"] = lambda: (
    op_fn("random_pdf_negative_binomial"),
    [ints((2, 3), 4).astype(np.float32), pos((2,), lo=1.0, hi=3.0),
     pos((2,), lo=0.3, hi=0.7)])
SPECS["random_pdf_generalized_negative_binomial"] = lambda: (
    op_fn("random_pdf_generalized_negative_binomial"),
    [ints((2, 3), 4).astype(np.float32), pos((2,)), pos((2,), lo=0.2,
                                                        hi=0.6)])


# misc
SPECS["div_sqrt_dim"] = unary("div_sqrt_dim")

# scalar-operand family (round-4 additions)
for _n in ["_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar"]:
    SPECS[_n] = unary(_n, scalar=1.7)
SPECS["_div_scalar"] = unary("_div_scalar", scalar=1.7)
SPECS["_rdiv_scalar"] = unary("_rdiv_scalar", dom=away0, scalar=1.7)
SPECS["_power_scalar"] = unary("_power_scalar", dom=pos, scalar=2.3)
SPECS["_rpower_scalar"] = unary("_rpower_scalar", scalar=1.8)
SPECS["_maximum_scalar"] = unary("_maximum_scalar", dom=lambda: distinct() + 0.07,
                                 scalar=0.0)
SPECS["_minimum_scalar"] = unary("_minimum_scalar", dom=lambda: distinct() + 0.07,
                                 scalar=0.0)
SPECS["_hypot_scalar"] = unary("_hypot_scalar", dom=away0, scalar=1.1)
SPECS["_grad_add"] = binary("_grad_add")
SPECS["trapz"] = unary("trapz", shape=(5,))
EXCLUDED.update({
    "_equal_scalar": NONDIFF, "_not_equal_scalar": NONDIFF,
    "_greater_scalar": NONDIFF, "_greater_equal_scalar": NONDIFF,
    "_lesser_scalar": NONDIFF, "_lesser_equal_scalar": NONDIFF,
    "_logical_and_scalar": NONDIFF, "_logical_or_scalar": NONDIFF,
    "_logical_xor_scalar": NONDIFF,
    "logical_and": NONDIFF, "logical_or": NONDIFF, "logical_xor": NONDIFF,
    "_mod_scalar": "piecewise-constant w.r.t. scalar modulus, kinks at "
                   "multiples",
    "_rmod_scalar": "piecewise-constant, kinks at multiples",
})
SPECS["logsumexp2"] = None
del SPECS["logsumexp2"]
SPECS["pick2"] = None
del SPECS["pick2"]


def _unique_names():
    seen = {}
    for name, od in registry.all_ops().items():
        seen.setdefault(id(od), od.name)
    return sorted(set(seen.values()))


ALL_NAMES = _unique_names()
EXTRA_SPECS = [n for n in SPECS if n not in ALL_NAMES]


def test_sweep_is_complete():
    """Every registered op is either spec'd or excluded with a reason."""
    missing = [n for n in ALL_NAMES if n not in SPECS and n not in EXCLUDED]
    assert not missing, f"ops with no gradient spec or exclusion: {missing}"
    stale = [n for n in EXCLUDED if n not in ALL_NAMES]
    assert not stale, f"excluded ops not in registry: {stale}"


def test_sweep_covers_200_plus():
    swept = [n for n in SPECS if n in ALL_NAMES or n in EXTRA_SPECS]
    assert len(swept) >= 200, len(swept)


# --------------------------------------------------------------------------
# Deep sweep (VERDICT r5 #5): rank-3/4 shapes, explicit axis variants,
# true broadcasting pairs — the places where tape/vjp wiring breaks
# silently while single-(2,3) specs stay green. Reference pattern:
# test_operator.py's per-op shape loops.
# --------------------------------------------------------------------------

DEEP = {}

# the deep cases draw inputs at import time; save/restore the shared
# RandomState so the per-test draws of the base SPECS (which happen at
# test time) see exactly the sequence they saw before this block existed
_SAVED_STATE = _R.get_state()
_R.seed(1234)


def _deep(label, fn, *inputs, atol=5e-3):
    assert label not in DEEP, label
    DEEP[label] = (fn, list(inputs), atol)


R3, R4 = (2, 3, 4), (2, 3, 2, 4)

# reductions: every axis form that exercises a distinct vjp layout
for _op, _dom in (("sum", u), ("mean", u), ("prod", pos),
                  ("max", distinct), ("min", distinct), ("norm", away0)):
    for _ax, _kd in ((0, False), (1, False), ((1, 2), False), (-1, True),
                     ((0, 2), True)):
        _deep(f"{_op}_r3_ax{_ax}_kd{int(_kd)}",
              op_fn(_op, axis=_ax, keepdims=_kd), _dom(R3))
    _deep(f"{_op}_r4_ax13", op_fn(_op, axis=(1, 3)), _dom(R4))

# broadcasting binaries: genuinely mismatched operand ranks/shapes
_PAIRS = [((2, 1, 4), (1, 3, 1)), ((2, 3, 4), (4,)),
          ((1,), (2, 3, 4)), ((3, 1, 5), (2, 1, 4, 5))]
for _op, _dl, _dr in (
        ("broadcast_add", u, u), ("broadcast_sub", u, u),
        ("broadcast_mul", u, u),
        ("broadcast_div", u, lambda s: away0(s, lo=0.4)),
        # base away from 1: grad wrt the exponent is y*ln(base), which
        # vanishes (pure noise vs central differences) around base=1
        ("broadcast_power", lambda s: pos(s, lo=1.4, hi=2.2),
         lambda s: u(s, lo=-1.2, hi=1.2)),
        # disjoint ranges keep max/min selections away from ties
        ("broadcast_maximum", lambda s: u(s, lo=-1.0, hi=-0.2),
         lambda s: u(s, lo=0.2, hi=1.0)),
        ("broadcast_minimum", lambda s: u(s, lo=-1.0, hi=-0.2),
         lambda s: u(s, lo=0.2, hi=1.0)),
        ("broadcast_hypot", lambda s: away0(s, lo=0.3),
         lambda s: away0(s, lo=0.3))):
    for _i, (_sl, _sr) in enumerate(_PAIRS):
        _deep(f"{_op}_bc{_i}", op_fn(_op), _dl(_sl), _dr(_sr))

# axis-parameterized movement / normalisation / scan ops at rank 3-4
for _ax in (0, 1, 2, -1):
    _deep(f"softmax_r3_ax{_ax}", op_fn("softmax", axis=_ax), u(R3))
    _deep(f"log_softmax_r3_ax{_ax}", op_fn("log_softmax", axis=_ax),
          u(R3))
    _deep(f"cumsum_r3_ax{_ax}", op_fn("cumsum", axis=_ax), u(R3))
    _deep(f"flip_r3_ax{_ax}", op_fn("flip", axis=_ax), u(R3))
    _deep(f"expand_dims_r3_ax{_ax}", op_fn("expand_dims", axis=_ax),
          u(R3))
_deep("transpose_r3", op_fn("transpose", axes=(2, 0, 1)), u(R3))
_deep("transpose_r4", op_fn("transpose", axes=(0, 3, 1, 2)), u(R4))
_deep("reshape_r4", op_fn("reshape", shape=(6, 8)), u(R4))
_deep("tile_r3", op_fn("tile", reps=(2, 1, 3)), u(R3))
_deep("repeat_r3_ax1", op_fn("repeat", repeats=2, axis=1), u(R3))
_deep("slice_r3", op_fn("slice", begin=(0, 1, 1), end=(2, 3, 3)), u(R3))
_deep("slice_axis_r4", op_fn("slice_axis", axis=2, begin=0, end=1),
      u(R4))
_deep("squeeze_r4", op_fn("squeeze", axis=2), u((2, 3, 1, 4)))
_deep("concat_r3_dim2", op_fn("concat", dim=2), u(R3), u(R3))
_deep("stack_r3_ax1", op_fn("stack", axis=1), u(R3), u(R3))
_deep("where_r3", lambda c, a, b: invoke("where", c, a, b),
      (u(R3) > 0).astype(np.float32), u(R3), u(R3))
_deep("take_r3_ax1", lambda d, i: invoke("take", d, i, axis=1),
      u(R3), ints((2, 2), 3))
_deep("take_r3_ax2", lambda d, i: invoke("take", d, i, axis=2),
      u(R3), ints((2,), 4))
_deep("dot_batched", op_fn("batch_dot"), u((3, 2, 4)), u((3, 4, 5)))
_deep("dot_Ta", op_fn("dot", transpose_a=True), u((4, 2)), u((4, 5)))
_deep("dot_Tb", op_fn("dot", transpose_b=True), u((2, 4)), u((5, 4)))
_deep("sum_negax_r4", op_fn("sum", axis=(-2, -1)), u(R4))
_deep("LayerNorm_r3_ax1",
      lambda x, g, b: invoke("LayerNorm", x, g, b, axis=1),
      u(R3), pos((3,)), u((3,)))
_deep("L2Normalization_r3",
      op_fn("L2Normalization", mode="channel"), away0(R3, lo=0.3))
# sum() over a batch-normalised tensor is translation-invariant (true
# input-gradient ~ 0, so the default sum head only measures noise); a
# fixed random weighting makes the head generic
_BN_W = u((2, 3, 4, 4), lo=0.5, hi=1.5)
_deep("BatchNorm_r4_train",
      lambda x, g, b: invoke(
          "BatchNorm", x, g, b,
          mx.nd.zeros(3).data, mx.nd.ones(3).data, training=True,
          fix_gamma=False, output_mean_var=False, axis=1)[0]
      * mx.nd.array(_BN_W),
      u((2, 3, 4, 4), lo=0.2, hi=1.0), pos((3,)), u((3,)),
      atol=0.02)  # x-grad is a near-cancellation in f32 one-pass var
_deep("BatchNorm_r4_axis3",
      lambda x, g, b: invoke(
          "BatchNorm", x, g, b,
          mx.nd.zeros(4).data, mx.nd.ones(4).data, training=True,
          fix_gamma=False, output_mean_var=False, axis=3)[0]
      * mx.nd.array(_BN_W),
      u((2, 3, 4, 4), lo=0.2, hi=1.0), pos((4,)), u((4,)),
      atol=0.02)


_R.set_state(_SAVED_STATE)


@pytest.mark.parametrize("label", sorted(DEEP))
def test_gradient_deep(label):
    fn, inputs, atol = DEEP[label]
    arrays = [mx.nd.array(x) for x in inputs]
    # slightly looser atol than the base sweep: f32 central differences
    # at eps=1e-3 carry ~1e-3 noise on the larger rank-3/4 reductions;
    # wiring bugs produce O(1) errors either way
    check_numeric_gradient(fn, arrays, eps=1e-3, rtol=2e-2, atol=atol)


# bf16 spot checks: numerically sensitive ops must produce tape grads in
# bfloat16 that track the float32 grads (numeric differencing at bf16
# resolution is meaningless, so this is a consistency check, not a
# central-difference one)
_BF16_OPS = ["exp", "log", "sigmoid", "tanh", "erf", "rsqrt", "softmax",
             "log_softmax", "sqrt", "square", "relu", "mean"]


@pytest.mark.parametrize("name", _BF16_OPS)
def test_gradient_bf16_consistency(name):
    # own RNG: drawing from the shared _R here would shift the base
    # SPECS' test-time sequences (defeating the save/restore above);
    # crc32 (not hash(): salted per-process) keeps draws reproducible
    import zlib

    rng = np.random.RandomState(zlib.crc32(name.encode()) % (2**31))
    if name in ("log", "rsqrt", "sqrt"):
        x32 = rng.uniform(0.3, 1.5, R3).astype(np.float32)
    else:
        x32 = rng.uniform(-1.0, 1.0, R3).astype(np.float32)
    fn = op_fn(name)

    def grad_of(arr):
        arr.attach_grad()
        with mx.autograd.record():
            out = fn(arr)
        out.backward()
        return arr.grad.asnumpy().astype(np.float32)

    g32 = grad_of(mx.nd.array(x32))
    g16 = grad_of(mx.nd.array(x32).astype("bfloat16"))
    np.testing.assert_allclose(g16, g32, rtol=0.05, atol=0.02)


@pytest.mark.parametrize("name", sorted(SPECS))
def test_gradient(name):
    fn, inputs = SPECS[name]()
    arrays = [mx.nd.array(x) for x in inputs]
    check_numeric_gradient(fn, arrays, eps=1e-3, rtol=2e-2, atol=2e-3)
