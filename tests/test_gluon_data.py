"""gluon.data tests (reference model: test_gluon_data.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.data import (
    ArrayDataset,
    BatchSampler,
    DataLoader,
    RandomSampler,
    SequentialSampler,
    SimpleDataset,
)


def test_array_dataset():
    X = np.arange(20).reshape(10, 2)
    Y = np.arange(10)
    ds = ArrayDataset(X, Y)
    assert len(ds) == 10
    x, y = ds[3]
    np.testing.assert_array_equal(x, [6, 7])
    assert y == 3


def test_dataset_transform():
    ds = SimpleDataset(list(range(5))).transform(lambda x: x * 2)
    assert ds[2] == 4
    ds2 = ArrayDataset(np.arange(4), np.arange(4)).transform_first(
        lambda x: x + 100)
    x, y = ds2[1]
    assert x == 101 and y == 1


def test_dataset_filter_shard_take():
    ds = SimpleDataset(list(range(10)))
    f = ds.filter(lambda x: x % 2 == 0)
    assert len(f) == 5
    s0 = ds.shard(3, 0)
    s1 = ds.shard(3, 1)
    s2 = ds.shard(3, 2)
    assert len(s0) + len(s1) + len(s2) == 10
    assert len(ds.take(4)) == 4


def test_samplers():
    assert list(SequentialSampler(5)) == [0, 1, 2, 3, 4]
    r = list(RandomSampler(100))
    assert sorted(r) == list(range(100))
    bs = BatchSampler(SequentialSampler(7), 3, "keep")
    assert [len(b) for b in bs] == [3, 3, 1]
    bs = BatchSampler(SequentialSampler(7), 3, "discard")
    assert [len(b) for b in bs] == [3, 3]
    bs = BatchSampler(SequentialSampler(7), 3, "rollover")
    assert [len(b) for b in bs] == [3, 3]
    assert [len(b) for b in bs] == [3, 3]  # rolled-over 1 + 7 = 8 -> 2x3


def test_dataloader_single_process():
    X = np.random.rand(10, 3).astype(np.float32)
    Y = np.arange(10).astype(np.float32)
    loader = DataLoader(ArrayDataset(X, Y), batch_size=4, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 3)
    assert batches[2][0].shape == (2, 3)
    assert isinstance(batches[0][0], mx.NDArray)


def test_dataloader_shuffle():
    X = np.arange(100).astype(np.float32)
    loader = DataLoader(ArrayDataset(X, X), batch_size=100, shuffle=True)
    (x, _), = list(loader)
    assert not np.array_equal(x.asnumpy(), np.arange(100))
    assert sorted(x.asnumpy().tolist()) == list(range(100))


def test_dataloader_multiworker():
    X = np.random.rand(12, 3).astype(np.float32)
    Y = np.arange(12).astype(np.float32)
    loader = DataLoader(ArrayDataset(X, Y), batch_size=4, num_workers=2)
    total = 0
    seen = []
    for x, y in loader:
        total += x.shape[0]
        seen.extend(y.asnumpy().tolist())
    assert total == 12
    assert sorted(seen) == list(range(12))
    # second epoch works
    assert sum(x.shape[0] for x, _ in loader) == 12


def test_dataloader_batchify_fn():
    def batchify(samples):
        xs = [s for s in samples]
        return mx.nd.array(np.stack(xs))

    loader = DataLoader(SimpleDataset([np.ones(2, np.float32) * i
                                       for i in range(6)]),
                        batch_size=2, batchify_fn=batchify)
    b = next(iter(loader))
    assert b.shape == (2, 2)


def test_record_file_dataset(tmp_path):
    from mxnet_tpu import recordio

    rec = str(tmp_path / "d.rec")
    idx = str(tmp_path / "d.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(5):
        w.write_idx(i, f"data{i}".encode())
    w.close()
    ds = gluon.data.RecordFileDataset(rec)
    assert len(ds) == 5
    assert ds[2] == b"data2"


def test_transforms_compose():
    from mxnet_tpu.gluon.data.vision import transforms

    t = transforms.Compose([transforms.ToTensor(),
                            transforms.Normalize(0.5, 0.5)])
    img = mx.nd.array((np.random.rand(8, 8, 3) * 255).astype(np.uint8),
                      dtype="uint8")
    out = t(img)
    assert out.shape == (3, 8, 8)
    assert out.asnumpy().min() >= -1.001 and out.asnumpy().max() <= 1.001


def test_transforms_geometric():
    from mxnet_tpu.gluon.data.vision import transforms

    img = mx.nd.array((np.random.rand(30, 40, 3) * 255).astype(np.uint8),
                      dtype="uint8")
    assert transforms.Resize(16)(img).shape == (16, 16, 3)
    assert transforms.CenterCrop(20)(img).shape == (20, 20, 3)
    assert transforms.RandomResizedCrop(14)(img).shape == (14, 14, 3)
    assert transforms.RandomFlipLeftRight(1.0)(img).shape == (30, 40, 3)
    np.testing.assert_array_equal(
        transforms.RandomFlipLeftRight(1.0)(img).asnumpy(),
        img.asnumpy()[:, ::-1])


# ---------------------------------------------------------------------------
# gluon.contrib.data.text (reference: contrib/data/text.py)
# ---------------------------------------------------------------------------


def test_corpus_dataset_next_token_layout(tmp_path):
    from mxnet_tpu.gluon.contrib.data import CorpusDataset

    f = tmp_path / "c.txt"
    f.write_text("a b c d e f g h\n")
    ds = CorpusDataset(str(f), seq_len=3)
    x, y = ds[0]
    # label is data shifted one token left (next-token prediction)
    ids = ds.vocabulary.to_indices("a b c d e f g h".split() + ["<eos>"])
    assert x.asnumpy().tolist() == ids[:3]
    assert y.asnumpy().tolist() == ids[1:4]
    assert len(ds) == (9 - 1) // 3


def test_wikitext_local_files_and_loader(tmp_path):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.contrib.data import WikiText2

    words = "the quick brown fox jumps over the lazy dog".split()
    text = "\n".join(" ".join(words) for _ in range(20))
    (tmp_path / "wiki.train.tokens").write_text(text)
    (tmp_path / "wiki.valid.tokens").write_text(text)
    train = WikiText2(root=str(tmp_path), segment="train", seq_len=5)
    # validation reuses the train vocabulary (reference behavior)
    val = WikiText2(root=str(tmp_path), segment="validation", seq_len=5,
                    vocab=train.vocabulary)
    assert val.vocabulary is train.vocabulary
    loader = gluon.data.DataLoader(train, batch_size=4, last_batch="discard")
    xb, yb = next(iter(loader))
    assert xb.shape == (4, 5) and yb.shape == (4, 5)
    # ids in range for an Embedding of vocab size
    assert int(xb.asnumpy().max()) < len(train.vocabulary)


def test_wikitext_missing_files_raise(tmp_path):
    import pytest as _pytest

    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.gluon.contrib.data import WikiText103

    with _pytest.raises(MXNetError, match="token file"):
        WikiText103(root=str(tmp_path))


def test_transforms_reproducible_under_seed():
    """Photometric transforms route through the _image_* ops and the
    framework key stream, so mx.random.seed pins the augmentation."""
    from mxnet_tpu.gluon.data.vision import transforms

    aug = transforms.Compose([
        transforms.RandomFlipLeftRight(),
        transforms.RandomColorJitter(brightness=0.4, contrast=0.3,
                                     saturation=0.3, hue=0.1),
        transforms.RandomLighting(0.1),
    ])
    x = mx.nd.array(np.random.RandomState(0)
                    .randint(0, 255, (8, 8, 3)).astype(np.float32))
    mx.random.seed(11)
    a = aug(x).asnumpy()
    mx.random.seed(11)
    b = aug(x).asnumpy()
    np.testing.assert_allclose(a, b)
    mx.random.seed(12)
    c = aug(x).asnumpy()
    assert not np.allclose(a, c)
