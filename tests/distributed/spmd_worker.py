"""Multi-process x multi-device SPMD train-step worker (VERDICT r3 item
8: the real v5e topology is N hosts x M local chips; the launcher tests
only covered N procs x 1 device and the dryrun 1 proc x 8 devices).

Run under ``tools/launch.py -n 2`` with
``XLA_FLAGS=--xla_force_host_platform_device_count=4`` -> a 2-process,
8-device global mesh running the fused ``SPMDTrainStep`` with dp x tp
sharding. Also runs standalone (1 process, 8 local devices) as the
equivalence reference: the final loss must match the multi-process run
bit-for-bit (same global batch, same init, same update order).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from jax.sharding import PartitionSpec as P

if "MXTPU_COORDINATOR" in os.environ:
    from mxnet_tpu.kvstore.dist import init_distributed

    init_distributed()
    nprocs = int(os.environ["MXTPU_NUM_PROCESSES"])
    rank = int(os.environ["MXTPU_PROCESS_ID"])
    assert jax.process_count() == nprocs, (jax.process_count(), nprocs)
else:
    nprocs, rank = 1, 0

assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 8 // nprocs

mesh = parallel.make_mesh({"dp": 4, "tp": 2})

# deterministic model: params from a fixed seed on every process
rng = np.random.RandomState(0)
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Dense(32, activation="relu", in_units=16),
        gluon.nn.Dense(8, in_units=32))
net.initialize(init=mx.initializer.Constant(0.0))
for name, p in sorted(net.collect_params().items()):
    p.set_data(mx.nd.array(rng.uniform(-0.2, 0.2, p.shape)
                           .astype(np.float32)))

# tensor-parallel shardings for the hidden layer, dp batch sharding
sharding = {}
for name in net.collect_params():
    if "dense0_weight" in name:
        sharding[name] = P("tp", None)   # (32, 16) row-sharded over tp
    elif "dense0_bias" in name:
        sharding[name] = P("tp")
    elif "dense1_weight" in name:
        sharding[name] = P(None, "tp")   # (8, 32) col-sharded over tp
loss_fn = gluon.loss.L2Loss()
step = parallel.SPMDTrainStep(net, loss_fn, "sgd", {"momentum": 0.9},
                              mesh=mesh, batch_axis="dp",
                              param_sharding=sharding)

X = rng.uniform(-1, 1, (16, 16)).astype(np.float32)
Y = rng.uniform(-1, 1, (16, 8)).astype(np.float32)

first = None
loss = None
for it in range(20):
    loss = float(step(mx.nd.array(X), mx.nd.array(Y), lr=0.2))
    if first is None:
        first = loss
final = loss
assert final < first, (first, final)  # it actually trains
print(f"SPMD_WORKER_OK rank={rank}/{nprocs} loss={final:.10f}", flush=True)
