"""Real multi-process dist_tpu_sync tests (reference:
``tests/nightly/dist_sync_kvstore.py`` launched via ``tools/launch.py -n N
--launcher local``, SURVEY.md §4).

Each test spawns N CPU worker processes through the actual launcher so the
env contract (MXTPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID), PJRT
coordination bootstrap, the psum allreduce, barrier, and compression all
run with ``jax.process_count() > 1`` for real.
"""

import os
import socket
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
LAUNCH = os.path.join(ROOT, "tools", "launch.py")
WORKER = os.path.join(ROOT, "tests", "distributed", "dist_worker.py")

#: HARD per-test wall budget (seconds) for every launcher subprocess —
#: a hung PJRT coordination handshake or dead-peer barrier must fail
#: THIS test loudly (with captured output) instead of burning the whole
#: tier-1 suite budget waiting on a 300-900 s default timeout. 0
#: disables the cap (soak runs).
DIST_TEST_TIMEOUT_S = int(os.environ.get("MXTPU_DIST_TEST_TIMEOUT", "120"))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _tail(out, n=4000):
    if out is None:
        return "<none captured>"
    if isinstance(out, bytes):
        out = out.decode(errors="replace")
    return out[-n:]


def _run_capped(cmd, env, timeout, what, cap=True):
    """subprocess.run with the hard cap + a diagnostic-rich failure:
    on timeout the test FAILS (not errors out of budget) with the
    partial stdout/stderr attached — 'which rank hung and on what' is
    readable straight from the pytest report. ``cap=False`` keeps the
    caller's full budget (the single-process REFERENCE workers pass
    today and may legitimately need their long cold-compile timeouts
    on a loaded host — only the multiprocess launcher runs, the known
    hang risk, get the hard cap)."""
    t = timeout if (DIST_TEST_TIMEOUT_S <= 0 or not cap) \
        else min(timeout, DIST_TEST_TIMEOUT_S)
    try:
        return subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=t)
    except subprocess.TimeoutExpired as e:
        pytest.fail(
            f"{what} exceeded the hard {t}s budget "
            f"(MXTPU_DIST_TEST_TIMEOUT={DIST_TEST_TIMEOUT_S}) — a "
            "worker is hung (PJRT coordination / collective / barrier "
            "never completed) rather than failing.\n"
            f"stdout tail:\n{_tail(e.stdout)}\n"
            f"stderr tail:\n{_tail(e.stderr)}", pytrace=False)


def _base_env(ndev=None, **extra):
    """CPU-backed env for launcher subprocesses: strips the conftest's
    8-device force flag (each worker decides its own device count via
    ``ndev``). THE shared copy — every dist test builds on this so the
    env contract changes in exactly one place."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)
    if ndev is not None:
        flags += f" --xla_force_host_platform_device_count={ndev}"
    env["XLA_FLAGS"] = flags
    env.update(extra)
    return env


def _launch(worker, nworkers, env=None, timeout=300):
    return _run_capped(
        [sys.executable, LAUNCH, "-n", str(nworkers),
         "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, worker],
        env if env is not None else _base_env(), timeout,
        f"launcher run of {os.path.basename(worker)} x{nworkers}")


def _run_launcher(nworkers, timeout=300):
    return _launch(WORKER, nworkers, timeout=timeout)


@pytest.mark.dist_baseline
@pytest.mark.parametrize("nworkers", [2, 3])
def test_dist_tpu_sync_multiprocess(nworkers):
    res = _run_launcher(nworkers)
    assert res.returncode == 0, (
        f"launcher rc={res.returncode}\nstdout:\n{res.stdout[-4000:]}\n"
        f"stderr:\n{res.stderr[-4000:]}")
    for rank in range(nworkers):
        assert f"DIST_WORKER_OK rank={rank}/{nworkers}" in res.stdout, (
            f"rank {rank} missing OK line\nstdout:\n{res.stdout[-4000:]}")


FM_WORKER = os.path.join(ROOT, "tests", "distributed", "fm_worker.py")


@pytest.mark.dist_baseline
def test_fm_sparse_dist_training():
    """BASELINE config #4: FM converges on synthetic CTR under
    tools/launch.py -n 2 with row_sparse gradient pushes, and all ranks
    end with identical parameters."""
    res = _launch(FM_WORKER, 2, timeout=600)
    assert res.returncode == 0, (
        f"launcher rc={res.returncode}\nstdout:\n{res.stdout[-4000:]}\n"
        f"stderr:\n{res.stderr[-4000:]}")
    import re

    checks = re.findall(r"FM_WORKER_OK rank=(\d)/2 .*? checksum=([0-9.]+)",
                        res.stdout)
    assert len(checks) == 2, res.stdout[-2000:]
    assert checks[0][1] == checks[1][1], checks  # bit-identical params


CKPT_WORKER = os.path.join(ROOT, "tests", "distributed", "ckpt_worker.py")


@pytest.mark.dist_baseline
def test_sharded_checkpoint_multiprocess(tmp_path):
    """spmd_save_states/load_states across 2 REAL processes: each rank
    writes only its addressable shards (ZeRO moments split), restore is
    bit-exact on every rank."""
    res = _launch(CKPT_WORKER, 2,
                  env=_base_env(MXTPU_TEST_CKPT_DIR=str(tmp_path)))
    assert res.returncode == 0, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-4000:]}\n"
        f"stderr:\n{res.stderr[-4000:]}")
    for rank in range(2):
        assert f"CKPT_WORKER_OK rank={rank}/2" in res.stdout, res.stdout[-2000:]


SPMD_WORKER = os.path.join(ROOT, "tests", "distributed", "spmd_worker.py")


@pytest.mark.dist_baseline
@pytest.mark.parametrize("nprocs,ndev", [(2, 4), (4, 2)])
def test_spmd_step_multiprocess_multidevice(nprocs, ndev):
    """VERDICT r3 item 8: the real pod topology is N hosts x M local
    chips. Run the fused SPMDTrainStep on an N-process x M-device global
    mesh (8 devices total, dp=4 x tp=2) and assert the final loss equals
    a 1-process 8-device run of the same program."""
    # reference: single process, 8 local devices
    ref = _run_capped([sys.executable, SPMD_WORKER], _base_env(8), 300,
                      "spmd reference worker (1 proc x 8 dev)", cap=False)
    assert ref.returncode == 0, ref.stderr[-3000:]
    import re

    ref_loss = re.search(r"loss=([0-9.]+)", ref.stdout).group(1)

    # N processes x M devices each over the launcher
    res = _launch(SPMD_WORKER, nprocs, env=_base_env(ndev), timeout=600)
    assert res.returncode == 0, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-4000:]}\n"
        f"stderr:\n{res.stderr[-4000:]}")
    losses = re.findall(rf"SPMD_WORKER_OK rank=\d/{nprocs} loss=([0-9.]+)",
                        res.stdout)
    assert len(losses) == nprocs, res.stdout[-2000:]
    assert len(set(losses)) == 1, losses  # every rank sees the same loss
    import numpy as _np

    _np.testing.assert_allclose(float(losses[0]), float(ref_loss),
                                rtol=1e-5, atol=1e-7)


OVERLAP_WORKER = os.path.join(ROOT, "tests", "distributed",
                              "overlap_worker.py")


@pytest.mark.dist_baseline
def test_overlap_zero_multiprocess():
    """PR10 overlap correctness on a REAL 2-process mesh: barrier-mode
    and bucket-ready-mode training are bit-identical, ZeRO-2 matches
    ZeRO-0, and the multi-process run agrees with the 1-process
    4-device reference."""
    import re

    ref = _run_capped([sys.executable, OVERLAP_WORKER], _base_env(4), 300,
                      "overlap reference worker (1 proc x 4 dev)",
                      cap=False)
    assert ref.returncode == 0, ref.stderr[-3000:]
    m = re.search(r"loss=([0-9.]+) checksum=([0-9.]+)", ref.stdout)
    assert m, ref.stdout[-2000:]
    ref_loss, ref_sum = m.groups()

    res = _launch(OVERLAP_WORKER, 2, env=_base_env(2), timeout=600)
    if res.returncode != 0 and \
            "Multiprocess computations aren't implemented" in res.stderr:
        # the documented environmental limitation behind the 8
        # dist_baseline failures (this container's XLA:CPU cannot run
        # cross-process collectives) — the 1-process 4-device reference
        # leg above already pinned the overlap/ZeRO parity claims, so
        # skip rather than grow the environmental-failure baseline
        pytest.skip("XLA:CPU cannot run multiprocess collectives here "
                    "(dist_baseline environment)")
    assert res.returncode == 0, (
        f"rc={res.returncode}\nstdout:\n{res.stdout[-4000:]}\n"
        f"stderr:\n{res.stderr[-4000:]}")
    got = re.findall(r"OVERLAP_WORKER_OK rank=\d/2 loss=([0-9.]+) "
                     r"checksum=([0-9.]+)", res.stdout)
    assert len(got) == 2, res.stdout[-2000:]
    assert got[0] == got[1], got  # ranks agree bit-for-bit
    import numpy as _np

    _np.testing.assert_allclose(float(got[0][0]), float(ref_loss),
                                rtol=1e-5, atol=1e-7)
    _np.testing.assert_allclose(float(got[0][1]), float(ref_sum),
                                rtol=1e-5)


PP_EP_WORKER = os.path.join(ROOT, "tests", "distributed", "pp_ep_worker.py")


@pytest.mark.dist_baseline
@pytest.mark.parametrize("nprocs,ndev", [(2, 4), (4, 2)])
def test_pp_ep_multiprocess_multidevice(nprocs, ndev):
    """VERDICT r5 #9: pipeline (pp) and MoE (ep) under REAL multi-process
    SPMD, not only the single-process dryrun: the GPipe grad step and the
    expert-parallel forward must produce the same scalars on an
    N-process x M-device global mesh as on 1 process x 8 devices."""
    import re

    ref = _run_capped([sys.executable, PP_EP_WORKER], _base_env(8), 600,
                      "pp/ep reference worker (1 proc x 8 dev)", cap=False)
    assert ref.returncode == 0, ref.stderr[-3000:]
    m = re.search(r"PP_EP_OK rank=0/1 (.*)", ref.stdout)
    assert m, (f"reference worker printed no OK line\nstdout:\n"
               f"{ref.stdout[-2000:]}\nstderr:\n{ref.stderr[-2000:]}")
    ref_line = m.group(1)

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        res = _launch(PP_EP_WORKER, nprocs,
                      env=_base_env(ndev, MXTPU_TEST_OUTDIR=td),
                      timeout=900)
        assert res.returncode == 0, (
            f"rc={res.returncode}\nstdout:\n{res.stdout[-4000:]}\n"
            f"stderr:\n{res.stderr[-4000:]}")
        lines = []
        for r in range(nprocs):
            with open(os.path.join(td, f"rank{r}.txt")) as f:
                m = re.search(rf"PP_EP_OK rank={r}/{nprocs} (.*)",
                              f.read())
                assert m, f"rank {r} output malformed"
                lines.append(m.group(1).strip())
    assert len(set(lines)) == 1, lines  # all ranks agree
    ref_vals = [float(x.split("=")[1]) for x in ref_line.split()]
    got_vals = [float(x.split("=")[1]) for x in lines[0].split()]
    import numpy as _np

    _np.testing.assert_allclose(got_vals, ref_vals, rtol=1e-5)


FED_WORKER = os.path.join(ROOT, "tests", "distributed", "fed_worker.py")


@pytest.mark.dist_baseline
def test_metric_federation_multiprocess():
    """PR15 tentpole: cross-rank metric federation rides the kvstore
    collective side-channel on a REAL 2-process world — one
    ``exchange()`` and every rank's cluster table carries every peer's
    series plus the job aggregates (the worker asserts per rank; the
    single-process merge semantics are pinned in
    ``tests/test_federation.py``)."""
    res = _launch(FED_WORKER, 2, timeout=600)
    if res.returncode != 0 and \
            "Multiprocess computations aren't implemented" in res.stderr:
        # the documented environmental limitation behind the
        # dist_baseline failures (this container's XLA:CPU cannot run
        # cross-process collectives) — the single-process federation
        # suite already pinned snapshot/merge/exposition semantics
        pytest.skip("XLA:CPU cannot run multiprocess collectives here "
                    "(dist_baseline environment)")
    assert res.returncode == 0, (
        f"launcher rc={res.returncode}\nstdout:\n{res.stdout[-4000:]}\n"
        f"stderr:\n{res.stderr[-4000:]}")
    for rank in range(2):
        assert f"FED_WORKER_OK rank={rank}/2" in res.stdout, (
            f"rank {rank} missing OK line\nstdout:\n{res.stdout[-4000:]}")
