"""Multi-process dist_tpu_sync worker (reference analog:
``tests/nightly/dist_sync_kvstore.py`` run under ``tools/launch.py``).

Spawned by ``tests/distributed/test_dist_tpu_sync.py`` via ``tools/launch.py -n N``.
Each rank runs the same assertions against analytically-known aggregates;
any assertion failure exits nonzero and fails the launching pytest.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.kvstore.dist import init_distributed

init_distributed()  # picks up the MXTPU_* env contract from tools/launch.py

rank = int(os.environ["MXTPU_PROCESS_ID"])
nworkers = int(os.environ["MXTPU_NUM_PROCESSES"])
assert jax.process_count() == nworkers, (jax.process_count(), nworkers)
assert jax.process_index() == rank

kv = mx.kv.create("dist_tpu_sync")
assert kv.rank == rank and kv.num_workers == nworkers

SHAPE = (4, 5)


def full(v):
    return mx.nd.array(np.full(SHAPE, v, np.float32))


# 1) init consistency: ranks propose different values; rank 0's must win
kv.init("w", full(7.0 + rank))
out = mx.nd.zeros(SHAPE)
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 7.0, np.float32))
kv.barrier()

# 2) push -> cross-process sum visible on every rank
kv.push("w", full(rank + 1.0))
kv.pull("w", out=out)
expect = nworkers * (nworkers + 1) / 2.0
np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, expect, np.float32))
kv.barrier()

# 3) pushpull(out=...) — Trainer allreduce path; store stays untouched
grad = full(2.0 * (rank + 1))
kv.pushpull("w", grad, out=grad)
np.testing.assert_allclose(grad.asnumpy(),
                           np.full(SHAPE, 2.0 * expect, np.float32))
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, expect, np.float32))
kv.barrier()

# 4) updater runs on the globally-summed gradient, identically on all ranks
kv2 = mx.kv.create("dist_tpu_sync")
kv2.init("u", full(1.0))


def updater(key, grad, weight):
    weight -= 0.1 * grad


kv2.set_updater(updater)
kv2.push("u", full(1.0))  # global grad = nworkers
kv2.pull("u", out=out)
np.testing.assert_allclose(
    out.asnumpy(), np.full(SHAPE, 1.0 - 0.1 * nworkers, np.float32), rtol=1e-6)
kv2.barrier()

# 5) row_sparse_pull after a distributed push
kv.init("emb", mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3)))
emb_out = mx.nd.zeros((4, 3))
kv.row_sparse_pull("emb", out=emb_out, row_ids=mx.nd.array([1, 3]))
expected = np.zeros((4, 3), np.float32)
expected[[1, 3]] = np.arange(12, dtype=np.float32).reshape(4, 3)[[1, 3]]
np.testing.assert_allclose(emb_out.asnumpy(), expected)
kv.barrier()

# 6) 2-bit gradient compression applied BEFORE the wire, with residuals
kv3 = mx.kv.create("dist_tpu_sync")
kv3.init("c", full(0.0))
kv3.set_gradient_compression({"type": "2bit", "threshold": 0.5})
kv3.push("c", full(1.0))  # each rank quantizes 1.0 -> 0.5, residual 0.5
kv3.pull("c", out=out)
np.testing.assert_allclose(out.asnumpy(),
                           np.full(SHAPE, 0.5 * nworkers, np.float32))
kv3.push("c", full(0.25))  # residual 0.5 + 0.25 >= thr -> 0.5 again
kv3.pull("c", out=out)
np.testing.assert_allclose(out.asnumpy(),
                           np.full(SHAPE, 0.5 * nworkers, np.float32))
kv3.barrier()

print(f"DIST_WORKER_OK rank={rank}/{nworkers}", flush=True)
