"""Multi-process metric-federation worker (PR-15 tentpole).

Spawned by ``tests/distributed/test_dist_tpu_sync.py`` via
``tools/launch.py -n N``. Every rank emits a rank-distinct counter
value, runs one ``federation.exchange()`` over the kvstore collective
side-channel, and asserts — ON EVERY RANK (the gather is symmetric) —
that the merged cluster table carries every peer's series plus the
job-level aggregates."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import re

import mxnet_tpu as mx
from mxnet_tpu import observability as obs
from mxnet_tpu.kvstore.dist import init_distributed
from mxnet_tpu.observability import federation as fed

init_distributed()  # picks up the MXTPU_* env contract from tools/launch.py

rank = int(os.environ["MXTPU_PROCESS_ID"])
nworkers = int(os.environ["MXTPU_NUM_PROCESSES"])
assert jax.process_count() == nworkers, (jax.process_count(), nworkers)
assert jax.process_index() == rank

kv = mx.kv.create("dist_tpu_sync")  # warms the collective channel

obs.set_enabled(True)
obs.TRAINER_STEP_TOTAL.inc(rank + 1)           # rank-distinct counter
obs.TRAINER_GRAD_NORM.set(float(rank + 1))     # rank-distinct gauge
obs.TRAINER_STEP_SECONDS.observe(0.01 * (rank + 1))
for _ in range(rank + 1):
    obs.tracer().mark_step()                   # rank-distinct step_epoch

got = fed.exchange()
assert got == nworkers, (got, nworkers)
assert fed.cluster_ranks() == list(range(nworkers)), fed.cluster_ranks()

text = fed.cluster_registry().dump_prometheus()


def val(metric, **labels):
    want = "{" + ",".join(f'{k}="{v}"' for k, v in
                          sorted(labels.items())) + "}"
    m = re.search(re.escape(metric + want) + r" ([-0-9.e+]+)", text)
    assert m, f"{metric}{want} missing from cluster exposition"
    return float(m.group(1))


# every peer's series present, labeled by its rank
for r in range(nworkers):
    assert val("mxtpu_trainer_step_total", rank=str(r)) == r + 1
# counters SUM across ranks
assert val("mxtpu_trainer_step_total",
           rank="all") == nworkers * (nworkers + 1) / 2
# gauges aggregate min/max across ranks
assert val("mxtpu_trainer_grad_norm", agg="min", rank="all") == 1.0
assert val("mxtpu_trainer_grad_norm", agg="max", rank="all") == nworkers
# histograms merge: the job count is the sum of per-rank counts
assert val("mxtpu_trainer_step_seconds_count", rank="all") == nworkers

# per-rank step_epoch rode the snapshots (the cross-rank skew picture)
stale = fed.update_cluster_meta()
assert stale == [], stale
assert obs.FEDERATION_LAST_STEP.value(rank=str(nworkers - 1)) == nworkers

kv.barrier()  # nobody exits before every rank finished asserting
print(f"FED_WORKER_OK rank={rank}/{nworkers}", flush=True)
