"""Multi-process overlap/ZeRO worker: barrier-mode vs bucket-ready
overlapped-mode training must be bit-identical, and ZeRO-2 must match
ZeRO-0, on a REAL multi-process mesh (2 procs x 2 devices under
``tools/launch.py -n 2`` with
``XLA_FLAGS=--xla_force_host_platform_device_count=2``). Also runs
standalone (1 proc x 4 devices) as the single-process reference."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel

if "MXTPU_COORDINATOR" in os.environ:
    from mxnet_tpu.kvstore.dist import init_distributed

    init_distributed()
    nprocs = int(os.environ["MXTPU_NUM_PROCESSES"])
    rank = int(os.environ["MXTPU_PROCESS_ID"])
else:
    nprocs, rank = 1, 0

assert jax.device_count() == 4, jax.device_count()
mesh = parallel.make_mesh({"dp": 4})
loss_fn = gluon.loss.L2Loss()

rng = np.random.RandomState(0)
X = rng.uniform(-1, 1, (16, 16)).astype(np.float32)
Y = rng.uniform(-1, 1, (16, 8)).astype(np.float32)


def build():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu", in_units=16),
            gluon.nn.Dense(8, in_units=32))
    net.initialize(init=mx.initializer.Constant(0.0))
    r = np.random.RandomState(1)
    for _, p in sorted(net.collect_params().items()):
        p.set_data(mx.nd.array(r.uniform(-0.2, 0.2, p.shape)
                               .astype(np.float32)))
    return net


def run(mode, stage=0, steps=10):
    mx.random.seed(5)
    net = build()
    step = parallel.SPMDTrainStep(net, loss_fn, "adam", {}, mesh,
                                  overlap=mode, zero_stage=stage)
    loss = None
    for _ in range(steps):
        loss = float(step(mx.nd.array(X), mx.nd.array(Y), lr=0.05))
    step.sync_to_block()
    csum = float(sum(np.abs(np.asarray(p.data().data)).sum()
                     for _, p in net.collect_params().items()))
    return loss, csum


loss_b, sum_b = run("barrier")
loss_r, sum_r = run("ready")
assert loss_b == loss_r, (loss_b, loss_r)
assert sum_b == sum_r, (sum_b, sum_r)
loss_z2, sum_z2 = run("ready", stage=2)
assert loss_z2 == loss_r, (loss_z2, loss_r)
assert sum_z2 == sum_r, (sum_z2, sum_r)
print(f"OVERLAP_WORKER_OK rank={rank}/{nprocs} loss={loss_r:.10f} "
      f"checksum={sum_r:.8f}", flush=True)
