"""Multi-process sharded-checkpoint worker (launched by
``tests/distributed/test_dist_tpu_sync.py`` via ``tools/launch.py -n 2``).

Proves the spmd_save_states/load_states design claims on a REAL
multi-process mesh: each process writes only its addressable shards
(ZeRO-sharded Adam moments live split across processes; replicated
params are written by replica 0 only), and restore reassembles them
under the live sharding with a bit-exact training resume on every rank.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, ROOT)

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.kvstore.dist import init_distributed

init_distributed()

rank = jax.process_index()
n = jax.process_count()
assert n == int(os.environ["MXTPU_NUM_PROCESSES"]), (n, os.environ.get("MXTPU_NUM_PROCESSES"))
mesh = parallel.make_mesh({"dp": n})

net = gluon.nn.Dense(6, in_units=4)
mx.random.seed(7)  # same init on every rank
net.initialize()
step = parallel.SPMDTrainStep(net, gluon.loss.L2Loss(), "adam", {},
                              mesh=mesh, shard_opt_states=True)

rng = np.random.RandomState(0)
x = mx.nd.array(rng.rand(2 * n, 4).astype(np.float32))
y = mx.nd.array(rng.rand(2 * n, 6).astype(np.float32))

for _ in range(3):
    step(x, y, lr=0.05)

ckpt_dir = os.environ["MXTPU_TEST_CKPT_DIR"]
prefix = os.path.join(ckpt_dir, "state")
fname = step.save_states(prefix)
assert fname.endswith(f".shard{rank}.npz"), fname

# every process must have written a file; ZeRO moments are genuinely
# split (each process's file holds only its slice of the weight moment)
from mxnet_tpu.kvstore.dist import _global_allreduce

_global_allreduce(np.ones((1,), np.float32))  # acts as a barrier
import glob

files = sorted(glob.glob(prefix + ".shard*.npz"))
assert len(files) == n, files
with np.load(files[rank]) as z:
    my_keys = [k for k in z.files if k.startswith("opt::") and
               "weight" in k and z[k].ndim == 2]
    assert my_keys, "expected a local ZeRO moment shard in this file"
    for k in my_keys:
        with np.load(files[rank]) as z2:
            assert z2[k].shape[0] == 6 // n, (k, z2[k].shape)

loss_cont = step(x, y, lr=0.05)

# fresh step, restore, resume — must match loss_cont exactly on all ranks
step2 = parallel.SPMDTrainStep(net, gluon.loss.L2Loss(), "adam", {},
                               mesh=mesh, shard_opt_states=True)
step2.init_state()
step2.load_states(prefix)
loss_resume = step2(x, y, lr=0.05)
assert abs(loss_cont - loss_resume) < 1e-6, (loss_cont, loss_resume)

# automated multi-host commit coordination: save_spmd_checkpoint with
# NO explicit barrier — default_commit_barrier stages every rank's
# shard, rank 0 alone manifests + commits (exactly once), and every
# rank can restore the committed checkpoint afterwards
from mxnet_tpu import resilience
from mxnet_tpu.resilience import checkpoint as _ckptmod

auto_dir = os.path.join(ckpt_dir, "auto")
out = resilience.save_spmd_checkpoint(auto_dir, step2, step=5)
if rank == 0:
    assert out is not None, "rank 0 must return the committed path"
else:
    assert out is None, f"rank {rank} must not commit"
committed = _ckptmod._committed_steps(auto_dir)
assert committed == [5], committed  # exactly one commit
assert resilience.verify(os.path.join(auto_dir, "step_0000000005")) == []
loss_c2 = step2(x, y, lr=0.05)

step3 = parallel.SPMDTrainStep(net, gluon.loss.L2Loss(), "adam", {},
                               mesh=mesh, shard_opt_states=True)
step3.init_state()
resilience.load_checkpoint(auto_dir, spmd_step=step3)
loss_r2 = step3(x, y, lr=0.05)
assert abs(loss_c2 - loss_r2) < 1e-6, (loss_c2, loss_r2)

print(f"CKPT_WORKER_OK rank={rank}/{n}", flush=True)
