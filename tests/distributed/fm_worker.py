"""Multi-process factorization-machine worker (BASELINE config #4:
sparse embedding grads + dot(csr, dense) + row_sparse push/pull through
dist_tpu_sync; reference analog: example/sparse/factorization_machine
trained with --kv-store dist_sync under tools/launch.py).

Each rank trains on its own shard of the same planted CTR problem; the
row_sparse gradient pushes are summed across workers by the dist store's
psum; every rank must converge AND end bit-identical (same updates seen
everywhere).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.kvstore.dist import init_distributed
from mxnet_tpu.models import fm as fm_mod
from mxnet_tpu.ndarray.sparse import csr_matrix

init_distributed()
rank = int(os.environ["MXTPU_PROCESS_ID"])
nworkers = int(os.environ["MXTPU_NUM_PROCESSES"])

kv = mx.kv.create("dist_tpu_sync")

F = 100
fm = fm_mod.FactorizationMachine(F, num_factors=4, seed=1)
# per-rank shard of the SAME planted model (seed fixes the planted
# weights; sample draw differs by rank via the offset)
vals, indptr, indices, labels = fm_mod.synthetic_ctr(
    120, F, seed=3)
lo, hi = rank * (120 // nworkers), (rank + 1) * (120 // nworkers)
row_slice = slice(lo, hi)
sub_indptr = indptr[lo:hi + 1] - indptr[lo]
sub_idx = indices[indptr[lo]:indptr[hi]]
sub_vals = vals[indptr[lo]:indptr[hi]]
X = csr_matrix((sub_vals, sub_idx, sub_indptr), shape=(hi - lo, F))
y = mx.nd.array(labels[lo:hi])

for name, p in fm.params().items():
    kv.init(name, p)

lr = 0.5


def updater(key, grad, weight):
    # grads arrive SUMMED across workers; average them
    weight._set_data((weight - (lr / nworkers) * grad).data)


kv.set_updater(updater)

first = last = None
for step in range(300):
    l = fm_mod.train_step(fm, X, y, kv=kv)
    if first is None:
        first = l
    last = l

assert last < first * 0.5, (first, last)
pred = np.sign(fm.forward(X).asnumpy())
acc = float((pred == labels[lo:hi]).mean())
assert acc > 0.8, acc
checksum = float(np.abs(fm.v.asnumpy()).sum() + np.abs(fm.w.asnumpy()).sum())
print(f"FM_WORKER_OK rank={rank}/{nworkers} loss {first:.4f}->{last:.4f} "
      f"acc={acc:.2f} checksum={checksum:.6f}", flush=True)
