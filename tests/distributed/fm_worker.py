"""Multi-process factorization-machine worker (BASELINE config #4:
sparse embedding grads + dot(csr, dense) + row_sparse push/pull through
dist_tpu_sync; reference analog: example/sparse/factorization_machine
trained with --kv-store dist_sync under tools/launch.py).

Each rank trains on its own shard of the same planted CTR problem; the
row_sparse gradient pushes are summed across workers by the dist store's
psum; every rank must converge AND end bit-identical (same updates seen
everywhere).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.kvstore.dist import init_distributed
from mxnet_tpu.models import fm as fm_mod

init_distributed()
rank = int(os.environ["MXTPU_PROCESS_ID"])
nworkers = int(os.environ["MXTPU_NUM_PROCESSES"])

kv = mx.kv.create("dist_tpu_sync")

F = 100
fm = fm_mod.FactorizationMachine(F, num_factors=4, seed=1)
# per-rank shard of the SAME planted model (seed fixes the planted
# weights; sample draw differs by rank via the offset). The shard goes
# through a .libsvm FILE and back via mx.io.LibSVMIter — the reference's
# sparse on-disk on-ramp (src/io/iter_libsvm.cc), exercised end-to-end.
vals, indptr, indices, labels = fm_mod.synthetic_ctr(
    120, F, seed=3)
lo, hi = rank * (120 // nworkers), (rank + 1) * (120 // nworkers)
import tempfile

shard_path = os.path.join(tempfile.gettempdir(),
                          f"fm_shard_{os.getpid()}_{rank}.libsvm")
with open(shard_path, "w") as f:
    for r in range(lo, hi):
        feats = " ".join(f"{indices[j]}:{vals[j]:g}"
                         for j in range(indptr[r], indptr[r + 1]))
        f.write(f"{labels[r]:g} {feats}\n")
it = mx.io.LibSVMIter(data_libsvm=shard_path, data_shape=(F,),
                      batch_size=hi - lo)
batch = next(iter(it))
assert batch.data[0].stype == "csr"
X = batch.data[0]
y = batch.label[0]
os.unlink(shard_path)

for name, p in fm.params().items():
    kv.init(name, p)

lr = 0.5


def updater(key, grad, weight):
    # grads arrive SUMMED across workers; average them
    weight._set_data((weight - (lr / nworkers) * grad).data)


kv.set_updater(updater)

first = last = None
for step in range(300):
    l = fm_mod.train_step(fm, X, y, kv=kv)
    if first is None:
        first = l
    last = l

assert last < first * 0.5, (first, last)
pred = np.sign(fm.forward(X).asnumpy())
acc = float((pred == labels[lo:hi]).mean())
assert acc > 0.8, acc
checksum = float(np.abs(fm.v.asnumpy()).sum() + np.abs(fm.w.asnumpy()).sum())
print(f"FM_WORKER_OK rank={rank}/{nworkers} loss {first:.4f}->{last:.4f} "
      f"acc={acc:.2f} checksum={checksum:.6f}", flush=True)
