"""Pipeline (pp) + MoE (ep) worker for real multi-process SPMD tests
(VERDICT r5 #9: P10/P12 were only exercised single-process in
dryrun_multichip; this runs the SAME programs on an N-process global
mesh and prints deterministic scalars for cross-topology equality).

Run standalone (1 process, 8 local devices) or under
``tools/launch.py -n 2`` with 4 devices per process.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from mxnet_tpu import parallel
from mxnet_tpu.parallel import moe as moe_mod
from mxnet_tpu.parallel.pipeline import (pipeline_apply, shard_stages,
                                         stack_stage_params)

if "MXTPU_COORDINATOR" in os.environ:
    from mxnet_tpu.kvstore.dist import init_distributed

    init_distributed()
    nprocs = int(os.environ["MXTPU_NUM_PROCESSES"])
    rank = int(os.environ["MXTPU_PROCESS_ID"])
    assert jax.process_count() == nprocs
else:
    nprocs, rank = 1, 0

NDEV = jax.device_count()
assert NDEV == 8, NDEV

rng = np.random.RandomState(0)
d_model = 8

# --- pipeline parallelism: GPipe over pp=8, one grad step -----------------
pp_mesh = parallel.make_mesh({"pp": NDEV})
eye = np.eye(d_model, dtype=np.float32)
stages = [{"w": jnp.asarray(eye + rng.randn(d_model, d_model)
                            .astype(np.float32) * 0.05)}
          for _ in range(NDEV)]


def stage_fn(p, a):
    return jnp.tanh(a @ p["w"])


stacked = shard_stages(stack_stage_params(stages), pp_mesh)
xs_np = rng.randn(2 * NDEV, d_model).astype(np.float32)
xs = jax.device_put(jnp.asarray(xs_np), NamedSharding(pp_mesh, P()))


def pipe_loss(params):
    out = pipeline_apply(stage_fn, params, xs, pp_mesh,
                         num_microbatches=NDEV)
    return jnp.sum(out ** 2)


pipe_val, pipe_grads = jax.jit(jax.value_and_grad(pipe_loss))(stacked)
# one SGD step, then a second loss: exercises grads -> update -> fwd
new_params = jax.tree.map(lambda p, g: p - 0.01 * g, stacked, pipe_grads)
pipe_val2 = jax.jit(pipe_loss)(new_params)
gsum = jax.jit(lambda g: jnp.sum(jnp.abs(g["w"])))(pipe_grads)

# --- expert parallelism: MoE over ep=8 ------------------------------------
ep_mesh = parallel.make_mesh({"ep": NDEV})
moe_params = moe_mod.shard_moe_params(
    moe_mod.init_moe_params(jax.random.PRNGKey(0), d_model, 16, NDEV),
    ep_mesh)
tok_np = rng.randn(4 * NDEV, d_model).astype(np.float32)
tok = jax.device_put(jnp.asarray(tok_np), NamedSharding(ep_mesh, P()))
moe_out, moe_aux = jax.jit(
    lambda p, t: moe_mod.moe_apply(p, t, mesh=ep_mesh))(moe_params, tok)
moe_sum = jax.jit(lambda o: jnp.sum(jnp.abs(o)))(moe_out)

line = (f"PP_EP_OK rank={rank}/{nprocs} pipe={float(pipe_val):.6f} "
        f"pipe2={float(pipe_val2):.6f} gsum={float(gsum):.6f} "
        f"moe={float(moe_sum):.6f} aux={float(moe_aux):.6f}")
outdir = os.environ.get("MXTPU_TEST_OUTDIR")
if outdir:  # per-rank files: multi-process stdout interleaves mid-line
    with open(os.path.join(outdir, f"rank{rank}.txt"), "w") as f:
        f.write(line + "\n")
print(line, flush=True)
