"""NDArray semantics tests (reference model: tests/python/unittest/test_ndarray.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


def test_creation():
    a = mx.nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    b = mx.nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    c = mx.nd.full((2, 2), 7.0)
    assert (c.asnumpy() == 7).all()
    d = mx.nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    e = mx.nd.arange(0, 10, 2)
    assert_almost_equal(e, np.arange(0, 10, 2, dtype=np.float32))


def test_arithmetic():
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = mx.nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert_almost_equal(a + b, np.array([[6, 8], [10, 12]]))
    assert_almost_equal(a - b, np.array([[-4, -4], [-4, -4]]))
    assert_almost_equal(a * b, np.array([[5, 12], [21, 32]]))
    assert_almost_equal(b / a, np.array([[5, 3], [7 / 3, 2]]))
    assert_almost_equal(a + 1, a.asnumpy() + 1)
    assert_almost_equal(2 * a, 2 * a.asnumpy())
    assert_almost_equal(2 - a, 2 - a.asnumpy())
    assert_almost_equal(a ** 2, a.asnumpy() ** 2)
    assert_almost_equal(-a, -a.asnumpy())
    assert_almost_equal(abs(-a), a.asnumpy())


def test_inplace():
    a = mx.nd.ones((2, 2))
    orig = a
    a += 1
    assert orig is a
    assert (a.asnumpy() == 2).all()
    a *= 3
    assert (a.asnumpy() == 6).all()


def test_comparison():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([3.0, 2.0, 1.0])
    assert_almost_equal(a == b, np.array([0, 1, 0], dtype=np.float32))
    assert_almost_equal(a < b, np.array([1, 0, 0], dtype=np.float32))
    assert_almost_equal(a >= b, np.array([0, 1, 1], dtype=np.float32))


def test_indexing_basic():
    a = mx.nd.array(np.arange(24).reshape(2, 3, 4))
    np_a = np.arange(24).reshape(2, 3, 4)
    assert_almost_equal(a[0], np_a[0])
    assert_almost_equal(a[1, 2], np_a[1, 2])
    assert_almost_equal(a[:, 1:3], np_a[:, 1:3])
    assert_almost_equal(a[0, :, ::2], np_a[0, :, ::2])


def test_view_aliasing():
    """b = a[1:3]; b[:] = 0 mutates a (reference shared-memory views)."""
    a = mx.nd.array(np.arange(10, dtype=np.float32))
    b = a[2:5]
    b[:] = 0
    expected = np.arange(10, dtype=np.float32)
    expected[2:5] = 0
    assert_almost_equal(a, expected)
    # mutations of a are visible through b
    a[3] = 99
    assert float(b[1].asscalar()) == 99


def test_setitem():
    a = mx.nd.zeros((3, 3))
    a[1] = 1.0
    a[0, 2] = 5.0
    a[2, :] = mx.nd.array([7.0, 8.0, 9.0])
    exp = np.zeros((3, 3), np.float32)
    exp[1] = 1
    exp[0, 2] = 5
    exp[2] = [7, 8, 9]
    assert_almost_equal(a, exp)


def test_reshape_special_codes():
    a = mx.nd.zeros((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((2, -4, 1, 3, 4)).shape == (2, 1, 3, 4)
    assert a.reshape((0, 0, -1)).shape == (2, 3, 4)
    assert a.reshape(6, 4).shape == (6, 4)  # varargs form


def test_astype_copy():
    a = mx.nd.ones((2, 2))
    b = a.astype("float16")
    assert b.dtype == np.float16
    c = a.astype(np.float32, copy=False)
    assert c is a


def test_save_load(tmp_path):
    fname = str(tmp_path / "nd.save")
    a = mx.nd.array([[1.0, 2.0]])
    b = mx.nd.arange(0, 4)
    mx.nd.save(fname, [a, b])
    loaded = mx.nd.load(fname)
    assert isinstance(loaded, list)
    assert_almost_equal(loaded[0], a)
    assert_almost_equal(loaded[1], b)
    mx.nd.save(fname, {"x": a, "y": b})
    loaded = mx.nd.load(fname)
    assert set(loaded.keys()) == {"x", "y"}
    assert_almost_equal(loaded["x"], a)


def test_scalar_conversion():
    a = mx.nd.array([3.5])
    assert a.asscalar() == pytest.approx(3.5)
    assert float(a) == pytest.approx(3.5)
    assert int(mx.nd.array([7])) == 7
    with pytest.raises(ValueError):
        mx.nd.ones((2,)).asscalar()


def test_methods():
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert_almost_equal(a.sum(), np.float32(10))
    assert_almost_equal(a.sum(axis=0), np.array([4, 6], np.float32))
    assert_almost_equal(a.mean(axis=1), np.array([1.5, 3.5], np.float32))
    assert_almost_equal(a.max(), np.float32(4))
    assert_almost_equal(a.T, a.asnumpy().T)
    assert_almost_equal(a.flatten(), a.asnumpy().reshape(2, 2))
    assert a.expand_dims(0).shape == (1, 2, 2)
    assert_almost_equal(a.clip(a_min=1.5, a_max=3.5),
                        np.clip(a.asnumpy(), 1.5, 3.5))


def test_waitall_and_sync():
    a = mx.nd.ones((100, 100))
    b = a @ a
    b.wait_to_read()
    mx.nd.waitall()


def test_copyto_and_context():
    a = mx.nd.ones((2, 2), ctx=mx.cpu())
    b = mx.nd.zeros((2, 2), ctx=mx.cpu())
    a.copyto(b)
    assert (b.asnumpy() == 1).all()
    c = a.as_in_context(mx.cpu())
    assert c is a
    assert a.context.device_type in ("cpu",)


def test_zeros_ones_like():
    a = mx.nd.array(np.random.rand(3, 3))
    assert (mx.nd.zeros_like(a).asnumpy() == 0).all()
    assert (mx.nd.ones_like(a).asnumpy() == 1).all()


def test_concat_stack():
    a = mx.nd.ones((2, 3))
    b = mx.nd.zeros((2, 3))
    c = mx.nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    d = mx.nd.stack(a, b, axis=0)
    assert d.shape == (2, 2, 3)


def test_pickle():
    import pickle

    a = mx.nd.array([[1.0, 2.0]])
    b = pickle.loads(pickle.dumps(a))
    assert_almost_equal(a, b)
