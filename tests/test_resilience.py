"""Fault-tolerant training (mxnet_tpu/resilience/): async checkpoint
commit protocol + manifest/checksum integrity, bit-exact resume parity
(sgd/adam x AMP off/fp16), subprocess SIGTERM kill-and-resume for the
fused loop AND the K-step superstep, elastic 2-device->1-device SPMD
restore, chaos fault injection (deterministic, zero dispatches when
off), SIGTERM handler chaining order, and the save_states/load_states
fused-state round-trip fixes."""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, fusedstep, gluon, resilience
from mxnet_tpu import observability as obs
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import chaos, checkpoint, resume

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()


@pytest.fixture(autouse=True)
def _clean():
    chaos.reset()
    obs.set_enabled(False)
    obs.reset()
    yield
    chaos.reset()
    amp.disable()
    obs.set_enabled(False)
    obs.reset()


def _build(seed=0, optimizer="adam", fp16=False, lr=0.05):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8))
    net.add(nn.Dense(4, in_units=16))
    net.initialize(init=mx.initializer.Xavier())
    if fp16:
        amp.init("float16")
        amp.convert_model(net)
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), optimizer,
                       {"learning_rate": lr, "multi_precision": fp16},
                       kvstore=None)
    if fp16:
        amp.init_trainer(tr)
        tr._amp_loss_scaler = amp.LossScaler(init_scale=1024.0)
    return net, tr


_X32 = mx.nd.ones((8, 8))
_Y = mx.nd.zeros((8,))


def _step(net, tr, fp16=False):
    X = _X32.astype("float16") if fp16 else _X32
    with autograd.record():
        l = loss_fn(net(X), _Y)
        if fp16:
            with amp.scale_loss(l, tr) as sl:
                sl.backward()
    if not fp16:
        l.backward()
    tr.step(8)
    return float(jnp.mean(l.data).astype(jnp.float32))


# ---------------------------------------------------------------------------
# commit protocol / verify / retention
# ---------------------------------------------------------------------------

def test_interval_commits_retention_and_verify(tmp_path):
    net, tr = _build()
    mgr = resilience.CheckpointManager(
        tmp_path / "ck", every_n_steps=2, keep=2, net=net,
        trainer=tr).attach(tr)
    try:
        for _ in range(9):
            _step(net, tr)
            # drain the writer at every step boundary: the async queue
            # is latest-wins by design, so under host pressure a slow
            # writer may legally SKIP an intermediate interval commit
            # (observed flake: committed steps [2, 8] or [4, 8] instead
            # of [6, 8]). Flushing per step pins the schedule to step
            # counts — every interval boundary commits, deterministically
            assert mgr.flush(timeout=120), "checkpoint writer stuck"
        steps = [s for s, _ in resilience.list_checkpoints(tmp_path / "ck")]
        assert steps == [6, 8], steps  # keep=2 trimmed 2 and 4
        assert resilience.verify(tmp_path / "ck") == []
        assert resilience.latest_checkpoint(tmp_path / "ck").endswith(
            "step_0000000008")
        assert mgr.last_error is None
    finally:
        mgr.close()


def test_commit_is_atomic_no_partial_dirs(tmp_path):
    net, tr = _build()
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=1,
                                       net=net, trainer=tr).attach(tr)
    try:
        for _ in range(3):
            _step(net, tr)
        mgr.flush()
        for d in os.listdir(tmp_path / "ck"):
            assert not d.startswith(".tmp"), d  # no half-written dirs
            if d.startswith("step_"):
                assert os.path.exists(tmp_path / "ck" / d / "MANIFEST.json")
    finally:
        mgr.close()


def test_verify_catches_corruption_and_truncation(tmp_path):
    net, tr = _build()
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=2,
                                       net=net, trainer=tr).attach(tr)
    try:
        _step(net, tr), _step(net, tr)
        mgr.flush()
    finally:
        mgr.close()
    step_dir = resilience.latest_checkpoint(tmp_path / "ck")
    payload = os.path.join(step_dir, "data.bin")
    blob = bytearray(open(payload, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(payload, "wb") as f:
        f.write(blob)
    problems = resilience.verify(step_dir)
    assert problems and any("checksum mismatch" in p for p in problems)
    # the loader refuses corrupt payloads outright
    with pytest.raises(mx.MXNetError, match="checksum"):
        checkpoint.read_checkpoint(step_dir)
    # truncation
    with open(payload, "wb") as f:
        f.write(bytes(blob[: len(blob) // 2]))
    problems = resilience.verify(step_dir)
    assert any("payload" in p or "past the end" in p for p in problems)


def test_verify_catches_missing_opt_state_tensors(tmp_path):
    """Completeness: a manifest that declares fused OR eager opt state
    whose tensors are absent must fail the lint (the loader would
    KeyError on it — the linter must not certify what cannot load)."""
    net, tr = _build(0, "adam")
    prev = fusedstep.set_enabled(False)
    try:
        _step(net, tr)  # eager path: _opt_state attached
    finally:
        fusedstep.set_enabled(prev)
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=100,
                                       net=net, trainer=tr)
    try:
        mgr.save_sync()
    finally:
        mgr.close()
    step_dir = resilience.latest_checkpoint(tmp_path / "ck")
    man_path = os.path.join(step_dir, "MANIFEST.json")
    man = json.load(open(man_path))
    assert any(k == "eager" for k in man["extras"]["opt_kind"].values())
    # drop one eager tensor from the manifest -> completeness failure
    eager_keys = [k for k in man["tensors"] if k.startswith("eager::")]
    assert eager_keys
    del man["tensors"][eager_keys[0]]
    json.dump(man, open(man_path, "w"))
    problems = resilience.verify(step_dir)
    assert any("declared eager" in p and "missing" in p
               for p in problems), problems


def test_verify_checkpoint_cli(tmp_path):
    net, tr = _build()
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=2,
                                       net=net, trainer=tr).attach(tr)
    try:
        _step(net, tr), _step(net, tr)
        mgr.flush()
    finally:
        mgr.close()
    tool = os.path.join(ROOT, "tools", "verify_checkpoint.py")
    res = subprocess.run([sys.executable, tool, str(tmp_path / "ck")],
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "OK" in res.stdout
    # corrupt -> rc 1 with the problem named
    step_dir = resilience.latest_checkpoint(tmp_path / "ck")
    with open(os.path.join(step_dir, "data.bin"), "r+b") as f:
        f.seek(0)
        f.write(b"\xff\xff")
    res = subprocess.run([sys.executable, tool, str(step_dir)],
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 1
    assert "checksum" in res.stdout


# ---------------------------------------------------------------------------
# bit-exact resume parity: sgd/adam x AMP off/fp16 (in-process)
# ---------------------------------------------------------------------------

# tier-1 keeps the diagonal (plain sgd + adam-with-masters-and-scaler);
# the off-diagonal cells re-cross already-covered axes and run under -m slow
@pytest.mark.parametrize("fp16,optimizer", [
    pytest.param(False, "sgd", id="fp32-sgd"),
    pytest.param(False, "adam", id="fp32-adam", marks=pytest.mark.slow),
    pytest.param(True, "sgd", id="fp16-sgd", marks=pytest.mark.slow),
    pytest.param(True, "adam", id="fp16-adam"),
])
def test_resume_parity_bit_exact(tmp_path, optimizer, fp16):
    """Train 8 steps with a checkpoint at 4; restore the step-4
    checkpoint into a FRESH model and run 4 more: the loss trajectory,
    params, optimizer pytrees (masters included) and scaler state must
    all match the uninterrupted run BIT-EXACTLY."""
    netA, trA = _build(0, optimizer, fp16)
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=4,
                                       net=netA, trainer=trA).attach(trA)
    try:
        lossesA = [_step(netA, trA, fp16) for _ in range(8)]
        mgr.flush()
    finally:
        mgr.close()
    amp.disable()

    netB, trB = _build(1234, optimizer, fp16)  # different init: must not leak
    rep = resilience.load_checkpoint(
        str(tmp_path / "ck" / "step_0000000004"), net=netB, trainer=trB)
    assert rep.step == 4 and rep.kind == "trainer" and not rep.elastic
    lossesB = [_step(netB, trB, fp16) for _ in range(4)]
    assert lossesA[4:] == lossesB, (lossesA[4:], lossesB)
    for p, p2 in zip(trA._params, trB._params):
        assert jnp.array_equal(p.data().data, p2.data().data), p.name
        assert p.data().data.dtype == p2.data().data.dtype
    for n, n2 in zip(sorted(trA._fused_states), sorted(trB._fused_states)):
        for a, b in zip(trA._fused_states[n], trB._fused_states[n2]):
            assert jnp.array_equal(a, b), (n, a, b)
    if fp16:
        assert trA._amp_loss_scaler.loss_scale == \
            trB._amp_loss_scaler.loss_scale
        assert trA._amp_loss_scaler.overflow_total == \
            trB._amp_loss_scaler.overflow_total
    assert trA._optimizer._index_update_count == \
        trB._optimizer._index_update_count


def test_resume_without_net_fails_loudly_not_silently_fresh(tmp_path):
    """A checkpoint saved with net= uses structural param names; a
    trainer-only restore cannot resolve them and must RAISE — not
    return success having restored nothing (silently training on from
    fresh weights + reset momentum is the worst possible outcome)."""
    net, tr = _build(0, "adam")
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=2,
                                       net=net, trainer=tr).attach(tr)
    try:
        _step(net, tr), _step(net, tr)
        assert mgr.flush()
    finally:
        mgr.close()
    net2, tr2 = _build(5, "adam")
    with pytest.raises(mx.MXNetError, match="net="):
        resilience.load_checkpoint(str(tmp_path / "ck"), trainer=tr2)


def test_resume_restores_rng_stream(tmp_path):
    net, tr = _build()
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=2,
                                       net=net, trainer=tr).attach(tr)
    try:
        _step(net, tr), _step(net, tr)
        mgr.flush()
    finally:
        mgr.close()
    a = mx.nd.random.uniform(shape=(4,)).asnumpy()
    net2, tr2 = _build(99)
    resilience.load_checkpoint(str(tmp_path / "ck"), net=net2, trainer=tr2)
    b = mx.nd.random.uniform(shape=(4,)).asnumpy()
    np.testing.assert_array_equal(a, b)  # same post-restore key stream


def test_cursor_rides_checkpoint_and_skip_batches(tmp_path):
    from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher

    pf = DevicePrefetcher(iter([np.ones((2, 8), np.float32)
                                for _ in range(6)]))
    it = iter(pf)
    next(it), next(it), next(it)
    assert pf.cursor == 3
    net, tr = _build()
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=1,
                                       net=net, trainer=tr,
                                       ring=pf).attach(tr)
    try:
        _step(net, tr)
        mgr.flush()
    finally:
        mgr.close()
    man, _ = checkpoint.read_checkpoint(str(tmp_path / "ck"))
    assert man["extras"]["cursor"] == 3
    rest = list(resume.skip_batches(range(10), man["extras"]["cursor"]))
    assert rest == [3, 4, 5, 6, 7, 8, 9]


# ---------------------------------------------------------------------------
# subprocess kill-and-resume: the acceptance path
# ---------------------------------------------------------------------------

_CHILD = """
import hashlib, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {root!r})
import numpy as np
import jax.numpy as jnp
import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon, resilience
from mxnet_tpu.gluon import nn

MODE = {mode!r}            # "full" | "resume"
SUPERSTEP = {superstep!r}  # 0 or K
FP16 = {fp16!r}
OPT = {opt!r}
KILL_MID = {kill_mid!r}    # arm a timer to SIGTERM ourselves MID-scan
STEPS = {steps!r}

np.random.seed(0)  # initializers draw from np.random (conftest seeds
mx.random.seed(0)  # it for in-process tests; a bare child must too)
net = nn.HybridSequential()
net.add(nn.Dense(16, activation="relu", in_units=8))
net.add(nn.Dense(4, in_units=16))
net.initialize(init=mx.initializer.Xavier())
if FP16:
    amp.init("float16")
    amp.convert_model(net)
net.hybridize()
tr = gluon.Trainer(net.collect_params(), OPT,
                   {{"learning_rate": 0.05, "multi_precision": FP16}},
                   kvstore=None)
if FP16:
    amp.init_trainer(tr)
    tr._amp_loss_scaler = amp.LossScaler(init_scale=1024.0)
mgr = resilience.maybe_checkpointing(net, tr)
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
X = mx.nd.ones((8, 8)).astype("float16" if FP16 else "float32")
Y = mx.nd.zeros((8,))

start = 0
if MODE == "resume":
    rep = resilience.load_checkpoint(os.environ["MXTPU_CHECKPOINT"]
                                     .rsplit(":", 1)[0], net=net, trainer=tr)
    start = rep.step
    if mgr is not None:
        mgr.restore_step(start)

def one_step():
    with autograd.record():
        l = loss_fn(net(X), Y)
        if FP16:
            with amp.scale_loss(l, tr) as sl:
                sl.backward()
    if not FP16:
        l.backward()
    tr.step(8)
    return float(jnp.mean(l.data).astype(jnp.float32))

losses = []
if SUPERSTEP:
    import signal as _signal
    import threading as _threading
    import time as _time
    sstep = gluon.Superstep(net, loss_fn, tr, k=SUPERSTEP)
    from mxnet_tpu.gluon.data.prefetcher import stack_batches
    xs = stack_batches([X] * SUPERSTEP)
    ys = stack_batches([Y] * SUPERSTEP)
    for g in range(start // SUPERSTEP, STEPS // SUPERSTEP):
        if KILL_MID and g == start // SUPERSTEP + 2:
            # SIGTERM aimed MID-superstep: a watcher thread fires the
            # instant the main thread is inside the step's critical
            # section (checkpoint._CRITICAL > 0 — typically while the
            # K-iteration scan dispatch executes), so the handler MUST
            # defer the final checkpoint to the completed K-boundary —
            # never a half-applied carry
            from mxnet_tpu.resilience import checkpoint as _ckm
            def _watch():
                while _ckm._CRITICAL[0] == 0:
                    _time.sleep(0.0002)
                os.kill(os.getpid(), _signal.SIGTERM)
            _threading.Thread(target=_watch, daemon=True).start()
        ls = sstep.step(xs, ys, 8)
        losses.extend(float(v) for v in
                      np.asarray(ls.data, dtype=np.float32))
else:
    for i in range(start, STEPS):
        losses.append(one_step())

h = hashlib.sha1()
for _, p in sorted(net.collect_params().items()):
    h.update(np.asarray(p.data().data).tobytes())
for n in sorted(tr._fused_states):
    for leaf in tr._fused_states[n]:
        h.update(np.asarray(leaf).tobytes())
print("LOSSES " + " ".join(repr(l) for l in losses[-4:]))
print("HASH " + h.hexdigest())
print("DONE steps", start, "->", STEPS)
"""


def _run_child(tmp_path, mode, ckpt_env, superstep=0, fp16=False,
               opt="adam", chaos_spec=None, expect_rc=0, kill_mid=0,
               steps=12):
    env = {k: v for k, v in os.environ.items() if k != "MXTPU_CHAOS"}
    env["MXTPU_CHECKPOINT"] = ckpt_env
    if chaos_spec:
        env["MXTPU_CHAOS"] = chaos_spec
    res = subprocess.run(
        [sys.executable, "-c",
         _CHILD.format(root=ROOT, mode=mode, superstep=superstep,
                       fp16=fp16, opt=opt, kill_mid=kill_mid,
                       steps=steps)],
        env=env, capture_output=True, text=True, timeout=300)
    assert res.returncode == expect_rc, (
        f"child rc={res.returncode} (wanted {expect_rc})\n"
        f"stdout:\n{res.stdout[-2000:]}\nstderr:\n{res.stderr[-2000:]}")
    return res


def _parse(res):
    losses = hashv = None
    for ln in res.stdout.splitlines():
        if ln.startswith("LOSSES "):
            losses = ln[len("LOSSES "):].split()
        if ln.startswith("HASH "):
            hashv = ln.split()[1]
    return losses, hashv


@pytest.mark.parametrize("superstep,fp16,opt", [
    # fused one-step loop, fp16 AMP + masters: same kill/resume drill
    # through a second subprocess pair (~12 s) — slow tier keeps it
    pytest.param(0, True, "adam", marks=pytest.mark.slow),
    (3, False, "sgd"),   # K-step superstep capture
], ids=["fused_adam_fp16", "superstep_sgd"])
def test_kill_and_resume_subprocess(tmp_path, superstep, fp16, opt):
    """SIGTERM (via a deterministic chaos fault) a live training loop
    mid-run; the final checkpoint commits on the way down; a fresh
    process resumes from it and must reproduce the uninterrupted run's
    loss tail and final params+opt-state hash BIT-EXACTLY."""
    ck = f"{tmp_path}/ck:3"
    # leg 1: uninterrupted reference
    full = _run_child(tmp_path, "full", f"{tmp_path}/ref:100",
                      superstep, fp16, opt)
    # leg 2: killed mid-run (chaos SIGTERM re-raises -> rc -SIGTERM)
    spec = "term@superstep:3" if superstep else "term@trainer:7"
    _run_child(tmp_path, "full", ck, superstep, fp16, opt,
               chaos_spec=spec, expect_rc=-signal.SIGTERM)
    assert resilience.verify(f"{tmp_path}/ck") == []
    # leg 3: resume from the committed checkpoint
    res = _run_child(tmp_path, "resume", ck, superstep, fp16, opt)
    losses_full, hash_full = _parse(full)
    losses_res, hash_res = _parse(res)
    assert losses_full == losses_res, (losses_full, losses_res)
    assert hash_full == hash_res


# kill_and_resume_subprocess[superstep_sgd] certifies chaos-SIGTERM ->
# k-boundary commit -> resume parity every tier-1 round; this twin
# re-proves the commit half only
@pytest.mark.slow
def test_sigterm_mid_superstep_commits_at_k_boundary(tmp_path):
    """ISSUE 11 satellite: SIGTERM arriving MID-``Superstep`` scan (a
    self-armed timer fires while the K-iteration dispatch executes, so
    the handler runs inside the step's critical section). The final
    checkpoint must commit at the last COMPLETED K-boundary — step
    divisible by K, params/opt-state/counts mutually consistent, never
    a half-applied carry — and a fresh process resuming from it must
    reproduce the uninterrupted run's loss tail bit-exactly."""
    k, steps = 4, 20
    ck = f"{tmp_path}/ck:1000"  # interval never fires; only the final
    # leg 1: uninterrupted reference
    full = _run_child(tmp_path, "full", f"{tmp_path}/ref:1000",
                      superstep=k, steps=steps)
    # leg 2: killed mid-scan by the in-child timer
    _run_child(tmp_path, "full", ck, superstep=k, steps=steps,
               kill_mid=1, expect_rc=-signal.SIGTERM)
    assert resilience.verify(f"{tmp_path}/ck") == []
    ckpts = resilience.list_checkpoints(f"{tmp_path}/ck")
    assert len(ckpts) == 1, ckpts
    committed_step = ckpts[0][0]
    # the contract under test: a K-boundary commit, not mid-carry —
    # and an INTERIOR one (the timer aimed at superstep 3 of 4), so
    # the resume leg has real steps left to reproduce
    assert committed_step % k == 0, (committed_step, k)
    assert 0 < committed_step < steps, (committed_step, steps)
    man = json.load(open(os.path.join(ckpts[0][1], "MANIFEST.json")))
    assert man["reason"] == "sigterm"
    # leg 3: resume; the loss tail and final state hash must match
    res = _run_child(tmp_path, "resume", ck, superstep=k, steps=steps)
    losses_full, hash_full = _parse(full)
    losses_res, hash_res = _parse(res)
    assert losses_full == losses_res, (losses_full, losses_res)
    assert hash_full == hash_res


@pytest.mark.slow
def test_chaos_smoke_sigterm_commits_verifiable_checkpoint(tmp_path):
    """The tier-1 chaos smoke (ISSUE 8 satellite): SIGTERM a live
    training subprocess from OUTSIDE (a real preemption, not an
    injected fault) and assert a committed checkpoint exists that
    tools/verify_checkpoint.py certifies."""
    child = f"""
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {ROOT!r})
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, resilience
from mxnet_tpu.gluon import nn
net = nn.Dense(4, in_units=8)
net.initialize(); net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd",
                   {{"learning_rate": 0.1, "momentum": 0.9}}, kvstore=None)
mgr = resilience.maybe_checkpointing(net, tr)
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
X, Y = mx.nd.ones((8, 8)), mx.nd.zeros((8,))
i = 0
while True:
    with autograd.record():
        l = loss_fn(net(X), Y)
    l.backward(); tr.step(8)
    i += 1
    if i == 3:
        open({str(tmp_path / 'ready')!r}, "w").write("ready")
    time.sleep(0.001)
"""
    env = dict(os.environ)
    env["MXTPU_CHECKPOINT"] = f"{tmp_path}/ck:1000"  # interval never fires
    proc = subprocess.Popen([sys.executable, "-c", child], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        t0 = time.monotonic()
        while not os.path.exists(tmp_path / "ready"):
            if proc.poll() is not None:
                raise AssertionError(
                    f"child died early: "
                    f"{proc.stderr.read().decode()[-2000:]}")
            assert time.monotonic() - t0 < 120, "child never became ready"
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGTERM, proc.returncode
    # ONLY the SIGTERM final save can have produced a checkpoint
    ckpts = resilience.list_checkpoints(f"{tmp_path}/ck")
    assert len(ckpts) == 1 and ckpts[0][0] >= 3, ckpts
    man = json.load(open(os.path.join(ckpts[0][1], "MANIFEST.json")))
    assert man["reason"] == "sigterm"
    tool = os.path.join(ROOT, "tools", "verify_checkpoint.py")
    res = subprocess.run([sys.executable, tool, f"{tmp_path}/ck"],
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr


# ---------------------------------------------------------------------------
# elastic SPMD resume: 2-device-sharded -> 1 device
# ---------------------------------------------------------------------------

def _spmd_net():
    mx.random.seed(0)
    net = nn.HybridSequential(prefix="ck_net_")
    net.add(nn.Dense(16, activation="relu", in_units=8, prefix="d0_"))
    net.add(nn.Dense(4, in_units=16, prefix="d1_"))
    net.initialize(init=mx.initializer.Xavier())
    return net


def test_elastic_spmd_2dev_to_1dev(tmp_path):
    from jax.sharding import Mesh

    from mxnet_tpu import parallel

    X = mx.nd.array(np.random.RandomState(0).rand(8, 8).astype(np.float32))
    Y = mx.nd.array(np.random.RandomState(1).randint(0, 4, (8,))
                    .astype(np.float32))
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    stepA = parallel.SPMDTrainStep(_spmd_net(), loss_fn, "adam", {},
                                   mesh=mesh, shard_opt_states=True)
    for _ in range(3):
        stepA(X, Y, lr=0.05)
    resilience.save_spmd_checkpoint(tmp_path / "ck", stepA, step=3)
    assert resilience.verify(tmp_path / "ck") == []

    stepB = parallel.SPMDTrainStep(_spmd_net(), loss_fn, "adam", {},
                                   mesh=None)
    stepB(X, Y, lr=0.05)  # init + compile; state replaced by restore
    rep = resilience.load_checkpoint(str(tmp_path / "ck"), spmd_step=stepB)
    assert rep.kind == "spmd" and rep.elastic  # 2 mesh devices -> 1
    lA = stepA(X, Y, lr=0.05)
    lB = stepB(X, Y, lr=0.05)
    np.testing.assert_allclose(lA, lB, rtol=1e-6)


# ---------------------------------------------------------------------------
# chaos harness
# ---------------------------------------------------------------------------

def test_chaos_spec_parsing_and_reset():
    faults = chaos.configure("term:5,nan@superstep:2,stall:4:0.25,"
                             "collective:1,seed=7")
    assert chaos.ENABLED and len(faults) == 4
    kinds = {f["kind"] for f in faults}
    assert kinds == {"term", "nan", "stall", "collective"}
    nan = next(f for f in faults if f["kind"] == "nan")
    assert nan["site"] == "superstep" and nan["step"] == 2
    chaos.reset()
    assert not chaos.ENABLED and chaos.fired() == []
    with pytest.raises(mx.MXNetError, match="cannot parse"):
        chaos.configure("frobnicate:1")
    with pytest.raises(mx.MXNetError, match="needs a"):
        chaos.configure("nan")
    chaos.reset()


def test_chaos_raise_and_stall_fire_deterministically():
    chaos.configure("raise:3")
    net, tr = _build()
    _step(net, tr)
    _step(net, tr)
    with pytest.raises(chaos.ChaosInjectedError):
        _step(net, tr)
    assert chaos.fired() == [("raise", "trainer", 3)]
    chaos.configure("stall@trainer:1:0.2")
    t0 = time.perf_counter()
    _step(net, tr)
    assert time.perf_counter() - t0 >= 0.2
    assert chaos.fired() == [("stall", "trainer", 1)]


def test_chaos_probabilistic_is_seeded_deterministic():
    chaos.configure("raise:p0.5", seed=42)
    seq1 = []
    for _ in range(12):
        try:
            chaos.step_point("t")
            seq1.append(0)
        except chaos.ChaosInjectedError:
            seq1.append(1)
    chaos.configure("raise:p0.5", seed=42)
    seq2 = []
    for _ in range(12):
        try:
            chaos.step_point("t")
            seq2.append(0)
        except chaos.ChaosInjectedError:
            seq2.append(1)
    assert seq1 == seq2 and 0 < sum(seq1) < 12


def test_chaos_nan_poisons_prefetched_batch():
    from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher

    chaos.configure("nan@prefetch:2")
    batches = [np.ones((2, 4), np.float32) for _ in range(3)]
    out = list(DevicePrefetcher(iter(batches)))
    assert np.isfinite(np.asarray(out[0].data)).all()
    assert np.isnan(np.asarray(out[1].data)).all()   # the poisoned one
    assert np.isfinite(np.asarray(out[2].data)).all()


def test_chaos_nan_superstep_fp16_skips_one_iteration():
    """nan@superstep poisons SLOT 0 of the stacked block; under fp16
    AMP exactly that iteration overflows + skips, the other K-1 apply
    (the PR-6 robustness claim, now injectable on demand)."""
    from mxnet_tpu.gluon.data.prefetcher import stack_batches

    obs.set_enabled(True)
    net, tr = _build(0, "sgd", fp16=True)
    sstep = gluon.Superstep(net, loss_fn, tr, k=4)
    X = _X32.astype("float16")
    xs, ys = stack_batches([X] * 4), stack_batches([_Y] * 4)
    sstep.step(xs, ys, 8)  # warm, no fault
    chaos.configure("nan@superstep:1")
    sstep.step(xs, ys, 8)
    ovf = obs.superstep_series()["overflow"]
    assert ovf == [1.0, 0.0, 0.0, 0.0], ovf
    w = np.asarray(net._children["0"].weight.data().data,
                   dtype=np.float32)
    assert np.isfinite(w).all()  # the skip kept NaN out of the weights


def test_chaos_collective_one_shot_and_barrier_retry():
    from mxnet_tpu.kvstore.dist import _global_allreduce

    chaos.configure("collective:1")
    with pytest.raises(chaos.ChaosInjectedError):
        _global_allreduce(jnp.ones((4,)))
    # one-shot: the retry (same call pattern the barrier uses) succeeds
    out = _global_allreduce(jnp.ones((4,)))
    assert np.asarray(out).tolist() == [1, 1, 1, 1]

    from mxnet_tpu import runtime

    chaos.configure("collective:1")
    calls = []

    def attempt():
        calls.append(1)
        chaos.collective_point("barrier")

    runtime.retry_with_backoff(attempt, attempts=3, base_delay=0.01,
                               desc="test barrier")
    assert len(calls) == 2  # failed once, recovered on retry

    # a watchdog TIMEOUT is never retried: peers are gone, and waiting
    # retries x timeout would turn "fail loudly" back into a hang
    from mxnet_tpu.kvstore.dist import CollectiveTimeoutError

    n = []

    def timed_out():
        n.append(1)
        raise CollectiveTimeoutError("peer gone")

    with pytest.raises(CollectiveTimeoutError):
        runtime.retry_with_backoff(timed_out, attempts=3, base_delay=0.01,
                                   desc="t",
                                   no_retry=(CollectiveTimeoutError,))
    assert len(n) == 1  # surfaced immediately, no retries


def test_collective_timeout_raises_instead_of_hanging():
    from mxnet_tpu.kvstore.dist import _call_with_timeout

    t0 = time.perf_counter()
    with pytest.raises(mx.MXNetError, match="timed out"):
        _call_with_timeout(lambda: time.sleep(30), 0.3, "test barrier")
    assert time.perf_counter() - t0 < 5
    # errors inside the worker surface on the caller thread
    with pytest.raises(ValueError, match="boom"):
        _call_with_timeout(lambda: (_ for _ in ()).throw(ValueError("boom")),
                           5.0, "test")
    assert _call_with_timeout(lambda: 42, 5.0, "test") == 42
    assert _call_with_timeout(lambda: 43, 0, "test") == 43  # 0 = off


def test_chaos_off_adds_zero_dispatches():
    """The zero-cost-when-off contract (telemetry-overhead style): the
    per-step dispatch count of the fused loop is IDENTICAL with chaos
    never imported-armed, and with chaos armed-but-not-firing."""
    obs.set_enabled(True)

    def measure():
        net, tr = _build()
        _step(net, tr), _step(net, tr)  # warm: compile everything
        c0 = obs.XLA_DISPATCH_TOTAL.total()
        for _ in range(5):
            _step(net, tr)
        return (obs.XLA_DISPATCH_TOTAL.total() - c0) / 5

    base = measure()
    chaos.configure("term:999999999")  # armed but never firing
    armed = measure()
    chaos.reset()
    off = measure()
    assert base == armed == off, (base, armed, off)


# ---------------------------------------------------------------------------
# SIGTERM chaining order (checkpoint FIRST, flight bundle second)
# ---------------------------------------------------------------------------

def test_sigterm_order_checkpoint_before_flight(tmp_path, monkeypatch):
    from mxnet_tpu.observability import flight

    order = []
    flight.install(str(tmp_path))
    try:
        net, tr = _build()
        mgr = resilience.CheckpointManager(tmp_path / "ck",
                                           every_n_steps=100, net=net,
                                           trainer=tr).attach(tr)
        try:
            _step(net, tr)
            real_save = mgr.save_sync
            monkeypatch.setattr(
                mgr, "save_sync",
                lambda *a, **k: (order.append("checkpoint"),
                                 real_save(*a, **k))[1])
            monkeypatch.setattr(
                flight, "dump",
                lambda *a, **k: order.append("flight") or "x")
            # simulate the delivered signal with a chained prev handler
            # (so the test process survives the re-raise)
            flight._STATE["prev_signal"][signal.SIGTERM] = \
                lambda *a: order.append("prev")
            flight._signal_handler(signal.SIGTERM, None)
        finally:
            mgr.close()
    finally:
        flight._STATE["prev_signal"].pop(signal.SIGTERM, None)
        flight.uninstall()
    assert order == ["checkpoint", "flight", "prev"], order
    assert resilience.verify(tmp_path / "ck") == []  # the save was real


def test_sigterm_order_holds_with_reversed_install(tmp_path, monkeypatch):
    """Manager installed FIRST, recorder second: the outermost handler
    is flight's, whose pre-dump hook still runs the checkpoint before
    the bundle — and the manager's own chained handler no-ops (the
    once-per-death flag) instead of double-saving."""
    from mxnet_tpu.observability import flight

    order = []
    net, tr = _build()
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=100,
                                       net=net, trainer=tr).attach(tr)
    try:
        _step(net, tr)
        flight.install(str(tmp_path))  # AFTER the manager
        real_save = mgr.save_sync
        monkeypatch.setattr(
            mgr, "save_sync",
            lambda *a, **k: (order.append("checkpoint"),
                             real_save(*a, **k))[1])
        monkeypatch.setattr(
            flight, "dump", lambda *a, **k: order.append("flight") or "x")
        flight._STATE["prev_signal"][signal.SIGTERM] = \
            lambda *a: order.append("prev")
        flight._signal_handler(signal.SIGTERM, None)
    finally:
        flight._STATE["prev_signal"].pop(signal.SIGTERM, None)
        flight.uninstall()
        mgr.close()
    assert order.count("checkpoint") == 1, order
    assert order.index("checkpoint") < order.index("flight"), order


# ---------------------------------------------------------------------------
# save_states / load_states round-trip (satellite fix)
# ---------------------------------------------------------------------------

def test_save_states_roundtrip_fused_to_eager_momentum_survives(tmp_path):
    """Momentum/adam-t trained on the FUSED path must survive
    save->load->continue on the EAGER path (pre-fix: only eager
    _opt_state round-tripped; fused-trained trainers saved state the
    eager path then ignored)."""
    net, tr = _build(0, "adam")
    for _ in range(3):
        _step(net, tr)
    fname = str(tmp_path / "states.bin")
    tr.save_states(fname)
    saved_m = {n: np.asarray(st[0]) for n, st in tr._fused_states.items()}

    net2, tr2 = _build(7, "adam")
    tr2.load_states(fname)
    assert tr2._optimizer.num_update == 3
    prev = fusedstep.set_enabled(False)  # force the eager path
    try:
        _step(net2, tr2)
        # migration happened from the RESTORED fused store (not fresh)
        for i, p in enumerate(tr2._params):
            assert getattr(p, "_opt_state", None) is not None
    finally:
        fusedstep.set_enabled(prev)
    # and fused continuation also sees the restored state
    net3, tr3 = _build(8, "adam")
    tr3.load_states(fname)
    _step(net3, tr3)
    for n, m0 in zip(sorted(tr3._fused_states), sorted(saved_m)):
        t_leaf = tr3._fused_states[n][2]
        assert int(t_leaf) == 4  # adam t continued from 3, not reset


def test_load_states_clears_stale_eager_state(tmp_path):
    """A trainer that ALREADY trained eagerly must not keep its stale
    per-param _opt_state shadowing the restored fused states."""
    net, tr = _build(0, "adam")
    for _ in range(3):
        _step(net, tr)  # fused path: state lives in _fused_states
    fname = str(tmp_path / "states.bin")
    tr.save_states(fname)

    net2, tr2 = _build(9, "adam")
    prev = fusedstep.set_enabled(False)
    try:
        _step(net2, tr2)  # eager: attaches _opt_state
        assert all(hasattr(p, "_opt_state") for p in tr2._params)
        tr2.load_states(fname)
        # restored file carries fused state for every param -> stale
        # eager attributes are gone
        assert not any(hasattr(p, "_opt_state") for p in tr2._params)
        _step(net2, tr2)  # eager continue migrates from restored store
        for p in tr2._params:
            assert p._opt_state is not None
    finally:
        fusedstep.set_enabled(prev)


def test_save_states_survives_digit_boundary_name_order(tmp_path):
    """Trainer param order is the LEXICOGRAPHIC name sort, which flips
    layer order at digit boundaries (d10_* sorts before d9_*). The
    saved index<->layer mapping must align by CONSTRUCTION order, or a
    model whose global name counter crossed 9/10 loads another layer's
    momentum (caught live: shape-mismatch crash in a full-suite run)."""
    def build(p0, p1):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=8, prefix=p0))
        net.add(nn.Dense(4, in_units=16, prefix=p1))
        net.initialize(init=mx.initializer.Xavier())
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.05}, kvstore=None)
        return net, tr

    # saver: lexicographic order REVERSES the layers (d10_* < d9_*)
    netA, trA = build("d9_", "d10_")
    assert [p.name for p in trA._params][0].startswith("d10_")
    for _ in range(3):
        _step(netA, trA)
    fname = str(tmp_path / "states.bin")
    trA.save_states(fname)

    # loader: plain order — same structure, different index meaning
    netB, trB = build("e0_", "e1_")
    trB.load_states(fname)
    # every restored state must sit on the param of ITS OWN shape
    # (pre-fix: the 4-wide output-bias state landed on the 16-wide
    # hidden bias and vice versa)
    for p in trB._params:
        assert trB._fused_states[p.name][0].shape == \
            tuple(p.data().shape), p.name
    # the eager path migrates restored states into per-param updates —
    # the misalignment crashed here with a broadcast TypeError
    prev = fusedstep.set_enabled(False)
    try:
        _step(netB, trB)
    finally:
        fusedstep.set_enabled(prev)
    _step(netB, trB)  # and the fused path continues adam t: 3 -> 5
    for p in trB._params:
        assert int(trB._fused_states[p.name][2]) == 5, p.name


def test_save_states_file_is_numpy_only(tmp_path):
    """format-2 files carry no device-array pickles (portable across
    hosts/backends)."""
    import pickle

    net, tr = _build(0, "sgd")
    prev = fusedstep.set_enabled(False)
    try:
        for _ in range(2):
            _step(net, tr)  # eager path: NDArray states
    finally:
        fusedstep.set_enabled(prev)
    fname = str(tmp_path / "states.bin")
    tr.save_states(fname)
    blob = pickle.load(open(fname, "rb"))
    assert blob["format"] == 2

    def walk(o):
        if isinstance(o, dict):
            return all(walk(v) for v in o.values())
        if isinstance(o, (tuple, list)):
            return all(walk(v) for v in o)
        return isinstance(o, (np.ndarray, np.generic, int, float,
                              str, bytes, type(None)))

    assert walk(blob["states"]) and walk(blob["fused_states"])


# ---------------------------------------------------------------------------
# checkpoint metrics (documented in docs/observability.md)
# ---------------------------------------------------------------------------

def test_checkpoint_metrics_recorded(tmp_path):
    obs.set_enabled(True)
    net, tr = _build()
    mgr = resilience.CheckpointManager(tmp_path / "ck", every_n_steps=2,
                                       net=net, trainer=tr).attach(tr)
    try:
        for _ in range(4):
            _step(net, tr)
        mgr.flush()
    finally:
        mgr.close()
    assert obs.CHECKPOINT_TOTAL.total() == 2
    assert obs.CHECKPOINT_BYTES_TOTAL.total() > 0
    assert obs.CHECKPOINT_LAST_STEP.value() == 4.0
    text = obs.dump_prometheus()
    assert "mxtpu_checkpoint_total" in text
    assert "mxtpu_checkpoint_seconds" in text
