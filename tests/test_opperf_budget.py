"""Dispatch-latency budget gate (VERDICT r5 #6).

The opperf harness (benchmark/opperf.py) is the per-op record; this
smoke test makes dispatch-latency REGRESSIONS visible round-to-round by
failing the suite when the imperative path slows down. Budgets are ~6x
the measured r5 values on this container (eager add (4,4): ~0.023 ms;
record+backward roundtrip: ~2.3 ms), so environment jitter passes but a
dispatch-path regression (an accidental sync, a cache-key rebuild, a
tape-overhead blowup) fails loudly.

Reference analog: benchmark/opperf's use in MXNet CI to track
``Imperative::Invoke`` overhead.
"""

import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd

EAGER_BUDGET_MS = 0.15
BACKWARD_BUDGET_MS = 14.0


def _best_of(fn, reps=3):
    best = None
    for _ in range(reps):
        t = fn()
        best = t if best is None or t < best else best
    return best


def test_eager_dispatch_latency_budget():
    a = mx.nd.array(np.ones((4, 4), np.float32))
    b = mx.nd.array(np.ones((4, 4), np.float32))
    for _ in range(100):
        c = a + b  # warm the jit/attr caches

    def run():
        n = 1000
        t0 = time.perf_counter()
        for _ in range(n):
            c = a + b
        c.asnumpy()
        return (time.perf_counter() - t0) / n * 1e3

    ms = _best_of(run)
    assert ms < EAGER_BUDGET_MS, (
        f"eager dispatch {ms:.4f} ms/op exceeds the {EAGER_BUDGET_MS} ms "
        "budget — check ops/dispatch.py for new per-call work")


def test_record_backward_roundtrip_budget():
    a = mx.nd.array(np.ones((8, 8), np.float32))
    b = mx.nd.array(np.ones((8, 8), np.float32))
    a.attach_grad()
    for _ in range(10):
        with autograd.record():
            c = (a + b).sum()
        c.backward()

    def run():
        n = 100
        t0 = time.perf_counter()
        for _ in range(n):
            with autograd.record():
                c = (a + b).sum()
            c.backward()
        return (time.perf_counter() - t0) / n * 1e3

    ms = _best_of(run)
    assert ms < BACKWARD_BUDGET_MS, (
        f"record+backward {ms:.4f} ms exceeds the {BACKWARD_BUDGET_MS} ms "
        "budget — check autograd tape / vjp dispatch overhead")
