"""Profiler aggregate stats + print_summary (reference:
``tests/python/unittest/test_profiler.py`` ``test_aggregate_stats`` and
``test_viz.py``)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import profiler


def test_aggregate_stats_records_ops():
    profiler.set_config(aggregate_stats=True)
    try:
        a = mx.nd.ones((64, 64))
        for _ in range(3):
            b = mx.nd.dot(a, a)
        _ = b.asnumpy()
        table = profiler.dumps(reset=True)
    finally:
        profiler.set_config(aggregate_stats=False)
    assert "dot" in table
    line = [l for l in table.splitlines() if l.strip().startswith("dot")][0]
    fields = line.split()
    assert int(fields[1]) >= 3  # count
    assert float(fields[2]) > 0  # total ms
    # reset=True cleared the table
    assert "dot" not in profiler.dumps()


def test_aggregate_stats_off_by_default():
    a = mx.nd.ones((8, 8))
    _ = (a + a).asnumpy()
    assert "broadcast_add" not in profiler.dumps()


def test_print_summary_real_params(capsys):
    data = mx.sym.Variable("data")
    w1 = mx.sym.Variable("fc1_weight")
    b1 = mx.sym.Variable("fc1_bias")
    fc1 = mx.sym.FullyConnected(data, w1, b1, num_hidden=64, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    w2 = mx.sym.Variable("fc2_weight")
    b2 = mx.sym.Variable("fc2_bias")
    out = mx.sym.FullyConnected(act, w2, b2, num_hidden=10, name="fc2")
    total = mx.visualization.print_summary(out, shape={"data": (32, 128)})
    captured = capsys.readouterr().out
    # fc1: 128*64 + 64; fc2: 64*10 + 10
    expected = 128 * 64 + 64 + 64 * 10 + 10
    assert total == expected
    assert f"Total params: {expected}" in captured
    assert "32x64" in captured  # fc1 output shape
    assert "32x10" in captured  # fc2 output shape
    assert "-" not in [l.split()[1] for l in captured.splitlines()
                       if l.startswith("fc")]  # no placeholder shapes


def test_monitor_taps_op_outputs():
    """VERDICT r2 Missing #7: per-op output tapping, the reference
    ``mx.monitor.Monitor`` engine-callback workflow."""
    from mxnet_tpu.monitor import Monitor

    mon = Monitor(interval=1, pattern=".*").install_ops()
    try:
        mon.tic()
        a = mx.nd.ones((2, 3))
        b = a + a                     # broadcast_add dispatch
        c = mx.nd.dot(b, mx.nd.ones((3, 2)))
        rows = mon.toc()
        names = [k for _, k, _ in rows]
        assert any("dot" in n for n in names), names
        assert any("add" in n for n in names), names
        # stat values are real: |1+1| mean = 2, dot output mean = 6
        dot_val = [v for _, k, v in rows if "dot" in k][0]
        assert abs(float(dot_val) - 6.0) < 1e-5, dot_val
    finally:
        mon.uninstall_ops()

    # after uninstall the tap is off
    mon.tic()
    _ = mx.nd.ones((2,)) * 2
    assert mon.toc() == []


def test_monitor_pattern_filters_ops():
    from mxnet_tpu.monitor import Monitor

    mon = Monitor(interval=1, pattern=".*dot.*").install_ops()
    try:
        mon.tic()
        a = mx.nd.ones((2, 2))
        _ = a + a
        _ = mx.nd.dot(a, a)
        rows = mon.toc()
        assert rows and all("dot" in k for _, k, _ in rows), rows
    finally:
        mon.uninstall_ops()


def test_monitor_stats_not_taped():
    """Tapped stats must not land on the autograd tape (they would pin
    vjp closures until toc)."""
    from mxnet_tpu import autograd
    from mxnet_tpu.monitor import Monitor

    mon = Monitor(interval=1).install_ops()
    try:
        mon.tic()
        a = mx.nd.ones((2, 2))
        a.attach_grad()
        with autograd.record():
            b = a * 2
            _ = (b * b).sum()
        assert mon.queue, "nothing tapped under record()"
        for _, _, stat in mon.queue:
            assert getattr(stat, "_ag", None) is None, "stat on the tape"
    finally:
        mon.uninstall_ops()
