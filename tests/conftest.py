"""Test configuration: force the XLA:CPU backend with 8 virtual devices.

Mirrors the reference's test strategy (SURVEY.md §4): the CPU suite is the
source of truth; TPU runs reuse it by flipping the default context. The
8-device host platform lets collective/sharding tests run without TPUs.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

# the axon sitecustomize pins JAX_PLATFORMS=axon; override before first use
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest


def natsort_key(name):
    """Natural sort key: ``dense2`` < ``dense10``. A PLAIN string sort
    swaps layers the moment the process-global gluon auto-name counter
    crosses a digit boundary mid-session (dense99 -> dense100 sorts
    before dense99's peers), silently pairing the wrong layers in any
    test that zips two sorted ``collect_params()`` views — a latent
    order-dependent flake (PR 10 hit it in test_overlap_zero)."""
    import re

    return [int(t) if t.isdigit() else t
            for t in re.split(r"(\d+)", name)]


def natsorted_items(items):
    """``(name, value)`` pairs sorted by NATURAL name order — the one
    way tests should order ``collect_params().items()`` / fused-state
    dicts (see :func:`natsort_key`)."""
    return sorted(items, key=lambda kv: natsort_key(kv[0]))


def pytest_configure(config):
    # XLA:CPU has no buffer donation; the fused step donates anyway
    # (no-op) and jax warns once per compiled function — pure noise here
    config.addinivalue_line(
        "filterwarnings",
        "ignore:Some donated buffers were not usable")
    # the 8 pre-existing multi-process failures (the container cannot
    # host spawned multi-process JAX workers): select with
    # `-m dist_baseline`, exclude with `-m 'not dist_baseline'` —
    # tier-1 triage without grepping test names
    config.addinivalue_line(
        "markers",
        "dist_baseline: known-environmental distributed multiprocess "
        "failures (launcher-spawned workers need real multi-core); "
        "diff tier-1 results against this set, not against zero")
    config.addinivalue_line(
        "markers",
        "slow: long-running tests (process-replica spawn/compile, "
        "multi-second chaos drills) — excluded from tier-1 via "
        "`-m 'not slow'`")


@pytest.fixture(autouse=True)
def _seed_rngs():
    """Deterministic per-test RNG (reference: common.py:with_seed)."""
    import random

    import numpy as np

    import mxnet_tpu as mx

    np.random.seed(1234)
    random.seed(1234)
    mx.random.seed(1234)
    yield
