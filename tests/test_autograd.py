"""Tape autograd tests (reference model: test_autograd.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import assert_almost_equal


def test_simple_grad():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + 2 * x
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy() + 2)


def test_chain_and_branches():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        a = x * 3
        b = a * a + x
    b.backward()
    # d/dx (9x^2 + x) = 18x + 1 = 37
    assert_almost_equal(x.grad, np.array([37.0], np.float32))


def test_grad_req_add():
    x = mx.nd.array([1.0, 1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = 2 * x
        y.backward()
    assert_almost_equal(x.grad, np.array([6.0, 6.0], np.float32))


def test_grad_req_null():
    x = mx.nd.array([1.0])
    x.attach_grad(grad_req="null")
    assert x.grad is None


def test_head_gradient():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(mx.nd.array([2.0, 0.5]))
    assert_almost_equal(x.grad, np.array([4.0, 2.0], np.float32))


def test_detach():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, np.array([4.0], np.float32))  # y treated const


def test_stop_gradient_op():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.stop_gradient(x * x) * x
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0], np.float32))


def test_pause():
    x = mx.nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = y * 3  # not recorded
        w = y + 1
    w.backward()
    assert_almost_equal(x.grad, np.array([2.0], np.float32))


def test_train_predict_mode():
    assert not autograd.is_training()
    with autograd.record(train_mode=True):
        assert autograd.is_training()
        assert autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
            assert autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()
        assert not autograd.is_recording()


def test_intermediate_attach_grad():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        y.attach_grad()
        z = y * 2
    z.backward()
    assert_almost_equal(y.grad, np.array([2.0], np.float32))


def test_autograd_grad_api():
    x = mx.nd.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    (gx,) = [autograd.grad(y, [x])[0]] if False else [autograd.grad(y, [x])[0]]
    assert_almost_equal(gx, 2 * x.asnumpy())


def test_multi_output_backward():
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        parts = mx.nd.split(x, num_outputs=2, axis=1)
        s = parts[0].sum() + (parts[1] * 2).sum()
    s.backward()
    assert_almost_equal(x.grad, np.array([[1, 2], [1, 2]], np.float32))


def test_retain_graph():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert_almost_equal(x.grad, g1)  # write (not add) semantics


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1 / (1 + mx.nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = mx.nd.array([0.5, -1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, sig * (1 - sig), rtol=1e-4, atol=1e-5)


def test_backward_through_mutation_snapshot():
    """The tape captures values at op time; later mutation doesn't corrupt it."""
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    x *= 10  # mutate after record
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0], np.float32))


def test_setitem_gradient_flow():
    """Recorded slice-assign (reference `_slice_assign` FGradient):
    gradients are zeroed through overwritten base positions AND flow
    into a tracked assigned value."""
    x = mx.nd.ones((4,))
    x.attach_grad()
    v = mx.nd.array(np.array([5.0], np.float32))
    v.attach_grad()
    with autograd.record():
        y = x * 3
        y[1:2] = v * 2
        s = (y * y).sum()
    s.backward()
    # y = [3, 2v, 3, 3]; ds/dx_i = 2*y_i*3 = 18 except overwritten idx -> 0
    np.testing.assert_allclose(x.grad.asnumpy(), [18, 0, 18, 18])
    # ds/dv = 2*(2v)*2 = 8v = 40
    np.testing.assert_allclose(v.grad.asnumpy(), [40.0])


def test_setitem_outside_record_unchanged():
    x = mx.nd.zeros((3,))
    x[1] = 7.0
    np.testing.assert_allclose(x.asnumpy(), [0, 7, 0])


def test_setitem_on_leaf_zeroes_overwritten_grad():
    """Review regression: in-place assign on an attach_grad LEAF must
    zero gradients through overwritten positions (snapshot keeps the
    leaf's tracking)."""
    a = mx.nd.ones((4,))
    a.attach_grad()
    v = mx.nd.array(np.array([5.0], np.float32))
    v.attach_grad()
    with autograd.record():
        a[1:2] = v
        s = (a * a).sum()
    s.backward()
    # a = [1, 5, 1, 1]; ds/da_i = 2*a_i except the overwritten slot -> 0
    np.testing.assert_allclose(a.grad.asnumpy(), [2, 0, 2, 2])
    np.testing.assert_allclose(v.grad.asnumpy(), [10.0])


def test_setitem_preserves_pre_mutation_consumers():
    """Review regression: consumers recorded BEFORE an in-place assign
    must keep their gradients (cotangents route via record-time slots)."""
    a = mx.nd.ones((4,))
    a.attach_grad()
    with autograd.record():
        b = (a * 2).sum()
        a[1:2] = 5.0
    b.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [2, 2, 2, 2])


def test_setitem_grad_req_add_no_double_count():
    """Review regression: grad_req='add' on a mutated leaf must not
    double-count via the shared grad buffer."""
    a = mx.nd.ones((4,))
    a.attach_grad(grad_req="add")
    v = mx.nd.array(np.array([5.0], np.float32))
    v.attach_grad()
    with autograd.record():
        a[1:2] = v
        s = (a * a).sum()
    s.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [2, 0, 2, 2])
    np.testing.assert_allclose(v.grad.asnumpy(), [10.0])


def test_get_symbol_exports_tape():
    """autograd.get_symbol (reference: MXAutogradGetSymbol) exports the
    recorded tape as a Symbol that round-trips through Symbol.save +
    SymbolBlock.imports with identical outputs."""
    import os
    import tempfile

    import numpy as np

    from mxnet_tpu import gluon

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(4, 6).astype(np.float32))
    w1 = mx.nd.array(rng.randn(8, 6).astype(np.float32) * 0.3)
    b1 = mx.nd.array(np.zeros(8, np.float32))
    w2 = mx.nd.array(rng.randn(3, 8).astype(np.float32) * 0.3)
    for a in (x, w1, b1, w2):
        a.attach_grad()
    with autograd.record():
        h = mx.nd.relu(mx.nd.FullyConnected(x, w1, b1, num_hidden=8))
        out = mx.nd.FullyConnected(h, w2, no_bias=True, num_hidden=3)
    sym = autograd.get_symbol(out)
    args = sym.list_arguments()
    assert len(args) == 4, args

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "tape-symbol.json")
        sym.save(path)
        # identify which varN is which by shape
        shapes = {"var0": x, "var1": w1, "var2": b1, "var3": w2}
        net = gluon.SymbolBlock.imports(path, ["var0"])
        for name, p in net.collect_params().items():
            p._load_init(shapes[name], None)
        y2 = net(x)
    np.testing.assert_allclose(y2.asnumpy(), out.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_get_symbol_untracked_raises():
    x = mx.nd.array([1.0, 2.0])
    with pytest.raises(mx.base.MXNetError):
        autograd.get_symbol(x)
