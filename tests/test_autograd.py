"""Tape autograd tests (reference model: test_autograd.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import assert_almost_equal


def test_simple_grad():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + 2 * x
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy() + 2)


def test_chain_and_branches():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        a = x * 3
        b = a * a + x
    b.backward()
    # d/dx (9x^2 + x) = 18x + 1 = 37
    assert_almost_equal(x.grad, np.array([37.0], np.float32))


def test_grad_req_add():
    x = mx.nd.array([1.0, 1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = 2 * x
        y.backward()
    assert_almost_equal(x.grad, np.array([6.0, 6.0], np.float32))


def test_grad_req_null():
    x = mx.nd.array([1.0])
    x.attach_grad(grad_req="null")
    assert x.grad is None


def test_head_gradient():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(mx.nd.array([2.0, 0.5]))
    assert_almost_equal(x.grad, np.array([4.0, 2.0], np.float32))


def test_detach():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, np.array([4.0], np.float32))  # y treated const


def test_stop_gradient_op():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.stop_gradient(x * x) * x
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0], np.float32))


def test_pause():
    x = mx.nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = y * 3  # not recorded
        w = y + 1
    w.backward()
    assert_almost_equal(x.grad, np.array([2.0], np.float32))


def test_train_predict_mode():
    assert not autograd.is_training()
    with autograd.record(train_mode=True):
        assert autograd.is_training()
        assert autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
            assert autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()
        assert not autograd.is_recording()


def test_intermediate_attach_grad():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        y.attach_grad()
        z = y * 2
    z.backward()
    assert_almost_equal(y.grad, np.array([2.0], np.float32))


def test_autograd_grad_api():
    x = mx.nd.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    (gx,) = [autograd.grad(y, [x])[0]] if False else [autograd.grad(y, [x])[0]]
    assert_almost_equal(gx, 2 * x.asnumpy())


def test_multi_output_backward():
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        parts = mx.nd.split(x, num_outputs=2, axis=1)
        s = parts[0].sum() + (parts[1] * 2).sum()
    s.backward()
    assert_almost_equal(x.grad, np.array([[1, 2], [1, 2]], np.float32))


def test_retain_graph():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert_almost_equal(x.grad, g1)  # write (not add) semantics


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1 / (1 + mx.nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = mx.nd.array([0.5, -1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, sig * (1 - sig), rtol=1e-4, atol=1e-5)


def test_backward_through_mutation_snapshot():
    """The tape captures values at op time; later mutation doesn't corrupt it."""
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    x *= 10  # mutate after record
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0], np.float32))
