"""Higher-order autograd (reference model: test_higher_order_grad.py).

Exercises ``mx.autograd.grad(..., create_graph=True)``: the tape-replay
path records the gradient computation as a new tape node, so 2nd and 3rd
derivatives compose (reference: ``Imperative::Backward`` create_graph).
"""

import numpy as np
import pytest
from conftest import natsorted_items

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import assert_almost_equal


def _check_second_order_unary(x_np, fwd, expect_grad_grad):
    """Reference pattern: grad-of-grad of an elementwise op via
    create_graph=True then .backward() on the first-order grad."""
    x = mx.nd.array(x_np)
    x.attach_grad()
    with autograd.record():
        y = fwd(x)
        gx = autograd.grad(y, x, create_graph=True, retain_graph=True)
    gx.backward()
    assert_almost_equal(x.grad, expect_grad_grad(x_np), rtol=1e-5, atol=1e-6)


def test_sin_second_order():
    x = np.random.uniform(-2, 2, (3, 4)).astype(np.float32)
    _check_second_order_unary(x, mx.nd.sin, lambda v: -np.sin(v))


def test_cos_second_order():
    x = np.random.uniform(-2, 2, (5,)).astype(np.float32)
    _check_second_order_unary(x, mx.nd.cos, lambda v: -np.cos(v))


def test_exp_second_order():
    x = np.random.uniform(-1, 1, (4,)).astype(np.float32)
    _check_second_order_unary(x, mx.nd.exp, np.exp)


def test_log_second_order():
    x = np.random.uniform(0.5, 3, (6,)).astype(np.float32)
    _check_second_order_unary(x, mx.nd.log, lambda v: -1.0 / v ** 2)


def test_sigmoid_second_order():
    x = np.random.uniform(-2, 2, (4,)).astype(np.float32)

    def expect(v):
        s = 1 / (1 + np.exp(-v))
        return s * (1 - s) * (1 - 2 * s)

    _check_second_order_unary(x, mx.nd.sigmoid, expect)


def test_relu_second_order():
    x = np.random.uniform(-2, 2, (8,)).astype(np.float32)
    _check_second_order_unary(x, mx.nd.relu, lambda v: np.zeros_like(v))


def test_polynomial_third_order():
    v = np.array([0.5, 1.5, -2.0], np.float32)
    x = mx.nd.array(v)
    x.attach_grad()
    with autograd.record():
        y = x ** 4
        g1 = autograd.grad(y, x, create_graph=True, retain_graph=True)
        g2 = autograd.grad(g1, x, create_graph=True, retain_graph=True)
    g2.backward()
    assert_almost_equal(x.grad, 24 * v, rtol=1e-5)


def test_two_variables_second_order():
    a_np = np.random.rand(3, 4).astype(np.float32)
    b_np = np.random.rand(4, 2).astype(np.float32)
    a, b = mx.nd.array(a_np), mx.nd.array(b_np)
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        z = (mx.nd.dot(a, b) ** 2).sum()
        ga, gb = autograd.grad(z, [a, b], create_graph=True,
                               retain_graph=True)
        s = (ga * ga).sum()
    s.backward()
    # z = sum(M^2), M = a@b; ga = 2*M@b.T; s = sum(ga^2)
    # ds/da = 2*ga * d(ga)/da contracted: d(ga)/da = 2*(I kron b)@b.T ...
    # verify against a JAX reference instead of hand algebra
    import jax
    import jax.numpy as jnp

    def s_of_a(ar):
        ga_ = jax.grad(lambda aa: jnp.sum((aa @ b_np) ** 2))(ar)
        return jnp.sum(ga_ ** 2)

    expect = jax.grad(s_of_a)(a_np)
    assert_almost_equal(a.grad, np.asarray(expect), rtol=1e-4, atol=1e-5)


def test_grad_grad_with_head_grads():
    v = np.array([1.0, 2.0], np.float32)
    x = mx.nd.array(v)
    x.attach_grad()
    w = mx.nd.array(np.array([3.0, 5.0], np.float32))
    with autograd.record():
        y = x ** 3
        gx = autograd.grad(y, x, head_grads=w, create_graph=True,
                           retain_graph=True)
    gx.backward()
    # gx = w * 3x^2; d(gx)/dx = w * 6x
    assert_almost_equal(x.grad, np.array([3.0, 5.0]) * 6 * v, rtol=1e-5)


def test_create_graph_through_block():
    """Second order through a small Gluon net (dense + activation)."""
    from mxnet_tpu import gluon

    net = gluon.nn.Dense(4, in_units=3)
    net.initialize()
    x = mx.nd.array(np.random.rand(2, 3).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.tanh(net(x)).sum()
        gx = autograd.grad(y, x, create_graph=True, retain_graph=True)
        s = (gx * gx).sum()
    s.backward()
    assert x.grad.shape == x.shape
    assert np.isfinite(x.grad.asnumpy()).all()


def test_create_graph_through_hybridized_block():
    """Second order through a hybridized block (CachedOp replay)."""
    from mxnet_tpu import gluon

    net = gluon.nn.Dense(4, in_units=3)
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.rand(2, 3).astype(np.float32))
    x.attach_grad()
    net(x)  # build the cache
    with autograd.record():
        y = mx.nd.tanh(net(x)).sum()
        gx = autograd.grad(y, x, create_graph=True, retain_graph=True)
        s = (gx * gx).sum()
    s.backward()
    # cross-check against the non-hybridized second-order result
    net2 = gluon.nn.Dense(4, in_units=3)
    net2.initialize()
    # natural sort (conftest): a plain sort swaps layers when the gluon
    # auto-name counter straddles a digit boundary, pairing p1/p2 wrong
    for (k1, p1), (k2, p2) in zip(
            natsorted_items(net.collect_params().items()),
            natsorted_items(net2.collect_params().items())):
        p2.set_data(p1.data())
    x2 = mx.nd.array(x.asnumpy())
    x2.attach_grad()
    with autograd.record():
        y2 = mx.nd.tanh(net2(x2)).sum()
        gx2 = autograd.grad(y2, x2, create_graph=True, retain_graph=True)
        s2 = (gx2 * gx2).sum()
    s2.backward()
    assert_almost_equal(x.grad, x2.grad.asnumpy(), rtol=1e-4, atol=1e-5)


def test_create_graph_outside_record_raises():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x ** 2
    with pytest.raises(mx.base.MXNetError):
        autograd.grad(y, x, create_graph=True, retain_graph=True)


def test_create_graph_after_mutation_raises():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        x += 1
        z = y + x
        with pytest.raises(mx.base.MXNetError):
            autograd.grad(z, x, create_graph=True, retain_graph=True)


def test_create_graph_requires_tracked():
    x = mx.nd.array([1.0])
    x.attach_grad()
    z = mx.nd.array([2.0])  # never tracked
    with autograd.record():
        y = x * 2
        with pytest.raises(mx.base.MXNetError):
            autograd.grad(y, z, create_graph=True, retain_graph=True)


def test_custom_function_raises():
    class Square(autograd.Function):
        def forward(self, x):
            return x * x

        def backward(self, dy):
            return 2 * dy

    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = Square()(x)
        with pytest.raises(mx.base.MXNetError):
            autograd.grad(y, x, create_graph=True, retain_graph=True)
