"""Pipeline parallelism (P10) + MoE expert parallelism (P12) on the
8-device virtual CPU mesh — the two strategies the reference lacks
entirely (SURVEY.md §2.5), built TPU-native."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.parallel import moe as moe_mod
from mxnet_tpu.parallel.pipeline import (PipelineTrainStep, pipeline_apply,
                                         shard_stages, stack_stage_params)


D = 16


def _stage_fn(params, x):
    return jax.nn.relu(x @ params["w"] + params["b"])


def _make_stages(S, seed=0):
    # near-identity init: signal survives 8 relu stages, so the
    # convergence test trains in tens of steps
    rng = np.random.RandomState(seed)
    eye = np.eye(D, dtype=np.float32)
    return [{"w": jnp.asarray(eye + rng.randn(D, D).astype(np.float32)
                              * 0.05),
             "b": jnp.asarray(np.full(D, 0.05, np.float32))}
            for _ in range(S)]


@pytest.mark.slow
def test_pipeline_matches_sequential():
    S, B = 8, 8
    mesh = parallel.make_mesh({"pp": S})
    stages = _make_stages(S)
    stacked = shard_stages(stack_stage_params(stages), mesh)
    x = jnp.asarray(np.random.RandomState(1).randn(B, D).astype(np.float32))

    got = pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=4)
    want = x
    for p in stages:
        want = _stage_fn(p, want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_pipeline_grads_match_sequential():
    S, B = 8, 8
    mesh = parallel.make_mesh({"pp": S})
    stages = _make_stages(S, seed=2)
    stacked = shard_stages(stack_stage_params(stages), mesh)
    x = jnp.asarray(np.random.RandomState(3).randn(B, D).astype(np.float32))

    def loss_pipe(params):
        return jnp.sum(pipeline_apply(_stage_fn, params, x, mesh,
                                      num_microbatches=4) ** 2)

    def loss_seq(stage_list):
        h = x
        for p in stage_list:
            h = _stage_fn(p, h)
        return jnp.sum(h ** 2)

    g_pipe = jax.grad(loss_pipe)(stack_stage_params(stages))
    g_seq = jax.grad(loss_seq)(stages)
    for si in range(S):
        np.testing.assert_allclose(
            np.asarray(g_pipe["w"][si]), np.asarray(g_seq[si]["w"]),
            rtol=1e-4, atol=1e-5)


def test_pipeline_train_step_converges():
    S, B = 8, 16
    mesh = parallel.make_mesh({"pp": S})
    stages = _make_stages(S, seed=4)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    w_true = rng.randn(D, D).astype(np.float32) * 0.4
    y = jnp.tanh(x @ jnp.asarray(w_true))  # learnable target

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    step = PipelineTrainStep(_stage_fn, stack_stage_params(stages), mesh,
                             loss_fn, num_microbatches=4)
    losses = [float(step(x, y, lr=0.05)) for _ in range(80)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_pipeline_bad_microbatch_raises():
    from mxnet_tpu.base import MXNetError

    mesh = parallel.make_mesh({"pp": 8})
    stages = _make_stages(8)
    stacked = shard_stages(stack_stage_params(stages), mesh)
    x = jnp.zeros((7, D), jnp.float32)
    with pytest.raises(MXNetError):
        pipeline_apply(_stage_fn, stacked, x, mesh, num_microbatches=4)


# ---------------------------------------------------------------------------
# MoE
# ---------------------------------------------------------------------------


def test_moe_routing_properties():
    T, E, C = 12, 4, 6
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    dispatch, combine, aux = moe_mod.top1_routing(logits, E, C)
    d = np.asarray(dispatch)
    # each token goes to at most one (expert, slot)
    assert (d.sum(axis=(1, 2)) <= 1.0 + 1e-6).all()
    # no slot is double-booked
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()
    assert float(aux) > 0


def test_moe_matches_dense_expert_eval():
    """Expert-parallel moe_apply == evaluating each token's top-1 expert
    directly (no capacity pressure)."""
    T, D_, H, E = 16, 8, 32, 8
    key = jax.random.PRNGKey(0)
    params = moe_mod.init_moe_params(key, D_, H, E)
    x = jnp.asarray(np.random.RandomState(1).randn(T, D_).astype(np.float32))

    mesh = parallel.make_mesh({"ep": 8})
    sparams = moe_mod.shard_moe_params(params, mesh)
    out, aux = moe_mod.moe_apply(sparams, x, mesh=mesh, capacity_factor=8.0)

    # direct evaluation
    probs = jax.nn.softmax(x @ params["gate"], axis=-1)
    expert = np.asarray(jnp.argmax(probs, axis=-1))
    want = np.zeros((T, D_), np.float32)
    for t in range(T):
        e = int(expert[t])
        h = np.maximum(np.asarray(x[t]) @ np.asarray(params["w1"][e]), 0)
        want[t] = (h @ np.asarray(params["w2"][e])) \
            * float(probs[t, e])
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_tokens():
    T, D_, H, E = 16, 8, 16, 2
    params = moe_mod.init_moe_params(jax.random.PRNGKey(1), D_, H, E)
    # force every token to expert 0 via the gate
    params["gate"] = params["gate"].at[:, 0].set(10.0)
    x = jnp.ones((T, D_), jnp.float32)
    out, _ = moe_mod.moe_apply(params, x, mesh=None, capacity_factor=0.5)
    # capacity = T/E * 0.5 = 4 slots; the rest drop to zero output
    nonzero = np.asarray((jnp.abs(out).sum(axis=1) > 1e-9))
    assert nonzero.sum() == 4, nonzero.sum()


def test_moe_trains_with_aux_loss():
    T, D_, H, E = 32, 8, 16, 8
    mesh = parallel.make_mesh({"ep": 8})
    params = moe_mod.shard_moe_params(
        moe_mod.init_moe_params(jax.random.PRNGKey(2), D_, H, E), mesh)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(T, D_).astype(np.float32))
    w_true = rng.randn(D_, D_).astype(np.float32) * 0.5
    y = jnp.tanh(x @ jnp.asarray(w_true))  # learnable target

    @jax.jit
    def train(params, x, y):
        def loss_of(p):
            out, aux = moe_mod.moe_apply(p, x, mesh=mesh,
                                         capacity_factor=4.0)
            return jnp.mean((out - y) ** 2) + 0.01 * aux

        loss, grads = jax.value_and_grad(loss_of)(params)
        return jax.tree_util.tree_map(lambda p, g: p - 0.2 * g, params,
                                      grads), loss

    losses = []
    for _ in range(120):
        params, l = train(params, x, y)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.75, (losses[0], losses[-1])


def test_top2_routing_properties():
    T, E, C = 12, 4, 8
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    dispatch, combine, aux = moe_mod.top2_routing(logits, E, C)
    d = np.asarray(dispatch)
    c = np.asarray(combine)
    # each token lands in at most two (expert, slot) cells
    per_token = d.sum(axis=(1, 2))
    assert (per_token <= 2.0 + 1e-6).all()
    assert (per_token >= 2.0 - 1e-6).all()  # ample capacity: both kept
    # no slot double-booked
    assert (d.reshape(T, -1).sum(axis=0) <= 1.0 + 1e-6).all()
    # combine weights renormalize over the two kept choices
    np.testing.assert_allclose(c.sum(axis=(1, 2)), np.ones(T),
                               rtol=1e-5)
    # aux is the GShard load-balance form over FIRST choices
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))
    e1 = probs.argmax(axis=-1)
    frac = np.bincount(e1, minlength=E) / float(T)
    want_aux = E * float((frac * probs.mean(axis=0)).sum())
    np.testing.assert_allclose(float(aux), want_aux, rtol=1e-5)


def test_top2_congestion_drops_second_choices_first():
    T, E = 8, 2
    # every token: expert 0 first choice, expert 1 second choice
    logits = jnp.asarray(np.tile([4.0, 2.0], (T, 1)).astype(np.float32))
    C = T  # expert 0 fits all first choices; expert 1 queues behind
    dispatch, _, _ = moe_mod.top2_routing(logits, E, C)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == T  # every first choice kept
    # second choices queue behind cnt1(expert1)=0 -> all kept too at C=T
    assert d[:, 1].sum() == T
    # now congest: same-expert second choices must drop before firsts
    logits2 = jnp.asarray(np.tile([4.0, 3.9], (T, 1)).astype(np.float32))
    C2 = T // 2
    d2 = np.asarray(moe_mod.top2_routing(logits2, E, C2)[0])
    # expert 0 holds exactly its capacity of first choices
    assert d2[:, 0].sum() == C2
    assert (d2[:C2, 0].sum(axis=1) == 1.0).all()  # earliest tokens kept


def test_top2_capacity_drop_determinism():
    T, E, C = 32, 4, 3  # heavy congestion: drops happen
    rng = np.random.RandomState(5)
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    a = moe_mod.top2_routing(logits, E, C)
    b = moe_mod.top2_routing(logits, E, C)
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))
    d = np.asarray(a[0])
    assert d.sum() < 2 * T  # congestion actually dropped something
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()


# two ep=8 shard_map compiles (~20 s) for one equivalence property
@pytest.mark.slow
def test_moe_a2a_comm_modes_agree():
    """chunked / serial / nocomm are relayouts of the SAME math — the
    all-to-all placement must not change the result."""
    T, D_, H, E = 32, 8, 16, 8
    mesh = parallel.make_mesh({"ep": 8})
    params = moe_mod.shard_moe_params(
        moe_mod.init_moe_params(jax.random.PRNGKey(3), D_, H, E), mesh)
    x = jnp.asarray(np.random.RandomState(4).randn(T, D_)
                    .astype(np.float32))
    outs = {}
    for comm in ("chunked", "serial", "nocomm"):
        out, aux = moe_mod.moe_apply_a2a(params, x, mesh, router="top2",
                                         capacity_factor=8.0, chunks=2,
                                         comm=comm)
        outs[comm] = np.asarray(out)
        assert np.isfinite(float(aux))
        assert outs[comm].shape == (T, D_)
    # nocomm is a shape-identical LOCAL relayout (the pure-compute
    # timing baseline) — only the real-exchange modes are equivalent
    np.testing.assert_allclose(outs["serial"], outs["chunked"],
                               rtol=1e-5, atol=1e-6)


def test_measure_moe_overlap_probe():
    mesh = parallel.make_mesh({"ep": 8})
    rep = moe_mod.measure_moe_overlap(mesh, d_model=8, d_hidden=16,
                                      steps=2, warmup=1)
    assert set(rep) == {"exposed", "hidden_fraction", "step_seconds"}
    assert -1.0 <= rep["hidden_fraction"] <= 1.0
    assert rep["exposed"]["chunked"] >= 0.0
    assert rep["exposed"]["serial"] >= 0.0


# functional parity (moe_matches_dense_expert_eval) stays tier-1;
# this gluon-wrapper twin of the same dense-equivalence ride -m slow
@pytest.mark.slow
def test_gluon_moe_dense_layer():
    """MoE through the Gluon surface: eager + hybridized + trained."""
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.contrib.nn import MoEDense

    layer = MoEDense(units=8, hidden_units=16, num_experts=4,
                     capacity_factor=4.0)
    layer.initialize(init=mx.initializer.Normal(0.1))
    x = mx.nd.random.normal(shape=(2, 6, 8))
    out, aux = layer(x)
    assert out.shape == (2, 6, 8)
    assert np.isfinite(float(aux.asnumpy()))

    eager = out.asnumpy()
    layer.hybridize()
    out2, aux2 = layer(x)
    np.testing.assert_allclose(out2.asnumpy(), eager, rtol=1e-5, atol=1e-6)

    # trains: grads reach gate AND experts through the tape
    trainer = gluon.Trainer(layer.collect_params(), "adam",
                            {"learning_rate": 5e-2})
    y = mx.nd.random.normal(shape=(2, 6, 8))
    losses = []
    for _ in range(40):
        with autograd.record():
            o, aux = layer(x)
            l = ((o - y) ** 2).mean() + 0.01 * aux
        l.backward()
        trainer.step(2)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
