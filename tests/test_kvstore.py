"""KVStore tests (reference model: tests/python/unittest/test_kvstore.py)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal

SHAPE = (4, 4)


def test_init_pull():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones(SHAPE))
    out = mx.nd.zeros(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.ones(SHAPE, np.float32))


def test_push_aggregation():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones(SHAPE))
    # push from 4 "devices": merged value is the sum
    kv.push(3, [mx.nd.ones(SHAPE) * 2] * 4)
    out = mx.nd.zeros(SHAPE)
    kv.pull(3, out=out)
    assert_almost_equal(out, np.full(SHAPE, 8.0, np.float32))


def test_pushpull_allreduce_semantics():
    """Trainer path: aggregate + broadcast WITHOUT touching stored weight."""
    kv = mx.kv.create("device")
    kv.init("w", mx.nd.ones(SHAPE))
    grads = [mx.nd.ones(SHAPE) * i for i in range(1, 4)]
    kv.pushpull("w", grads, out=grads)
    for g in grads:
        assert_almost_equal(g, np.full(SHAPE, 6.0, np.float32))
    stored = mx.nd.zeros(SHAPE)
    kv.pull("w", out=stored)
    assert_almost_equal(stored, np.ones(SHAPE, np.float32))  # untouched


def test_updater():
    kv = mx.kv.create("local")
    kv.init(1, mx.nd.ones(SHAPE))

    def updater(key, grad, weight):
        weight -= 0.1 * grad

    kv.set_updater(updater)
    kv.push(1, [mx.nd.ones(SHAPE)] * 2)  # merged grad = 2
    out = mx.nd.zeros(SHAPE)
    kv.pull(1, out=out)
    assert_almost_equal(out, np.full(SHAPE, 0.8, np.float32))


def test_set_optimizer():
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.ones(SHAPE))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    kv.push(0, [mx.nd.ones(SHAPE)])
    out = mx.nd.zeros(SHAPE)
    kv.pull(0, out=out)
    assert_almost_equal(out, np.full(SHAPE, 0.5, np.float32))


def test_list_kv():
    kv = mx.kv.create("local")
    keys = [5, 7, 9]
    kv.init(keys, [mx.nd.ones(SHAPE)] * 3)
    kv.push(keys, [[mx.nd.ones(SHAPE) * 4]] * 3)
    outs = [mx.nd.zeros(SHAPE) for _ in keys]
    kv.pull(keys, out=outs)
    for o in outs:
        assert_almost_equal(o, np.full(SHAPE, 4.0, np.float32))


def test_dist_tpu_sync_single_process():
    kv = mx.kv.create("dist_tpu_sync")
    assert kv.rank == 0
    assert kv.num_workers == 1
    kv.init("x", mx.nd.ones(SHAPE))
    kv.push("x", [mx.nd.ones(SHAPE) * 3])
    out = mx.nd.zeros(SHAPE)
    kv.pull("x", out=out)
    assert_almost_equal(out, np.full(SHAPE, 3.0, np.float32))
    kv.barrier()


def test_type_aliases():
    assert mx.kv.create("nccl").type == "nccl"
    assert mx.kv.create("dist_sync").rank == 0


def test_row_sparse_pull():
    kv = mx.kv.create("local")
    w = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    kv.init("emb", w)
    out = mx.nd.zeros((4, 3))
    kv.row_sparse_pull("emb", out=out, row_ids=mx.nd.array([1, 3]))
    expected = np.zeros((4, 3), np.float32)
    expected[[1, 3]] = w.asnumpy()[[1, 3]]
    assert_almost_equal(out, expected)


def test_grouped_pushpull_multidevice():
    """The fused multi-key pushpull gathers per-device values to one
    device before the single jitted sum (review regression: committed
    arrays on different devices cannot feed one jit call)."""
    import jax

    import numpy as np

    kv = mx.kv.create("device")
    devs = jax.devices()
    assert len(devs) >= 2
    keys = ["a", "b", "c"]
    shapes = [(4, 3), (5,), (2, 2)]
    outs = []
    vals = []
    rng = np.random.RandomState(0)
    expect = []
    for k, sh in zip(keys, shapes):
        kv.init(k, mx.nd.zeros(sh))
        per_dev = []
        tot = np.zeros(sh, np.float32)
        for d in devs[:2]:
            a = rng.rand(*sh).astype(np.float32)
            tot += a
            nd = mx.nd.array(a)
            nd._set_data(jax.device_put(nd.data, d))
            per_dev.append(nd)
        vals.append(per_dev)
        outs.append(mx.nd.zeros(sh))
        expect.append(tot)
    kv.pushpull(keys, vals, out=outs)
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(o.asnumpy(), e, rtol=1e-6)
