"""Fused one-dispatch train step (PR3 tentpole): dispatch-count
regression, fused-vs-eager parity, bucketed-allreduce round-trips, and
the fallback contract (never wrong answers, loudly logged)."""

import numpy as np
import pytest
from conftest import natsorted_items

import mxnet_tpu as mx
from mxnet_tpu import autograd, fusedstep, gluon, observability as obs
from mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _fused_on():
    prev = fusedstep.set_enabled(True)
    yield
    fusedstep.set_enabled(prev)


def _sorted_weights(net):
    # param names carry run-dependent global prefixes; NATURAL sort
    # (conftest) keeps layer order stable across the gluon auto-name
    # counter's digit boundaries (dense99 -> dense100) and pairs two
    # nets built back-to-back positionally
    return [p.data().asnumpy() for _, p in
            natsorted_items(net.collect_params().items())]


def _build_mlp(n_hidden, width=16, in_units=8, classes=3):
    net = nn.HybridSequential()
    for _ in range(n_hidden):
        net.add(nn.Dense(width, activation="relu", in_units=in_units))
        in_units = width
    net.add(nn.Dense(classes, in_units=in_units))
    net.initialize(init=mx.initializer.Xavier())
    return net


def _train(fused, steps=5, opt="sgd", opt_params=None, n_hidden=2,
           hybridize=True, kvstore=None, lr_schedule=None, mults=False):
    prev = fusedstep.set_enabled(fused)
    try:
        mx.random.seed(0)
        np.random.seed(0)
        net = _build_mlp(n_hidden)
        if hybridize:
            net.hybridize()
        params = dict(opt_params or {})
        if lr_schedule:
            params["lr_scheduler"] = lr_schedule()
        if mults:
            for k, p in net.collect_params().items():
                if "bias" in k:
                    p.lr_mult, p.wd_mult = 2.0, 0.0
        tr = gluon.Trainer(net.collect_params(), opt, params,
                           kvstore=kvstore)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        X = mx.nd.array(np.random.RandomState(1).randn(16, 8)
                        .astype(np.float32))
        Y = mx.nd.array(np.random.RandomState(2).randint(0, 3, (16,))
                        .astype(np.float32))
        for _ in range(steps):
            with autograd.record():
                l = loss_fn(net(X), Y)
            l.backward()
            tr.step(16)
        return _sorted_weights(net), tr
    finally:
        fusedstep.set_enabled(prev)


# ---------------------------------------------------------------------------
# parity: fused step == eager per-param loop, to 1e-5
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt,params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9,
             "clip_gradient": 0.05}),
    ("adam", {"learning_rate": 0.01, "wd": 0.01}),
    ("adam", {"learning_rate": 0.01, "clip_gradient": 0.1}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("lamb", {"learning_rate": 0.01, "wd": 0.01}),
])
def test_fused_step_parity(opt, params):
    wf, trf = _train(True, opt=opt, opt_params=params)
    we, _ = _train(False, opt=opt, opt_params=params, hybridize=False)
    assert trf._fused not in (False, None), \
        f"fused path did not engage for {opt} {params}"
    for a, b in zip(wf, we):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fused_step_parity_lr_scheduler():
    mk = lambda: mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)  # noqa: E731
    wf, trf = _train(True, opt="sgd",
                     opt_params={"learning_rate": 0.2, "momentum": 0.9},
                     lr_schedule=mk)
    we, _ = _train(False, opt="sgd",
                   opt_params={"learning_rate": 0.2, "momentum": 0.9},
                   lr_schedule=mk, hybridize=False)
    assert trf._fused not in (False, None), "lr_scheduler disqualified fused"
    for a, b in zip(wf, we):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fused_step_parity_lr_wd_mults():
    wf, trf = _train(True, opt="sgd", mults=True,
                     opt_params={"learning_rate": 0.1, "momentum": 0.9,
                                 "wd": 1e-2})
    we, _ = _train(False, opt="sgd", mults=True, hybridize=False,
                   opt_params={"learning_rate": 0.1, "momentum": 0.9,
                               "wd": 1e-2})
    assert trf._fused not in (False, None), "lr/wd mults disqualified fused"
    for a, b in zip(wf, we):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fused_step_parity_through_kvstore():
    """Multi-key store allreduce + fused update together (explicit
    store; single-device, so the grouped no-op path carries it)."""
    wf, trf = _train(True, opt="adam", opt_params={"learning_rate": 0.01},
                     kvstore=mx.kv.create("device"))
    we, _ = _train(False, opt="adam", opt_params={"learning_rate": 0.01},
                   hybridize=False)
    assert trf._fused not in (False, None)
    for a, b in zip(wf, we):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_set_learning_rate_invalidates_but_keeps_momentum():
    def run(fused):
        prev = fusedstep.set_enabled(fused)
        try:
            mx.random.seed(0)
            np.random.seed(0)
            net = nn.Dense(4, in_units=6)
            net.initialize(init=mx.initializer.Xavier())
            if fused:
                net.hybridize()
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore=None)
            X = mx.nd.array(np.random.RandomState(1).randn(8, 6)
                            .astype(np.float32))
            for i in range(6):
                if i == 3:
                    tr.set_learning_rate(0.01)
                with autograd.record():
                    l = (net(X) ** 2).sum()
                l.backward()
                tr.step(8)
            return net.weight.data().asnumpy(), tr
        finally:
            fusedstep.set_enabled(prev)

    wf, trf = run(True)
    we, _ = run(False)
    assert trf._fused not in (False, None)
    np.testing.assert_allclose(wf, we, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# dispatch-count regression: O(1) in parameter count
# ---------------------------------------------------------------------------

def _dispatches_per_step(n_hidden):
    prev_obs = obs.set_enabled(True)
    try:
        mx.random.seed(0)
        net = _build_mlp(n_hidden)
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           kvstore=None)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        X = mx.nd.array(np.random.RandomState(1).randn(4, 8)
                        .astype(np.float32))
        Y = mx.nd.array(np.random.RandomState(2).randint(0, 3, (4,))
                        .astype(np.float32))

        def one():
            with autograd.record():
                l = loss_fn(net(X), Y)
            l.backward()
            tr.step(4)

        one()
        one()  # warmup: compile, build the fused plan
        assert tr._fused not in (False, None)
        obs.reset()
        one()
        return obs.XLA_DISPATCH_TOTAL.total()
    finally:
        obs.set_enabled(prev_obs)
        obs.reset()


def test_dispatch_count_constant_in_param_count():
    """With MXTPU_TELEMETRY, a hybridized-MLP train step issues a
    CONSTANT number of executable dispatches regardless of depth: the
    whole param-proportional work (backward, allreduce, update) rides in
    O(1) fused executables."""
    small = _dispatches_per_step(1)
    large = _dispatches_per_step(6)
    assert small == large, (small, large)
    assert large < 40, large  # 1 fwd + 1 bwd + 1 update + eager loss ops


def test_bucketed_variable_length_compiles_bounded_executables():
    """Shape stabilization (PR4): variable-length batches routed through
    a SequenceBucketer compile AT MOST len(buckets) train-step variants
    — the retrace-count extension of the dispatch-count harness. The
    same lengths unbucketed would compile one executable per length."""
    from mxnet_tpu.gluon.data import SequenceBucketer

    prev_obs = obs.set_enabled(True)
    try:
        mx.random.seed(0)
        # per-timestep head: handles any sequence length (B, T, 1)
        net = nn.Dense(4, in_units=1, flatten=False)
        net.initialize(init=mx.initializer.Xavier())
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           kvstore=None)
        buckets = SequenceBucketer([8, 16])
        obs.reset()
        lengths = [5, 8, 12, 3, 16, 9, 7]  # 7 lengths -> 2 shapes
        for t in lengths:
            x = mx.nd.array(np.random.RandomState(t).rand(4, t, 1)
                            .astype(np.float32))
            xb, _valid = buckets(x)
            with autograd.record():
                l = (net(xb) ** 2).sum()
            l.backward()
            tr.step(4)
        compiled = obs.CACHEDOP_COMPILE_TOTAL.value(block=net.name)
        assert compiled <= len(buckets.buckets), \
            f"{compiled} compiles for {len(buckets.buckets)} buckets"
        assert tr._fused not in (False, None)
    finally:
        obs.set_enabled(prev_obs)
        obs.reset()


def _dispatches_per_step_amp(n_hidden, target_dtype):
    """The AMP variant of the dispatch-count harness: cast policy on,
    convert_model'd net, fp32 masters (and for fp16 the in-graph loss
    scaler). The whole step must still be O(1) executables."""
    from mxnet_tpu import amp

    prev_obs = obs.set_enabled(True)
    amp.init(target_dtype)
    try:
        mx.random.seed(0)
        net = _build_mlp(n_hidden)
        amp.convert_model(net)
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9,
                            "multi_precision": True}, kvstore=None)
        if target_dtype == "float16":
            amp.init_trainer(tr)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        X = mx.nd.array(np.random.RandomState(1).randn(4, 8)
                        .astype(np.float32)).astype(target_dtype)
        Y = mx.nd.array(np.random.RandomState(2).randint(0, 3, (4,))
                        .astype(np.float32))

        def one():
            with autograd.record():
                l = loss_fn(net(X), Y)
                if target_dtype == "float16":
                    with amp.scale_loss(l, tr) as sl:
                        sl.backward()
            if target_dtype != "float16":
                l.backward()
            tr.step(4)

        one()
        one()  # warmup: compile, build the fused plan
        assert tr._fused not in (False, None)
        obs.reset()
        one()
        return obs.XLA_DISPATCH_TOTAL.total()
    finally:
        amp.disable()
        obs.set_enabled(prev_obs)
        obs.reset()


@pytest.mark.parametrize("target_dtype", [
    pytest.param("bfloat16", marks=pytest.mark.slow),  # same dispatch
    "float16",  # contract; fp16 cell adds the scaler arrays
])
def test_dispatch_count_constant_with_amp(target_dtype):
    """Acceptance contract: amp.init() + MXTPU_FUSED_STEP keeps the
    train step O(1) XLA dispatches — the cast policy lands inside the
    traced executables and loss scaling lives inside the fused update,
    so AMP adds ZERO dispatches over the fp32 fast path."""
    small = _dispatches_per_step_amp(1, target_dtype)
    large = _dispatches_per_step_amp(6, target_dtype)
    assert small == large, (small, large)
    assert large < 40, large


def test_fused_multi_precision_parity_bf16():
    """Fused mp update == eager mp per-param loop on a bf16 net (both
    keep fp32 masters; the stored weights must agree to bf16 ulp)."""
    from mxnet_tpu import amp

    def run(fused):
        prev = fusedstep.set_enabled(fused)
        amp.init("bfloat16")
        try:
            mx.random.seed(0)
            np.random.seed(0)
            net = _build_mlp(1)
            amp.convert_model(net)
            if fused:
                net.hybridize()
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9,
                                "multi_precision": True}, kvstore=None)
            X = mx.nd.array(np.random.RandomState(1).rand(8, 8)
                            .astype(np.float32)).astype("bfloat16")
            for _ in range(5):
                with autograd.record():
                    l = (net(X) ** 2).sum()
                l.backward()
                tr.step(8)
            return [w.astype(np.float32) for w in _sorted_weights(net)], tr
        finally:
            amp.disable()
            fusedstep.set_enabled(prev)

    wf, trf = run(True)
    we, _ = run(False)
    assert trf._fused not in (False, None), "mp bf16 must ride the fused path"
    for a, b in zip(wf, we):
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)


def test_grad_norm_gauge_is_lazy_with_fused_step():
    """The fused step folds the grad-norm gauge into the update
    executable: Trainer.step records a device scalar (no sync); the
    float conversion happens only when the gauge is read."""
    prev_obs = obs.set_enabled(True)
    try:
        mx.random.seed(0)
        net = _build_mlp(1)
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore=None)
        X = mx.nd.array(np.random.RandomState(1).randn(4, 8)
                        .astype(np.float32))
        for _ in range(2):
            with autograd.record():
                l = (net(X) ** 2).sum()
            l.backward()
            tr.step(4)
        assert tr._fused not in (False, None)
        stored = obs.TRAINER_GRAD_NORM._values.get(())
        assert stored is not None and not isinstance(stored, float), \
            "gauge should hold a lazy device scalar, not a synced float"
        # reading the gauge (or dumping) syncs and matches the eager probe
        assert obs.TRAINER_GRAD_NORM.value() == pytest.approx(
            tr._grad_norm(), rel=1e-4)
        assert "mxtpu_trainer_grad_norm" in obs.dump_prometheus()
    finally:
        obs.set_enabled(prev_obs)
        obs.reset()


# ---------------------------------------------------------------------------
# bucketed allreduce
# ---------------------------------------------------------------------------

def test_bucketed_pushpull_roundtrip_mixed_dtypes_odd_sizes():
    import jax

    kv = mx.kv.create("device")
    devs = jax.devices()
    assert len(devs) >= 2
    rng = np.random.RandomState(0)
    shapes = [((7, 13), np.float32), ((1,), np.float32),
              ((5, 3, 2), np.float16), ((997,), np.float32),
              ((64, 64), np.float32), ((3,), np.float16), ((), np.float32)]
    keys, vals, outs, expect = [], [], [], []
    for i, (sh, dt) in enumerate(shapes):
        kv.init(f"k{i}", mx.nd.zeros(sh, dtype=dt.__name__))
        per_dev, tot = [], np.zeros(sh, np.float64)
        for d in devs[:2]:
            a = np.asarray(rng.rand(*sh)).astype(dt)
            tot += a.astype(np.float64)
            nd = mx.nd.array(a, dtype=dt.__name__)
            nd._set_data(jax.device_put(nd.data, d))
            per_dev.append(nd)
        keys.append(f"k{i}")
        vals.append(per_dev)
        outs.append(mx.nd.zeros(sh, dtype=dt.__name__))
        expect.append(tot)
    kv.pushpull(keys, vals, out=outs)
    assert len(kv._bucket_plans) == 1
    for o, e, (sh, dt) in zip(outs, expect, shapes):
        rtol = 1e-6 if dt == np.float32 else 2e-3
        np.testing.assert_allclose(o.asnumpy().astype(np.float64), e,
                                   rtol=rtol)
    # same signature: the compiled plan is reused, not rebuilt
    kv.pushpull(keys, vals, out=outs)
    assert len(kv._bucket_plans) == 1


def _two_device_copies(arr):
    """The same value on two devices (bucketing needs a real reduction:
    the identity single-device case short-circuits to the grouped
    no-op)."""
    import jax

    out = []
    for d in jax.devices()[:2]:
        nd = mx.nd.array(arr.copy())
        nd._set_data(jax.device_put(nd.data, d))
        out.append(nd)
    return out


def test_bucketed_pushpull_splits_by_target_bytes(monkeypatch):
    monkeypatch.setenv("MXTPU_BUCKET_BYTES", "8192")  # force many buckets
    kv = mx.kv.create("device")
    rng = np.random.RandomState(1)
    keys, vals, outs, expect = [], [], [], []
    for i in range(6):
        sh = (1024,)  # 4096 B each -> 2 per 8 KiB bucket
        a = rng.rand(*sh).astype(np.float32)
        kv.init(i, mx.nd.zeros(sh))
        keys.append(i)
        vals.append(_two_device_copies(a))
        outs.append(mx.nd.zeros(sh))
        expect.append(2 * a)
    kv.pushpull(keys, vals, out=outs)
    plan = next(iter(kv._bucket_plans.values()))
    assert len(plan["buckets"]) == 3, plan["buckets"]
    for o, e in zip(outs, expect):
        np.testing.assert_allclose(o.asnumpy(), e, rtol=1e-6)


def test_bucketed_pushpull_dtype_homogeneous_buckets():
    kv = mx.kv.create("device")
    keys = [0, 1, 2, 3]
    dts = ["float32", "float16", "float32", "float16"]
    vals, outs = [], []
    for k, dt in zip(keys, dts):
        kv.init(k, mx.nd.zeros((4,), dtype=dt))
        vals.append(_two_device_copies(
            np.full((4,), k + 1, dtype=np.dtype(dt))))
        outs.append(mx.nd.zeros((4,), dtype=dt))
    kv.pushpull(keys, vals, out=outs)
    plan = next(iter(kv._bucket_plans.values()))
    for idxs in plan["buckets"]:
        assert len({dts[ki] for ki in idxs}) == 1, "mixed-dtype bucket"
    for k, o in zip(keys, outs):
        np.testing.assert_allclose(o.asnumpy(),
                                   np.full((4,), 2.0 * (k + 1)), rtol=1e-3)


def test_bucketed_skips_identity_reduction():
    """Single device + in-process store: nothing to reduce — the bucket
    machinery must stay out of the way (the grouped no-op handles it)."""
    kv = mx.kv.create("device")
    kv.init(0, mx.nd.zeros((8,)))
    kv.init(1, mx.nd.zeros((8,)))
    vals = [[mx.nd.ones((8,))], [mx.nd.ones((8,)) * 2]]
    outs = [mx.nd.zeros((8,)), mx.nd.zeros((8,))]
    kv.pushpull([0, 1], vals, out=outs)
    assert not kv._bucket_plans  # no plan built for identity work
    np.testing.assert_allclose(outs[0].asnumpy(), np.ones((8,)))
    np.testing.assert_allclose(outs[1].asnumpy(), np.full((8,), 2.0))


def test_bucketed_falls_back_for_sparse():
    from mxnet_tpu.ndarray.sparse import row_sparse_array

    kv = mx.kv.create("device")
    kv.init("dense", mx.nd.zeros((4, 3)))
    kv.init("sp", mx.nd.zeros((4, 3)))
    dense = [mx.nd.ones((4, 3))]
    sp = [row_sparse_array(([[1.0, 1.0, 1.0]], [1]), shape=(4, 3))]
    outs = [mx.nd.zeros((4, 3)), mx.nd.zeros((4, 3))]
    kv.pushpull(["dense", "sp"], [dense, sp], out=outs)
    np.testing.assert_allclose(outs[0].asnumpy(), np.ones((4, 3)), rtol=1e-6)
    exp = np.zeros((4, 3), np.float32)
    exp[1] = 1.0
    np.testing.assert_allclose(outs[1].asnumpy(), exp, rtol=1e-6)
    assert not kv._bucket_plans  # sparse signature never built a plan


# ---------------------------------------------------------------------------
# fallback contract
# ---------------------------------------------------------------------------

def test_unsupported_optimizer_falls_back_and_logs():
    prev_obs = obs.set_enabled(True)
    try:
        obs.reset()
        fusedstep.reset_fallback_log()
        w, tr = _train(True, steps=2, opt="rmsprop",
                       opt_params={"learning_rate": 0.01})
        assert tr._fused is False  # cached verdict, not permanent None
        assert all(np.isfinite(x).all() for x in w)
        reasons = [dict(k).get("reason", "")
                   for k in obs.FUSED_FALLBACK_TOTAL._values]
        assert any("rmsprop" in r for r in reasons), reasons
    finally:
        obs.set_enabled(prev_obs)
        obs.reset()


def test_sparse_grad_param_falls_back():
    from mxnet_tpu.gluon.parameter import Parameter

    p = Parameter("w", shape=(4, 3), grad_stype="row_sparse")
    p.initialize(ctx=mx.cpu())
    tr = gluon.Trainer([p], "sgd", {"learning_rate": 0.1}, kvstore=None)
    assert tr._fused_setup() is False


def test_deferred_init_does_not_permanently_disable_fused():
    """Seed bug: probing before the first forward cached _fused=False
    forever. The verdict must wait until params exist."""
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))  # deferred shapes
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    assert tr._fused_setup() is False  # not ready ...
    assert tr._fused is None           # ... but NOT cached as ineligible
    X = mx.nd.ones((4, 8))
    for _ in range(2):
        with autograd.record():
            l = (net(X) ** 2).sum()
        l.backward()
        tr.step(4)
    assert tr._fused not in (False, None), \
        "fused path must engage once deferred params are initialized"


def test_multi_device_param_falls_back():
    import jax

    from mxnet_tpu.context import Context

    devs = jax.devices()
    assert len(devs) >= 2
    from mxnet_tpu.gluon.parameter import Parameter

    p = Parameter("w", shape=(4, 3))
    p.initialize(ctx=[Context("cpu", 0), Context("cpu", 1)])
    tr = gluon.Trainer([p], "sgd", {"learning_rate": 0.1})
    tr._init_kvstore()
    assert tr._fused_setup() is False


def test_retain_graph_backward_after_donation():
    """Donated residuals: a second backward (retain_graph) recomputes
    them with one extra forward — same gradients, no dead-buffer error."""
    net = nn.Dense(3, in_units=3)
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 3))
    with autograd.record():
        y = net(x)
        l = (y * y).sum()
    l.backward(retain_graph=True)
    g1 = net.weight.grad(None).asnumpy().copy()
    l.backward(retain_graph=True)
    np.testing.assert_allclose(net.weight.grad(None).asnumpy(), g1,
                               rtol=1e-6)


def test_fused_step_save_load_states_roundtrip(tmp_path):
    w, tr = _train(True, steps=3, opt="adam",
                   opt_params={"learning_rate": 0.01})
    assert tr._fused not in (False, None)
    fname = str(tmp_path / "trainer.states")
    tr.save_states(fname)
    assert tr._fused_states
    tr.load_states(fname)
    assert tr._fused is None  # plan invalidated; states preserved
    assert tr._fused_states


def test_flip_to_eager_midrun_keeps_momentum():
    """Flipping the fused path off mid-run migrates the optimizer states
    back to the eager per-param path: results match an all-eager run."""
    def run(flip_at):
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.Dense(4, in_units=6)
        net.initialize(init=mx.initializer.Xavier())
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           kvstore=None)
        X = mx.nd.array(np.random.RandomState(1).randn(8, 6)
                        .astype(np.float32))
        for i in range(6):
            if i == flip_at:
                fusedstep.set_enabled(False)
            with autograd.record():
                l = (net(X) ** 2).sum()
            l.backward()
            tr.step(8)
        fusedstep.set_enabled(True)
        return net.weight.data().asnumpy()

    mixed = run(flip_at=3)
    eager = run(flip_at=0)
    np.testing.assert_allclose(mixed, eager, rtol=1e-5, atol=1e-6)


def test_toggle_fused_off_and_on_keeps_momentum():
    """fused → eager → fused round-trip: the re-enabled fast path must
    rebuild from the eager-advanced states, not reuse the cached plan's
    pre-flip copies."""
    def run(toggle):
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.Dense(4, in_units=6)
        net.initialize(init=mx.initializer.Xavier())
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           kvstore=None)
        X = mx.nd.array(np.random.RandomState(1).randn(8, 6)
                        .astype(np.float32))
        for i in range(6):
            if toggle:
                fusedstep.set_enabled(i < 2 or i >= 4)
            else:
                fusedstep.set_enabled(False)
            with autograd.record():
                l = (net(X) ** 2).sum()
            l.backward()
            tr.step(8)
        fusedstep.set_enabled(True)
        return net.weight.data().asnumpy()

    toggled = run(True)
    eager = run(False)
    np.testing.assert_allclose(toggled, eager, rtol=1e-5, atol=1e-6)


def test_empty_multikey_pushpull_is_noop():
    kv = mx.kv.create("device")
    kv.pushpull([], [], out=[])  # must not raise (was a silent no-op)


def test_set_learning_rate_does_not_rebuild_valid_plan():
    """lr is a jit operand: per-step manual scheduling (the warmup
    idiom) must not retrace the fused executable."""
    mx.random.seed(0)
    net = nn.Dense(4, in_units=6)
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=None)
    X = mx.nd.ones((4, 6))

    def one():
        with autograd.record():
            l = (net(X) ** 2).sum()
        l.backward()
        tr.step(4)

    one()
    plan = tr._fused
    assert plan not in (False, None)
    for i in range(3):
        tr.set_learning_rate(0.1 / (i + 2))
        one()
        assert tr._fused is plan, "valid plan must survive lr changes"


def test_mutating_trace_constant_hyper_rebuilds_plan():
    """momentum/betas are trace constants; direct attribute mutation
    mid-run must rebuild the plan (parity with the eager path), not
    silently keep the baked-in value."""
    def run(fused):
        prev = fusedstep.set_enabled(fused)
        try:
            mx.random.seed(0)
            np.random.seed(0)
            net = nn.Dense(4, in_units=6)
            net.initialize(init=mx.initializer.Xavier())
            if fused:
                net.hybridize()
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.05, "momentum": 0.9},
                               kvstore=None)
            X = mx.nd.array(np.random.RandomState(1).randn(8, 6)
                            .astype(np.float32))
            for i in range(6):
                if i == 3:
                    tr._optimizer.momentum = 0.5
                with autograd.record():
                    l = (net(X) ** 2).sum()
                l.backward()
                tr.step(8)
            return net.weight.data().asnumpy(), tr
        finally:
            fusedstep.set_enabled(prev)

    wf, trf = run(True)
    we, _ = run(False)
    assert trf._fused not in (False, None)
    np.testing.assert_allclose(wf, we, rtol=1e-5, atol=1e-6)


def test_freezing_param_midrun_rebuilds_plan():
    """Gluon fine-tuning idiom: setting grad_req='null' after N steps
    must stop updates to that param on the fused path too."""
    def run(fused):
        prev = fusedstep.set_enabled(fused)
        try:
            mx.random.seed(0)
            np.random.seed(0)
            net = _build_mlp(1)
            if fused:
                net.hybridize()
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore=None)
            X = mx.nd.array(np.random.RandomState(1).randn(8, 8)
                            .astype(np.float32))
            frozen = natsorted_items(net.collect_params().items())[0][1]
            snap = None
            for i in range(6):
                if i == 3:
                    frozen.grad_req = "null"
                    snap = frozen.data().asnumpy().copy()
                with autograd.record():
                    l = (net(X) ** 2).sum()
                l.backward()
                tr.step(8)
            return frozen.data().asnumpy(), snap, tr
        finally:
            fusedstep.set_enabled(prev)

    wf, snap_f, trf = run(True)
    we, snap_e, _ = run(False)
    assert trf._fused not in (False, None)
    np.testing.assert_allclose(wf, snap_f, rtol=0, atol=0,
                               err_msg="frozen param was updated (fused)")
    np.testing.assert_allclose(we, snap_e, rtol=0, atol=0)


def test_fused_adam_honors_begin_num_update():
    """Warm-restart idiom: begin_num_update seeds adam's bias-correction
    t in the fused state, matching the eager path."""
    def run(fused):
        prev = fusedstep.set_enabled(fused)
        try:
            mx.random.seed(0)
            np.random.seed(0)
            net = nn.Dense(4, in_units=6)
            net.initialize(init=mx.initializer.Xavier())
            if fused:
                net.hybridize()
            tr = gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.01,
                                "begin_num_update": 10000}, kvstore=None)
            X = mx.nd.array(np.random.RandomState(1).randn(8, 6)
                            .astype(np.float32))
            for _ in range(3):
                with autograd.record():
                    l = (net(X) ** 2).sum()
                l.backward()
                tr.step(8)
            return net.weight.data().asnumpy(), tr
        finally:
            fusedstep.set_enabled(prev)

    wf, trf = run(True)
    we, _ = run(False)
    assert trf._fused not in (False, None)
    np.testing.assert_allclose(wf, we, rtol=1e-5, atol=1e-6)


def test_dist_store_single_process_skips_bucket_roundtrip():
    """A dist store at process_count()==1 has an identity reduction —
    the bucket pack/unpack must stay out of the way there too."""
    kv = mx.kv.create("dist_tpu_sync")
    kv.init("a", mx.nd.zeros((8,)))
    kv.init("b", mx.nd.zeros((8,)))
    vals = [[mx.nd.ones((8,))], [mx.nd.ones((8,)) * 3]]
    outs = [mx.nd.zeros((8,)), mx.nd.zeros((8,))]
    kv.pushpull(["a", "b"], vals, out=outs)
    assert not kv._bucket_plans
    np.testing.assert_allclose(outs[1].asnumpy(), np.full((8,), 3.0))


def test_fused_step_disabled_matches_legacy():
    """MXTPU_FUSED_STEP=0 restores the legacy remat backward + per-param
    update; results agree with the fast path."""
    wf, _ = _train(True, opt="sgd",
                   opt_params={"learning_rate": 0.1, "momentum": 0.9})
    wl, trl = _train(False, opt="sgd",
                     opt_params={"learning_rate": 0.1, "momentum": 0.9})
    assert trl._fused in (False, None) or not trl._fused
    for a, b in zip(wf, wl):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
