"""Detection model end-to-end (reference: GluonCV SSD driven by
contrib MultiBox* ops; BASELINE.json config #2 names the detection path).
"""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo.vision import ssd_tiny, SSDLoss


def test_ssd_forward_shapes():
    net = ssd_tiny(classes=4)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    anchor, cls_pred, box_pred = net(x)
    n = anchor.shape[1]
    assert anchor.shape == (1, n, 4)
    assert cls_pred.shape == (2, 5, n)
    assert box_pred.shape == (2, n * 4)
    a = anchor.asnumpy()
    assert np.isfinite(a).all()


def test_ssd_convergence_and_detection():
    """Loss decreases on a fixed synthetic scene; NMS output is static."""
    net = ssd_tiny(classes=3)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    label = mx.nd.array(np.array(
        [[[1.0, 0.2, 0.2, 0.5, 0.5]],
         [[2.0, 0.6, 0.6, 0.9, 0.9]]], np.float32))
    loss_fn = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    losses = []
    for _ in range(15):
        with autograd.record():
            a, c, b = net(x)
            l = loss_fn(a, c, b, label)
        l.backward()
        trainer.step(2)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0], losses

    anchor, cls_pred, box_pred = net(x)
    det = mx.nd.MultiBoxDetection(mx.nd.softmax(cls_pred, axis=1),
                                  box_pred, anchor)
    n = anchor.shape[1]
    assert det.shape == (2, n, 6)  # static/padded output
    rows = det.asnumpy()
    kept = rows[rows[..., 0] >= 0]
    assert len(kept) > 0
    # all kept rows have sane scores and corner-ordered boxes
    assert (kept[:, 1] >= 0).all() and (kept[:, 1] <= 1).all()
    assert (kept[:, 2] <= kept[:, 4] + 1e-5).all()
    assert (kept[:, 3] <= kept[:, 5] + 1e-5).all()


def test_ssd_hybridize():
    net = ssd_tiny(classes=2)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(1, 3, 64, 64))
    eager = [o.asnumpy() for o in net(x)]
    net.hybridize()
    hybrid = [o.asnumpy() for o in net(x)]
    for e, h in zip(eager, hybrid):
        np.testing.assert_allclose(e, h, rtol=1e-4, atol=1e-5)
