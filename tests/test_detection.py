"""Detection model end-to-end (reference: GluonCV SSD driven by
contrib MultiBox* ops; BASELINE.json config #2 names the detection path).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo.vision import ssd_tiny, SSDLoss


def test_ssd_forward_shapes():
    net = ssd_tiny(classes=4)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    anchor, cls_pred, box_pred = net(x)
    n = anchor.shape[1]
    assert anchor.shape == (1, n, 4)
    assert cls_pred.shape == (2, 5, n)
    assert box_pred.shape == (2, n * 4)
    a = anchor.asnumpy()
    assert np.isfinite(a).all()


@pytest.mark.slow
def test_ssd_convergence_and_detection():
    """Loss decreases on a fixed synthetic scene; NMS output is static."""
    net = ssd_tiny(classes=3)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    label = mx.nd.array(np.array(
        [[[1.0, 0.2, 0.2, 0.5, 0.5]],
         [[2.0, 0.6, 0.6, 0.9, 0.9]]], np.float32))
    loss_fn = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    losses = []
    for _ in range(15):
        with autograd.record():
            a, c, b = net(x)
            l = loss_fn(a, c, b, label)
        l.backward()
        trainer.step(2)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0], losses

    anchor, cls_pred, box_pred = net(x)
    det = mx.nd.MultiBoxDetection(mx.nd.softmax(cls_pred, axis=1),
                                  box_pred, anchor)
    n = anchor.shape[1]
    assert det.shape == (2, n, 6)  # static/padded output
    rows = det.asnumpy()
    kept = rows[rows[..., 0] >= 0]
    assert len(kept) > 0
    # all kept rows have sane scores and corner-ordered boxes
    assert (kept[:, 1] >= 0).all() and (kept[:, 1] <= 1).all()
    assert (kept[:, 2] <= kept[:, 4] + 1e-5).all()
    assert (kept[:, 3] <= kept[:, 5] + 1e-5).all()


def test_ssd_hybridize():
    net = ssd_tiny(classes=2)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(1, 3, 64, 64))
    eager = [o.asnumpy() for o in net(x)]
    net.hybridize()
    hybrid = [o.asnumpy() for o in net(x)]
    for e, h in zip(eager, hybrid):
        np.testing.assert_allclose(e, h, rtol=1e-4, atol=1e-5)


def _best_iou(kept_rows, want_box):
    """Max IoU between kept [.., x1 y1 x2 y2] rows and one box."""
    if len(kept_rows) == 0:
        return 0.0
    b = kept_rows[:, -4:]
    ix1 = np.maximum(b[:, 0], want_box[0])
    iy1 = np.maximum(b[:, 1], want_box[1])
    ix2 = np.minimum(b[:, 2], want_box[2])
    iy2 = np.minimum(b[:, 3], want_box[3])
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    area_w = (want_box[2] - want_box[0]) * (want_box[3] - want_box[1])
    return float(np.max(inter / np.maximum(area_b + area_w - inter, 1e-9)))


def test_ssd_localizes_planted_box():
    """After training on one synthetic scene, the top detection must
    overlap the planted gt box with IoU > 0.5 (VERDICT r2 Weak #8)."""
    net = ssd_tiny(classes=3)
    net.initialize(init=mx.initializer.Xavier())
    rng = np.random.RandomState(0)
    img = np.full((1, 3, 64, 64), 0.1, np.float32)
    img[:, :, 16:40, 16:40] = 0.9  # bright square = the object
    x = mx.nd.array(img)
    gt = np.array([[[0.0, 16 / 64, 16 / 64, 40 / 64, 40 / 64]]], np.float32)
    label = mx.nd.array(gt)
    loss_fn = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    for _ in range(60):
        with autograd.record():
            a, c, b = net(x)
            l = loss_fn(a, c, b, label)
        l.backward()
        trainer.step(1)
    anchor, cls_pred, box_pred = net(x)
    det = mx.nd.MultiBoxDetection(mx.nd.softmax(cls_pred, axis=1),
                                  box_pred, anchor).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    kept = kept[np.argsort(-kept[:, 1])][:5]  # top-5 by score
    iou = _best_iou(kept, np.array([16, 16, 40, 40]) / 64.0)
    assert iou > 0.5, (iou, kept[:3])


def test_faster_rcnn_forward_shapes():
    from mxnet_tpu.gluon.model_zoo.vision import faster_rcnn_tiny

    net = faster_rcnn_tiny(classes=3)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    im_info = mx.nd.array(np.array([[64, 64, 1.0]] * 2, np.float32))
    rpn_cls, rpn_bbox, rois, cls_scores, bbox_pred = net(x, im_info)
    A = net.num_anchors
    H = W = 64 // net.feature_stride
    assert rpn_cls.shape == (2, 2 * A, H, W)
    assert rpn_bbox.shape == (2, 4 * A, H, W)
    assert rois.shape == (2 * net.rpn_post_nms, 5)
    assert cls_scores.shape == (2 * net.rpn_post_nms, 4)
    assert bbox_pred.shape == (2 * net.rpn_post_nms, 16)
    # roi batch indices partition correctly
    ridx = rois.asnumpy()[:, 0]
    assert set(np.unique(ridx)) <= {0.0, 1.0}


@pytest.mark.slow
def test_faster_rcnn_trains_and_localizes():
    """Two-stage pipeline end to end: loss decreases AND the planted box
    is recovered at IoU > 0.5 through Proposal -> ROIAlign -> heads ->
    decode -> NMS (VERDICT r2 Missing #4)."""
    from mxnet_tpu.gluon.model_zoo.vision import (FasterRCNNLoss,
                                                  faster_rcnn_tiny)

    net = faster_rcnn_tiny(classes=2)
    net.initialize(init=mx.initializer.Xavier())
    img = np.full((1, 3, 64, 64), 0.1, np.float32)
    img[:, :, 12:36, 20:48] = 0.9
    x = mx.nd.array(img)
    im_info = mx.nd.array(np.array([[64, 64, 1.0]], np.float32))
    gt = mx.nd.array(np.array([[[0.0, 20, 12, 47, 35]]], np.float32))
    loss_fn = FasterRCNNLoss(net)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    losses = []
    for _ in range(120):
        with autograd.record():
            out = net(x, im_info, gt)
            l = loss_fn(out, gt)
        l.backward()
        trainer.step(1)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    det = net.detect(x, im_info).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    assert len(kept) > 0, "no detections survived NMS"
    kept = kept[np.argsort(-kept[:, 1])][:5]
    iou = _best_iou(kept, np.array([20, 12, 47, 35], np.float32))
    assert iou > 0.5, (iou, kept[:3])


def test_yolo3_forward_and_decode_shapes():
    from mxnet_tpu.gluon.model_zoo.vision import yolo3_tiny
    from mxnet_tpu.gluon.model_zoo.vision.yolo import decode_predictions

    net = yolo3_tiny(classes=4)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    preds = net(x)
    assert len(preds) == 2
    grids = net.grids(64)
    n_total = sum(H * W * A for H, W, A, _ in grids)
    assert sum(p.shape[1] for p in preds) == n_total
    dec = decode_predictions(preds, grids)
    assert dec.shape == (2, n_total, 5 + 4)
    import numpy as _np

    d = _np.asarray(dec)
    assert (d[..., 4] >= 0).all() and (d[..., 4] <= 1).all()  # obj in [0,1]
    assert (d[..., 2] > 0).all() and (d[..., 3] > 0).all()    # sizes > 0


@pytest.mark.slow
def test_yolo3_trains_and_localizes():
    """One-stage path end to end (BASELINE config #2's third architecture):
    loss decreases AND the planted box is recovered at IoU > 0.5."""
    from mxnet_tpu.gluon.model_zoo.vision import yolo3_tiny
    from mxnet_tpu.gluon.model_zoo.vision.yolo import (YOLOv3Loss,
                                                       yolo_detect)

    net = yolo3_tiny(classes=2)
    net.initialize(init=mx.initializer.Xavier())
    img = np.full((1, 3, 64, 64), 0.1, np.float32)
    img[:, :, 16:40, 12:44] = 0.9
    x = mx.nd.array(img)
    # normalized gt [cls, x1, y1, x2, y2]
    gt = mx.nd.array(np.array([[[1.0, 12 / 64, 16 / 64, 44 / 64, 40 / 64]]],
                              np.float32))
    loss_fn = YOLOv3Loss(net)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    losses = []
    for _ in range(120):
        with autograd.record():
            preds = net(x)
            l = loss_fn(preds, gt, 64)
        l.backward()
        trainer.step(1)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    det = yolo_detect(net, x).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    assert len(kept) > 0, "no detections survived NMS"
    kept = kept[np.argsort(-kept[:, 1])][:5]
    iou = _best_iou(kept, np.array([12, 16, 44, 40], np.float32) / 64.0)
    assert iou > 0.5, (iou, kept[:3])
    # the class must be the planted one
    assert kept[0, 0] == 1.0, kept[0]


def test_yolo3_grids_follow_base_channels():
    """Review regression: grids() must track the stem depth."""
    from mxnet_tpu.gluon.model_zoo.vision import YOLOv3

    net = YOLOv3(classes=2, base_channels=(8, 16))
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(1, 3, 64, 64))
    preds = net(x)
    grids = net.grids(64)
    for p, (H, W, A, _) in zip(preds, grids):
        assert p.shape[1] == H * W * A, (p.shape, (H, W, A))


def test_yolo3_ignore_mask_active():
    """Cells predicting a gt at high IoU but unassigned must be excluded
    from the objectness loss (weight 0)."""
    from mxnet_tpu.gluon.model_zoo.vision import yolo3_tiny
    from mxnet_tpu.gluon.model_zoo.vision.yolo import YOLOv3Loss

    net = yolo3_tiny(classes=2)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(1, 3, 64, 64))
    preds = net(x)
    gt = np.array([[[0.0, 0.25, 0.25, 0.75, 0.75]]], np.float32)
    # with a permissive threshold the big centered gt overlaps many
    # random-init predictions: the mask must actually fire
    loss_low = YOLOv3Loss(net, ignore_iou=0.01)
    masks = loss_low._ignore_mask(preds, net.grids(64), gt)
    assert sum(int(m.sum()) for m in masks) > 0
    loss_fn = YOLOv3Loss(net, ignore_iou=0.5)
    # with an impossible threshold nothing is ignored
    loss_none = YOLOv3Loss(net, ignore_iou=1.1)
    m2 = loss_none._ignore_mask(preds, net.grids(64), gt)
    assert sum(int(m.sum()) for m in m2) == 0
    # and the loss value responds to the threshold when cells are ignored
    l_a = float(loss_fn(preds, mx.nd.array(gt), 64).asnumpy())
    l_b = float(loss_none(preds, mx.nd.array(gt), 64).asnumpy())
    assert np.isfinite(l_a) and np.isfinite(l_b)
