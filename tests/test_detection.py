"""Detection model end-to-end (reference: GluonCV SSD driven by
contrib MultiBox* ops; BASELINE.json config #2 names the detection path).
"""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo.vision import ssd_tiny, SSDLoss


def test_ssd_forward_shapes():
    net = ssd_tiny(classes=4)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    anchor, cls_pred, box_pred = net(x)
    n = anchor.shape[1]
    assert anchor.shape == (1, n, 4)
    assert cls_pred.shape == (2, 5, n)
    assert box_pred.shape == (2, n * 4)
    a = anchor.asnumpy()
    assert np.isfinite(a).all()


def test_ssd_convergence_and_detection():
    """Loss decreases on a fixed synthetic scene; NMS output is static."""
    net = ssd_tiny(classes=3)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    label = mx.nd.array(np.array(
        [[[1.0, 0.2, 0.2, 0.5, 0.5]],
         [[2.0, 0.6, 0.6, 0.9, 0.9]]], np.float32))
    loss_fn = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    losses = []
    for _ in range(15):
        with autograd.record():
            a, c, b = net(x)
            l = loss_fn(a, c, b, label)
        l.backward()
        trainer.step(2)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0], losses

    anchor, cls_pred, box_pred = net(x)
    det = mx.nd.MultiBoxDetection(mx.nd.softmax(cls_pred, axis=1),
                                  box_pred, anchor)
    n = anchor.shape[1]
    assert det.shape == (2, n, 6)  # static/padded output
    rows = det.asnumpy()
    kept = rows[rows[..., 0] >= 0]
    assert len(kept) > 0
    # all kept rows have sane scores and corner-ordered boxes
    assert (kept[:, 1] >= 0).all() and (kept[:, 1] <= 1).all()
    assert (kept[:, 2] <= kept[:, 4] + 1e-5).all()
    assert (kept[:, 3] <= kept[:, 5] + 1e-5).all()


def test_ssd_hybridize():
    net = ssd_tiny(classes=2)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(1, 3, 64, 64))
    eager = [o.asnumpy() for o in net(x)]
    net.hybridize()
    hybrid = [o.asnumpy() for o in net(x)]
    for e, h in zip(eager, hybrid):
        np.testing.assert_allclose(e, h, rtol=1e-4, atol=1e-5)


def _best_iou(kept_rows, want_box):
    """Max IoU between kept [.., x1 y1 x2 y2] rows and one box."""
    if len(kept_rows) == 0:
        return 0.0
    b = kept_rows[:, -4:]
    ix1 = np.maximum(b[:, 0], want_box[0])
    iy1 = np.maximum(b[:, 1], want_box[1])
    ix2 = np.minimum(b[:, 2], want_box[2])
    iy2 = np.minimum(b[:, 3], want_box[3])
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    area_w = (want_box[2] - want_box[0]) * (want_box[3] - want_box[1])
    return float(np.max(inter / np.maximum(area_b + area_w - inter, 1e-9)))


def test_ssd_localizes_planted_box():
    """After training on one synthetic scene, the top detection must
    overlap the planted gt box with IoU > 0.5 (VERDICT r2 Weak #8)."""
    net = ssd_tiny(classes=3)
    net.initialize(init=mx.initializer.Xavier())
    rng = np.random.RandomState(0)
    img = np.full((1, 3, 64, 64), 0.1, np.float32)
    img[:, :, 16:40, 16:40] = 0.9  # bright square = the object
    x = mx.nd.array(img)
    gt = np.array([[[0.0, 16 / 64, 16 / 64, 40 / 64, 40 / 64]]], np.float32)
    label = mx.nd.array(gt)
    loss_fn = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    for _ in range(60):
        with autograd.record():
            a, c, b = net(x)
            l = loss_fn(a, c, b, label)
        l.backward()
        trainer.step(1)
    anchor, cls_pred, box_pred = net(x)
    det = mx.nd.MultiBoxDetection(mx.nd.softmax(cls_pred, axis=1),
                                  box_pred, anchor).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    kept = kept[np.argsort(-kept[:, 1])][:5]  # top-5 by score
    iou = _best_iou(kept, np.array([16, 16, 40, 40]) / 64.0)
    assert iou > 0.5, (iou, kept[:3])


def test_faster_rcnn_forward_shapes():
    from mxnet_tpu.gluon.model_zoo.vision import faster_rcnn_tiny

    net = faster_rcnn_tiny(classes=3)
    net.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.uniform(shape=(2, 3, 64, 64))
    im_info = mx.nd.array(np.array([[64, 64, 1.0]] * 2, np.float32))
    rpn_cls, rpn_bbox, rois, cls_scores, bbox_pred = net(x, im_info)
    A = net.num_anchors
    H = W = 64 // net.feature_stride
    assert rpn_cls.shape == (2, 2 * A, H, W)
    assert rpn_bbox.shape == (2, 4 * A, H, W)
    assert rois.shape == (2 * net.rpn_post_nms, 5)
    assert cls_scores.shape == (2 * net.rpn_post_nms, 4)
    assert bbox_pred.shape == (2 * net.rpn_post_nms, 16)
    # roi batch indices partition correctly
    ridx = rois.asnumpy()[:, 0]
    assert set(np.unique(ridx)) <= {0.0, 1.0}


def test_faster_rcnn_trains_and_localizes():
    """Two-stage pipeline end to end: loss decreases AND the planted box
    is recovered at IoU > 0.5 through Proposal -> ROIAlign -> heads ->
    decode -> NMS (VERDICT r2 Missing #4)."""
    from mxnet_tpu.gluon.model_zoo.vision import (FasterRCNNLoss,
                                                  faster_rcnn_tiny)

    net = faster_rcnn_tiny(classes=2)
    net.initialize(init=mx.initializer.Xavier())
    img = np.full((1, 3, 64, 64), 0.1, np.float32)
    img[:, :, 12:36, 20:48] = 0.9
    x = mx.nd.array(img)
    im_info = mx.nd.array(np.array([[64, 64, 1.0]], np.float32))
    gt = mx.nd.array(np.array([[[0.0, 20, 12, 47, 35]]], np.float32))
    loss_fn = FasterRCNNLoss(net)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    losses = []
    for _ in range(120):
        with autograd.record():
            out = net(x, im_info, gt)
            l = loss_fn(out, gt)
        l.backward()
        trainer.step(1)
        losses.append(float(l.asnumpy()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    det = net.detect(x, im_info).asnumpy()[0]
    kept = det[det[:, 0] >= 0]
    assert len(kept) > 0, "no detections survived NMS"
    kept = kept[np.argsort(-kept[:, 1])][:5]
    iou = _best_iou(kept, np.array([20, 12, 47, 35], np.float32))
    assert iou > 0.5, (iou, kept[:3])
