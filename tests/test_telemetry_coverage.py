"""Telemetry coverage gate (tools/check_telemetry_coverage.py): every
metric / trace-series / dispatch-site name emitted in mxnet_tpu/ must
be documented in docs/observability.md — a new instrumentation site
cannot land undocumented. Pure static check, no jax needed."""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))
import check_telemetry_coverage as ctc  # noqa: E402

sys.path.pop(0)


def test_every_emitted_name_is_documented():
    missing, found = ctc.check(ROOT)
    assert not missing, (
        "telemetry names emitted but missing from docs/observability.md "
        f"coverage map: {missing}")
    # sanity: the scanner actually sees the catalog (an empty scan
    # passing would make this gate vacuous)
    assert len(found["metric"]) >= 30
    assert "trainer.step" in found["trace"]
    assert "trainer_fused" in found["site"]


def test_scanner_catches_an_undocumented_name(tmp_path):
    """End-to-end negative case on a synthetic tree: the checker must
    actually fail when a name is emitted but not documented."""
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'C = REG.counter("mxtpu_documented_total")\n'
        'D = REG.counter("mxtpu_undocumented_total")\n'
        'tracer.record("my.series", cat="x")\n'
        'record_xla_dispatch("mystery_site")\n')
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "`mxtpu_documented_total` and the `my.series` span\n")
    missing, _ = ctc.check(str(tmp_path))
    names = {m[1] for m in missing}
    assert names == {"mxtpu_undocumented_total", "mystery_site"}


def test_cli_exit_codes(capsys):
    assert ctc.main(["--root", ROOT]) == 0
    assert "coverage OK" in capsys.readouterr().out
