"""Async-error semantics tests (reference model: test_exc_handling.py —
exceptions surface at sync points; SURVEY.md §5.3)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def test_shape_error_is_eager():
    a = mx.nd.ones((2, 3))
    b = mx.nd.ones((4, 5))
    with pytest.raises(Exception):
        mx.nd.dot(a, b).wait_to_read()


def test_invalid_op_param():
    with pytest.raises(Exception):
        mx.nd.Activation(mx.nd.ones((2,)), act_type="not_a_thing")


def test_uninitialized_param_message():
    net = gluon.nn.Dense(3, in_units=2)
    with pytest.raises(mx.MXNetError, match="initialize"):
        net(mx.nd.ones((1, 2)))


def test_deferred_init_message():
    p = gluon.Parameter("w", shape=(3, 0), allow_deferred_init=True)
    p.initialize()
    with pytest.raises(gluon.DeferredInitializationError, match="deferred"):
        p.data()


def test_backward_without_record():
    x = mx.nd.ones((2,))
    x.attach_grad()
    y = x * 2
    with pytest.raises(mx.MXNetError, match="tape"):
        y.backward()


def test_nan_propagates_not_raises():
    # like the reference: NaN is data, not an error
    x = mx.nd.array([0.0])
    y = mx.nd.log(x)  # -inf
    z = y - y          # nan
    assert np.isnan(z.asnumpy()).all()


def test_waitall_after_error_recovers():
    try:
        mx.nd.dot(mx.nd.ones((2, 3)), mx.nd.ones((4, 5))).asnumpy()
    except Exception:
        pass
    mx.nd.waitall()  # framework still usable
    assert mx.nd.ones((2,)).sum().asscalar() == 2.0


def test_sync_exec_env_flag():
    from mxnet_tpu import engine

    assert engine.sync_exec_enabled() in (True, False)


def test_exception_inside_hybridized_block():
    class Bad(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.reshape(x, shape=(999, 999))  # invalid reshape

    b = Bad()
    b.initialize()
    b.hybridize()
    with pytest.raises(Exception):
        b(mx.nd.ones((2, 2)))
