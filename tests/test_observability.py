"""Unified runtime telemetry (mxnet_tpu.observability): registry
semantics, hot-path instrumentation (dispatch / CachedOp / kvstore /
trainer / engine.wait), exporters, and the disabled-path guarantee.

Reference analog: ``tests/python/unittest/test_profiler.py`` — extended
to the Prometheus/chrome-trace model this repro uses instead of the
engine-integrated profiler."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, observability as obs
from mxnet_tpu.gluon import nn

TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


@pytest.fixture(autouse=True)
def _telemetry_state():
    """Each test starts from a clean, DISABLED registry and leaves the
    process-default state behind (tier-1 runs with MXTPU_TELEMETRY unset)."""
    obs.set_enabled(False)
    obs.reset()
    yield
    obs.set_enabled(False)
    obs.reset()


def _tiny_net(in_units=8, hidden=16, classes=4, prefix=None):
    net = nn.HybridSequential(prefix=prefix)
    net.add(nn.Dense(hidden, activation="relu", in_units=in_units),
            nn.Dense(classes, in_units=hidden))
    net.initialize()
    return net


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram():
    reg = obs.MetricsRegistry()
    c = reg.counter("t_requests_total", "help text")
    c.inc()
    c.inc(2, route="a")
    c.inc(3, route="b")
    assert c.value() == 1
    assert c.value(route="a") == 2
    assert c.total() == 6
    with pytest.raises(mx.MXNetError):
        c.inc(-1)

    g = reg.gauge("t_depth")
    g.set(5)
    g.dec(2)
    assert g.value() == 3

    h = reg.histogram("t_latency_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.value() == 3
    assert h.sum() == pytest.approx(5.55)

    # same name -> same object; kind mismatch -> loud error
    assert reg.counter("t_requests_total") is c
    with pytest.raises(mx.MXNetError):
        reg.gauge("t_requests_total")


def test_registry_prometheus_exposition():
    reg = obs.MetricsRegistry()
    c = reg.counter("t_ops_total", "ops processed")
    c.inc(4, op="dot")
    h = reg.histogram("t_lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = reg.dump_prometheus()
    assert "# HELP t_ops_total ops processed" in text
    assert "# TYPE t_ops_total counter" in text
    assert 't_ops_total{op="dot"} 4' in text
    assert "# TYPE t_lat_seconds histogram" in text
    assert 't_lat_seconds_bucket{le="0.1"} 1' in text
    assert 't_lat_seconds_bucket{le="+Inf"} 2' in text
    assert "t_lat_seconds_count 2" in text
    # label values escape per the exposition format
    c.inc(1, op='say "hi"\nback\\slash')
    line = [l for l in reg.dump_prometheus().splitlines() if "say" in l][0]
    assert line == 't_ops_total{op="say \\"hi\\"\\nback\\\\slash"} 1'


# ---------------------------------------------------------------------------
# hot-path instrumentation
# ---------------------------------------------------------------------------

def test_op_dispatch_counters():
    obs.set_enabled(True)
    a = mx.nd.ones((8, 8))
    for _ in range(3):
        b = mx.nd.dot(a, a)
    b.asnumpy()
    assert obs.OP_DISPATCH_TOTAL.value(op="dot") >= 3
    assert obs.OP_DISPATCH_SECONDS.value(op="dot") > 0


def test_cachedop_exactly_one_compile_then_hits():
    net = _tiny_net()
    net.hybridize()
    obs.set_enabled(True)
    x = mx.nd.ones((2, 8))
    for _ in range(5):
        net(x).asnumpy()
    assert obs.CACHEDOP_COMPILE_TOTAL.total() == 1
    assert obs.CACHEDOP_CACHE_HITS.total() == 4
    assert obs.CACHEDOP_TRACE_SECONDS.total() > 0
    # compile event landed in the tracer with cause=first
    compiles = [ev for ev in obs.tracer().events() if ev["cat"] == "compile"]
    assert len(compiles) == 1
    assert compiles[0]["args"]["cause"] == "first"


def test_cachedop_retrace_cause_diagnosis():
    net = _tiny_net()
    net.hybridize()
    obs.set_enabled(True)
    net(mx.nd.ones((2, 8))).asnumpy()
    net(mx.nd.ones((3, 8))).asnumpy()  # batch change -> shape retrace
    causes = obs.CACHEDOP_RETRACE_TOTAL.labelsets()
    assert any(ls.get("cause") == "shape" for ls in causes), causes
    with autograd.record():  # recording flips -> another retrace
        net(mx.nd.ones((3, 8)))
    causes = [ls.get("cause") for ls in obs.CACHEDOP_RETRACE_TOTAL.labelsets()]
    assert any("recording" in c for c in causes), causes
    assert obs.CACHEDOP_COMPILE_TOTAL.total() == 3


def test_kvstore_push_pull_byte_accounting():
    kv = mx.kv.create("local")
    shape = (4, 5)  # f32: 80 bytes
    kv.init(3, mx.nd.ones(shape))
    obs.set_enabled(True)
    kv.push(3, mx.nd.ones(shape))
    out = mx.nd.zeros(shape)
    kv.pull(3, out=out)
    assert obs.KV_PUSH_TOTAL.total() == 1
    assert obs.KV_PUSH_BYTES.total() == 80
    assert obs.KV_PULL_TOTAL.total() == 1
    assert obs.KV_PULL_BYTES.total() == 80
    # multi-device-style push: bytes sum over the value list
    kv.push(3, [mx.nd.ones(shape), mx.nd.ones(shape)])
    assert obs.KV_PUSH_BYTES.total() == 80 + 160


def test_kvstore_pushpull_accounting():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones((2, 8)))  # 64 bytes
    obs.set_enabled(True)
    g = mx.nd.ones((2, 8))
    kv.pushpull("w", g, out=g)
    assert obs.KV_PUSHPULL_TOTAL.total() == 1
    assert obs.KV_PUSH_BYTES.total() == 64
    assert obs.KV_PULL_BYTES.total() == 64


def test_engine_wait_instrumented():
    obs.set_enabled(True)
    from mxnet_tpu import engine

    a = mx.nd.ones((4, 4)) + 1
    engine.wait(a.data)
    assert obs.ENGINE_WAIT_TOTAL.value(path="native") >= 1
    assert obs.ENGINE_WAIT_SECONDS.value(path="native") >= 0


def test_engine_wait_relay_path_instrumented(monkeypatch):
    """The relay dependent-read sync reports under path="relay"."""
    from mxnet_tpu import engine

    obs.set_enabled(True)
    monkeypatch.setattr(engine, "_RELAY", True)
    a = mx.nd.ones((4, 4)) + 1
    engine.wait(a.data)
    assert obs.ENGINE_WAIT_TOTAL.value(path="relay") >= 1
    assert obs.ENGINE_WAIT_TOTAL.value(path="native") == 0


# ---------------------------------------------------------------------------
# the acceptance loop: hybridized Trainer training on CPU
# ---------------------------------------------------------------------------

def test_trainer_loop_end_to_end_telemetry():
    rng = np.random.RandomState(0)
    net = _tiny_net()
    net.hybridize()
    kv = mx.kv.create("local")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv)
    loss_fn = gluon.loss.L2Loss()
    x = mx.nd.array(rng.rand(2, 8).astype(np.float32))
    y = mx.nd.array(rng.rand(2, 4).astype(np.float32))

    obs.set_enabled(True)
    for _ in range(3):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(2)
    loss.asnumpy()

    # per-op dispatch counters ticked (loss math runs eagerly)
    assert obs.OP_DISPATCH_TOTAL.total() > 0
    # exactly-one-compile cache stats for the whole loop
    assert obs.CACHEDOP_COMPILE_TOTAL.total() == 1
    assert obs.CACHEDOP_CACHE_HITS.total() == 2
    # kvstore byte totals: 4 params aggregated per step
    assert obs.KV_PUSHPULL_TOTAL.total() == 12
    assert obs.KV_PUSH_BYTES.total() > 0
    assert obs.KV_PUSH_BYTES.total() == obs.KV_PULL_BYTES.total()
    # step metrics + grad-norm gauge
    assert obs.TRAINER_STEP_TOTAL.total() == 3
    assert obs.TRAINER_GRAD_NORM.value() > 0
    # step spans exportable both ways
    spans = [ev for ev in obs.tracer().events()
             if ev["name"] == "trainer.step"]
    assert [ev["args"]["step"] for ev in spans] == [1, 2, 3]
    prom = obs.dump_prometheus()
    for name in ("mxtpu_op_dispatch_total", "mxtpu_cachedop_compile_total",
                 "mxtpu_kvstore_push_bytes_total", "mxtpu_trainer_step_total",
                 "mxtpu_trainer_grad_norm"):
        assert name in prom, name
    chrome = json.loads(obs.dump_chrome_trace())
    assert any(ev["name"] == "trainer.step" and ev["ph"] == "X"
               for ev in chrome["traceEvents"])
    # summary is renderable and mentions the step count
    assert "3 steps" in obs.summary()


def test_disabled_path_records_nothing():
    """MXTPU_TELEMETRY=0 semantics: instrumented paths record zero."""
    assert not obs.enabled()
    net = _tiny_net()
    net.hybridize()
    kv = mx.kv.create("local")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=kv)
    loss_fn = gluon.loss.L2Loss()
    x = mx.nd.ones((2, 8))
    y = mx.nd.ones((2, 4))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(2)
    loss.asnumpy()
    for m in obs.registry().metrics():
        if m is obs.PROFILE_COUNTER:
            continue  # user-driven, not hot-path
        assert m.total() == 0, m.name
    assert len(obs.tracer()) == 0


def test_env_switch_parsing():
    """MXTPU_TELEMETRY=1 flips the import-time default (the unset->off
    default is exercised by every other test via the autouse fixture)."""
    code = ("import mxnet_tpu as mx; "
            "print(mx.observability.enabled())")
    env = dict(os.environ, MXTPU_TELEMETRY="1", JAX_PLATFORMS="cpu")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert res.stdout.strip() == "True", res.stdout


# ---------------------------------------------------------------------------
# exporters round-trip + report tool
# ---------------------------------------------------------------------------

def test_jsonl_round_trip(tmp_path):
    obs.set_enabled(True)
    with obs.span("work", cat="test", tag="a"):
        pass
    obs.tracer().instant("marker", cat="test")
    path = str(tmp_path / "trace.jsonl")
    obs.dump_jsonl(path)
    events = obs.load_jsonl(path)
    assert [ev["name"] for ev in events] == ["work", "marker"]
    assert events[0]["ph"] == "X" and events[1]["ph"] == "i"
    assert events[0]["args"]["tag"] == "a"
    # chrome trace holds the same events under traceEvents
    chrome = json.loads(obs.dump_chrome_trace(str(tmp_path / "trace.json")))
    assert len(chrome["traceEvents"]) == 2


def test_trace_ring_buffer_bounded():
    tr = obs.Tracer(capacity=16)
    for i in range(100):
        tr.record(f"ev{i}", cat="test")
    assert len(tr) == 16
    assert tr.events()[-1]["name"] == "ev99"


def test_telemetry_report_cli(tmp_path):
    """tools/telemetry_report.py renders the dumps-style table (tier-1
    smoke: pure-stdlib subprocess, no jax import)."""
    obs.set_enabled(True)
    for _ in range(3):
        with obs.span("trainer.step", cat="trainer"):
            pass
    with obs.span("cachedop.compile[net]", cat="compile"):
        pass
    path = str(tmp_path / "t.jsonl")
    obs.dump_jsonl(path)

    res = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "telemetry_report.py"), path,
         "--steps"],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    out = res.stdout
    assert "Telemetry Trace Statistics:" in out
    assert "Total Count" in out and "Avg (ms)" in out
    line = [l for l in out.splitlines() if l.startswith("trainer.step")][0]
    assert int(line.split()[1]) == 3
    # --cat filter drops other categories
    res2 = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "telemetry_report.py"), path,
         "--cat", "compile"],
        capture_output=True, text=True, timeout=60)
    assert "trainer.step" not in res2.stdout
    assert "cachedop.compile[net]" in res2.stdout


def test_profile_counter_absorbed_into_registry():
    from mxnet_tpu import profiler

    c = profiler.ProfileCounter("requests")
    c.increment(5)
    c.decrement(2)
    c.value = 7  # legacy attribute-style write still works
    assert c.value == 7
    assert 'mxtpu_profile_counter{name="requests"} 7' \
        in obs.dump_prometheus()


# ---------------------------------------------------------------------------
# training-loop integrations
# ---------------------------------------------------------------------------

def test_estimator_telemetry_handler(caplog):
    import logging

    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.gluon.contrib.estimator.event_handler import (
        TelemetryHandler,
    )

    net = _tiny_net()
    net.hybridize()
    est = Estimator(net, gluon.loss.L2Loss(),
                    metrics=mx.metric.Loss("l2"))
    data = [(mx.nd.ones((2, 8)), mx.nd.ones((2, 4)))] * 2
    handler = TelemetryHandler()
    with caplog.at_level(logging.INFO, logger="telemetry"):
        est.fit(data, epochs=1, event_handlers=[handler])
    assert obs.enabled()  # attaching the handler is the opt-in
    text = caplog.text
    assert "op dispatches" in text
    assert "telemetry summary" in text
    epochs = [ev for ev in obs.tracer().events() if ev["cat"] == "epoch"]
    assert len(epochs) == 1 and epochs[0]["args"]["batches"] == 2


def test_callback_telemetry_logger(caplog):
    import logging

    obs.set_enabled(True)
    a = mx.nd.ones((2, 2))
    (a + a).asnumpy()
    cb = mx.callback.TelemetryLogger()
    with caplog.at_level(logging.INFO, logger="telemetry"):
        cb(0, None, None, None)  # epoch_end_callback signature
    assert "telemetry summary" in caplog.text
    assert "[Epoch 0]" in caplog.text


# ---------------------------------------------------------------------------
# Prometheus histogram exposition spec (PR7 satellite): cumulative
# bucket counts, an explicit +Inf bucket equal to _count, and the
# _sum/_count series — the format prometheus scrapers actually require
# ---------------------------------------------------------------------------

def test_histogram_prometheus_spec_compliance():
    reg = obs.MetricsRegistry()
    h = reg.histogram("t_lat_seconds", "spec probe",
                      buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    h.observe(0.5, route="a")
    lines = h.expose()
    assert lines.count("# TYPE t_lat_seconds histogram") == 1

    def val(line):
        return float(line.rsplit(" ", 1)[1])

    # unlabeled series: cumulative, monotonically non-decreasing counts
    unl = [ln for ln in lines
           if ln.startswith('t_lat_seconds_bucket{le=')]
    assert [val(ln) for ln in unl] == [1, 3, 4, 5]
    assert unl[-1].startswith('t_lat_seconds_bucket{le="+Inf"}')
    # +Inf bucket == _count, and _sum is the exact observation sum
    assert val([ln for ln in lines
                if ln.startswith("t_lat_seconds_count ")][0]) == 5
    assert val([ln for ln in lines
                if ln.startswith("t_lat_seconds_sum ")][0]) \
        == pytest.approx(56.05)
    # labeled series carry their labels plus le, same cumulative rule
    lab = [ln for ln in lines
           if ln.startswith('t_lat_seconds_bucket{route="a"')]
    assert [val(ln) for ln in lab] == [0, 1, 1, 1]
    assert 'le="+Inf"' in lab[-1]
    assert val([ln for ln in lines if ln.startswith(
        't_lat_seconds_count{route="a"}')][0]) == 1


def test_series_gauge_lazy_array_semantics():
    import jax.numpy as jnp

    reg = obs.MetricsRegistry()
    s = reg.series_gauge("t_iter_series", "per-slot probe")
    s.set_series(jnp.asarray([1.0, 2.0, 3.0]))  # stored lazy, whole
    assert s.series() == [1.0, 2.0, 3.0]
    assert s.value() == 3.0  # last slot
    assert s.total() == 6.0
    lines = s.expose()
    assert 't_iter_series{slot="0"} 1' in lines
    assert 't_iter_series{slot="2"} 3' in lines
    s.set_series([5.0])  # plain lists work too; old slots drop
    assert s.series() == [5.0]
    assert len([ln for ln in s.expose() if "slot=" in ln]) == 1


# ---------------------------------------------------------------------------
# scrape endpoint (PR7 satellite): /metrics + /healthz on a
# background thread, idempotent shutdown
# ---------------------------------------------------------------------------

def test_serve_metrics_endpoint_and_idempotent_shutdown():
    import urllib.error
    import urllib.request

    port = obs.serve_metrics(0)  # ephemeral
    try:
        assert obs.metrics_port() == port
        # idempotent start: same port back, no second server
        assert obs.serve_metrics(0) == port
        obs.registry().counter("t_http_probe_total").inc(7)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "t_http_probe_total 7" in body
        assert "mxtpu_trainer_step_total" in body  # whole catalog served
        hz = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10)
        assert hz.read() == b"ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
    finally:
        obs.stop_metrics_server()
        obs.stop_metrics_server()  # idempotent
    assert obs.metrics_port() is None
    # restartable after shutdown
    p2 = obs.serve_metrics(0)
    try:
        assert p2
    finally:
        obs.stop_metrics_server()


# ---------------------------------------------------------------------------
# telemetry-overhead regression (PR7 satellite): MXTPU_TELEMETRY=1 must
# add ZERO XLA dispatches to the fused loop (the in-graph grad norm is
# a lazy device scalar, not an extra executable) and bounded wall cost
# ---------------------------------------------------------------------------

def test_telemetry_adds_zero_dispatches_and_bounded_wall():
    import time as _time

    from mxnet_tpu import autograd as ag, engine, gluon as gl

    loss_fn = gl.loss.SoftmaxCrossEntropyLoss()
    net = _tiny_net()
    net.hybridize()
    tr = gl.Trainer(net.collect_params(), "sgd",
                    {"learning_rate": 0.05, "momentum": 0.9},
                    kvstore=None)
    X, Y = mx.nd.ones((8, 8)), mx.nd.zeros((8,))

    def one():
        with ag.record():
            l = loss_fn(net(X), Y)
        l.backward()
        tr.step(8)
        return l

    def timed(n):
        t0 = _time.perf_counter()
        l = None
        for _ in range(n):
            l = one()
        engine.wait(l.data)
        return _time.perf_counter() - t0

    N = 30
    one(); engine.wait(one().data)      # warm (telemetry off)
    t_off = timed(N)
    obs.set_enabled(True)
    # telemetry flips the CachedOp key + fused-plan signature: one
    # warm step absorbs the rebuild before counting
    one(); engine.wait(one().data)
    c0 = obs.XLA_DISPATCH_TOTAL.total()
    engine.wait(one().data)
    per_step = obs.XLA_DISPATCH_TOTAL.total() - c0  # steady-state cost
    c0 = obs.XLA_DISPATCH_TOTAL.total()
    fused0 = obs.XLA_DISPATCH_TOTAL.value(site="trainer_fused")
    op0 = obs.XLA_DISPATCH_TOTAL.value(site="op")
    t_on = timed(N)
    delta = obs.XLA_DISPATCH_TOTAL.total() - c0
    # telemetry dispatches NOTHING of its own: every step costs exactly
    # the steady-state constant (the grad-norm gauge rides the fused
    # executable as a lazy scalar — no probe executable, no sync), and
    # the fused trio stays one dispatch per site per step. The only
    # `op` dispatches are the un-hybridized loss block's own eager ops
    # (a property of the loop, identical with telemetry off).
    assert delta == per_step * N, (delta, per_step, N)
    assert obs.XLA_DISPATCH_TOTAL.value(site="trainer_fused") \
        - fused0 == N
    assert (obs.XLA_DISPATCH_TOTAL.value(site="op") - op0) \
        == (per_step - 3) * N  # fwd + bwd + fused update = the 3
    # bounded wall overhead; re-measure BOTH legs once before failing —
    # CI host pressure must not masquerade as a telemetry regression
    # (and the retry baseline must really run telemetry-OFF, or the
    # retry would compare on-vs-on and the gate would be vacuous)
    if t_on > 4.0 * t_off:
        obs.set_enabled(False)
        engine.wait(one().data)  # re-warm the off-keyed executables
        t_off = timed(N)
        obs.set_enabled(True)
        engine.wait(one().data)
        t_on = timed(N)
    assert t_on < 4.0 * t_off, (t_on, t_off)
