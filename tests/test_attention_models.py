"""Flash attention, ring attention, and NLP model tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, models, parallel


def _dense_attn(q, k, v, causal=False):
    D = q.shape[-1]
    s = np.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(D)
    if causal:
        T, S = q.shape[2], k.shape[2]
        mask = np.tril(np.ones((T, S), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhts,bhsd->bhtd", p, v)


def test_flash_attention_forward():
    B, H, T, D = 2, 2, 16, 8
    q = np.random.randn(B, H, T, D).astype(np.float32) * 0.5
    k = np.random.randn(B, H, T, D).astype(np.float32) * 0.5
    v = np.random.randn(B, H, T, D).astype(np.float32) * 0.5
    out = mx.nd.flash_attention(mx.nd.array(q), mx.nd.array(k), mx.nd.array(v))
    np.testing.assert_allclose(out.asnumpy(), _dense_attn(q, k, v),
                               rtol=1e-4, atol=1e-5)
    outc = mx.nd.flash_attention(mx.nd.array(q), mx.nd.array(k),
                                 mx.nd.array(v), causal=True)
    np.testing.assert_allclose(outc.asnumpy(), _dense_attn(q, k, v, True),
                               rtol=1e-4, atol=1e-5)


def test_flash_attention_grad_matches_dense():
    B, H, T, D = 1, 2, 8, 4
    qn = np.random.randn(B, H, T, D).astype(np.float32) * 0.5
    kn = np.random.randn(B, H, T, D).astype(np.float32) * 0.5
    vn = np.random.randn(B, H, T, D).astype(np.float32) * 0.5
    q, k, v = mx.nd.array(qn), mx.nd.array(kn), mx.nd.array(vn)
    for a in (q, k, v):
        a.attach_grad()
    with autograd.record():
        o = mx.nd.flash_attention(q, k, v, causal=True)
        loss = (o * o).sum()
    loss.backward()

    def dense(qq, kk, vv):
        s = jnp.einsum("bhtd,bhsd->bhts", qq, kk) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhts,bhsd->bhtd", p, vv)
        return (o * o).sum()

    gq, gk, gv = jax.grad(dense, argnums=(0, 1, 2))(
        jnp.asarray(qn), jnp.asarray(kn), jnp.asarray(vn))
    np.testing.assert_allclose(q.grad.asnumpy(), np.asarray(gq), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(k.grad.asnumpy(), np.asarray(gk), rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_allclose(v.grad.asnumpy(), np.asarray(gv), rtol=1e-3,
                               atol=1e-4)


def test_ring_attention_matches_dense():
    B, H, T, D = 2, 2, 16, 8
    q = np.random.randn(B, H, T, D).astype(np.float32) * 0.5
    k = np.random.randn(B, H, T, D).astype(np.float32) * 0.5
    v = np.random.randn(B, H, T, D).astype(np.float32) * 0.5
    mesh = parallel.make_mesh({"sp": 4}, devices=jax.devices()[:4])
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh)
    np.testing.assert_allclose(np.asarray(out), _dense_attn(q, k, v),
                               rtol=1e-4, atol=1e-5)
    outc = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), mesh, causal=True)
    np.testing.assert_allclose(np.asarray(outc), _dense_attn(q, k, v, True),
                               rtol=1e-4, atol=1e-5)


def test_multi_head_attention_block():
    mha = models.MultiHeadAttention(units=32, num_heads=4)
    mha.initialize(init=mx.initializer.Xavier())
    x = mx.nd.random.normal(shape=(2, 10, 32))
    out = mha(x)
    assert out.shape == (2, 10, 32)


def test_bert_forward_and_hybrid():
    bert = models.get_bert_model("bert_12_768_12", vocab_size=50,
                                 num_layers=2, units=32, hidden_size=64,
                                 num_heads=4, dropout=0.0)
    bert.initialize(init=mx.initializer.Normal(0.02))
    ids = mx.nd.array(np.random.randint(0, 50, (2, 12)).astype(np.float32))
    tt = mx.nd.zeros((2, 12))
    seq, pooled, cls, dec = bert(ids, tt)
    assert seq.shape == (2, 12, 32)
    assert pooled.shape == (2, 32)
    assert cls.shape == (2, 2)
    assert dec.shape == (2, 12, 50)
    bert.hybridize()
    seq2 = bert(ids, tt)[0]
    np.testing.assert_allclose(seq.asnumpy(), seq2.asnumpy(), rtol=1e-4,
                               atol=1e-5)


def test_bert_trains():
    bert = models.get_bert_model("bert_12_768_12", vocab_size=50,
                                 num_layers=1, units=32, hidden_size=64,
                                 num_heads=4, dropout=0.0,
                                 use_decoder=False)
    bert.initialize(init=mx.initializer.Normal(0.02))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def nsp_loss(outputs, labels):
        return loss_fn(outputs[2], labels)

    step = parallel.SPMDTrainStep(bert, nsp_loss, "adam", {}, mesh=None)
    ids = mx.nd.array(np.random.randint(0, 50, (4, 12)).astype(np.float32))
    y = mx.nd.array(np.random.randint(0, 2, (4,)).astype(np.float32))
    losses = [step(ids, y, lr=1e-3) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_llama_tiny_train():
    net = models.llama_tiny()
    net.initialize(init=mx.initializer.Normal(0.02))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def lm_loss(logits, labels):
        return loss_fn(logits.reshape((-1, logits.shape[-1])),
                       labels.reshape((-1,)))

    step = parallel.SPMDTrainStep(net, lm_loss, "adam", {}, mesh=None)
    x = mx.nd.array(np.random.randint(0, 256, (2, 16)).astype(np.float32))
    losses = [step(x, x, lr=1e-3) for _ in range(5)]
    assert losses[-1] < losses[0]


# tp x dp mesh composition parity is pinned every tier-1 round by
# test_composed4d.py; llama_tiny_train keeps the model itself tier-1
@pytest.mark.slow
def test_llama_tp_dp_mesh():
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    net = models.llama_tiny()
    net.initialize(init=mx.initializer.Normal(0.02))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def lm_loss(logits, labels):
        return loss_fn(logits.reshape((-1, logits.shape[-1])),
                       labels.reshape((-1,)))

    step = parallel.SPMDTrainStep(net, lm_loss, "adam", {}, mesh=mesh,
                                  param_sharding=net.tp_sharding_map())
    x = mx.nd.array(np.random.randint(0, 256, (8, 16)).astype(np.float32))
    l0 = step(x, x, lr=1e-3)
    l1 = step(x, x, lr=1e-3)
    assert np.isfinite(l0) and l1 < l0


def test_transformer_mt():
    tr = models.Transformer(30, 40, num_layers=1, units=16, hidden_size=32,
                            num_heads=2, dropout=0.0)
    tr.initialize(init=mx.initializer.Normal(0.02))
    src = mx.nd.array(np.random.randint(0, 30, (2, 8)).astype(np.float32))
    tgt = mx.nd.array(np.random.randint(0, 40, (2, 6)).astype(np.float32))
    out = tr(src, tgt)
    assert out.shape == (2, 6, 40)


def test_interleaved_matches_flash():
    """contrib interleaved attention and flash attention agree."""
    T, N, H, D = 8, 2, 2, 4
    qkv = np.random.randn(T, N, 3 * H * D).astype(np.float32) * 0.5
    att = mx.nd.contrib.interleaved_matmul_selfatt_qk(mx.nd.array(qkv), heads=H)
    probs = mx.nd.softmax(att, axis=-1)
    out1 = mx.nd.contrib.interleaved_matmul_selfatt_valatt(
        mx.nd.array(qkv), probs, heads=H).asnumpy()
    # same computation via flash path
    qkv_r = qkv.reshape(T, N, H, 3, D)
    q = np.transpose(qkv_r[:, :, :, 0], (1, 2, 0, 3))
    k = np.transpose(qkv_r[:, :, :, 1], (1, 2, 0, 3))
    v = np.transpose(qkv_r[:, :, :, 2], (1, 2, 0, 3))
    out2 = mx.nd.flash_attention(mx.nd.array(q), mx.nd.array(k),
                                 mx.nd.array(v)).asnumpy()
    out2 = np.transpose(out2, (2, 0, 1, 3)).reshape(T, N, H * D)
    np.testing.assert_allclose(out1, out2, rtol=1e-4, atol=1e-5)


def test_flash_attention_sliding_window_cpu_oracle():
    """window>0 (Mistral-style local attention): fwd and grads match a
    dense-masked softmax reference on the CPU path."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import flash_attention as fa

    rng = np.random.RandomState(0)
    B, H, T, D, W = 1, 2, 64, 16, 12
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    mask = np.tril(np.ones((T, T), bool)) \
        & (np.arange(T)[:, None] - np.arange(T)[None, :] < W)

    def dense(q_, k_, v_):
        s = jnp.einsum("bhtd,bhsd->bhts", q_, k_) / np.sqrt(D)
        s = jnp.where(jnp.asarray(mask), s, -1e30)
        return jnp.einsum("bhts,bhsd->bhtd", jax.nn.softmax(s, axis=-1), v_)

    out = fa.flash_attention(q, k, v, window=W, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense(q, k, v)),
                               rtol=1e-4, atol=1e-5)
    for argnum in range(3):
        g1 = jax.grad(lambda *a: jnp.sum(
            fa.flash_attention(*a, window=W, block_size=16) ** 2),
            argnums=argnum)(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(dense(*a) ** 2),
                      argnums=argnum)(q, k, v)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-3, atol=1e-4)
    # window attention requires self-attention shapes
    with pytest.raises(ValueError):
        fa.flash_attention(q, k[:, :, :32], v[:, :, :32], window=W)


def test_flash_attention_grouped_query_cpu_oracle():
    """GQA (fewer kv heads than q heads): fwd and all three grads match
    the repeated-kv dense reference; dk/dv fold the group correctly."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import flash_attention as fa

    rng = np.random.RandomState(0)
    B, H, KVH, T, D = 1, 4, 2, 32, 8
    G = H // KVH
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, KVH, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, KVH, T, D), jnp.float32)
    mask = np.tril(np.ones((T, T), bool))

    def dense(q_, k_, v_):
        k2 = jnp.repeat(k_, G, axis=1)
        v2 = jnp.repeat(v_, G, axis=1)
        s = jnp.einsum("bhtd,bhsd->bhts", q_, k2) / np.sqrt(D)
        s = jnp.where(jnp.asarray(mask), s, -1e30)
        return jnp.einsum("bhts,bhsd->bhtd", jax.nn.softmax(s, -1), v2)

    out = fa.flash_attention(q, k, v, causal=True, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense(q, k, v)),
                               rtol=1e-4, atol=1e-5)
    for argnum in range(3):
        g1 = jax.grad(lambda *a: jnp.sum(fa.flash_attention(
            *a, causal=True, block_size=16) ** 2), argnums=argnum)(q, k, v)
        g2 = jax.grad(lambda *a: jnp.sum(dense(*a) ** 2),
                      argnums=argnum)(q, k, v)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-3, atol=1e-4)
    # indivisible head counts are rejected loudly
    with pytest.raises(ValueError):
        fa.flash_attention(q, k[:, :1][:, [0, 0, 0]], v[:, :3], causal=True)


def test_flash_gqa_native_over_cap_routing():
    """native_gqa routing around the fused-backward VMEM cap: with the
    default split backward (no full-T scratch) an over-cap flattened q
    stays on the NATIVE unrepeated path; with MXTPU_FLASH_BWD=fused the
    cap forces the repeat-and-fold path whose inner grad then runs the
    split kernel (r4 behavior; supersedes the r2 jnp-fallback contract)."""
    import os

    import jax.numpy as jnp

    from mxnet_tpu.ops import flash_attention as fa

    calls = []

    def fake_split(q, k, v, out, lse, g, scale, causal, bq=512, bk=512,
                   window=0):
        calls.append(("split", q.shape, k.shape))
        return (jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v))

    def fake_fused(q, k, v, out, lse, g, scale, causal, bq=512, bk=512,
                   window=0):
        calls.append(("fused", q.shape, k.shape))
        return (jnp.zeros_like(q), jnp.zeros_like(k), jnp.zeros_like(v))

    orig = (fa._pallas_ready, fa._PALLAS_BWD_MAX_T,
            fa._pallas_flash_bwd_split, fa._pallas_flash_bwd)
    fa._pallas_ready = lambda q, k, causal, bs: True
    fa._PALLAS_BWD_MAX_T = 2  # group*T=16 and T=4 both exceed
    fa._pallas_flash_bwd_split = fake_split
    fa._pallas_flash_bwd = fake_fused
    q = jnp.ones((1, 8, 4, 8))
    k = jnp.ones((1, 2, 4, 8))
    v = jnp.ones((1, 2, 4, 8))
    res = (q, k, v, jnp.ones_like(q), jnp.ones((1, 8, 4)), )
    try:
        # default split: native stays unrepeated despite the cap
        os.environ.pop("MXTPU_FLASH_BWD", None)
        dq, dk, dv = fa._flash_bwd_rule(1.0, True, 4, 0, True,
                                        (q, k, v, res[3], res[4]),
                                        jnp.ones_like(q))
        assert calls == [("split", q.shape, k.shape)], calls
        assert dq.shape == q.shape and dk.shape == k.shape

        # fused mode: cap forces repeat-and-fold; inner grad goes split
        calls.clear()
        os.environ["MXTPU_FLASH_BWD"] = "fused"
        dq, dk, dv = fa._flash_bwd_rule(1.0, True, 4, 0, True,
                                        (q, k, v, res[3], res[4]),
                                        jnp.ones_like(q))
        assert len(calls) == 1 and calls[0][0] == "split", calls
        assert calls[0][2] == (1, 8, 4, 8)  # repeated kv heads
        assert dk.shape == k.shape and dv.shape == v.shape  # folded back
    finally:
        os.environ.pop("MXTPU_FLASH_BWD", None)
        (fa._pallas_ready, fa._PALLAS_BWD_MAX_T,
         fa._pallas_flash_bwd_split, fa._pallas_flash_bwd) = orig
