"""Crash flight recorder (observability/flight.py): bundle contents,
in-flight dispatch tracking, hook install/uninstall hygiene, and the
end-to-end contract — killing a training run mid-step with
MXTPU_DUMP_ON_CRASH set produces a parseable bundle (via subprocess,
for both SIGTERM and an unhandled exception)."""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, observability as obs
from mxnet_tpu.gluon import nn
from mxnet_tpu.observability import flight, introspect

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()


@pytest.fixture(autouse=True)
def _clean():
    obs.set_enabled(False)
    obs.reset()
    introspect.set_enabled(False)
    introspect.reset()
    yield
    flight.uninstall()
    obs.set_enabled(False)
    obs.reset()
    introspect.set_enabled(False)
    introspect.reset()


def _train_steps(n=2):
    net = nn.Dense(4, in_units=8)
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    X, Y = mx.nd.ones((8, 8)), mx.nd.zeros((8,))
    for _ in range(n):
        with autograd.record():
            l = loss_fn(net(X), Y)
        l.backward()
        tr.step(8)


# ---------------------------------------------------------------------------
# in-process bundle
# ---------------------------------------------------------------------------

def test_manual_dump_bundle_contents(tmp_path):
    flight.install(str(tmp_path))
    obs.set_enabled(True)
    introspect.set_enabled(True)
    _train_steps()
    path = flight.dump(reason="manual-test")
    assert path and os.path.exists(path)
    b = json.load(open(path))
    assert b["format"] == "mxtpu-flight-recorder-v1"
    assert b["reason"] == "manual-test"
    assert b["step"] >= 2
    assert b["trace_events"] and all("name" in ev
                                     for ev in b["trace_events"])
    assert "trainer_fused" in b["executables"]
    assert b["executables"]["trainer_fused"]["flops"] > 0
    assert "mxtpu_trainer_step_total" in b["metrics"]
    assert b["in_flight"] == {}
    assert b["backend"] is not None


def test_dump_without_dir_returns_none():
    assert flight.dump(reason="nowhere") is None


def test_in_flight_tracking():
    with flight.dispatch("t_site"):
        with flight.dispatch("t_site"):
            assert flight.in_flight() == {"t_site": 2}
        assert flight.in_flight() == {"t_site": 1}
    assert flight.in_flight() == {}


def test_in_flight_captured_in_bundle(tmp_path):
    flight.install(str(tmp_path))
    with flight.dispatch("trainer_fused"):
        b = flight.build_bundle("probe")
    assert b["in_flight"] == {"trainer_fused": 1}


def test_install_uninstall_restores_hooks(tmp_path):
    prev_hook = sys.excepthook
    prev_term = signal.getsignal(signal.SIGTERM)
    flight.install(str(tmp_path))
    assert flight.INSTALLED
    assert sys.excepthook is not prev_hook
    flight.install(str(tmp_path))  # idempotent
    flight.uninstall()
    assert not flight.INSTALLED
    assert sys.excepthook is prev_hook
    assert signal.getsignal(signal.SIGTERM) == prev_term
    flight.uninstall()  # idempotent too


def test_bundle_survives_lazy_device_gauges(tmp_path):
    """Lazy device scalars stored by the fused step must serialize
    (synced at dump time), not crash the JSON encoder."""
    import jax.numpy as jnp

    flight.install(str(tmp_path))
    obs.TRAINER_GRAD_NORM.set_lazy(jnp.float32(3.5))
    path = flight.dump(reason="lazy")
    b = json.load(open(path))
    assert b["metrics"]["mxtpu_trainer_grad_norm"]["values"][""] == 3.5


# ---------------------------------------------------------------------------
# subprocess: the real crash paths
# ---------------------------------------------------------------------------

_CHILD = """
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {root!r})
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

net = nn.Dense(4, in_units=8)
net.initialize(); net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd",
                   {{"learning_rate": 0.1}}, kvstore=None)
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
X, Y = mx.nd.ones((8, 8)), mx.nd.zeros((8,))

def one():
    with autograd.record():
        l = loss_fn(net(X), Y)
    l.backward()
    tr.step(8)

one(); one()  # warm: compile + register executables
open({ready!r}, "w").write("ready")
i = 0
while True:
    one()
    i += 1
    if {raise_at} and i >= {raise_at}:
        raise RuntimeError("mid-training crash for the recorder test")
    time.sleep(0.001)
"""


def _spawn(tmp_path, raise_at=0):
    dump_dir = tmp_path / "dumps"
    ready = str(tmp_path / "ready")
    env = dict(os.environ)
    env.update(MXTPU_DUMP_ON_CRASH=str(dump_dir), MXTPU_TELEMETRY="1",
               MXTPU_INTROSPECT="1")
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _CHILD.format(root=ROOT, ready=ready, raise_at=raise_at)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    return proc, dump_dir, ready


def _wait_ready(proc, ready, timeout=120):
    t0 = time.monotonic()
    while not os.path.exists(ready):
        if proc.poll() is not None:
            raise AssertionError(
                f"child died early: {proc.stderr.read().decode()[-2000:]}")
        if time.monotonic() - t0 > timeout:
            proc.kill()
            raise AssertionError("child never became ready")
        time.sleep(0.05)


def _read_bundle(dump_dir):
    files = glob.glob(str(dump_dir / "flight_*.json"))
    assert len(files) == 1, files
    return json.load(open(files[0]))


# the exception-path bundle test stays tier-1; SIGTERM handler order
# is separately pinned by test_resilience's sigterm_order tests
@pytest.mark.slow
def test_sigterm_mid_training_writes_bundle(tmp_path):
    """The acceptance path: kill a live training loop with SIGTERM and
    get a parseable bundle with the last trace events and the
    executable cost table."""
    proc, dump_dir, ready = _spawn(tmp_path)
    try:
        _wait_ready(proc, ready)
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    # the handler re-raises the signal after dumping, so the parent
    # sees a true SIGTERM death, not a clean exit
    assert proc.returncode == -signal.SIGTERM, proc.returncode
    b = _read_bundle(dump_dir)
    assert b["reason"] == "signal: SIGTERM"
    names = {ev["name"] for ev in b["trace_events"]}
    assert "trainer.step" in names
    assert b["executables"].get("trainer_fused", {}).get("flops")
    assert b["step"] > 0
    assert b["env"].get("MXTPU_DUMP_ON_CRASH")


def test_unhandled_exception_writes_bundle(tmp_path):
    proc, dump_dir, ready = _spawn(tmp_path, raise_at=3)
    try:
        _wait_ready(proc, ready)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 1  # the original traceback still exits 1
    assert b"mid-training crash" in proc.stderr.read()
    b = _read_bundle(dump_dir)
    assert b["reason"].startswith("exception: RuntimeError")
    assert "trainer_fused" in b["executables"]
