"""Symbol API tests (reference model: test_symbol.py + test_executor.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.test_utils import assert_almost_equal


def test_variable_and_compose():
    x = sym.var("x")
    y = sym.var("y")
    z = x + y
    assert set(z.list_arguments()) == {"x", "y"}
    assert z.list_outputs()[0].endswith("_output")


def test_symbol_eval():
    x = sym.var("x")
    y = sym.FullyConnected(x, sym.var("w"), sym.var("b"), num_hidden=3)
    out = y.eval(x=mx.nd.ones((2, 4)),
                 w=mx.nd.ones((3, 4)),
                 b=mx.nd.zeros((3,)))[0]
    assert_almost_equal(out, np.full((2, 3), 4.0, np.float32))


def test_infer_shape():
    x = sym.var("x")
    w = sym.var("w")
    b = sym.var("b")
    y = sym.FullyConnected(x, w, b, num_hidden=5)
    arg_shapes, out_shapes, _ = y.infer_shape(x=(2, 3), w=(5, 3), b=(5,))
    assert out_shapes == [(2, 5)]


def test_simple_bind_forward_backward():
    x = sym.var("x")
    w = sym.var("w")
    y = sym.FullyConnected(x, w, None, num_hidden=2, no_bias=True)
    loss = sym.sum(y * y)
    exe = loss.simple_bind(ctx=mx.cpu(), x=(3, 4), w=(2, 4))
    exe.arg_dict["x"]._set_data(np.ones((3, 4), np.float32))
    exe.arg_dict["w"]._set_data(np.full((2, 4), 0.5, np.float32))
    (out,) = exe.forward(is_train=True)
    # y = 2.0 everywhere (3x2); loss = 24
    assert out.asscalar() == pytest.approx(24.0)
    exe.backward()
    # dL/dw = sum over batch of 2*y*x = 2*2*1 summed over 3 rows = 12
    assert_almost_equal(exe.grad_dict["w"], np.full((2, 4), 12.0, np.float32))


def test_tojson_load_roundtrip(tmp_path):
    x = sym.var("data")
    y = sym.Activation(sym.FullyConnected(
        x, sym.var("w"), sym.var("b"), num_hidden=4), act_type="relu")
    f = str(tmp_path / "net.json")
    y.save(f)
    y2 = sym.load(f)
    assert set(y2.list_arguments()) == set(y.list_arguments())
    args = dict(data=mx.nd.ones((1, 3)), w=mx.nd.ones((4, 3)),
                b=mx.nd.zeros((4,)))
    o1 = y.eval(**args)[0]
    o2 = y2.eval(**args)[0]
    assert_almost_equal(o1, o2.asnumpy())


def test_multi_output_split():
    x = sym.var("x")
    parts = sym.split(x, num_outputs=2, axis=1)
    assert len(parts) == 2
    o = parts[1].eval(x=mx.nd.array(np.arange(8).reshape(2, 4)))[0]
    assert_almost_equal(o, np.array([[2, 3], [6, 7]], np.float32))


def test_symbol_arithmetic():
    a = sym.var("a")
    b = sym.var("b")
    c = (a * 2 + b) / 4
    out = c.eval(a=mx.nd.array([2.0]), b=mx.nd.array([4.0]))[0]
    assert out.asscalar() == pytest.approx(2.0)


def test_group():
    a = sym.var("a")
    g = sym.Group([a * 2, a + 1])
    outs = g.eval(a=mx.nd.array([3.0]))
    assert outs[0].asscalar() == pytest.approx(6.0)
    assert outs[1].asscalar() == pytest.approx(4.0)


def test_batchnorm_aux_states():
    x = sym.var("x")
    bn = sym.BatchNorm(x, sym.var("gamma"), sym.var("beta"),
                       sym.var("moving_mean", __aux__=True),
                       sym.var("moving_var", __aux__=True))
    assert "moving_mean" in bn.list_auxiliary_states()
    assert "moving_mean" not in bn.list_arguments()


def test_get_internals():
    x = sym.var("x")
    h = sym.FullyConnected(x, sym.var("w"), None, num_hidden=3, no_bias=True,
                           name="fc1")
    y = sym.relu(h, name="act")
    internals = y.get_internals()
    assert any("fc1" in str(s.name) for s in internals._inputs)


def test_check_symbolic_helpers():
    """test_utils.check_symbolic_forward/backward + same_symbol_structure
    (reference: python/mxnet/test_utils.py)."""
    from mxnet_tpu import test_utils as tu

    net = sym.FullyConnected(sym.var("x"), sym.var("w"), None,
                             no_bias=True, num_hidden=3)
    xd = np.random.rand(2, 4).astype(np.float32)
    wd = np.random.rand(3, 4).astype(np.float32)
    tu.check_symbolic_forward(net, {"x": xd, "w": wd}, [xd @ wd.T])
    og = np.random.rand(2, 3).astype(np.float32)
    tu.check_symbolic_backward(net, {"x": xd, "w": wd}, [og],
                               {"x": og @ wd, "w": og.T @ xd})
    same = sym.FullyConnected(sym.var("a"), sym.var("b"), None,
                              no_bias=True, num_hidden=3)
    other = sym.FullyConnected(sym.var("a"), sym.var("b"), None,
                               no_bias=True, num_hidden=5)
    assert tu.same_symbol_structure(net, same)
    assert not tu.same_symbol_structure(net, other)
    # a wrong expectation must raise
    import pytest as _pytest

    with _pytest.raises(AssertionError):
        tu.check_symbolic_forward(net, {"x": xd, "w": wd},
                                  [np.zeros((2, 3), np.float32)])


def test_loss_blocks_trace_symbolically():
    """The gluon losses must trace with Symbol inputs (export path) —
    the r5 lse-pick rewrite briefly used NDArray-only .astype (review
    regression)."""
    from mxnet_tpu import gluon
    from mxnet_tpu.symbol.symbol import var

    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    s = sce(var("pred"), var("label"))
    assert set(s.list_arguments()) == {"pred", "label"}
