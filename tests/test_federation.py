"""Cross-rank metric federation (observability.federation): snapshot /
ingest / merge semantics on a forced multi-device CPU mesh, exact
histogram bucket merging, stale-rank marking, the /metrics/cluster
endpoint, and the zero-added-dispatch contract with the whole
observability plane (publisher + watchdog) armed.

The REAL multi-process exchange leg lives in
``tests/distributed/test_dist_tpu_sync.py::test_federation_multiprocess``
(fed_worker.py under tools/launch.py); these tests pin the merge and
exposition semantics the exchange feeds.
"""

import json
import os
import re
import subprocess
import sys
import time
import urllib.request

import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, observability as obs
from mxnet_tpu.gluon import nn
from mxnet_tpu.observability import federation as fed
from mxnet_tpu.observability import watchdog as wd

TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")


@pytest.fixture(autouse=True)
def _federation_state():
    """Every test starts from an empty cluster table and a clean,
    enabled registry; nothing leaks into the tier-1 process state."""
    obs.set_enabled(True)
    obs.reset()
    fed.stop()
    fed.reset()
    wd.reset()
    yield
    fed.stop()
    fed.reset()
    wd.set_enabled(False)
    wd.reset()
    obs.stop_metrics_server()
    obs.set_enabled(False)
    obs.reset()


def _clone(snap):
    """JSON round-trip — exactly what the wire does to a snapshot."""
    return json.loads(json.dumps(snap))


def _peer(local, rank, **overrides):
    p = _clone(local)
    p["rank"] = rank
    p.update(overrides)
    return p


def _val(text, metric, **labels):
    want = "{" + ",".join(f'{k}="{v}"' for k, v in
                          sorted(labels.items())) + "}"
    m = re.search(re.escape(metric + want) + r" ([-0-9.e+]+|nan|inf)",
                  text)
    return float(m.group(1)) if m else None


# ---------------------------------------------------------------------------
# snapshot / side-channel plumbing
# ---------------------------------------------------------------------------

def test_all_gather_bytes_single_process_identity():
    from mxnet_tpu.kvstore.dist import all_gather_bytes

    assert all_gather_bytes(b"payload") == [b"payload"]
    assert all_gather_bytes(b"") == [b""]


def test_snapshot_carries_every_metric_kind():
    obs.TRAINER_STEP_TOTAL.inc(3)
    obs.TRAINER_GRAD_NORM.set(1.5)
    obs.TRAINER_STEP_SECONDS.observe(0.02)
    obs.SUPERSTEP_ITER_LOSS.set_series([0.5, 0.4])
    snap = fed.snapshot()
    assert snap["rank"] == 0
    assert isinstance(snap["step_epoch"], int)
    m = snap["metrics"]
    assert m["mxtpu_trainer_step_total"]["kind"] == "counter"
    assert m["mxtpu_trainer_grad_norm"]["kind"] == "gauge"
    assert m["mxtpu_trainer_step_seconds"]["kind"] == "histogram"
    assert m["mxtpu_trainer_step_seconds"]["buckets"]
    assert m["mxtpu_superstep_iter_loss"]["kind"] == "series_gauge"
    # a snapshot survives the JSON wire intact
    assert _clone(snap) == json.loads(json.dumps(snap))


def test_ingest_and_cluster_ranks():
    obs.TRAINER_STEP_TOTAL.inc()
    fed.publish_local()
    local = _clone(fed.snapshot())
    fed.ingest(_peer(local, 2))
    fed.ingest(_peer(local, 1))
    assert fed.cluster_ranks() == [0, 1, 2]


# ---------------------------------------------------------------------------
# merge semantics
# ---------------------------------------------------------------------------

def test_cluster_counter_sum_and_gauge_aggregates():
    obs.TRAINER_STEP_TOTAL.inc(5)
    obs.TRAINER_GRAD_NORM.set(2.0)
    fed.publish_local()
    local = _clone(fed.snapshot())
    for rank, (steps, gn) in ((1, (7.0, 6.0)), (2, (4.0, 1.0))):
        p = _peer(local, rank)
        p["metrics"]["mxtpu_trainer_step_total"]["values"]["[]"] = steps
        p["metrics"]["mxtpu_trainer_grad_norm"]["values"]["[]"] = gn
        fed.ingest(p)
    text = fed.cluster_registry().dump_prometheus()
    # per-rank series with the rank label
    assert _val(text, "mxtpu_trainer_step_total", rank="0") == 5
    assert _val(text, "mxtpu_trainer_step_total", rank="1") == 7
    # job aggregate: counters SUM
    assert _val(text, "mxtpu_trainer_step_total", rank="all") == 16
    # job aggregate: gauges min / median / max
    assert _val(text, "mxtpu_trainer_grad_norm",
                agg="min", rank="all") == 1.0
    assert _val(text, "mxtpu_trainer_grad_norm",
                agg="median", rank="all") == 2.0
    assert _val(text, "mxtpu_trainer_grad_norm",
                agg="max", rank="all") == 6.0


def test_cluster_histogram_merge_is_exact():
    """The rank="all" histogram must be the element-wise bucket sum —
    byte-exact against a local histogram that observed the union."""
    vals0 = (0.0005, 0.003, 0.2)
    vals1 = (0.0007, 0.05, 3.0, 0.00005)
    for v in vals0:
        obs.TRAINER_STEP_SECONDS.observe(v)
    fed.publish_local()
    local = _clone(fed.snapshot())

    # rank 1 observed a different set: build its record through a real
    # histogram with the same bucket layout (no hand-rolled records)
    scratch = obs.MetricsRegistry()
    h1 = scratch.histogram("h1", buckets=obs.TRAINER_STEP_SECONDS.buckets)
    for v in vals1:
        h1.observe(v)
    p = _peer(local, 1)
    p["metrics"]["mxtpu_trainer_step_seconds"]["values"]["[]"] = [
        float(x) for x in h1._values[()]]
    fed.ingest(p)

    reg = fed.cluster_registry()
    merged = reg.histogram("mxtpu_trainer_step_seconds")
    got = merged._values[(("rank", "all"),)]

    ref_reg = obs.MetricsRegistry()
    ref = ref_reg.histogram("ref", buckets=obs.TRAINER_STEP_SECONDS.buckets)
    for v in vals0 + vals1:
        ref.observe(v)
    expect = list(ref._values[()])
    assert got[:-2] == expect[:-2]                     # bucket counts
    assert got[-1] == expect[-1] == len(vals0) + len(vals1)
    assert got[-2] == pytest.approx(expect[-2])        # sum (float)
    # quantiles over the merged series match the union-observed ones
    assert merged.quantile(0.5, rank="all") == \
        pytest.approx(ref.quantile(0.5))


def test_cluster_histogram_bucket_mismatch_degrades():
    """Disagreeing bucket layouts must NOT fabricate an aggregate —
    per-rank series stay, the rank="all" row is absent."""
    obs.TRAINER_STEP_SECONDS.observe(0.01)
    fed.publish_local()
    local = _clone(fed.snapshot())
    p = _peer(local, 1)
    ent = p["metrics"]["mxtpu_trainer_step_seconds"]
    ent["buckets"] = [0.1, 1.0]
    ent["values"]["[]"] = [1.0, 0.0, 0.0, 0.01, 1.0]
    fed.ingest(p)
    text = fed.cluster_registry().dump_prometheus()
    assert _val(text, "mxtpu_trainer_step_seconds_count", rank="0") == 1
    # the foreign layout can't be rendered against our `le` edges and
    # must not fabricate a job aggregate — but it must not crash the
    # scrape either (dump_prometheus above IS the assertion for that)
    assert _val(text, "mxtpu_trainer_step_seconds_count",
                rank="1") is None
    assert _val(text, "mxtpu_trainer_step_seconds_count",
                rank="all") is None


def test_series_gauges_stay_per_rank():
    obs.SUPERSTEP_ITER_LOSS.set_series([0.5, 0.4])
    fed.publish_local()
    fed.ingest(_peer(_clone(fed.snapshot()), 1))
    text = fed.cluster_registry().dump_prometheus()
    assert _val(text, "mxtpu_superstep_iter_loss",
                rank="1", slot="0") == 0.5
    # no fabricated job-level aggregate for per-dispatch series
    assert 'mxtpu_superstep_iter_loss{rank="all"' not in text


# ---------------------------------------------------------------------------
# staleness
# ---------------------------------------------------------------------------

def test_stale_rank_marked_never_dropped():
    obs.TRAINER_STEP_TOTAL.inc(2)
    fed.publish_local()
    local = _clone(fed.snapshot())
    fed.ingest(_peer(local, 1), recv_mono=time.monotonic() - 999.0)
    fed.ingest(_peer(local, 2))
    assert fed.update_cluster_meta() == [1]
    text = fed.dump_prometheus_cluster()
    # the stale rank's last-known series are STILL exposed
    assert _val(text, "mxtpu_trainer_step_total", rank="1") == 2
    # ... and the marker gauge says so (observed rank -> peer label)
    assert _val(text, "mxtpu_federation_stale_ranks",
                peer="1", rank="0") == 1.0
    assert _val(text, "mxtpu_federation_stale_ranks",
                peer="2", rank="0") == 0.0
    # per-rank snapshot age + step epoch ride the same meta gauges
    assert _val(text, "mxtpu_federation_snapshot_age_seconds",
                peer="1", rank="0") >= 999.0
    assert _val(text, "mxtpu_federation_last_step",
                peer="2", rank="0") is not None


def test_stale_detection_disabled_by_zero(monkeypatch):
    monkeypatch.setenv("MXTPU_FEDERATION_STALE_S", "0")
    fed.publish_local()
    fed.ingest(_peer(_clone(fed.snapshot()), 1),
               recv_mono=time.monotonic() - 99999.0)
    assert fed.stale_ranks() == []


# ---------------------------------------------------------------------------
# endpoint + bundle
# ---------------------------------------------------------------------------

def test_metrics_cluster_endpoint():
    obs.TRAINER_STEP_TOTAL.inc(3)
    fed.publish_local()
    fed.ingest(_peer(_clone(fed.snapshot()), 1))
    port = obs.serve_metrics(0, host="127.0.0.1")
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics/cluster", timeout=10) as r:
        assert r.status == 200
        body = r.read().decode()
    # plain /metrics still serves the local, unlabeled view
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        local_body = r.read().decode()
    assert _val(body, "mxtpu_trainer_step_total", rank="0") == 3
    assert _val(body, "mxtpu_trainer_step_total", rank="all") == 6
    assert "mxtpu_trainer_step_total 3" in local_body


def test_dump_cluster_snapshot_renders_in_report(tmp_path):
    """The JSON bundle feeds tools/telemetry_report.py: the new Cluster
    and Anomalies sections render alongside the existing table."""
    obs.TRAINER_STEP_TOTAL.inc()
    wd.set_enabled(True)
    obs.SUPERSTEP_ITER_LOSS.set_series([float("nan")])
    obs.tracer().mark_step()
    assert "nan" in wd.check_now()
    fed.publish_local()
    local = _clone(fed.snapshot())
    fed.ingest(_peer(local, 1, step_epoch=local["step_epoch"] - 3),
               recv_mono=time.monotonic() - 999.0)
    path = str(tmp_path / "bundle.json")
    fed.dump_cluster_snapshot(path)
    res = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "telemetry_report.py"), path],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "Cluster (federated snapshots):" in res.stdout
    assert "STALE" in res.stdout
    assert "Anomalies (watchdog):" in res.stdout
    assert re.search(r"nan: 1 firing", res.stdout)


# ---------------------------------------------------------------------------
# step-beat poll: cross-rank collective-ordering discipline
# ---------------------------------------------------------------------------

def test_poll_noop_when_unarmed_or_single_process():
    assert fed.poll() is False        # not armed
    fed.start(interval=60.0)
    try:
        assert fed.poll() is False    # armed, but single-process
    finally:
        fed.stop()


def test_poll_exchanges_on_step_beat(monkeypatch):
    """The multi-process exchange fires ONLY from the step-boundary
    poll, on a step-count beat derived from the shared tracer step —
    identical on every rank, so the side-channel collectives stay
    identically ordered against the training allreduces."""
    calls = []
    monkeypatch.setenv("MXTPU_FEDERATION_BEAT_STEPS", "4")
    monkeypatch.setattr(fed, "_world_size", lambda: 2)
    monkeypatch.setattr(fed, "exchange",
                        lambda: calls.append(obs.tracer().step))
    fed.start(interval=60.0)
    try:
        for _ in range(9):
            obs.tracer().mark_step()
            fed.poll()
    finally:
        fed.stop()
    # beat indices 0/1/2 -> first poll at steps 1, 4 and 8; the polls
    # in between are pure host-side compares (no exchange)
    assert calls == [1, 4, 8]
    assert obs.FEDERATION_PUBLISH_TOTAL.total() >= 3


def test_poll_degrades_to_local_on_exchange_failure(monkeypatch):
    """A failed exchange is COUNTED and degrades to a local publish —
    the scrape endpoint never goes dark, and the error signal the
    federation contract promises actually fires."""
    monkeypatch.setattr(fed, "_world_size", lambda: 2)

    def boom():
        raise RuntimeError("collective down")

    monkeypatch.setattr(fed, "exchange", boom)
    fed.start(interval=60.0)
    try:
        obs.tracer().mark_step()
        assert fed.poll() is True
    finally:
        fed.stop()
    assert obs.FEDERATION_ERRORS_TOTAL.total() == 1
    assert fed.cluster_ranks() == [0]   # local publish still landed


def test_publisher_thread_never_issues_collectives(monkeypatch):
    """The heartbeat daemon stays LOCAL-ONLY even in a multi-process
    world: its timer fires on an independent clock per rank, so a
    collective launched from it would interleave differently with the
    training loop's allreduces on different processes."""
    monkeypatch.setattr(fed, "_world_size", lambda: 2)

    def forbidden():
        raise AssertionError("exchange() ran on the timer thread")

    monkeypatch.setattr(fed, "exchange", forbidden)
    fed.start(interval=0.02)
    try:
        deadline = time.monotonic() + 5.0
        while obs.FEDERATION_PUBLISH_TOTAL.total() < 3:
            assert time.monotonic() < deadline, "publisher never ticked"
            time.sleep(0.01)
    finally:
        fed.stop()
    assert obs.FEDERATION_ERRORS_TOTAL.total() == 0
    assert fed.cluster_ranks() == [0]


def test_side_channel_collectives_exempt_from_chaos():
    """A one-shot MXTPU_CHAOS collective fault armed for the data
    plane must never be consumed by a federation side-channel reduce
    (chaos certification stays deterministic with MXTPU_FEDERATION=1)."""
    import jax.numpy as jnp

    from mxnet_tpu.kvstore import dist as kvd
    from mxnet_tpu.resilience import chaos
    from mxnet_tpu.resilience.chaos import ChaosInjectedError

    chaos.configure("collective:1")
    try:
        arr = jnp.ones((2,), dtype=jnp.float32)
        kvd._global_allreduce(arr, chaos_point=None)   # exempt: no fire
        with pytest.raises(ChaosInjectedError):
            kvd._global_allreduce(arr)                 # data plane fires
    finally:
        chaos.reset()


# ---------------------------------------------------------------------------
# publisher thread + the zero-dispatch contract
# ---------------------------------------------------------------------------

def test_publisher_thread_idempotent_start_stop():
    assert fed.start(interval=0.02) is True
    assert fed.start(interval=0.02) is False  # already running
    deadline = time.monotonic() + 5.0
    while obs.FEDERATION_PUBLISH_TOTAL.total() < 2:
        assert time.monotonic() < deadline, "publisher never ticked"
        time.sleep(0.01)
    fed.stop()
    fed.stop()  # idempotent
    assert fed.cluster_ranks() == [0]
    assert _val(fed.dump_prometheus_cluster(),
                "mxtpu_federation_ranks", rank="0") == 1


def test_maybe_start_respects_env(monkeypatch):
    monkeypatch.delenv("MXTPU_FEDERATION", raising=False)
    fed.maybe_start()
    assert not fed.federation_enabled()
    monkeypatch.setenv("MXTPU_FEDERATION", "1")
    assert fed.federation_enabled()
    fed.maybe_start()
    try:
        assert fed.start() is False  # maybe_start already took the slot
    finally:
        fed.stop()


def _tiny_net(in_units=8, hidden=16, classes=4):
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu", in_units=in_units),
            nn.Dense(classes, in_units=hidden))
    net.initialize()
    return net


def test_observability_plane_adds_zero_dispatches():
    """THE hot-path contract: federation publisher + watchdog armed add
    exactly zero XLA dispatches per training step (same template as
    test_observability.py's telemetry gate)."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net = _tiny_net()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=None)
    X, Y = mx.nd.ones((8, 8)), mx.nd.zeros((8,))

    def one():
        with autograd.record():
            l = loss_fn(net(X), Y)
        l.backward()
        tr.step(8)
        return l

    one()
    engine.wait(one().data)  # warm: compile fwd/bwd/update
    c0 = obs.XLA_DISPATCH_TOTAL.total()
    engine.wait(one().data)
    per_step = obs.XLA_DISPATCH_TOTAL.total() - c0  # steady-state cost

    wd.set_enabled(True)
    wd.reset()
    fed.start(interval=0.02)  # aggressive cadence: force real traffic
    try:
        time.sleep(0.05)
        N = 20
        c0 = obs.XLA_DISPATCH_TOTAL.total()
        l = None
        for _ in range(N):
            l = one()
        engine.wait(l.data)
        delta = obs.XLA_DISPATCH_TOTAL.total() - c0
    finally:
        fed.stop()
        wd.set_enabled(False)
    assert delta == per_step * N, (delta, per_step, N)
    assert obs.FEDERATION_PUBLISH_TOTAL.total() >= 1
