"""RecordIO / IO / image pipeline tests (incl. the C++ native path)."""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.test_utils import assert_almost_equal


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"x" * 1000, b"", b"abcd" * 7]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None  # EOF
    r.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "test.rec")
    idx = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    for i in range(10):
        w.write_idx(i, f"record{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"record7"
    assert r.read_idx(2) == b"record2"
    r.close()


def test_irheader_pack_unpack():
    hdr = recordio.IRHeader(0, 3.5, 42, 0)
    s = recordio.pack(hdr, b"payload")
    hdr2, payload = recordio.unpack(s)
    assert hdr2.label == pytest.approx(3.5)
    assert hdr2.id == 42
    assert payload == b"payload"
    # multi-label
    hdr = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], np.float32), 7, 0)
    s = recordio.pack(hdr, b"xyz")
    hdr2, payload = recordio.unpack(s)
    assert hdr2.flag == 3
    np.testing.assert_allclose(hdr2.label, [1, 2, 3])
    assert payload == b"xyz"


def test_native_recordio_compat(tmp_path):
    """The C++ reader parses packs written by the Python writer."""
    from mxnet_tpu import _native

    if not _native.available():
        pytest.skip("native lib unavailable")
    import ctypes

    path = str(tmp_path / "n.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"native-check-1")
    w.write(b"second record longer payload")
    w.close()
    lib = _native.get_lib()
    h = ctypes.c_void_p()
    assert lib.MXTPURecordIOOpen(path.encode(), 0, ctypes.byref(h)) == 0
    ptr = ctypes.POINTER(ctypes.c_uint8)()
    n = lib.MXTPURecordIOReadRecord(h, ctypes.byref(ptr))
    assert bytes(bytearray(ptr[:n])) == b"native-check-1"
    n = lib.MXTPURecordIOReadRecord(h, ctypes.byref(ptr))
    assert bytes(bytearray(ptr[:n])) == b"second record longer payload"
    assert lib.MXTPURecordIOReadRecord(h, ctypes.byref(ptr)) == 0
    lib.MXTPURecordIOClose(h)


def _make_image_pack(tmp_path, n=12, hw=(40, 48)):
    from mxnet_tpu.image import imencode

    rec = str(tmp_path / "img.rec")
    idx = str(tmp_path / "img.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = (rng.rand(hw[0], hw[1], 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 3), i, 0), imencode(img)))
    w.close()
    return rec, idx


def test_image_record_iter_native(tmp_path):
    from mxnet_tpu import _native

    if not _native.available():
        pytest.skip("native lib unavailable")
    rec, idx = _make_image_pack(tmp_path)
    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                               batch_size=4, shuffle=False,
                               preprocess_threads=2)
    total = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3, 32, 32)
        total += 4 - (batch.pad or 0)
    assert total == 12
    it.reset()
    b = next(iter(it))
    assert b.data[0].shape == (4, 3, 32, 32)


def test_ndarray_iter():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    label = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=3, shuffle=False,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    assert batches[-1].pad == 2
    # discard mode
    it2 = mx.io.NDArrayIter(data, label, batch_size=3,
                            last_batch_handle="discard")
    assert len(list(it2)) == 3


def test_resize_iter():
    data = np.random.rand(10, 4).astype(np.float32)
    base = mx.io.NDArrayIter(data, np.zeros(10), batch_size=5)
    r = mx.io.ResizeIter(base, 7)
    assert len(list(r)) == 7


def test_csv_iter(tmp_path):
    f = str(tmp_path / "d.csv")
    np.savetxt(f, np.random.rand(9, 4), delimiter=",")
    it = mx.io.CSVIter(data_csv=f, data_shape=(4,), batch_size=3)
    batches = list(it)
    assert batches[0].data[0].shape == (3, 4)


def test_prefetching_iter():
    data = np.random.rand(12, 4).astype(np.float32)
    base = mx.io.NDArrayIter(data, np.zeros(12), batch_size=4)
    pf = mx.io.PrefetchingIter(base)
    assert len(list(pf)) == 3
    pf.reset()
    assert len(list(pf)) == 3


def test_native_image_decode_matches_pil():
    from mxnet_tpu import _native
    from mxnet_tpu.image import imdecode, imencode

    if not _native.available():
        pytest.skip("native lib unavailable")
    img = (np.random.RandomState(1).rand(24, 30, 3) * 255).astype(np.uint8)
    buf = imencode(img)
    nat = _native.decode_image(buf)
    pil = imdecode(buf).asnumpy()
    assert np.abs(nat.astype(int) - pil.astype(int)).max() == 0


def test_image_ops(tmp_path):
    from mxnet_tpu import image

    img = mx.nd.array((np.random.rand(30, 40, 3) * 255).astype(np.uint8),
                      dtype="uint8")
    resized = image.imresize(img, 20, 10)
    assert resized.shape == (10, 20, 3)
    cropped, _ = image.center_crop(img, (16, 16))
    assert cropped.shape == (16, 16, 3)
    rc, _ = image.random_crop(img, (8, 8))
    assert rc.shape == (8, 8, 3)
    short = image.resize_short(img, 20)
    assert min(short.shape[:2]) == 20
