"""Step-time attribution plane (observability.attribution) and its
consumers: the budget-decomposition invariants, the hot-path hooks
(trainer / prefetch-wait / watchdog), the zero-added-dispatch contract,
the multi-track timeline export (tools/timeline.py), and mxtpu-doctor
verdicts / --diff / --env (tools/mxtpu_doctor.py).

The plane is arithmetic over host floats the hot paths already record:
every test here drives either REAL training steps or the exact record
shapes those paths emit — no synthetic phase math that the production
code doesn't produce."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, gluon, observability as obs
from mxnet_tpu.gluon import nn
from mxnet_tpu.observability import attribution as attr
from mxnet_tpu.observability import watchdog as wd

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools import mxtpu_doctor as doctor  # noqa: E402
from tools import timeline  # noqa: E402


@pytest.fixture(autouse=True)
def _plane_state():
    """Armed telemetry + a pristine attribution plane per test."""
    obs.set_enabled(True)
    obs.reset()
    attr.set_enabled(True)
    attr.reset()
    yield
    wd.set_enabled(False)
    wd.reset()
    attr.set_enabled(True)
    attr.reset()
    obs.set_enabled(False)
    obs.reset()


def _tiny_loop(steps=6, hybridize=True, width=8):
    """A real fused Gluon train loop; returns (wall_seconds, loss)."""
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(width, activation="relu", in_units=width))
    net.add(nn.Dense(4, in_units=width))
    net.initialize(init=mx.initializer.Xavier())
    if hybridize:
        net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    X = mx.nd.array(np.random.RandomState(0).rand(4, width)
                    .astype(np.float32))
    Y = mx.nd.array(np.array([0, 1, 2, 3], dtype=np.float32))

    def one():
        with autograd.record():
            l = loss_fn(net(X), Y)
        l.backward()
        tr.step(4)
        return l

    engine.wait(one().data)  # warmup: compile fwd/bwd/update
    t0 = time.perf_counter()
    l = None
    for _ in range(steps):
        l = one()
    engine.wait(l.data)
    return time.perf_counter() - t0, l


# ---------------------------------------------------------------------------
# the budget decomposition: invariants by construction
# ---------------------------------------------------------------------------

def test_budget_sum_equals_period_and_nonnegative():
    """Every phase >= 0 and sum(phases) == period, exactly — with all
    three feeder kinds active at once (counter delta, host-timed comm,
    single-wait max)."""
    t = time.perf_counter()
    obs.DATA_PREFETCH_WAIT_SECONDS.inc(0.004)
    attr.note_input_wait(0.003)
    attr.note_input_wait(0.001)  # not the max: must not overwrite
    attr.note_comm(0.002)
    rec = attr.record_step(t, t + 0.010)
    assert rec is not None
    for ph in attr.PHASES:
        assert rec[ph] >= 0.0, rec
    assert sum(rec[ph] for ph in attr.PHASES) == \
        pytest.approx(rec["period_s"], rel=1e-9)
    # first record after reset: period is the dispatch span alone
    assert rec["period_s"] == pytest.approx(0.010, rel=1e-6)
    assert rec["input_wait"] == pytest.approx(0.004, rel=1e-6)
    assert rec["comm_exposed"] == pytest.approx(0.002, rel=1e-6)
    assert rec["compute"] == pytest.approx(0.004, rel=1e-6)
    assert rec["input_wait_max_s"] == pytest.approx(0.003, rel=1e-6)


def test_budget_caps_oversized_feeders():
    """A feeder backlog larger than the period cannot push any phase
    negative or the sum past the period (the cap order is the budget
    contract)."""
    t = time.perf_counter()
    obs.DATA_PREFETCH_WAIT_SECONDS.inc(10.0)  # absurd backlog
    attr.note_comm(5.0)
    rec = attr.record_step(t, t + 0.002)
    assert rec["input_wait"] == pytest.approx(0.002, rel=1e-6)
    for ph in ("h2d", "ckpt_overhead", "comm_exposed", "compute",
               "host_gap"):
        assert rec[ph] == 0.0, rec
    assert sum(rec[ph] for ph in attr.PHASES) == \
        pytest.approx(rec["period_s"], rel=1e-9)


def test_superstep_amortizes_per_k():
    """A K-step dispatch publishes per-step amortized phases: the
    per-step sum times K recovers the whole period."""
    t = time.perf_counter()
    rec = attr.record_step(t, t + 0.008, k=4, site="superstep")
    assert rec["k"] == 4
    assert sum(rec[ph] for ph in attr.PHASES) * 4 == \
        pytest.approx(rec["period_s"], rel=1e-9)
    assert rec["compute"] == pytest.approx(0.002, rel=1e-6)


def test_real_loop_phases_sum_bounded_by_wall():
    """Real fused loop: every record's phases sum to its period, and
    the periods together never exceed the measured outer wall (the
    acceptance-criteria inequality, on real records)."""
    attr.reset()
    t_begin = time.perf_counter()  # outer wall covers EVERY record's
    _tiny_loop(steps=6)            # period (warmup included)
    wall = time.perf_counter() - t_begin
    recs = [r for r in attr.records() if r["site"] == "trainer"]
    assert len(recs) >= 6, recs
    for r in recs:
        assert all(r[ph] >= 0.0 for ph in attr.PHASES), r
        assert sum(r[ph] for ph in attr.PHASES) * r["k"] == \
            pytest.approx(r["period_s"], rel=1e-9)
    assert sum(r["period_s"] for r in recs) <= wall * 1.001, \
        (sum(r["period_s"] for r in recs), wall)
    mean = attr.mean_phases(site="trainer", last_n=6)
    assert mean["count"] == 6
    assert mean["step_wall"] > 0


def test_series_gauge_and_trace_span_publish():
    """Each record lands in the lazy last-N series gauge and as a
    ``step.phases`` trace span with per-phase ms args."""
    t = time.perf_counter()
    attr.record_step(t, t + 0.004)
    attr.record_step(t + 0.004, t + 0.009)
    series = obs.STEP_PHASE_LAST.series(phase="compute")
    assert isinstance(series, list) and len(series) == 2, series
    assert series[-1] == pytest.approx(0.005, rel=1e-6)
    spans = [e for e in obs.tracer().events()
             if e.get("name") == "step.phases"]
    assert len(spans) >= 2
    args = spans[-1]["args"]
    assert args["site"] == "trainer"
    assert set(f"{ph}_ms" for ph in attr.PHASES) <= set(args), args
    assert args["period_ms"] == pytest.approx(5.0, rel=1e-4)


def test_disarmed_plane_records_nothing():
    """MXTPU_ATTRIBUTION=0 semantics: hot sites skip the plane
    entirely (records stay empty through a real loop)."""
    attr.set_enabled(False)
    attr.reset()
    _tiny_loop(steps=3)
    assert attr.records() == []


# ---------------------------------------------------------------------------
# hot-path hooks: prefetch wait delta series + watchdog detector
# ---------------------------------------------------------------------------

def test_prefetch_wait_delta_series():
    """The per-step DELTA gauge (satellite of the PR-4 running total)
    tracks each boundary's increment, not the cumulative value."""
    t = time.perf_counter()
    obs.DATA_PREFETCH_WAIT_SECONDS.inc(0.004)
    attr.record_step(t, t + 0.010)
    assert obs.DATA_PREFETCH_WAIT_DELTA.value() == \
        pytest.approx(0.004, rel=1e-6)
    obs.DATA_PREFETCH_WAIT_SECONDS.inc(0.001)
    attr.record_step(t + 0.010, t + 0.020)
    assert obs.DATA_PREFETCH_WAIT_DELTA.value() == \
        pytest.approx(0.001, rel=1e-6)


def test_watchdog_input_wait_detector_fires_once():
    """input_wait >= half the step period -> one anomaly per NEW
    record; re-sweeping the same record must not re-fire."""
    wd.reset()
    wd.set_enabled(True)
    t = time.perf_counter()
    obs.DATA_PREFETCH_WAIT_SECONDS.inc(0.008)
    obs.tracer().mark_step()
    attr.record_step(t, t + 0.010)
    wd.check_now()
    assert obs.ANOMALY_TOTAL.value(kind="input_wait") == 1
    wd.check_now()  # same record: stale, no re-fire
    assert obs.ANOMALY_TOTAL.value(kind="input_wait") == 1
    obs.DATA_PREFETCH_WAIT_SECONDS.inc(0.009)
    obs.tracer().mark_step()
    attr.record_step(t + 0.010, t + 0.020)
    wd.check_now()
    assert obs.ANOMALY_TOTAL.value(kind="input_wait") == 2


def test_watchdog_input_wait_ignores_healthy_steps():
    """A small wait fraction (below half the period) never fires."""
    wd.reset()
    wd.set_enabled(True)
    t = time.perf_counter()
    obs.DATA_PREFETCH_WAIT_SECONDS.inc(0.0005)
    obs.tracer().mark_step()
    attr.record_step(t, t + 0.010)
    wd.check_now()
    assert obs.ANOMALY_TOTAL.value(kind="input_wait") == 0


def test_flight_bundle_carries_phase_records():
    """The crash bundle ships the last-N phase records (post-mortem
    'where did the step time go' without a live process)."""
    from mxnet_tpu.observability import flight

    t = time.perf_counter()
    attr.record_step(t, t + 0.004)
    bundle = flight.build_bundle("test")
    assert bundle["phase_records"], bundle.keys()
    rec = bundle["phase_records"][-1]
    assert set(attr.PHASES) <= set(rec), rec


# ---------------------------------------------------------------------------
# the zero-added-dispatch contract (armed plane == free, in dispatches)
# ---------------------------------------------------------------------------

def test_zero_added_device_dispatches_when_armed():
    """The armed attribution plane adds ZERO XLA dispatches per step:
    the same fused loop costs the same dispatch count with the plane
    on and off (host arithmetic only — the tentpole's hard contract)."""
    _tiny_loop(steps=2)  # settle compilation before counting

    d0 = obs.XLA_DISPATCH_TOTAL.total()
    _tiny_loop(steps=5)
    armed = obs.XLA_DISPATCH_TOTAL.total() - d0

    attr.set_enabled(False)
    d0 = obs.XLA_DISPATCH_TOTAL.total()
    _tiny_loop(steps=5)
    disarmed = obs.XLA_DISPATCH_TOTAL.total() - d0
    assert armed == disarmed, (armed, disarmed)


# ---------------------------------------------------------------------------
# mxtpu-doctor: verdict fixtures per bottleneck class
# ---------------------------------------------------------------------------

def _phase_event(site="trainer", k=1, step=1, **phase_ms):
    """One ``step.phases`` span exactly as attribution emits it (args
    are per-step amortized; period covers the whole K-step dispatch)."""
    ms = {f"{ph}_ms": 0.0 for ph in attr.PHASES}
    ms.update({f"{key}_ms": val for key, val in phase_ms.items()})
    period = sum(ms.values()) * k
    return {"name": "step.phases", "cat": "attribution", "ph": "X",
            "ts": 0.0, "dur": period * 1e3, "pid": 1, "tid": 1,
            "args": {"site": site, "k": k, "step": step,
                     "period_ms": period, "dispatch_ms": period, **ms}}


def _cost_event(site="trainer_fused", ai=2.0):
    return {"name": "introspect.cost", "cat": "introspect", "ph": "i",
            "ts": 0.0, "pid": 1, "tid": 1,
            "args": {"site": site, "arith_intensity": ai,
                     "peak_tflops": 197.0, "peak_hbm_gbs": 819.0}}


def _serve_event(model="m", **phase_ms):
    args = {"model": model, "req": 1}
    args.update({f"{key}_ms": val for key, val in phase_ms.items()})
    return {"name": "serving.request", "cat": "serving", "ph": "X",
            "ts": 0.0, "dur": 1.0, "pid": 1, "tid": 2, "args": args}


def _verdict(events, site):
    report = doctor.diagnose(events)
    for v in report["training"]:
        if v["site"] == site:
            return v
    raise AssertionError((site, report))


def test_doctor_input_bound_verdict():
    events = [_phase_event(input_wait=4.0, compute=4.0, host_gap=0.5,
                           step=i) for i in range(5)]
    v = _verdict(events, "trainer")
    assert v["verdict"] == "input_bound"
    assert any("input_wait" in e for e in v["evidence"]), v
    assert "MXTPU_DEVICE_PREFETCH" in v["recipe"]


def test_doctor_comm_bound_verdict():
    events = [_phase_event(site="spmd_staged", comm_exposed=3.0,
                           compute=6.0, host_gap=1.0, step=i)
              for i in range(5)]
    v = _verdict(events, "spmd_staged")
    assert v["verdict"] == "comm_bound"
    assert "MXTPU_OVERLAP" in v["recipe"]


def test_doctor_host_bound_verdict():
    events = [_phase_event(host_gap=5.0, compute=3.0, step=i)
              for i in range(5)]
    v = _verdict(events, "trainer")
    assert v["verdict"] == "host_bound"
    assert "MXTPU_SUPERSTEP_K" in v["recipe"]


def test_doctor_roofline_split_memory_vs_flops():
    """Compute-dominated sites split at the roofline ridge when a cost
    record is present, and default to flops-bound (with an explicit
    evidence line) when it is not."""
    compute = [_phase_event(compute=9.0, host_gap=1.0, step=i)
               for i in range(4)]
    v = _verdict(compute + [_cost_event(ai=2.0)], "trainer")
    assert v["verdict"] == "compute_memory_bound", v
    v = _verdict(compute + [_cost_event(ai=500.0)], "trainer")
    assert v["verdict"] == "compute_flops_bound", v
    v = _verdict(compute, "trainer")  # no cost analysis in the dump
    assert v["verdict"] == "compute_flops_bound"
    assert any("no cost-analysis" in e for e in v["evidence"]), v


def test_doctor_serving_verdicts():
    queuey = [_serve_event(queue=6.0, batch=2.0, dispatch=1.0,
                           slice=0.2) for _ in range(4)]
    report = doctor.diagnose(queuey)
    assert report["serving"][0]["verdict"] == "serving_queue_bound"
    dispatchy = [_serve_event(queue=0.5, batch=0.2, dispatch=7.0,
                              slice=0.2) for _ in range(4)]
    report = doctor.diagnose(dispatchy)
    assert report["serving"][0]["verdict"] == "compute_flops_bound"


def test_doctor_ranks_unhealthy_first():
    """The top verdict is the dominant bottleneck, not whichever site
    sorts first alphabetically."""
    events = [_phase_event(site="a_healthy", compute=9.7, host_gap=0.1,
                           input_wait=0.1, step=i) for i in range(4)]
    events += [_cost_event(site="a_healthy", ai=500.0)]
    events += [_phase_event(site="z_starved", input_wait=8.0,
                            compute=2.0, step=i) for i in range(4)]
    report = doctor.diagnose(events)
    assert report["top"]["site"] == "z_starved"
    assert report["top"]["verdict"] == "input_bound"


def _pipeline_event(schedule="gpipe", bubble=0.3, ticks=22, stash=8):
    """One ``pipeline.schedule`` instant as record_pipeline_schedule
    emits it at step-build time."""
    return {"name": "pipeline.schedule", "cat": "parallel", "ph": "i",
            "ts": 0.0, "pid": 1, "tid": 1,
            "args": {"schedule": schedule, "bubble_fraction": bubble,
                     "ticks": ticks, "stash_slots": stash}}


def test_doctor_pipeline_bubble_bound_verdict():
    """A fat measured bubble joined with compute-dominated phase spans
    yields pipeline_bubble_bound: the host books schedule idle as
    device compute, so the roofline verdict alone would mislead."""
    events = [_phase_event(compute=9.0, host_gap=1.0, step=i)
              for i in range(4)]
    events += [_pipeline_event(schedule="gpipe", bubble=0.273)]
    report = doctor.diagnose(events)
    assert report["pipeline"], report
    v = report["pipeline"][0]
    assert v["verdict"] == "pipeline_bubble_bound"
    assert v["schedule"] == "gpipe"
    assert abs(v["bubble_fraction"] - 0.273) < 1e-9
    # the join: evidence names the compute-dominated site's share
    assert any("compute-bound" in e for e in v["evidence"]), v["evidence"]
    assert "MXTPU_PIPELINE" in v["recipe"]
    # phase-bound verdicts outrank it; with only compute-flops sites
    # in the trace, the bubble is the actionable top verdict
    assert report["top"]["verdict"] == "pipeline_bubble_bound"
    rendered = doctor.render(report)
    assert "pipeline_bubble_bound" in rendered


def test_doctor_pipeline_bubble_below_threshold_silent():
    """A tuned interleaved schedule (bubble under the bound) emits no
    pipeline verdict, and an input-bound site still wins top."""
    events = [_phase_event(input_wait=8.0, compute=2.0, step=i)
              for i in range(4)]
    events += [_pipeline_event(schedule="interleaved", bubble=0.059,
                               ticks=34, stash=4)]
    report = doctor.diagnose(events)
    assert report["pipeline"] == []
    assert report["top"]["verdict"] == "input_bound"
    # over threshold but a starved input pipeline still outranks it
    report2 = doctor.diagnose(events + [_pipeline_event(bubble=0.4)])
    assert report2["pipeline"]
    assert report2["top"]["verdict"] == "input_bound"


def test_doctor_cli_seeded_scenarios(tmp_path):
    """The acceptance pair, end-to-end through the REAL plumbing: an
    input-starved loop and a staged-comm loop, recorded by attribution
    itself, dumped to JSONL, diagnosed by the CLI."""
    base = time.perf_counter()
    for i in range(8):  # starved: waits dominate each 10 ms period
        obs.DATA_PREFETCH_WAIT_SECONDS.inc(0.006)
        attr.record_step(base + i * 0.010, base + i * 0.010 + 0.004)
    attr.reset()  # scenario boundary (bench does the same): the idle
    # gap between the two loops must not attribute as a giant host_gap
    for i in range(8):  # staged comm: the host-timed comm leg dominates
        attr.note_comm(0.005)
        attr.record_step(base + 1 + i * 0.010,
                         base + 1 + i * 0.010 + 0.008,
                         site="spmd_staged")
    trace = tmp_path / "trace.jsonl"
    obs.dump_jsonl(str(trace))
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "mxtpu_doctor.py"),
         "--json", str(trace)],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    report = json.loads(res.stdout)
    verdicts = {v["site"]: v["verdict"] for v in report["training"]}
    assert verdicts["trainer"] == "input_bound", report
    assert verdicts["spmd_staged"] == "comm_bound", report
    # human rendering also resolves (no --json)
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "mxtpu_doctor.py"),
         str(trace)],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    assert "input_bound" in res.stdout and "comm_bound" in res.stdout


# ---------------------------------------------------------------------------
# mxtpu-doctor --diff: which phase moved
# ---------------------------------------------------------------------------

def _bench_artifact(path, sps, input_ms):
    path.write_text(json.dumps({
        "scenario": "train_step", "steps_per_sec": sps,
        "_phases": {"fused": {"input_wait_ms": input_ms, "h2d_ms": 0.0,
                              "ckpt_overhead_ms": 0.0,
                              "comm_exposed_ms": 0.0, "compute_ms": 5.0,
                              "host_gap_ms": 0.5}}}))


def test_doctor_diff_pinpoints_slowed_phase(tmp_path):
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    _bench_artifact(a, 100.0, 0.1)
    _bench_artifact(b, 60.0, 4.1)  # synthetically starve the input side
    pd = doctor.phase_diff(str(a), str(b))
    assert pd["dominant"]["phase"] == "input_wait", pd
    assert pd["dominant"]["delta_ms"] == pytest.approx(4.0)
    assert pd["dominant"]["share"] == pytest.approx(1.0)
    line = doctor.phase_diff_one_liner(str(a), str(b))
    assert "input_wait" in line and "slower" in line, line
    # and the bench_diff gate prints that line on its failure path
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "bench_diff.py"),
         str(b), str(a)],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 1, (res.stdout, res.stderr)
    assert "mxtpu-doctor --diff: 'input_wait'" in res.stdout, res.stdout


def test_doctor_diff_silent_without_phase_stamps(tmp_path):
    """Artifacts without phase fields: the one-liner degrades to empty
    (bench_diff must not print a bogus attribution)."""
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"steps_per_sec": 100.0}))
    b.write_text(json.dumps({"steps_per_sec": 50.0}))
    assert doctor.phase_diff_one_liner(str(a), str(b)) == ""


# ---------------------------------------------------------------------------
# mxtpu-doctor --env (the ported legacy diagnose tool)
# ---------------------------------------------------------------------------

def test_doctor_env_report():
    report = doctor.env_report()
    assert report["format"] == "mxtpu-doctor-env-v1"
    assert report["jax"]["backend"]
    assert report["mxnet_tpu"]["ops"] > 400
    assert isinstance(report["warnings"], list)
    text = doctor.render_env(report)
    assert "mxtpu-doctor --env:" in text and "jax" in text


# ---------------------------------------------------------------------------
# tools/timeline.py: valid multi-track chrome://tracing export
# ---------------------------------------------------------------------------

def _timeline_fixture():
    return [
        _phase_event(input_wait=2.0, compute=3.0, host_gap=1.0, k=2),
        {"name": "serving.batch", "cat": "serving", "ph": "X", "ts": 50.0,
         "dur": 30.0, "pid": 9, "tid": 9, "id": 7, "args": {}},
        {"name": "serving.request", "cat": "serving", "ph": "X",
         "ts": 60.0, "dur": 10.0, "pid": 9, "tid": 10,
         "args": {"model": "m", "parent": 7}},
        {"name": "anomaly", "cat": "watchdog", "ph": "i", "ts": 70.0,
         "args": {"kind": "input_wait"}},
    ]


def test_timeline_is_valid_chrome_trace():
    doc = timeline.build_timeline(_timeline_fixture())
    text = json.dumps(doc)  # must serialize round-trip
    doc2 = json.loads(text)
    evs = doc2["traceEvents"]
    assert isinstance(evs, list) and evs
    for ev in evs:
        assert "ph" in ev and "pid" in ev, ev
        if ev["ph"] in ("X", "i", "s", "f"):
            assert isinstance(ev["ts"], (int, float)), ev
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"train loop", "attribution", "prefetcher", "collectives",
            "checkpoint writer", "serving batcher"} <= names, names


def test_timeline_expands_phase_slices_and_flows():
    doc = timeline.build_timeline(_timeline_fixture())
    evs = doc["traceEvents"]
    slices = [e for e in evs if e.get("cat") == "attribution.phase"]
    got = {e["name"]: e["dur"] for e in slices}
    # per-step amortized args * k=2 lay the slices across the period
    assert got["input_wait"] == pytest.approx(2.0 * 1e3 * 2)
    assert got["compute"] == pytest.approx(3.0 * 1e3 * 2)
    assert "host_gap" in got and "h2d" not in got  # zero phases skipped
    span_dur = [e for e in evs if e.get("name") == "step.phases"][0]["dur"]
    assert sum(got.values()) == pytest.approx(span_dur, rel=1e-6)
    flows = [e for e in evs if e.get("cat") == "correlation"]
    assert {e["ph"] for e in flows} == {"s", "f"}, flows
    # instants carry a scope, not a duration
    inst = [e for e in evs if e.get("name") == "anomaly"][0]
    assert inst["s"] == "t" and "dur" not in inst


def test_timeline_cli_roundtrip(tmp_path):
    trace = tmp_path / "trace.jsonl"
    with open(trace, "w") as f:
        for ev in _timeline_fixture():
            f.write(json.dumps(ev) + "\n")
    out = tmp_path / "out.json"
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "timeline.py"),
         str(trace), "-o", str(out)],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    doc = json.load(open(out))
    assert doc["traceEvents"], doc
    # the tool also reads its own output (chrome-trace shaped input)
    assert timeline.load_events(str(out))
