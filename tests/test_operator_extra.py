"""Round-2 operator additions: la_op linalg family, tensor/math extras,
vision/sampling ops, detection pipeline.

Reference model: tests/python/unittest/test_operator.py (forward vs numpy
+ check_numeric_gradient central differences).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient

nd = mx.nd


def _spd(n, batch=()):
    a = np.random.rand(*batch, n, n).astype(np.float32)
    return a @ np.swapaxes(a, -1, -2) + 3 * np.eye(n, dtype=np.float32)


# ---------------------------------------------------------------------------
# linalg la_op family
# ---------------------------------------------------------------------------


def test_linalg_trsm_trmm():
    spd = _spd(4, (2,))
    L = np.linalg.cholesky(spd)
    B = np.random.rand(2, 4, 3).astype(np.float32)
    X = nd.linalg_trsm(nd.array(L), nd.array(B), alpha=2.0).asnumpy()
    np.testing.assert_allclose(L @ X, 2.0 * B, rtol=1e-4, atol=1e-4)
    Y = nd.linalg_trmm(nd.array(L), nd.array(B)).asnumpy()
    np.testing.assert_allclose(Y, np.tril(L) @ B, rtol=1e-5, atol=1e-5)
    # rightside B (2, 3, 4): X A = B
    B2 = np.random.rand(2, 3, 4).astype(np.float32)
    X2 = nd.linalg_trsm(nd.array(L), nd.array(B2), rightside=True).asnumpy()
    np.testing.assert_allclose(X2 @ L, B2, rtol=1e-4, atol=1e-4)


def test_linalg_potri():
    spd = _spd(5)
    L = np.linalg.cholesky(spd)
    inv = nd.linalg_potri(nd.array(L)).asnumpy()
    np.testing.assert_allclose(inv @ spd, np.eye(5), rtol=1e-3, atol=1e-3)


def test_linalg_diag_trian_roundtrip():
    a = np.random.rand(2, 4, 4).astype(np.float32)
    d = nd.linalg_extractdiag(nd.array(a)).asnumpy()
    np.testing.assert_allclose(d, np.diagonal(a, axis1=-2, axis2=-1))
    m = nd.linalg_makediag(nd.array(d)).asnumpy()
    np.testing.assert_allclose(np.diagonal(m, axis1=-2, axis2=-1), d)
    tri = nd.linalg_extracttrian(nd.array(a)).asnumpy()
    assert tri.shape == (2, 10)
    back = nd.linalg_maketrian(nd.array(tri)).asnumpy()
    np.testing.assert_allclose(np.tril(a), back, rtol=1e-6)
    s = nd.linalg_sumlogdiag(nd.array(_spd(4, (2,)))).asnumpy()
    assert s.shape == (2,)


def test_linalg_syevd_inverse_det():
    spd = _spd(4)
    U, L = nd.linalg_syevd(nd.array(spd))
    U, L = U.asnumpy(), L.asnumpy()
    np.testing.assert_allclose(U.T @ np.diag(L) @ U, spd, rtol=1e-3, atol=1e-3)
    inv = nd.linalg_inverse(nd.array(spd)).asnumpy()
    np.testing.assert_allclose(inv, np.linalg.inv(spd), rtol=1e-3, atol=1e-3)
    det = float(nd.linalg_det(nd.array(spd)).asnumpy())
    np.testing.assert_allclose(det, np.linalg.det(spd), rtol=1e-3)
    sign, logdet = nd.linalg_slogdet(nd.array(spd))
    np.testing.assert_allclose(float(sign.asnumpy()) * np.exp(float(logdet.asnumpy())),
                               np.linalg.det(spd), rtol=1e-3)


def test_linalg_gelqf_svd_solve():
    a = np.random.rand(3, 5).astype(np.float32)
    Lm, Q = nd.linalg_gelqf(nd.array(a))
    Lm, Q = Lm.asnumpy(), Q.asnumpy()
    np.testing.assert_allclose(Lm @ Q, a, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(Q @ Q.T, np.eye(3), rtol=1e-4, atol=1e-4)
    u, s, vt = nd.linalg_svd(nd.array(a))
    np.testing.assert_allclose(
        u.asnumpy() @ np.diag(s.asnumpy()) @ vt.asnumpy(), a,
        rtol=1e-4, atol=1e-4)
    spd = _spd(4)
    b = np.random.rand(4, 2).astype(np.float32)
    x = nd.linalg_solve(nd.array(spd), nd.array(b)).asnumpy()
    np.testing.assert_allclose(spd @ x, b, rtol=1e-3, atol=1e-3)


def test_linalg_gradients():
    spd = _spd(3)
    check_numeric_gradient(lambda x: nd.linalg_sumlogdiag(x), [spd],
                           rtol=1e-2, atol=1e-3)
    L = np.linalg.cholesky(spd)
    B = np.random.rand(3, 2).astype(np.float32)
    check_numeric_gradient(lambda a, b: nd.linalg_trsm(a, b).sum(),
                           [L, B], rtol=2e-2, atol=1e-3)


# ---------------------------------------------------------------------------
# histogram / unique / searchsorted
# ---------------------------------------------------------------------------


def test_histogram():
    x = np.random.rand(100).astype(np.float32)
    cnt, edges = nd.histogram(nd.array(x), bin_cnt=8, range=(0.0, 1.0))
    ref_cnt, ref_edges = np.histogram(x, bins=8, range=(0.0, 1.0))
    np.testing.assert_allclose(cnt.asnumpy(), ref_cnt)
    np.testing.assert_allclose(edges.asnumpy(), ref_edges, rtol=1e-6)
    be = np.array([0.0, 0.25, 0.5, 1.0], np.float32)
    cnt2, _ = nd.histogram(nd.array(x), nd.array(be))
    ref2, _ = np.histogram(x, bins=be)
    np.testing.assert_allclose(cnt2.asnumpy(), ref2)


def test_unique_bincount_searchsorted():
    x = np.array([3, 1, 3, 2, 1, 7], np.float32)
    np.testing.assert_allclose(nd.unique(nd.array(x)).asnumpy(), [1, 2, 3, 7])
    b = nd.bincount(nd.array(np.array([0, 1, 1, 3], np.float32))).asnumpy()
    np.testing.assert_allclose(b, [1, 2, 0, 1])
    ss = nd.searchsorted(nd.array(np.array([1.0, 2, 3], np.float32)),
                         nd.array(np.array([2.5], np.float32))).asnumpy()
    assert ss[0] == 2


# ---------------------------------------------------------------------------
# layout / structure ops
# ---------------------------------------------------------------------------


def test_tril_triu_trace():
    x = np.random.rand(4, 4).astype(np.float32)
    np.testing.assert_allclose(nd.tril(nd.array(x), k=-1).asnumpy(),
                               np.tril(x, -1))
    np.testing.assert_allclose(nd.triu(nd.array(x), k=1).asnumpy(),
                               np.triu(x, 1))
    np.testing.assert_allclose(float(nd.trace(nd.array(x)).asnumpy()),
                               np.trace(x), rtol=1e-6)


def test_roll_moveaxis_rot90():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    np.testing.assert_allclose(nd.roll(nd.array(x), shift=2, axis=2).asnumpy(),
                               np.roll(x, 2, 2))
    np.testing.assert_allclose(
        nd.moveaxis(nd.array(x), source=0, destination=2).asnumpy(),
        np.moveaxis(x, 0, 2))
    np.testing.assert_allclose(nd.rot90(nd.array(x), k=1, axes=(1, 2)).asnumpy(),
                               np.rot90(x, 1, (1, 2)))


def test_depth_space_roundtrip():
    x = np.random.rand(2, 8, 4, 6).astype(np.float32)
    d = nd.space_to_depth(nd.array(x), block_size=2)
    assert d.shape == (2, 32, 2, 3)
    back = nd.depth_to_space(d, block_size=2).asnumpy()
    np.testing.assert_allclose(back, x)


def test_ravel_unravel():
    shape = (3, 4, 5)
    flat = np.array([0, 17, 59], np.float32)
    multi = nd.unravel_index(nd.array(flat), shape=shape).asnumpy()
    ref = np.stack(np.unravel_index(flat.astype(np.int64), shape))
    np.testing.assert_allclose(multi, ref)
    back = nd.ravel_multi_index(nd.array(multi.astype(np.float32)),
                                shape=shape).asnumpy()
    np.testing.assert_allclose(back, flat)


# ---------------------------------------------------------------------------
# reductions & special functions
# ---------------------------------------------------------------------------


def test_reduction_extras():
    x = np.random.rand(3, 4, 5).astype(np.float32)
    a = nd.array(x)
    np.testing.assert_allclose(nd.logsumexp(a, axis=1).asnumpy(),
                               np.log(np.exp(x).sum(1)), rtol=1e-5)
    np.testing.assert_allclose(nd.std(a, axis=0).asnumpy(), x.std(0), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(nd.var(a, axis=2).asnumpy(), x.var(2), rtol=1e-4,
                               atol=1e-6)
    m, v = nd.moments(a, axes=(0, 2))
    np.testing.assert_allclose(m.asnumpy(), x.mean((0, 2)), rtol=1e-5)
    np.testing.assert_allclose(v.asnumpy(), x.var((0, 2)), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(float(nd.median(a).asnumpy()), np.median(x),
                               rtol=1e-5)
    np.testing.assert_allclose(float(nd.ptp(a).asnumpy()), np.ptp(x), rtol=1e-5)


def test_special_and_binary():
    x = np.random.uniform(0.1, 3.0, (3, 4)).astype(np.float32)
    y = np.random.uniform(0.1, 3.0, (3, 4)).astype(np.float32)
    # erfc(x) = 1 - erf(x)
    np.testing.assert_allclose(nd.erfc(nd.array(x)).asnumpy(),
                               1.0 - nd.erf(nd.array(x)).asnumpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(nd.logaddexp(nd.array(x), nd.array(y)).asnumpy(),
                               np.logaddexp(x, y), rtol=1e-5)
    np.testing.assert_allclose(nd.copysign(nd.array(x), nd.array(-y)).asnumpy(),
                               np.copysign(x, -y))
    np.testing.assert_allclose(nd.fmod(nd.array(x), nd.array(y)).asnumpy(),
                               np.fmod(x, y), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        nd.squared_difference(nd.array(x), nd.array(y)).asnumpy(),
        (x - y) ** 2, rtol=1e-5)
    ints = np.array([[5, 3], [12, 10]], np.float32)
    np.testing.assert_allclose(
        nd.bitwise_and(nd.array(ints), nd.array(ints * 0 + 6)).asnumpy(),
        np.bitwise_and(ints.astype(np.int32), 6))


def test_products():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4, 5).astype(np.float32)
    np.testing.assert_allclose(nd.tensordot(nd.array(a), nd.array(b), axes=1).asnumpy(),
                               np.tensordot(a, b, 1), rtol=1e-5)
    np.testing.assert_allclose(
        nd.einsum(nd.array(a), nd.array(b), subscripts="ij,jk->ik").asnumpy(),
        a @ b, rtol=1e-5)
    np.testing.assert_allclose(nd.kron(nd.array(a), nd.array(b)).asnumpy(),
                               np.kron(a, b), rtol=1e-5)
    v1 = np.random.rand(3).astype(np.float32)
    v2 = np.random.rand(3).astype(np.float32)
    np.testing.assert_allclose(nd.cross(nd.array(v1), nd.array(v2)).asnumpy(),
                               np.cross(v1, v2), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(nd.outer(nd.array(v1), nd.array(v2)).asnumpy(),
                               np.outer(v1, v2), rtol=1e-6)


def test_cumulative():
    x = np.random.rand(3, 4).astype(np.float32)
    np.testing.assert_allclose(nd.cumprod(nd.array(x), axis=1).asnumpy(),
                               np.cumprod(x, 1), rtol=1e-5)
    np.testing.assert_allclose(nd.cummax(nd.array(x), axis=0).asnumpy(),
                               np.maximum.accumulate(x, 0))
    np.testing.assert_allclose(nd.diff(nd.array(x), axis=1).asnumpy(),
                               np.diff(x, axis=1), rtol=1e-5, atol=1e-7)


def test_activation_extras():
    x = np.random.randn(3, 4).astype(np.float32)
    a = nd.array(x)
    np.testing.assert_allclose(nd.elu(a).asnumpy(),
                               np.where(x > 0, x, np.expm1(x)), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(nd.silu(a).asnumpy(), x / (1 + np.exp(-x)),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(nd.hard_sigmoid(a).asnumpy(),
                               np.clip(0.2 * x + 0.5, 0, 1), rtol=1e-5)
    np.testing.assert_allclose(nd.mish(a).asnumpy(),
                               x * np.tanh(np.log1p(np.exp(x))), rtol=1e-4,
                               atol=1e-5)
    g = np.full((3, 4), 0.25, np.float32)
    np.testing.assert_allclose(nd.prelu(a, nd.array(g)).asnumpy(),
                               np.where(x >= 0, x, 0.25 * x), rtol=1e-6)
    check_numeric_gradient(lambda t: nd.gelu(t), [x], rtol=2e-2, atol=1e-3)


# ---------------------------------------------------------------------------
# vision / sampling ops
# ---------------------------------------------------------------------------


def test_upsampling():
    x = np.random.rand(2, 3, 4, 4).astype(np.float32)
    up = nd.UpSampling(nd.array(x), scale=2, sample_type="nearest").asnumpy()
    assert up.shape == (2, 3, 8, 8)
    np.testing.assert_allclose(up[:, :, ::2, ::2], x)
    np.testing.assert_allclose(up[:, :, 1::2, 1::2], x)
    bi = nd.UpSampling(nd.array(x), scale=2, sample_type="bilinear",
                       num_filter=3).asnumpy()
    assert bi.shape == (2, 3, 8, 8)


def test_roi_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)
    out = nd.ROIPooling(nd.array(x), nd.array(rois), pooled_size=(2, 2),
                        spatial_scale=1.0).asnumpy()
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])


def test_grid_generator_bilinear_sampler_identity():
    x = np.random.rand(2, 3, 5, 5).astype(np.float32)
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    grid = nd.GridGenerator(nd.array(theta), transform_type="affine",
                            target_shape=(5, 5))
    out = nd.BilinearSampler(nd.array(x), grid).asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-5)
    st = nd.SpatialTransformer(nd.array(x), nd.array(theta),
                               target_shape=(5, 5)).asnumpy()
    np.testing.assert_allclose(st, x, rtol=1e-4, atol=1e-5)


def test_im2col_col2im():
    x = np.random.rand(2, 3, 6, 6).astype(np.float32)
    cols = nd.im2col(nd.array(x), kernel=(3, 3), pad=(1, 1))
    assert cols.shape == (2, 27, 36)
    back = nd.col2im(cols, input_size=(3, 6, 6), kernel=(3, 3),
                     pad=(1, 1)).asnumpy()
    # col2im is the adjoint: interior pixels are counted 9x
    assert back.shape == x.shape
    np.testing.assert_allclose(back[:, :, 2:4, 2:4], 9 * x[:, :, 2:4, 2:4],
                               rtol=1e-5)


def test_deformable_convolution_zero_offset():
    x = np.random.rand(2, 4, 8, 8).astype(np.float32)
    w = (np.random.randn(6, 4, 3, 3) * 0.1).astype(np.float32)
    off = np.zeros((2, 18, 8, 8), np.float32)
    dc = nd.DeformableConvolution(nd.array(x), nd.array(off), nd.array(w),
                                  kernel=(3, 3), pad=(1, 1), num_filter=6,
                                  no_bias=True).asnumpy()
    cv = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3), pad=(1, 1),
                        num_filter=6, no_bias=True).asnumpy()
    np.testing.assert_allclose(dc, cv, rtol=1e-4, atol=1e-5)
    # half-pixel x-shift ~ average of neighbors on a linear ramp
    ramp = np.tile(np.arange(8, dtype=np.float32), (1, 1, 8, 1))
    off2 = np.zeros((1, 18, 8, 8), np.float32)
    off2[:, 1::2] = 0.5  # x offsets
    w1 = np.zeros((1, 1, 3, 3), np.float32)
    w1[0, 0, 1, 1] = 1.0
    out = nd.DeformableConvolution(nd.array(ramp), nd.array(off2),
                                   nd.array(w1), kernel=(3, 3), pad=(1, 1),
                                   num_filter=1, no_bias=True).asnumpy()
    np.testing.assert_allclose(out[0, 0, 2, 2:5], [2.5, 3.5, 4.5], rtol=1e-5)


def test_correlation_self():
    x = np.random.rand(1, 2, 6, 6).astype(np.float32)
    out = nd.Correlation(nd.array(x), nd.array(x), kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=1).asnumpy()
    assert out.shape[1] == 9  # 3x3 displacement grid
    center = out[:, 4]  # zero displacement channel: mean over C of x*x
    np.testing.assert_allclose(center[0], (x[0] ** 2).mean(0), rtol=1e-5)


def test_regression_outputs():
    data = np.random.randn(4, 3).astype(np.float32)
    label = np.random.randn(4, 3).astype(np.float32)
    d = nd.array(data)
    d.attach_grad()
    with autograd.record():
        out = nd.LinearRegressionOutput(d, nd.array(label))
    out.backward()
    np.testing.assert_allclose(out.asnumpy(), data)
    # reference normalizes by per-sample output count (3 here), not batch
    np.testing.assert_allclose(d.grad.asnumpy(), (data - label) / 3,
                               rtol=1e-5, atol=1e-6)
    with autograd.record():
        out = nd.LogisticRegressionOutput(d, nd.array(label))
    out.backward()
    sig = 1 / (1 + np.exp(-data))
    np.testing.assert_allclose(out.asnumpy(), sig, rtol=1e-5)
    np.testing.assert_allclose(d.grad.asnumpy(), (sig - label) / 3,
                               rtol=1e-5, atol=1e-6)
    with autograd.record():
        out = nd.MAERegressionOutput(d, nd.array(label))
    out.backward()
    np.testing.assert_allclose(d.grad.asnumpy(), np.sign(data - label) / 3,
                               rtol=1e-5)


def test_svm_output():
    data = np.random.randn(4, 5).astype(np.float32)
    label = np.array([0, 2, 1, 4], np.float32)
    d = nd.array(data)
    d.attach_grad()
    with autograd.record():
        out = nd.SVMOutput(d, nd.array(label), use_linear=True)
    out.backward()
    np.testing.assert_allclose(out.asnumpy(), data)  # forward = identity
    g = d.grad.asnumpy()
    assert g.shape == data.shape
    # gradient sums to zero per row (pull toward true class, push others)
    np.testing.assert_allclose(g.sum(1), np.zeros(4), atol=1e-5)


# ---------------------------------------------------------------------------
# detection pipeline
# ---------------------------------------------------------------------------


def test_multibox_target():
    anchors = np.array([[[0.0, 0.0, 0.2, 0.2], [0.4, 0.4, 0.6, 0.6],
                         [0.7, 0.7, 0.9, 0.9]]], np.float32)
    label = np.array([[[1.0, 0.42, 0.42, 0.62, 0.62],
                       [-1, -1, -1, -1, -1]]], np.float32)
    cls_pred = np.zeros((1, 3, 3), np.float32)
    bt, bm, ct = nd.MultiBoxTarget(nd.array(anchors), nd.array(label),
                                   nd.array(cls_pred))
    np.testing.assert_allclose(ct.asnumpy(), [[0, 2, 0]])
    mask = bm.asnumpy().reshape(3, 4)
    np.testing.assert_allclose(mask[:, 0], [0, 1, 0])
    # encoded offsets for the matched anchor: gt center (0.52) vs anchor
    # center (0.5), variance 0.1 -> (0.02/0.2)/0.1 = 1.0
    tgt = bt.asnumpy().reshape(3, 4)
    np.testing.assert_allclose(tgt[1], [1.0, 1.0, 0.0, 0.0], atol=1e-4)


def test_multibox_target_padded_labels_force_match():
    """Padded (-1) label rows must not clobber a real gt's force-match:
    a weak-IoU gt whose best anchor is anchor 0 still becomes a positive."""
    anchors = np.array([[[0.0, 0.0, 0.3, 0.3], [0.5, 0.5, 0.9, 0.9]]],
                       np.float32)
    label = np.array([[[0.0, 0.0, 0.0, 0.15, 0.15],
                       [-1, -1, -1, -1, -1]]], np.float32)
    cls_pred = np.zeros((1, 2, 2), np.float32)
    _, bm, ct = nd.MultiBoxTarget(nd.array(anchors), nd.array(label),
                                  nd.array(cls_pred))
    np.testing.assert_allclose(ct.asnumpy(), [[1, 0]])
    np.testing.assert_allclose(bm.asnumpy().reshape(2, 4)[:, 0], [1, 0])


def test_multibox_target_negative_mining():
    anchors = np.random.rand(1, 20, 2).astype(np.float32)
    lo = anchors
    anchors = np.concatenate([lo, lo + 0.1], axis=-1)
    label = np.array([[[0.0, 0.05, 0.05, 0.15, 0.15]]], np.float32)
    logits = np.random.randn(1, 4, 20).astype(np.float32)
    _, _, ct = nd.MultiBoxTarget(nd.array(anchors), nd.array(label),
                                 nd.array(logits), negative_mining_ratio=3.0,
                                 negative_mining_thresh=0.0)
    vals = ct.asnumpy()
    assert ((vals == -1) | (vals >= 0)).all()
    assert (vals == -1).sum() > 0  # some anchors ignored by mining


def test_multibox_detection():
    anchors = np.array([[[0.1, 0.1, 0.3, 0.3], [0.11, 0.11, 0.31, 0.31],
                         [0.6, 0.6, 0.8, 0.8]]], np.float32)
    # C=3 (bg + 2 classes); anchors 0,1 strongly class 1; anchor 2 class 2
    cls_prob = np.array([[[0.05, 0.1, 0.2], [0.9, 0.85, 0.1],
                          [0.05, 0.05, 0.7]]], np.float32)
    loc = np.zeros((1, 12), np.float32)
    out = nd.MultiBoxDetection(nd.array(cls_prob), nd.array(loc),
                               nd.array(anchors), nms_threshold=0.5).asnumpy()
    assert out.shape == (1, 3, 6)
    rows = out[0]
    kept = rows[rows[:, 0] >= 0]
    # NMS suppressed the overlapping duplicate of class 0 (first fg class)
    assert len(kept) == 2
    assert set(kept[:, 0].tolist()) == {0.0, 1.0}
    top = rows[0]
    np.testing.assert_allclose(top[1], 0.9, rtol=1e-6)
    np.testing.assert_allclose(top[2:], [0.1, 0.1, 0.3, 0.3], atol=1e-5)


def test_proposal():
    np.random.seed(0)
    cp = np.random.rand(2, 24, 4, 4).astype(np.float32)
    bp = (np.random.randn(2, 48, 4, 4) * 0.1).astype(np.float32)
    info = np.array([[64, 64, 1.0], [64, 64, 1.0]], np.float32)
    rois = nd.Proposal(nd.array(cp), nd.array(bp), nd.array(info),
                       rpn_pre_nms_top_n=60, rpn_post_nms_top_n=8,
                       feature_stride=16).asnumpy()
    assert rois.shape == (16, 5)
    assert (rois[:8, 0] == 0).all() and (rois[8:, 0] == 1).all()
    assert (rois[:, 1] <= rois[:, 3]).all() and (rois[:, 2] <= rois[:, 4]).all()
    assert (rois[:, 1:] >= 0).all() and (rois[:, 1:] <= 63).all()


# ---------------------------------------------------------------------------
# round-3 advisor regression tests
# ---------------------------------------------------------------------------


def test_linalg_trian_roundtrip_offsets():
    """extracttrian/maketrian must round-trip for |offset| >= 2 (advisor
    round-2 finding: the size-solving loop was wrong for shrunk triangles)."""
    a = np.random.rand(2, 4, 4).astype(np.float32)
    for offset in (-2, -1, 1, 2):
        for lower in (True, False):
            tri = nd.linalg_extracttrian(nd.array(a), offset=offset,
                                         lower=lower).asnumpy()
            back = nd.linalg_maketrian(nd.array(tri), offset=offset,
                                       lower=lower).asnumpy()
            assert back.shape == a.shape, (offset, lower, back.shape)
            ref = np.zeros_like(a)
            r, c = (np.tril_indices(4, k=offset) if lower
                    else np.triu_indices(4, k=offset))
            ref[..., r, c] = a[..., r, c]
            np.testing.assert_allclose(back, ref, rtol=1e-6)


def test_proposal_short_anchor_grid():
    """Proposal must pad, not crash, when HW*A < rpn_post_nms_top_n
    (advisor round-2 finding: top_k with k > len raised)."""
    np.random.seed(1)
    cp = np.random.rand(1, 24, 4, 4).astype(np.float32)  # 192 anchors
    bp = (np.random.randn(1, 48, 4, 4) * 0.1).astype(np.float32)
    info = np.array([[64, 64, 1.0]], np.float32)
    rois = nd.Proposal(nd.array(cp), nd.array(bp), nd.array(info),
                       rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
                       feature_stride=16).asnumpy()
    assert rois.shape == (300, 5)
    assert (rois[:, 1] <= rois[:, 3]).all() and (rois[:, 2] <= rois[:, 4]).all()


def test_multibox_target_negative_mining_iou_gate():
    """Negative mining gates eligibility on anchor max-IoU < thresh (the
    reference multibox_target.cc rule), not on prediction confidence."""
    anchors = np.array([[[0.0, 0.0, 0.2, 0.2],      # IoU 1.0 -> positive
                         [0.0, 0.08, 0.2, 0.28],    # IoU ~0.43 -> ignored
                         [0.7, 0.7, 0.9, 0.9]]],    # IoU 0 -> negative
                       np.float32)
    label = np.array([[[0.0, 0.0, 0.0, 0.2, 0.2]]], np.float32)
    cls_pred = np.zeros((1, 3, 3), np.float32)
    _, _, ct = nd.MultiBoxTarget(nd.array(anchors), nd.array(label),
                                 nd.array(cls_pred),
                                 negative_mining_ratio=3.0,
                                 negative_mining_thresh=0.3)
    np.testing.assert_allclose(ct.asnumpy(), [[1.0, -1.0, 0.0]])


def test_multibox_detection_nms_topk_pre_truncation():
    """nms_topk truncates the score-ranked candidate list BEFORE NMS
    (reference behavior). Distinguishing case: A(0.9), B(0.8) overlapping
    A, C(0.7) disjoint, nms_topk=2 -> candidates {A, B}, B suppressed,
    output {A} only. Post-NMS masking would instead keep {A, C}."""
    anchors = np.array([[[0.1, 0.1, 0.3, 0.3],
                         [0.11, 0.11, 0.31, 0.31],   # overlaps A
                         [0.5, 0.5, 0.7, 0.7]]],      # disjoint
                       np.float32)
    cls_prob = np.array([[[0.1, 0.1, 0.1],
                          [0.9, 0.8, 0.7]]], np.float32)
    loc = np.zeros((1, 12), np.float32)
    out = nd.MultiBoxDetection(nd.array(cls_prob), nd.array(loc),
                               nd.array(anchors), nms_threshold=0.5,
                               nms_topk=2).asnumpy()
    rows = out[0]
    kept = rows[rows[:, 0] >= 0]
    assert len(kept) == 1, kept
    np.testing.assert_allclose(kept[0, 1], 0.9, rtol=1e-6)
    # without topk, the disjoint C survives alongside A
    out2 = nd.MultiBoxDetection(nd.array(cls_prob), nd.array(loc),
                                nd.array(anchors), nms_threshold=0.5).asnumpy()
    kept2 = out2[0][out2[0][:, 0] >= 0]
    assert len(kept2) == 2


def test_correlation_ceil_output_size():
    """Output extent uses ceil division like correlation.cc: 7x7 input with
    stride1=2 gives a 4x4 (not 3x3) displacement map."""
    x = np.random.rand(1, 2, 7, 7).astype(np.float32)
    out = nd.Correlation(nd.array(x), nd.array(x), kernel_size=1,
                         max_displacement=1, stride1=2, stride2=1,
                         pad_size=1).asnumpy()
    assert out.shape == (1, 9, 4, 4)


# ---------------------------------------------------------------------------
# round-3 additions: box codec, bipartite matching, sliding-window attention,
# multi-tensor LAMB, legacy Crop
# ---------------------------------------------------------------------------


def test_box_encode_decode_roundtrip():
    rng = np.random.RandomState(7)
    anchors = np.zeros((2, 6, 4), np.float32)
    anchors[..., :2] = rng.rand(2, 6, 2)
    anchors[..., 2:] = anchors[..., :2] + 0.2 + rng.rand(2, 6, 2) * 0.5
    refs = np.zeros((2, 3, 4), np.float32)
    refs[..., :2] = rng.rand(2, 3, 2)
    refs[..., 2:] = refs[..., :2] + 0.2 + rng.rand(2, 3, 2) * 0.5
    samples = np.ones((2, 6), np.float32)
    matches = rng.randint(0, 3, (2, 6)).astype(np.float32)

    t, m = nd.contrib.box_encode(nd.array(samples), nd.array(matches),
                                 nd.array(anchors), nd.array(refs))
    assert m.asnumpy().min() == 1.0
    dec = nd.contrib.box_decode(t, nd.array(anchors))
    want = refs[np.arange(2)[:, None], matches.astype(int)]
    assert_almost_equal(dec.asnumpy(), want, rtol=1e-4, atol=1e-4)
    # unmatched anchors get zeroed targets and masks
    t2, m2 = nd.contrib.box_encode(nd.zeros((2, 6)), nd.array(matches),
                                   nd.array(anchors), nd.array(refs))
    assert np.all(t2.asnumpy() == 0) and np.all(m2.asnumpy() == 0)


def test_bipartite_matching():
    score = np.array([[[0.5, 0.6, 0.9],
                       [0.8, 0.3, 0.4]]], np.float32)
    rows, cols = nd.contrib.bipartite_matching(nd.array(score),
                                               threshold=0.1)
    # greedy: (0,2)=0.9 first, then (1,0)=0.8
    assert rows.asnumpy().tolist() == [[2.0, 0.0]]
    assert cols.asnumpy().tolist() == [[1.0, -1.0, 0.0]]
    # threshold prunes weak pairs
    rows2, _ = nd.contrib.bipartite_matching(nd.array(score), threshold=0.85)
    assert rows2.asnumpy().tolist() == [[2.0, -1.0]]
    # ascending = smallest first
    rows3, _ = nd.contrib.bipartite_matching(nd.array(score), is_ascend=True,
                                             threshold=10.0)
    assert rows3.asnumpy()[0, 1] == 1.0


def test_sldwin_atten_vs_dense():
    rng = np.random.RandomState(3)
    BH, T, D, w = 2, 7, 4, 2
    q = rng.randn(BH, T, D).astype(np.float32)
    k = rng.randn(BH, T, D).astype(np.float32)
    v = rng.randn(BH, T, D).astype(np.float32)
    s = nd.contrib.sldwin_atten_score(nd.array(q), nd.array(k), w=w).asnumpy()
    dense = np.einsum("btd,bsd->bts", q, k)
    for i in range(T):
        for j, off in enumerate(range(-w, w + 1)):
            col = i + off
            want = dense[:, i, col] if 0 <= col < T else 0.0
            assert_almost_equal(s[:, i, j], want, rtol=1e-5, atol=1e-5)
    ctx = nd.contrib.sldwin_atten_context(nd.array(s), nd.array(v),
                                          w=w).asnumpy()
    mask = np.zeros((T, T), np.float32)
    for i in range(T):
        mask[i, max(0, i - w):min(T, i + w + 1)] = 1
    want_ctx = np.einsum("bts,bsd->btd", dense * mask, v)
    assert_almost_equal(ctx, want_ctx, rtol=1e-4, atol=1e-4)


def test_multi_lamb_update_matches_phases():
    rng = np.random.RandomState(0)
    ws = [rng.rand(4).astype(np.float32) for _ in range(2)]
    gs = [rng.rand(4).astype(np.float32) for _ in range(2)]
    arrays = []
    for w, g in zip(ws, gs):
        arrays += [nd.array(w), nd.array(g), nd.zeros(4), nd.zeros(4)]
    out = nd.multi_lamb_update(*arrays, step_count=(1, 1),
                               learning_rates=(0.02, 0.02), wds=(0.01, 0.01))
    assert len(out) == 6
    for i, (w, g) in enumerate(zip(ws, gs)):
        d, m2, v2 = nd.lamb_update_phase1(nd.array(w), nd.array(g),
                                          nd.zeros(4), nd.zeros(4),
                                          t=1, wd=0.01)
        r1 = np.linalg.norm(w)
        r2 = np.linalg.norm(d.asnumpy())
        want = nd.lamb_update_phase2(nd.array(w), d, nd.array(r1),
                                     nd.array(r2), 0.02)
        assert_almost_equal(out[3 * i].asnumpy(), want.asnumpy(),
                            rtol=1e-5, atol=1e-6)
        assert_almost_equal(out[3 * i + 1].asnumpy(), m2.asnumpy(),
                            rtol=1e-5, atol=1e-6)
    # mp variant keeps fp32 master weights
    arrays5 = []
    for w, g in zip(ws, gs):
        arrays5 += [nd.array(w).astype("float16"), nd.array(g),
                    nd.zeros(4), nd.zeros(4), nd.array(w)]
    out5 = nd.multi_mp_lamb_update(*arrays5, step_count=(1, 1),
                                   learning_rates=(0.02, 0.02),
                                   wds=(0.01, 0.01))
    assert out5[0].dtype == np.float16 and out5[3].dtype == np.float32


def test_crop_op():
    x = nd.array(np.arange(2 * 3 * 8 * 8, dtype=np.float32).reshape(2, 3, 8, 8))
    y = nd.Crop(x, h_w=(4, 4), center_crop=True)
    assert y.shape == (2, 3, 4, 4)
    assert_almost_equal(y.asnumpy(), x.asnumpy()[:, :, 2:6, 2:6])
    ref = nd.zeros((1, 1, 5, 6))
    z = nd.Crop(x, ref, offset=(1, 2))
    assert z.shape == (2, 3, 5, 6)
    assert_almost_equal(z.asnumpy(), x.asnumpy()[:, :, 1:6, 2:8])
