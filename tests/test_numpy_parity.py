"""``mx.np`` / ``mx.npx`` parity sweep against real NumPy.

Reference model: ``tests/python/unittest/test_numpy_op.py`` +
``test_numpy_interoperability.py`` — every function is exercised with
representative inputs and compared elementwise to the NumPy oracle.
"""

import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.numpy as np
import mxnet_tpu.numpy_extension as npx

RTOL, ATOL = 1e-5, 1e-6


def _chk(mx_out, np_out, rtol=RTOL, atol=ATOL):
    mx_arr = mx_out.asnumpy() if hasattr(mx_out, "asnumpy") else onp.asarray(mx_out)
    onp.testing.assert_allclose(mx_arr, np_out, rtol=rtol, atol=atol)


A = onp.random.RandomState(7).rand(3, 4).astype(onp.float32)
B = onp.random.RandomState(8).rand(3, 4).astype(onp.float32)
V = onp.random.RandomState(9).rand(5).astype(onp.float32)

UNARY = [
    "exp", "expm1", "log1p", "sqrt", "cbrt", "square", "sin", "cos", "tan",
    "arcsin", "arctan", "sinh", "cosh", "tanh", "arcsinh", "floor", "ceil",
    "trunc", "rint", "sign", "negative", "reciprocal", "degrees", "radians",
    "abs", "fabs", "isnan", "isinf", "isfinite", "real", "conj",
]

BINARY = [
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "power", "mod", "remainder", "fmod", "maximum", "minimum", "fmax",
    "fmin", "arctan2", "hypot", "logical_and", "logical_or", "logical_xor",
    "copysign", "nextafter", "equal", "not_equal", "greater", "less",
    "greater_equal", "less_equal",
]

REDUCTIONS = [
    "sum", "prod", "mean", "std", "var", "min", "max", "amin", "amax",
    "median", "ptp", "argmin", "argmax", "any", "all", "count_nonzero",
    "nansum", "nanprod", "nanmean", "nanmin", "nanmax",
]


@pytest.mark.parametrize("name", UNARY)
def test_np_unary(name):
    _chk(getattr(np, name)(np.array(A)), getattr(onp, name)(A))


@pytest.mark.parametrize("name", BINARY)
def test_np_binary(name):
    _chk(getattr(np, name)(np.array(A), np.array(B)),
         getattr(onp, name)(A, B))


@pytest.mark.parametrize("name", REDUCTIONS)
def test_np_reduction(name):
    _chk(getattr(np, name)(np.array(A)), getattr(onp, name)(A))
    if name not in ("count_nonzero",):
        _chk(getattr(np, name)(np.array(A), axis=1),
             getattr(onp, name)(A, axis=1))


def test_np_creation():
    _chk(np.zeros((2, 3)), onp.zeros((2, 3)))
    _chk(np.ones((2, 3)), onp.ones((2, 3)))
    _chk(np.full((2, 2), 7.0), onp.full((2, 2), 7.0))
    _chk(np.arange(2, 10, 2), onp.arange(2, 10, 2))
    _chk(np.linspace(0, 1, 7), onp.linspace(0, 1, 7))
    _chk(np.logspace(0, 2, 5), onp.logspace(0, 2, 5), rtol=1e-4)
    _chk(np.eye(4, k=1), onp.eye(4, k=1))
    _chk(np.identity(3), onp.identity(3))
    _chk(np.tri(3), onp.tri(3))
    _chk(np.zeros_like(np.array(A)), onp.zeros_like(A))
    _chk(np.full_like(np.array(A), 2.5), onp.full_like(A, 2.5))


def test_np_manipulation():
    a = np.array(A)
    _chk(np.reshape(a, (4, 3)), A.reshape(4, 3))
    _chk(np.ravel(a), A.ravel())
    _chk(np.transpose(a), A.T)
    _chk(np.expand_dims(a, 1), onp.expand_dims(A, 1))
    _chk(np.squeeze(np.expand_dims(a, 0)), A)
    _chk(np.concatenate([a, a], axis=0), onp.concatenate([A, A], 0))
    _chk(np.stack([a, a], axis=1), onp.stack([A, A], 1))
    _chk(np.vstack([a, a]), onp.vstack([A, A]))
    _chk(np.hstack([a, a]), onp.hstack([A, A]))
    _chk(np.tile(a, (2, 1)), onp.tile(A, (2, 1)))
    _chk(np.repeat(a, 2, axis=0), onp.repeat(A, 2, 0))
    _chk(np.flip(a, axis=1), onp.flip(A, 1))
    _chk(np.roll(a, 1, axis=0), onp.roll(A, 1, 0))
    _chk(np.rot90(a), onp.rot90(A))
    _chk(np.pad(a, ((1, 1), (0, 0))), onp.pad(A, ((1, 1), (0, 0))))
    _chk(np.broadcast_to(np.array(V), (3, 5)), onp.broadcast_to(V, (3, 5)))
    _chk(np.atleast_2d(np.array(V)), onp.atleast_2d(V))
    parts = np.split(a, 2, axis=1)
    ref = onp.split(A, 2, 1)
    for p, r in zip(parts, ref):
        _chk(p, r)


def test_np_sorting_searching():
    _chk(np.sort(np.array(V)), onp.sort(V))
    _chk(np.argsort(np.array(V)), onp.argsort(V))
    _chk(np.searchsorted(np.sort(np.array(V)), np.array(V)),
         onp.searchsorted(onp.sort(V), V))
    _chk(np.unique(np.array([1.0, 3.0, 1.0, 2.0])),
         onp.unique([1.0, 3.0, 1.0, 2.0]))
    _chk(np.where(np.array(A) > 0.5, np.array(A), np.array(B)),
         onp.where(A > 0.5, A, B))
    _chk(np.nonzero(np.array([0.0, 1.0, 0.0, 2.0]))[0],
         onp.nonzero([0.0, 1.0, 0.0, 2.0])[0])
    _chk(np.argwhere(np.array(A) > 0.5), onp.argwhere(A > 0.5))


def test_np_linalg_products():
    a, b = np.array(A), np.array(B)
    _chk(np.dot(a, b.T), A @ B.T, rtol=1e-4)
    _chk(np.matmul(a, b.T), A @ B.T, rtol=1e-4)
    _chk(np.einsum("ij,kj->ik", a, b), onp.einsum("ij,kj->ik", A, B),
         rtol=1e-4)
    _chk(np.tensordot(a, b, axes=([1], [1])),
         onp.tensordot(A, B, ([1], [1])), rtol=1e-4)
    _chk(np.inner(a, b), onp.inner(A, B), rtol=1e-4)
    _chk(np.outer(np.array(V), np.array(V)), onp.outer(V, V))
    _chk(np.kron(a, b), onp.kron(A, B), rtol=1e-4)
    _chk(np.trace(np.array(A[:3, :3])), onp.trace(A[:3, :3]))
    _chk(np.cross(np.array(V[:3]), np.array(V[1:4])),
         onp.cross(V[:3], V[1:4]))


def test_np_linalg_module():
    spd = (A[:3, :3] @ A[:3, :3].T + 3 * onp.eye(3)).astype(onp.float32)
    _chk(np.linalg.norm(np.array(A)), onp.linalg.norm(A), rtol=1e-4)
    _chk(np.linalg.inv(np.array(spd)), onp.linalg.inv(spd), rtol=1e-3,
         atol=1e-4)
    _chk(np.linalg.det(np.array(spd)), onp.linalg.det(spd), rtol=1e-3)
    _chk(np.linalg.cholesky(np.array(spd)), onp.linalg.cholesky(spd),
         rtol=1e-3, atol=1e-4)
    w_mx = np.linalg.eigvalsh(np.array(spd))
    _chk(np.sort(w_mx), onp.sort(onp.linalg.eigvalsh(spd)), rtol=1e-3,
         atol=1e-4)
    x = np.linalg.solve(np.array(spd), np.array(V[:3]))
    onp.testing.assert_allclose(spd @ x.asnumpy(), V[:3], rtol=1e-3,
                                atol=1e-4)
    u, s, vt = np.linalg.svd(np.array(A))
    onp.testing.assert_allclose(
        u.asnumpy()[:, :3] @ onp.diag(s.asnumpy()) @ vt.asnumpy()[:3], A,
        rtol=1e-3, atol=1e-4)


def test_np_statistics():
    _chk(np.percentile(np.array(V), 50), onp.percentile(V, 50))
    _chk(np.quantile(np.array(V), 0.25), onp.quantile(V, 0.25), rtol=1e-4)
    _chk(np.average(np.array(V), weights=np.array(V)),
         onp.average(V, weights=V), rtol=1e-4)
    _chk(np.cov(np.array(A)), onp.cov(A), rtol=1e-4)
    _chk(np.corrcoef(np.array(A)), onp.corrcoef(A), rtol=1e-4)
    cnt, edges = np.histogram(np.array(V), 4)
    rcnt, redges = onp.histogram(V, 4)
    _chk(cnt, rcnt)
    _chk(edges, redges, rtol=1e-5)
    _chk(np.bincount(np.array([0, 1, 1, 3])), onp.bincount([0, 1, 1, 3]))
    _chk(np.diff(np.array(V)), onp.diff(V))
    _chk(np.gradient(np.array(V)), onp.gradient(V), rtol=1e-4)
    _chk(np.interp(np.array([1.5]), np.array([1.0, 2.0]),
                   np.array([10.0, 20.0])), [15.0])
    _chk(np.convolve(np.array(V), np.array([1.0, 0.5])),
         onp.convolve(V, [1.0, 0.5]), rtol=1e-4)


def test_np_indexing_functions():
    a = np.array(A)
    _chk(np.take(a, np.array([0, 2]), axis=0), onp.take(A, [0, 2], 0))
    _chk(np.take_along_axis(a, np.argsort(a, axis=1), axis=1),
         onp.take_along_axis(A, onp.argsort(A, 1), 1))
    _chk(np.compress(np.array([True, False, True]), a, axis=0),
         onp.compress([True, False, True], A, 0))
    idx = np.unravel_index(np.array([5, 11]), (3, 4))
    ref = onp.unravel_index([5, 11], (3, 4))
    for i, r in zip(idx, ref):
        _chk(i, r)


def test_np_array_interop():
    """mx.np arrays are framework NDArrays: autograd + Gluon interop."""
    from mxnet_tpu import autograd

    a = np.array(A)
    assert isinstance(a, mx.nd.NDArray)
    a.attach_grad()
    with autograd.record():
        out = (np.sin(a) * np.array(B)).sum()
    out.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), onp.cos(A) * B, rtol=1e-5)


def test_npx_surface():
    x = np.array(A)
    s = npx.softmax(x, axis=-1).asnumpy()
    onp.testing.assert_allclose(s.sum(-1), onp.ones(3), rtol=1e-5)
    r = npx.relu(np.array(A - 0.5)).asnumpy()
    assert (r >= 0).all()
    t = npx.topk(x, k=2, axis=-1)
    assert t.shape == (3, 2)


def test_npx_set_np_shape_semantics():
    """npx.set_np flips the unknown-dim sentinel from 0 to -1 (reference:
    mx.util.set_np / np_shape)."""
    from mxnet_tpu.gluon import nn

    npx.set_np()
    try:
        assert npx.is_np_array() and mx.util.is_np_shape()
        # -1 marks deferred dims under np semantics
        from mxnet_tpu.gluon.parameter import Parameter
        p = Parameter("w", shape=(-1, 4), allow_deferred_init=True)
        p.initialize()
        p.shape = (3, 4)
        assert p.shape == (3, 4)
        assert p.data().shape == (3, 4)
        # zero-dim scalars are real arrays
        z = np.array(1.5)
        assert z.shape == ()
        assert float(z.asnumpy()) == 1.5
    finally:
        npx.reset_np()
    # legacy: 0 marks deferred dims
    from mxnet_tpu.gluon.parameter import Parameter
    p = Parameter("w2", shape=(0, 4), allow_deferred_init=True)
    p.initialize()
    p.shape = (5, 4)
    assert p.data().shape == (5, 4)


def test_round3_breadth_functions():
    """New round-3 np functions agree with numpy on representative calls."""
    a = onp.array([3.0, 1.0, 2.0], onp.float32)
    m = onp.array([[4.0, 1.0], [2.0, 3.0]], onp.float32)
    z = onp.array([0., 1., 2., 0.], onp.float32)
    b2 = onp.array([2.0, 5.0], onp.float32)
    checks = [
        (np.sinc(np.array(a)), onp.sinc(a)),
        (np.i0(np.array(a)), onp.i0(a)),
        (np.float_power(np.array(a), 2.0), onp.float_power(a, 2.0)),
        (np.logaddexp2(np.array(a), np.array(a)), onp.logaddexp2(a, a)),
        (np.nanmedian(np.array(a)), onp.nanmedian(a)),
        (np.msort(np.array(m)), onp.sort(m, axis=0)),
        (np.trim_zeros(np.array(z)), onp.trim_zeros(z)),
        (np.union1d(np.array(a), np.array(b2)), onp.union1d(a, b2)),
        (np.unwrap(np.array(a)), onp.unwrap(a)),
    ]
    for got, want in checks:
        got_np = got.asnumpy() if hasattr(got, "asnumpy") else onp.asarray(got)
        onp.testing.assert_allclose(got_np, onp.asarray(want),
                                    rtol=1e-5, atol=1e-6)
    r = np.isin(np.array(a), np.array(onp.array([1.0, 9.0], onp.float32)))
    onp.testing.assert_array_equal(r.asnumpy(), [False, True, False])

    # in-place contracts (numpy semantics: mutate, return None)
    w = np.array(m)
    assert np.fill_diagonal(w, 9.0) is None
    onp.testing.assert_allclose(w.asnumpy(),
                                onp.array([[9., 1.], [2., 9.]], onp.float32))
    w2 = np.zeros((3, 3))
    idx = np.array(onp.array([[1], [0], [2]], onp.int32))
    assert np.put_along_axis(w2, idx, 7.0, 1) is None
    ref = onp.zeros((3, 3), onp.float32)
    onp.put_along_axis(ref, onp.array([[1], [0], [2]]), 7.0, 1)
    onp.testing.assert_allclose(w2.asnumpy(), ref)


def test_inplace_np_funcs_keep_tape_lineage():
    """Review regression: fill_diagonal/put_along_axis must rewire _ag so
    gradients through overwritten positions are zero."""
    from mxnet_tpu import autograd

    x2 = mx.nd.ones((3, 3))
    x2.attach_grad()
    with autograd.record():
        y2 = x2 * 2
        np.fill_diagonal(y2, 0.0)
        s = y2.sum()
    s.backward()
    g = x2.grad.asnumpy()
    onp.testing.assert_allclose(onp.diag(g), [0, 0, 0])
    assert (g[onp.eye(3) == 0] == 2).all()

    x3 = mx.nd.ones((3, 1))
    x3.attach_grad()
    with autograd.record():
        y3 = x3 * 2
        np.put_along_axis(y3, np.array(onp.array([[0], [0], [0]],
                                                 onp.int32)), 0.0, 1)
        s3 = y3.sum()
    s3.backward()
    onp.testing.assert_allclose(x3.grad.asnumpy(), onp.zeros((3, 1)))


def test_inplace_np_outside_record_preserves_lineage():
    """Review regression: mutating a tape-resident array OUTSIDE record
    must not sever upstream gradients (pre-existing semantics)."""
    from mxnet_tpu import autograd

    x = mx.nd.ones((3, 3))
    x.attach_grad()
    with autograd.record():
        y = x * 2
        s = y.sum()
    with autograd.pause():
        np.fill_diagonal(y, 0.0)
    s.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), onp.full((3, 3), 2.0))


def test_npx_save_load_waitall_use_np(tmp_path):
    f = str(tmp_path / "arrs.params")
    npx.save(f, {"w": np.ones((2, 3)), "b": np.zeros(4)})
    back = npx.load(f)
    assert set(back) == {"w", "b"}
    assert onp.allclose(back["w"].asnumpy(), 1.0)
    npx.save(f, [np.arange(5)])
    lst = npx.load(f)
    assert onp.allclose(lst[0].asnumpy(), onp.arange(5))
    npx.waitall()
    # namespace hygiene: no camelCase or loop-variable leaks
    assert not hasattr(npx, "batchNorm") and not hasattr(npx, "low")
    assert callable(npx.batch_norm) and callable(npx.use_np)


def test_np_round3_stragglers():
    """geomspace/block/in1d/row_stack/fromiter/frombuffer/shares_memory/
    apply_along_axis/fromfunction/setxor1d/einsum_path (reference: the
    mx.np surface mirrors numpy's main namespace)."""
    assert onp.allclose(np.geomspace(1, 1000, 4).asnumpy(),
                        [1, 10, 100, 1000])
    b = np.block([[np.ones((2, 2)), np.zeros((2, 2))]])
    assert b.shape == (2, 4) and b.asnumpy()[0, 3] == 0
    assert np.in1d(np.array([1, 2, 5]),
                   np.array([2, 5])).asnumpy().tolist() == [False, True, True]
    assert np.row_stack([np.ones(3), np.zeros(3)]).shape == (2, 3)
    assert np.fromiter(range(5), dtype="int32").asnumpy().tolist() == \
        [0, 1, 2, 3, 4]
    assert np.frombuffer(b"\x00\x00\x80?",
                         dtype="float32").asnumpy()[0] == 1.0
    a = np.array([1.0, 2.0])
    assert np.shares_memory(a, a)
    assert not np.may_share_memory(a, np.array([1.0]))
    # write-through slice views share memory with their base
    base = np.array(onp.arange(6.0))
    view = base[1:4]
    assert np.shares_memory(base, view) and np.may_share_memory(view, base)
    # einsum_path is metadata-only: safe on TRACKED arrays inside record
    from mxnet_tpu import autograd as _ag

    t = np.ones((2, 3))
    t.attach_grad()
    with _ag.record():
        assert np.einsum_path("ij,jk->ik", t, np.ones((3, 4))) is not None
    # real_if_close preserves lineage on real input
    y = np.ones((2, 2))
    y.attach_grad()
    with _ag.record():
        zz = (np.real_if_close(y) * 2).sum()
    zz.backward()
    assert onp.allclose(y.grad.asnumpy(), 2.0)
    assert np.real_if_close(
        np.array(onp.array([], dtype="complex64"))).shape == (0,)
    assert np.apply_along_axis(lambda x: x.sum(), 1,
                               np.ones((3, 4))).shape == (3,)
    assert np.fromfunction(lambda i, j: i + j,
                           (2, 2)).asnumpy().tolist() == [[0, 1], [1, 2]]
    assert np.setxor1d(np.array([1, 2, 3]),
                       np.array([2, 3, 4])).asnumpy().tolist() == [1, 4]
    assert np.einsum_path("ij,jk->ik", np.ones((2, 3)),
                          np.ones((3, 4))) is not None
    # autograd flows through the new wrappers like every other np fn
    from mxnet_tpu import autograd

    x = np.ones((2, 3))
    x.attach_grad()
    with autograd.record():
        y = np.geomspace(1, 100, 3) * x
        z = y.sum()
    z.backward()
    assert onp.allclose(x.grad.asnumpy(), [[1, 10, 100]] * 2)


def test_npx_expanded_surface():
    """Round-4 npx growth (VERDICT r3 item 9): the reference
    numpy_extension names resolve and a sample of each family executes."""
    expected = [
        # original core
        "relu", "sigmoid", "softmax", "log_softmax", "topk", "pick",
        "one_hot", "embedding", "fully_connected", "convolution",
        "deconvolution", "pooling", "batch_norm", "layer_norm",
        "group_norm", "instance_norm", "dropout", "rnn", "arange_like",
        "sequence_mask", "reshape_like", "batch_dot", "broadcast_like",
        "gather_nd", "leaky_relu", "activation",
        # round-4 additions
        "smooth_l1", "erf", "erfinv", "gamma", "gammaln", "digamma",
        "softmax_cross_entropy", "gelu", "log_sigmoid", "softplus",
        "multibox_prior", "multibox_target", "multibox_detection",
        "roi_pooling", "roi_align", "box_nms", "box_iou",
        "bilinear_resize_2d", "deformable_convolution",
        "modulated_deformable_convolution", "spatial_transformer",
        "grid_generator", "bilinear_sampler", "sequence_last",
        "sequence_reverse", "ctc_loss", "interleaved_matmul_selfatt_qk",
        "interleaved_matmul_selfatt_valatt", "interleaved_matmul_encdec_qk",
        "interleaved_matmul_encdec_valatt", "slice", "slice_axis",
        "slice_like", "scatter_nd", "index_add", "index_update",
        "index_copy", "batch_take", "pad", "im2col", "col2im",
        "depth_to_space", "space_to_depth", "batch_flatten",
        "stop_gradient", "moments", "cast", "amp_cast", "amp_multicast",
        "shape_array", "all_finite",
        # utilities
        "save", "load", "waitall", "seed", "set_np", "reset_np",
        "is_np_array", "use_np",
    ]
    missing = [n for n in expected if not hasattr(npx, n)]
    assert not missing, missing
    assert len(expected) >= 80  # well past the reference's ~50-op bar

    # sample executions across the new families
    x = np.array(onp.arange(12, dtype=onp.float32).reshape(3, 4) / 12.0)
    onp.testing.assert_allclose(npx.smooth_l1(x).asnumpy(),
                               0.5 * x.asnumpy() ** 2, rtol=1e-5)
    assert npx.sequence_last(np.array(onp.random.rand(3, 2, 4)
                                       .astype(onp.float32))).shape == (2, 4)
    assert npx.batch_flatten(np.array(onp.random.rand(2, 3, 4)
                                       .astype(onp.float32))).shape == (2, 12)
    anchors = npx.multibox_prior(np.array(onp.random.rand(1, 3, 4, 4)
                                           .astype(onp.float32)),
                                 sizes=(0.5,), ratios=(1.0,))
    assert anchors.shape[-1] == 4
    m = npx.moments(np.array(onp.random.rand(4,).astype(onp.float32)))
    assert len(m) == 2
