"""Operator correctness vs NumPy + numeric gradient checks.

Reference model: tests/python/unittest/test_operator.py (forward vs numpy,
backward vs central differences).
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import (
    assert_almost_equal,
    check_numeric_gradient,
    with_seed,
)


def test_unary_ops():
    x = np.random.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    a = mx.nd.array(x)
    for name, ref in [
        ("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
        ("square", np.square), ("abs", np.abs), ("sin", np.sin),
        ("cos", np.cos), ("tanh", np.tanh), ("floor", np.floor),
        ("ceil", np.ceil), ("sign", np.sign), ("log1p", np.log1p),
    ]:
        assert_almost_equal(getattr(mx.nd, name)(a), ref(x), rtol=1e-5,
                            atol=1e-5, names=(name, "np"))
    assert_almost_equal(mx.nd.sigmoid(a), 1 / (1 + np.exp(-x)), rtol=1e-5)
    assert_almost_equal(mx.nd.relu(a - 1), np.maximum(x - 1, 0), rtol=1e-5)


def test_broadcast_ops():
    a = np.random.rand(2, 1, 3).astype(np.float32)
    b = np.random.rand(1, 4, 3).astype(np.float32)
    ma, mb = mx.nd.array(a), mx.nd.array(b)
    assert_almost_equal(mx.nd.broadcast_add(ma, mb), a + b, rtol=1e-5)
    assert_almost_equal(mx.nd.broadcast_mul(ma, mb), a * b, rtol=1e-5)
    assert_almost_equal(mx.nd.broadcast_maximum(ma, mb), np.maximum(a, b))
    assert_almost_equal(mx.nd.broadcast_power(ma + 1, mb), (a + 1) ** b, rtol=1e-4)


def test_reductions():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    a = mx.nd.array(x)
    assert_almost_equal(mx.nd.sum(a), x.sum(), rtol=1e-5)
    assert_almost_equal(mx.nd.sum(a, axis=1), x.sum(1), rtol=1e-5)
    assert_almost_equal(mx.nd.sum(a, axis=(0, 2)), x.sum((0, 2)), rtol=1e-5)
    assert_almost_equal(mx.nd.sum(a, axis=1, exclude=True), x.sum((0, 2)), rtol=1e-5)
    assert_almost_equal(mx.nd.mean(a, axis=1, keepdims=True),
                        x.mean(1, keepdims=True), rtol=1e-5)
    assert_almost_equal(mx.nd.max(a, axis=2), x.max(2))
    assert_almost_equal(mx.nd.min(a), x.min())
    assert_almost_equal(mx.nd.prod(a, axis=0), x.prod(0), rtol=1e-5)
    assert_almost_equal(mx.nd.argmax(a, axis=1),
                        x.argmax(1).astype(np.float32))
    assert_almost_equal(mx.nd.norm(a), np.sqrt((x ** 2).sum()), rtol=1e-5)


def test_dot():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4, 5).astype(np.float32)
    assert_almost_equal(mx.nd.dot(mx.nd.array(a), mx.nd.array(b)), a @ b,
                        rtol=1e-5)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a), mx.nd.array(b.T), transpose_b=True),
        a @ b, rtol=1e-5)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a.T), mx.nd.array(b), transpose_a=True),
        a @ b, rtol=1e-5)
    # batch dot
    x = np.random.rand(2, 3, 4).astype(np.float32)
    y = np.random.rand(2, 4, 5).astype(np.float32)
    assert_almost_equal(mx.nd.batch_dot(mx.nd.array(x), mx.nd.array(y)),
                        np.matmul(x, y), rtol=1e-5)


def test_fully_connected():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    w = np.random.rand(5, 12).astype(np.float32)
    b = np.random.rand(5).astype(np.float32)
    out = mx.nd.FullyConnected(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                               num_hidden=5)
    expected = x.reshape(2, 12) @ w.T + b
    assert_almost_equal(out, expected, rtol=1e-5)
    out_nf = mx.nd.FullyConnected(mx.nd.array(x),
                                  mx.nd.array(np.random.rand(5, 4).astype(np.float32)),
                                  None, num_hidden=5, no_bias=True,
                                  flatten=False)
    assert out_nf.shape == (2, 3, 5)


def test_convolution():
    x = np.random.rand(1, 2, 5, 5).astype(np.float32)
    w = np.random.rand(3, 2, 3, 3).astype(np.float32)
    out = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), None,
                            kernel=(3, 3), num_filter=3, no_bias=True,
                            pad=(1, 1))
    assert out.shape == (1, 3, 5, 5)
    # check center value against direct correlation
    ref = np.zeros((1, 3, 5, 5), np.float32)
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    for f in range(3):
        for i in range(5):
            for j in range(5):
                ref[0, f, i, j] = (xp[0, :, i:i + 3, j:j + 3] * w[f]).sum()
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_pooling():
    x = np.random.rand(1, 1, 4, 4).astype(np.float32)
    out = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2),
                        pool_type="max")
    ref = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
    assert_almost_equal(out, ref)
    out_avg = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2),
                            pool_type="avg")
    ref_avg = x.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
    assert_almost_equal(out_avg, ref_avg, rtol=1e-5)
    out_g = mx.nd.Pooling(mx.nd.array(x), global_pool=True, pool_type="max",
                          kernel=(1, 1))
    assert out_g.shape == (1, 1, 1, 1)


def test_softmax():
    x = np.random.rand(3, 5).astype(np.float32)
    out = mx.nd.softmax(mx.nd.array(x))
    e = np.exp(x - x.max(axis=-1, keepdims=True))
    assert_almost_equal(out, e / e.sum(-1, keepdims=True), rtol=1e-5)
    lout = mx.nd.log_softmax(mx.nd.array(x))
    assert_almost_equal(lout, np.log(e / e.sum(-1, keepdims=True)), rtol=1e-5)


def test_batchnorm_inference():
    x = np.random.rand(2, 3, 4, 4).astype(np.float32)
    gamma = np.random.rand(3).astype(np.float32)
    beta = np.random.rand(3).astype(np.float32)
    mean = np.random.rand(3).astype(np.float32)
    var = np.random.rand(3).astype(np.float32) + 0.5
    out = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(gamma),
                          mx.nd.array(beta), mx.nd.array(mean),
                          mx.nd.array(var), fix_gamma=False, eps=1e-5)
    ref = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + 1e-5) \
        * gamma[None, :, None, None] + beta[None, :, None, None]
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_batchnorm_running_stats_keep_dtype():
    """Training-mode BN must not promote narrow running-stat aux arrays
    to f32 (the f32 one-pass moments are an internal detail; r4 advisor)."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import nn as nn_ops

    x = jnp.asarray(np.random.rand(2, 3, 4, 4), jnp.float32)
    g = jnp.ones(3, jnp.float32)
    b = jnp.zeros(3, jnp.float32)
    mm = jnp.zeros(3, jnp.float16)
    mv = jnp.ones(3, jnp.float16)
    out, nm, nv = nn_ops.batch_norm(x, g, b, mm, mv, training=True,
                                    fix_gamma=False)
    assert nm.dtype == jnp.float16 and nv.dtype == jnp.float16


def test_layernorm():
    x = np.random.rand(2, 5).astype(np.float32)
    g = np.ones(5, np.float32)
    b = np.zeros(5, np.float32)
    out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(b))
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    assert_almost_equal(out, (x - mean) / np.sqrt(var + 1e-5), rtol=1e-4)


def test_shape_ops():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    a = mx.nd.array(x)
    assert_almost_equal(mx.nd.transpose(a, axes=(2, 0, 1)),
                        x.transpose(2, 0, 1))
    assert_almost_equal(mx.nd.swapaxes(a, 0, 2), x.swapaxes(0, 2))
    assert_almost_equal(mx.nd.flip(a, axis=1), np.flip(x, 1))
    assert_almost_equal(mx.nd.tile(a, reps=(1, 2, 1)), np.tile(x, (1, 2, 1)))
    assert_almost_equal(mx.nd.repeat(a, repeats=2, axis=0), np.repeat(x, 2, 0))
    assert_almost_equal(mx.nd.expand_dims(a, axis=1), x[:, None])
    assert_almost_equal(mx.nd.slice(a, begin=(0, 1), end=(2, 3)), x[0:2, 1:3])
    assert_almost_equal(mx.nd.slice_axis(a, axis=2, begin=1, end=3),
                        x[:, :, 1:3])
    assert_almost_equal(mx.nd.broadcast_to(mx.nd.array(x[:1]), shape=(5, 3, 4)),
                        np.broadcast_to(x[:1], (5, 3, 4)))
    assert_almost_equal(mx.nd.pad(a, mode="constant",
                                  pad_width=(0, 0, 0, 0, 1, 1),
                                  constant_value=0),
                        np.pad(x, ((0, 0), (0, 0), (1, 1))))


def test_take_gather():
    x = np.random.rand(5, 3).astype(np.float32)
    idx = np.array([0, 4, 2], np.float32)
    assert_almost_equal(mx.nd.take(mx.nd.array(x), mx.nd.array(idx)),
                        x[idx.astype(int)])
    # Embedding
    w = np.random.rand(10, 4).astype(np.float32)
    data = np.array([[1, 2], [3, 4]], np.float32)
    out = mx.nd.Embedding(mx.nd.array(data), mx.nd.array(w), input_dim=10,
                          output_dim=4)
    assert_almost_equal(out, w[data.astype(int)])
    # one_hot
    oh = mx.nd.one_hot(mx.nd.array([1.0, 0.0, 2.0]), depth=3)
    assert_almost_equal(oh, np.eye(3, dtype=np.float32)[[1, 0, 2]])
    # pick
    p = mx.nd.pick(mx.nd.array(x), mx.nd.array(np.array([0, 1, 2, 0, 1], np.float32)), axis=1)
    assert_almost_equal(p, x[np.arange(5), [0, 1, 2, 0, 1]])


def test_topk_sort():
    x = np.random.rand(3, 6).astype(np.float32)
    a = mx.nd.array(x)
    vals = mx.nd.topk(a, k=2, ret_typ="value")
    ref = np.sort(x, axis=-1)[:, ::-1][:, :2]
    assert_almost_equal(vals, ref)
    assert_almost_equal(mx.nd.sort(a), np.sort(x, -1))
    idx = mx.nd.argsort(a).asnumpy().astype(int)
    assert_almost_equal(np.take_along_axis(x, idx, -1), np.sort(x, -1))


def test_where_clip():
    x = np.random.uniform(-1, 1, (3, 3)).astype(np.float32)
    cond = (x > 0).astype(np.float32)
    out = mx.nd.where(mx.nd.array(cond), mx.nd.array(x), mx.nd.array(-x))
    assert_almost_equal(out, np.abs(x))
    assert_almost_equal(mx.nd.clip(mx.nd.array(x), a_min=-0.5, a_max=0.5),
                        np.clip(x, -0.5, 0.5))


def test_split_concat():
    x = np.random.rand(4, 6).astype(np.float32)
    parts = mx.nd.split(mx.nd.array(x), num_outputs=3, axis=1)
    assert len(parts) == 3
    for i, p in enumerate(parts):
        assert_almost_equal(p, x[:, 2 * i:2 * i + 2])
    back = mx.nd.concat(*parts, dim=1)
    assert_almost_equal(back, x)


# ---- gradient checks (central difference vs tape) -------------------------


def test_grad_elemwise():
    x = np.random.uniform(0.5, 1.5, (2, 3)).astype(np.float32)
    check_numeric_gradient(lambda a: (a * a + mx.nd.exp(a)).sum(), [x])


def test_grad_dot():
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.rand(4, 2).astype(np.float32)
    check_numeric_gradient(lambda x, y: mx.nd.dot(x, y).sum(), [a, b])


def test_grad_softmax_ce():
    x = np.random.rand(2, 4).astype(np.float32)

    def fn(a):
        return -(mx.nd.log_softmax(a) * mx.nd.one_hot(
            mx.nd.array([1.0, 3.0]), depth=4)).sum()

    check_numeric_gradient(fn, [x], rtol=2e-2, atol=1e-3)


def test_grad_conv():
    x = np.random.rand(1, 1, 4, 4).astype(np.float32)
    w = np.random.rand(2, 1, 3, 3).astype(np.float32)

    def fn(a, b):
        return mx.nd.Convolution(a, b, None, kernel=(3, 3), num_filter=2,
                                 no_bias=True, pad=(1, 1)).sum()

    check_numeric_gradient(fn, [x, w], rtol=2e-2, atol=1e-2)


def test_rnn_op_shapes():
    T, N, C, H, L = 4, 2, 3, 5, 2
    ngates = 4
    sizes = 0
    for layer in range(L):
        inc = C if layer == 0 else H
        sizes += ngates * H * inc + ngates * H * H + 2 * ngates * H
    params = mx.nd.random.normal(shape=(sizes,), scale=0.1)
    data = mx.nd.random.normal(shape=(T, N, C))
    h0 = mx.nd.zeros((L, N, H))
    c0 = mx.nd.zeros((L, N, H))
    out, hN, cN = mx.nd.RNN(data, params, h0, c0, state_size=H,
                            num_layers=L, mode="lstm")
    assert out.shape == (T, N, H)
    assert hN.shape == (L, N, H)
    assert cN.shape == (L, N, H)


def test_ctc_loss_smoke():
    T, N, C = 10, 2, 5
    pred = mx.nd.random.normal(shape=(T, N, C))
    label = mx.nd.array(np.array([[1, 2, 0], [2, 3, 4]], np.float32))
    from mxnet_tpu.ops.dispatch import invoke

    loss = invoke("_ctc_loss", pred, label)
    assert loss.shape == (N,)
    assert (loss.asnumpy() > 0).all()
