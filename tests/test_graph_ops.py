"""DGL graph op family (reference: contrib/dgl_graph.cc)."""

import numpy as np

import mxnet_tpu as mx


def _graph():
    dense = np.array([[0, 1, 2, 0],
                      [0, 0, 3, 0],
                      [4, 0, 0, 5],
                      [0, 6, 0, 0]], np.float32)
    return dense, mx.nd.array(dense).tostype("csr")


def test_edge_id():
    dense, g = _graph()
    out = mx.nd.contrib.edge_id(g, mx.nd.array([0, 1, 3, 2]),
                                mx.nd.array([2, 0, 1, 0]))
    np.testing.assert_allclose(out.asnumpy(), [2.0, -1.0, 6.0, 4.0])


def test_dgl_adjacency():
    dense, g = _graph()
    adj = mx.nd.contrib.dgl_adjacency(g)
    np.testing.assert_allclose(adj.asnumpy(),
                               (dense != 0).astype(np.float32))


def test_dgl_subgraph():
    dense, g = _graph()
    sub, emap = mx.nd.contrib.dgl_subgraph(g, mx.nd.array([0, 2]),
                                           return_mapping=True)
    # induced on {0, 2}: edges 0->2 (id 2) and 2->0 (id 4); the mapping
    # stores id+1 so DGL's legal edge id 0 survives the 0=no-edge dense
    # encoding
    np.testing.assert_allclose(sub.asnumpy(), [[0, 1], [1, 0]])
    np.testing.assert_allclose(emap.asnumpy(), [[0, 3], [5, 0]])
    # two vid sets in one call
    s1, s2 = mx.nd.contrib.dgl_subgraph(g, mx.nd.array([0, 1]),
                                        mx.nd.array([1, 2, 3]))
    assert s1.shape == (2, 2) and s2.shape == (3, 3)


def test_neighbor_sampling():
    dense, g = _graph()
    mx.random.seed(3)
    ids, sub = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        g, mx.nd.array([0]), num_hops=2, num_neighbor=2,
        max_num_vertices=4)
    idn = ids.asnumpy()
    count = int(idn[-1])
    assert idn[0] == 0 and 1 <= count <= 4
    assert all(v == -1 for v in idn[count:-1])
    # sampled edges exist in the original graph, ids stored +1 (0 is the
    # no-edge sentinel of the dense-CSR emulation; DGL ids are 0-based)
    sn = sub.asnumpy()
    vid = idn[:count]
    for i in range(count):
        for j in range(count):
            if sn[i, j] != 0:
                assert dense[int(vid[i]), int(vid[j])] == sn[i, j] - 1.0
    # non-uniform: zero-probability neighbors are never sampled
    prob = mx.nd.array([1.0, 0.0, 1.0, 1.0])  # vertex 1 excluded
    mx.random.seed(4)
    ids2, sub2 = mx.nd.contrib.dgl_csr_neighbor_non_uniform_sample(
        g, prob, mx.nd.array([0, 3]), num_hops=1, num_neighbor=1,
        max_num_vertices=4)
    idn2 = ids2.asnumpy()
    # with p(vertex 1) = 0, vertex 1 can never be sampled (seeds were 0, 3
    # and 3's only neighbor IS 1 -> renormalized p is degenerate there, so
    # only assert 1 absent when it has a sampleable alternative)
    sampled = set(int(v) for v in idn2[:int(idn2[-1])])
    assert 0 in sampled and 3 in sampled
