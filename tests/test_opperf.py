"""Operator micro-benchmark harness smoke (reference: benchmark/opperf)."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_opperf_eager_and_graph(tmp_path):
    out = tmp_path / "opperf.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmark", "opperf.py"),
         "--ops", "relu,dot,sample_normal", "--chain", "3",
         "--json", str(out)],
        capture_output=True, text=True, timeout=300, env=env)
    assert res.returncode == 0, res.stderr[-2000:]
    recs = json.loads(out.read_text())
    assert {r["op"] for r in recs} == {"relu", "dot", "sample_normal"}
    assert all(r["avg_time_ms"] >= 0 for r in recs)
    # JAX_PLATFORMS must be honored despite the axon sitecustomize
    assert all(r["backend"] == "cpu" for r in recs), recs

    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "benchmark", "opperf.py"),
         "--ops", "relu,sample_normal", "--mode", "graph", "--json",
         str(out)],
        capture_output=True, text=True, timeout=300, env=env)
    assert res.returncode == 0, res.stderr[-2000:]
    recs = json.loads(out.read_text())
    # random ops are eager-only in graph mode
    assert {r["op"] for r in recs} == {"relu"}
    assert "random ops are eager-only" in res.stdout
