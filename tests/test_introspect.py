"""Performance introspection (observability/introspect.py): per-site
XLA cost/memory registration, donation verification, the MFU/roofline
estimator's null-with-reason contract, graceful degradation on
backends whose analyses return None/partial, profiler windows, and the
bench.py flops_per_step/mfu stamping contract."""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, observability as obs
from mxnet_tpu.gluon import nn
from mxnet_tpu.observability import introspect

TOOLS = os.path.join(os.path.dirname(__file__), os.pardir, "tools")
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()


@pytest.fixture(autouse=True)
def _clean_introspect():
    """Every test starts with introspection off and an empty site
    table, and restores the process defaults."""
    introspect.set_enabled(False)
    introspect.reset()
    obs.set_enabled(False)
    obs.reset()
    yield
    introspect.set_enabled(False)
    introspect.reset()
    obs.set_enabled(False)
    obs.reset()


def _train_steps(n=3, hybridize=True):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8),
            nn.Dense(4, in_units=16))
    net.initialize()
    if hybridize:
        net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=None)
    X, Y = mx.nd.ones((8, 8)), mx.nd.zeros((8,))
    for _ in range(n):
        with autograd.record():
            l = loss_fn(net(X), Y)
        l.backward()
        tr.step(8)
    return net, tr


# ---------------------------------------------------------------------------
# cost/memory registration
# ---------------------------------------------------------------------------

def test_fused_loop_registers_all_sites():
    introspect.set_enabled(True)
    _train_steps()
    sites = set(introspect.costs())
    assert "trainer_fused" in sites
    assert any(s.startswith("cachedop_fwd[") for s in sites)
    assert any(s.startswith("cachedop_bwd[") for s in sites)
    rec = introspect.site_cost("trainer_fused")
    # the XLA CPU backend reports both analyses: every numeric field set
    assert rec["flops"] > 0
    assert rec["bytes_accessed"] > 0
    assert rec["arith_intensity"] == pytest.approx(
        rec["flops"] / rec["bytes_accessed"])
    assert rec["argument_bytes"] > 0 and rec["output_bytes"] > 0
    assert rec["donated"] is True
    # registration happens ONCE per site: the gauge sees the same value
    # and the table stays one row per site over repeated steps
    assert len([s for s in sites if s == "trainer_fused"]) == 1


def test_eager_op_sites_register():
    introspect.set_enabled(True)
    (mx.nd.ones((4, 4)) + mx.nd.ones((4, 4))).asnumpy()
    sites = introspect.costs()
    assert any(s.startswith("op[") for s in sites), sites


def test_disabled_registers_nothing():
    _train_steps()
    assert introspect.costs() == {}


def test_cost_gauges_published_under_telemetry():
    obs.set_enabled(True)
    introspect.set_enabled(True)
    _train_steps()
    assert obs.EXEC_FLOPS.value(site="trainer_fused") > 0
    expo = obs.dump_prometheus()
    assert 'mxtpu_executable_flops{site="trainer_fused"}' in expo
    # each registration also records one introspect.cost trace event
    names = [ev["name"] for ev in obs.tracer().events()]
    assert "introspect.cost" in names


def test_cost_table_renders():
    introspect.set_enabled(True)
    _train_steps()
    table = introspect.cost_table()
    assert "trainer_fused" in table and "GFLOPs" in table
    # and the empty-state message is not an exception either
    introspect.reset()
    assert "no executables registered" in introspect.cost_table()


# ---------------------------------------------------------------------------
# donation verification
# ---------------------------------------------------------------------------

def test_donation_unaliased_warns_once_and_counts(caplog):
    obs.set_enabled(True)
    rec = {"site": "t_fake_site", "donated": True, "alias_bytes": 0}
    import logging

    with caplog.at_level(logging.WARNING, "mxnet_tpu.introspect"):
        introspect._verify_donation(rec)
        introspect._verify_donation(rec)  # second call: silent
    msgs = [r for r in caplog.records if "donation FAILED" in r.message]
    assert len(msgs) == 1
    assert obs.DONATION_UNALIASED_TOTAL.value(site="t_fake_site") == 1


def test_donation_ok_or_unknown_stays_quiet(caplog):
    import logging

    with caplog.at_level(logging.WARNING, "mxnet_tpu.introspect"):
        introspect._verify_donation(
            {"site": "t_ok", "donated": True, "alias_bytes": 128})
        introspect._verify_donation(
            {"site": "t_na", "donated": True, "alias_bytes": None})
        introspect._verify_donation(
            {"site": "t_nodon", "donated": False, "alias_bytes": 0})
    assert not [r for r in caplog.records if "donation" in r.message]


# ---------------------------------------------------------------------------
# graceful degradation: None / partial analyses (satellite)
# ---------------------------------------------------------------------------

class _FakeCompiled:
    def __init__(self, cost, mem=None, raise_cost=False):
        self._cost, self._mem, self._raise = cost, mem, raise_cost

    def cost_analysis(self):
        if self._raise:
            raise NotImplementedError("no cost analysis on this backend")
        return self._cost

    def memory_analysis(self):
        return self._mem


@pytest.mark.parametrize("cost", [
    None, {}, [{}], [], {"flops": 12.0},            # partial dicts
    {"bytes accessed": 8.0}, "not-a-dict",
])
def test_analyze_compiled_survives_partial_cost(cost):
    rec = introspect.analyze_compiled("t_site", _FakeCompiled(cost))
    assert rec["site"] == "t_site"
    assert rec["temp_bytes"] is None  # no memory analysis
    # flops/bytes filled only when the dict had them
    if isinstance(cost, dict) and "flops" in cost:
        assert rec["flops"] == 12.0
    else:
        assert rec["arith_intensity"] is None


def test_analyze_compiled_survives_raising_backend():
    rec = introspect.analyze_compiled(
        "t_site", _FakeCompiled(None, raise_cost=True))
    assert rec["flops"] is None and rec["bytes_accessed"] is None


def test_register_jit_unlowerable_records_error_stub():
    introspect.set_enabled(True)
    rec = introspect.register_jit("t_bad", object(), ())
    assert rec["flops"] is None and "error" in rec
    # the stub registers: the report/table paths see it, nothing raised
    assert "t_bad" in introspect.costs()
    assert "t_bad" in introspect.cost_table()


def test_flops_per_step_null_reasons():
    flops, reason = introspect.flops_per_step()
    assert flops is None and "no executable sites" in reason
    introspect.set_enabled(True)
    introspect.register_jit("t_bad2", object(), ())
    flops, reason = introspect.flops_per_step(sites=["t_bad2"])
    assert flops is None and reason


def test_flops_per_step_sums_fused_sites():
    introspect.set_enabled(True)
    _train_steps()
    flops, reason = introspect.flops_per_step()
    assert reason is None
    rec = introspect.site_cost("trainer_fused")
    assert flops >= rec["flops"]


# ---------------------------------------------------------------------------
# MFU / roofline estimator
# ---------------------------------------------------------------------------

def test_mfu_estimate_null_with_reason_paths():
    est = obs.mfu_estimate("nowhere", 0.01)
    assert est["mfu"] is None and "not registered" in est["reason"]
    introspect._publish({"site": "t_noflops", "flops": None,
                         "donated": False})
    est = obs.mfu_estimate("t_noflops", 0.01)
    assert est["mfu"] is None and est["reason"]
    # CPU backend: achieved computes, mfu null with the peak reason
    introspect._publish({"site": "t_cpu", "flops": 2e9,
                         "bytes_accessed": 1e9, "arith_intensity": 2.0,
                         "peak_tflops": None, "peak_hbm_gbs": None,
                         "peak_reason": "no peak-FLOPs table for device "
                                        "kind 'cpu'", "donated": False})
    est = obs.mfu_estimate("t_cpu", 0.001)
    assert est["achieved_tflops"] == pytest.approx(2.0)
    assert est["mfu"] is None and "peak" in est["reason"]


def test_mfu_estimate_with_peak_tables():
    # a synthetic accelerator record: 100 TFLOP/s peak, 1000 GB/s HBM
    introspect._publish({"site": "t_tpu", "flops": 1e12,
                         "bytes_accessed": 1e10, "arith_intensity": 100.0,
                         "peak_tflops": 100.0, "peak_hbm_gbs": 1000.0,
                         "donated": False})
    est = obs.mfu_estimate("t_tpu", 0.1)  # 10 TFLOP/s achieved
    assert est["achieved_tflops"] == pytest.approx(10.0)
    assert est["mfu"] == pytest.approx(0.1)
    assert est["bound"] == "compute"  # AI 100 >= ridge 100e12/1000e9=100
    introspect._publish({"site": "t_mem", "flops": 1e12,
                         "bytes_accessed": 1e12, "arith_intensity": 1.0,
                         "peak_tflops": 100.0, "peak_hbm_gbs": 1000.0,
                         "donated": False})
    assert obs.mfu_estimate("t_mem", 0.1)["bound"] == "memory"


def test_device_peaks_reason_on_cpu():
    peak, bw, reason = introspect.device_peaks()
    if jax.default_backend() == "cpu":
        assert peak is None and bw is None and "cpu" in reason


# ---------------------------------------------------------------------------
# profiler windows
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("value,expect", [
    ("/tmp/prof", ("/tmp/prof", 1, 10)),
    ("/tmp/prof:5:20", ("/tmp/prof", 5, 20)),
    ("/tmp/pro:f", ("/tmp/pro:f", 1, 10)),       # colon in path, no ints
    ("/tmp/prof:0:0", ("/tmp/prof", 1, 1)),      # clamped to >= 1
])
def test_profile_env_parsing(value, expect):
    assert introspect._parse_profile_env(value) == expect


def test_profile_step_window_state_machine(monkeypatch, tmp_path):
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d, **kw: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    introspect.configure_profile(str(tmp_path), start=3, stop=4)
    assert introspect.PROFILING
    for _ in range(6):
        if introspect.PROFILING:
            with introspect.profile_step():
                pass
    assert calls == [("start", str(tmp_path)), ("stop",)]
    st = introspect.profile_state()
    assert st["done"] and not st["active"]
    assert not introspect.PROFILING  # disarmed after the window closed


def test_profile_step_counts_superstep_k(monkeypatch, tmp_path):
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d, **kw: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"))
    introspect.configure_profile(str(tmp_path), start=5, stop=8)
    with introspect.profile_step(4):   # steps 1-4: before the window
        pass
    assert calls == []
    with introspect.profile_step(4, name="superstep"):  # steps 5-8
        pass
    assert calls == ["start", "stop"]


def test_trainer_step_under_profile_window(monkeypatch, tmp_path):
    """The Trainer.step hook drives the window: armed via
    configure_profile, steps open/close the (stubbed) trace."""
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d, **kw: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"))
    introspect.configure_profile(str(tmp_path), start=1, stop=2)
    try:
        _train_steps(n=4)
        assert calls == ["start", "stop"]
    finally:
        introspect.configure_profile(None)


def test_profile_window_writes_real_trace(tmp_path):
    """End-to-end jax.profiler capture through the public context
    manager (one real trace per test run — start_trace costs seconds)."""
    d = str(tmp_path / "prof")
    try:
        with obs.profile_window(d):
            with introspect.annotate("mxtpu.test_region"):
                jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))
                        ).block_until_ready()
    except Exception as e:  # pragma: no cover - env-specific plugin
        pytest.skip(f"jax profiler unavailable here: {e}")
    files = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert files, "profiler window produced no trace files"


# ---------------------------------------------------------------------------
# report tool roofline + bench stamping contracts
# ---------------------------------------------------------------------------

def _cost_event(site, **args):
    return {"name": "introspect.cost", "cat": "introspect", "ph": "i",
            "ts": 0.0, "dur": 0.0, "pid": 1, "tid": 1,
            "args": dict(site=site, **args)}


def test_report_tool_renders_roofline(tmp_path, capsys):
    sys.path.insert(0, TOOLS)
    try:
        import telemetry_report as tr
    finally:
        sys.path.pop(0)
    events = [
        _cost_event("superstep", flops=8.5e7, bytes_accessed=5e5,
                    arith_intensity=170.0, peak_tflops=100.0,
                    peak_hbm_gbs=1000.0),
        # timing span so achieved TFLOP/s + MFU fill in
        {"name": "trainer.superstep", "cat": "trainer", "ph": "X",
         "ts": 0.0, "dur": 850.0, "pid": 1, "tid": 1,
         "args": {"k": 8}},
        # malformed records must render as '-' rows, never crash
        _cost_event("t_partial"),
        _cost_event("t_strings", flops="oops", peak_tflops="x"),
        {"name": "introspect.cost", "args": None},
    ]
    out = tr.render_roofline(events)
    assert "Executable roofline" in out
    assert "superstep" in out and "compute" in out
    assert "t_partial" in out and "t_strings" in out
    # achieved = 8.5e7 flops / 0.85ms span / 1e12 = 0.1 TFLOP/s
    assert "0.100" in out
    # absent series -> empty string
    assert tr.render_roofline([{"name": "trainer.step"}]) == ""
    # and the CLI path end-to-end
    p = tmp_path / "t.jsonl"
    p.write_text("\n".join(json.dumps(ev) for ev in events) + "\n")
    assert tr.main([str(p)]) == 0
    assert "Executable roofline" in capsys.readouterr().out


def test_bench_rows_always_carry_flops_and_mfu():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    try:
        import bench
    finally:
        sys.path.pop(0)
    bench._EMIT_BUFFER = buf = []
    try:
        # no stamping at all -> explicit nulls + reason
        bench._emit("t_metric_a", 1.0, "u")
        # flops known, mfu unknowable (CPU) -> mfu null + reason
        bench._emit("t_metric_b", 1.0, "u", flops_per_step=123.0)
        # both known -> no reason field
        bench._emit("t_metric_c", 1.0, "u", flops_per_step=123.0, mfu=0.2)
    finally:
        bench._EMIT_BUFFER = None
    recs = [json.loads(ln) for ln in buf]
    for rec in recs:
        assert "flops_per_step" in rec and "mfu" in rec
        if rec["mfu"] is None:
            assert rec["mfu_reason"], rec
    a, b, c = recs
    assert a["flops_per_step"] is None and a["mfu"] is None
    assert b["flops_per_step"] == 123.0 and b["mfu"] is None
    assert c["mfu"] == 0.2 and "mfu_reason" not in c
