"""Detection augmentation + ImageDetIter tests (reference:
``python/mxnet/image/detection.py`` + test_image.py ImageDetIter cases)."""

import random

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img_mod
from mxnet_tpu.image.detection import (CreateDetAugmenter, DetBorrowAug,
                                       DetHorizontalFlipAug, DetRandomCropAug,
                                       DetRandomPadAug, DetRandomSelectAug,
                                       ImageDetIter, _update_labels_crop)
from mxnet_tpu.recordio import IRHeader, MXIndexedRecordIO, pack_img


def _img(h=32, w=32, box=None):
    arr = np.full((h, w, 3), 30, np.uint8)
    if box is not None:
        x0, y0, x1, y1 = (np.array(box) * [w, h, w, h]).astype(int)
        arr[y0:y1, x0:x1] = 220
    return arr


def test_flip_remaps_boxes():
    random.seed(0)
    label = np.array([[0, 0.1, 0.2, 0.4, 0.6]], np.float32)
    src = mx.nd.array(_img(box=label[0, 1:5]))
    aug = DetHorizontalFlipAug(p=1.0)
    out, lab = aug(src, label)
    np.testing.assert_allclose(lab[0, 1:5], [0.6, 0.2, 0.9, 0.6], atol=1e-6)
    # the bright object must have moved to the mirrored location
    arr = out.asnumpy()
    assert arr[10, int(0.7 * 32)].mean() > 150
    assert arr[10, int(0.2 * 32)].mean() < 100


def test_update_labels_crop_clip_and_eject():
    label = np.array([
        [0, 0.0, 0.0, 0.4, 0.4],    # half-inside the crop
        [1, 0.8, 0.8, 0.95, 0.95],  # fully outside -> ejected
        [2, 0.3, 0.3, 0.5, 0.5],    # fully inside
    ], np.float32)
    crop = (0.25, 0.25, 0.75, 0.75)
    out = _update_labels_crop(label, crop, min_eject_coverage=0.1)
    assert list(out[:, 0]) == [0.0, 2.0]
    # the half-inside box clips to the crop origin
    np.testing.assert_allclose(out[0, 1:5], [0, 0, 0.3, 0.3], atol=1e-6)
    # fully-inside box remaps linearly
    np.testing.assert_allclose(out[1, 1:5], [0.1, 0.1, 0.5, 0.5], atol=1e-6)


def test_random_crop_respects_min_object_covered():
    random.seed(1)
    aug = DetRandomCropAug(min_object_covered=0.9, area_range=(0.3, 0.8),
                           min_eject_coverage=0.2, max_attempts=200)
    label = np.array([[0, 0.45, 0.45, 0.55, 0.55]], np.float32)
    src = mx.nd.array(_img(box=label[0, 1:5]))
    for _ in range(10):
        out, lab = aug(src, label)
        if lab.shape[0]:  # crop accepted: the object stayed covered
            w = lab[0, 3] - lab[0, 1]
            h = lab[0, 4] - lab[0, 2]
            assert w > 0 and h > 0
            assert lab[0, 1] >= 0 and lab[0, 4] <= 1


def test_random_pad_scales_boxes_down():
    random.seed(2)
    aug = DetRandomPadAug(area_range=(2.0, 2.5), max_attempts=50)
    label = np.array([[0, 0.25, 0.25, 0.75, 0.75]], np.float32)
    src = mx.nd.array(_img(box=label[0, 1:5]))
    out, lab = aug(src, label)
    # area grew >= 2x, so box area (normalized) must shrink <= 1/2
    area = (lab[0, 3] - lab[0, 1]) * (lab[0, 4] - lab[0, 2])
    assert area <= 0.25 / 2 + 1e-6
    assert out.shape[0] > 32 and out.shape[1] > 32


def test_borrow_and_select():
    random.seed(3)
    label = np.array([[0, 0.1, 0.1, 0.5, 0.5]], np.float32)
    src = mx.nd.array(_img())
    borrow = DetBorrowAug(img_mod.CastAug())
    out, lab = borrow(src, label)
    np.testing.assert_allclose(lab, label)
    sel = DetRandomSelectAug([DetHorizontalFlipAug(1.0)], skip_prob=0.0)
    out, lab = sel(src, label)
    np.testing.assert_allclose(lab[0, 1], 0.5, atol=1e-6)


def _make_det_rec(tmp_path, n=12, size=48):
    """Synthetic detection .rec: one bright rectangle per image."""
    rec_path = str(tmp_path / "det.rec")
    idx_path = str(tmp_path / "det.idx")
    rec = MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    boxes = []
    for i in range(n):
        x0, y0 = rng.uniform(0.05, 0.45, 2)
        x1, y1 = x0 + rng.uniform(0.2, 0.45), y0 + rng.uniform(0.2, 0.45)
        box = np.array([min(x0, 0.95), min(y0, 0.95),
                        min(x1, 0.99), min(y1, 0.99)], np.float32)
        cls = float(rng.randint(0, 2))
        # header: A=2 (header width), B=5 (object width), then the object
        label = np.concatenate([[2, 5], [cls], box]).astype(np.float32)
        arr = _img(size, size, box)
        rec.write_idx(i, pack_img(IRHeader(0, label, i, 0), arr,
                                  quality=95, img_fmt=".png"))
        boxes.append((cls, box))
    rec.close()
    return rec_path, boxes


def test_imagedetiter_from_rec(tmp_path):
    random.seed(4)
    rec_path, boxes = _make_det_rec(tmp_path)
    it = ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                      path_imgrec=rec_path, shuffle=False)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape[0] == 4 and batch.label[0].shape[2] == 5
    lab = batch.label[0].asnumpy()
    for i in range(4):
        cls, box = boxes[i]
        np.testing.assert_allclose(lab[i, 0, 0], cls)
        np.testing.assert_allclose(lab[i, 0, 1:5], box, atol=0.02)
    # two epochs yield the same number of batches
    it.reset()
    assert len(list(it)) == 3


def test_imagedetiter_augmented(tmp_path):
    random.seed(5)
    rec_path, _ = _make_det_rec(tmp_path)
    it = ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                      path_imgrec=rec_path, shuffle=True,
                      rand_crop=0.5, rand_pad=0.5, rand_mirror=True,
                      min_object_covered=0.5)
    for batch in it:
        lab = batch.label[0].asnumpy()
        valid = lab[lab[:, :, 0] >= 0]
        assert valid.size  # augmentation never ejects every object
        assert (valid[:, 1:5] >= -1e-6).all() and (valid[:, 1:5] <= 1 + 1e-6).all()
        assert (valid[:, 3] >= valid[:, 1]).all()
        assert (valid[:, 4] >= valid[:, 2]).all()


def test_sync_label_shape(tmp_path):
    rec_path, _ = _make_det_rec(tmp_path)
    a = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                     path_imgrec=rec_path, label_pad_width=7)
    b = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                     path_imgrec=rec_path)
    a.sync_label_shape(b)
    assert a.provide_label[0][1] == b.provide_label[0][1] == (2, 7, 5)


@pytest.mark.slow
def test_ssd_trains_through_pipeline(tmp_path):
    """VERDICT r3 item 5 done-criterion: SSD trains from a synthetic
    detection .rec via ImageDetIter with augmentation on."""
    random.seed(6)
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.model_zoo.vision.ssd import ssd_tiny, SSDLoss

    rec_path, _ = _make_det_rec(tmp_path, n=8, size=48)
    it = ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                      path_imgrec=rec_path, shuffle=True,
                      rand_crop=0.3, rand_mirror=True,
                      min_object_covered=0.7)
    net = ssd_tiny(classes=2)
    net.initialize(init=mx.initializer.Xavier())
    loss_fn = SSDLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    losses = []
    for epoch in range(6):
        it.reset()
        tot = 0.0
        for batch in it:
            x = batch.data[0] / 255.0
            y = batch.label[0]
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                loss = loss_fn(anchors, cls_preds, box_preds, y)
            loss.backward()
            trainer.step(batch.data[0].shape[0])
            tot += float(loss.asnumpy())
        losses.append(tot)
    assert losses[-1] < losses[0], losses
