"""mx.nd.image op family (reference: src/operator/image/image_random.cc,
resize.cc, crop.cc; python/mxnet/ndarray/image.py)."""

import numpy as np

import mxnet_tpu as mx


def _img(h=8, w=6, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 255, (h, w, 3)).astype(np.uint8)


def test_to_tensor_and_normalize():
    img = _img()
    t = mx.nd.image.to_tensor(mx.nd.array(img))
    assert t.shape == (3, 8, 6)
    np.testing.assert_allclose(t.asnumpy(),
                               img.transpose(2, 0, 1) / 255.0, rtol=1e-6)
    n = mx.nd.image.normalize(t, mean=(0.1, 0.2, 0.3), std=(0.5, 0.5, 0.5))
    want = (img.transpose(2, 0, 1) / 255.0
            - np.array([0.1, 0.2, 0.3])[:, None, None]) / 0.5
    np.testing.assert_allclose(n.asnumpy(), want, rtol=1e-5, atol=1e-6)
    # batched NHWC -> NCHW
    tb = mx.nd.image.to_tensor(mx.nd.array(np.stack([img, img])))
    assert tb.shape == (2, 3, 8, 6)


def test_resize_crop_flip():
    img = _img()
    r = mx.nd.image.resize(mx.nd.array(img), size=(3, 4))  # (w, h)
    assert r.shape == (4, 3, 3)
    # nearest at identity size == input
    same = mx.nd.image.resize(mx.nd.array(img), size=(6, 8), interp=0)
    np.testing.assert_array_equal(same.asnumpy(), img)
    # keep_ratio with int size scales the short side
    kr = mx.nd.image.resize(mx.nd.array(img), size=4, keep_ratio=True)
    assert kr.shape[1] == 4 and kr.shape[0] == round(8 * 4 / 6)
    c = mx.nd.image.crop(mx.nd.array(img), x=1, y=2, width=4, height=5)
    np.testing.assert_array_equal(c.asnumpy(), img[2:7, 1:5])
    np.testing.assert_array_equal(
        mx.nd.image.flip_left_right(mx.nd.array(img)).asnumpy(),
        img[:, ::-1])
    np.testing.assert_array_equal(
        mx.nd.image.flip_top_bottom(mx.nd.array(img)).asnumpy(),
        img[::-1])


def test_color_jitter_family():
    mx.random.seed(7)
    img = mx.nd.array(_img().astype(np.float32))
    # reference contract: f ~ U[min_factor, max_factor]; f=1 is identity
    np.testing.assert_allclose(
        mx.nd.image.random_brightness(img, 1.0, 1.0).asnumpy(),
        img.asnumpy())
    np.testing.assert_allclose(
        mx.nd.image.random_hue(img, 1.0, 1.0).asnumpy(),
        img.asnumpy(), rtol=1e-4, atol=1e-3)
    # a pinned factor scales all channels identically
    b = mx.nd.image.random_brightness(img, 1.5, 1.5).asnumpy()
    src = img.asnumpy()
    nz = src > 1.0
    f = (b[nz] / src[nz]).flat[0]
    np.testing.assert_allclose(b, src * f, rtol=1e-4)
    np.testing.assert_allclose(f, 1.5, rtol=1e-5)
    # pinned contrast factor 1.0 is identity even batched (per-image mean)
    batch = mx.nd.array(np.stack([src, src * 0.1]))
    cb = mx.nd.image.random_contrast(batch, 1.0, 1.0).asnumpy()
    np.testing.assert_allclose(cb, batch.asnumpy(), rtol=1e-5)
    # factor-0 contrast collapses each image to ITS OWN gray mean
    c0 = mx.nd.image.random_contrast(batch, 0.0, 0.0).asnumpy()
    g = batch.asnumpy() @ np.array([0.299, 0.587, 0.114], np.float32)
    m_per = g.reshape(2, -1).mean(axis=1)
    np.testing.assert_allclose(c0[0], np.full_like(c0[0], m_per[0]), rtol=1e-4)
    np.testing.assert_allclose(c0[1], np.full_like(c0[1], m_per[1]), rtol=1e-4)
    # saturation toward gray: factor-0 blend equals the gray image
    j = mx.nd.image.random_color_jitter(img, brightness=0.2, contrast=0.2,
                                        saturation=0.2, hue=0.1)
    assert j.shape == img.shape
    # lighting is a per-channel additive shift
    l = mx.nd.image.adjust_lighting(img, alpha=(0.01, 0.0, 0.0)).asnumpy()
    delta = l - img.asnumpy()
    assert np.allclose(delta, delta[0, 0], atol=1e-4)


def test_image_random_ops_reproducible():
    img = mx.nd.array(_img().astype(np.float32))
    mx.random.seed(42)
    a = mx.nd.image.random_color_jitter(img, brightness=0.4).asnumpy()
    mx.random.seed(42)
    b = mx.nd.image.random_color_jitter(img, brightness=0.4).asnumpy()
    np.testing.assert_allclose(a, b)
