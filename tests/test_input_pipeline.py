"""Input-side fast path (PR4 tentpole): async device prefetch, shape
stabilization (pad/bucket + retrace budget), persistent compile cache +
warmup, and the PrefetchingIter/DataLoader lifecycle fixes."""

import gc
import json
import logging
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, observability as obs
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data import (
    ArrayDataset,
    DataLoader,
    DevicePrefetcher,
    SequenceBucketer,
    pad_batch,
)
from mxnet_tpu.gluon.data.prefetcher import wrap_for_fit

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# DevicePrefetcher
# ---------------------------------------------------------------------------

def test_prefetcher_matches_direct_iteration():
    X = np.random.rand(10, 3).astype(np.float32)
    Y = np.arange(10).astype(np.float32)
    loader = DataLoader(ArrayDataset(X, Y), batch_size=4, last_batch="keep")
    direct = [(x.asnumpy(), y.asnumpy()) for x, y in loader]
    pf = DevicePrefetcher(loader, device=mx.cpu())
    for _ in range(2):  # two epochs through the same wrapper
        got = [(x.asnumpy(), y.asnumpy()) for x, y in pf]
        assert len(got) == len(direct)
        for (dx, dy), (gx, gy) in zip(direct, got):
            np.testing.assert_array_equal(dx, gx)
            np.testing.assert_array_equal(dy, gy)


def test_prefetcher_preserves_structure_and_commits_to_device():
    import jax

    loader = DataLoader(ArrayDataset(np.random.rand(8, 2).astype(np.float32),
                                     np.arange(8).astype(np.float32)),
                        batch_size=4)
    (x, y) = next(iter(DevicePrefetcher(loader, device=mx.cpu())))
    assert isinstance(x, mx.NDArray) and isinstance(y, mx.NDArray)
    assert x.data.devices() == {jax.local_devices()[0]}


def test_prefetcher_propagates_source_error_and_closes():
    def bad():
        yield mx.nd.ones((2, 2))
        raise RuntimeError("boom in source")

    pf = DevicePrefetcher(bad(), device=mx.cpu())
    it = iter(pf)
    next(it)
    with pytest.raises(RuntimeError, match="boom in source"):
        next(it)
    assert pf._thread is None  # closed (thread joined), not leaked
    pf.close()
    pf.close()  # idempotent


def test_prefetcher_close_unblocks_full_queue():
    def endless():
        i = 0
        while True:
            yield np.full((4,), i, np.float32)
            i += 1

    pf = DevicePrefetcher(endless(), device=mx.cpu(), depth=2)
    it = iter(pf)
    next(it)
    time.sleep(0.1)  # let the producer fill + block on the bounded queue
    pf.close()
    assert pf._thread is None


def test_prefetcher_dataiter_protocol():
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    it = mx.io.NDArrayIter(data, np.arange(12, dtype=np.float32),
                           batch_size=4, shuffle=False)
    pf = DevicePrefetcher(it, device=mx.cpu())
    assert pf.batch_size == 4  # attribute passthrough
    assert len(pf.provide_data) == 1
    for _ in range(2):  # epochs: wrapper resets the exhausted source
        batches = list(pf)
        assert len(batches) == 3
        np.testing.assert_array_equal(batches[0].data[0].asnumpy(),
                                      data[:4])


def test_prefetcher_shards_over_mesh():
    import jax

    from mxnet_tpu.parallel import make_mesh, shard_batch

    mesh = make_mesh({"dp": len(jax.devices())})
    src = [[mx.nd.array(np.random.rand(8, 3).astype(np.float32))]
           for _ in range(2)]
    pf = DevicePrefetcher(src, mesh=mesh)
    (batch,), = [b for b in pf][:1]
    assert batch.shape == (8, 3)
    # already-sharded: shard_batch recognizes the placement and returns
    # the SAME array instead of a host round-trip
    again = shard_batch(batch, mesh)
    assert again is batch.data


def test_spmd_step_accepts_presharded_batches():
    """An SPMDTrainStep fed mesh-sharded batches (the DevicePrefetcher
    staging path) must still resolve deferred init — the eager probe
    runs on a host copy, never on the 8-device global array."""
    import jax

    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"dp": len(jax.devices())})
    net = nn.Dense(2, in_units=8)
    net.initialize(init=mx.initializer.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = parallel.SPMDTrainStep(net, loss_fn, "sgd", {}, mesh=mesh)
    rng = np.random.RandomState(0)
    src = [(rng.randn(16, 8).astype(np.float32),
            rng.randint(0, 2, (16,)).astype(np.float32))
           for _ in range(3)]
    losses = [step(x, y, lr=0.1)
              for x, y in DevicePrefetcher(src, mesh=mesh)]
    assert all(np.isfinite(l) for l in losses)


def test_wrap_for_fit_respects_env(monkeypatch):
    src = [1, 2, 3]
    monkeypatch.setenv("MXTPU_DEVICE_PREFETCH", "0")
    assert wrap_for_fit(src) is src
    monkeypatch.setenv("MXTPU_DEVICE_PREFETCH", "3")
    wrapped = wrap_for_fit(src)
    assert isinstance(wrapped, DevicePrefetcher)
    assert wrap_for_fit(wrapped) is wrapped  # never double-wraps
    # a device-enabled DataLoader already prefetches: no second wrapper
    loader = DataLoader(ArrayDataset(np.zeros((4, 2), np.float32),
                                     np.zeros((4,), np.float32)),
                        batch_size=2, device=mx.cpu())
    assert wrap_for_fit(loader) is loader


def test_prefetcher_iter_on_inflight_iterator_loses_nothing():
    """list(it) / enumerate(it) call iter() on the returned iterator
    again — that must NOT restart the epoch (a restart drops whatever
    the producer already staged)."""
    loader = DataLoader(ArrayDataset(np.arange(10, dtype=np.float32),
                                     np.arange(10, dtype=np.float32)),
                        batch_size=4, last_batch="keep", device=mx.cpu())
    it = iter(loader)
    time.sleep(0.1)  # let the producer stage batches ahead
    assert len(list(it)) == 3  # list() re-invokes iter() internally


def test_prefetcher_stays_exhausted_until_reiterated():
    """Iterator protocol: next() after exhaustion keeps raising
    StopIteration (no silent epoch restart / duplicated batches); a new
    iter() or reset() starts the next epoch."""
    pf = DevicePrefetcher(DataLoader(
        ArrayDataset(np.arange(8, dtype=np.float32),
                     np.arange(8, dtype=np.float32)), batch_size=4),
        device=mx.cpu())
    it = iter(pf)
    assert len(list(it)) == 2
    for _ in range(3):
        with pytest.raises(StopIteration):
            next(it)
    assert len(list(iter(pf))) == 2  # explicit re-iteration restarts


def test_prefetcher_telemetry_series():
    prev = obs.set_enabled(True)
    try:
        obs.reset()
        loader = DataLoader(
            ArrayDataset(np.random.rand(8, 4).astype(np.float32),
                         np.arange(8, dtype=np.float32)), batch_size=4)
        list(DevicePrefetcher(loader, device=mx.cpu()))
        assert obs.DATA_PREFETCH_BATCHES.total() == 2
        # X: 8 rows x 4 cols x 4 B; Y: 8 x 4 B — across the 2 batches
        assert obs.DATA_H2D_BYTES.total() == 8 * 4 * 4 + 8 * 4
        assert obs.DATA_H2D_SECONDS.total() == 2
        prom = obs.dump_prometheus()
        assert "mxtpu_data_h2d_bytes_total" in prom
        assert "mxtpu_data_prefetch_wait_seconds_total" in prom
    finally:
        obs.set_enabled(prev)
        obs.reset()


# ---------------------------------------------------------------------------
# DataLoader: device=, last_batch="pad", pin_memory, __del__
# ---------------------------------------------------------------------------

def test_dataloader_device_and_pad_last_batch():
    X = np.random.rand(10, 3).astype(np.float32)
    Y = np.arange(10).astype(np.float32)
    loader = DataLoader(ArrayDataset(X, Y), batch_size=4, last_batch="pad",
                        device=mx.cpu())
    assert len(loader) == 3
    for _ in range(2):
        shapes = [tuple(x.shape) for x, _ in loader]
        assert shapes == [(4, 3)] * 3  # shape-stable epoch
    # the pad rows wrap from the epoch start
    last_y = list(loader)[-1][1].asnumpy()
    np.testing.assert_array_equal(last_y, [8, 9, 0, 1])


def test_dataloader_pad_shorter_than_one_batch():
    loader = DataLoader(ArrayDataset(np.arange(3, dtype=np.float32),
                                     np.arange(3, dtype=np.float32)),
                        batch_size=8, last_batch="pad")
    (x, _), = list(loader)
    assert x.shape == (8,)
    np.testing.assert_array_equal(x.asnumpy(), [0, 1, 2, 0, 1, 2, 0, 1])


def test_dataloader_pin_memory_warns_exactly_once(caplog):
    import mxnet_tpu.gluon.data.dataloader as dl

    prev = dl._PIN_MEMORY_WARNED
    dl._PIN_MEMORY_WARNED = False
    try:
        ds = ArrayDataset(np.zeros((4, 2), np.float32),
                          np.zeros((4,), np.float32))
        with caplog.at_level(logging.WARNING,
                             logger="mxnet_tpu.gluon.data.dataloader"):
            DataLoader(ds, batch_size=2, pin_memory=True)
            DataLoader(ds, batch_size=2, pin_memory=True)
        warns = [r for r in caplog.records if "pin_memory" in r.message]
        assert len(warns) == 1
    finally:
        dl._PIN_MEMORY_WARNED = prev


def test_dataloader_del_robust_when_init_raised():
    with pytest.raises(ValueError):
        DataLoader(ArrayDataset(np.zeros((4, 2), np.float32),
                                np.zeros((4,), np.float32)))  # no batch_size
    obj = DataLoader.__new__(DataLoader)  # __init__ never ran at all
    obj.__del__()  # must not raise
    gc.collect()


# ---------------------------------------------------------------------------
# shape stabilization
# ---------------------------------------------------------------------------

def test_pad_batch_mask_parity_with_discard():
    """A padded final batch + validity mask produces the same loss and
    gradients as discarding the tail (mask correctness)."""
    mx.random.seed(0)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    Xv = mx.nd.array(np.random.RandomState(0).randn(5, 6)
                     .astype(np.float32))
    Yv = mx.nd.array(np.random.RandomState(1).randint(0, 3, (5,))
                     .astype(np.float32))

    def run(padded):
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.Dense(3, in_units=6)
        net.initialize(init=mx.initializer.Xavier())
        if padded:
            (x, y), mask = pad_batch([Xv, Yv], 8)
            with autograd.record():
                l = loss_fn(net(x), y)
                total = (l * mask).sum() / mask.sum()
        else:
            with autograd.record():
                total = loss_fn(net(Xv), Yv).sum() / 5.0
        total.backward()
        return (float(total.asnumpy()),
                net.weight.grad(None).asnumpy().copy(),
                net.bias.grad(None).asnumpy().copy())

    lp, wp, bp = run(True)
    ld, wd, bd = run(False)
    assert lp == pytest.approx(ld, rel=1e-6)
    np.testing.assert_allclose(wp, wd, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(bp, bd, rtol=1e-5, atol=1e-7)


def test_pad_batch_structure_and_errors():
    from mxnet_tpu.base import MXNetError

    d = mx.nd.ones((3, 2))
    (out, mask) = pad_batch(d, 4)
    assert out.shape == (4, 2) and list(mask.asnumpy()) == [1, 1, 1, 0]
    nested, mask = pad_batch([d, [d, d]], 4)
    assert nested[1][0].shape == (4, 2)
    with pytest.raises(MXNetError):
        pad_batch(mx.nd.ones((5, 2)), 4)  # batch larger than target


def test_sequence_bucketer():
    from mxnet_tpu.base import MXNetError

    b = SequenceBucketer([8, 16])
    x, L = b(mx.nd.ones((2, 5)))
    assert x.shape == (2, 8) and L == 5
    assert x.asnumpy()[:, 5:].sum() == 0  # padded with pad_value
    x, L = b(mx.nd.ones((2, 16)))
    assert x.shape == (2, 16) and L == 16
    host, L = b(np.ones((2, 9), np.float32))
    assert host.shape == (2, 16)
    with pytest.raises(MXNetError):
        b(mx.nd.ones((2, 17)))  # longer than the largest bucket
    with pytest.raises(MXNetError):
        SequenceBucketer([])


def test_shape_wobble_budget_flags_loudly(monkeypatch, caplog):
    monkeypatch.setenv("MXTPU_RETRACE_BUDGET", "2")
    prev = obs.set_enabled(True)
    try:
        obs.reset()
        net = nn.Dense(4, in_units=8)
        net.initialize()
        net.hybridize()
        name = net.name
        with caplog.at_level(logging.WARNING, logger="mxnet_tpu.gluon.block"):
            for bsz in (1, 2, 3, 4):
                net(mx.nd.ones((bsz, 8)))
        assert obs.SHAPE_WOBBLE_TOTAL.value(block=name) == 2  # 3rd + 4th
        warns = [r for r in caplog.records if "shape_wobble" in r.message]
        assert len(warns) == 1  # loud but once per block
    finally:
        obs.set_enabled(prev)
        obs.reset()


def test_shape_wobble_budget_disabled(monkeypatch):
    monkeypatch.setenv("MXTPU_RETRACE_BUDGET", "0")
    prev = obs.set_enabled(True)
    try:
        obs.reset()
        net = nn.Dense(4, in_units=8)
        net.initialize()
        net.hybridize()
        for bsz in (1, 2, 3, 4):
            net(mx.nd.ones((bsz, 8)))
        assert obs.SHAPE_WOBBLE_TOTAL.total() == 0
    finally:
        obs.set_enabled(prev)
        obs.reset()


# ---------------------------------------------------------------------------
# warmup
# ---------------------------------------------------------------------------

def _mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=6),
            nn.Dense(3, in_units=8))
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    return net


def _params_in_order(net):
    """Parameters in structural (layer) order — name sorting is unstable
    once the global dense counter reaches double digits."""
    out = []
    for child in net._children.values():
        out.extend(p for _, p in sorted(child._reg_params.items()))
    return out


def test_warmup_precompiles_inference_shapes():
    prev = obs.set_enabled(True)
    try:
        obs.reset()
        net = _mlp()
        assert net.warmup([(4, 6), (8, 6)]) == 2
        compiled = obs.CACHEDOP_COMPILE_TOTAL.total()
        assert compiled >= 2
        with autograd.predict_mode():
            net(mx.nd.ones((4, 6)))
            net(mx.nd.ones((8, 6)))
        assert obs.CACHEDOP_COMPILE_TOTAL.total() == compiled, \
            "warmed shapes must not compile again"
    finally:
        obs.set_enabled(prev)
        obs.reset()


def test_warmup_full_step_restores_training_state():
    net = _mlp()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=None)
    before = {k: p.data().asnumpy().copy()
              for k, p in net.collect_params().items()}
    assert net.warmup([(4, 6), (8, 6)], loss_fn=loss_fn, trainer=tr) == 2
    for k, p in net.collect_params().items():
        np.testing.assert_array_equal(p.data().asnumpy(), before[k])
    assert not tr._optimizer._index_update_count  # update counts restored
    assert not tr._fused_states                   # momentum restored
    # training after warmup matches training without warmup
    X = mx.nd.array(np.random.RandomState(1).randn(4, 6).astype(np.float32))
    Y = mx.nd.array(np.random.RandomState(2).randint(0, 3, (4,))
                    .astype(np.float32))
    for _ in range(3):
        with autograd.record():
            l = loss_fn(net(X), Y)
        l.backward()
        tr.step(4)
    assert tr._fused not in (False, None)

    # a fresh net given the SAME initial weights, trained WITHOUT warmup
    net2 = _mlp()
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        kvstore=None)
    for p1, p2 in zip(_params_in_order(net), _params_in_order(net2)):
        p2.set_data(mx.nd.array(before[p1.name]))
    for _ in range(3):
        with autograd.record():
            l = loss_fn(net2(X), Y)
        l.backward()
        tr2.step(4)
    for p1, p2 in zip(_params_in_order(net), _params_in_order(net2)):
        np.testing.assert_allclose(p1.data().asnumpy(),
                                   p2.data().asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_warmup_accepts_single_shape_forms():
    net = _mlp()
    assert net.warmup((4, 6)) == 1   # bare tuple
    assert net.warmup([4, 6]) == 1   # bare list
    assert net.warmup([[4, 6], (8, 6)]) == 2


def test_warmup_resolves_deferred_init():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))  # deferred shapes
    net.initialize()
    net.hybridize()
    assert net.warmup([(4, 6)]) == 1
    assert net(mx.nd.ones((4, 6))).shape == (4, 2)


# ---------------------------------------------------------------------------
# persistent compile cache
# ---------------------------------------------------------------------------

_CACHE_SNIPPET = """
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {root!r})
import mxnet_tpu as mx
from mxnet_tpu import observability as obs
from mxnet_tpu.gluon import nn
net = nn.Dense(4, in_units=8)
net.initialize()
net.hybridize()
net(mx.nd.ones((2, 8)))
import json
print(json.dumps({{"hits": int(obs.COMPILE_CACHE_HITS.total()),
                   "misses": int(obs.COMPILE_CACHE_MISSES.total()),
                   "dir": __import__("mxnet_tpu.runtime", fromlist=["x"])
                          .compile_cache_dir()}}))
"""


def test_compile_cache_cold_then_warm(tmp_path):
    """MXTPU_COMPILE_CACHE: run 1 populates the cache (misses), run 2
    compiles NOTHING (zero misses, all hits) — restart cost is tracing
    only."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["MXTPU_COMPILE_CACHE"] = str(tmp_path / "cc")

    def run():
        res = subprocess.run(
            [sys.executable, "-c", _CACHE_SNIPPET.format(root=ROOT)],
            env=env, capture_output=True, text=True, timeout=240)
        assert res.returncode == 0, res.stderr[-2000:]
        return json.loads(res.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["misses"] > 0
    assert cold["dir"] == str(tmp_path / "cc")
    assert os.listdir(str(tmp_path / "cc"))
    warm = run()
    assert warm["misses"] == 0, warm
    assert warm["hits"] > 0


# ---------------------------------------------------------------------------
# PrefetchingIter lifecycle (io/io.py)
# ---------------------------------------------------------------------------

class _BoomIter(mx.io.DataIter):
    def __init__(self, good_batches=1):
        super().__init__(2)
        self._n = 0
        self._good = good_batches
        self.provide_data = [mx.io.DataDesc("data", (2, 2))]
        self.provide_label = [mx.io.DataDesc("softmax_label", (2,))]

    def reset(self):
        self._n = 0

    def next(self):
        self._n += 1
        if self._n > self._good:
            raise ValueError("decode failed")
        return mx.io.DataBatch(data=[mx.nd.ones((2, 2))],
                               label=[mx.nd.ones((2,))], pad=0)


def test_prefetching_iter_propagates_worker_exception():
    it = mx.io.PrefetchingIter(_BoomIter(good_batches=1))
    it.next()
    with pytest.raises(ValueError, match="decode failed"):
        it.next()
    # threads are shut down and JOINED, not leaked
    for t in it.prefetch_threads:
        t.join(timeout=5.0)
        assert not t.is_alive()


def test_prefetching_iter_close_idempotent():
    inner = mx.io.NDArrayIter(np.zeros((6, 2), np.float32),
                              np.zeros((6,), np.float32), batch_size=2)
    it = mx.io.PrefetchingIter(inner)
    assert it.next() is not None
    it.close()
    it.close()
    for t in it.prefetch_threads:
        assert not t.is_alive()


def test_prefetching_iter_normal_epoch_still_works():
    inner = mx.io.NDArrayIter(np.arange(12, dtype=np.float32).reshape(6, 2),
                              np.arange(6, dtype=np.float32), batch_size=2)
    it = mx.io.PrefetchingIter(inner)
    n = sum(1 for _ in it)
    assert n == 3
    it.reset()
    assert sum(1 for _ in it) == 3
    it.close()
