"""Anomaly watchdog (observability.watchdog): one test per detector —
chaos-seeded NaN through a REAL superstep, loss spike, grad explosion,
step-time regression, serving queue saturation — plus the firing
side-effects (typed counter, trace instant, opt-in proactive
checkpoint) and the poll/daemon cadence plumbing.

The watchdog is detection-only: every test also pins that it consumed
series the hot paths ALREADY emit (nothing here adds instrumentation
to the training step)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, observability as obs
from mxnet_tpu.gluon import nn
from mxnet_tpu.observability import watchdog as wd
from mxnet_tpu.resilience import chaos


@pytest.fixture(autouse=True)
def _watchdog_state(monkeypatch):
    """Armed watchdog over a clean registry; no cadence gate (tests
    drive ``check_now`` directly) and no chaos leakage."""
    monkeypatch.setenv("MXTPU_WATCHDOG_INTERVAL_S", "0")
    obs.set_enabled(True)
    obs.reset()
    wd.stop()
    wd.reset()
    wd.set_enabled(True)
    yield
    chaos.reset()
    wd.stop()
    wd.set_enabled(False)
    wd.reset()
    wd.attach_checkpoint_manager(None)
    obs.set_enabled(False)
    obs.reset()


def _anomaly_events(kind):
    return [e for e in obs.tracer().events()
            if e.get("name") == "anomaly"
            and e.get("args", {}).get("kind") == kind]


def _mark():
    obs.tracer().mark_step()


# ---------------------------------------------------------------------------
# nan detector — end-to-end through a chaos-poisoned superstep
# ---------------------------------------------------------------------------

def test_chaos_nan_fires_exactly_once():
    """Chaos seeds ONE NaN into a real K-step superstep; the watchdog
    fires ``mxtpu_anomaly_total{kind="nan"}`` exactly once for it —
    re-sweeping the same (stale) series must not re-fire."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8),
            nn.Dense(4, in_units=16))
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    sstep = gluon.Superstep(net, loss_fn, tr, k=2)

    from mxnet_tpu.gluon.data.prefetcher import stack_batches

    rng = np.random.default_rng(0)
    X = rng.standard_normal((8, 8)).astype(np.float32)
    Y = np.zeros((8,), np.float32)
    xs = stack_batches([mx.nd.array(X)] * 2)
    ys = stack_batches([mx.nd.array(Y)] * 2)

    sstep.step(xs, ys, 8)           # warm, clean
    assert wd.check_now() == []     # nothing anomalous yet

    chaos.configure("nan@superstep:1")
    sstep.step(xs, ys, 8)           # poisoned dispatch
    # the trainer-cadence poll() INSIDE the superstep already swept the
    # fresh series (interval=0 in this fixture) — the firing needs no
    # test intervention, and manual re-sweeps of the same stale series
    # stay latched
    assert obs.ANOMALY_TOTAL.value(kind="nan") == 1.0
    assert wd.check_now() == []
    assert wd.check_now() == []
    assert obs.ANOMALY_TOTAL.value(kind="nan") == 1.0
    ev = _anomaly_events("nan")
    assert len(ev) == 1 and ev[0]["args"]["source"] == "loss"


def test_nan_from_grad_norm_gauge():
    obs.TRAINER_GRAD_NORM.set(float("inf"))
    _mark()
    assert wd.check_now() == ["nan"]
    assert _anomaly_events("nan")[0]["args"]["source"] == "grad_norm"


# ---------------------------------------------------------------------------
# median-window detectors
# ---------------------------------------------------------------------------

def test_loss_spike_detector():
    for i in range(4):                       # grow the trailing window
        obs.SUPERSTEP_ITER_LOSS.set_series([1.0, 1.1, 0.9])
        _mark()
        assert wd.check_now() == []
    obs.SUPERSTEP_ITER_LOSS.set_series([55.0])   # >10x the median
    _mark()
    assert wd.check_now() == ["loss_spike"]
    args = _anomaly_events("loss_spike")[0]["args"]
    assert args["peak"] == 55.0 and 0.5 < args["median"] < 2.0


def test_grad_explosion_detector():
    for i in range(12):                      # arm the trailing window
        obs.TRAINER_GRAD_NORM.set(1.0 + 0.01 * i)
        _mark()
        assert wd.check_now() == []
    obs.TRAINER_GRAD_NORM.set(99.0)          # >25x the median
    _mark()
    assert wd.check_now() == ["grad_explosion"]
    assert obs.ANOMALY_TOTAL.value(kind="grad_explosion") == 1.0


def test_step_time_regression_detector():
    for _ in range(10):                      # warmup baseline: 10ms
        obs.TRAINER_STEP_SECONDS.observe(0.01)
    assert wd.check_now() == []              # absorbed into the baseline
    obs.TRAINER_STEP_SECONDS.observe(0.2)    # 20x regression
    assert wd.check_now() == ["step_time"]
    args = _anomaly_events("step_time")[0]["args"]
    assert args["recent_mean_s"] == pytest.approx(0.2)
    assert args["baseline_s"] == pytest.approx(0.01)
    # back to normal: no firing
    obs.TRAINER_STEP_SECONDS.observe(0.011)
    assert wd.check_now() == []


def test_queue_saturation_latches_per_model():
    from mxnet_tpu.serving.engine import serve_queue_cap

    cap = serve_queue_cap()
    obs.SERVE_QUEUE_DEPTH.set(int(cap * 0.95), model="m")
    assert wd.check_now() == ["queue_saturation"]
    # still saturated: latched, no alarm storm
    assert wd.check_now() == []
    # drains below half: unlatches quietly
    obs.SERVE_QUEUE_DEPTH.set(int(cap * 0.25), model="m")
    assert wd.check_now() == []
    # saturates again: a NEW firing
    obs.SERVE_QUEUE_DEPTH.set(int(cap * 0.95), model="m")
    assert wd.check_now() == ["queue_saturation"]
    assert obs.ANOMALY_TOTAL.value(kind="queue_saturation") == 2.0


# ---------------------------------------------------------------------------
# firing side-effects
# ---------------------------------------------------------------------------

class _FakeMgr:
    def __init__(self):
        self.calls = []

    def save_async(self, reason=None):
        self.calls.append(reason)


def test_proactive_checkpoint_opt_in(monkeypatch):
    mgr = _FakeMgr()
    wd.attach_checkpoint_manager(mgr)
    # default: detection only — no save requested
    obs.SUPERSTEP_ITER_LOSS.set_series([float("nan")])
    _mark()
    assert "nan" in wd.check_now()
    assert mgr.calls == []
    # opt-in: the recovery point moves before the job dies
    monkeypatch.setenv("MXTPU_WATCHDOG_CHECKPOINT", "1")
    obs.SUPERSTEP_ITER_LOSS.set_series([float("nan")])
    _mark()
    assert "nan" in wd.check_now()
    assert mgr.calls == ["anomaly"]


def test_reset_clears_checkpoint_wiring(monkeypatch):
    """reset() restores WIRING too: a stale CheckpointManager from a
    previous trainer must not keep receiving proactive saves, and the
    flight-note flag re-arms for a fresh registration."""
    mgr = _FakeMgr()
    wd.attach_checkpoint_manager(mgr)
    wd.reset()
    assert wd._STATE["ckpt_mgr"] is None
    assert wd._STATE["note_registered"] is False
    monkeypatch.setenv("MXTPU_WATCHDOG_CHECKPOINT", "1")
    obs.SUPERSTEP_ITER_LOSS.set_series([float("nan")])
    _mark()
    assert "nan" in wd.check_now()
    assert mgr.calls == []  # the detached manager saw nothing


def test_real_checkpoint_manager_attach_wires_watchdog(tmp_path,
                                                       monkeypatch):
    """CheckpointManager.attach hands itself to the armed watchdog; a
    NaN firing with MXTPU_WATCHDOG_CHECKPOINT=1 produces a real async
    save request (the PR-8 manager records it)."""
    from mxnet_tpu.resilience.checkpoint import CheckpointManager

    monkeypatch.setenv("MXTPU_WATCHDOG_CHECKPOINT", "1")
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=4))
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    mgr = CheckpointManager(str(tmp_path), net=net, trainer=tr,
                            keep=2).attach()
    try:
        assert wd._STATE["ckpt_mgr"] is mgr  # attach() wired us in
        obs.SUPERSTEP_ITER_LOSS.set_series([float("nan")])
        _mark()
        assert "nan" in wd.check_now()
        mgr.flush()
        assert mgr.last_saved is not None    # proactive save landed
    finally:
        mgr.close()


# ---------------------------------------------------------------------------
# cadence plumbing
# ---------------------------------------------------------------------------

def test_poll_respects_enabled_switch():
    obs.TRAINER_GRAD_NORM.set(float("nan"))
    _mark()
    wd.set_enabled(False)
    assert wd.poll() == []                   # disarmed: free no-op
    wd.set_enabled(True)
    assert wd.poll() == ["nan"]              # armed: detectors run


def test_poll_interval_gate(monkeypatch):
    assert wd.poll() == []                   # clean sweep stamps the clock
    monkeypatch.setenv("MXTPU_WATCHDOG_INTERVAL_S", "3600")
    obs.TRAINER_GRAD_NORM.set(float("nan"))
    _mark()
    assert wd.poll() == []                   # inside the window: gated
    monkeypatch.setenv("MXTPU_WATCHDOG_INTERVAL_S", "0")
    assert wd.poll() == ["nan"]


def test_daemon_thread_idempotent_start_stop():
    assert wd.start(interval=0.01) is True
    assert wd.start(interval=0.01) is False  # already running
    wd.stop()
    wd.stop()                                # idempotent
    assert wd.start(interval=0.01) is True   # restartable after stop
    wd.stop()
