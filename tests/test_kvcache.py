"""Paged KV cache (``mxnet_tpu.serving.kvcache``): the block-table
allocator (free list + refcounts, typed OOM, fork/copy-on-write) and
the pure in-graph paging helpers the decode model compiles against
(null-block routing for inactive slots / pad positions, scatter +
gather round-trips through the table indirection)."""

import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu import observability as obs
from mxnet_tpu.serving import BlockTable, KVCacheOOM, PagedKVCache
from mxnet_tpu.serving.kvcache import (
    paged_gather,
    paged_prefill_write,
    paged_write,
    slot_coords,
)


@pytest.fixture(autouse=True)
def _telemetry_state():
    obs.set_enabled(False)
    obs.reset()
    yield
    obs.set_enabled(False)
    obs.reset()


def _cache(num_blocks=16, block_size=4, layers=2, kv_heads=2, head_dim=3,
           max_seq=32):
    return PagedKVCache(layers, kv_heads, head_dim, max_seq=max_seq,
                        num_blocks=num_blocks, block_size=block_size)


# ---------------------------------------------------------------------------
# allocator
# ---------------------------------------------------------------------------

def test_allocate_release_round_trip():
    c = _cache()
    assert c.blocks_used() == 0
    t = c.allocate(10)  # 3 blocks of 4
    assert len(t.blocks) == 3
    assert c.blocks_used() == 3
    assert 0 not in t.blocks  # the null block is never handed out
    c.release(t)
    assert c.blocks_used() == 0
    assert t.blocks == [] and t.length == 0
    c.release(t)  # idempotent
    assert c.blocks_used() == 0


def test_zero_token_allocation_is_empty():
    c = _cache()
    t = c.allocate(0)
    assert t.blocks == []
    c.release(t)


def test_oom_is_typed_and_non_destructive():
    c = _cache(num_blocks=4)  # 3 usable
    t = c.allocate(12)
    with pytest.raises(KVCacheOOM, match="exhausted"):
        c.allocate(1)
    # the failed take mutated nothing: the held table still frees fully
    c.release(t)
    assert c.blocks_free() == 3
    t2 = c.allocate(12)
    c.release(t2)


def test_ensure_grows_in_place():
    c = _cache()
    t = c.allocate(4)  # exactly 1 block
    t.length = 4
    c.ensure(t, 5)
    assert len(t.blocks) == 2
    c.ensure(t, 5)  # already covered: no growth
    assert len(t.blocks) == 2
    c.release(t)
    assert c.blocks_used() == 0


def test_fork_is_free_until_divergence():
    c = _cache()
    t = c.allocate(6)  # 2 blocks, second one partial (len 6, bs 4)
    t.length = 6
    used = c.blocks_used()
    f = c.fork(t)
    assert c.blocks_used() == used  # refcount bump only
    assert f.blocks == t.blocks and f is not t
    assert c.forks == 1
    # release one holder: blocks stay (the other still references them)
    c.release(f)
    assert c.blocks_used() == used
    c.release(t)
    assert c.blocks_used() == 0


def test_fork_copy_on_write_copies_exactly_one_block():
    c = _cache()
    t = c.allocate(6)
    t.length = 6
    f = c.fork(t)
    used = c.blocks_used()
    shared_tail = t.blocks[-1]
    # the WRITER appending into the shared partial block gets a private
    # copy of that one block; the reader keeps the original
    c.ensure(f, 7)
    assert c.cow_copies == 1
    assert c.blocks_used() == used + 1
    assert f.blocks[-1] != shared_tail
    assert t.blocks[-1] == shared_tail
    assert f.blocks[:-1] == t.blocks[:-1]  # full blocks still shared
    # appending at a block boundary is NOT a divergence (no shared
    # partial block to split) — plain growth
    c.release(f)
    f2 = c.fork(t)
    f2.length = t.length = 8
    c.ensure(f2, 9)
    assert c.cow_copies == 1  # unchanged
    c.release(f2)
    c.release(t)
    assert c.blocks_used() == 0


def test_fork_free_round_trip_interleaved():
    """Fork chains release in arbitrary order without leaking or
    double-freeing blocks."""
    c = _cache(num_blocks=32)
    t = c.allocate(10)
    t.length = 10
    forks = [c.fork(t) for _ in range(3)]
    c.release(t)                      # parent first
    assert c.blocks_used() == 3      # children keep the blocks alive
    c.ensure(forks[0], 11)            # COW under surviving forks
    for f in forks:
        c.release(f)
    assert c.blocks_used() == 0
    assert c.blocks_free() == 31
    # every block is reusable after the churn
    t2 = c.allocate(31 * 4)
    assert len(t2.blocks) == 31
    c.release(t2)


def test_occupancy_accounting_and_gauges():
    c = _cache(num_blocks=11)  # 10 usable
    obs.set_enabled(True)
    t = c.allocate(20)  # 5 blocks
    assert c.occupancy() == pytest.approx(0.5)
    assert c.stats()["blocks_used"] == 5
    assert obs.KVCACHE_BLOCKS_USED.value(model=c.name) == 5
    assert obs.KVCACHE_OCCUPANCY.value(model=c.name) == pytest.approx(0.5)
    assert c.can_allocate(20) and not c.can_allocate(21)
    c.release(t)
    assert obs.KVCACHE_BLOCKS_USED.value(model=c.name) == 0


def test_oom_counter_increments():
    c = _cache(num_blocks=3)
    obs.set_enabled(True)
    t = c.allocate(8)
    with pytest.raises(KVCacheOOM):
        c.allocate(4)
    assert obs.KVCACHE_OOM_TOTAL.value(model=c.name) == 1
    c.release(t)


def test_block_table_device_row_pads_with_null():
    t = BlockTable([5, 9, 2], 0)
    row = t.device_row(6)
    assert row.dtype == np.int32
    assert row.tolist() == [5, 9, 2, 0, 0, 0]


# ---------------------------------------------------------------------------
# pure in-graph helpers (the decode model compiles these)
# ---------------------------------------------------------------------------

def test_slot_coords_routes_inactive_to_null_block():
    tables = np.array([[3, 7], [4, 6]], np.int32)
    pos = np.array([5, 1], np.int32)
    blk, off = slot_coords(tables, pos, 4,
                           active=np.array([True, False]))
    blk, off = np.asarray(blk), np.asarray(off)
    assert blk.tolist() == [7, 0]  # slot 1 inactive -> null sink
    assert off.tolist() == [1, 1]
    blk2, _ = slot_coords(tables, pos, 4)  # no mask: all live
    assert np.asarray(blk2).tolist() == [7, 4]


def test_paged_write_then_gather_round_trip():
    bs, kvh, d = 4, 2, 3
    pool = jnp.zeros((8, bs, kvh, d), jnp.float32)
    tables = np.array([[2, 5], [3, 0]], np.int32)
    vals = np.arange(2 * kvh * d, dtype=np.float32).reshape(2, kvh, d)
    blk, off = slot_coords(tables, np.array([5, 2], np.int32), bs)
    pool = np.asarray(paged_write(pool, blk, off, vals))
    # slot 0 pos 5 -> table[0][1]=5, offset 1; slot 1 pos 2 -> blk 3
    assert np.array_equal(pool[5, 1], vals[0])
    assert np.array_equal(pool[3, 2], vals[1])
    gathered = np.asarray(paged_gather(pool, tables))
    assert gathered.shape == (2, 2 * bs, kvh, d)
    assert np.array_equal(gathered[0, 5], vals[0])
    assert np.array_equal(gathered[1, 2], vals[1])


def test_paged_prefill_write_masks_pad_positions():
    bs, kvh, d = 4, 1, 2
    pool = jnp.zeros((6, bs, kvh, d), jnp.float32)
    table_row = np.array([2, 4], np.int32)
    vals = np.ones((8, kvh, d), np.float32)  # padded prompt of bucket 8
    pool = np.asarray(paged_prefill_write(pool, table_row, 5, vals))
    # 5 real positions land through the table...
    assert pool[2].sum() == 4 * kvh * d
    assert pool[4, 0].sum() == kvh * d
    assert pool[4, 1:].sum() == 0.0
    # ...and the 3 pad positions hit ONLY the null sink (block 0)
    assert pool[[1, 3, 5]].sum() == 0.0


def test_null_block_absorbs_inactive_writes():
    """An inactive slot's write lands in block 0 and paged_gather of a
    real table never reads it back."""
    bs, kvh, d = 2, 1, 2
    pool = jnp.zeros((4, bs, kvh, d), jnp.float32)
    tables = np.array([[1], [2]], np.int32)
    blk, off = slot_coords(tables, np.array([0, 0], np.int32), bs,
                           active=np.array([True, False]))
    vals = np.full((2, kvh, d), 7.0, np.float32)
    pool = np.asarray(paged_write(pool, blk, off, vals))
    assert pool[1, 0].sum() == kvh * d * 7.0   # the live slot's write
    assert pool[2].sum() == 0.0                # inactive slot's block clean
    assert pool[0, 0].sum() == kvh * d * 7.0   # absorbed by the sink
    got = np.asarray(paged_gather(pool, tables))
    assert got[1].sum() == 0.0  # the sink never leaks into a real read


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def test_env_knob_defaults_and_floors(monkeypatch):
    from mxnet_tpu.serving import kvcache_block_size, kvcache_blocks

    monkeypatch.delenv("MXTPU_KVCACHE_BLOCKS", raising=False)
    monkeypatch.delenv("MXTPU_KVCACHE_BLOCK_SIZE", raising=False)
    assert kvcache_blocks() == 512
    assert kvcache_block_size() == 16
    monkeypatch.setenv("MXTPU_KVCACHE_BLOCKS", "1")
    assert kvcache_blocks() == 2  # block 0 is the sink: need >= 1 usable
    monkeypatch.setenv("MXTPU_KVCACHE_BLOCKS", "64")
    monkeypatch.setenv("MXTPU_KVCACHE_BLOCK_SIZE", "8")
    c = PagedKVCache(1, 1, 2, max_seq=32)
    assert c.num_blocks == 64 and c.block_size == 8
    assert c.max_blocks_per_seq == 4
