"""Contrib ops / control flow / custom op / AMP tests."""

import collections

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.test_utils import assert_almost_equal


def test_box_iou():
    a = mx.nd.array([[0.0, 0.0, 1.0, 1.0]])
    b = mx.nd.array([[0.5, 0.5, 1.5, 1.5], [2.0, 2.0, 3.0, 3.0]])
    iou = mx.nd.contrib.box_iou(a, b)
    assert_almost_equal(iou, np.array([[0.25 / 1.75, 0.0]], np.float32),
                        rtol=1e-4)


def test_box_nms_suppression():
    dets = mx.nd.array([[[0, 0.9, 0.1, 0.1, 0.5, 0.5],
                         [0, 0.8, 0.12, 0.12, 0.52, 0.52],
                         [0, 0.7, 0.6, 0.6, 0.9, 0.9]]])
    out = mx.nd.contrib.box_nms(dets, overlap_thresh=0.5).asnumpy()[0]
    assert out[0][1] == pytest.approx(0.9)      # best kept
    assert (out[1] == -1).all()                 # overlapping suppressed
    assert out[2][1] == pytest.approx(0.7)      # distant kept


def test_multibox_prior():
    x = mx.nd.zeros((1, 3, 4, 4))
    anchors = mx.nd.contrib.MultiBoxPrior(x, sizes=(0.5, 0.25),
                                          ratios=(1, 2))
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors.asnumpy()
    assert (a[..., 2] >= a[..., 0]).all() and (a[..., 3] >= a[..., 1]).all()


def test_roi_align():
    data = mx.nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    rois = mx.nd.array([[0, 0, 0, 3, 3]])
    out = mx.nd.contrib.ROIAlign(data, rois, pooled_size=(2, 2),
                                 spatial_scale=1.0)
    assert out.shape == (1, 1, 2, 2)
    o = out.asnumpy()
    assert o[0, 0, 0, 0] < o[0, 0, 1, 1]  # increasing ramp preserved


def test_bilinear_resize():
    x = mx.nd.array(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    out = mx.nd.contrib.BilinearResize2D(x, height=4, width=4)
    assert out.shape == (1, 1, 4, 4)


def test_adaptive_avg_pool():
    x = mx.nd.random.normal(shape=(2, 3, 8, 8))
    out = mx.nd.contrib.AdaptiveAvgPooling2D(x, output_size=(2, 2))
    assert out.shape == (2, 3, 2, 2)
    assert_almost_equal(out,
                        x.asnumpy().reshape(2, 3, 2, 4, 2, 4).mean((3, 5)),
                        rtol=1e-5)


def test_foreach_eager_and_hybrid():
    def body(item, state):
        return item * 2 + state, state + 1

    data = mx.nd.array([1.0, 2.0, 3.0])
    out, final = mx.nd.contrib.foreach(body, data, mx.nd.array([0.0]))
    assert_almost_equal(out, np.array([[2], [5], [8]], np.float32))
    assert_almost_equal(final, np.array([3.0], np.float32))

    class ScanBlock(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            out, _ = mx.nd.contrib.foreach(body, x, mx.nd.zeros((1,)))
            return out

    blk = ScanBlock()
    blk.initialize()
    eager = blk(data).asnumpy()
    blk.hybridize()
    hybrid = blk(data).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-6)


def test_while_loop():
    def cond(i, s):
        return i < 5

    def func(i, s):
        return (s, (i + 1, s + i))

    outs, (i, s) = mx.nd.contrib.while_loop(
        cond, func, (mx.nd.array([0.0]), mx.nd.array([0.0])),
        max_iterations=10)
    assert float(i.asscalar()) == 5
    assert float(s.asscalar()) == 10  # 0+1+2+3+4


def test_cond():
    t = mx.nd.contrib.cond(lambda: mx.nd.array([1.0]),
                           lambda: mx.nd.array([7.0]),
                           lambda: mx.nd.array([9.0]))
    assert float(t.asscalar()) == 7.0


def test_custom_op_grad():
    import mxnet_tpu.operator as operator

    @operator.register("sq_custom")
    class SquareProp(operator.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            class Op(operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0] * in_data[0])

                def backward(self, req, out_grad, in_data, out_data, in_grad,
                             aux):
                    self.assign(in_grad[0], req[0],
                                2 * in_data[0] * out_grad[0])

            return Op()

    x = mx.nd.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="sq_custom")
    y.backward()
    assert_almost_equal(y, np.array([4.0, 9.0], np.float32))
    assert_almost_equal(x.grad, np.array([4.0, 6.0], np.float32))


def test_np_namespace():
    a = mx.np.array([[1.0, 2.0], [3.0, 4.0]])
    assert isinstance(a, mx.NDArray)
    assert_almost_equal(mx.np.mean(a), np.float32(2.5))
    assert_almost_equal(mx.np.linalg.norm(a), np.linalg.norm([[1, 2], [3, 4]]),
                        rtol=1e-5)
    assert mx.np.arange(5).shape == (5,)
    u, s, vt = mx.np.linalg.svd(a)
    assert s.shape == (2,)
    r = mx.np.random.rand(3, 2)
    assert r.shape == (3, 2)


def test_npx():
    out = mx.npx.softmax(mx.nd.array([[1.0, 2.0, 3.0]]))
    assert out.shape == (1, 3)
    assert_almost_equal(out.sum(), np.float32(1.0), rtol=1e-5)


def test_amp_bf16():
    mx.amp._STATE["target_dtype"] = None
    mx.amp.init(target_dtype="bfloat16")
    assert mx.amp.is_enabled()
    net = gluon.nn.Dense(4, in_units=3)
    net.initialize()
    mx.amp.convert_model(net)
    assert str(net.weight.data().dtype) == "bfloat16"
    out = net(mx.nd.ones((2, 3)).astype("bfloat16"))
    assert str(out.dtype) == "bfloat16"
    mx.amp._STATE["target_dtype"] = None


def test_amp_fp16_loss_scaler():
    scaler = mx.amp.LossScaler(init_scale=4.0, scale_factor=2.0,
                               scale_window=2)
    scaler.update_scale(True)
    assert scaler.loss_scale == 2.0
    scaler.update_scale(False)
    scaler.update_scale(False)
    assert scaler.loss_scale == 4.0


def test_gradientmultiplier():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.contrib.gradientmultiplier(x, scalar=3.0).sum()
    y.backward()
    assert_almost_equal(x.grad, np.array([3.0, 3.0], np.float32))


def test_quantize_2bit():
    g = mx.nd.array([0.7, -0.2, -0.9, 0.1])
    r = mx.nd.zeros((4,))
    q, new_r = mx.nd.contrib.quantize_2bit(g, r, threshold=0.5)
    assert_almost_equal(q, np.array([0.5, 0.0, -0.5, 0.0], np.float32))
    assert_almost_equal(new_r, np.array([0.2, -0.2, -0.4, 0.1], np.float32))


def test_interleaved_selfatt():
    T, N, H, D = 4, 2, 2, 8
    qkv = mx.nd.random.normal(shape=(T, N, 3 * H * D))
    att = mx.nd.contrib.interleaved_matmul_selfatt_qk(qkv, heads=H)
    assert att.shape == (N * H, T, T)
    probs = mx.nd.softmax(att, axis=-1)
    out = mx.nd.contrib.interleaved_matmul_selfatt_valatt(qkv, probs, heads=H)
    assert out.shape == (T, N, H * D)


def test_text_vocabulary():
    import collections

    from mxnet_tpu.contrib import text

    counter = text.count_tokens_from_str("a b b c c c\nd d d d", to_lower=True)
    assert counter["c"] == 3 and counter["d"] == 4
    vocab = text.Vocabulary(counter, most_freq_count=None, min_freq=2,
                            reserved_tokens=["<pad>"])
    # <unk>, <pad>, then by frequency desc: d, c, b ('a' dropped: freq 1)
    assert vocab.idx_to_token == ["<unk>", "<pad>", "d", "c", "b"]
    assert vocab.to_indices(["d", "zzz"]) == [2, 0]
    assert vocab.to_tokens([2, 0]) == ["d", "<unk>"]
    assert len(vocab) == 5

    capped = text.Vocabulary(counter, most_freq_count=2)
    assert len(capped) == 3  # <unk> + the 2 most frequent corpus tokens
    assert capped.idx_to_token == ["<unk>", "d", "c"]


def test_text_custom_embedding(tmp_path):
    import numpy as np

    from mxnet_tpu.contrib import text

    p = tmp_path / "vecs.txt"
    p.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
    emb = text.CustomEmbedding(str(p))
    assert emb.vec_len == 3
    v = emb.get_vecs_by_tokens("world").asnumpy()
    np.testing.assert_allclose(v, [4.0, 5.0, 6.0])
    unk = emb.get_vecs_by_tokens("nope").asnumpy()
    np.testing.assert_allclose(unk, [0.0, 0.0, 0.0])

    emb.update_token_vectors("hello", mx.nd.array(
        np.array([9.0, 9.0, 9.0], np.float32)))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [9.0, 9.0, 9.0])

    # composite over a vocabulary
    vocab = text.Vocabulary(collections.Counter(["hello", "world"]))
    comp = text.CompositeEmbedding(vocab, [emb, emb])
    assert comp.vec_len == 6
    vv = comp.get_vecs_by_tokens("world").asnumpy()
    np.testing.assert_allclose(vv, [4., 5., 6., 4., 5., 6.])


def test_text_pretrained_gated():
    import pytest as _pytest

    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.contrib import text

    names = text.get_pretrained_file_names("glove")
    assert "glove.6B.300d.txt" in names
    with _pytest.raises(MXNetError):
        text.GloVe("glove.6B.50d.txt")


def test_amp_loss_scaler_dynamics():
    """LossScaler (reference: contrib/amp loss_scaler.py): overflow
    detection via all_finite, halving on overflow, growth after a
    stable window."""
    from mxnet_tpu.amp import LossScaler

    ls = LossScaler(init_scale=1024.0, scale_factor=2.0,
                    scale_window=2)
    good = [mx.nd.ones((2,))]
    bad = [mx.nd.array([np.inf, 1.0])]
    assert not ls.has_overflow(good)
    assert ls.has_overflow(bad)
    s0 = ls.loss_scale
    ls.update_scale(True)
    assert ls.loss_scale == s0 / 2.0
    ls.update_scale(False)
    ls.update_scale(False)  # scale_window=2 stable steps -> grow
    assert ls.loss_scale == s0
    # never collapses below 1
    for _ in range(40):
        ls.update_scale(True)
    assert ls.loss_scale >= 1.0


def test_amp_scale_loss_trains_fp16_safely():
    """amp.scale_loss + init_trainer: gradients are unscaled before the
    optimizer step, so training matches the unscaled run."""
    from mxnet_tpu import amp, autograd, gluon

    def build():
        mx.random.seed(0)
        net = gluon.nn.Dense(3, in_units=4)
        net.initialize()
        return net

    x = mx.nd.random.uniform(shape=(6, 4))
    y = mx.nd.ones((6, 3))
    loss_fn = gluon.loss.L2Loss()

    init_net = build()
    # key by suffix: the dense prefix counter differs per instance
    ref_params = {k.rsplit("_", 1)[1]: v.data().asnumpy()
                  for k, v in init_net.collect_params().items()}

    def run(scaled):
        net = build()
        for k, v in net.collect_params().items():
            v.set_data(mx.nd.array(ref_params[k.rsplit("_", 1)[1]]))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1})
        if scaled:
            old = amp._STATE["target_dtype"]
            amp._STATE["target_dtype"] = "float16"  # engage the scaler
            try:
                amp.init_trainer(tr)
            finally:
                amp._STATE["target_dtype"] = old
        for _ in range(3):
            with autograd.record():
                loss = loss_fn(net(x), y).mean()
                if scaled:
                    # reference idiom: backward on the scaled loss inside
                    # the scale_loss context (its exit unscales the grads)
                    with amp.scale_loss(loss, tr) as sloss:
                        sloss.backward()
            if not scaled:
                loss.backward()
            tr.step(1)
        return net.weight.data().asnumpy()

    np.testing.assert_allclose(run(True), run(False), rtol=1e-4, atol=1e-5)


def test_control_flow_cond_eager_and_hybrid():
    """contrib.cond in both execution modes (reference:
    control_flow.cc cond over subgraphs -> lax.cond under trace)."""
    def then_fn():
        return mx.nd.array([1.0])

    def else_fn():
        return mx.nd.array([-1.0])

    def first(o):
        return o[0] if isinstance(o, (list, tuple)) else o

    assert first(mx.nd.contrib.cond(mx.nd.array([1.0]), then_fn,
                                    else_fn)).asnumpy()[0] == 1.0
    assert first(mx.nd.contrib.cond(mx.nd.array([0.0]), then_fn,
                                    else_fn)).asnumpy()[0] == -1.0

    # traced mode: the lax.cond branch inside a hybridized block, where
    # the predicate is a TRACER (data-dependent at runtime)
    from mxnet_tpu import gluon

    class CondNet(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.contrib.cond(
                x.sum() > 0,
                lambda: x * 2.0,
                lambda: x * -1.0)

    net = CondNet()
    net.hybridize()
    pos = mx.nd.ones((2,))
    neg = mx.nd.full((2,), -1.0)
    np.testing.assert_allclose(first(net(pos)).asnumpy(), [2.0, 2.0])
    np.testing.assert_allclose(first(net(neg)).asnumpy(), [1.0, 1.0])


def test_f_contrib_symbolic_export_roundtrip(tmp_path):
    """F.contrib.* must resolve on BOTH F namespaces: traced (nd op) and
    symbolic (export/SymbolBlock.imports) — review regression."""
    from mxnet_tpu import gluon

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.fc = gluon.nn.Dense(4, in_units=4)

        def hybrid_forward(self, F, x):
            y = self.fc(x)
            return y + F.contrib.arange_like(y, axis=1)

    net = Net()
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 4))
    out1 = net(x)
    prefix = str(tmp_path / "net")
    net.export(prefix)
    back = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                     prefix + "-0000.params")
    np.testing.assert_allclose(out1.asnumpy(), back(x).asnumpy(), rtol=1e-5)
