"""Model zoo smoke tests (reference model: test_gluon_model_zoo.py).

Each model builds, hybridizes, and runs forward on a small batch.
Input sizes are the reference's canonical ones, shrunk where the
architecture allows to keep CPU CI fast.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import vision


def _smoke(name, input_size=224, classes=10, batch=1):
    net = vision.get_model(name, classes=classes)
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    x = mx.nd.random.normal(shape=(batch, 3, input_size, input_size))
    out = net(x)
    assert out.shape == (batch, classes)
    assert np.isfinite(out.asnumpy()).all()
    return net


# zoo construction stays tier-1 via resnet50_v1_shape / save-load
# roundtrip; the train path through a zoo resnet runs every tier-1
# round inside the bench smoke's resnet scenario
@pytest.mark.slow
def test_resnet18_v1_forward_backward():
    net = vision.get_model("resnet18_v1", classes=10)
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    x = mx.nd.random.normal(shape=(2, 3, 64, 64))
    y = mx.nd.array([1.0, 3.0])
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    grads = [p.grad() for p in net.collect_params().values()
             if p.grad_req != "null"]
    assert all(np.isfinite(g.asnumpy()).all() for g in grads)


@pytest.mark.slow
def test_resnet34_v2():
    _smoke("resnet34_v2", input_size=64)


# zoo construction stays tier-1 via save-load roundtrip and the bench
# smoke's resnet scenario (trains a zoo resnet every tier-1 round)
@pytest.mark.slow
def test_resnet50_v1_shape():
    net = vision.get_model("resnet50_v1", classes=7)
    net.initialize()
    out = net(mx.nd.random.normal(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 7)


@pytest.mark.slow
def test_alexnet():
    _smoke("alexnet", input_size=224)


@pytest.mark.slow
def test_vgg11():
    _smoke("vgg11", input_size=224)


@pytest.mark.slow
def test_vgg11_bn():
    _smoke("vgg11_bn", input_size=224)


@pytest.mark.slow
def test_squeezenet():
    _smoke("squeezenet1.1", input_size=224)


@pytest.mark.slow
def test_densenet121():
    _smoke("densenet121", input_size=64)


@pytest.mark.slow
def test_mobilenet():
    _smoke("mobilenet0.25", input_size=64)


@pytest.mark.slow
def test_mobilenet_v2():
    _smoke("mobilenetv2_0.25", input_size=64)


@pytest.mark.slow
def test_inception_v3():
    _smoke("inceptionv3", input_size=299)


def test_get_model_unknown():
    with pytest.raises(mx.MXNetError):
        vision.get_model("not_a_model")


def test_model_save_load_roundtrip(tmp_path):
    net = vision.get_model("resnet18_v1", classes=4)
    net.initialize()
    x = mx.nd.random.normal(shape=(1, 3, 32, 32))
    ref = net(x).asnumpy()
    f = str(tmp_path / "r18.params")
    net.save_parameters(f)
    net2 = vision.get_model("resnet18_v1", classes=4)
    net2.load_parameters(f)
    out = net2(x).asnumpy()
    np.testing.assert_allclose(ref, out, rtol=1e-5, atol=1e-6)
