"""Fleet recovery certification, process plane: real child-process
replicas (one OS process per 'host'), SIGKILL host death mid-traffic,
typed in-flight failover (never a hang), autoscaler replacement, chaos
fault classes (``kill_replica@fleet`` / ``stall@replica<k>``) — plus
the PR-13 swap-race satellite: ``EngineClosed`` from a SWAPPING engine
retries onto the new version while ``EngineClosed`` from a DEAD
replica diverges into router failover.
"""

import time

import numpy as np
import pytest

from mxnet_tpu import observability as obs
from mxnet_tpu.resilience import chaos
from mxnet_tpu.serving import (
    EngineClosed,
    LocalReplica,
    ModelRepository,
    ProcessReplica,
    ReplicaDead,
    ServingFleet,
    SLOAutoscaler,
)


@pytest.fixture(autouse=True)
def _state():
    obs.set_enabled(False)
    obs.reset()
    chaos.reset()
    yield
    obs.set_enabled(False)
    obs.reset()
    chaos.reset()


FEAT = 8
SPEC = {"net": {"dense": {"classes": 4, "feat": FEAT, "bias": 0.5}},
        "shapes": [(FEAT,)], "version": "v1",
        "engine": {"max_batch": 4, "max_wait_ms": 2.0}}
SPEC_V2 = dict(SPEC, version="v2",
               net={"dense": {"classes": 4, "feat": FEAT, "bias": 9.0}})
X = np.ones((FEAT,), np.float32)
EXPECT_V1 = np.full(4, 0.1 * FEAT + 0.5)
EXPECT_V2 = np.full(4, 0.1 * FEAT + 9.0)


# -- satellite: swap-race vs replica-death divergence ----------------------

def test_repo_submit_retries_engine_closed_from_swap():
    """EngineClosed raced by a version flip is absorbed: the retry loop
    re-reads the live pointer and the request lands on the NEW
    version — continuous traffic across a swap never fails spuriously."""
    from mxnet_tpu.serving.replica import build_net

    repo = ModelRepository(keep=1)
    try:
        repo.load("m", lambda: build_net(SPEC["net"]), SPEC["shapes"],
                  version="v1", **SPEC["engine"])
        old = repo.engine("m")
        repo.load("m", lambda: build_net(SPEC_V2["net"]),
                  SPEC_V2["shapes"], version="v2", **SPEC["engine"])
        # the OLD engine is paused (standby): submitting through the
        # repository must NOT surface its EngineClosed — the pointer
        # re-read routes to v2
        with pytest.raises(EngineClosed):
            old.submit(X)  # direct submit: typed refusal, proves the race
        out = np.asarray(repo.predict("m", X, timeout=30.0))
        np.testing.assert_allclose(out.ravel(), EXPECT_V2, rtol=1e-5)
        assert repo.live_version("m") == "v2"
    finally:
        repo.close()


def test_dead_replica_engine_closed_diverges_to_replica_dead():
    """The SAME wire error (EngineClosed) means two different things:
    from a swapping engine it is retried in place; from a DEAD replica
    it must surface as ReplicaDead so the router fails over instead of
    spinning the swap-retry loop against a corpse."""
    replica = LocalReplica(0, SPEC, name="m")
    try:
        replica.kill()
        with pytest.raises(ReplicaDead):
            replica.submit(X)
    finally:
        replica.close()


def test_swap_race_retry_with_concurrent_replica_loss_in_fleet():
    """Both paths at once: replica 0 dies while replica 1 swaps. A
    request must fail over off the corpse AND land on a coherent
    version of the survivor — never a stale answer, never a hang."""
    fleet = ServingFleet(SPEC, name="m", replicas=2,
                         autostart_heartbeat=False)
    try:
        fleet.kill_replica(0)
        survivor = fleet.replica_set.live()[0]
        survivor.swap(SPEC_V2)
        fut = fleet.submit(X)
        out = np.asarray(fut.result(30.0))
        np.testing.assert_allclose(out.ravel(), EXPECT_V2, rtol=1e-5)
    finally:
        fleet.close()


# -- local host-kill: queued work fails typed, never hangs -----------------

def test_killed_replica_fails_queued_requests_typed():
    spec = dict(SPEC, engine={"max_batch": 2, "max_wait_ms": 300.0})
    replica = LocalReplica(0, spec, name="m")
    try:
        futs = [replica.submit(X) for _ in range(6)]
        replica.kill()
        t0 = time.monotonic()
        outcomes = []
        for f in futs:
            try:
                f.result(5.0)
                outcomes.append("ok")
            except ReplicaDead:
                outcomes.append("dead")
            except EngineClosed:
                outcomes.append("dead")
        # every future resolved FAST and TYPED — zero hangs
        assert time.monotonic() - t0 < 5.0
        assert "dead" in outcomes
    finally:
        replica.close()


# -- chaos fault classes ---------------------------------------------------

def test_chaos_kill_replica_spec_fires_once_mid_traffic():
    chaos.configure("kill_replica@fleet:5:0")
    fleet = ServingFleet(SPEC, name="m", replicas=2,
                         autostart_heartbeat=False)
    try:
        for i in range(12):
            out = fleet.predict(X, timeout=30.0)  # traffic never breaks
            assert out is not None
        fired = chaos.fired()
        assert ("kill_replica", "fleet", 5) in fired
        assert len([f for f in fired if f[0] == "kill_replica"]) == 1
        assert fleet.n_live() == 1  # the victim is dead, survivor serves
    finally:
        fleet.close()
        chaos.reset()


def test_chaos_stall_replica_site_injects_latency():
    chaos.configure("stall@replica0:2:0.2")
    replica = LocalReplica(0, SPEC, name="m")
    try:
        replica.submit(X).result(30.0)  # step 1
        t0 = time.monotonic()
        replica.submit(X).result(30.0)  # step 2: stalled 0.2s
        assert time.monotonic() - t0 >= 0.18
        assert ("stall", "replica0", 2) in chaos.fired()
    finally:
        replica.close()
        chaos.reset()


# -- process replicas (real host-kill) -------------------------------------

@pytest.mark.slow
def test_process_replica_roundtrip_and_swap():
    r = ProcessReplica(0, SPEC, name="m").wait_ready(timeout=180.0)
    try:
        out = np.asarray(r.submit(X).result(60.0))
        np.testing.assert_allclose(out.ravel(), EXPECT_V1, rtol=1e-5)
        info = r.ping(timeout=10.0)
        assert info["version"] == "v1"
        assert r.swap(SPEC_V2) == "v2"
        out2 = np.asarray(r.submit(X).result(60.0))
        np.testing.assert_allclose(out2.ravel(), EXPECT_V2, rtol=1e-5)
    finally:
        r.close()


@pytest.mark.slow
def test_process_replica_sigkill_fails_pending_typed():
    r = ProcessReplica(0, SPEC, name="m").wait_ready(timeout=180.0)
    futs = [r.submit(X) for _ in range(4)]
    r.kill()
    t0 = time.monotonic()
    for f in futs:
        try:
            f.result(10.0)
        except (ReplicaDead, Exception):
            pass
    assert time.monotonic() - t0 < 10.0  # resolved, not hung
    assert r.state == "dead"
    with pytest.raises(ReplicaDead):
        r.submit(X)


@pytest.mark.slow
def test_process_fleet_host_kill_recovery_end_to_end():
    """The tentpole certification in miniature: SIGKILL one of two
    host processes mid-traffic; every in-flight request is retried or
    typed-failed; the autoscaler replaces the host; the fleet serves
    the same answers afterward."""
    fleet = ServingFleet(SPEC, name="m", replicas=2, process=True,
                         heartbeat_s=0.3, suspect_misses=3)
    scaler = SLOAutoscaler(fleet, min_replicas=2, max_replicas=3,
                           cooldown_s=3600.0, use_watchdog=False)
    try:
        fleet.predict(X, timeout=60.0)
        futs = [fleet.submit(X, key=i) for i in range(8)]
        fleet.kill_replica(0)
        ok = 0
        for f in futs:
            out = np.asarray(f.result(60.0))  # typed or ok — never hung
            np.testing.assert_allclose(out.ravel(), EXPECT_V1, rtol=1e-5)
            ok += 1
        assert ok == 8
        for _ in range(20):
            scaler.tick()
            if scaler.replaced >= 1 and fleet.n_live() >= 2:
                break
            time.sleep(0.2)
        assert scaler.replaced >= 1
        assert fleet.n_live() == 2
        assert fleet.last_recovery_s is not None
        out = np.asarray(fleet.predict(X, timeout=60.0))
        np.testing.assert_allclose(out.ravel(), EXPECT_V1, rtol=1e-5)
    finally:
        scaler.stop()
        fleet.close()


@pytest.mark.slow
def test_process_replica_warm_pause_resume():
    r = ProcessReplica(0, SPEC, name="m").wait_ready(timeout=180.0)
    try:
        r.submit(X).result(60.0)
        r.pause()
        assert r.state == "warm"
        r.resume(timeout=180.0)  # respawn through the compile cache
        assert r.state == "live"
        out = np.asarray(r.submit(X).result(60.0))
        np.testing.assert_allclose(out.ravel(), EXPECT_V1, rtol=1e-5)
    finally:
        r.close()
