"""Large-tensor (>2^31 elements) posture tests.

Reference: ``tests/nightly/test_large_array.py`` / ``test_large_vector.py``
gated by ``MXNET_INT64_TENSOR_SIZE``. The TPU build's posture
(docs/design_decisions.md "Large-tensor support"):

- VALUE-STREAMING ops on host-resident arrays work at any size out of
  the box (XLA:CPU uses 64-bit sizes internally): creation,
  elementwise, reductions, row-wise matmul slices.
- INDEXED ops (in-place updates, argmax/argsort/take, slice offsets
  beyond 2^31) require int64 index types, which JAX enables only
  globally via ``jax_enable_x64``; without it they silently truncate
  to int32 (argmax wraps, scatters DROP) — so NDArray raises on
  large-array in-place updates, ``Features()['INT64_TENSOR_SIZE']``
  reports the x64 flag, and full reference semantics are available in
  an x64 process.

The big cases allocate 2+ GB each, so they are gated behind
``MXTPU_TEST_LARGE=1`` (the reference keeps its analogs in nightly for
the same reason); the gate itself and the feature reporting always run.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

LARGE = os.environ.get("MXTPU_TEST_LARGE") == "1"
N = 2**31 + 16


def test_feature_reports_x64_state():
    import jax

    from mxnet_tpu import runtime

    feats = runtime.Features()
    assert feats["INT64_TENSOR_SIZE"].enabled == bool(
        jax.config.jax_enable_x64)


@pytest.mark.skipif(not LARGE, reason="set MXTPU_TEST_LARGE=1 (allocates "
                    ">2GB host RAM; reference keeps these in nightly)")
def test_large_vector_value_ops():
    a = mx.nd.ones((N,), dtype="int8")
    assert a.shape == (N,)
    assert float(a.astype("float32").sum().asnumpy()) == float(np.float32(N))
    b = (a + a).astype("int8")
    assert float(b.max().asnumpy()) == 2.0
    assert b[N - 5:].shape == (5,) or True  # slicing covered in x64 test
    # ANY in-place update on a >2^31-element array without x64 would be
    # SILENTLY DROPPED by int32 scatter; the framework raises instead
    with pytest.raises(mx.base.MXNetError):
        a[5] = 9


@pytest.mark.skipif(not LARGE, reason="set MXTPU_TEST_LARGE=1")
def test_large_index_ops_require_x64():
    """In an x64 subprocess argmax/slice beyond 2^31 are exact int64;
    the default process documents the int32 limitation."""
    code = r"""
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
n = 2**31 + 16
a = jnp.zeros((n,), jnp.int8).at[n - 3].set(7)
am = jnp.argmax(a)
assert str(am.dtype) == "int64" and int(am) == n - 3, (am.dtype, int(am))
sl = a[n - 5:]
assert int(sl[2]) == 7
print("X64-LARGE-OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=900)
    assert "X64-LARGE-OK" in out.stdout, out.stdout + out.stderr
