"""Self-healing serving fleet, unit plane (``mxnet_tpu.serving.fleet``
/ ``router`` / ``autoscaler`` / ``replica``): least-depth routing with
consistent-hash fallback, typed at-most-once failover, the latched
brownout state machine, the SLO autoscaler's deterministic ``tick()``
through the elastic membership signal bus, plus the PR's satellites —
``ServeFuture.cancel``, decorrelated-jitter backoff, the federation
``cluster_values`` consumer and the watchdog listener registry.

Everything here is in-process (LocalReplica / fakes) — the
process-level recovery certification lives in test_fleet_recovery.py.
"""

import random
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import observability as obs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.observability import federation as fed
from mxnet_tpu.observability import watchdog
from mxnet_tpu.resilience.elastic import MembershipMonitor
from mxnet_tpu.runtime import backoff_delays, retry_with_backoff
from mxnet_tpu.serving import (
    BrownoutShed,
    InferenceEngine,
    LocalReplica,
    ReplicaDead,
    ReplicaLost,
    ReplicaRouter,
    RequestCancelled,
    ServerOverloaded,
    ServingFleet,
    SLOAutoscaler,
)
from mxnet_tpu.serving.replica import build_net, _dense_net
from mxnet_tpu.serving.router import federation_depth_feed


@pytest.fixture(autouse=True)
def _telemetry_state():
    obs.set_enabled(False)
    obs.reset()
    watchdog.reset()
    fed.reset()
    yield
    obs.set_enabled(False)
    obs.reset()
    watchdog.reset()
    fed.reset()


FEAT = 8
SPEC = {"net": {"dense": {"classes": 4, "feat": FEAT, "bias": 0.5}},
        "shapes": [(FEAT,)], "version": "v1",
        "engine": {"max_batch": 4, "max_wait_ms": 2.0}}
X = np.ones((FEAT,), np.float32)


def _fleet(**kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("autostart_heartbeat", False)
    return ServingFleet(SPEC, name="clf", **kw)


# -- fakes for deterministic router tests ----------------------------------

class _FakeFuture:
    def __init__(self, value=None, error=None, ready=True):
        self.value, self.error, self.ready = value, error, ready
        self.version = "v1"

    def done(self):
        return self.ready

    def result(self, timeout=None):
        if not self.ready:
            if timeout in (None, 0):
                raise TimeoutError("fake future never completes")
            time.sleep(min(timeout, 0.05))
            raise TimeoutError("fake future never completes")
        if self.error is not None:
            raise self.error
        return self.value


class _FakeReplica:
    """Scripted replica: a fixed depth (None = no fresh signal) and a
    scripted submit outcome per call."""

    _uid = iter(range(1000, 9999))

    def __init__(self, index, depth=None, outcomes=None):
        self.uid = next(self._uid)
        self.index = index
        self.state = "live"
        self.depth = depth
        self.submits = 0
        self.outcomes = list(outcomes or [])

    def queue_depth(self):
        return self.depth or 0

    def depth_age(self):
        return 0.0 if self.depth is not None else float("inf")

    def submit(self, x, **kw):
        self.submits += 1
        if self.outcomes:
            out = self.outcomes.pop(0)
            if isinstance(out, Exception):
                raise out
            return out
        return _FakeFuture(value=("ok", self.index))


# -- replica spec / net materialization ------------------------------------

def test_build_net_variants():
    direct = build_net({"dense": {"classes": 4, "feat": FEAT,
                                  "bias": 2.0}})
    assert hasattr(direct, "aot_predict_fn") or callable(direct)
    by_path = build_net("mxnet_tpu.serving.replica:_dense_net")
    assert type(by_path).__name__ == type(_dense_net()).__name__
    by_factory = build_net(lambda: _dense_net(bias=1.0))
    assert by_factory is not None
    with pytest.raises(MXNetError):
        build_net(42)


def test_dense_net_is_deterministic():
    net = _dense_net(classes=4, feat=FEAT, bias=0.5, scale=0.1)
    eng = InferenceEngine(net, [(FEAT,)], max_batch=2, max_wait_ms=0.0,
                          name="det")
    try:
        out = np.asarray(eng.predict(X, timeout=30.0))
        np.testing.assert_allclose(out.ravel(),
                                   np.full(4, 0.1 * FEAT + 0.5),
                                   rtol=1e-5)
    finally:
        eng.close()


# -- router: placement -----------------------------------------------------

def test_router_prefers_least_depth():
    shallow = _FakeReplica(0, depth=1)
    deep = _FakeReplica(1, depth=9)
    router = ReplicaRouter(lambda: [deep, shallow], retries=0, hedge_ms=0)
    fut = router.submit(X)
    assert fut.replica is shallow
    assert shallow.submits == 1 and deep.submits == 0


def test_router_hash_fallback_is_deterministic_per_key():
    replicas = [_FakeReplica(i, depth=None) for i in range(4)]
    router = ReplicaRouter(lambda: list(replicas), retries=0, hedge_ms=0)
    first = {k: router._order(k, set())[0].uid for k in range(16)}
    again = {k: router._order(k, set())[0].uid for k in range(16)}
    assert first == again  # same key -> same placement, every time
    assert len(set(first.values())) > 1  # keys actually spread


def test_router_hash_fallback_survives_replica_loss():
    replicas = [_FakeReplica(i, depth=None) for i in range(4)]
    router = ReplicaRouter(lambda: list(replicas), retries=0, hedge_ms=0)
    before = {k: router._order(k, set())[0].uid for k in range(64)}
    gone = replicas.pop(0)
    after = {k: router._order(k, set())[0].uid for k in range(64)}
    moved = sum(1 for k in before
                if before[k] != after[k] and before[k] != gone.uid)
    # consistent hashing: keys NOT owned by the lost replica stay put
    assert moved == 0


def test_router_depth_feed_wins_over_local():
    a = _FakeReplica(0, depth=0)   # local says idle...
    b = _FakeReplica(1, depth=9)
    feed = {a.uid: 50.0, b.uid: 1.0}  # ...but the cluster sees a pile-up
    router = ReplicaRouter(lambda: [a, b], retries=0, hedge_ms=0,
                           depth_feed=lambda r: feed[r.uid])
    assert router.submit(X).replica is b


# -- router: failover ------------------------------------------------------

def test_failover_at_most_once_per_replica():
    dead1 = _FakeReplica(0, depth=0, outcomes=[ReplicaDead("x")] * 9)
    dead2 = _FakeReplica(1, depth=1, outcomes=[ReplicaDead("x")] * 9)
    alive = _FakeReplica(2, depth=2)
    router = ReplicaRouter(lambda: [dead1, dead2, alive], retries=0,
                           hedge_ms=0)
    fut = router.submit(X)
    assert fut.result(5.0) == ("ok", 2)
    assert dead1.submits == 1 and dead2.submits == 1  # at most once each
    assert fut.tried_count() == 3


def test_replica_lost_only_when_all_candidates_fail():
    dead = [_FakeReplica(i, depth=i, outcomes=[ReplicaDead("x")] * 9)
            for i in range(3)]
    router = ReplicaRouter(lambda: list(dead), retries=0, hedge_ms=0)
    with pytest.raises(ReplicaLost):
        router.submit(X)
    assert all(r.submits == 1 for r in dead)


def test_failover_after_dispatch_death():
    # the replica ACCEPTED the request, then died while it waited
    dies_later = _FakeReplica(
        0, depth=0, outcomes=[_FakeFuture(error=ReplicaDead("host kill"))])
    alive = _FakeReplica(1, depth=5)
    router = ReplicaRouter(lambda: [dies_later, alive], retries=0,
                           hedge_ms=0)
    fut = router.submit(X)
    assert fut.replica is dies_later
    assert fut.result(5.0) == ("ok", 1)  # transparently re-dispatched
    assert fut.replica is alive


def test_death_callback_feeds_health_plane():
    seen = []
    dead = _FakeReplica(0, depth=0, outcomes=[ReplicaDead("x")])
    alive = _FakeReplica(1, depth=1)
    router = ReplicaRouter(lambda: [dead, alive], retries=0, hedge_ms=0,
                           on_death=lambda r, e: seen.append(r))
    router.submit(X)
    assert seen == [dead]


def test_retry_budget_caps_candidates():
    dead = [_FakeReplica(i, depth=i, outcomes=[ReplicaDead("x")] * 9)
            for i in range(4)]
    router = ReplicaRouter(lambda: list(dead), retries=1, hedge_ms=0)
    with pytest.raises(ReplicaLost):
        router.submit(X)
    assert sum(r.submits for r in dead) == 2  # first try + 1 retry


def test_hedged_request_promotes_survivor():
    stall = _FakeReplica(0, depth=0,
                         outcomes=[_FakeFuture(ready=False)])
    fast = _FakeReplica(1, depth=5)
    router = ReplicaRouter(lambda: [stall, fast], retries=0, hedge_ms=5.0)
    fut = router.submit(X)
    assert fut.replica is stall
    assert fut.result(10.0) == ("ok", 1)
    assert fut.was_hedged()


# -- brownout state machine ------------------------------------------------

def test_brownout_latches_and_sheds_in_priority_order():
    fleet = _fleet(replicas=1, brownout_enter=0.8, brownout_exit=0.2,
                   brownout_hold_s=10.0)
    try:
        assert fleet._evaluate_brownout(0.85, now=0.0) == 1
        assert not fleet._admit("bulk")
        assert fleet._admit("interactive") and fleet._admit("critical")
        assert fleet._evaluate_brownout(0.95, now=0.1) == 2
        assert not fleet._admit("bulk") and not fleet._admit("interactive")
        assert fleet._admit("critical")  # critical is NEVER policy-shed
        # a dip below exit does not unlatch without the hold window
        assert fleet._evaluate_brownout(0.1, now=0.2) == 2
    finally:
        fleet.close()


def test_brownout_deescalates_one_level_per_hold_window():
    fleet = _fleet(replicas=1, brownout_enter=0.8, brownout_exit=0.2,
                   brownout_hold_s=1.0)
    try:
        assert fleet._evaluate_brownout(0.96, now=0.0) == 2
        assert fleet._evaluate_brownout(0.1, now=0.5) == 2   # draining...
        assert fleet._evaluate_brownout(0.1, now=1.6) == 1   # one step
        assert fleet._evaluate_brownout(0.1, now=2.0) == 1   # not two
        assert fleet._evaluate_brownout(0.1, now=2.8) == 0   # clear
    finally:
        fleet.close()


def test_brownout_relapse_resets_drain_clock():
    fleet = _fleet(replicas=1, brownout_enter=0.8, brownout_exit=0.2,
                   brownout_hold_s=1.0)
    try:
        assert fleet._evaluate_brownout(0.85, now=0.0) == 1
        assert fleet._evaluate_brownout(0.1, now=0.9) == 1
        assert fleet._evaluate_brownout(0.5, now=1.0) == 1  # relapse
        # the earlier 0.9s of drain does not count toward the hold
        assert fleet._evaluate_brownout(0.1, now=1.5) == 1
        assert fleet._evaluate_brownout(0.1, now=2.6) == 0
    finally:
        fleet.close()


def test_brownout_shed_is_typed_and_counted():
    obs.set_enabled(True)
    fleet = _fleet(replicas=1, brownout_enter=0.8, brownout_exit=0.2,
                   brownout_hold_s=60.0)
    try:
        fleet._evaluate_brownout(0.9, now=0.0)
        with pytest.raises(BrownoutShed) as ei:
            fleet.submit(X, priority="bulk")
        assert isinstance(ei.value, ServerOverloaded)  # 503 mapping holds
        shed = obs.FLEET_SHED_TOTAL.value(model="clf", priority="bulk")
        assert shed == 1
    finally:
        fleet.close()


def test_brownout_threshold_validation():
    with pytest.raises(MXNetError):
        _fleet(replicas=1, brownout_enter=0.3, brownout_exit=0.5)


def test_unknown_priority_rejected():
    fleet = _fleet(replicas=1)
    try:
        with pytest.raises(MXNetError):
            fleet.submit(X, priority="shiny")
    finally:
        fleet.close()


# -- local fleet end to end ------------------------------------------------

def test_local_fleet_serves_and_fails_over():
    fleet = _fleet(replicas=2)
    try:
        out = np.asarray(fleet.predict(X, timeout=30.0))
        np.testing.assert_allclose(out.ravel(),
                                   np.full(4, 0.1 * FEAT + 0.5),
                                   rtol=1e-5)
        fut = fleet.submit(X)
        fleet.kill_replica(fut.replica.index)
        np.testing.assert_allclose(np.asarray(fut.result(30.0)).ravel(),
                                   np.full(4, 0.1 * FEAT + 0.5),
                                   rtol=1e-5)
        assert fleet.n_live() == 1
    finally:
        fleet.close()


def test_all_replicas_dead_is_typed_replica_lost():
    fleet = _fleet(replicas=2)
    try:
        fleet.kill_replica(-1)
        fleet.kill_replica(-1)
        with pytest.raises(ReplicaLost):
            fleet.submit(X).result(10.0)
    finally:
        fleet.close()


def test_scale_to_zero_and_restore_on_demand():
    fleet = _fleet(replicas=2)
    try:
        fleet.replica_set.scale_to_zero()
        assert fleet.n_live() == 0
        assert len(fleet.replica_set.warm()) == 2
        # first submit against a parked fleet restores, not fails
        out = fleet.predict(X, timeout=30.0)
        assert out is not None
        assert fleet.n_live() == 2
    finally:
        fleet.close()


def test_rolling_swap_keeps_version_coherent():
    fleet = _fleet(replicas=2)
    try:
        v2 = dict(SPEC, version="v2",
                  net={"dense": {"classes": 4, "feat": FEAT,
                                 "bias": 9.0}})
        assert fleet.swap(v2) == ["v2", "v2"]
        fut = fleet.submit(X)
        np.testing.assert_allclose(np.asarray(fut.result(30.0)).ravel(),
                                   np.full(4, 0.1 * FEAT + 9.0),
                                   rtol=1e-5)
    finally:
        fleet.close()


def test_heartbeat_walks_suspect_then_dead():
    fleet = _fleet(replicas=2, suspect_misses=2)
    rs = fleet.replica_set
    try:
        victim = rs.replicas()[0]
        victim._dead = True  # ping now raises, but state is still live
        rs.heartbeat_once()
        assert victim.state == "suspect"
        rs.heartbeat_once()
        assert victim.state == "dead"
        assert fleet.n_live() == 1
    finally:
        fleet.close()


# -- autoscaler ------------------------------------------------------------

def test_autoscaler_replaces_dead_replica():
    fleet = _fleet(replicas=2)
    scaler = SLOAutoscaler(fleet, min_replicas=2, max_replicas=4,
                           cooldown_s=3600.0, use_watchdog=False)
    try:
        fleet.kill_replica(0)
        assert fleet.n_live() == 1
        scaler.tick()
        assert scaler.replaced == 1
        assert fleet.n_live() == 2
        assert fleet.last_recovery_s is not None
        assert fleet.last_recovery_s >= 0.0
        # the replacement serves
        assert fleet.predict(X, timeout=30.0) is not None
    finally:
        scaler.stop()
        fleet.close()


def test_autoscaler_grows_on_slo_breach():
    fleet = _fleet(replicas=2)
    scaler = SLOAutoscaler(fleet, min_replicas=1, max_replicas=3,
                           slo_p99_ms=50.0, cooldown_s=0.0,
                           use_watchdog=False)
    try:
        for _ in range(20):
            fleet.router.record_latency(1.0)  # 1000ms >> 50ms SLO
        signals = scaler.tick()
        assert any(s["kind"] == "resize" and s["reason"] == "slo"
                   for s in signals)
        assert fleet.n_live() == 3
    finally:
        scaler.stop()
        fleet.close()


def test_autoscaler_growth_respects_cooldown_and_max():
    fleet = _fleet(replicas=2)
    scaler = SLOAutoscaler(fleet, min_replicas=1, max_replicas=3,
                           slo_p99_ms=50.0, cooldown_s=3600.0,
                           use_watchdog=False)
    try:
        for _ in range(20):
            fleet.router.record_latency(1.0)
        scaler.tick()
        assert fleet.n_live() == 3
        scaler.tick()  # still breaching, but cooldown + max cap hold
        assert fleet.n_live() == 3
    finally:
        scaler.stop()
        fleet.close()


def test_autoscaler_shrinks_on_sustained_headroom():
    fleet = _fleet(replicas=3)
    scaler = SLOAutoscaler(fleet, min_replicas=1, max_replicas=4,
                           slo_p99_ms=1000.0, cooldown_s=0.0,
                           use_watchdog=False)
    try:
        for _ in range(20):
            fleet.router.record_latency(0.001)  # way under SLO
        scaler.tick()
        assert fleet.n_live() == 2
    finally:
        scaler.stop()
        fleet.close()


def test_autoscaler_scale_to_zero_on_idle():
    fleet = _fleet(replicas=2)
    scaler = SLOAutoscaler(fleet, min_replicas=0, max_replicas=4,
                           cooldown_s=0.0, idle_to_zero_s=0.01,
                           use_watchdog=False)
    try:
        fleet._last_submit_mono = time.monotonic() - 60.0
        scaler.tick()
        assert fleet.n_live() == 0
        assert len(fleet.replica_set.warm()) == 2
        # traffic returns: restore on demand, then the scaler sees live
        assert fleet.predict(X, timeout=30.0) is not None
        assert fleet.n_live() >= 1
    finally:
        scaler.stop()
        fleet.close()


def test_autoscaler_signals_ride_the_membership_bus():
    fleet = _fleet(replicas=2)
    monitor = MembershipMonitor(straggler_factor=0.0, notice_path="")
    scaler = SLOAutoscaler(fleet, min_replicas=2, max_replicas=4,
                           cooldown_s=3600.0, monitor=monitor,
                           use_watchdog=False)
    try:
        fleet.kill_replica(0)
        scaler._ingest_deaths()
        pend = monitor.pending()
        assert any(s["kind"] == "dead_peer" for s in pend)
        scaler.tick()
        assert fleet.n_live() == 2
    finally:
        scaler.stop()
        fleet.close()


def test_watchdog_saturation_anomaly_requests_growth():
    fleet = _fleet(replicas=2)
    scaler = SLOAutoscaler(fleet, min_replicas=1, max_replicas=4,
                           cooldown_s=0.0, use_watchdog=True)
    try:
        scaler._on_anomaly("queue_saturation", {"depth": 99})
        pend = scaler.monitor.pending()
        assert any(s["kind"] == "resize"
                   and s["reason"] == "queue_saturation" for s in pend)
        scaler.tick()
        assert fleet.n_live() == 3
    finally:
        scaler.stop()
        fleet.close()


def test_watchdog_listener_registry():
    calls = []

    def listener(kind, details):
        calls.append((kind, details))

    watchdog.register_listener(listener)
    watchdog.register_listener(listener)  # idempotent
    watchdog._fire("queue_saturation", depth=7)
    assert calls == [("queue_saturation", {"depth": 7})]
    watchdog.unregister_listener(listener)
    watchdog._fire("queue_saturation", depth=8)
    assert len(calls) == 1


def test_broken_listener_never_breaks_detection():
    def bad(kind, details):
        raise RuntimeError("actuator crashed")

    watchdog.register_listener(bad)
    watchdog._fire("nan_loss", step=3)  # must not raise
    watchdog.unregister_listener(bad)


# -- satellite: ServeFuture.cancel -----------------------------------------

def test_cancel_queued_request_is_typed_and_never_dispatched():
    net = _dense_net(feat=FEAT)
    eng = InferenceEngine(net, [(FEAT,)], max_batch=4, max_wait_ms=500.0,
                          name="cx")
    try:
        batches_before = eng.stats()["batches"]
        fut = eng.submit(X)
        assert fut.cancel() is True
        assert fut.cancelled() is True
        with pytest.raises(RequestCancelled):
            fut.result(5.0)
        # a second cancel / a cancel race is a no-op
        assert fut.cancel() is False
        # the cancelled entry is skipped at drain: submit another and
        # confirm the engine only ever dispatched the live one
        out = eng.predict(X, timeout=30.0)
        assert out is not None
        assert eng.stats()["batches"] == batches_before + 1
    finally:
        eng.close()


def test_cancel_after_completion_returns_false():
    net = _dense_net(feat=FEAT)
    eng = InferenceEngine(net, [(FEAT,)], max_batch=1, max_wait_ms=0.0,
                          name="cy")
    try:
        fut = eng.submit(X)
        fut.result(30.0)
        assert fut.cancel() is False
        assert fut.cancelled() is False
    finally:
        eng.close()


def test_cancel_frees_queue_slot():
    net = _dense_net(feat=FEAT)
    eng = InferenceEngine(net, [(FEAT,)], max_batch=1, max_wait_ms=200.0,
                          queue_cap=64, name="cz")
    try:
        futs = [eng.submit(X) for _ in range(8)]
        for f in futs[2:]:
            assert f.cancel() is True
        # the two uncancelled requests complete normally
        for f in futs[:2]:
            assert f.result(30.0) is not None
    finally:
        eng.close()


# -- satellite: decorrelated-jitter backoff --------------------------------

def test_backoff_delays_decorrelated_jitter_bounds():
    rng = random.Random(42)
    delays = backoff_delays(8, 0.5, max_delay=10.0, rng=rng)
    assert len(delays) == 7
    prev = 0.5
    for d in delays:
        assert 0.5 <= d <= min(10.0, max(0.5, prev * 3.0)) + 1e-9
        prev = d
    # two processes (seeds) must NOT produce the same schedule
    other = backoff_delays(8, 0.5, max_delay=10.0,
                           rng=random.Random(43))
    assert delays != other


def test_backoff_delays_linear_when_jitter_off():
    assert backoff_delays(4, 0.5, jitter=False) == [0.5, 1.0, 1.5]


def test_backoff_delays_respect_max_delay():
    delays = backoff_delays(20, 1.0, max_delay=3.0,
                            rng=random.Random(7))
    assert all(d <= 3.0 for d in delays)


def test_retry_with_backoff_sleeps_jittered_delays():
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("transient")
        return "done"

    out = retry_with_backoff(flaky, attempts=3, base_delay=0.5,
                             rng=random.Random(1),
                             sleep=sleeps.append)
    assert out == "done"
    assert len(sleeps) == 2
    assert all(s >= 0.5 for s in sleeps)


def test_retry_with_backoff_no_retry_is_immediate():
    from mxnet_tpu.kvstore.dist import CollectiveTimeoutError

    calls = []

    def fatal():
        calls.append(1)
        raise CollectiveTimeoutError("partition, not transient")

    with pytest.raises(CollectiveTimeoutError):
        retry_with_backoff(fatal, attempts=5, base_delay=0.01,
                           no_retry=(CollectiveTimeoutError,),
                           sleep=lambda s: None)
    assert len(calls) == 1


# -- satellite: federation cluster_values consumer -------------------------

def _synth_snap(rank, depth, labels=(("model", "clf"),)):
    from mxnet_tpu.observability.federation import _encode_key

    return {"rank": rank, "wall": time.time(), "step_epoch": 1,
            "metrics": {"mxtpu_serving_queue_depth": {
                "kind": "gauge", "help": "",
                "values": {_encode_key(tuple(labels)): float(depth)}}}}


def test_cluster_values_reads_per_rank_depths():
    fed.ingest(_synth_snap(0, 3.0))
    fed.ingest(_synth_snap(1, 11.0))
    vals = fed.cluster_values("mxtpu_serving_queue_depth")
    assert vals == {0: 3.0, 1: 11.0}


def test_cluster_values_match_filter_and_sum():
    from mxnet_tpu.observability.federation import _encode_key

    snap = {"rank": 2, "wall": time.time(), "step_epoch": 1,
            "metrics": {"mxtpu_serving_queue_depth": {
                "kind": "gauge", "help": "",
                "values": {
                    _encode_key((("model", "clf"),)): 4.0,
                    _encode_key((("model", "other"),)): 100.0}}}}
    fed.ingest(snap)
    assert fed.cluster_values("mxtpu_serving_queue_depth",
                              match={"model": "clf"}) == {2: 4.0}
    # no filter: labelsets sum per rank
    assert fed.cluster_values(
        "mxtpu_serving_queue_depth")[2] == pytest.approx(104.0)


def test_cluster_values_excludes_stale_ranks():
    fed.ingest(_synth_snap(0, 3.0), recv_mono=time.monotonic() - 9999.0)
    assert fed.cluster_values("mxtpu_serving_queue_depth") == {}
    assert 0 in fed.cluster_values("mxtpu_serving_queue_depth",
                                   fresh_only=False)


def test_federation_depth_feed_routes_to_cluster_view():
    fed.ingest(_synth_snap(0, 50.0, labels=()))
    fed.ingest(_synth_snap(1, 1.0, labels=()))
    a = _FakeReplica(0, depth=None)
    b = _FakeReplica(1, depth=None)
    feed = federation_depth_feed(lambda r: r.index)
    router = ReplicaRouter(lambda: [a, b], retries=0, hedge_ms=0,
                           depth_feed=feed)
    assert router.submit(X).replica is b


def test_cold_federation_feed_falls_back_to_hash():
    a = _FakeReplica(0, depth=None)
    b = _FakeReplica(1, depth=None)
    feed = federation_depth_feed(lambda r: r.index)  # nothing ingested
    router = ReplicaRouter(lambda: [a, b], retries=0, hedge_ms=0,
                           depth_feed=feed)
    first = router._order("stable-key", set())
    again = router._order("stable-key", set())
    assert [r.uid for r in first] == [r.uid for r in again]


# -- telemetry report: Fleet section ---------------------------------------

def test_report_fleet_section():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools"))
    try:
        import telemetry_report as tr
    finally:
        sys.path.pop(0)

    events = [
        {"name": "fleet.autoscale", "cat": "serving",
         "args": {"model": "clf", "action": "replace", "n": 2}},
        {"name": "fleet.autoscale", "cat": "serving",
         "args": {"model": "clf", "action": "replace", "n": 2}},
        {"name": "fleet.autoscale", "cat": "serving",
         "args": {"model": "clf", "action": "grow", "n": 3}},
        {"name": "fleet.brownout", "cat": "serving",
         "args": {"model": "clf", "level": 1, "prev": 0}},
    ]
    out = tr.render_fleet(events)
    assert "Fleet:" in out
    assert "autoscale [clf] replace: 2" in out
    assert "autoscale [clf] grow: 1" in out
    assert "brownout [clf] level 0 -> 1" in out
    # crash-proofing contract: malformed args render, never raise
    assert "Fleet:" in tr.render_fleet(
        [{"name": "fleet.brownout", "args": None},
         {"name": "fleet.autoscale", "args": "garbage"}])
    assert tr.render_fleet([{"name": "trainer.step"}]) == ""
