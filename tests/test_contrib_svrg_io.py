"""contrib.svrg_optimization + contrib.io tests (reference:
tests/python/unittest/test_contrib_svrg_module.py, contrib/io.py)."""

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.contrib.io import DataLoaderIter
from mxnet_tpu.contrib.svrg_optimization import SVRGModule

sym = mx.sym


def _lin_problem(n=40, batch=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 3).astype(np.float32)
    w = np.array([[1.5], [-2.0], [0.5]], np.float32)
    Y = X @ w
    it = mx.io.NDArrayIter(X, Y, batch_size=batch, label_name="lin_label")
    data = sym.var("data")
    net = sym.FullyConnected(data, sym.var("fc_weight"), sym.var("fc_bias"),
                             num_hidden=1, name="fc")
    out = sym.LinearRegressionOutput(net, sym.var("lin_label"), name="lin")
    return it, out, w


def test_svrg_module_converges():
    it, out, w = _lin_problem()
    mod = SVRGModule(out, label_names=("lin_label",), update_freq=2)
    mod.fit(it, num_epoch=30, optimizer_params=(("learning_rate", 0.5),),
            eval_metric="mse")
    arg, _ = mod.get_params()
    got = arg["fc_weight"].asnumpy().ravel()
    assert np.max(np.abs(got - w.ravel())) < 0.25, got


def test_svrg_full_grads_and_correction():
    it, out, _ = _lin_problem()
    mod = SVRGModule(out, label_names=("lin_label",), update_freq=1)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer_params=(("learning_rate", 0.1),))
    mod.update_full_grads(it)
    assert "fc_weight" in mod._full_grads
    # snapshot grads at snapshot weights equal current grads before any
    # update -> corrected grad == full grad on the first step
    it.reset()
    batch = next(iter(it))
    mod.forward_backward(batch)
    g = mod._exec.grad_dict["fc_weight"].asnumpy()
    g_aux = mod._mod_aux._exec.grad_dict["fc_weight"].asnumpy()
    assert np.allclose(g, g_aux, atol=1e-5)
    # after an update the weights diverge from the snapshot
    mod.update()
    mod.forward_backward(batch)
    g2 = mod._exec.grad_dict["fc_weight"].asnumpy()
    g2_aux = mod._mod_aux._exec.grad_dict["fc_weight"].asnumpy()
    assert not np.allclose(g2, g2_aux, atol=1e-7)


def test_dataloader_iter():
    ds = gluon.data.ArrayDataset(mx.nd.random.uniform(shape=(20, 4)),
                                 mx.nd.arange(20))
    loader = gluon.data.DataLoader(ds, batch_size=5)
    it = DataLoaderIter(loader)
    assert it.batch_size == 5
    assert it.provide_data[0].shape == (5, 4)
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (5, 4)
    assert batches[0].label[0].shape == (5,)
    it.reset()
    assert len(list(it)) == 4
    # Module can consume it directly
    data = sym.var("data")
    fc = sym.FullyConnected(data, sym.var("w"), sym.var("b"), num_hidden=3)
    out = sym.SoftmaxOutput(fc, sym.var("softmax_label"))
    mod = mx.mod.Module(out)
    it.reset()
    mod.fit(it, num_epoch=1, optimizer_params=(("learning_rate", 0.01),))
