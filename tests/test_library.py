"""mx.library.load — runtime-loaded native op libraries (reference:
MXLoadLib, src/lib_api.cc; python/mxnet/library.py). The test compiles a
real C library with g++ and drives it through nd, jit, and hybridize."""

import os
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

_C_SRC = r"""
#include <string.h>

extern "C" {

int mxtpu_lib_num_ops(void) { return 2; }

const char* mxtpu_lib_op_name(int op) {
    return op == 0 ? "my_gemm" : "my_relu6";
}

int mxtpu_lib_op_num_inputs(int op) { return op == 0 ? 2 : 1; }

int mxtpu_lib_op_infer_shape(int op, const long long** in_shapes,
                             const int* in_ndims, int nin,
                             long long* out_shape) {
    if (op == 0) {
        if (nin != 2 || in_ndims[0] != 2 || in_ndims[1] != 2) return -1;
        if (in_shapes[0][1] != in_shapes[1][0]) return -1;
        out_shape[0] = in_shapes[0][0];
        out_shape[1] = in_shapes[1][1];
        return 2;
    }
    for (int d = 0; d < in_ndims[0]; ++d) out_shape[d] = in_shapes[0][d];
    return in_ndims[0];
}

int mxtpu_lib_op_compute(int op, const float** inputs,
                         const long long** in_shapes, const int* in_ndims,
                         int nin, float* out, const long long* out_shape,
                         int out_ndim) {
    if (op == 0) {
        long long m = in_shapes[0][0], k = in_shapes[0][1], n = in_shapes[1][1];
        for (long long i = 0; i < m; ++i)
            for (long long j = 0; j < n; ++j) {
                float acc = 0.f;
                for (long long p = 0; p < k; ++p)
                    acc += inputs[0][i * k + p] * inputs[1][p * n + j];
                out[i * n + j] = acc;
            }
        return 0;
    }
    long long total = 1;
    for (int d = 0; d < out_ndim; ++d) total *= out_shape[d];
    for (long long i = 0; i < total; ++i) {
        float v = inputs[0][i];
        out[i] = v < 0.f ? 0.f : (v > 6.f ? 6.f : v);
    }
    return 0;
}

}  // extern "C"
"""


@pytest.fixture(scope="module")
def native_lib(tmp_path_factory):
    d = tmp_path_factory.mktemp("libops")
    src = d / "myops.cc"
    so = d / "libmyops.so"
    src.write_text(_C_SRC)
    subprocess.check_call(["g++", "-O2", "-shared", "-fPIC",
                           str(src), "-o", str(so)])
    return str(so)


def test_library_load_and_compute(native_lib):
    names = mx.library.load(native_lib, verbose=False)
    assert set(names) == {"my_gemm", "my_relu6"}
    a = mx.nd.random.uniform(shape=(3, 4))
    b = mx.nd.random.uniform(shape=(4, 5))
    got = mx.nd.my_gemm(a, b).asnumpy()
    np.testing.assert_allclose(got, a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    x = mx.nd.array([-1.0, 3.0, 9.0])
    np.testing.assert_allclose(mx.nd.my_relu6(x).asnumpy(), [0.0, 3.0, 6.0])


def test_library_op_composes_with_jit(native_lib):
    import jax
    import jax.numpy as jnp

    mx.library.load(native_lib, verbose=False)
    from mxnet_tpu.ops.registry import get

    relu6 = get("my_relu6").fn

    @jax.jit
    def f(x):
        return relu6(x * 2.0) + 1.0

    out = f(jnp.array([-3.0, 1.0, 5.0]))
    np.testing.assert_allclose(np.asarray(out), [1.0, 3.0, 7.0])


def test_library_errors(native_lib):
    with pytest.raises(mx.base.MXNetError):
        mx.library.load("/nonexistent/libnope.so")
    mx.library.load(native_lib, verbose=False)
    # infer_shape failure surfaces as MXNetError (k mismatch)
    with pytest.raises(mx.base.MXNetError):
        mx.nd.my_gemm(mx.nd.ones((2, 3)), mx.nd.ones((4, 5)))


def test_library_op_available_in_symbol_api(native_lib):
    mx.library.load(native_lib, verbose=False)
    s = mx.sym.my_relu6(mx.sym.var("x"))
    ex = s.simple_bind(x=(3,))
    out = ex.forward(x=mx.nd.array([-1.0, 3.0, 9.0]))[0]
    np.testing.assert_allclose(out.asnumpy(), [0.0, 3.0, 6.0])
