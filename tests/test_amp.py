"""End-to-end mixed precision (PR5 tentpole): the dispatch-time fp32
cast policy, convert_model's norm pinning, fp32 master weights (fused
and eager), in-graph fp16 loss scaling (overflow -> skip -> backoff),
and the reduced-precision bucketed allreduce."""

import importlib.util
import os

import numpy as np
import pytest

from conftest import natsorted_items

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, fusedstep, gluon, observability as obs
from mxnet_tpu.gluon import nn

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _amp_off_after():
    yield
    amp.disable()


def _build_mlp(width=16, in_units=8, classes=3, n_hidden=2, seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    for _ in range(n_hidden):
        net.add(nn.Dense(width, activation="relu", in_units=in_units))
        in_units = width
    net.add(nn.Dense(classes, in_units=in_units))
    net.initialize(init=mx.initializer.Xavier())
    return net


# ---------------------------------------------------------------------------
# cast policy at op dispatch / trace time
# ---------------------------------------------------------------------------

def test_cast_policy_swaps_executables_and_keeps_dtype():
    from mxnet_tpu.ops import registry

    op = registry.get("softmax")
    off = registry.jitted(op, {"axis": -1})
    amp.init("bfloat16")
    on = registry.jitted(op, {"axis": -1})
    assert on is not off, "FP32-list op must use the cast-policy executable"
    x = mx.nd.array(np.random.rand(2, 5).astype(np.float32)).astype(
        "bfloat16")
    out = mx.nd.softmax(x)
    assert str(out.dtype) == "bfloat16"  # downcast back: activations stay low
    amp.disable()
    assert registry.jitted(op, {"axis": -1}) is off, \
        "disabling AMP must restore the original executable"


def test_cast_policy_upcasts_reduction_math():
    """mean over many bf16 values accumulates in fp32 under the policy:
    the result matches the fp64 reference to fp32-level error even
    though in- and outputs are bf16."""
    rng = np.random.RandomState(0)
    vals = rng.rand(4096).astype(np.float32)
    amp.init("bfloat16")
    x = mx.nd.array(vals).astype("bfloat16")
    got = float(mx.nd.mean(x).asnumpy().astype(np.float64))
    ref = float(np.asarray(vals, np.float64).mean())
    # the inputs are bf16-rounded (~0.4% per-element), but the fp32
    # accumulation keeps the MEAN error at rounding level, not O(n) drift
    assert got == pytest.approx(ref, rel=5e-3)
    assert str(mx.nd.mean(x).dtype) == "bfloat16"


def test_direct_state_reset_disables_policy():
    """Legacy tests flip ``amp._STATE['target_dtype']`` directly; the
    policy checks must read the shared dict, not a separate flag."""
    from mxnet_tpu.amp import policy

    amp.init("bfloat16")
    assert policy.cast_active()
    mx.amp._STATE["target_dtype"] = None
    assert not policy.cast_active()
    assert not amp.is_enabled()


def test_amp_toggle_retraces_cached_graph():
    """The CachedGraph key carries the AMP dtype: toggling amp.init()
    must not replay a pre-policy executable (and names the cause)."""
    prev = obs.set_enabled(True)
    try:
        obs.reset()
        net = nn.Dense(4, in_units=6)
        net.initialize(init=mx.initializer.Xavier())
        net.hybridize()
        x = mx.nd.ones((2, 6))
        net(x)
        net(x)
        compiled0 = obs.CACHEDOP_COMPILE_TOTAL.value(block=net.name)
        amp.init("bfloat16")
        net(x)
        assert obs.CACHEDOP_COMPILE_TOTAL.value(block=net.name) \
            == compiled0 + 1
        causes = [dict(k).get("cause", "")
                  for k in obs.CACHEDOP_RETRACE_TOTAL._values]
        assert any("amp" in c for c in causes), causes
    finally:
        obs.set_enabled(prev)
        obs.reset()


# ---------------------------------------------------------------------------
# convert_model: norm layers pinned fp32
# ---------------------------------------------------------------------------

def test_convert_model_pins_norm_stats_fp32_model_zoo():
    from mxnet_tpu.gluon.model_zoo import vision

    amp.init("bfloat16")
    net = vision.resnet18_v1(classes=4)
    net.initialize(init=mx.initializer.Xavier())
    amp.convert_model(net)
    # resolve deferred-init shapes (conv in_channels) with one forward
    with autograd.predict_mode():
        net(mx.nd.zeros((1, 3, 32, 32)).astype("bfloat16"))
    saw_bn = saw_conv = False
    for name, p in net.collect_params().items():
        if "batchnorm" in name or "running_" in name or "gamma" in name \
                or "beta" in name:
            assert str(p.data().dtype) == "float32", \
                f"norm param {name} must stay fp32"
            saw_bn = True
        elif "conv" in name or "dense" in name:
            assert str(p.data().dtype) == "bfloat16", \
                f"compute param {name} must be bf16"
            saw_conv = True
    assert saw_bn and saw_conv
    x = mx.nd.random.uniform(shape=(2, 3, 32, 32)).astype("bfloat16")
    with autograd.predict_mode():
        out = net(x)
    assert str(out.dtype) == "bfloat16"
    assert np.isfinite(out.asnumpy().astype(np.float32)).all()


def test_convert_model_layernorm_pinned():
    amp.init("bfloat16")
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=8), nn.LayerNorm(in_channels=8))
    net.initialize()
    amp.convert_model(net)
    dense = net._children["0"]
    ln = net._children["1"]
    assert str(dense.weight.data().dtype) == "bfloat16"
    assert str(ln.gamma.data().dtype) == "float32"
    out = net(mx.nd.ones((2, 8)).astype("bfloat16"))
    assert str(out.dtype) == "bfloat16"  # policy downcasts LayerNorm's fp32


# ---------------------------------------------------------------------------
# bf16 training parity + master weights
# ---------------------------------------------------------------------------

def _train_losses(dtype, steps=6, multi_precision=True):
    if dtype != "float32":
        amp.init(dtype)
    try:
        np.random.seed(0)
        net = _build_mlp()
        if dtype != "float32":
            amp.convert_model(net)
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9,
                            "multi_precision": multi_precision
                            and dtype != "float32"},
                           kvstore=None)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        X = mx.nd.array(np.random.RandomState(1).rand(16, 8)
                        .astype(np.float32))
        Y = mx.nd.array(np.random.RandomState(2).randint(0, 3, (16,))
                        .astype(np.float32))
        if dtype != "float32":
            X = X.astype(dtype)
        losses = []
        for _ in range(steps):
            with autograd.record():
                l = loss_fn(net(X), Y)
            l.backward()
            tr.step(16)
            losses.append(float(l.mean().asnumpy().astype(np.float64)))
        assert tr._fused not in (False, None), "fused path must engage"
        return losses
    finally:
        amp.disable()


def test_bf16_fp32_loss_trajectory_parity():
    """The acceptance contract: bf16 training (cast policy + fp32
    masters) tracks the fp32 loss trajectory within bf16 tolerance on
    the bench MLP."""
    l32 = _train_losses("float32")
    l16 = _train_losses("bfloat16")
    for a, b in zip(l32, l16):
        assert b == pytest.approx(a, rel=0.08, abs=0.05), (l32, l16)
    # and it actually trains (loss decreases)
    assert l16[-1] < l16[0]


def test_fused_bf16_master_weights_in_state():
    amp.init("bfloat16")
    net = _build_mlp(n_hidden=1)
    amp.convert_model(net)
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9,
                        "multi_precision": True}, kvstore=None)
    X = mx.nd.ones((4, 8)).astype("bfloat16")
    for _ in range(2):
        with autograd.record():
            l = (net(X) ** 2).sum()
        l.backward()
        tr.step(4)
    assert tr._fused not in (False, None)
    name, st = natsorted_items(tr._fused_states.items())[0]
    # (fp32 master, fp32 momentum) for a bf16 param
    assert len(st) == 2 and all(str(s.dtype) == "float32" for s in st)
    p = dict(net.collect_params().items())[name]
    assert str(p.data().dtype) == "bfloat16"
    # stored weight is the rounded view of the master
    np.testing.assert_allclose(
        p.data().asnumpy().astype(np.float32),
        np.asarray(st[0].astype(np.float32)), rtol=1e-2, atol=1e-2)


def test_eager_bf16_master_weights(monkeypatch):
    """Satellite: create_state_multi_precision/update_multi_precision
    treat bfloat16 like float16 — the eager path gets masters too."""
    from mxnet_tpu.optimizer import SGD

    opt = SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    w = mx.nd.array(np.ones((4,), np.float32)).astype("bfloat16")
    state = opt.create_state_multi_precision(0, w)
    master, mom = state
    assert str(master.dtype) == "float32"
    assert str(mom.dtype) == "float32"
    g = mx.nd.array(np.full((4,), 0.5, np.float32)).astype("bfloat16")
    opt.update_multi_precision(0, w, g, state)
    assert str(w.dtype) == "bfloat16"
    np.testing.assert_allclose(np.asarray(master.data), np.full((4,), 0.95),
                               rtol=1e-6)


def test_mp_bf16_fused_to_eager_migration_keeps_master():
    """Flipping the fused path off mid-run must hand the fp32 master
    (and momentum) to the eager per-param path — trajectory matches an
    all-eager multi_precision run."""
    def run(flip_at):
        amp.init("bfloat16")
        try:
            np.random.seed(0)
            net = _build_mlp(n_hidden=1, seed=0)
            amp.convert_model(net)
            net.hybridize()
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9,
                                "multi_precision": True}, kvstore=None)
            X = mx.nd.array(np.random.RandomState(1).rand(8, 8)
                            .astype(np.float32)).astype("bfloat16")
            for i in range(6):
                if i == flip_at:
                    fusedstep.set_enabled(False)
                with autograd.record():
                    l = (net(X) ** 2).sum()
                l.backward()
                tr.step(8)
            fusedstep.set_enabled(True)
            p = natsorted_items(net.collect_params().items())[0][1]
            return p.data().asnumpy().astype(np.float32)
        finally:
            fusedstep.set_enabled(True)
            amp.disable()

    mixed = run(flip_at=3)
    eager = run(flip_at=0)
    np.testing.assert_allclose(mixed, eager, rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# fp16 in-graph loss scaling
# ---------------------------------------------------------------------------

def _fp16_net_and_trainer(window=1000):
    amp.init("float16")
    mx.random.seed(0)
    net = nn.Dense(4, in_units=8)
    net.initialize(init=mx.initializer.Xavier())
    amp.convert_model(net)
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01, "multi_precision": True},
                       kvstore=None)
    amp.init_trainer(tr)
    tr._amp_loss_scaler = amp.LossScaler(init_scale=1024.0,
                                         scale_factor=2.0,
                                         scale_window=window)
    return net, tr


def test_fp16_overflow_skip_backoff_fused():
    import jax.numpy as jnp

    net, tr = _fp16_net_and_trainer()
    X = mx.nd.ones((4, 8)).astype("float16")
    w_snap = None
    for i in range(4):
        with autograd.record():
            l = (net(X) ** 2).sum()
            with amp.scale_loss(l, tr) as sl:
                sl.backward()
        if i == 1:  # inject an overflow after backward
            w_snap = net.weight.data().asnumpy().copy()
            g = net.weight.grad(None)
            g._set_data(jnp.full(g.shape, jnp.inf, g.data.dtype))
        tr.step(4)
        if i == 1:
            # skip-update: the poisoned step left the weights untouched
            np.testing.assert_array_equal(net.weight.data().asnumpy(),
                                          w_snap)
    assert tr._fused not in (False, None), "fp16 amp must ride the fused path"
    scaler = tr._amp_loss_scaler
    assert scaler.loss_scale == 512.0  # one backoff
    assert scaler.overflow_total == 1
    w = net.weight.data().asnumpy().astype(np.float32)
    assert np.isfinite(w).all(), "no NaN may reach the (master) weights"
    # master state stayed finite too
    for st in tr._fused_states.values():
        for leaf in st:
            assert np.isfinite(np.asarray(leaf, np.float32)).all()


def test_fp16_tiny_combined_rescale_does_not_underflow():
    """Code-review regression: (1/batch)/loss_scale at batch 4096 x
    scale 2^15 is 7.5e-9 — below fp16's 6e-8 subnormal floor. The fused
    update must apply it AFTER upcasting the grad to fp32, or every
    update silently rounds to zero while training 'runs' happily.
    (2^15, not 2^16: a 2^16 cotangent itself exceeds fp16 max 65504 and
    would trigger the overflow-skip path instead of exercising the
    rescale.)"""
    amp.init("float16")
    mx.random.seed(0)
    net = nn.Dense(4, in_units=8)
    net.initialize(init=mx.initializer.Xavier())
    amp.convert_model(net)
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 1.0, "multi_precision": True},
                       kvstore=None)
    amp.init_trainer(tr)
    tr._amp_loss_scaler = amp.LossScaler(init_scale=2 ** 15,
                                         scale_window=10 ** 6)
    X = (mx.nd.ones((4, 8)) * 0.01).astype("float16")
    w0 = net.weight.data().asnumpy().astype(np.float64)
    for _ in range(3):
        with autograd.record():
            l = (net(X) ** 2).sum()
            with amp.scale_loss(l, tr) as sl:
                sl.backward()
        tr.step(4096)
    assert tr._fused not in (False, None)
    assert tr._amp_loss_scaler.overflow_total == 0, \
        "probe invalidated: grads overflowed, rescale never exercised"
    master = np.asarray(
        tr._fused_states[net.weight.name][0]).astype(np.float64)
    delta = np.abs(master - w0).max()
    assert delta > 0.0, \
        "combined rescale underflowed fp16: updates silently zeroed"


def test_amp_reinit_with_fp32_ops_retraces():
    """Code-review regression: re-initializing AMP with an extended
    fp32_ops list must retrace cached executables (the cast_ops set is
    part of the CachedGraph key, not just the target dtype)."""
    prev = obs.set_enabled(True)
    try:
        obs.reset()
        amp.init("bfloat16")
        net = nn.Dense(4, in_units=6)
        net.initialize(init=mx.initializer.Xavier())
        net.hybridize()
        x = mx.nd.ones((2, 6)).astype("bfloat16")
        net(x)
        net(x)
        compiled0 = obs.CACHEDOP_COMPILE_TOTAL.value(block=net.name)
        amp.init("bfloat16", fp32_ops=["FullyConnected"])
        net(x)
        assert obs.CACHEDOP_COMPILE_TOTAL.value(block=net.name) \
            == compiled0 + 1, "extended fp32_ops silently ignored"
    finally:
        obs.set_enabled(prev)
        obs.reset()


def test_fp16_eager_fallback_unscales_buffers():
    """The per-param fallback divides the gradient BUFFERS by the scale
    (not a hidden rescale fold): user-visible grads are TRUE grads
    after step, like the pre-deferral scale_loss semantics."""
    prev = fusedstep.set_enabled(False)
    try:
        net, tr = _fp16_net_and_trainer()
        X = mx.nd.ones((4, 8)).astype("float16")
        with autograd.record():
            l = (net(X) ** 2).sum()
            with amp.scale_loss(l, tr) as sl:
                sl.backward()
        scaled = net.weight.grad(None).asnumpy().astype(np.float32).copy()
        tr.step(4)
        unscaled = net.weight.grad(None).asnumpy().astype(np.float32)
        np.testing.assert_allclose(unscaled * 1024.0, scaled, rtol=2e-3,
                                   atol=1e-4)
    finally:
        fusedstep.set_enabled(prev)


def test_fp16_scale_growth_after_window():
    net, tr = _fp16_net_and_trainer(window=2)
    X = mx.nd.ones((4, 8)).astype("float16")
    for _ in range(4):  # 4 clean scaled steps, window 2 -> two growths
        with autograd.record():
            l = (net(X) ** 2).sum()
            with amp.scale_loss(l, tr) as sl:
                sl.backward()
        tr.step(4)
    assert tr._amp_loss_scaler.loss_scale == 4096.0


def test_fp16_eager_fallback_skips_and_backs_off():
    """MXTPU_FUSED_STEP off: the deferred scale_loss resolves on the
    per-param path — one fused isfinite reduction, hard skip, host-side
    scale update."""
    import jax.numpy as jnp

    prev = fusedstep.set_enabled(False)
    try:
        net, tr = _fp16_net_and_trainer()
        X = mx.nd.ones((4, 8)).astype("float16")
        for i in range(3):
            with autograd.record():
                l = (net(X) ** 2).sum()
                with amp.scale_loss(l, tr) as sl:
                    sl.backward()
            if i == 1:
                snap = net.weight.data().asnumpy().copy()
                g = net.weight.grad(None)
                g._set_data(jnp.full(g.shape, jnp.inf, g.data.dtype))
            tr.step(4)
            if i == 1:
                np.testing.assert_array_equal(net.weight.data().asnumpy(),
                                              snap)
        assert tr._amp_loss_scaler.loss_scale == 512.0
        assert np.isfinite(net.weight.data().asnumpy()
                           .astype(np.float32)).all()
    finally:
        fusedstep.set_enabled(prev)


def test_unscale_divides_pending_grads():
    net, tr = _fp16_net_and_trainer()
    X = mx.nd.ones((4, 8)).astype("float16")
    with autograd.record():
        l = (net(X) ** 2).sum()
        with amp.scale_loss(l, tr) as sl:
            sl.backward()
    scaled = net.weight.grad(None).asnumpy().astype(np.float32).copy()
    amp.unscale(tr)
    unscaled = net.weight.grad(None).asnumpy().astype(np.float32)
    np.testing.assert_allclose(unscaled * 1024.0, scaled, rtol=1e-3)
    # pending moves to "unscaled" (NOT off): step keeps the overflow
    # check + scale update armed, it just won't divide again
    assert tr._amp_pending == "unscaled"


def test_unscale_then_step_no_double_division():
    """Code-review regression: amp.unscale moves pending to 'unscaled'
    — the following step must NOT divide by the scale again. The
    unscale+step run lands on the same weights as the plain
    scale_loss+step run (fused path)."""
    def run(with_unscale):
        np.random.seed(0)
        net, tr = _fp16_net_and_trainer()
        X = mx.nd.ones((4, 8)).astype("float16")
        for _ in range(3):
            with autograd.record():
                l = (net(X) ** 2).sum()
                with amp.scale_loss(l, tr) as sl:
                    sl.backward()
            if with_unscale:
                amp.unscale(tr)
            tr.step(4)
        assert tr._fused not in (False, None)
        return net.weight.data().asnumpy().astype(np.float32)

    np.testing.assert_allclose(run(True), run(False), rtol=2e-3,
                               atol=1e-4)


@pytest.mark.parametrize("fused", [True, False])
def test_unscale_keeps_overflow_protection_armed(fused):
    """Code-review regression (CONFIRMED repro): the documented
    unscale-then-clip recipe must not disarm the deferred overflow
    check — an inf gradient after amp.unscale still skips the update
    and backs the scale off, on both paths."""
    import jax.numpy as jnp

    prev = fusedstep.set_enabled(fused)
    try:
        net, tr = _fp16_net_and_trainer()
        X = mx.nd.ones((4, 8)).astype("float16")
        with autograd.record():
            l = (net(X) ** 2).sum()
            with amp.scale_loss(l, tr) as sl:
                sl.backward()
        g = net.weight.grad(None)
        g._set_data(jnp.full(g.shape, jnp.inf, g.data.dtype))
        amp.unscale(tr)  # inf/scale is still inf: check must stay armed
        w0 = net.weight.data().asnumpy().copy()
        tr.step(4)
        np.testing.assert_array_equal(net.weight.data().asnumpy(), w0)
        assert np.isfinite(net.weight.data().asnumpy()
                           .astype(np.float32)).all()
        assert tr._amp_loss_scaler.loss_scale == 512.0, \
            "scale must back off even after a user unscale"
        assert tr._amp_loss_scaler.overflow_total == 1
    finally:
        fusedstep.set_enabled(prev)


def test_has_overflow_single_fused_reduction():
    """Satellite: no per-param numpy loop — one fused reduction handles
    Parameters, NDArrays-with-grads, and plain arrays alike."""
    ls = amp.LossScaler()
    assert not ls.has_overflow([])
    assert not ls.has_overflow([mx.nd.ones((3,)), mx.nd.ones((2, 2))])
    assert ls.has_overflow([mx.nd.ones((3,)),
                            mx.nd.array([np.nan, 1.0])])
    p = gluon.Parameter("w", shape=(4,))
    p.initialize(ctx=mx.cpu())
    p.data().attach_grad()
    with autograd.record():
        (p.data() * 2).sum().backward()
    assert not ls.has_overflow([p])


# ---------------------------------------------------------------------------
# reduced-precision bucketed allreduce
# ---------------------------------------------------------------------------

def _fake_dist_store():
    """A KVStoreLocal subclass whose bucket reduction is live (simulates
    the dist store's per-bucket allreduce on one process): doubles each
    bucket and records the dtype it saw on the 'wire'."""
    from mxnet_tpu.kvstore.local import KVStoreLocal

    seen = []

    class FakeDist(KVStoreLocal):
        def _reduce_raw(self, raw):
            seen.append(str(raw.dtype))
            return raw + raw

        def _reduce(self, key, merged):  # per-key path parity
            from mxnet_tpu.ndarray.ndarray import NDArray

            return NDArray(merged.data * 2, ctx=merged.ctx)

    return FakeDist(), seen


def test_amp_allreduce_dtype_casts_buckets(monkeypatch):
    monkeypatch.setenv("MXTPU_AMP_ALLREDUCE_DTYPE", "bfloat16")
    kv, seen = _fake_dist_store()
    rng = np.random.RandomState(0)
    keys, vals, outs, ref = [], [], [], []
    for i, sh in enumerate([(64,), (7, 3), (129,)]):
        a = rng.rand(*sh).astype(np.float32)
        kv.init(i, mx.nd.zeros(sh))
        keys.append(i)
        vals.append([mx.nd.array(a)])
        outs.append(mx.nd.zeros(sh))
        ref.append(2 * a)
    kv.pushpull(keys, vals, out=outs)
    assert seen and all(d == "bfloat16" for d in seen), seen
    for o, e in zip(outs, ref):
        assert str(o.dtype) == "float32"
        np.testing.assert_allclose(o.asnumpy(), e, rtol=1e-2, atol=1e-2)


def test_amp_allreduce_dtype_off_by_default():
    kv, seen = _fake_dist_store()
    kv.init(0, mx.nd.zeros((16,)))
    outs = [mx.nd.zeros((16,))]
    kv.pushpull([0], [[mx.nd.ones((16,))]], out=outs)
    assert seen == ["float32"], seen
    np.testing.assert_allclose(outs[0].asnumpy(), np.full((16,), 2.0))


def test_amp_allreduce_dtype_leaves_fp16_buckets_alone(monkeypatch):
    monkeypatch.setenv("MXTPU_AMP_ALLREDUCE_DTYPE", "bfloat16")
    kv, seen = _fake_dist_store()
    kv.init(0, mx.nd.zeros((8,), dtype="float16"))
    outs = [mx.nd.zeros((8,), dtype="float16")]
    kv.pushpull([0], [[mx.nd.ones((8,), dtype="float16")]], out=outs)
    assert seen == ["float16"], seen  # already half: no extra cast


def test_amp_allreduce_dtype_invalid_ignored(monkeypatch):
    monkeypatch.setenv("MXTPU_AMP_ALLREDUCE_DTYPE", "float8")
    assert fusedstep.amp_allreduce_dtype() == ""


def test_dist_accum_sum_fp32_accumulation():
    import jax.numpy as jnp

    from mxnet_tpu.kvstore.dist import _accum_sum

    # 256 bf16 ones: a bf16 accumulator saturates (1 ulp at 256 is 2),
    # fp32 accumulation returns the exact count
    a = jnp.ones((256, 4), jnp.bfloat16) * 1.0078125  # needs low bits
    out = _accum_sum(a)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.full((4,), 258.0), rtol=1e-2)


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_amp_gauges_lazy_under_fused_step():
    import jax.numpy as jnp

    prev = obs.set_enabled(True)
    try:
        obs.reset()
        net, tr = _fp16_net_and_trainer()
        X = mx.nd.ones((4, 8)).astype("float16")
        for i in range(2):
            with autograd.record():
                l = (net(X) ** 2).sum()
                with amp.scale_loss(l, tr) as sl:
                    sl.backward()
            if i == 0:
                g = net.weight.grad(None)
                g._set_data(jnp.full(g.shape, jnp.inf, g.data.dtype))
            tr.step(4)
        stored = obs.AMP_OVERFLOW_TOTAL._values.get(())
        assert stored is not None and not isinstance(stored, float), \
            "fused amp must store a lazy device scalar, not a synced float"
        assert obs.AMP_OVERFLOW_TOTAL.value() == 1.0
        assert obs.AMP_LOSS_SCALE.value() == 512.0
        dump = obs.dump_prometheus()
        assert "mxtpu_amp_overflow_total" in dump
        assert "mxtpu_amp_loss_scale" in dump
    finally:
        obs.set_enabled(prev)
        obs.reset()


def _load_report_tool():
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(ROOT, "tools",
                                         "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_telemetry_report_amp_section_crash_proof():
    tool = _load_report_tool()
    assert tool.render_amp([]) == ""
    assert tool.render_amp([{"name": "trainer.step", "dur": 1.0}]) == ""
    evs = [
        {"name": "amp.scale_update", "cat": "amp", "dur": 0.0,
         "args": {"scale": 512.0, "overflow_total": 1, "overflow": True}},
        {"name": "amp.scale_update", "cat": "amp", "dur": 0.0,
         "args": {"scale": 512.0, "overflow_total": 1, "overflow": False}},
        {"name": "amp.scale_update", "cat": "amp", "dur": 0.0,
         "args": None},  # malformed args must not crash
    ]
    out = tool.render_amp(evs)
    assert "AMP loss scaling" in out and "overflows (skipped steps): 1" in out
    # and the generic table aggregates the unknown series without crashing
    assert "amp.scale_update" in tool.render_table(evs)


def test_eager_update_scale_emits_trace_event():
    prev = obs.set_enabled(True)
    try:
        obs.reset()
        ls = amp.LossScaler(init_scale=64.0, scale_factor=2.0)
        ls.update_scale(True)
        evs = [e for e in obs.tracer().events()
               if e["name"] == "amp.scale_update"]
        assert evs and evs[-1]["args"]["overflow"] is True
        assert obs.AMP_LOSS_SCALE.value() == 32.0
    finally:
        obs.set_enabled(prev)
        obs.reset()


# ---------------------------------------------------------------------------
# load_parameters after convert_model (PR 8 satellite): the saved mixed
# dtype set (fp32-pinned norm layers + low-precision compute weights)
# must restore to exactly the same dtypes, and the fused plan must keep
# working across the reload
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [
    pytest.param("bfloat16", marks=pytest.mark.slow),  # fp16 cell is
    "float16",  # the superset: masters + scaler ride the load
])
def test_load_parameters_after_convert_model(tmp_path, dtype):
    amp.init(dtype)

    def build():
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, in_units=8))
        net.add(nn.BatchNorm(in_channels=8))
        net.add(nn.Dense(3, in_units=8))
        net.initialize(init=mx.initializer.Xavier())
        amp.convert_model(net)
        net.hybridize()
        return net

    net = build()
    X = mx.nd.ones((4, 8)).astype(dtype)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9,
                        "multi_precision": True}, kvstore=None)
    if dtype == "float16":
        amp.init_trainer(tr)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(2):  # real training: running stats move, plan builds
        with autograd.record():
            l = loss_fn(net(X), mx.nd.zeros((4,)))
            if dtype == "float16":
                with amp.scale_loss(l, tr) as sl:
                    sl.backward()
        if dtype != "float16":
            l.backward()
        tr.step(4)
    assert isinstance(tr._fused, dict)  # fast path active pre-save
    fname = str(tmp_path / "mixed.params")
    net.save_parameters(fname)

    # restore into a FRESH converted net: every dtype must come back
    # exactly (low-precision compute weights, fp32 norm params + stats)
    net2 = build()
    net2.load_parameters(fname)
    p1 = net._collect_params_with_prefix()
    p2 = net2._collect_params_with_prefix()
    saw_low = saw_f32 = False
    for name in p1:
        d1, d2 = p1[name].data(), p2[name].data()
        assert str(d2.dtype) == str(d1.dtype), \
            f"{name}: saved {d1.dtype} restored as {d2.dtype}"
        np.testing.assert_array_equal(
            np.asarray(d1.data.astype("float32")),
            np.asarray(d2.data.astype("float32")))
        if str(d1.dtype) == dtype:
            saw_low = True
        if str(d1.dtype) == "float32":
            saw_f32 = True
    assert saw_low and saw_f32  # the mix survived, not a blanket cast

    # and reloading into the LIVE net must not break the fused plan:
    # _load_init mutates the existing handles in place, so the cached
    # plan stays valid and the next step still takes the fast path
    plan_before = tr._fused
    net.load_parameters(fname)
    with autograd.record():
        l = loss_fn(net(X), mx.nd.zeros((4,)))
        if dtype == "float16":
            with amp.scale_loss(l, tr) as sl:
                sl.backward()
    if dtype != "float16":
        l.backward()
    tr.step(4)
    assert isinstance(tr._fused, dict)
    assert tr._fused is plan_before  # not invalidated by the reload
