"""gluon.rnn tests (reference model: test_gluon_rnn.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import rnn


def test_lstm_layer_shapes():
    layer = rnn.LSTM(hidden_size=10, num_layers=2)
    layer.initialize()
    x = mx.nd.random.normal(shape=(5, 3, 8))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 10)
    states = layer.begin_state(batch_size=3)
    out, new_states = layer(x, states)
    assert out.shape == (5, 3, 10)
    assert new_states[0].shape == (2, 3, 10)
    assert new_states[1].shape == (2, 3, 10)


def test_gru_rnn_layers():
    for layer in (rnn.GRU(hidden_size=6), rnn.RNN(hidden_size=6,
                                                  activation="tanh")):
        layer.initialize()
        out = layer(mx.nd.random.normal(shape=(4, 2, 5)))
        assert out.shape == (4, 2, 6)


def test_bidirectional_lstm():
    layer = rnn.LSTM(hidden_size=7, bidirectional=True)
    layer.initialize()
    out = layer(mx.nd.random.normal(shape=(4, 2, 5)))
    assert out.shape == (4, 2, 14)


def test_ntc_layout():
    layer = rnn.LSTM(hidden_size=4, layout="NTC")
    layer.initialize()
    out = layer(mx.nd.random.normal(shape=(2, 6, 3)))
    assert out.shape == (2, 6, 4)


def test_lstm_gradient_flow():
    layer = rnn.LSTM(hidden_size=5)
    layer.initialize()
    x = mx.nd.random.normal(shape=(3, 2, 4))
    x.attach_grad()
    with autograd.record():
        out = layer(x)
        loss = out.sum()
    loss.backward()
    assert np.abs(x.grad.asnumpy()).sum() > 0
    for p in layer.collect_params().values():
        if p.grad_req != "null":
            assert np.isfinite(p.grad().asnumpy()).all()


def test_lstm_cell_unroll():
    cell = rnn.LSTMCell(hidden_size=6, input_size=4)
    cell.initialize()
    x = mx.nd.random.normal(shape=(2, 5, 4))  # NTC
    outputs, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 5, 6)
    assert states[0].shape == (2, 6)


def test_cell_fused_consistency():
    """Unfused LSTMCell.unroll must match the fused LSTM layer."""
    T, N, C, H = 4, 2, 3, 5
    fused = rnn.LSTM(hidden_size=H, input_size=C, prefix="l_")
    fused.initialize()
    cell = rnn.LSTMCell(hidden_size=H, input_size=C, prefix="c_")
    cell.initialize()
    # copy fused params into the cell
    fp = {k.split("l_")[-1]: v for k, v in fused.collect_params().items()}
    cp = cell.collect_params()
    cp["c_i2h_weight"].set_data(fp["l0_i2h_weight"].data())
    cp["c_h2h_weight"].set_data(fp["l0_h2h_weight"].data())
    cp["c_i2h_bias"].set_data(fp["l0_i2h_bias"].data())
    cp["c_h2h_bias"].set_data(fp["l0_h2h_bias"].data())

    x = mx.nd.random.normal(shape=(T, N, C))
    out_fused = fused(x).asnumpy()
    outs, _ = cell.unroll(T, [x[t] for t in range(T)], layout="TNC",
                          merge_outputs=False)
    out_cell = np.stack([o.asnumpy() for o in outs])
    np.testing.assert_allclose(out_fused, out_cell, rtol=1e-4, atol=1e-5)


def test_sequential_rnn_cell():
    seq = rnn.SequentialRNNCell()
    seq.add(rnn.LSTMCell(4, input_size=3))
    seq.add(rnn.GRUCell(5, input_size=4))
    seq.initialize()
    states = seq.begin_state(batch_size=2)
    out, new_states = seq(mx.nd.random.normal(shape=(2, 3)), states)
    assert out.shape == (2, 5)
    assert len(new_states) == 3  # 2 lstm + 1 gru


def test_residual_and_dropout_cells():
    cell = rnn.ResidualCell(rnn.RNNCell(4, input_size=4))
    cell.initialize()
    x = mx.nd.random.normal(shape=(2, 4))
    out, _ = cell(x, cell.begin_state(2))
    assert out.shape == (2, 4)
    dc = rnn.DropoutCell(0.5)
    out2, _ = dc(x, [])
    assert out2.shape == (2, 4)


def test_hybridized_lstm():
    layer = rnn.LSTM(hidden_size=6, input_size=5)
    layer.initialize()
    x = mx.nd.random.normal(shape=(3, 2, 5))
    ref = layer(x).asnumpy()
    layer.hybridize()
    out = layer(x).asnumpy()
    np.testing.assert_allclose(ref, out, rtol=1e-4, atol=1e-5)
