"""gluon.rnn tests (reference model: test_gluon_rnn.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import rnn


def test_lstm_layer_shapes():
    layer = rnn.LSTM(hidden_size=10, num_layers=2)
    layer.initialize()
    x = mx.nd.random.normal(shape=(5, 3, 8))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 10)
    states = layer.begin_state(batch_size=3)
    out, new_states = layer(x, states)
    assert out.shape == (5, 3, 10)
    assert new_states[0].shape == (2, 3, 10)
    assert new_states[1].shape == (2, 3, 10)


def test_gru_rnn_layers():
    for layer in (rnn.GRU(hidden_size=6), rnn.RNN(hidden_size=6,
                                                  activation="tanh")):
        layer.initialize()
        out = layer(mx.nd.random.normal(shape=(4, 2, 5)))
        assert out.shape == (4, 2, 6)


def test_bidirectional_lstm():
    layer = rnn.LSTM(hidden_size=7, bidirectional=True)
    layer.initialize()
    out = layer(mx.nd.random.normal(shape=(4, 2, 5)))
    assert out.shape == (4, 2, 14)


def test_ntc_layout():
    layer = rnn.LSTM(hidden_size=4, layout="NTC")
    layer.initialize()
    out = layer(mx.nd.random.normal(shape=(2, 6, 3)))
    assert out.shape == (2, 6, 4)


def test_lstm_gradient_flow():
    layer = rnn.LSTM(hidden_size=5)
    layer.initialize()
    x = mx.nd.random.normal(shape=(3, 2, 4))
    x.attach_grad()
    with autograd.record():
        out = layer(x)
        loss = out.sum()
    loss.backward()
    assert np.abs(x.grad.asnumpy()).sum() > 0
    for p in layer.collect_params().values():
        if p.grad_req != "null":
            assert np.isfinite(p.grad().asnumpy()).all()


def test_lstm_cell_unroll():
    cell = rnn.LSTMCell(hidden_size=6, input_size=4)
    cell.initialize()
    x = mx.nd.random.normal(shape=(2, 5, 4))  # NTC
    outputs, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 5, 6)
    assert states[0].shape == (2, 6)


def test_cell_fused_consistency():
    """Unfused LSTMCell.unroll must match the fused LSTM layer."""
    T, N, C, H = 4, 2, 3, 5
    fused = rnn.LSTM(hidden_size=H, input_size=C, prefix="l_")
    fused.initialize()
    cell = rnn.LSTMCell(hidden_size=H, input_size=C, prefix="c_")
    cell.initialize()
    # copy fused params into the cell
    fp = {k.split("l_")[-1]: v for k, v in fused.collect_params().items()}
    cp = cell.collect_params()
    cp["c_i2h_weight"].set_data(fp["l0_i2h_weight"].data())
    cp["c_h2h_weight"].set_data(fp["l0_h2h_weight"].data())
    cp["c_i2h_bias"].set_data(fp["l0_i2h_bias"].data())
    cp["c_h2h_bias"].set_data(fp["l0_h2h_bias"].data())

    x = mx.nd.random.normal(shape=(T, N, C))
    out_fused = fused(x).asnumpy()
    outs, _ = cell.unroll(T, [x[t] for t in range(T)], layout="TNC",
                          merge_outputs=False)
    out_cell = np.stack([o.asnumpy() for o in outs])
    np.testing.assert_allclose(out_fused, out_cell, rtol=1e-4, atol=1e-5)


def test_sequential_rnn_cell():
    seq = rnn.SequentialRNNCell()
    seq.add(rnn.LSTMCell(4, input_size=3))
    seq.add(rnn.GRUCell(5, input_size=4))
    seq.initialize()
    states = seq.begin_state(batch_size=2)
    out, new_states = seq(mx.nd.random.normal(shape=(2, 3)), states)
    assert out.shape == (2, 5)
    assert len(new_states) == 3  # 2 lstm + 1 gru


def test_residual_and_dropout_cells():
    cell = rnn.ResidualCell(rnn.RNNCell(4, input_size=4))
    cell.initialize()
    x = mx.nd.random.normal(shape=(2, 4))
    out, _ = cell(x, cell.begin_state(2))
    assert out.shape == (2, 4)
    dc = rnn.DropoutCell(0.5)
    out2, _ = dc(x, [])
    assert out2.shape == (2, 4)


def test_hybridized_lstm():
    layer = rnn.LSTM(hidden_size=6, input_size=5)
    layer.initialize()
    x = mx.nd.random.normal(shape=(3, 2, 5))
    ref = layer(x).asnumpy()
    layer.hybridize()
    out = layer(x).asnumpy()
    np.testing.assert_allclose(ref, out, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# contrib cells (reference: gluon/contrib/rnn/)
# ---------------------------------------------------------------------------


def test_contrib_conv_cells():
    from mxnet_tpu.gluon.contrib import rnn as crnn

    cases = [
        (crnn.Conv1DRNNCell, 1, 1), (crnn.Conv1DLSTMCell, 1, 2),
        (crnn.Conv1DGRUCell, 1, 1), (crnn.Conv2DRNNCell, 2, 1),
        (crnn.Conv2DLSTMCell, 2, 2), (crnn.Conv2DGRUCell, 2, 1),
        (crnn.Conv3DLSTMCell, 3, 2),
    ]
    for Cell, dims, nstates in cases:
        ishape = (3,) + (6,) * dims
        cell = Cell(input_shape=ishape, hidden_channels=4,
                    i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
        cell.initialize()
        x = mx.nd.random.uniform(shape=(2,) + ishape)
        out, states = cell(x, cell.begin_state(2))
        assert out.shape == (2, 4) + ishape[1:], Cell.__name__
        assert len(states) == nstates
        outs, _ = cell.unroll(3, mx.nd.random.uniform(shape=(2, 3) + ishape),
                              merge_outputs=True)
        assert outs.shape == (2, 3, 4) + ishape[1:]


def test_contrib_conv_lstm_state_shape_mismatch_guard():
    import pytest as _pytest

    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.gluon.contrib import rnn as crnn

    with _pytest.raises(MXNetError):
        crnn.Conv2DLSTMCell(input_shape=(3, 6, 6), hidden_channels=4,
                            i2h_kernel=3, h2h_kernel=2)


def test_contrib_lstmp_cell():
    from mxnet_tpu.gluon.contrib import rnn as crnn

    cell = crnn.LSTMPCell(16, projection_size=5)
    cell.initialize()
    out, states = cell(mx.nd.random.uniform(shape=(4, 10)),
                       cell.begin_state(4))
    assert out.shape == (4, 5)
    assert states[0].shape == (4, 5) and states[1].shape == (4, 16)
    outs, _ = cell.unroll(3, mx.nd.random.uniform(shape=(4, 3, 10)),
                          merge_outputs=True)
    assert outs.shape == (4, 3, 5)


def test_contrib_variational_dropout_cell():
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.contrib import rnn as crnn

    base = gluon.rnn.LSTMCell(8, input_size=8)
    cell = crnn.VariationalDropoutCell(base, drop_inputs=0.5,
                                       drop_outputs=0.5)
    cell.initialize()
    x = mx.nd.ones((2, 8))
    with autograd.record():
        _, s = cell(x, cell.begin_state(2))
        _ = cell(x, s)
    mask1 = cell._output_mask.asnumpy()
    with autograd.record():
        _ = cell(x, s)
    # same mask reused across steps of one sequence
    assert np.allclose(cell._output_mask.asnumpy(), mask1)
    cell.reset()
    assert cell._output_mask is None
    # eval mode: dropout is identity, output deterministic
    o1, _ = cell(x, cell.begin_state(2))
    cell.reset()
    o2, _ = cell(x, cell.begin_state(2))
    assert np.allclose(o1.asnumpy(), o2.asnumpy())
