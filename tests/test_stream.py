"""Streaming data plane (gluon/data/stream.py): indexed shards,
deterministic global order, decode pool, and the resize-proof cursor.

The load-bearing guarantees pinned here:
- all three RecordIO index paths (sidecar / native scan / Python scan)
  agree, and webdataset tar shards group members into samples;
- the (seed, epoch)-derived global order covers every record exactly
  once per epoch and is identical across processes;
- the cursor is a plain dict that round-trips through JSON bit-exactly
  and a restored reader continues the EXACT uninterrupted sequence;
- a 4→2→4 chaos resize (and a kill-and-resume in a fresh process)
  yields zero skipped and zero replayed samples;
- decode-pool backpressure is bounded and errors propagate to next().
"""

import json
import os
import subprocess
import sys
import tarfile
import threading
import time

import numpy as np
import pytest

import mxnet_tpu.observability as obs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.data import stream as st
from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher
from mxnet_tpu.gluon.data.stream import (
    GlobalOrder,
    ShardIndex,
    ShardSet,
    StreamReader,
    write_recordio_shards,
)
from mxnet_tpu.resilience import resume

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_shards(tmp_path, n=64, dim=4, shard_size=16, prefix="shard"):
    samples = [(np.full(dim, i, np.float32), float(i)) for i in range(n)]
    return st.write_recordio_shards(str(tmp_path), samples,
                                    shard_size=shard_size, prefix=prefix)


def drain_labels(reader):
    """Consume a reader to exhaustion -> flat list of int labels."""
    out = [int(x) for _, lab in reader for x in lab]
    reader.close()
    return out


def reader(paths, **kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("seed", 3)
    kw.setdefault("window", 8)
    kw.setdefault("epochs", 2)
    return StreamReader(paths, **kw)


# ---------------------------------------------------------------------------
# shard index
# ---------------------------------------------------------------------------

def test_index_paths_agree(tmp_path):
    """Sidecar .idx, native C scan, and pure-Python scan produce the
    identical offset table."""
    paths = make_shards(tmp_path)
    sidecar = ShardIndex.recordio(paths[0])._index  # .idx exists
    py = st._python_scan_recordio(paths[0])
    assert np.array_equal(sidecar, py)
    native = st._native_scan_recordio(paths[0])
    if native is not None:  # toolchain-less env: python path already pinned
        assert np.array_equal(native, py)


def test_python_scan_rejects_corrupt_magic(tmp_path):
    paths = make_shards(tmp_path, n=4, shard_size=4)
    with open(paths[0], "r+b") as f:
        f.write(b"\x00\x00\x00\x00")
    with pytest.raises(MXNetError, match="magic"):
        st._python_scan_recordio(paths[0])


def test_native_and_python_reads_agree(tmp_path, monkeypatch):
    """ShardIndex.read via MXTPURecordIOReadAt == the Python
    seek+read fallback, record for record."""
    paths = make_shards(tmp_path, n=8, shard_size=8)
    si = ShardIndex.recordio(paths[0])
    native = [si.read(i) for i in range(len(si))]
    si.close()
    monkeypatch.setattr(st, "get_lib", lambda: None)
    si2 = ShardIndex.recordio(paths[0])
    python = [si2.read(i) for i in range(len(si2))]
    si2.close()
    assert native == python
    from mxnet_tpu.recordio import unpack

    for i, payload in enumerate(python):
        header, body = unpack(payload)
        assert header.label == float(i)
        assert np.frombuffer(body, np.float32)[0] == float(i)


def test_webdataset_tar_index_and_read(tmp_path):
    """Tar members sharing a basename stem group into one sample dict;
    reads return each member's exact bytes."""
    p = tmp_path / "shard-0.tar"
    with tarfile.open(p, "w") as tf:
        for i in range(5):
            for ext, blob in [("cls", str(i).encode()),
                              ("data.bin", bytes([i]) * 32)]:
                info = tarfile.TarInfo(f"sample{i:04d}.{ext}")
                info.size = len(blob)
                import io as _io

                tf.addfile(info, _io.BytesIO(blob))
    si = ShardIndex.webdataset(str(p))
    assert len(si) == 5
    for i in range(5):
        sample = si.read(i)
        assert sample == {"cls": str(i).encode(),
                          "data.bin": bytes([i]) * 32}
    si.close()


def test_compressed_tar_shard_rejected(tmp_path):
    p = tmp_path / "shard.tar.gz"
    p.write_bytes(b"x")
    with pytest.raises(MXNetError, match="compressed"):
        st._open_shard(str(p))


def test_webdataset_stream_end_to_end(tmp_path):
    """A StreamReader over tar shards with a custom decode delivers
    every sample exactly once."""
    p = tmp_path / "wds.tar"
    with tarfile.open(p, "w") as tf:
        import io as _io

        for i in range(24):
            blob = json.dumps({"i": i}).encode()
            info = tarfile.TarInfo(f"s{i:05d}.json")
            info.size = len(blob)
            tf.addfile(info, _io.BytesIO(blob))
    rd = StreamReader([str(p)], batch_size=4, seed=0, epochs=1,
                      decode=lambda s: np.int64(
                          json.loads(s["json"])["i"]),
                      collate=lambda xs: np.asarray(xs))
    seen = [int(x) for b in rd for x in b]
    rd.close()
    assert sorted(seen) == list(range(24))


# ---------------------------------------------------------------------------
# deterministic global order
# ---------------------------------------------------------------------------

def test_global_order_is_a_permutation_each_epoch(tmp_path):
    paths = make_shards(tmp_path, n=50, shard_size=16)
    order = GlobalOrder(ShardSet(paths), seed=11, window=8)
    for epoch in (0, 1):
        locs = [order.locate(epoch, i) for i in range(50)]
        assert len(set(locs)) == 50  # every record exactly once
    e0 = [order.locate(0, i) for i in range(50)]
    e1 = [order.locate(1, i) for i in range(50)]
    assert e0 != e1  # reshuffled across epochs


def test_global_order_cross_instance_deterministic(tmp_path):
    """Two independent instances (as two processes would build) agree
    on every position — string-seeded RNG, not PYTHONHASHSEED."""
    paths = make_shards(tmp_path, n=50, shard_size=16)
    a = GlobalOrder(ShardSet(paths), seed=11, window=8)
    b = GlobalOrder(ShardSet(paths), seed=11, window=8)
    assert [a.locate(2, i) for i in range(50)] == \
        [b.locate(2, i) for i in range(50)]


def test_window_zero_preserves_within_shard_order(tmp_path):
    paths = make_shards(tmp_path, n=32, shard_size=16)
    order = GlobalOrder(ShardSet(paths), seed=5, window=0)
    locs = [order.locate(0, i) for i in range(32)]
    # records of each shard appear in ascending record order
    per_shard = {}
    for s, r in locs:
        per_shard.setdefault(s, []).append(r)
    for recs in per_shard.values():
        assert recs == sorted(recs)


def test_window_shuffle_stays_within_window(tmp_path):
    paths = make_shards(tmp_path, n=64, shard_size=64)  # one shard
    order = GlobalOrder(ShardSet(paths), seed=5, window=16,
                        shuffle_shards=False)
    locs = [order.locate(0, i)[1] for i in range(64)]
    for w in range(4):
        block = locs[w * 16:(w + 1) * 16]
        assert sorted(block) == list(range(w * 16, (w + 1) * 16))
        assert block != sorted(block)  # actually shuffled


# ---------------------------------------------------------------------------
# reader: order, determinism, epochs
# ---------------------------------------------------------------------------

def test_stream_reader_content_and_determinism(tmp_path):
    paths = make_shards(tmp_path)
    rd = reader(paths)
    seen = []
    for data, label in rd:
        assert np.array_equal(data[:, 0], label)  # decode correctness
        seen.extend(int(x) for x in label)
    rd.close()
    # 64 records, bs=8 -> 8 whole batches per epoch, 2 epochs
    assert len(seen) == 128
    assert sorted(seen[:64]) == list(range(64))  # epoch 0 complete
    assert drain_labels(reader(paths)) == seen  # replayable


def test_stream_reader_drop_tail_whole_batches(tmp_path):
    paths = make_shards(tmp_path, n=50, shard_size=16)
    seen = drain_labels(StreamReader(paths, batch_size=8, seed=1,
                                     epochs=1))
    assert len(seen) == 48  # 50 -> 6 whole batches, tail dropped
    assert len(set(seen)) == 48  # no dup inside the epoch


def test_stream_reader_infinite_reshuffles(tmp_path):
    paths = make_shards(tmp_path, n=16, shard_size=8)
    rd = StreamReader(paths, batch_size=16, seed=2, window=4,
                      epochs=None)
    it = iter(rd)
    e0 = [int(x) for x in next(it)[1]]
    e1 = [int(x) for x in next(it)[1]]
    e2 = [int(x) for x in next(it)[1]]  # infinite: keeps going
    rd.close()
    assert sorted(e0) == sorted(e1) == sorted(e2) == list(range(16))
    assert not (e0 == e1 == e2)  # epochs reshuffle


# ---------------------------------------------------------------------------
# cursor: checkpoint round-trip, resume, repartition
# ---------------------------------------------------------------------------

def test_cursor_json_roundtrip_bit_exact(tmp_path):
    paths = make_shards(tmp_path)
    rd = reader(paths)
    it = iter(rd)
    for _ in range(3):
        next(it)
    state = rd.state()
    rd.close()
    wire = json.loads(json.dumps(state))
    assert wire == state  # bit-exact through serialization
    rd2 = reader(paths).restore(wire)
    assert rd2.state() == state
    rd2.close()


def test_kill_and_resume_exact_sequence(tmp_path):
    """Consume 5 batches, 'die', restore from the JSON cursor in a new
    reader: the concatenation IS the uninterrupted sequence — no
    sample skipped, none replayed."""
    paths = make_shards(tmp_path)
    full = drain_labels(reader(paths))
    rd = reader(paths)
    it = iter(rd)
    head = [int(x) for _ in range(5) for x in next(it)[1]]
    wire = json.dumps(rd.state())
    rd.close()  # staged read-ahead discarded — cursor marks delivered
    tail = drain_labels(reader(paths).restore(json.loads(wire)))
    assert head + tail == full


def test_restore_rejects_diverging_configuration(tmp_path):
    paths = make_shards(tmp_path)
    state = reader(paths).state()
    with pytest.raises(MXNetError, match="diverge"):
        reader(paths, batch_size=4).restore(state)
    with pytest.raises(MXNetError, match="diverge"):
        reader(paths, seed=99).restore(state)
    short = make_shards(tmp_path / "other", n=32, prefix="o")
    with pytest.raises(MXNetError, match="records"):
        reader(short).restore(state)
    with pytest.raises(MXNetError, match="not a stream"):
        reader(paths).restore(7)


def interleave(parts):
    """Round-robin step-major merge of per-rank batch lists — the
    global consumption order of a data-parallel group."""
    out = []
    for i in range(max(len(p) for p in parts)):
        for p in parts:
            if i < len(p):
                out.extend(p[i])
    return out


def rank_batches(paths, state, world, rank, steps=None, limit=None,
                 **kw):
    """Restore `state`, repartition to (world, rank), consume up to
    `limit` batches -> list of per-batch label lists + final state."""
    rd = reader(paths, **kw).restore(state)
    rd.repartition(world=world, rank=rank, steps=steps)
    out = []
    it = iter(rd)
    while limit is None or len(out) < limit:
        try:
            out.append([int(x) for x in next(it)[1]])
        except StopIteration:
            break
    state = rd.state()
    rd.close()
    return out, state


def test_chaos_resize_4_2_4_zero_skip_zero_replay(tmp_path):
    """The acceptance pin: a 4->2->4 elastic resize mid-stream yields
    the EXACT uninterrupted global sample sequence — zero skipped,
    zero replayed — with every leg's cursor travelling as JSON."""
    paths = make_shards(tmp_path, n=256, shard_size=32)
    full = drain_labels(StreamReader(paths, batch_size=4, seed=9,
                                     window=16, epochs=1))
    kw = dict(batch_size=4, seed=9, window=16, epochs=1)
    base = StreamReader(paths, **kw).state()

    # leg 1: world=4, 3 steps each
    legs, states = [], []
    for r in range(4):
        out, s = rank_batches(paths, json.loads(json.dumps(base)),
                              4, r, limit=3, **kw)
        legs.append(out)
        states.append(s)
    leg1 = interleave(legs)
    assert all(s["steps"] == 3 for s in states)

    # shrink 4 -> 2 (two survivors re-partition from the step boundary)
    legs2, states2 = [], []
    for r in range(2):
        out, s = rank_batches(paths, json.loads(json.dumps(states[r])),
                              2, r, limit=4, **kw)
        legs2.append(out)
        states2.append(s)
    leg2 = interleave(legs2)

    # grow 2 -> 4 (two ranks rejoin) and drain to the end
    legs3 = []
    for r in range(4):
        out, _ = rank_batches(paths,
                              json.loads(json.dumps(states2[r % 2])),
                              4, r, **kw)
        legs3.append(out)
    leg3 = interleave(legs3)

    got = leg1 + leg2 + leg3
    assert got == full  # exact order: no skip, no replay, no reorder
    assert sorted(got) == sorted(full)


def test_repartition_requires_step_boundary_consistency(tmp_path):
    paths = make_shards(tmp_path)
    rd = reader(paths)
    with pytest.raises(MXNetError, match="rank"):
        rd.repartition(world=2, rank=2)
    rd.close()


def test_reset_rewinds_to_stream_start(tmp_path):
    paths = make_shards(tmp_path)
    rd = reader(paths)
    it = iter(rd)
    first = [int(x) for x in next(it)[1]]
    for _ in range(2):
        next(it)
    rd.reset()
    assert rd.state()["steps"] == 0 and rd.state()["base_batch"] == 0
    assert [int(x) for x in next(iter(rd))[1]] == first
    rd.close()


# ---------------------------------------------------------------------------
# decode pool: backpressure, errors, wait accounting
# ---------------------------------------------------------------------------

def test_decode_error_propagates_to_consumer(tmp_path):
    paths = make_shards(tmp_path, n=32, shard_size=32)

    def bomb(payload):
        sample = st.decode_recordio_f32(payload)
        if int(sample[1]) == 13:
            raise ValueError("record 13 is cursed")
        return sample

    rd = StreamReader(paths, batch_size=4, seed=0, epochs=1,
                      window=0, shuffle_shards=False, decode=bomb)
    with pytest.raises(ValueError, match="cursed"):
        for _ in rd:
            pass
    with pytest.raises(ValueError, match="cursed"):  # error is sticky
        next(rd)
    rd.close()


def test_backpressure_bounds_readahead(tmp_path):
    """With readahead=4 a stalled consumer never sees more than the
    bounded raw + reorder staging — the reader does not inhale the
    whole dataset."""
    paths = make_shards(tmp_path, n=64, shard_size=64)
    rd = StreamReader(paths, batch_size=4, seed=0, epochs=1,
                      readahead=4, pool=2)
    it = iter(rd)
    next(it)  # spin up the pipeline
    time.sleep(0.3)  # consumer stalls; producers hit the bound
    with rd._cv:
        staged = len(rd._reorder)
    raw = rd._raw_q.qsize()
    # decode pool may hold one in-flight record per worker beyond the
    # buffer bound
    assert staged <= 4 + 2 + rd.batch_size
    assert raw <= 4
    rd.close()


def test_decode_pool_runs_off_consumer_thread(tmp_path):
    paths = make_shards(tmp_path, n=32, shard_size=32)
    tids = set()

    def spy(payload):
        tids.add(threading.get_ident())
        return st.decode_recordio_f32(payload)

    rd = StreamReader(paths, batch_size=4, seed=0, epochs=1,
                      decode=spy, pool=3)
    drain_labels(rd)
    assert threading.get_ident() not in tids  # never on the train thread
    assert len(tids) >= 1


def test_stream_telemetry_counters(tmp_path):
    paths = make_shards(tmp_path, n=32, shard_size=16)
    obs.reset()
    obs.set_enabled(True)
    try:
        drain_labels(StreamReader(paths, batch_size=4, seed=0,
                                  epochs=1))
        assert obs.STREAM_BATCHES_TOTAL.total() == 8
        assert obs.STREAM_RECORDS_TOTAL.total() == 32
        assert obs.STREAM_READ_BYTES.total() > 0
        assert obs.STREAM_DECODE_SECONDS.total() >= 0
        assert obs.STREAM_CONSUMER_WAIT_SECONDS.total() >= 0
        names = {r["name"] for r in obs.tracer().events()}
        assert "stream.batch" in names
    finally:
        obs.set_enabled(False)
        obs.reset()


def test_emulated_latency_slows_reads(tmp_path, monkeypatch):
    paths = make_shards(tmp_path, n=8, shard_size=8)
    si = ShardIndex.recordio(paths[0])
    t0 = time.perf_counter()
    si.read(0)
    fast = time.perf_counter() - t0
    monkeypatch.setenv("MXTPU_STREAM_LATENCY_MS", "30")
    t0 = time.perf_counter()
    si.read(0)
    slow = time.perf_counter() - t0
    si.close()
    assert slow >= 0.03 > fast


def test_env_knob_defaults(monkeypatch):
    for var in ("MXTPU_STREAM_DECODE_THREADS", "MXTPU_STREAM_READAHEAD",
                "MXTPU_STREAM_LATENCY_MS", "MXTPU_STREAM_WINDOW"):
        monkeypatch.delenv(var, raising=False)
    assert st.decode_threads() == 4
    assert st.readahead_records() == 128
    assert st.emulated_latency_ms() == 0.0
    assert st.shuffle_window() == 0
    monkeypatch.setenv("MXTPU_STREAM_DECODE_THREADS", "0")
    assert st.decode_threads() == 1  # clamped


# ---------------------------------------------------------------------------
# prefetcher + checkpoint integration
# ---------------------------------------------------------------------------

def test_prefetcher_structured_cursor_counts_delivered_only(tmp_path):
    paths = make_shards(tmp_path)
    rd = reader(paths, epochs=1)
    pf = DevicePrefetcher(rd, depth=4)
    it = iter(pf)
    for _ in range(3):
        next(it)
    cur = pf.cursor
    assert cur["kind"] == "stream" and cur["steps"] == 3
    # the SOURCE is ahead (staged batches) — the cursor must not be
    assert rd.state()["steps"] >= cur["steps"]
    pf.close()


def test_prefetcher_world_repartition_zero_skip(tmp_path):
    paths = make_shards(tmp_path, n=64, shard_size=16)
    kw = dict(batch_size=4, seed=7, window=8, epochs=1)
    full = drain_labels(StreamReader(paths, **kw))
    pf = DevicePrefetcher(StreamReader(paths, **kw), depth=3)
    it = iter(pf)
    head = [int(x) for _ in range(3) for x in next(it)[1].data.ravel()]
    wire = json.loads(json.dumps(pf.cursor))
    pf.repartition(world=2, rank=0)
    mine = [[int(x) for x in b[1].data.ravel()] for b in pf]
    pf.close()
    sib = StreamReader(paths, **kw).restore(wire)
    sib.repartition(world=2, rank=1)
    theirs = [[int(x) for x in lab] for _, lab in sib]
    sib.close()
    assert head + interleave([mine, theirs]) == full


def test_prefetcher_world_repartition_needs_stream_source():
    pf = DevicePrefetcher(iter([]), depth=1)
    with pytest.raises(ValueError, match="no repartition"):
        pf.repartition(world=2, rank=0)
    pf.close()


def test_checkpoint_extras_carry_dict_cursor(tmp_path):
    from mxnet_tpu.resilience import checkpoint as ckpt

    cursor = reader([p for p in make_shards(tmp_path)]).state()
    path = ckpt.write_checkpoint(
        str(tmp_path / "ckpt"), {"param::w": np.zeros(2, np.float32)},
        {"cursor": dict(cursor), "kind": "trainer"}, step=5)
    manifest, _ = ckpt.read_checkpoint(path)
    assert manifest["extras"]["cursor"] == cursor  # bit-exact


def test_restore_cursor_dispatch(tmp_path):
    paths = make_shards(tmp_path)
    full = drain_labels(reader(paths))
    rd = reader(paths)
    it = iter(rd)
    head = [int(x) for _ in range(2) for x in next(it)[1]]
    cur = rd.state()
    rd.close()
    # dict cursor -> native restore
    it2 = resume.restore_cursor(reader(paths), cur)
    tail = [int(x) for _, lab in it2 for x in lab]
    assert head + tail == full
    # int cursor -> skip_batches fallback
    it3 = resume.restore_cursor(iter([1, 2, 3]), 2)
    assert list(it3) == [3]
    # dict cursor onto a restore-less source -> loud failure
    with pytest.raises(MXNetError, match="restore"):
        resume.restore_cursor([1, 2, 3], cur)


def test_kill_and_resume_subprocess(tmp_path):
    """Fresh-process resume: a child consumes 4 batches and prints its
    cursor; a SECOND process restores from that JSON and drains. The
    two halves concatenate to the exact single-process sequence."""
    paths = make_shards(tmp_path, n=64, shard_size=16)
    full = drain_labels(StreamReader(paths, batch_size=8, seed=3,
                                     window=8, epochs=1))
    child = f"""
import json, sys
sys.path.insert(0, {ROOT!r})
from mxnet_tpu.gluon.data.stream import StreamReader
paths = {paths!r}
rd = StreamReader(paths, batch_size=8, seed=3, window=8, epochs=1)
cursor = sys.argv[1] if len(sys.argv) > 1 else None
if cursor:
    rd.restore(json.loads(cursor))
out = []
it = iter(rd)
limit = 4 if cursor is None else None
while limit is None or len(out) < limit * 8:
    try:
        out.extend(int(x) for x in next(it)[1])
    except StopIteration:
        break
print("RESULT " + json.dumps({{"seen": out, "cursor": rd.state()}}))
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"

    def run(*args):
        res = subprocess.run([sys.executable, "-c", child, *args],
                             env=env, capture_output=True, text=True,
                             timeout=120)
        assert res.returncode == 0, res.stderr
        line = [ln for ln in res.stdout.splitlines()
                if ln.startswith("RESULT ")][-1]
        return json.loads(line[len("RESULT "):])

    first = run()
    assert first["cursor"]["steps"] == 4
    second = run(json.dumps(first["cursor"]))
    assert first["seen"] + second["seen"] == full


# ---------------------------------------------------------------------------
# telemetry report: Input pipeline section
# ---------------------------------------------------------------------------

def _report_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(ROOT, "tools", "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_report_input_pipeline_section_end_to_end(tmp_path):
    """Stream with telemetry on, dump the trace, run the report CLI:
    the Input-pipeline section shows per-shard reads, decode-pool
    utilization, and the consumer-wait join against step spans."""
    paths = make_shards(tmp_path, n=32, shard_size=16)
    obs.reset()
    obs.set_enabled(True)
    try:
        with obs.span("trainer.step", cat="trainer"):
            drain_labels(StreamReader(paths, batch_size=4, seed=0,
                                      epochs=1))
        trace = str(tmp_path / "t.jsonl")
        obs.dump_jsonl(trace)
    finally:
        obs.set_enabled(False)
        obs.reset()
    res = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tools", "telemetry_report.py"), trace],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    out = res.stdout
    assert "Input pipeline:" in out
    assert "batches delivered" in out
    assert "decode pool:" in out and "utilization" in out
    assert "input wait / step time:" in out
    assert "shard-00000.rec" in out  # per-shard read table


def test_report_input_pipeline_crash_proof():
    """Malformed/absent args never crash the section (the report
    contract: absent series -> empty string, junk args -> '-'/zero)."""
    tr = _report_module()
    assert tr.render_input_pipeline([]) == ""
    assert tr.render_input_pipeline(
        [{"name": "trainer.step", "dur": 5.0}]) == ""
    junk = [
        {"name": "stream.batch"},  # no args at all
        {"name": "stream.batch", "args": {"consumer_wait": "nan?"}},
        {"name": "stream.batch", "args": {"consumer_wait": 0.001,
                                          "reorder_depth": 3}},
        {"name": "stream.stats", "args": None},
        {"name": "stream.stats",
         "args": {"per_shard": {"s": "junk", "t": {"bytes": 1e6,
                                                   "seconds": 0.5,
                                                   "records": 10}},
                  "decode_busy": "x", "depth_reorder": None}},
    ]
    out = tr.render_input_pipeline(junk)
    assert "Input pipeline:" in out
    assert "3 batches delivered" in out
    assert "t" in out  # well-formed shard row survives its junk sibling


def test_doctor_input_bound_recipe_names_stream_knobs():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mxtpu_doctor", os.path.join(ROOT, "tools", "mxtpu_doctor.py"))
    doctor = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(doctor)
    _meaning, recipe = doctor.RECIPES["input_bound"]
    assert "MXTPU_STREAM_DECODE_THREADS" in recipe
    assert "MXTPU_STREAM_READAHEAD" in recipe
    assert "shard" in recipe  # shard-parallelism guidance


# ---------------------------------------------------------------------------
# on-device augmentation
# ---------------------------------------------------------------------------

def test_device_augment_inside_jit():
    import jax
    import jax.numpy as jnp

    aug = st.device_augment(crop=(4, 4), flip=True,
                            mean=(1.0, 2.0, 3.0), std=(2.0, 2.0, 2.0))
    images = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(
        2, 8, 8, 3)
    key = jax.random.PRNGKey(0)
    out = jax.jit(aug)(images, key)
    assert out.shape == (2, 4, 4, 3)  # static crop under jit
    # deterministic in the key; different keys differ
    again = jax.jit(aug)(images, key)
    assert jnp.array_equal(out, again)
    other = jax.jit(aug)(images, jax.random.PRNGKey(1))
    assert not jnp.array_equal(out, other)


def test_device_augment_normalize_only_matches_numpy():
    import jax
    import jax.numpy as jnp

    aug = st.device_augment(mean=(0.5,), std=(0.25,))
    x = jnp.linspace(0, 1, 2 * 3 * 3 * 1).reshape(2, 3, 3, 1)
    out = aug(x, jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        np.asarray(out), (np.asarray(x) - 0.5) / 0.25, rtol=1e-6)
