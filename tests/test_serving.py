"""Production inference serving (``mxnet_tpu.serving``): AOT
shape-bucket executables + the sealed no-retrace contract, continuous
batching (deadlines, load shed, drain-on-close), multi-model hosting
with live swap/rollback, and the serving SLO surface.

Reference analog: the C predict API / model-server heritage tests —
here the contracts under test are the TPU-native ones (one executable
per bucket, zero recompiles after warmup, atomic version flips)."""

import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, observability as obs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data.shape_guard import pad_to_shape
from mxnet_tpu.observability.metrics import Histogram
from mxnet_tpu.serving import (
    ContinuousBatcher,
    EngineClosed,
    InferenceEngine,
    ModelRepository,
    RequestTimeout,
    RequestTooLarge,
    RetraceForbidden,
    ServerOverloaded,
    ServingError,
    StagedLoadError,
)
from mxnet_tpu.serving.batcher import _Request


@pytest.fixture(autouse=True)
def _telemetry_state():
    obs.set_enabled(False)
    obs.reset()
    yield
    obs.set_enabled(False)
    obs.reset()


FEAT = 6
CLASSES = 4
BUCKETS = [(4, FEAT), (8, FEAT), (16, FEAT)]


class _RaggedNet(gluon.HybridBlock):
    """Rows are (T, FEAT) sequences, ragged on T; output (CLASSES,)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.proj = nn.Dense(CLASSES, flatten=False, in_units=FEAT)

    def hybrid_forward(self, F, x):
        return F.mean(self.proj(x), axis=1)


def _ragged_net():
    net = _RaggedNet()
    net.initialize()
    return net


def _vec_net(bias=0.0, feat=8, classes=CLASSES):
    """Fixed-shape net with deterministic params: y = 0.1 * sum(x) + bias
    per class — versions are distinguishable by their bias."""
    net = nn.HybridSequential()
    net.add(nn.Dense(classes, in_units=feat))
    net.initialize()
    net[0].weight.set_data(mx.nd.ones((classes, feat)) * 0.1)
    net[0].bias.set_data(mx.nd.ones((classes,)) * bias)
    return net


def _engine(net=None, shapes=None, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 20.0)
    return InferenceEngine(net or _ragged_net(),
                           shapes or BUCKETS, **kw)


def _expect(net, row):
    """Ground truth for a request row: the net applied to the
    bucket-padded input (padding participates in non-row-wise math like
    the mean above, by design — the bucket IS the contract shape)."""
    return net(mx.nd.array(row[None])).asnumpy()[0]


# -- satellite units: pad_to_shape / Histogram.quantile --------------------

def test_pad_to_shape():
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    p = pad_to_shape(a, (2, 5))
    assert p.shape == (2, 5)
    assert np.array_equal(p[:, :3], a) and np.all(p[:, 3:] == 0)
    p = pad_to_shape(a, (4, 3), pad_value=7)
    assert p.shape == (4, 3) and np.all(p[2:] == 7)
    nd = pad_to_shape(mx.nd.array(a), (3, 4))
    assert nd.shape == (3, 4)
    with pytest.raises(MXNetError):
        pad_to_shape(a, (2, 3, 1))  # rank mismatch
    with pytest.raises(MXNetError):
        pad_to_shape(a, (2, 2))  # truncation is never implicit


def test_histogram_quantile():
    h = Histogram("t_q", buckets=(1.0, 2.0, 4.0, 8.0))
    assert h.quantile(0.5) is None  # no observations
    for v in (0.5, 1.5, 3.0, 3.5, 6.0):
        h.observe(v)
    p50 = h.quantile(0.5)
    assert 1.0 <= p50 <= 4.0
    assert h.quantile(0.0) <= h.quantile(0.99) <= 8.0
    h.observe(100.0)  # beyond the last finite bucket: clamps, no inf
    assert h.quantile(1.0) == 8.0
    with pytest.raises(MXNetError):
        h.quantile(1.5)


# -- AOT extraction hook ---------------------------------------------------

def test_aot_predict_fn_parity():
    import jax

    net = _ragged_net()
    fn, params = net.aot_predict_fn(sample_shape=(1, 8, FEAT))
    x = np.random.RandomState(0).rand(3, 8, FEAT).astype(np.float32)
    got = np.asarray(jax.jit(fn)(params, x))
    want = net(mx.nd.array(x)).asnumpy()
    assert np.allclose(got, want, atol=1e-5)


def test_aot_predict_fn_required():
    with pytest.raises(MXNetError, match="aot_predict_fn"):
        InferenceEngine(object(), BUCKETS)


# -- engine: AOT buckets, parity, the sealed no-retrace contract -----------

def test_engine_parity_and_zero_recompiles():
    net = _ragged_net()
    eng = _engine(net)
    try:
        assert eng.sealed and eng.stats()["compiles"] == len(BUCKETS)
        rng = np.random.RandomState(1)
        for t in [1, 3, 4, 5, 8, 9, 16, 2, 13]:  # ragged traffic
            row = rng.rand(t, FEAT).astype(np.float32)
            bucket = eng._bucket_for(row.shape)
            padded_row = pad_to_shape(row[None], (1,) + bucket)[0]
            out = eng.predict(row, timeout=10.0)
            assert out.shape == (1, CLASSES)
            assert np.allclose(out[0], _expect(net, padded_row), atol=1e-5)
        st = eng.stats()
        assert st["compiles"] == len(BUCKETS)  # FLAT after warmup
        assert st["retraces_after_warmup"] == 0
        assert st["requests_ok"] == 9
        assert st["latency_p50_ms"] is not None
    finally:
        eng.close()


def test_engine_micro_batch_rows():
    net = _ragged_net()
    eng = _engine(net)
    try:
        x = np.random.RandomState(2).rand(3, 4, FEAT).astype(np.float32)
        out = eng.predict(x, timeout=10.0)
        assert out.shape == (3, CLASSES)  # exactly the request's rows
        for i in range(3):
            assert np.allclose(out[i], _expect(net, x[i]), atol=1e-5)
    finally:
        eng.close()


def test_engine_refuses_unbucketable_shape():
    eng = _engine()
    try:
        with pytest.raises(RetraceForbidden, match="shape"):
            eng.submit(np.zeros((40, FEAT), np.float32))
        with pytest.raises(RetraceForbidden):
            eng.submit(np.zeros((2, 3, 4, 5), np.float32))  # bad rank
        assert eng.stats()["refused"] == 2
        assert eng.stats()["compiles"] == len(BUCKETS)  # refused != traced
    finally:
        eng.close()


def test_engine_refuses_dtype_with_cast_off():
    eng = _engine()
    try:
        x = np.zeros((4, FEAT), np.int32)
        with pytest.raises(RetraceForbidden, match="dtype"):
            eng.submit(x, cast=False)
        out = eng.predict(x, timeout=10.0)  # default casts instead
        assert out.shape == (1, CLASSES)
    finally:
        eng.close()


def test_engine_oversized_request_typed():
    eng = _engine(max_batch=4)
    try:
        with pytest.raises(RequestTooLarge, match="split it client-side"):
            eng.submit(np.zeros((5, 4, FEAT), np.float32))
    finally:
        eng.close()


# -- continuous batching ---------------------------------------------------

def test_batching_coalesces_requests():
    eng = _engine(max_batch=4, max_wait_ms=100.0)
    try:
        x = np.zeros((4, FEAT), np.float32)
        futs = [eng.submit(x) for _ in range(4)]
        for f in futs:
            assert f.result(timeout=10.0).shape == (1, CLASSES)
        st = eng.stats()
        assert st["requests_ok"] == 4
        assert st["batches"] <= 2  # coalesced, not one dispatch each
        assert st["mean_batch_fill"] >= 0.5
    finally:
        eng.close()


def test_deadline_expires_as_typed_timeout():
    # autostart=False holds the scheduler so the expiry is deterministic
    eng = _engine(autostart=False)
    try:
        fut = eng.submit(np.zeros((4, FEAT), np.float32), deadline_ms=1.0)
        time.sleep(0.03)
        eng._batcher.start()
        with pytest.raises(RequestTimeout, match="deadline expired"):
            fut.result(timeout=10.0)
        assert eng.stats()["timeouts"] == 1
    finally:
        eng.close()


def test_full_queue_sheds_typed():
    eng = _engine(autostart=False, queue_cap=2)
    x = np.zeros((4, FEAT), np.float32)
    accepted = [eng.submit(x), eng.submit(x)]
    with pytest.raises(ServerOverloaded, match="load shed"):
        eng.submit(x)
    assert eng.stats()["shed"] == 1
    eng.close()  # scheduler never ran: accepted work fails typed
    for f in accepted:
        with pytest.raises(EngineClosed):
            f.result(timeout=10.0)


def test_close_drains_inflight():
    net = _ragged_net()
    eng = _engine(net, max_wait_ms=200.0)  # long window: work sits queued
    x = np.random.RandomState(3).rand(4, FEAT).astype(np.float32)
    futs = [eng.submit(x) for _ in range(5)]
    eng.close()  # DevicePrefetcher contract: accepted work completes
    for f in futs:
        out = f.result(timeout=10.0)
        assert np.allclose(out[0], _expect(net, x), atol=1e-5)
    with pytest.raises(EngineClosed):
        eng.submit(x)
    eng.close()  # idempotent


def test_pause_resume_cycle():
    eng = _engine()
    try:
        x = np.zeros((4, FEAT), np.float32)
        eng.predict(x, timeout=10.0)
        compiles = eng.stats()["compiles"]
        eng.pause()
        with pytest.raises(EngineClosed, match="paused"):
            eng.submit(x)
        eng.resume()
        eng.predict(x, timeout=10.0)  # serving again, no recompile
        assert eng.stats()["compiles"] == compiles
    finally:
        eng.close()
    with pytest.raises(EngineClosed, match="released"):
        eng.resume()


def test_batcher_dispatch_error_propagates():
    def bad_dispatch(bucket, reqs):
        raise ValueError("device exploded")

    b = ContinuousBatcher(bad_dispatch, max_batch=2, max_wait=0.001,
                          queue_cap=8)
    try:
        req = _Request(np.zeros((1, 2), np.float32), 1, (2,))
        b.submit(req)
        assert req.event.wait(10.0)
        with pytest.raises(ValueError, match="device exploded"):
            from mxnet_tpu.serving.batcher import ServeFuture
            ServeFuture(req).result(0)
    finally:
        b.close()
        b.close()  # idempotent


def test_future_client_timeout_does_not_cancel():
    eng = _engine(autostart=False)  # result will never arrive
    try:
        fut = eng.submit(np.zeros((4, FEAT), np.float32))
        with pytest.raises(TimeoutError, match="still in flight"):
            fut.result(timeout=0.01)
        assert not fut.done()  # client patience != request deadline
    finally:
        eng.close()


# -- multi-model repository: swap, rollback, corrupt loads -----------------

def test_repository_swap_and_rollback():
    repo = ModelRepository(keep=1)
    try:
        x = np.ones((8,), np.float32)
        repo.load("clf", _vec_net(bias=0.0), shapes=[(8,)], version="v1",
                  max_batch=2, max_wait_ms=1.0)
        v1_out = repo.predict("clf", x, timeout=10.0)
        assert np.allclose(v1_out, 0.8, atol=1e-5)  # 0.1 * 8

        e2 = repo.load("clf", _vec_net(bias=100.0), shapes=[(8,)],
                       version="v2", max_batch=2, max_wait_ms=1.0)
        assert repo.models()["clf"] == {"live": "v2", "standby": ["v1"]}
        assert np.allclose(repo.predict("clf", x, timeout=10.0),
                           100.8, atol=1e-4)

        compiles_v1 = repo._models["clf"]["standby"][0].stats()["compiles"]
        restored = repo.rollback("clf")
        assert restored.version == "v1"
        assert np.allclose(repo.predict("clf", x, timeout=10.0),
                           0.8, atol=1e-5)
        # rollback is a pointer flip + resume, never a recompile
        assert restored.stats()["compiles"] == compiles_v1
        assert repo.models()["clf"] == {"live": "v1", "standby": ["v2"]}
        assert e2.version == "v2"
    finally:
        repo.close()


def test_repository_corrupt_load_never_serves():
    repo = ModelRepository()
    try:
        x = np.ones((8,), np.float32)
        repo.load("clf", _vec_net(bias=0.0), shapes=[(8,)], version="v1",
                  max_batch=2, max_wait_ms=1.0)
        with pytest.raises(StagedLoadError, match="keeps serving"):
            repo.load("clf", _vec_net(bias=float("nan")), shapes=[(8,)],
                      version="v2", max_batch=2, max_wait_ms=1.0)
        # the canary veto means v2 never became visible
        assert repo.models()["clf"] == {"live": "v1", "standby": []}
        assert np.allclose(repo.predict("clf", x, timeout=10.0),
                           0.8, atol=1e-5)
        # a crashing factory is equally invisible
        with pytest.raises(StagedLoadError):
            repo.load("clf", lambda: 1 / 0, shapes=[(8,)])
        assert repo.models()["clf"]["live"] == "v1"
    finally:
        repo.close()


def test_repository_swap_version_coherence_under_traffic():
    """Continuous requests across a live swap: every request succeeds
    and is answered by exactly one coherent version (its result matches
    the version stamped on its future)."""
    repo = ModelRepository(keep=1)
    expected = {"v1": 0.8, "v2": 100.8}
    stop = threading.Event()
    outcomes, errors = [], []

    def client():
        x = np.ones((8,), np.float32)
        while not stop.is_set():
            try:
                fut = repo.submit("clf", x)
                out = fut.result(timeout=10.0)
                outcomes.append((fut.version, float(out[0, 0])))
            except BaseException as e:  # no error is acceptable mid-swap
                errors.append(e)
                return

    try:
        repo.load("clf", _vec_net(bias=0.0), shapes=[(8,)], version="v1",
                  max_batch=2, max_wait_ms=1.0)
        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.05)  # traffic flowing on v1
        repo.load("clf", _vec_net(bias=100.0), shapes=[(8,)],
                  version="v2", max_batch=2, max_wait_ms=1.0)
        time.sleep(0.05)  # traffic flowing on v2
        stop.set()
        t.join(timeout=30.0)
        assert not t.is_alive()
        assert not errors, f"requests failed across the swap: {errors!r}"
        assert len(outcomes) > 0
        versions = {v for v, _ in outcomes}
        assert versions <= {"v1", "v2"}
        assert "v2" in versions  # the swap actually happened under load
        for version, value in outcomes:
            assert abs(value - expected[version]) < 1e-3, \
                f"request answered by an incoherent version: " \
                f"{version} -> {value}"
    finally:
        stop.set()
        repo.close()


def test_repository_unknown_model_and_unload():
    repo = ModelRepository()
    with pytest.raises(ServingError, match="no live version"):
        repo.engine("ghost")
    repo.load("m", _vec_net(), shapes=[(8,)], max_batch=2,
              max_wait_ms=1.0)
    assert repo.stats("m")["model"] == "m"
    repo.unload("m")
    with pytest.raises(ServingError):
        repo.predict("m", np.ones((8,), np.float32))
    repo.unload("m")  # idempotent
    repo.close()


def test_repository_rollback_without_standby():
    repo = ModelRepository()
    try:
        repo.load("m", _vec_net(), shapes=[(8,)], max_batch=2,
                  max_wait_ms=1.0)
        with pytest.raises(ServingError, match="no standby"):
            repo.rollback("m")
    finally:
        repo.close()


# -- int8 path -------------------------------------------------------------

def test_engine_serves_quantized_net():
    from mxnet_tpu.contrib.quantization import quantize_net

    net = _vec_net(bias=1.0)
    rng = np.random.RandomState(4)
    calib = [rng.rand(4, 8).astype(np.float32) for _ in range(3)]
    qnet = quantize_net(net, calib_data=calib)
    eng = InferenceEngine(qnet, shapes=[(8,)], max_batch=2,
                          max_wait_ms=1.0, name="int8")
    try:
        x = calib[0][0]
        got = eng.predict(x, timeout=10.0)[0]
        want = net(mx.nd.array(x[None])).asnumpy()[0]
        assert np.allclose(got, want, atol=0.1)  # int8 tolerance
        assert eng.stats()["retraces_after_warmup"] == 0
    finally:
        eng.close()


# -- SLO observability -----------------------------------------------------

def test_serving_metrics_and_slo_snapshot():
    obs.set_enabled(True)
    obs.reset()
    eng = _engine(name="slo")
    try:
        x = np.zeros((4, FEAT), np.float32)
        for _ in range(3):
            eng.predict(x, timeout=10.0)
        with pytest.raises(RetraceForbidden):
            eng.submit(np.zeros((99, FEAT), np.float32))
        assert obs.SERVE_REQUESTS_TOTAL.value(model="slo", code="ok") == 3
        assert obs.SERVE_REQUESTS_TOTAL.value(model="slo",
                                              code="error") == 1
        assert obs.SERVE_COMPILE_TOTAL.value(model="slo") == len(BUCKETS)
        assert obs.SERVE_BATCHES_TOTAL.value(model="slo",
                                             bucket=str((4, FEAT))) >= 1
        assert obs.XLA_DISPATCH_TOTAL.value(site="serving") >= 1
        snap = obs.serve_slo_snapshot("slo")
        assert snap["requests_ok"] == 3
        assert snap["latency_p50_s"] is not None
        assert snap["compiles"] == len(BUCKETS)
        names = [ev["name"] for ev in obs.tracer().events()]
        assert "serving.batch" in names and "serving.compile" in names
        text = obs.registry().dump_prometheus()
        assert "mxtpu_serving_latency_seconds" in text
    finally:
        eng.close()


def test_report_serving_section():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools"))
    try:
        import telemetry_report as tr
    finally:
        sys.path.pop(0)

    events = [
        {"name": "serving.batch", "cat": "serving", "dur": 2000.0,
         "args": {"model": "clf", "bucket": "(8, 6)", "n_valid": 3,
                  "capacity": 4, "fill": 0.75, "queue_depth": 2}},
        {"name": "serving.batch", "cat": "serving", "dur": 1000.0,
         "args": {"model": "clf", "bucket": "(8, 6)", "n_valid": 4,
                  "capacity": 4, "fill": 1.0, "queue_depth": 0}},
        {"name": "serving.shed", "cat": "serving", "args": {"model": "clf"}},
        {"name": "serving.timeout", "cat": "serving",
         "args": {"model": "clf"}},
        {"name": "serving.compile", "cat": "serving",
         "args": {"model": "clf", "bucket": "(8, 6)"}},
        {"name": "serving.swap", "cat": "serving",
         "args": {"model": "clf", "outcome": "committed",
                  "version": "v2", "prev_version": "v1"}},
    ]
    out = tr.render_serving(events)
    assert "Serving:" in out
    assert "clf: 2 batches, 7 requests" in out
    assert "shed: 1, deadline timeouts: 1" in out
    assert "AOT bucket compiles: 1" in out
    assert "committed: v1 -> v2" in out
    # crash-proofing contract: malformed args render, never raise
    assert "Serving:" in tr.render_serving(
        [{"name": "serving.batch", "args": None},
         {"name": "serving.swap", "args": "garbage"}])
    assert tr.render_serving([{"name": "trainer.step"}]) == ""


def test_env_knob_defaults(monkeypatch):
    from mxnet_tpu.serving import (serve_max_batch, serve_max_wait_ms,
                                   serve_queue_cap)

    monkeypatch.delenv("MXTPU_SERVE_MAX_BATCH", raising=False)
    monkeypatch.delenv("MXTPU_SERVE_MAX_WAIT_MS", raising=False)
    monkeypatch.delenv("MXTPU_SERVE_QUEUE", raising=False)
    assert serve_max_batch() == 8
    assert serve_max_wait_ms() == 5.0
    assert serve_queue_cap() == 256
    monkeypatch.setenv("MXTPU_SERVE_MAX_BATCH", "2")
    monkeypatch.setenv("MXTPU_SERVE_MAX_WAIT_MS", "0.5")
    monkeypatch.setenv("MXTPU_SERVE_QUEUE", "3")
    assert serve_max_batch() == 2
    assert serve_max_wait_ms() == 0.5
    assert serve_queue_cap() == 3
