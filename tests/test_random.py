"""Random API tests (reference model: test_random.py distribution checks)."""

import numpy as np
import pytest

import mxnet_tpu as mx


def test_seed_reproducible():
    mx.random.seed(42)
    a = mx.nd.random.uniform(shape=(100,)).asnumpy()
    mx.random.seed(42)
    b = mx.nd.random.uniform(shape=(100,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    c = mx.nd.random.uniform(shape=(100,)).asnumpy()
    assert not np.allclose(b, c)


def test_uniform_range():
    x = mx.nd.random.uniform(2.0, 5.0, shape=(10000,)).asnumpy()
    assert x.min() >= 2.0 and x.max() < 5.0
    assert abs(x.mean() - 3.5) < 0.1


def test_normal_moments():
    x = mx.nd.random.normal(1.0, 2.0, shape=(20000,)).asnumpy()
    assert abs(x.mean() - 1.0) < 0.1
    assert abs(x.std() - 2.0) < 0.1


def test_randint():
    x = mx.nd.random.randint(0, 10, shape=(5000,)).asnumpy()
    assert x.min() >= 0 and x.max() <= 9
    assert x.dtype == np.int32
    assert len(np.unique(x)) == 10


# distribution moments are certified tier-1 by test_operator_breadth's
# sample-op sweep; this mx.random twin of the same moments rides slow
@pytest.mark.slow
def test_gamma_exponential_poisson():
    g = mx.nd.random.gamma(2.0, 2.0, shape=(5000,)).asnumpy()
    assert abs(g.mean() - 4.0) < 0.3  # mean = alpha*beta
    e = mx.nd.random.exponential(2.0, shape=(5000,)).asnumpy()
    assert abs(e.mean() - 2.0) < 0.2
    p = mx.nd.random.poisson(3.0, shape=(5000,)).asnumpy()
    assert abs(p.mean() - 3.0) < 0.2


def test_multinomial():
    probs = mx.nd.array([0.1, 0.0, 0.9])
    s = mx.nd.random.multinomial(probs, shape=5000).asnumpy()
    assert set(np.unique(s)) <= {0, 2}
    assert (s == 2).mean() > 0.8
    # batched + get_prob
    bprobs = mx.nd.array([[1.0, 0.0], [0.0, 1.0]])
    s2, lp = mx.nd.random.multinomial(bprobs, get_prob=True)
    assert s2.shape == (2,)
    np.testing.assert_array_equal(s2.asnumpy(), [0, 1])


def test_shuffle():
    x = mx.nd.arange(0, 100)
    y = mx.nd.random.shuffle(x).asnumpy()
    assert sorted(y.tolist()) == list(range(100))
    assert not np.array_equal(y, np.arange(100))


def test_dropout_uses_key_stream():
    from mxnet_tpu import autograd

    mx.random.seed(0)
    with autograd.record():
        a = mx.nd.Dropout(mx.nd.ones((50, 50)), p=0.5).asnumpy()
        b = mx.nd.Dropout(mx.nd.ones((50, 50)), p=0.5).asnumpy()
    assert not np.allclose(a, b)  # distinct draws from the stream
