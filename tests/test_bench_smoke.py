"""bench.py must keep working — the driver runs it at the end of every
round and records the TAIL line as the headline metric. This smoke runs
the whole suite on the CPU backend (tiny configs, ~40 s) and checks the
emitted contract."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_RUNNER = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {root!r})
import bench
bench.main()
"""


def test_bench_emits_driver_contract(tmp_path):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BENCH_")}  # ambient knobs must not leak in
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)
    # keep the smoke from overwriting the repo's committed bench records
    env["BENCH_PR3_OUT"] = str(tmp_path / "BENCH_pr3.json")
    env["BENCH_PR4_OUT"] = str(tmp_path / "BENCH_pr4.json")
    res = subprocess.run(
        [sys.executable, "-c", _RUNNER.format(root=ROOT)],
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr[-3000:]
    lines = [ln for ln in res.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) >= 5, res.stdout
    recs = [json.loads(ln) for ln in lines]
    for rec in recs:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(rec), rec
        assert isinstance(rec["value"], (int, float))
    # the tail line is the ResNet headline the driver records
    assert recs[-1]["metric"].startswith("resnet50_v1_train"), recs[-1]
    names = [r["metric"] for r in recs]
    assert any("bert" in n for n in names)
    assert any("flash_attention" in n for n in names)
    assert any("allreduce" in n for n in names)
    assert any(n.startswith("input_pipeline_prefetch") for n in names)
    # warm persistent-compile-cache start must skip recompilation
    warm = [r for r in recs
            if r["metric"].startswith("compile_cache_warm")]
    assert warm and warm[0]["cache_misses"] == 0, warm
