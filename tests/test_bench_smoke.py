"""bench.py must keep working — the driver runs it at the end of every
round and records the TAIL line as the headline metric. This smoke runs
the whole suite on the CPU backend (tiny configs, ~40 s) and checks the
emitted contract."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_RUNNER = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {root!r})
import bench
bench.main()
"""


def _smoke_env(tmp_path):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BENCH_")}  # ambient knobs must not leak in
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f)
    # keep the smoke from overwriting the repo's committed bench records
    env["BENCH_PR3_OUT"] = str(tmp_path / "BENCH_pr3.json")
    env["BENCH_PR4_OUT"] = str(tmp_path / "BENCH_pr4.json")
    env["BENCH_PR5_OUT"] = str(tmp_path / "BENCH_pr5.json")
    env["BENCH_PR6_OUT"] = str(tmp_path / "BENCH_pr6.json")
    env["BENCH_PR8_OUT"] = str(tmp_path / "BENCH_pr8.json")
    env["BENCH_PR10_OUT"] = str(tmp_path / "BENCH_pr10.json")
    env["BENCH_PR11_OUT"] = str(tmp_path / "BENCH_pr11.json")
    env["BENCH_PR13_OUT"] = str(tmp_path / "BENCH_pr13.json")
    env["BENCH_PR15_OUT"] = str(tmp_path / "BENCH_pr15.json")
    env["BENCH_PR17_OUT"] = str(tmp_path / "BENCH_pr17.json")
    env["BENCH_PR18_OUT"] = str(tmp_path / "BENCH_pr18.json")
    env["BENCH_PR19_OUT"] = str(tmp_path / "BENCH_pr19.json")
    env["BENCH_PR20_OUT"] = str(tmp_path / "BENCH_pr20.json")
    env["BENCH_STATUS_OUT"] = str(tmp_path / "BENCH_STATUS.json")
    env["BENCH_TELEMETRY_OUT"] = str(tmp_path / "BENCH_telemetry.jsonl")
    return env


def _warm_cache_rec(recs):
    warm = [r for r in recs
            if r["metric"].startswith("compile_cache_warm")]
    return warm[0] if warm else None


def _ckpt_rec(recs):
    ck = [r for r in recs
          if r["metric"].startswith("checkpoint_async_superstep")]
    return ck[0] if ck else None


def _overlap_rec(recs):
    ov = [r for r in recs if r["metric"].startswith("overlap_ready")]
    return ov[0] if ov else None


def _elastic_rec(recs):
    el = [r for r in recs if r["metric"].startswith("elastic_resize")]
    return el[0] if el else None


def _serving_rec(recs):
    sv = [r for r in recs if r["metric"].startswith("serving_batched")]
    return sv[0] if sv else None


def _federation_rec(recs):
    fd = [r for r in recs if r["metric"].startswith("federation_plane")]
    return fd[0] if fd else None


def _train_fused_rec(recs):
    tf = [r for r in recs if r["metric"].startswith("train_step_fused")]
    return tf[0] if tf else None


def _fleet_rec(recs):
    fl = [r for r in recs if r["metric"].startswith("fleet_recovery")]
    return fl[0] if fl else None


def _decode_rec(recs):
    dc = [r for r in recs if r["metric"].startswith("decode_tokens_per_s")]
    return dc[0] if dc else None


def _parallel4d_rec(recs):
    p4 = [r for r in recs
          if r["metric"].startswith("parallel4d_pipeline_overlap")]
    return p4[0] if p4 else None


def _input_scale_rec(recs):
    sc = [r for r in recs if r["metric"].startswith("input_scale_stream")]
    return sc[0] if sc else None


#: the shared BENCH_ONLY re-run contract: a timing/pressure-sensitive
#: assert that fails during the FULL run gets exactly one clean-
#: subprocess retry of JUST its scenario (host pressure across a 10-
#: scenario suite must not masquerade as a regression), with the
#: retried scenario's record outputs redirected to ``.retry`` files so
#: the full run's committed records stay what the other asserts see.
#: scenario name -> (record picker, env keys of its record outputs)
_STANDALONE = {
    "train_step": (_train_fused_rec, ("BENCH_PR3_OUT",)),
    "input_pipeline": (_warm_cache_rec, ("BENCH_PR4_OUT",)),
    "checkpoint": (_ckpt_rec, ("BENCH_PR8_OUT",)),
    "overlap": (_overlap_rec, ("BENCH_PR10_OUT",)),
    "elastic": (_elastic_rec, ("BENCH_PR11_OUT",)),
    "serving": (_serving_rec, ("BENCH_PR13_OUT",)),
    "federation": (_federation_rec, ("BENCH_PR15_OUT",)),
    "fleet": (_fleet_rec, ("BENCH_PR17_OUT",)),
    "decode": (_decode_rec, ("BENCH_PR18_OUT",)),
    "parallel4d": (_parallel4d_rec, ("BENCH_PR19_OUT",)),
    "input_scale": (_input_scale_rec, ("BENCH_PR20_OUT",)),
}


def _rerun_standalone(env, scenario):
    """Re-run ONE scenario standalone (see ``_STANDALONE``); returns
    (its record or None, the completed subprocess)."""
    picker, out_keys = _STANDALONE[scenario]
    env2 = dict(env)
    env2["BENCH_ONLY"] = scenario
    for key in out_keys + ("BENCH_STATUS_OUT",):
        env2[key] = env[key] + ".retry"
    res = subprocess.run(
        [sys.executable, "-c", _RUNNER.format(root=ROOT)],
        env=env2, capture_output=True, text=True, timeout=600)
    recs = [json.loads(ln) for ln in res.stdout.strip().splitlines()
            if ln.startswith("{")]
    return picker(recs), res


def test_bench_emits_driver_contract(tmp_path):
    env = _smoke_env(tmp_path)
    res = subprocess.run(
        [sys.executable, "-c", _RUNNER.format(root=ROOT)],
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr[-3000:]
    lines = [ln for ln in res.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) >= 5, res.stdout
    recs = [json.loads(ln) for ln in lines]
    for rec in recs:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(rec), rec
        assert isinstance(rec["value"], (int, float))
    # the tail line is the ResNet headline the driver records
    assert recs[-1]["metric"].startswith("resnet50_v1_train"), recs[-1]
    names = [r["metric"] for r in recs]
    assert any("bert" in n for n in names)
    assert any("flash_attention" in n for n in names)
    assert any("allreduce" in n for n in names)
    assert any(n.startswith("input_pipeline_prefetch") for n in names)
    # warm persistent-compile-cache start must skip recompilation; a
    # nonzero miss count gets ONE clean-subprocess retry first (host
    # pressure must not masquerade as a cache regression)
    warm = _warm_cache_rec(recs)
    if not (warm and warm["cache_misses"] == 0):
        warm, res2 = _rerun_standalone(env, "input_pipeline")
        assert warm and warm["cache_misses"] == 0, \
            (warm, res.stderr[-1000:], res2.stderr[-1000:])
    # superstep scenario (PR6): K=1 vs K=8 legs, dispatches/step
    # amortized >= 4x, and BENCH_pr6.json lands
    ss = [r for r in recs if "superstep_k8" in r["metric"]]
    assert ss, names
    assert ss[0]["dispatch_reduction"] >= 4, ss
    assert any("superstep_k1" in n for n in names)
    pr6 = json.load(open(tmp_path / "BENCH_pr6.json"))
    assert pr6["scenario"] == "superstep" \
        and pr6["dispatch_reduction"] >= 4, pr6
    # async-checkpoint scenario (PR8): both legs emitted, overhead
    # < 5% (bench takes best-of-3 pairwise attempts against host
    # pressure), every committed checkpoint verified, BENCH_pr8.json
    ck = _ckpt_rec(recs)
    assert ck, names
    assert ck["committed"] >= 1, ck
    assert any(n.startswith("checkpoint_off_superstep") for n in names)
    pr8 = json.load(open(tmp_path / "BENCH_pr8.json"))
    assert pr8["scenario"] == "checkpoint" and pr8["verified"], pr8
    if not ck["overhead_pct"] < 5.0:
        ck, res2 = _rerun_standalone(env, "checkpoint")
        assert ck and ck["overhead_pct"] < 5.0, \
            (ck, res.stderr[-1000:], res2.stderr[-1000:])
    # overlapped-allreduce scenario (PR10): the bucket-ready schedule
    # must hide a positive fraction of the staged baseline's exposed
    # comm, and the ZeRO-2/3 rows must show per-rank optimizer+gradient
    # memory reduced ~ (N-1)/N at a parity loss trajectory
    ov = _overlap_rec(recs)
    assert ov, names
    if not (ov.get("comm_hidden_fraction") or 0) > 0:
        ov, res2 = _rerun_standalone(env, "overlap")
        assert ov and (ov.get("comm_hidden_fraction") or 0) > 0, \
            (ov, res.stderr[-1000:], res2.stderr[-1000:])
    # live-elasticity scenario (PR11): a chaos-driven mid-run 4->2->4
    # resize loses ZERO committed steps (bit-exact state at the resize
    # boundary), completes without a process restart, evicts the
    # chaos-stalled straggler, and recovers >=90% of steady-state
    # throughput after warm re-entry (throughput is the one pressure-
    # sensitive number — it gets the standalone retry)
    el = _elastic_rec(recs)
    assert el, names
    assert el["committed_steps_lost"] == 0, el
    assert el["boundary_bitexact"] is True, el
    assert el["losses_bitexact_to_boundary"] is True, el
    assert el["descriptor_verified"] is True, el
    assert el["straggler_evicted"] is True, el
    assert el["resizes"] == 2, el
    if not el["value"] >= 0.9:
        el, res2 = _rerun_standalone(env, "elastic")
        assert el and el["value"] >= 0.9 \
            and el["committed_steps_lost"] == 0 \
            and el["boundary_bitexact"] is True, \
            (el, res.stderr[-1000:], res2.stderr[-1000:])
    pr11 = json.load(open(tmp_path / "BENCH_pr11.json"))
    assert pr11["scenario"] == "elastic" \
        and pr11["committed_steps_lost"] == 0 \
        and pr11["boundary_bitexact"] and pr11["warm_reentry"], pr11
    for stage in ("2", "3"):
        zr = [r for r in recs
              if r["metric"].startswith(f"zero{stage}_optgrad_mem")]
        assert zr, names
        assert zr[0]["value"] >= zr[0]["target_fraction"] - 0.01, zr
        assert zr[0]["loss_max_diff_vs_zero0"] < 1e-5, zr
    pr10 = json.load(open(tmp_path / "BENCH_pr10.json"))
    assert pr10["scenario"] == "overlap" and "zero" in pr10, pr10
    # serving scenario (PR13): batched continuous serving beats the
    # single-request baseline, ZERO recompiles after warmup (the
    # sealed-engine contract — hard, never pressure-sensitive), real
    # p50/p99, and BENCH_pr13.json lands. The QPS comparison is the
    # pressure-sensitive number — it gets the standalone retry.
    sv = _serving_rec(recs)
    assert sv, names
    assert sv["recompiles_after_warmup"] == 0, sv
    assert sv["p50_ms"] is not None and sv["p99_ms"] is not None, sv
    assert any(n.startswith("serving_single") for n in names)
    pr13 = json.load(open(tmp_path / "BENCH_pr13.json"))
    assert pr13["scenario"] == "serving" \
        and pr13["recompiles_after_warmup"] == 0, pr13
    single = [r for r in recs if r["metric"].startswith("serving_single")]
    if not sv["value"] > single[0]["value"]:
        sv, res2 = _rerun_standalone(env, "serving")
        assert sv and sv["recompiles_after_warmup"] == 0 \
            and (sv.get("speedup_vs_single") or 0) > 1.0, \
            (sv, res.stderr[-1000:], res2.stderr[-1000:])
    # observability-plane scenario (PR15): federation + watchdog armed
    # over a real 4-device train loop. The structural gates are HARD
    # (zero added dispatches, 4 ranks federated, cluster endpoint +
    # aggregates + exact histogram merge + stale marking + exactly-once
    # NaN anomaly); the telemetry overhead number is the one pressure-
    # sensitive figure — it gets the standalone retry.
    fd = _federation_rec(recs)
    assert fd, names
    assert fd["dispatch_delta"] == 0, fd
    assert fd["ranks_federated"] == 4, fd
    for flag in ("cluster_endpoint_ok", "aggregates_ok",
                 "histogram_merge_ok", "stale_marked",
                 "watchdog_nan_exactly_once"):
        assert fd[flag] is True, (flag, fd)
    if not fd["overhead_pct"] < 2.0:
        fd, res2 = _rerun_standalone(env, "federation")
        assert fd and fd["overhead_pct"] < 2.0 \
            and fd["dispatch_delta"] == 0, \
            (fd, res.stderr[-1000:], res2.stderr[-1000:])
    pr15 = json.load(open(tmp_path / "BENCH_pr15.json"))
    assert pr15["scenario"] == "federation" \
        and pr15["ranks_federated"] == 4 \
        and pr15["dispatch_delta"] == 0, pr15
    # the bench regression gate (tools/bench_diff.py) closes the loop:
    # the fresh record passes against the committed trajectory (wide
    # band — CPU hosts differ), and a doctored -30% throughput copy
    # FAILS at the default band (the gate actually gates)
    import subprocess as sp
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   str(tmp_path / "BENCH_pr15.json"),
                   os.path.join(ROOT, "BENCH_pr15.json"),
                   "--tolerance", "0.8", "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 0, (diff.stdout, diff.stderr)
    verdict = json.loads(diff.stdout)
    assert verdict["pass"] and verdict["checked"] > 0, verdict
    doctored = dict(pr15)
    doctored["steps_per_sec_federated"] = \
        pr15["steps_per_sec_federated"] * 0.7
    doc_path = tmp_path / "BENCH_pr15_doctored.json"
    doc_path.write_text(json.dumps(doctored))
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   str(doc_path), str(tmp_path / "BENCH_pr15.json"),
                   "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 1, (diff.returncode, diff.stdout)
    verdict = json.loads(diff.stdout)
    assert not verdict["pass"] and any(
        f["key"] == "steps_per_sec_federated"
        for f in verdict["failures"]), verdict
    # self-healing fleet scenario (PR17): chaos SIGKILLs a replica
    # process mid-traffic. The robustness gates are HARD: the kill
    # fired, ZERO requests hung, every in-flight request was retried or
    # failed typed, zero stale-version responses across the concurrent
    # staged swap, the autoscaler replaced the replica, and the burst
    # shed strictly by priority class (critical NEVER policy-shed).
    # p99-back-in-SLO rides recovery timing — the pressure-sensitive
    # pair gets the standalone retry.
    fl = _fleet_rec(recs)
    assert fl, names
    assert fl["kill_injected"] is True, fl
    assert fl["hung_requests"] == 0, fl
    assert fl["stale_version_responses"] == 0, fl
    assert fl["shed_critical"] == 0, fl
    assert fl["priority_shed_ok"] is True, fl
    assert fl["shed_bulk"] > 0, fl
    assert fl["replaced"] >= 1, fl
    assert fl["inflight_ok"] + fl["inflight_typed_failed"] > 0, fl
    pr17_path = env["BENCH_PR17_OUT"]
    base17 = json.load(open(os.path.join(ROOT, "BENCH_pr17.json")))
    lim = base17["recovery_s"] * 1.9  # the diff gate's lower-better band
    if not (fl["p99_in_slo"] is True and 0.0 <= fl["value"] <= lim):
        fl, res2 = _rerun_standalone(env, "fleet")
        assert fl and fl["p99_in_slo"] is True \
            and 0.0 <= fl["value"] <= lim \
            and fl["hung_requests"] == 0 \
            and fl["stale_version_responses"] == 0, \
            (fl, res.stderr[-1000:], res2.stderr[-1000:])
        pr17_path += ".retry"  # gate the clean re-run, not the noisy one
    pr17 = json.load(open(pr17_path))
    assert pr17["scenario"] == "fleet" \
        and pr17["hung_requests"] == 0 \
        and pr17["stale_version_responses"] == 0 \
        and pr17["priority_shed_ok"], pr17
    # the committed BENCH_pr17.json baseline gates the record: the
    # fresh run passes at a wide band (recovery_s is lower-is-better;
    # p99_in_slo / priority_shed_ok are exact booleans), and a doctored
    # copy that flips the in-SLO contract FAILS (the gate gates)
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   pr17_path, os.path.join(ROOT, "BENCH_pr17.json"),
                   "--tolerance", "0.9", "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 0, (diff.stdout, diff.stderr)
    verdict = json.loads(diff.stdout)
    assert verdict["pass"] and verdict["checked"] > 0, verdict
    doctored = dict(pr17)
    doctored["p99_in_slo"] = False
    doc_path = tmp_path / "BENCH_pr17_doctored.json"
    doc_path.write_text(json.dumps(doctored))
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   str(doc_path), pr17_path, "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 1, (diff.returncode, diff.stdout)
    verdict = json.loads(diff.stdout)
    assert not verdict["pass"] and any(
        f["key"] == "p99_in_slo" for f in verdict["failures"]), verdict
    # decode fast-path scenario (PR18): the correctness gates are HARD
    # — greedy decode through the paged cache matched the dense
    # full-context oracle, a request late-joined the running batch, the
    # sealed engine never recompiled, the cache drained to empty, and
    # dispatches/token held the 1/chunk amortized contract (bench.py
    # raises on any of these, so the record existing means they held;
    # re-assert the flags it stamped anyway). tokens/s + ITL are the
    # pressure-sensitive pair — they gate against the committed
    # BENCH_pr18.json through bench_diff with the standalone retry.
    dc = _decode_rec(recs)
    assert dc, names
    assert dc["recompiles_after_warmup"] == 0, dc
    assert dc["cache_match_ok"] == 1, dc
    assert dc["late_join_ok"] == 1, dc
    assert any(n.startswith("decode_itl_p50") for n in names)
    assert any(n.startswith("decode_itl_p99") for n in names)
    assert any(n.startswith("decode_cache_peak_occupancy")
               for n in names)
    pr18_path = env["BENCH_PR18_OUT"]
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   pr18_path, os.path.join(ROOT, "BENCH_pr18.json"),
                   "--tolerance", "0.9", "--json"],
                  capture_output=True, text=True, timeout=60)
    if diff.returncode != 0:
        dc, res2 = _rerun_standalone(env, "decode")
        assert dc and dc["recompiles_after_warmup"] == 0 \
            and dc["cache_match_ok"] == 1, \
            (dc, res.stderr[-1000:], res2.stderr[-1000:])
        pr18_path += ".retry"  # gate the clean re-run, not the noisy one
        diff = sp.run([sys.executable,
                       os.path.join(ROOT, "tools", "bench_diff.py"),
                       pr18_path, os.path.join(ROOT, "BENCH_pr18.json"),
                       "--tolerance", "0.9", "--json"],
                      capture_output=True, text=True, timeout=60)
    assert diff.returncode == 0, (diff.stdout, diff.stderr)
    verdict = json.loads(diff.stdout)
    assert verdict["pass"] and verdict["checked"] > 0, verdict
    pr18 = json.load(open(pr18_path))
    assert pr18["scenario"] == "decode" \
        and pr18["recompiles_after_warmup"] == 0 \
        and pr18["cache_match_ok"] == 1 \
        and pr18["late_join_ok"] == 1 \
        and pr18["cache_freed_ok"] == 1, pr18
    # the committed baseline gates the trajectory both ways: a
    # doctored copy with tokens/s collapsed -60% FAILS at the default
    # band (higher-is-better direction pin), as does doctored ITL +60%
    # (lower-is-better)
    doctored = dict(pr18)
    doctored["tokens_per_s"] = pr18["tokens_per_s"] * 0.4
    doc_path = tmp_path / "BENCH_pr18_doctored.json"
    doc_path.write_text(json.dumps(doctored))
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   str(doc_path), pr18_path, "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 1, (diff.returncode, diff.stdout)
    verdict = json.loads(diff.stdout)
    assert not verdict["pass"] and any(
        f["key"] == "tokens_per_s" for f in verdict["failures"]), verdict
    doctored = dict(pr18)
    doctored["itl_p99_ms"] = pr18["itl_p99_ms"] * 1.6
    doc_path.write_text(json.dumps(doctored))
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   str(doc_path), pr18_path, "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 1, (diff.returncode, diff.stdout)
    verdict = json.loads(diff.stdout)
    assert not verdict["pass"] and any(
        f["key"] == "itl_p99_ms" for f in verdict["failures"]), verdict
    # 4D-parallel scenario (PR19): the correctness gates are HARD —
    # every composed (dp,pp,tp) layout matched the pure-dp loss
    # trajectory, the interleaved-1F1B bubble sat strictly below
    # fill-drain GPipe at matched microbatches, and pipeline overlap
    # cleared 90% (bench.py raises on any of these, so the record
    # existing means they held). The record gates against the
    # committed BENCH_pr19.json; the contract values (bubbles, stash
    # slots, memory layout bytes) are deterministic, so a clean retry
    # only shields transient child-spawn pressure.
    p4 = _parallel4d_rec(recs)
    assert p4, names
    assert p4["value"] >= 0.9, p4
    assert p4["interleaved_bubble_fraction"] < \
        p4["gpipe_bubble_fraction"], p4
    # plain 1F1B keeps GPipe's bubble and only shrinks the stash —
    # the honest schedule table, pinned
    assert p4["f1b_bubble_fraction"] == p4["gpipe_bubble_fraction"], p4
    assert p4["f1b_stash_slots"] < p4["gpipe_stash_slots"], p4
    assert any(n.startswith("parallel4d_dp2_pp4_1f1b") for n in names)
    assert any(n.startswith("parallel4d_dp2_pp2_tp2") for n in names)
    assert any(n.startswith("parallel4d_dp2_pp2_zero2") for n in names)
    assert any(n.startswith("parallel4d_moe_a2a_hidden") for n in names)
    pr19_path = env["BENCH_PR19_OUT"]
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   pr19_path, os.path.join(ROOT, "BENCH_pr19.json"),
                   "--tolerance", "0.9", "--json"],
                  capture_output=True, text=True, timeout=60)
    if diff.returncode != 0:
        p4, res2 = _rerun_standalone(env, "parallel4d")
        assert p4 and p4["value"] >= 0.9, \
            (p4, res.stderr[-1000:], res2.stderr[-1000:])
        pr19_path += ".retry"  # gate the clean re-run, not the noisy one
        diff = sp.run([sys.executable,
                       os.path.join(ROOT, "tools", "bench_diff.py"),
                       pr19_path, os.path.join(ROOT, "BENCH_pr19.json"),
                       "--tolerance", "0.9", "--json"],
                      capture_output=True, text=True, timeout=60)
    assert diff.returncode == 0, (diff.stdout, diff.stderr)
    verdict = json.loads(diff.stdout)
    assert verdict["pass"] and verdict["checked"] > 0, verdict
    pr19 = json.load(open(pr19_path))
    assert pr19["scenario"] == "parallel4d" \
        and pr19["loss_parity_ok"] == 1 \
        and pr19["pipeline_overlap_fraction"] >= 0.9, pr19
    # direction pins both ways: a doctored interleaved bubble +60%
    # FAILS (bubble_fraction is lower-is-better — the bare "fraction"
    # token must not read it as higher-better), and a doctored overlap
    # fraction -40% FAILS (higher-is-better)
    doctored = dict(pr19)
    doctored["interleaved_bubble_fraction"] = \
        pr19["interleaved_bubble_fraction"] * 1.6
    doc_path = tmp_path / "BENCH_pr19_doctored.json"
    doc_path.write_text(json.dumps(doctored))
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   str(doc_path), pr19_path, "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 1, (diff.returncode, diff.stdout)
    verdict = json.loads(diff.stdout)
    assert not verdict["pass"] and any(
        f["key"] == "interleaved_bubble_fraction"
        for f in verdict["failures"]), verdict
    doctored = dict(pr19)
    doctored["pipeline_overlap_fraction"] = \
        pr19["pipeline_overlap_fraction"] * 0.6
    doc_path.write_text(json.dumps(doctored))
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   str(doc_path), pr19_path, "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 1, (diff.returncode, diff.stdout)
    verdict = json.loads(diff.stdout)
    assert not verdict["pass"] and any(
        f["key"] == "pipeline_overlap_fraction"
        for f in verdict["failures"]), verdict
    # streaming-input scenario (PR20): the determinism gates are HARD —
    # the 4->2->4 repartition skipped/replayed zero samples, the union
    # continued the uninterrupted order exactly, and the cursor
    # round-tripped JSON bit-exactly (bench.py raises otherwise, so the
    # record existing means they held). Saturation (consumer-wait ~ 0)
    # is timing-sensitive on a 1-core host -> standalone retry shields
    # transient pressure before it reads as a regression.
    isc = _input_scale_rec(recs)
    assert isc, names
    if not isc["input_saturated"]:
        isc, res2 = _rerun_standalone(env, "input_scale")
        assert isc and isc["input_saturated"], \
            (isc, res.stderr[-1000:], res2.stderr[-1000:])
    assert isc["resize_zero_skip"] is True \
        and isc["resize_zero_replay"] is True \
        and isc["cursor_roundtrip_bitexact"] is True, isc
    pr20_path = env["BENCH_PR20_OUT"]
    # wait metrics are sub-ms means on a noisy host: the per-metric
    # bands widen them to 9x while samples_per_s keeps the 0.9 band
    # (a real regression to input-bound is ~80x the baseline wait)
    diff_args = [sys.executable,
                 os.path.join(ROOT, "tools", "bench_diff.py"),
                 pr20_path, os.path.join(ROOT, "BENCH_pr20.json"),
                 "--tolerance", "0.9",
                 "--metric-tolerance", "consumer_wait_ms_per_step=8.0",
                 "--metric-tolerance", "consumer_wait_fraction=8.0",
                 "--json"]
    diff = sp.run(diff_args, capture_output=True, text=True, timeout=60)
    if diff.returncode != 0:
        isc, res2 = _rerun_standalone(env, "input_scale")
        assert isc and isc["input_saturated"], \
            (isc, res.stderr[-1000:], res2.stderr[-1000:])
        pr20_path += ".retry"  # gate the clean re-run, not the noisy one
        diff_args[2] = pr20_path
        diff = sp.run(diff_args, capture_output=True, text=True,
                      timeout=60)
    assert diff.returncode == 0, (diff.stdout, diff.stderr)
    verdict = json.loads(diff.stdout)
    assert verdict["pass"] and verdict["checked"] > 0, verdict
    pr20 = json.load(open(pr20_path))
    assert pr20["scenario"] == "input_scale" \
        and pr20["skipped_samples"] == 0 \
        and pr20["replayed_samples"] == 0 \
        and pr20["resize_order_exact"] is True, pr20
    # direction pins both ways: a doctored consumer wait +30x FAILS
    # (consumer_wait* is lower-is-better even as a _fraction — the
    # PR-15/PR-19 inversion shape), and doctored samples/s -60% FAILS
    doctored = dict(pr20)
    doctored["consumer_wait_ms_per_step"] = \
        max(pr20["consumer_wait_ms_per_step"], 0.05) * 30
    doctored["consumer_wait_fraction"] = \
        max(pr20["consumer_wait_fraction"], 0.001) * 30
    doc_path = tmp_path / "BENCH_pr20_doctored.json"
    doc_path.write_text(json.dumps(doctored))
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   str(doc_path), pr20_path,
                   "--metric-tolerance", "consumer_wait_ms_per_step=8.0",
                   "--metric-tolerance", "consumer_wait_fraction=8.0",
                   "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 1, (diff.returncode, diff.stdout)
    verdict = json.loads(diff.stdout)
    assert not verdict["pass"] and any(
        "consumer_wait" in f["key"] for f in verdict["failures"]), verdict
    doctored = dict(pr20)
    doctored["samples_per_s"] = pr20["samples_per_s"] * 0.4
    doctored["input_saturated"] = False
    doc_path.write_text(json.dumps(doctored))
    diff = sp.run([sys.executable,
                   os.path.join(ROOT, "tools", "bench_diff.py"),
                   str(doc_path), pr20_path, "--json"],
                  capture_output=True, text=True, timeout=60)
    assert diff.returncode == 1, (diff.returncode, diff.stdout)
    verdict = json.loads(diff.stdout)
    assert not verdict["pass"] and any(
        f["key"] == "samples_per_s" for f in verdict["failures"]) and any(
        f["key"] == "input_saturated" and f["kind"] == "bool"
        for f in verdict["failures"]), verdict
    # mixed-precision scenario (PR5): both legs emitted, the bf16 leg
    # carries the speedup + fp16 recovery flag, and BENCH_pr5.json lands
    amp_recs = [r for r in recs
                if r["metric"].startswith("train_step_amp_bf16")]
    assert amp_recs, names
    assert amp_recs[0]["fp16_overflow_recovered"] is True, amp_recs
    assert "speedup_vs_fp32" in amp_recs[0]
    pr5 = json.load(open(tmp_path / "BENCH_pr5.json"))
    assert pr5["scenario"] == "amp" and pr5["fp16_overflow_recovered"]
    # run-status record (VERDICT r5 hardening): rc 0 + every scenario
    # listed as completed, failures (none here) keyed by scenario
    status = json.load(open(tmp_path / "BENCH_STATUS.json"))
    assert status["rc"] == 0, status
    assert "amp" in status["completed"] and "superstep" in \
        status["completed"] and "elastic" in status["completed"] \
        and "fleet" in status["completed"] \
        and "decode" in status["completed"] \
        and not status["failed"], status
    # MFU accounting contract (PR7): EVERY row carries flops_per_step
    # and mfu; a null always pairs with a reason (this CPU smoke has no
    # peak table, so mfu is null-with-reason while flops_per_step is
    # real on the cost-analysis-backed rows)
    for rec in recs:
        assert "flops_per_step" in rec and "mfu" in rec, rec
        if rec["mfu"] is None:
            assert rec.get("mfu_reason"), rec
    fused = [r for r in recs if r["metric"].startswith("train_step_fused")]
    assert fused and fused[0]["flops_per_step"] > 0, fused
    assert ss[0]["flops_per_step"] > 0, ss  # superstep scan FLOPs / K
    # the bench telemetry dump feeds the report tool's roofline table
    tel = tmp_path / "BENCH_telemetry.jsonl"
    assert tel.exists()
    import subprocess as sp
    rep = sp.run([sys.executable,
                  os.path.join(ROOT, "tools", "telemetry_report.py"),
                  str(tel)], capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stderr
    assert "Executable roofline" in rep.stdout, rep.stdout[-2000:]
    assert "superstep" in rep.stdout
    # the static graph-contracts section rides along on every report
    # (pinned sites + rule catalog + baseline size, PR 14)
    assert "Graph contracts" in rep.stdout, rep.stdout[-2000:]
    assert "spmd_step" in rep.stdout
    # the attribution section renders from the same dump (PR 16)
    assert "Attribution" in rep.stdout, rep.stdout[-2000:]
    # step-time attribution (PR16): the train_step rows stamp per-phase
    # fields whose sum reconstructs the measured step wall within 10%
    # (host pressure on the one-shot timing gets the standalone retry)
    tf = _train_fused_rec(recs)
    assert tf, names
    if not ("phase_sum_ms" in tf and
            abs(tf["phase_sum_ms"] - tf["step_ms"]) <=
            0.10 * tf["step_ms"]):
        tf, res2 = _rerun_standalone(env, "train_step")
        assert tf and "phase_sum_ms" in tf \
            and abs(tf["phase_sum_ms"] - tf["step_ms"]) <= \
            0.10 * tf["step_ms"], \
            (tf, res.stderr[-1000:], res2.stderr[-1000:])
    for ph in ("input_wait", "h2d", "ckpt_overhead", "comm_exposed",
               "compute", "host_gap"):
        assert tf[f"phase_{ph}_ms"] >= 0.0, tf
    pr3 = json.load(open(env["BENCH_PR3_OUT"]))
    assert pr3["_phases"]["fused"]["compute_ms"] >= 0.0, pr3
    # mxtpu-doctor renders a verdict from the bench telemetry for the
    # train_step AND serving scenarios (tier-1 doctor smoke, PR16)
    doc = sp.run([sys.executable,
                  os.path.join(ROOT, "tools", "mxtpu_doctor.py"),
                  "--json", str(tel)],
                 capture_output=True, text=True, timeout=60)
    assert doc.returncode == 0, doc.stderr
    report = json.loads(doc.stdout)
    assert report["format"] == "mxtpu-doctor-v1", report
    sys.path.insert(0, ROOT)
    from tools.mxtpu_doctor import RECIPES
    train_sites = {v["site"] for v in report["training"]}
    known = set(RECIPES)
    assert {"trainer", "superstep"} & train_sites, report
    for v in report["training"]:
        assert v["verdict"] in known and v["recipe"], v
    assert report["serving"], report  # bench_serving arms telemetry
    for v in report["serving"]:
        assert v["verdict"] in known and v["requests"] > 0, v
    assert "top" in report, report


_HARNESS_RUNNER = """
import json, sys
sys.path.insert(0, {root!r})
from tools.mxtpu_lint.graphcheck.harness import collect_records
records, sites = collect_records()
print("SITES=" + json.dumps(sites))
"""


# canonical-site coverage is certified every tier-1 run by
# test_graphcheck.py::test_graph_cli_clean_and_canonical_sites_covered
# (the real CLI); this harness twin compiles the same sites again
@pytest.mark.slow
def test_graphcheck_harness_covers_canonical_sites():
    """The --graph trace harness must register AT LEAST the canonical
    compiled-site set (trainer_fused, superstep, spmd_step/superstep,
    kv_bucket, plus one of each prefixed family) — a silently-skipped
    harness leg would otherwise let the graph gate fake green."""
    from tools.mxtpu_lint.graphcheck import missing_canonical

    res = subprocess.run(
        [sys.executable, "-c", _HARNESS_RUNNER.format(root=ROOT)],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr[-3000:]
    line = [ln for ln in res.stdout.splitlines()
            if ln.startswith("SITES=")]
    assert line, res.stdout[-2000:]
    sites = json.loads(line[0][len("SITES="):])
    missing = missing_canonical(sites)
    assert missing == [], (missing, sites)


def test_bench_diff_direction_classification():
    """The bench gate's direction map must read count metrics as
    lower-is-better: an unanchored 'per_s' token substring-matched
    '_per_step' names, inverting the gate for dispatch counters (a
    +20% dispatch regression passed, an improvement failed)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(ROOT, "tools", "bench_diff.py"))
    bd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bd)

    # dispatch counts: MORE dispatches is worse
    assert bd.direction("dispatches_per_step") == "lower"
    assert bd.direction("dispatches_per_step_superstep") == "lower"
    # rate metrics keep higher-is-better (anchored per_s / per_sec)
    assert bd.direction("steps_per_sec_federated") == "higher"
    assert bd.direction("images_per_s") == "higher"
    assert bd.direction("train_throughput") == "higher"
    # latency stays lower-is-better; unknown names stay symmetric
    assert bd.direction("step_time_p99_ms") == "lower"
    # PR17 fleet gate: recovery is wall time (lower), shed counts have
    # no inherent direction (gated by the priority_shed_ok boolean)
    assert bd.direction("recovery_s") == "lower"
    assert bd.direction("stale_version_responses") == "lower"
    assert bd.direction("shed_bulk") == "both"
    assert bd.direction("some_novel_metric") == "both"
    # unit classification still takes precedence over the name
    assert bd.direction("weird_name", unit="img/s") == "higher"
    # PR19 pipeline gate: bubble_fraction is idle time (lower), while
    # the *_hidden_fraction overlap probes stay higher-is-better — the
    # bare 'fraction' token must not invert the bubble direction
    assert bd.direction("bubble_fraction") == "lower"
    assert bd.direction("bubble_fraction_1f1b") == "lower"
    assert bd.direction("gpipe_bubble_fraction") == "lower"
    assert bd.direction("comm_hidden_fraction") == "higher"
    assert bd.direction("moe_a2a_hidden_fraction") == "higher"
    assert bd.direction("moe_dropped_fraction") == "lower"
    assert bd.direction("weird_name", unit="ms") == "lower"
    # PR20 streaming-input gate: the wait family is idle time (lower)
    # even when suffixed _fraction — 'consumer_wait_fraction' must not
    # invert via the bare 'fraction' token; samples_per_s stays a rate
    assert bd.direction("samples_per_s") == "higher"
    assert bd.direction("samples_per_s_resize_leg") == "higher"
    assert bd.direction("consumer_wait_ms_per_step") == "lower"
    assert bd.direction("consumer_wait_fraction") == "lower"
    assert bd.direction("decode_wait_seconds_total") == "lower"
    assert bd.direction("baseline_input_wait_fraction") == "lower"
    assert bd.direction("skipped_samples") == "lower"
    assert bd.direction("replayed_samples") == "lower"
