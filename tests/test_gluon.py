"""Gluon Block/HybridBlock/Parameter/Trainer tests (reference model:
tests/python/unittest/test_gluon.py)."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(3, 4))
    p.initialize(init=mx.initializer.One(), ctx=mx.cpu())
    assert p.data().shape == (3, 4)
    assert (p.data().asnumpy() == 1).all()
    assert p.grad().shape == (3, 4)
    p.set_data(mx.nd.zeros((3, 4)))
    assert (p.data().asnumpy() == 0).all()


def test_parameter_deferred():
    p = gluon.Parameter("w", shape=(5, 0), allow_deferred_init=True)
    p.initialize(ctx=mx.cpu())
    with pytest.raises(gluon.DeferredInitializationError):
        p.data()
    p.shape = (5, 7)
    assert p.data().shape == (5, 7)


def test_dense_shapes_and_values():
    layer = nn.Dense(4, in_units=3, use_bias=True)
    layer.initialize(init=mx.initializer.One())
    x = mx.nd.ones((2, 3))
    out = layer(x)
    # weight -> ones (3 per row); bias dispatches to zeros by name
    assert_almost_equal(out, np.full((2, 4), 3.0, np.float32))


def test_deferred_infer_dense():
    layer = nn.Dense(7)
    layer.initialize()
    out = layer(mx.nd.ones((2, 5)))
    assert out.shape == (2, 7)
    assert layer.weight.shape == (7, 5)


def test_hybrid_consistency():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="tanh"), nn.Dense(3))
    net.initialize()
    x = mx.nd.random.normal(shape=(4, 6))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-5, atol=1e-6)
    # second call goes through the cache
    hybrid2 = net(x).asnumpy()
    assert_almost_equal(hybrid, hybrid2)


def test_hybrid_grad_consistency():
    def run(hybridize):
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
        net.initialize(init=mx.initializer.Xavier())
        # identical params via fixed numpy seed
        for i, p in enumerate(sorted(net.collect_params().keys())):
            param = net.collect_params()[p]
        if hybridize:
            net.hybridize()
        x = mx.nd.array(np.linspace(-1, 1, 12).reshape(3, 4))
        with autograd.record():
            out = net(x)
            loss = (out * out).sum()
        loss.backward()
        # pair by INSERTION order, not name sort: the global dense<N>
        # prefix counters differ between the two nets, and once the
        # suite has created >9 Dense blocks, lexicographic order
        # ("dense10_" < "dense9_") misaligns the weight/bias pairing
        grads = [v.grad().asnumpy()
                 for v in net.collect_params().values()
                 if v.grad_req != "null"]
        params = [v.data().asnumpy()
                  for v in net.collect_params().values()]
        return grads, params

    np.random.seed(42)
    g_eager, p_eager = run(False)
    np.random.seed(42)
    g_hybrid, p_hybrid = run(True)
    for a, b in zip(p_eager, p_hybrid):
        np.testing.assert_allclose(a, b, rtol=1e-6)
    for a, b in zip(g_eager, g_hybrid):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_conv_block():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1), nn.BatchNorm(), nn.Activation("relu"),
            nn.MaxPool2D(2), nn.Flatten(), nn.Dense(3))
    net.initialize()
    out = net(mx.nd.ones((2, 3, 8, 8)))
    assert out.shape == (2, 3)
    net.hybridize()
    out2 = net(mx.nd.ones((2, 3, 8, 8)))
    assert_almost_equal(out, out2.asnumpy(), rtol=1e-4, atol=1e-5)


def test_batchnorm_moving_stats_eager_and_hybrid():
    for hybridize in (False, True):
        bn = nn.BatchNorm(in_channels=3)
        bn.initialize()
        if hybridize:
            bn.hybridize()
        x = mx.nd.random.normal(loc=2.0, shape=(4, 3, 5, 5))
        _ = bn(x)  # inference: stats unchanged
        rm0 = bn.running_mean.data().asnumpy().copy()
        assert_almost_equal(rm0, np.zeros(3, np.float32))
        with autograd.record():
            out = bn(x)
        rm1 = bn.running_mean.data().asnumpy()
        assert not np.allclose(rm1, rm0), f"hybridize={hybridize}"


def test_dropout_modes():
    do = nn.Dropout(0.5)
    do.initialize()
    x = mx.nd.ones((100, 100))
    out_inf = do(x)
    assert_almost_equal(out_inf, x.asnumpy())  # identity at inference
    with autograd.record():
        out_train = do(x)
    frac_zero = (out_train.asnumpy() == 0).mean()
    assert 0.3 < frac_zero < 0.7


def test_embedding_block():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    out = emb(mx.nd.array([[1.0, 2.0], [3.0, 4.0]]))
    assert out.shape == (2, 2, 4)


def test_save_load_parameters(tmp_path):
    fname = str(tmp_path / "net.params")
    net = nn.HybridSequential()
    net.add(nn.Dense(5, in_units=4), nn.Dense(2, in_units=5))
    net.initialize()
    ref = net(mx.nd.ones((1, 4))).asnumpy()
    net.save_parameters(fname)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(5, in_units=4), nn.Dense(2, in_units=5))
    net2.load_parameters(fname)
    out = net2(mx.nd.ones((1, 4))).asnumpy()
    assert_almost_equal(ref, out)


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=1, use_bias=False)
    net.initialize(init=mx.initializer.One())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    x = mx.nd.array([[2.0]])
    with autograd.record():
        y = net(x)  # w*2, w=1
        loss = y * y  # (2w)^2 -> dL/dw = 8w = 8
    loss.backward()
    trainer.step(1)
    # w = 1 - 0.5*8 = -3
    assert_almost_equal(net.weight.data(), np.array([[-3.0]], np.float32))


def test_trainer_lr_scheduler():
    net = nn.Dense(1, in_units=1)
    net.initialize()
    sched = mx.lr_scheduler.FactorScheduler(step=1, factor=0.5)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0, "lr_scheduler": sched})
    assert trainer.learning_rate == pytest.approx(1.0)


def test_mlp_convergence():
    """Tiny end-to-end convergence (the S1 milestone — SURVEY.md §7)."""
    np.random.seed(0)
    mx.random.seed(0)
    n = 256
    x_np = np.random.randn(n, 10).astype(np.float32)
    w_true = np.random.randn(10, 3).astype(np.float32)
    y_np = (x_np @ w_true).argmax(1).astype(np.float32)

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(3))
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = mx.nd.array(x_np), mx.nd.array(y_np)
    for epoch in range(60):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(n)
    preds = net(x).asnumpy().argmax(1)
    acc = (preds == y_np).mean()
    assert acc > 0.9, f"convergence failed: acc={acc}"


def test_sequential_getitem_len():
    net = nn.HybridSequential()
    net.add(nn.Dense(3), nn.Dense(4), nn.Dense(5))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)


def test_collect_params_select():
    net = nn.HybridSequential()
    net.add(nn.Dense(3, in_units=2), nn.Dense(4, in_units=3))
    net.initialize()
    weights = net.collect_params(".*weight")
    assert all("weight" in k for k in weights.keys())
    assert len(weights) == 2


def test_losses():
    pred = mx.nd.array([[1.0, 2.0], [0.5, 0.5]])
    label = mx.nd.array([[1.5, 1.5], [1.0, 0.0]])
    l2 = gluon.loss.L2Loss()(pred, label)
    ref = ((pred.asnumpy() - label.asnumpy()) ** 2).mean(1) / 2
    assert_almost_equal(l2, ref, rtol=1e-5)
    l1 = gluon.loss.L1Loss()(pred, label)
    assert_almost_equal(l1, np.abs(pred.asnumpy() - label.asnumpy()).mean(1),
                        rtol=1e-5)
    ce = gluon.loss.SoftmaxCrossEntropyLoss()(
        mx.nd.array([[10.0, 0.0]]), mx.nd.array([0.0]))
    assert float(ce.asscalar()) < 0.01
    bce = gluon.loss.SigmoidBCELoss()(mx.nd.array([[10.0]]), mx.nd.array([[1.0]]))
    assert float(bce.asscalar()) < 0.01
    hu = gluon.loss.HuberLoss()(pred, label)
    assert hu.shape == (2,)


def test_lambda_blocks():
    lam = nn.HybridLambda(lambda F, x: x * 2)
    out = lam(mx.nd.ones((2, 2)))
    assert_almost_equal(out, np.full((2, 2), 2.0, np.float32))
    lam2 = nn.Lambda("tanh")
    out2 = lam2(mx.nd.zeros((2,)))
    assert_almost_equal(out2, np.zeros(2, np.float32))


def test_block_repr_and_summary(capsys):
    net = nn.HybridSequential()
    net.add(nn.Dense(3, in_units=2))
    net.initialize()
    repr(net)
    net.summary()


def test_cast():
    net = nn.Dense(3, in_units=2)
    net.initialize()
    net.cast("float16")
    assert net.weight.data().dtype == np.float16


def test_trainer_fused_matches_eager():
    """The fused multi-tensor update path must match per-param updates."""

    def train(fused_allowed):
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(3, in_units=16))
        net.initialize(init=mx.initializer.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9,
                            "wd": 1e-4}, kvstore=None)
        if not fused_allowed:
            tr._fused = False
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        X = mx.nd.array(np.random.RandomState(1).randn(16, 8)
                        .astype(np.float32))
        Y = mx.nd.array(np.random.RandomState(2).randint(0, 3, (16,))
                        .astype(np.float32))
        for _ in range(5):
            with autograd.record():
                l = loss_fn(net(X), Y)
            l.backward()
            tr.step(16)
        return [v.data().asnumpy()
                for _, v in sorted(net.collect_params().items())], tr

    wf, trf = train(True)
    we, _ = train(False)
    assert trf._fused not in (False, None), "fused path did not engage"
    for a, b in zip(wf, we):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
