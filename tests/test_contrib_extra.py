"""Contrib tranche 2 (reference: contrib/count_sketch.cu, hawkes_ll.cc,
psroi_pooling.cc, deformable_psroi_pooling.cc, rroi_align.cc,
mrcnn_mask_target.cu, multi_proposal.cc): forward semantics vs numpy
oracles."""

import numpy as np

import mxnet_tpu as mx

nd = mx.nd


def test_count_sketch_oracle():
    rng = np.random.RandomState(0)
    data = rng.randn(2, 5).astype(np.float32)
    h = np.array([0, 2, 1, 2, 0])
    s = np.array([1.0, -1.0, 1.0, 1.0, -1.0], np.float32)
    out = nd.contrib.count_sketch(nd.array(data), nd.array(h), nd.array(s),
                                  out_dim=3).asnumpy()
    want = np.zeros((2, 3), np.float32)
    for i in range(5):
        want[:, h[i]] += s[i] * data[:, i]
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_hawkesll_oracle():
    rng = np.random.RandomState(1)
    N, T, K = 2, 4, 3
    lda = rng.rand(N, K).astype(np.float32) + 0.5
    alpha = rng.rand(K).astype(np.float32) * 0.5
    beta = rng.rand(K).astype(np.float32) + 1.0
    lags = rng.rand(N, T).astype(np.float32)
    marks = rng.randint(0, K, (N, T)).astype(np.float32)
    vl = np.array([4.0, 2.0], np.float32)
    mt = np.array([5.0, 3.0], np.float32)
    ll, state = nd.contrib.hawkesll(
        nd.array(lda), nd.array(alpha), nd.array(beta), nd.zeros((N, K)),
        nd.array(lags), nd.array(marks), nd.array(vl), nd.array(mt))
    for n in range(N):
        r = np.zeros(K)
        llw, t = 0.0, 0.0
        for i in range(T):
            t += lags[n, i]
            r = r * np.exp(-beta * lags[n, i])
            if i < vl[n]:
                m = int(marks[n, i])
                llw += np.log(lda[n, m] + alpha[m] * beta[m] * r[m])
                llw -= alpha[m] * (1 - np.exp(-beta[m] * max(mt[n] - t, 0)))
                r[m] += 1
        llw -= mt[n] * lda[n].sum()
        assert abs(float(ll.asnumpy()[n]) - llw) < 1e-3


def test_psroi_pooling():
    C_out, p = 2, 3
    # constant per channel-group: output bin must read its OWN group
    data = np.zeros((1, C_out * p * p, 8, 8), np.float32)
    for c in range(C_out * p * p):
        data[0, c] = c
    rois = np.array([[0.0, 1.0, 1.0, 7.0, 7.0]], np.float32)
    out = nd.contrib.PSROIPooling(nd.array(data), nd.array(rois),
                                  spatial_scale=1.0, output_dim=C_out,
                                  pooled_size=p).asnumpy()
    assert out.shape == (1, C_out, p, p)
    for c in range(C_out):
        for i in range(p):
            for j in range(p):
                assert out[0, c, i, j] == c * p * p + i * p + j


def test_deformable_psroi_pooling_zero_offsets_match_psroi():
    rng = np.random.RandomState(0)
    C_out, p = 2, 3
    data = rng.rand(1, C_out * p * p, 8, 8).astype(np.float32)
    rois = np.array([[0.0, 1.0, 1.0, 7.0, 7.0]], np.float32)
    base = nd.contrib.PSROIPooling(nd.array(data), nd.array(rois),
                                   output_dim=C_out, pooled_size=p).asnumpy()
    trans = np.zeros((1, 2 * p * p), np.float32)
    dp = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), nd.array(trans), output_dim=C_out,
        pooled_size=p, part_size=p, sample_per_part=2).asnumpy()
    np.testing.assert_allclose(dp, base, rtol=1e-4, atol=1e-5)
    # no_trans path ignores the offsets entirely
    nt = nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), nd.array(trans * 100),
        output_dim=C_out, pooled_size=p, no_trans=True).asnumpy()
    np.testing.assert_allclose(nt, base, rtol=1e-4, atol=1e-5)


def test_rroi_align_rotation():
    img = np.arange(64.0, dtype=np.float32).reshape(1, 1, 8, 8)
    # angle 0: axis-aligned window around the center
    roi0 = np.array([[0.0, 4.0, 4.0, 4.0, 4.0, 0.0]], np.float32)
    o0 = nd.contrib.RROIAlign(nd.array(img), nd.array(roi0),
                              pooled_size=(2, 2)).asnumpy()
    assert o0.shape == (1, 1, 2, 2)
    # 180 degrees flips both axes of the sampled window
    roi180 = np.array([[0.0, 4.0, 4.0, 4.0, 4.0, 180.0]], np.float32)
    o180 = nd.contrib.RROIAlign(nd.array(img), nd.array(roi180),
                                pooled_size=(2, 2)).asnumpy()
    np.testing.assert_allclose(o180[0, 0], o0[0, 0, ::-1, ::-1], atol=1e-3)


def test_mrcnn_mask_target():
    rois = np.array([[[0.0, 0.0, 8.0, 8.0], [2.0, 2.0, 6.0, 6.0]]],
                    np.float32)
    gt = np.zeros((1, 2, 8, 8), np.float32)
    gt[0, 0, :, :4] = 1.0  # mask 0: left half on
    matches = np.array([[0.0, 1.0]], np.float32)
    cls = np.array([[1.0, 0.0]], np.float32)
    t, w = nd.contrib.mrcnn_mask_target(
        nd.array(rois), nd.array(gt), nd.array(matches), nd.array(cls),
        num_rois=2, mask_size=(4, 4), num_classes=3)
    t, w = t.asnumpy(), w.asnumpy()
    assert t.shape == (1, 2, 3, 4, 4) and w.shape == t.shape
    # roi 0 (class 1): left columns of the crop are on
    assert t[0, 0, 1, :, 0].min() > 0.5 and t[0, 0, 1, :, -1].max() < 0.5
    # weights: one-hot at class 1 for roi 0; background roi 1 all-zero
    assert w[0, 0, 1].all() and not w[0, 0, 0].any() and not w[0, 1].any()


def test_multi_proposal_is_batched_proposal():
    assert nd.contrib.MultiProposal is not None
    from mxnet_tpu.ops.registry import get

    assert get("MultiProposal") is get("Proposal")
