"""Fused 1x1-conv (matmul) + BN-stats op tests (CPU: reference path +
interpret-mode Pallas parity; the on-chip path is covered by
tests_tpu/test_fused_conv_bn_tpu.py).

Reference semantics: ``src/operator/nn/batch_norm.cc`` +
``src/operator/subgraph/mkldnn/mkldnn_conv.cc`` (conv+BN subgraph
fusion); the TPU design is original — see ops/fused_conv_bn.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops import fused_conv_bn as F


@pytest.fixture
def data():
    rng = np.random.RandomState(7)
    M, K, N = 128, 64, 32
    return {
        "x": jnp.asarray(rng.randn(M, K), jnp.float32),
        "w": jnp.asarray(rng.randn(K, N) * 0.1, jnp.float32),
        "s": jnp.asarray(rng.rand(K) + 0.5, jnp.float32),
        "t": jnp.asarray(rng.randn(K) * 0.1, jnp.float32),
        "cd": (jnp.asarray(rng.randn(M, N), jnp.float32),
               jnp.asarray(rng.randn(N), jnp.float32),
               jnp.asarray(rng.randn(N) * 0.01, jnp.float32)),
    }


def test_fwd_interpret_matches_reference(data):
    for scale, bias, relu in [(None, None, False),
                              (data["s"], data["t"], True),
                              (data["s"], data["t"], False)]:
        y1, s1, q1 = F._fused_fwd_pallas(data["x"], data["w"], scale, bias,
                                         relu=relu, interpret=True)
        y2, s2, q2 = F._fused_fwd_reference(data["x"], data["w"], scale,
                                            bias, relu=relu)
        np.testing.assert_allclose(np.asarray(y1, np.float32),
                                   np.asarray(y2, np.float32), rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(s1, s2, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(q1, q2, rtol=1e-5, atol=1e-5)


def test_bwd_interpret_matches_reference(data):
    x, w, s, t = data["x"], data["w"], data["s"], data["t"]
    dy, dsum, dssq = data["cd"]
    for scale, bias, relu in [(None, None, False), (s, t, True)]:
        y, _, _ = F._fused_fwd_reference(x, w, scale, bias, relu=relu)
        r1 = F._fused_bwd_pallas(x, w, y, scale, bias, dy, dsum, dssq,
                                 relu=relu, interpret=True)
        r2 = F._fused_bwd_reference(x, w, y, scale, bias, dy, dsum, dssq,
                                    relu=relu)
        for a, b in zip(r1, r2):
            if b is None:
                continue
            np.testing.assert_allclose(
                np.asarray(a, np.float32).reshape(np.asarray(b).shape),
                np.asarray(b, np.float32), rtol=2e-5, atol=1e-5)


def test_custom_vjp_matches_autodiff(data):
    """The hand-derived backward (stat cotangents as per-channel scalars,
    dY = dy + dsum + 2*y*dssq) must equal jax.grad of the plain form."""
    x, w, s, t = data["x"], data["w"], data["s"], data["t"]
    cd = data["cd"]

    def loss_custom(x, w):
        y, a, b = F.matmul_stats(x, w)
        return jnp.sum(y * cd[0]) + jnp.sum(a * cd[1]) + jnp.sum(b * cd[2])

    def loss_plain(x, w):
        y, a, b = F._fused_fwd_reference(x, w, None, None)
        return jnp.sum(y * cd[0]) + jnp.sum(a * cd[1]) + jnp.sum(b * cd[2])

    g1 = jax.grad(loss_custom, (0, 1))(x, w)
    g2 = jax.grad(loss_plain, (0, 1))(x, w)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def loss_custom2(x, s_, t_, w):
        y, a, b = F.scaled_matmul_stats(x, s_, t_, w, True)
        return jnp.sum(y * cd[0]) + jnp.sum(a * cd[1]) + jnp.sum(b * cd[2])

    def loss_plain2(x, s_, t_, w):
        y, a, b = F._fused_fwd_reference(x, w, s_, t_, relu=True)
        return jnp.sum(y * cd[0]) + jnp.sum(a * cd[1]) + jnp.sum(b * cd[2])

    g1 = jax.grad(loss_custom2, (0, 1, 2, 3))(x, s, t, w)
    g2 = jax.grad(loss_plain2, (0, 1, 2, 3))(x, s, t, w)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def _build_r50(pfx, x32):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet50_v1(classes=10, prefix=pfx)
    net.initialize(init=mx.initializer.Xavier())
    net(x32)
    return net


def _suffix_params(net):
    return {k.split("_", 1)[1]: v for k, v in net.collect_params().items()}


@pytest.mark.slow
def test_resnet50_fused_parity_f32():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(8, 3, 32, 32).astype(np.float32))
    n1, n2 = _build_r50("fa_", x), _build_r50("fb_", x)
    p1, p2 = _suffix_params(n1), _suffix_params(n2)
    for k in p1:
        p2[k].set_data(p1[k].data())
    fused = n2.optimize_for(backend="tpu_fused_conv_bn")

    cnt = [0]

    def walk(b):
        cnt[0] += bool(getattr(b, "_tpu_fused", False))
        for c in b._children.values():
            walk(c)

    walk(n2)
    assert cnt[0] >= 30, cnt[0]  # every stride-1 1x1 conv marked

    # eval parity is tight (running stats, no batch-stat conditioning)
    y1, y2 = n1(x), fused(x)
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), atol=1e-5)

    # one training step: loss close, running stats track
    lab = mx.nd.array(rng.randint(0, 10, (8,)).astype(np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for net in (n1, n2):
        for p in net.collect_params().values():
            if p.grad_req != "null":
                p.data().attach_grad()
    with autograd.record():
        l1 = loss_fn(n1(x), lab).mean()
    l1.backward()
    with autograd.record():
        l2 = loss_fn(fused(x), lab).mean()
    l2.backward()
    assert abs(float(l1.asnumpy()) - float(l2.asnumpy())) < 5e-3
    for k in p1:
        if "running" in k:
            np.testing.assert_allclose(p1[k].data().asnumpy(),
                                       p2[k].data().asnumpy(), atol=5e-3)
    # early-stage weight grads match before BN conditioning compounds
    for k in p1:
        if p1[k].grad_req == "null" or "bias" in k:
            continue
        if "stage1" in k or k.startswith("conv"):
            g1 = p1[k].data().grad.asnumpy()
            g2 = p2[k].data().grad.asnumpy()
            rel = np.abs(g1 - g2).max() / (np.abs(g1).max() + 1e-8)
            # late-stage BNs run at var ~ eps on these tiny shapes and
            # chaotically amplify rounding (see the x64 test for the
            # exact-parity proof); early stages stay well-conditioned
            assert rel < 0.1, (k, rel)


@pytest.mark.slow
def test_resnet50_fused_parity_x64_subprocess():
    """Run the float64 semantic-parity check in a subprocess (x64 flag
    must be set before backend init). Verifies loss diff < 1e-9 and all
    weight grads < 1e-8 relative — the fused path is exact, not merely
    close."""
    import subprocess
    import sys as _sys

    code = r'''
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import vision
rng = np.random.RandomState(0)
# 64x64 keeps the last stage at 2x2 spatial: batch-stat variance stays
# well above eps, so BN does not chaotically amplify reassociation noise
# (at 32x32 / spatial 1x1, var ~ eps amplifies 1e-12 to 1e-5 even in f64)
x32 = mx.nd.array(rng.rand(2, 3, 64, 64).astype(np.float32))
x = x32.astype("float64")
def build(pfx):
    net = vision.resnet50_v1(classes=10, prefix=pfx)
    net.initialize(init=mx.initializer.Xavier())
    net(x32)
    net.cast("float64")
    return net
n1, n2 = build("xa_"), build("xb_")
p1 = {k.split("_",1)[1]: v for k,v in n1.collect_params().items()}
p2 = {k.split("_",1)[1]: v for k,v in n2.collect_params().items()}
for k in p1: p2[k].set_data(p1[k].data())
fused = n2.optimize_for(backend="tpu_fused_conv_bn")
lab = mx.nd.array(rng.randint(0, 10, (2,)).astype(np.float64))
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
for net in (n1, n2):
    for p in net.collect_params().values():
        if p.grad_req != "null": p.data().attach_grad()
with autograd.record():
    l1 = loss_fn(n1(x), lab).mean()
l1.backward()
with autograd.record():
    l2 = loss_fn(fused(x), lab).mean()
l2.backward()
assert abs(float(l1.asnumpy()) - float(l2.asnumpy())) < 1e-9
for k in p1:
    if p1[k].grad_req == "null" or "bias" in k: continue
    g1 = p1[k].data().grad.asnumpy(); g2 = p2[k].data().grad.asnumpy()
    rel = np.abs(g1-g2).max() / (np.abs(g1).max() + 1e-12)
    assert rel < 1e-8, (k, rel)
print("X64-PARITY-OK")
'''
    env = dict(__import__("os").environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([_sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=600)
    assert "X64-PARITY-OK" in out.stdout, out.stdout + out.stderr


def test_bn_equivalence_through_stats():
    """Composing matmul_stats with scalar BN math reproduces the
    framework's batch_norm (training mode) bit-for-bit-ish."""
    from mxnet_tpu.ops import nn as nn_ops

    rng = np.random.RandomState(3)
    M, K, N = 64, 16, 8
    x = jnp.asarray(rng.randn(M, K), jnp.float32)
    w = jnp.asarray(rng.randn(K, N) * 0.3, jnp.float32)
    g = jnp.asarray(rng.rand(N) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(N), jnp.float32)

    y, ysum, yssq = F.matmul_stats(x, w)
    mean = ysum / M
    var = jnp.maximum(yssq / M - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + 1e-3)
    out_fused = (y - mean) * inv * g + b

    y2 = jnp.dot(x, w)
    mm = jnp.zeros(N)
    mv = jnp.ones(N)
    out_bn, _, _ = nn_ops.batch_norm(
        y2.reshape(M, N, 1, 1), g, b, mm, mv, training=True,
        fix_gamma=False, axis=1)
    np.testing.assert_allclose(out_fused, out_bn.reshape(M, N),
                               rtol=1e-4, atol=1e-5)


def test_dense_after_conv_nhwc_parity():
    """ADVICE r5 medium: Dense(flatten=True) directly after a conv (no
    explicit Flatten) must see NCHW feature order under optimize_for, or
    its NCHW-trained weights silently mismatch the NHWC interior."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(2, 3, 8, 8).astype(np.float32))
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=3, in_channels=3),
            nn.Dense(5, in_units=4 * 6 * 6))
    net.initialize()
    y_ref = net(x).asnumpy()
    fused = net.optimize_for(backend="tpu_fused_conv_bn")
    np.testing.assert_allclose(y_ref, fused(x).asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_nchw_adapter_tuple_outputs():
    """ADVICE r5 low: multi-feature-map nets (tuple/list outputs) get
    every 4-D element transposed back to NCHW by the adapter."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.block import HybridBlock

    class TwoMaps(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.c1 = nn.Conv2D(4, kernel_size=1, in_channels=3)
                self.c2 = nn.Conv2D(6, kernel_size=3, in_channels=3)

        def hybrid_forward(self, F, x):
            return self.c1(x), self.c2(x)

    rng = np.random.RandomState(1)
    x = mx.nd.array(rng.rand(2, 3, 8, 8).astype(np.float32))
    net = TwoMaps()
    net.initialize()
    refs = [o.asnumpy() for o in net(x)]
    fused = net.optimize_for(backend="tpu_fused_conv_bn")
    outs = fused(x)
    assert isinstance(outs, tuple) and len(outs) == 2
    assert outs[0].shape == (2, 4, 8, 8)  # NCHW restored
    assert outs[1].shape == (2, 6, 6, 6)
    for ref, out in zip(refs, outs):
        np.testing.assert_allclose(ref, out.asnumpy(), rtol=1e-5, atol=1e-5)

    # namedtuple outputs keep their type and field order
    import collections

    Out = collections.namedtuple("Out", ["feat", "aux"])

    class NamedMaps(TwoMaps):
        def hybrid_forward(self, F, x):
            return Out(self.c1(x), self.c2(x))

    net2 = NamedMaps()
    net2.initialize()
    fused2 = net2.optimize_for(backend="tpu_fused_conv_bn")
    out2 = fused2(x)
    assert type(out2) is Out
    assert out2.feat.shape == (2, 4, 8, 8)
    assert out2.aux.shape == (2, 6, 6, 6)


def test_optimized_net_symbolic_forward_no_attribute_error():
    """ADVICE r5 low: symbolic forward of an optimize_for'd BatchNorm
    must not crash on Symbol's missing ndim (falls back to the
    configured axis or raises a clean MXNetError)."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=1, in_channels=3),
            nn.BatchNorm(in_channels=4))
    net.initialize()
    net(mx.nd.ones((1, 3, 4, 4)))
    net.optimize_for(backend="tpu_fused_conv_bn")
    try:
        out = net(mx.sym.Variable("data"))
        assert isinstance(out, mx.sym.Symbol)
    except mx.MXNetError:
        pass  # a clean unsupported-path error is also acceptable

    # marked Dense/Flatten refuse symbol mode loudly (skipping the NCHW
    # restore would silently contract NHWC features vs NCHW weights)
    import pytest as _pytest

    for tail in (nn.Dense(3, in_units=64), nn.Flatten()):
        net2 = nn.HybridSequential()
        net2.add(nn.Conv2D(4, kernel_size=1, in_channels=3), tail)
        net2.initialize()
        net2.optimize_for(backend="tpu_fused_conv_bn")
        with _pytest.raises(mx.MXNetError):
            net2(mx.sym.Variable("data"))
