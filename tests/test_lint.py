"""mxtpu-lint tier-1 gate + rule-engine coverage.

The repo run must be clean against the checked-in baseline (rc-0
contract); every shipped rule must both FIRE on its seeded-violation
fixture and stay QUIET on the clean twin; suppression comments and the
baseline freeze must round-trip. Pure static analysis — no jax import.
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.mxtpu_lint import (REGISTRY, apply_baseline,  # noqa: E402
                              load_baseline, run, write_baseline)
from tools.mxtpu_lint.__main__ import main as lint_main  # noqa: E402

FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")


def run_on(files, rules=None):
    findings, _ = run(ROOT, rules=rules,
                      files=[os.path.join(FIXTURES, f) for f in files])
    return findings


# ---------------------------------------------------------------------------
# the tier-1 gate: the shipped tree is clean vs the shipped baseline
# ---------------------------------------------------------------------------

def test_repo_is_clean_rc0():
    """rc-0-on-baseline contract, through the real CLI."""
    res = subprocess.run(
        [sys.executable, "-m", "tools.mxtpu_lint", "--root", ROOT],
        cwd=ROOT, capture_output=True, text=True)
    assert res.returncode == 0, (
        f"mxtpu-lint found NEW violations:\n{res.stdout}\n{res.stderr}")


def test_shipped_fixes_are_load_bearing():
    """The shipped baseline is EMPTY: every finding the linter ever
    raised in-tree was FIXED (env-discipline in engine.py /
    kvstore/dist.py / ops/flash_attention.py) or explicitly annotated
    at the line. Reverting any one fix therefore creates a NEW finding
    and fails test_repo_is_clean_rc0."""
    entries = load_baseline(os.path.join(ROOT, "tools",
                                         "lint_baseline.json"))
    assert entries == [], (
        "baseline grew — fix new findings instead of freezing them: "
        f"{entries}")
    fixed = [os.path.join(ROOT, p) for p in (
        "mxnet_tpu/engine.py", "mxnet_tpu/kvstore/dist.py",
        "mxnet_tpu/ops/flash_attention.py")]
    findings, _ = run(ROOT, rules=["env-var-discipline"], files=fixed)
    assert findings == [], [str(f) for f in findings]


def test_rule_catalog_complete():
    assert len(REGISTRY) >= 5, sorted(REGISTRY)
    for required in ("host-sync-in-hot-path", "donation-after-use",
                     "capture-unsafe-in-graph", "env-var-discipline",
                     "thread-guard", "telemetry-coverage",
                     "overlap-window-sync", "lock-order",
                     # graph leg (PR 14): same registry, graph=True
                     "donation-dead", "amp-dtype-leak", "baked-constant",
                     "collective-order", "host-callback-in-graph"):
        assert required in REGISTRY


# ---------------------------------------------------------------------------
# per-rule fixtures: seeded violations fire, clean twins stay quiet
# ---------------------------------------------------------------------------

CASES = [
    ("host-sync-in-hot-path", "host_sync_bad.py", 3, "host_sync_clean.py"),
    ("donation-after-use", "donation_bad.py", 2, "donation_clean.py"),
    ("capture-unsafe-in-graph", "capture_bad.py", 8, "capture_clean.py"),
    ("env-var-discipline", "env_bad.py", 3, "env_clean.py"),
    ("thread-guard", "guard_bad.py", 3, "guard_clean.py"),
    ("overlap-window-sync", "overlap_bad.py", 6, "overlap_clean.py"),
    ("lock-order", "lock_order_bad.py", 3, "lock_order_clean.py"),
]


@pytest.mark.parametrize("rule,bad,n_min,clean", CASES,
                         ids=[c[0] for c in CASES])
def test_rule_fires_and_stays_quiet(rule, bad, n_min, clean):
    hits = [f for f in run_on([bad], rules=[rule]) if f.rule == rule]
    assert len(hits) >= n_min, (
        f"{rule} found {len(hits)} < {n_min} on {bad}: "
        f"{[str(f) for f in hits]}")
    assert all(f.file.endswith(bad) for f in hits)
    assert all(f.line > 0 and f.message for f in hits)
    quiet = run_on([clean], rules=[rule])
    assert quiet == [], (
        f"{rule} false-positives on {clean}: {[str(f) for f in quiet]}")


def test_telemetry_rule_on_synthetic_tree(tmp_path):
    """The migrated PR-7 gate inside the engine: an undocumented
    emitted name is a finding; documented names are not."""
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'C = REG.counter("mxtpu_documented_total")\n'
        'D = REG.counter("mxtpu_undocumented_total")\n'
        'tracer.record("my.series", cat="x")\n'
        'record_xla_dispatch("mystery_site")\n')
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "`mxtpu_documented_total` and the `my.series` span\n")
    (docs / "env_vars.md").write_text("none\n")
    findings, _ = run(str(tmp_path), targets=("mxnet_tpu",),
                      rules=["telemetry-coverage"])
    names = {f.message.split("`")[1] for f in findings}
    assert names == {"mxtpu_undocumented_total", "mystery_site"}
    # documenting them empties the finding list
    (docs / "observability.md").write_text(
        "mxtpu_documented_total mxtpu_undocumented_total my.series "
        "mystery_site\n")
    findings, _ = run(str(tmp_path), targets=("mxnet_tpu",),
                      rules=["telemetry-coverage"])
    assert findings == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def _lint_snippet(tmp_path, text, rules):
    p = tmp_path / "snippet.py"
    p.write_text(text)
    findings, _ = run(ROOT, rules=rules, files=[str(p)])
    return findings


def test_suppression_same_line(tmp_path):
    findings = _lint_snippet(
        tmp_path,
        "def f(x):  # mxtpu-lint: hot-path\n"
        "    return x.item()  # mxtpu-lint: disable=host-sync-in-hot-path\n",
        ["host-sync-in-hot-path"])
    assert findings == []


def test_suppression_alias_and_comment_above(tmp_path):
    findings = _lint_snippet(
        tmp_path,
        "def f(x):  # mxtpu-lint: hot-path\n"
        "    a = x.item()  # mxtpu-lint: host-sync-ok\n"
        "    # mxtpu-lint: disable=host-sync-in-hot-path\n"
        "    b = x.item()\n"
        "    return a + b\n",
        ["host-sync-in-hot-path"])
    assert findings == []


def test_suppression_file_level(tmp_path):
    findings = _lint_snippet(
        tmp_path,
        "# mxtpu-lint: disable-file=host-sync-in-hot-path\n"
        "def f(x):  # mxtpu-lint: hot-path\n"
        "    return x.item()\n",
        ["host-sync-in-hot-path"])
    assert findings == []


def test_suppression_is_rule_scoped(tmp_path):
    """A disable for rule A must not swallow rule B on the same line."""
    findings = _lint_snippet(
        tmp_path,
        "def f(x):  # mxtpu-lint: hot-path\n"
        "    return x.item()  # mxtpu-lint: disable=thread-guard\n",
        ["host-sync-in-hot-path"])
    assert len(findings) == 1


# ---------------------------------------------------------------------------
# baseline freeze round-trip
# ---------------------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    bad = os.path.join(FIXTURES, "host_sync_bad.py")
    baseline = tmp_path / "baseline.json"
    # 1. freeze the current findings
    rc = lint_main([bad, "--root", ROOT, "--baseline", str(baseline),
                    "--update-baseline"])
    assert rc == 0
    entries = load_baseline(str(baseline))
    assert len(entries) >= 3
    # 2. frozen findings no longer fail the gate
    rc = lint_main([bad, "--root", ROOT, "--baseline", str(baseline)])
    assert rc == 0
    # 3. a NEW violation still fails
    extra = tmp_path / "fresh.py"
    extra.write_text("def g(x):  # mxtpu-lint: hot-path\n"
                     "    return x.item()\n")
    rc = lint_main([bad, str(extra), "--root", ROOT,
                    "--baseline", str(baseline)])
    assert rc == 1
    # 4. apply_baseline splits new vs frozen vs stale
    findings, _ = run(ROOT, files=[bad, str(extra)])
    new, frozen, stale = apply_baseline(findings, entries)
    assert {f.file.rsplit("/", 1)[-1] for f in new} == {"fresh.py"}
    assert len(frozen) == len(entries) and stale == []


def test_baseline_output_is_stable_sorted(tmp_path):
    """--update-baseline emits sorted, byte-stable JSON so baseline
    churn reviews as a plain diff."""
    findings, _ = run(ROOT, files=[
        os.path.join(FIXTURES, "env_bad.py"),
        os.path.join(FIXTURES, "host_sync_bad.py")])
    p1, p2 = tmp_path / "b1.json", tmp_path / "b2.json"
    write_baseline(str(p1), findings)
    write_baseline(str(p2), list(reversed(findings)))
    assert p1.read_bytes() == p2.read_bytes()
    data = json.loads(p1.read_text())
    keys = [(e["file"], e["rule"], e["message"])
            for e in data["findings"]]
    assert keys == sorted(keys)


def test_baseline_identity_survives_line_drift(tmp_path):
    """Baseline identity is (file, rule, message), NOT the line: edits
    above a frozen finding must not unfreeze it."""
    p = tmp_path / "drift.py"
    p.write_text("def f(x):  # mxtpu-lint: hot-path\n"
                 "    return x.item()\n")
    findings, _ = run(ROOT, files=[str(p)])
    baseline = tmp_path / "b.json"
    entries = write_baseline(str(baseline), findings)
    p.write_text("# a new comment shifts every line\n\n"
                 "def f(x):  # mxtpu-lint: hot-path\n"
                 "    return x.item()\n")
    findings2, _ = run(ROOT, files=[str(p)])
    new, frozen, stale = apply_baseline(findings2, entries)
    assert new == [] and len(frozen) == 1 and stale == []


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "host-sync-in-hot-path" in out and "telemetry-coverage" in out


def test_cli_json_output(tmp_path, capsys):
    bad = os.path.join(FIXTURES, "guard_bad.py")
    rc = lint_main([bad, "--root", ROOT, "--no-baseline", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert {f["rule"] for f in out["new"]} == {"thread-guard"}
    assert all(f["file"] and f["line"] and f["message"]
               for f in out["new"])


def test_cli_unknown_rule():
    assert lint_main(["--rule", "no-such-rule"]) == 2
